"""One-shot TPU tunnel probe (round 5). Named .tpu_probe* so bench.py's
stale-holder cleanup terminates it if it is somehow still alive when the
driver's bench starts. Writes status lines to .tpu_probe.r5.json."""
import json
import os
import time

OUT = "/root/repo/.tpu_probe.r5.json"


def log(**kw):
    kw["ts"] = round(time.time(), 1)
    with open(OUT, "a") as f:
        f.write(json.dumps(kw) + "\n")


os.environ["JAX_PLATFORMS"] = "axon"
log(event="init_start")
t0 = time.time()
try:
    import jax

    jax.config.update("jax_platforms", "axon")
    devs = jax.devices()
    log(event="init_ok", seconds=round(time.time() - t0, 1),
        devices=[str(d) for d in devs])
    # tiny smoke op + serialization capability check
    import jax.numpy as jnp
    import numpy as np

    f = jax.jit(lambda x: (jnp.sin(x) @ jnp.ones((256, 256))).sum())
    t1 = time.time()
    v = float(f(np.ones((8, 256), np.float32)))
    log(event="smoke_ok", seconds=round(time.time() - t1, 1), value=v)
    # can the compiled executable be serialized? (decides whether a
    # persistent compile cache can ever help the driver's bench)
    try:
        lowered = jax.jit(lambda x: jnp.cos(x).sum()).lower(
            np.ones((4, 4), np.float32))
        compiled = lowered.compile()
        from jax._src.compilation_cache import compress_executable  # noqa
        ser = compiled.runtime_executable().serialize()
        log(event="serialize_ok", nbytes=len(ser))
    except Exception as e:  # noqa: BLE001
        log(event="serialize_fail", error=f"{type(e).__name__}: {e}"[:300])
except Exception as e:  # noqa: BLE001
    log(event="init_fail", seconds=round(time.time() - t0, 1),
        error=f"{type(e).__name__}: {e}"[:300])
