#!/usr/bin/env python
"""Tracer-safety + determinism lint for datafusion_distributed_tpu.

A custom AST lint (pure stdlib — no jax import, no device, no network) for
the failure modes generic linters cannot see because they are about WHEN
code runs, not what it says:

- code inside a traced/jitted function executes ONCE at trace time with
  abstract Tracer values: ``float()``/``int()``/``bool()`` on a traced
  value raises (or worse, silently bakes a trace-time constant), Python
  ``if`` on a tracer raises ConcretizationTypeError, ``np.*`` on a tracer
  either errors or silently falls back to host constants, and
  ``time``/``random`` calls bake one trace's value into every later
  execution of the compiled program.
- the engine guarantees byte-identical results between single-node and
  distributed execution. Iterating an UNORDERED collection (``set``/
  ``frozenset``) in codec / fingerprint / planner paths makes plan bytes,
  fingerprints or plan shapes depend on hash-seed iteration order —
  "wrong results, no error" across processes.
- mutable default arguments alias one instance across calls — in a
  long-lived worker process that is cross-query state leakage.

Rule codes (DFTPU1xx; the DFTPU0xx range is the plan verifier's,
plan/verify.py):

  DFTPU101  tracer-coercion      float()/int()/bool() in a trace path
  DFTPU102  tracer-branch        if/while/assert on a jnp/lax expression
  DFTPU103  np-in-trace          np.* call in a trace path
  DFTPU104  unordered-iteration  iterating a set/frozenset expression
  DFTPU105  time-random-in-trace time.*/random.* call in a trace path
                                 (EXCEPT the monotonic clocks —
                                 time.monotonic/perf_counter[_ns] report
                                 as DFTPU109, the tracing-span rule)
  DFTPU106  mutable-default      def f(x=[] / {} / set())
  DFTPU109  span-in-trace        tracing-span API / time.monotonic /
                                 time.perf_counter call in a trace path
                                 (distributed-tracing instrumentation is
                                 host-side only: a span opened inside a
                                 jitted function would record trace-time
                                 once and bake its clock reads into the
                                 compiled program). Takes precedence
                                 over DFTPU105 for the monotonic clocks
                                 — allowlist entries must name DFTPU109
  DFTPU110  telemetry-in-trace   telemetry / event-log API call
                                 (runtime/telemetry.py metric mutation,
                                 registry snapshot, runtime/eventlog.py
                                 log_event) in a trace path — metrics
                                 and structured events are host-side
                                 only: inside a jitted function the
                                 call runs ONCE at trace time (one
                                 phantom increment/event baked per
                                 compile, nothing per execution), and a
                                 Tracer argument in a field errors

"Trace path" = a function that executes under jax tracing: ``_execute``
and ``evaluate`` methods in the plan/ops/parallel layers, any function
passed to jit/shard_map/cond/while_loop/fori_loop/scan, nested functions
defined inside those, and (transitively, within one module) functions
they call.

Intentional exceptions live in tools/tracer_safety_allowlist.txt as
``path::RULE::qualname  # one-line justification``; the gate fails on any
finding not covered there AND on any stale allowlist entry (an entry
matching no finding is dead weight that can mask a future regression
under the same key — tools/lint_common.py, shared with the concurrency
gate). Exit code 0 = clean, 1 = violations/stale entries, 2 = usage
error.

Usage:
  python tools/check_tracer_safety.py                # lint the package
  python tools/check_tracer_safety.py FILE [FILE..]  # lint specific files
  python tools/check_tracer_safety.py --json         # machine-readable
  python tools/check_tracer_safety.py --allowlist F  # alternate allowlist
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from lint_common import (  # noqa: E402
    Finding,
    apply_allowlist,
    load_allowlist,
    report_text,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = "datafusion_distributed_tpu"
DEFAULT_ALLOWLIST = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "tracer_safety_allowlist.txt"
)

#: method names that ARE trace paths in these layers (operators trace their
#: whole pipeline; expressions evaluate inside the traced program)
TRACE_METHOD_NAMES = {"_execute", "evaluate", "_execute_mesh_arm"}
#: kernel entry points called (cross-module) from _execute during tracing —
#: the per-module call-graph closure cannot see those edges, so they seed
#: explicitly; same-module helpers they call are then traced transitively
TRACE_SEED_NAMES = {
    "hash_aggregate", "global_aggregate", "hash_join", "build_join_table",
    "sort_table", "limit_table", "window_compute", "shuffle_exchange",
    "range_shuffle_exchange", "coalesce_exchange", "broadcast_exchange",
    "group_coalesce_exchange", "expr_to_column", "concat_tables",
    "hash_columns", "pallas_multiway_probe", "pallas_global_hash_aggregate",
}
#: directories (package-relative) whose TRACE_METHOD_NAMES methods trace
TRACE_DIRS = ("ops", "plan", "parallel")
#: extra module files containing traced closures outside those directories
TRACE_FILES = ("runtime/mesh_executor.py", "runtime/mesh_worker.py")
#: calls whose function-valued arguments become traced code
TRACING_CALLS = {
    "jit", "shard_map", "_shard_map", "cond", "while_loop", "fori_loop",
    "scan", "vmap", "pmap", "checkpoint", "switch",
}
#: np.* members that construct static scalars / dtype metadata — standard
#: and safe at trace time (np.uint32(7) is a constant, not host compute)
NP_STATIC_MEMBERS = {
    "uint8", "uint16", "uint32", "uint64", "int8", "int16", "int32",
    "int64", "float16", "float32", "float64", "bool_", "dtype", "iinfo",
    "finfo", "promote_types", "result_type", "issubdtype",
}
#: jnp/lax calls that inspect dtype METADATA (static), not traced values —
#: Python branching on these is fine
TRACED_STATIC_CALLS = {
    "issubdtype", "dtype", "result_type", "promote_types", "iinfo", "finfo",
}
#: argument shapes considered static (host values) for DFTPU101
STATIC_CALLS = {"len", "round_up_pow2", "ord"}
STATIC_ATTRS = {
    "shape", "ndim", "size", "capacity", "num_slots", "out_capacity",
    "fetch", "skip", "value", "task_index", "task_count", "node_id",
}


# ---------------------------------------------------------------------------
# per-module analysis
# ---------------------------------------------------------------------------


def _dotted(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' otherwise."""
    parts: list = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, ast.Set):
        return True
    if isinstance(node, ast.Call):
        name = _dotted(node.func)
        if name in ("set", "frozenset"):
            return True
        if name.endswith((".intersection", ".union", ".difference",
                          ".symmetric_difference")):
            # conservative: only when the receiver is itself a set expr
            return isinstance(node.func, ast.Attribute) and _is_set_expr(
                node.func.value
            )
    return False


def _is_static_arg(node: ast.AST) -> bool:
    """Arguments whose float()/int()/bool() coercion is host-side by
    construction: literals, len()/env lookups, static plan attributes."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Call):
        name = _dotted(node.func)
        if name.split(".")[-1] in STATIC_CALLS:
            return True
        if name.startswith(("os.environ", "os.getenv")):
            return True
    if isinstance(node, ast.Attribute) and node.attr in STATIC_ATTRS:
        return True
    if isinstance(node, ast.Subscript):
        # x.shape[0] and friends
        return _is_static_arg(node.value)
    if isinstance(node, (ast.BinOp, ast.UnaryOp)):
        kids = ([node.operand] if isinstance(node, ast.UnaryOp)
                else [node.left, node.right])
        return all(_is_static_arg(k) for k in kids)
    if isinstance(node, ast.Name) and node.id in ("capacity", "n", "cap"):
        return True
    return False


def _contains_traced_expr(node: ast.AST) -> bool:
    """Does the expression contain a jnp/lax VALUE-producing call (a
    definite tracer branch when used as a Python condition)? Bare dtype
    attributes (``jnp.float32``) and metadata calls (``jnp.issubdtype``)
    are static and excluded."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            d = _dotted(sub.func)
            if d.startswith(("jnp.", "jax.lax.", "lax.")) and (
                d.split(".")[-1] not in TRACED_STATIC_CALLS
            ):
                return True
    return False


class _FunctionInfo:
    def __init__(self, qualname: str, node: ast.AST, parent: "str | None"):
        self.qualname = qualname
        self.node = node
        self.parent = parent  # enclosing function qualname
        self.calls: set = set()  # bare names this function calls


class _ModuleAnalyzer(ast.NodeVisitor):
    """One pass to index functions, call edges, and tracing-call seeds."""

    def __init__(self) -> None:
        self.functions: dict[str, _FunctionInfo] = {}
        self.by_name: dict[str, list] = {}  # bare name -> qualnames
        self.seeds: set = set()  # qualnames passed to jit/cond/...
        self._stack: list = []

    def _qual(self, name: str) -> str:
        return ".".join([f for f in self._stack] + [name])

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    def _visit_func(self, node) -> None:
        qual = self._qual(node.name)
        parent = ".".join(self._stack) if self._stack else None
        self.functions[qual] = _FunctionInfo(qual, node, parent)
        self.by_name.setdefault(node.name, []).append(qual)
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Call(self, node: ast.Call) -> None:
        fname = _dotted(node.func).split(".")[-1]
        if fname in TRACING_CALLS:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name):
                    self.seeds.add(arg.id)
        if self._stack:
            qual = ".".join(self._stack)
            info = self.functions.get(qual)
            if info is not None and isinstance(node.func, ast.Name):
                info.calls.add(node.func.id)
        self.generic_visit(node)


def _trace_path_functions(analyzer: _ModuleAnalyzer, relpath: str) -> set:
    """Fixpoint of: seed methods by name/layer, functions passed to tracing
    calls, their nested functions, and (same-module) callees."""
    parts = relpath.split("/")
    # classify by components so files outside the repo (the seeded-violation
    # tests lint temp copies) still land in the right layer
    sub = parts[parts.index(PACKAGE) + 1:] if PACKAGE in parts else parts
    in_trace_layer = (len(sub) >= 2 and sub[0] in TRACE_DIRS) or (
        "/".join(sub[-2:]) in TRACE_FILES
    )
    traced: set = set()
    for qual, info in analyzer.functions.items():
        bare = qual.split(".")[-1]
        if in_trace_layer and bare in TRACE_METHOD_NAMES:
            traced.add(qual)
        if in_trace_layer and bare in TRACE_SEED_NAMES:
            traced.add(qual)
        if bare in analyzer.seeds:
            traced.add(qual)
        for dec in getattr(info.node, "decorator_list", ()):
            d = _dotted(dec if not isinstance(dec, ast.Call) else dec.func)
            if d.split(".")[-1] in ("jit",):
                traced.add(qual)
    changed = True
    while changed:
        changed = False
        for qual, info in analyzer.functions.items():
            if qual in traced:
                continue
            # nested inside a traced function -> traced (defined+called at
            # trace time)
            if info.parent and any(
                t == info.parent or info.parent.startswith(t + ".")
                for t in traced
            ):
                traced.add(qual)
                changed = True
                continue
            # called from a traced function in this module -> traced
            bare = qual.split(".")[-1]
            for t in traced:
                tinfo = analyzer.functions.get(t)
                if tinfo is not None and bare in tinfo.calls:
                    traced.add(qual)
                    changed = True
                    break
    return traced


class _RuleVisitor(ast.NodeVisitor):
    def __init__(self, relpath: str, traced: set,
                 findings: list) -> None:
        self.relpath = relpath
        self.traced = traced
        self.findings = findings
        self._stack: list = []

    # -- helpers ------------------------------------------------------------
    def _qual(self) -> str:
        return ".".join(self._stack) if self._stack else "<module>"

    def _in_trace_path(self) -> bool:
        qual = self._qual()
        return any(
            qual == t or qual.startswith(t + ".") for t in self.traced
        )

    def _emit(self, node: ast.AST, rule: str, message: str) -> None:
        self.findings.append(Finding(
            self.relpath, getattr(node, "lineno", 0), rule, self._qual(),
            message,
        ))

    # -- structure ----------------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    def _visit_func(self, node) -> None:
        defaults = list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None
        ]
        for d in defaults:
            mutable = isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(d, ast.Call)
                and _dotted(d.func) in ("list", "dict", "set")
                and not d.args and not d.keywords
            )
            if mutable:
                self.findings.append(Finding(
                    self.relpath, d.lineno, "DFTPU106",
                    ".".join(self._stack + [node.name]),
                    "mutable default argument is shared across calls "
                    "(cross-query state on a long-lived worker); default "
                    "to None and allocate inside",
                ))
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # -- rules --------------------------------------------------------------
    @staticmethod
    def _is_tracing_api(name: str) -> bool:
        """Calls that belong to the distributed-tracing span surface
        (runtime/tracing.py): any receiver/attribute chain naming a
        tracer (`self._tracer.span`, `tr.event`, `NULL_TRACER...`), the
        module-level span constructors, and the monotonic clocks the
        span layer is built on."""
        if name in ("time.monotonic", "time.perf_counter",
                    "time.perf_counter_ns", "time.monotonic_ns"):
            return True
        parts = name.split(".")
        if any("tracer" in p.lower() for p in parts):
            return True
        return parts[-1] in ("start_span", "end_span", "worker_span",
                             "finish_reserved") or (
            len(parts) > 1 and parts[-1] in ("span", "event")
            and parts[-2] in ("tr", "tracing")
        )

    @staticmethod
    def _is_telemetry_api(name: str) -> bool:
        """Calls that belong to the telemetry / event-log surface
        (runtime/telemetry.py, runtime/eventlog.py): any receiver or
        attribute chain naming a telemetry object (`self.telemetry...`,
        `registry.counter`, `eventlog.log`), the module-level
        `log_event`, and metric-mutation methods on receivers that look
        like metrics (`*_counter.inc`, `hist.observe`)."""
        parts = name.split(".")
        if any("telemetry" in p.lower() or "eventlog" in p.lower()
               for p in parts):
            return True
        if parts[-1] in ("log_event", "render_openmetrics",
                         "merge_snapshots"):
            return True
        if len(parts) > 1 and parts[-1] in ("inc", "dec", "observe",
                                            "set_function"):
            recv = parts[-2].lower()
            return any(h in recv for h in (
                "counter", "gauge", "histogram", "metric", "_tm_",
            )) or recv.startswith("tm_") or recv.endswith("_tm")
        return False

    def visit_Call(self, node: ast.Call) -> None:
        name = _dotted(node.func)
        if self._in_trace_path():
            if self._is_telemetry_api(name):
                self._emit(
                    node, "DFTPU110",
                    f"{name}() inside a traced function: telemetry and "
                    "event-log instrumentation must stay host-side — "
                    "under jit the call runs once at trace time (one "
                    "phantom increment/event per COMPILE, nothing per "
                    "execution) and a Tracer argument errors",
                )
            elif self._is_tracing_api(name):
                self._emit(
                    node, "DFTPU109",
                    f"{name}() inside a traced function: tracing "
                    "instrumentation must stay host-side — a span or "
                    "monotonic-clock read under jit runs once at trace "
                    "time and bakes that instant into every compiled "
                    "re-execution (and times nothing)",
                )
            elif name in ("float", "int", "bool") and node.args and not (
                _is_static_arg(node.args[0])
            ):
                self._emit(
                    node, "DFTPU101",
                    f"{name}() coercion inside a traced function: on a "
                    "Tracer this raises (or bakes a trace-time constant); "
                    "use jnp casts / keep the value traced",
                )
            elif (name.startswith("np.") or name.startswith("numpy.")) and (
                name.split(".")[-1] not in NP_STATIC_MEMBERS
            ):
                self._emit(
                    node, "DFTPU103",
                    f"{name}() inside a traced function: numpy executes "
                    "at trace time on host — a Tracer argument errors, a "
                    "static argument silently bakes a constant; use jnp "
                    "or hoist to load time",
                )
            elif name.split(".")[0] in ("time", "random"):
                self._emit(
                    node, "DFTPU105",
                    f"{name}() inside a traced function: evaluated once "
                    "at trace time, every compiled re-execution replays "
                    "that single value (nondeterministic across "
                    "processes, stale within one)",
                )
        self.generic_visit(node)

    def _check_branch(self, node, test) -> None:
        if self._in_trace_path() and _contains_traced_expr(test):
            self._emit(
                node, "DFTPU102",
                "Python control flow on a jnp/lax expression inside a "
                "traced function: raises ConcretizationTypeError under "
                "jit; use jnp.where / lax.cond",
            )

    def visit_If(self, node: ast.If) -> None:
        self._check_branch(node, node.test)
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_branch(node, node.test)
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        self._check_branch(node, node.test)
        self.generic_visit(node)

    def _check_iter(self, node, it) -> None:
        if _is_set_expr(it):
            self._emit(
                node, "DFTPU104",
                "iteration over an unordered set expression: order "
                "follows the process hash seed, breaking byte-identical "
                "plans/fingerprints across processes; wrap in sorted()",
            )

    def visit_For(self, node: ast.For) -> None:
        self._check_iter(node, node.iter)
        self.generic_visit(node)

    def visit_comprehension(self, node: ast.comprehension) -> None:
        self._check_iter(node, node.iter)
        self.generic_visit(node)

    def visit_Call_iterables(self, node):  # pragma: no cover - helper
        pass


def _lint_file(path: str, findings: list) -> None:
    relpath = os.path.relpath(path, REPO_ROOT).replace(os.sep, "/")
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        findings.append(Finding(relpath, e.lineno or 0, "DFTPU100",
                                "<module>", f"syntax error: {e.msg}"))
        return
    analyzer = _ModuleAnalyzer()
    analyzer.visit(tree)
    traced = _trace_path_functions(analyzer, relpath)
    # list()/tuple()/sorted-free join over set expressions at any position
    rv = _RuleVisitor(relpath, traced, findings)
    rv.visit(tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name in ("list", "tuple") and node.args and _is_set_expr(
                node.args[0]
            ):
                findings.append(Finding(
                    relpath, node.lineno, "DFTPU104", "<module>",
                    f"{name}() over an unordered set expression: element "
                    "order follows the process hash seed; wrap in "
                    "sorted()",
                ))


def _package_files() -> list:
    out: list = []
    pkg_root = os.path.join(REPO_ROOT, PACKAGE)
    for dirpath, _dirs, files in os.walk(pkg_root):
        for f in sorted(files):
            if f.endswith(".py"):
                out.append(os.path.join(dirpath, f))
    return sorted(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("files", nargs="*",
                    help="files to lint (default: the whole package)")
    ap.add_argument("--allowlist", default=DEFAULT_ALLOWLIST)
    ap.add_argument("--json", action="store_true",
                    help="emit findings as JSON")
    args = ap.parse_args(argv)

    files = args.files or _package_files()
    for f in files:
        if not os.path.exists(f):
            print(f"no such file: {f}", file=sys.stderr)
            return 2
    findings: list = []
    for f in files:
        _lint_file(os.path.abspath(f), findings)

    allow = load_allowlist(args.allowlist)
    violations, allowed, stale = apply_allowlist(
        findings, allow, check_stale=not args.files
    )

    if args.json:
        # stdout is the JSON document, nothing else — machine consumers
        # json.loads() it directly; the verdict rides the exit code
        print(json.dumps({
            "violations": [f.__dict__ for f in violations],
            "allowed": [f.__dict__ for f in allowed],
            "stale_allowlist": [list(k) for k in stale],
        }, indent=2))
        return 1 if (violations or stale) else 0
    return report_text(violations, allowed, stale, args.allowlist,
                       REPO_ROOT, "tracer-safety", len(files))


if __name__ == "__main__":
    sys.exit(main())
