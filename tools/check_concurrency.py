#!/usr/bin/env python
"""Concurrency-safety lint for datafusion_distributed_tpu.

A pure-AST analyzer (stdlib only — no jax import, no device, no network,
sub-second) for the failure modes PRs 4-8 made possible: the runtime is
now heavily concurrent (stage-DAG fan-out threads, the multi-query
serving tier, a shared TableStore) and protected by ~40 ad-hoc
``threading.Lock``/``RLock``/``Condition`` sites whose conventions
nothing enforced. The Rust reference gets this safety from ``Send``/
``Sync`` at the type level (SURVEY §L0); this tool is the Python-side
equivalent: a declarative concurrency model plus a lint that holds the
code to it.

The declarative model: a threaded class declares which lock guards each
shared field, either with a trailing comment on the field's init ::

    self._pending = []  # guarded-by: _lock

or with a class-level map (for dataclasses / lazily-created fields) ::

    _GUARDED_BY = {"_span_shipped": "_span_lock"}

``threading.Condition(self._lock)`` aliases are resolved — holding the
condition IS holding the lock. Construction (``__init__``/
``__post_init__``/``__new__``) is exempt (happens-before publication),
and the ``*_locked``-suffix method convention means "caller holds the
lock".

Rule codes (DFTPU2xx; DFTPU0xx is the plan verifier's, DFTPU1xx the
tracer-safety lint's):

  DFTPU201  unguarded-write      write / augmented write / del /
                                 container mutation of a declared
                                 guarded field outside a ``with
                                 self._lock`` block or a ``*_locked``
                                 method
  DFTPU202  locked-reacquire     a ``*_locked`` method acquiring its own
                                 class's lock (the suffix PROMISES the
                                 caller holds it; acquiring again
                                 deadlocks a plain Lock)
  DFTPU203  unlocked-helper-call calling a ``*_locked`` helper with no
                                 lock held on the calling path
  DFTPU204  guarded-escape       ``return``/``yield`` of a direct
                                 reference to a guarded MUTABLE
                                 container (hand out a snapshot copy;
                                 the reference escapes the lock)
  DFTPU205  blocking-while-locked a blocking call — RPC dispatch
                                 (set_plan / set_stage_plan /
                                 execute_task*), cf.wait / Future
                                 .result, Event.wait, time.sleep, XLA
                                 compile entry points — while holding a
                                 lock
  DFTPU206  lock-order-cycle     a cycle in the static nested-
                                 acquisition graph (built from ``with``
                                 nesting and cross-class calls): a
                                 potential deadlock
  DFTPU207  same-lock-reentry    re-acquiring a NON-reentrant Lock
                                 already held on the same path (lexical
                                 nesting or a transitive call) — a
                                 guaranteed self-deadlock

The nested-acquisition graph this tool builds is also the contract the
runtime checker (datafusion_distributed_tpu/runtime/lockcheck.py,
``DFTPU_LOCK_CHECK=1``) asserts OBSERVED acquisition order against;
``--json`` includes it under ``lock_graph``.

Intentional exceptions live in tools/concurrency_allowlist.txt as
``path::RULE::qualname  # one-line justification``; the gate fails on
any finding not covered there AND on any stale entry. Exit code 0 =
clean, 1 = violations/stale entries, 2 = usage error.

Usage:
  python tools/check_concurrency.py                # lint the package
  python tools/check_concurrency.py FILE [FILE..]  # lint specific files
  python tools/check_concurrency.py --json         # machine-readable
  python tools/check_concurrency.py --allowlist F  # alternate allowlist
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from lint_common import (  # noqa: E402
    Finding,
    apply_allowlist,
    load_allowlist,
    report_text,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = "datafusion_distributed_tpu"
DEFAULT_ALLOWLIST = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "concurrency_allowlist.txt"
)

GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)")

#: threading factory -> lock kind. "lock" is the only NON-reentrant kind
#: (DFTPU207); Condition carries its wrapped lock's kind via aliasing.
_LOCK_FACTORIES = {"Lock": "lock", "RLock": "rlock",
                   "Condition": "condition"}
#: methods that run happens-before publication of self
_INIT_METHODS = {"__init__", "__post_init__", "__new__", "__init_subclass__"}
#: container-mutating method names (rule 201's "container mutation")
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "add", "discard",
    "remove", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "sort", "reverse",
}
#: calls that construct a mutable container (rule 204 typing + aliasing)
_MUTABLE_CTORS = {"list", "dict", "set", "deque", "defaultdict",
                  "OrderedDict", "Counter"}
#: dotted names (exact) that block (rule 205)
_BLOCKING_EXACT = {
    "time.sleep", "cf.wait", "futures.wait", "concurrent.futures.wait",
}
#: last-attribute names that block regardless of receiver (rule 205):
#: the worker RPC dispatch surface + XLA compile entry points + the
#: hedge-dispatch entry points (runtime/coordinator.py straggler
#: hedging: each spawns/awaits speculative RPC attempts — a hedge issued
#: under a lock would stall every contending thread for a full race)
_BLOCKING_TAIL = {
    "set_plan", "set_stage_plan", "execute_task", "execute_task_stream",
    "execute_task_partitions", "execute_plan", "block_until_ready",
    "_execute_attempt", "_dispatch_hedge", "_hedged_execute",
    "_hedged_first_chunk",
    # spill-segment I/O entry points (runtime/spill.py): encoding a
    # table to disk / decoding it back must never run under a store
    # lock — the TableStore picks victims locked, does the I/O
    # unlocked, then re-acquires to swap the entry
    "write_spill", "read_spill",
    # shm-plane I/O entry points (runtime/shm_plane.py SegmentPool):
    # segment publish/link/read are tmpfs I/O under the same
    # decide-locked / do-unlocked / account-locked discipline
    "publish", "publish_file", "open_segment",
}
#: receiver hints for ``.wait()`` / ``.result()`` blocking calls — an
#: ``Event.wait`` or ``Future.result`` under a lock stalls every other
#: holder; a Condition's own ``.wait`` RELEASES the lock and is excluded
#: by comparing against the held with-expressions
_WAIT_RECEIVER_HINTS = ("event", "done", "cancel", "stop", "future", "fut")
#: identifier fragments that make a non-Call ``with`` expression count as
#: a lock acquisition
_LOCKISH_FRAGMENTS = ("lock", "_cv", "cond", "mutex", "sem", "gate")


def _dotted(node: ast.AST) -> str:
    parts: list = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_mutable_init(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        name = _dotted(node.func).split(".")[-1]
        return name in _MUTABLE_CTORS
    return False


def _ann_names(node) -> list:
    """All identifiers inside an annotation node (handles string
    annotations like 'TableStore' and Optional[X] nesting)."""
    out: list = []
    if node is None:
        return out
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return re.findall(r"[A-Za-z_]\w*", node.value)
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            out.append(sub.id)
        elif isinstance(sub, ast.Attribute):
            out.append(sub.attr)
        elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            out.extend(re.findall(r"[A-Za-z_]\w*", sub.value))
    return out


class ClassInfo:
    def __init__(self, name: str, module: str) -> None:
        self.name = name
        self.module = module  # repo-relative path
        self.guarded: dict = {}        # field -> lock attr (canonical)
        self.locks: dict = {}          # lock attr -> kind
        self.aliases: dict = {}        # condition attr -> wrapped lock attr
        self.mutable_fields: set = set()
        self.attr_type_raw: dict = {}  # attr -> candidate class-name str
        self.attr_types: dict = {}     # attr -> ClassInfo (resolved)
        self.methods: dict = {}        # name -> FuncRecord

    def canon_lock(self, attr: str) -> str:
        seen = set()
        while attr in self.aliases and attr not in seen:
            seen.add(attr)
            attr = self.aliases[attr]
        return attr

    def lock_id(self, attr: str) -> str:
        return f"{self.name}.{self.canon_lock(attr)}"

    def lock_kind(self, attr: str) -> str:
        return self.locks.get(self.canon_lock(attr), "unknown")


class FuncRecord:
    def __init__(self, qualname: str, cls, module: str) -> None:
        self.qualname = qualname
        self.cls = cls  # ClassInfo or None
        self.module = module
        #: lock ids this function acquires directly via ``with``
        self.acquires: set = set()
        #: calls made: (held_lock_id_or_None, func_dotted, lineno)
        self.calls: list = []
        #: transitively acquired lock ids (fixpoint-filled)
        self.closure: set = set()


class Analysis:
    def __init__(self) -> None:
        self.classes: dict = {}        # name -> ClassInfo
        self.module_locks: dict = {}   # (module, name) -> kind
        self.module_types: dict = {}   # (module, name) -> class name str
        self.module_funcs: dict = {}   # (module, name) -> FuncRecord
        self.findings: list = []
        #: (src_id, dst_id) -> (path, line, qualname) first site
        self.edges: dict = {}
        #: lock id -> kind
        self.lock_kinds: dict = {}


# ---------------------------------------------------------------------------
# pass 1: class / lock / guarded-field indexing
# ---------------------------------------------------------------------------


def _index_module(tree: ast.Module, relpath: str, lines: list,
                  an: Analysis) -> None:
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            _index_class(node, relpath, lines, an)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 and (
            isinstance(node.targets[0], ast.Name)
        ):
            name = node.targets[0].id
            kind = _lock_call_kind(node.value)
            if kind is not None:
                an.module_locks[(relpath, name)] = kind
                an.lock_kinds[f"{relpath}:{name}"] = kind
            elif isinstance(node.value, ast.Call):
                cname = _dotted(node.value.func).split(".")[-1]
                if cname and cname[0].isupper():
                    an.module_types[(relpath, name)] = cname


def _lock_call_kind(value: ast.AST):
    """'lock'/'rlock'/'condition' when ``value`` constructs one (directly
    or via dataclasses.field(default_factory=threading.Lock))."""
    if not isinstance(value, ast.Call):
        return None
    tail = _dotted(value.func).split(".")[-1]
    if tail in _LOCK_FACTORIES:
        return _LOCK_FACTORIES[tail]
    if tail == "field":
        for kw in value.keywords:
            if kw.arg == "default_factory":
                t2 = _dotted(kw.value).split(".")[-1]
                if t2 in _LOCK_FACTORIES:
                    return _LOCK_FACTORIES[t2]
    return None


def _index_class(cnode: ast.ClassDef, relpath: str, lines: list,
                 an: Analysis) -> None:
    ci = an.classes.setdefault(cnode.name, ClassInfo(cnode.name, relpath))

    def guarded_comment(lineno: int):
        if 1 <= lineno <= len(lines):
            m = GUARDED_RE.search(lines[lineno - 1])
            if m:
                return m.group(1)
        return None

    def note_self_assign(target: ast.AST, value, lineno: int,
                         annotation=None, in_init: bool = False,
                         func_args=None) -> None:
        if not (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"):
            return
        attr = target.attr
        kind = _lock_call_kind(value) if value is not None else None
        if kind is not None:
            ci.locks[attr] = kind
            if kind == "condition" and isinstance(value, ast.Call) and (
                value.args
            ):
                wrapped = value.args[0]
                if isinstance(wrapped, ast.Attribute) and isinstance(
                    wrapped.value, ast.Name
                ) and wrapped.value.id == "self":
                    ci.aliases[attr] = wrapped.attr
            return
        g = guarded_comment(lineno)
        if g is not None:
            ci.guarded[attr] = g
            if value is not None and _is_mutable_init(value):
                ci.mutable_fields.add(attr)
        # attr type: self.X = ClassName(...) / annotated / self.X = param
        cand = None
        if isinstance(value, ast.Call):
            n = _dotted(value.func).split(".")[-1]
            if n and n[0].isupper():
                cand = n
        elif isinstance(value, ast.Name) and func_args is not None:
            ann = func_args.get(value.id)
            for n in _ann_names(ann):
                if n and n[0].isupper():
                    cand = n
                    break
        if cand is None and annotation is not None:
            for n in _ann_names(annotation):
                if n and n[0].isupper() and n not in (
                    "Optional", "None", "Dict", "List", "Set", "Tuple",
                    "Callable", "Any",
                ):
                    cand = n
                    break
        if cand is not None:
            ci.attr_type_raw.setdefault(attr, cand)

    # class-level statements
    for stmt in cnode.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and (
            isinstance(stmt.targets[0], ast.Name)
        ):
            name = stmt.targets[0].id
            if name == "_GUARDED_BY" and isinstance(stmt.value, ast.Dict):
                for k, v in zip(stmt.value.keys, stmt.value.values):
                    if isinstance(k, ast.Constant) and isinstance(
                        v, ast.Constant
                    ):
                        ci.guarded[str(k.value)] = str(v.value)
                continue
            kind = _lock_call_kind(stmt.value)
            if kind is not None:
                ci.locks[name] = kind
                continue
            g = guarded_comment(stmt.lineno)
            if g is not None:
                ci.guarded[name] = g
                if _is_mutable_init(stmt.value):
                    ci.mutable_fields.add(name)
        elif isinstance(stmt, ast.AnnAssign) and isinstance(
            stmt.target, ast.Name
        ):
            # dataclass field
            name = stmt.target.id
            kind = _lock_call_kind(stmt.value) if stmt.value else None
            if kind is None and any(
                n in _LOCK_FACTORIES for n in _ann_names(stmt.annotation)
            ):
                for n in _ann_names(stmt.annotation):
                    if n in _LOCK_FACTORIES:
                        kind = _LOCK_FACTORIES[n]
                        break
            if kind is not None:
                ci.locks[name] = kind
                continue
            g = guarded_comment(stmt.lineno)
            if g is not None:
                ci.guarded[name] = g
                if stmt.value is not None and (
                    _is_mutable_init(stmt.value)
                    or (_lock_call_kind(stmt.value) is None
                        and isinstance(stmt.value, ast.Call)
                        and _dotted(stmt.value.func).split(".")[-1]
                        == "field")
                ):
                    # field(default_factory=dict/list/set)
                    if isinstance(stmt.value, ast.Call):
                        for kw in stmt.value.keywords:
                            if kw.arg == "default_factory" and _dotted(
                                kw.value
                            ).split(".")[-1] in _MUTABLE_CTORS:
                                ci.mutable_fields.add(name)
                    else:
                        ci.mutable_fields.add(name)
            for n in _ann_names(stmt.annotation):
                if n and n[0].isupper() and n not in (
                    "Optional", "Callable", "Any",
                ):
                    ci.attr_type_raw.setdefault(name, n)
                    break

    # method bodies: lock creation, guarded comments, attr types
    for stmt in cnode.body:
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        in_init = stmt.name in _INIT_METHODS
        func_args = {
            a.arg: a.annotation
            for a in (list(stmt.args.posonlyargs) + list(stmt.args.args)
                      + list(stmt.args.kwonlyargs))
        }
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Assign):
                for t in sub.targets:
                    note_self_assign(t, sub.value, sub.lineno,
                                     in_init=in_init, func_args=func_args)
            elif isinstance(sub, ast.AnnAssign):
                note_self_assign(sub.target, sub.value, sub.lineno,
                                 annotation=sub.annotation,
                                 in_init=in_init, func_args=func_args)


# ---------------------------------------------------------------------------
# pass 2: rules + graph
# ---------------------------------------------------------------------------


class _Held:
    __slots__ = ("ident", "kind", "text")

    def __init__(self, ident: str, kind: str, text: str) -> None:
        self.ident = ident   # canonical lock id, or "" for unresolved
        self.kind = kind
        self.text = text     # the with-expression's dotted/source text


class _ModuleChecker:
    def __init__(self, relpath: str, an: Analysis, findings: list) -> None:
        self.relpath = relpath
        self.an = an
        self.findings = findings
        self.cls: "ClassInfo | None" = None
        self.func_stack: list = []       # function name parts
        self.func_rec: "FuncRecord | None" = None
        self.held: list = []             # _Held, innermost last

    # -- helpers ------------------------------------------------------------
    def _qual(self) -> str:
        return ".".join(
            ([self.cls.name] if self.cls else []) + self.func_stack
        ) or "<module>"

    def _emit(self, node, rule: str, message: str) -> None:
        self.findings.append(Finding(
            self.relpath, getattr(node, "lineno", 0), rule, self._qual(),
            message,
        ))

    def _in_exempt_func(self) -> bool:
        return any(
            f in _INIT_METHODS or f.endswith("_locked")
            for f in self.func_stack
        )

    def _caller_holds_by_convention(self) -> bool:
        return any(f.endswith("_locked") for f in self.func_stack)

    def _held_ids(self) -> set:
        ids = {h.ident for h in self.held if h.ident}
        if self._caller_holds_by_convention() and self.cls is not None:
            # a *_locked method runs with its class's lock held; with
            # exactly one lock on the class the identity is unambiguous
            canon = {self.cls.canon_lock(a) for a in self.cls.locks}
            if len(canon) == 1:
                ids.add(f"{self.cls.name}.{next(iter(canon))}")
        return ids

    def _resolve_lock_expr(self, expr: ast.AST) -> "_Held | None":
        """Lock identity/kind of a with-context expression (None = not
        lock-like)."""
        if isinstance(expr, ast.Call):
            return None
        text = _dotted(expr)
        if isinstance(expr, ast.Subscript):
            base = _dotted(expr.value)
            key = ""
            if isinstance(expr.slice, ast.Constant):
                key = str(expr.slice.value)
            text = f"{base}[{key}]"
        if not text:
            return None
        lowered = text.lower()
        parts = text.split(".")
        ident, kind = "", "unknown"
        cls = self.cls
        if parts[0] == "self" and cls is not None and len(parts) == 2:
            attr = parts[1]
            if attr in cls.locks or attr in cls.aliases:
                ident = cls.lock_id(attr)
                kind = cls.lock_kind(attr)
        elif parts[0] == "self" and cls is not None and len(parts) == 3:
            # with self.<attr>.<lockattr>: resolve <attr>'s class
            target = cls.attr_types.get(parts[1])
            if target is not None and (
                parts[2] in target.locks or parts[2] in target.aliases
            ):
                ident = target.lock_id(parts[2])
                kind = target.lock_kind(parts[2])
        elif len(parts) == 1:
            key = (self.relpath, parts[0])
            if key in self.an.module_locks:
                ident = f"{self.relpath}:{parts[0]}"
                kind = self.an.module_locks[key]
        elif len(parts) >= 2:
            # Class.lockattr (possibly module-prefixed: _w.Worker._lock)
            target = self.an.classes.get(parts[-2])
            if target is not None and (
                parts[-1] in target.locks or parts[-1] in target.aliases
            ):
                ident = target.lock_id(parts[-1])
                kind = target.lock_kind(parts[-1])
        if not ident and not any(
            frag in lowered for frag in _LOCKISH_FRAGMENTS
        ):
            return None
        if ident:
            self.an.lock_kinds.setdefault(ident, kind)
        return _Held(ident, kind, text)

    def _attr_class(self, name: str) -> "ClassInfo | None":
        cls = self.cls
        if cls is not None:
            t = cls.attr_types.get(name)
            if t is not None:
                return t
        cname = self.an.module_types.get((self.relpath, name))
        if cname is not None:
            return self.an.classes.get(cname)
        return None

    # -- module entry -------------------------------------------------------
    def run(self, tree: ast.Module) -> None:
        self._stmts(tree.body)

    def _stmts(self, stmts) -> None:
        for s in stmts:
            self._stmt(s)

    def _stmt(self, node) -> None:
        if isinstance(node, ast.ClassDef):
            prev_cls, prev_stack = self.cls, self.func_stack
            self.cls = self.an.classes.get(node.name, None)
            self.func_stack = []
            self._stmts(node.body)
            self.cls, self.func_stack = prev_cls, prev_stack
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            prev_rec, prev_held = self.func_rec, self.held
            self.func_stack.append(node.name)
            # nested defs execute later: lock state does not carry in
            self.held = []
            qual = self._qual()
            rec = FuncRecord(qual, self.cls, self.relpath)
            self.func_rec = rec
            if self.cls is not None and len(self.func_stack) == 1:
                self.cls.methods[node.name] = rec
            elif self.cls is None and len(self.func_stack) == 1:
                self.an.module_funcs[(self.relpath, node.name)] = rec
            if node.name.endswith("_locked"):
                self._check_202(node)
            self._stmts(node.body)
            self.func_stack.pop()
            self.func_rec, self.held = prev_rec, prev_held
            return
        if isinstance(node, ast.With) or isinstance(node, ast.AsyncWith):
            pushed = 0
            for item in node.items:
                h = self._resolve_lock_expr(item.context_expr)
                if h is None:
                    self._exprs(item.context_expr)
                    continue
                self._acquire(h, node)
                self.held.append(h)
                pushed += 1
            self._stmts(node.body)
            for _ in range(pushed):
                self.held.pop()
            return
        if isinstance(node, (ast.If,)):
            self._exprs(node.test)
            self._stmts(node.body)
            self._stmts(node.orelse)
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            self._exprs(node.iter)
            self._check_write_target(node.target, node)
            self._stmts(node.body)
            self._stmts(node.orelse)
            return
        if isinstance(node, ast.While):
            self._exprs(node.test)
            self._stmts(node.body)
            self._stmts(node.orelse)
            return
        if isinstance(node, ast.Try):
            self._stmts(node.body)
            for h in node.handlers:
                self._stmts(h.body)
            self._stmts(node.orelse)
            self._stmts(node.finalbody)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                self._check_write_target(t, node)
            if getattr(node, "value", None) is not None:
                self._exprs(node.value)
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                self._check_write_target(t, node)
            return
        if isinstance(node, (ast.Return, ast.Expr)):
            val = node.value
            if isinstance(node, ast.Expr) and isinstance(val, (ast.Yield,
                                                               ast.YieldFrom)):
                val = val.value
                self._check_204(val, node)
                if val is not None:
                    self._exprs(val)
                return
            if isinstance(node, ast.Return):
                self._check_204(val, node)
            if val is not None:
                self._exprs(val)
            return
        # default: visit expressions of the statement
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._exprs(child)
            elif isinstance(child, ast.stmt):
                self._stmt(child)

    # -- expression walking (calls) ----------------------------------------
    def _exprs(self, expr) -> None:
        if expr is None:
            return
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                self._check_call(sub)

    # -- rule 201 -----------------------------------------------------------
    def _guard_of(self, attr: str):
        cls = self.cls
        if cls is None or attr not in cls.guarded:
            return None
        return f"{cls.name}.{cls.canon_lock(cls.guarded[attr])}"

    def _check_write_target(self, target, node) -> None:
        # self.F = / self.F op= / del self.F / self.F[k] =
        attr = None
        if isinstance(target, ast.Attribute) and isinstance(
            target.value, ast.Name
        ) and target.value.id == "self":
            attr = target.attr
        elif isinstance(target, ast.Subscript):
            base = target.value
            if isinstance(base, ast.Attribute) and isinstance(
                base.value, ast.Name
            ) and base.value.id == "self":
                attr = base.attr
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._check_write_target(elt, node)
            return
        if attr is None:
            return
        need = self._guard_of(attr)
        if need is None or self._in_exempt_func():
            return
        if need not in self._held_ids():
            self._emit(
                node, "DFTPU201",
                f"write to guarded field self.{attr} without holding "
                f"{need.split('.')[-1]} (declared `guarded-by`); wrap in "
                f"`with self.{need.split('.')[-1]}:` or move into a "
                "*_locked helper",
            )

    # -- rule 202 -----------------------------------------------------------
    def _check_202(self, fnode) -> None:
        cls = self.cls
        if cls is None:
            return
        own = {f"{cls.name}.{cls.canon_lock(a)}" for a in cls.locks}
        for sub in ast.walk(fnode):
            if isinstance(sub, (ast.With, ast.AsyncWith)):
                for item in sub.items:
                    h = self._resolve_lock_expr_in(item.context_expr, cls)
                    if h is not None and h.ident in own:
                        self.findings.append(Finding(
                            self.relpath, sub.lineno, "DFTPU202",
                            f"{cls.name}.{fnode.name}",
                            f"*_locked method acquires {h.text} itself: "
                            "the suffix promises the CALLER holds the "
                            "lock; acquiring again self-deadlocks a "
                            "plain Lock",
                        ))

    def _resolve_lock_expr_in(self, expr, cls):
        prev, self.cls = self.cls, cls
        try:
            return self._resolve_lock_expr(expr)
        finally:
            self.cls = prev

    # -- rule 203 / 205 / graph (calls) -------------------------------------
    def _check_call(self, node: ast.Call) -> None:
        name = _dotted(node.func)
        tail = name.split(".")[-1] if name else ""
        held = self.held[-1] if self.held else None
        # record for the cross-class graph
        if self.func_rec is not None and name:
            self.func_rec.calls.append(
                (held.ident if held and held.ident else None, name,
                 node.lineno)
            )
        # 201: container mutation through self.F.<mutator>(...)
        if tail in _MUTATORS and isinstance(node.func, ast.Attribute):
            recv = node.func.value
            if isinstance(recv, ast.Attribute) and isinstance(
                recv.value, ast.Name
            ) and recv.value.id == "self":
                need = self._guard_of(recv.attr)
                if need is not None and not self._in_exempt_func() and (
                    need not in self._held_ids()
                ):
                    self._emit(
                        node, "DFTPU201",
                        f"mutation self.{recv.attr}.{tail}() of a guarded "
                        f"container without holding "
                        f"{need.split('.')[-1]} (declared `guarded-by`)",
                    )
        # 203: *_locked helper call without the lock
        if tail.endswith("_locked") and not self._in_exempt_func():
            if not self._held_ids() and not self.held:
                self._emit(
                    node, "DFTPU203",
                    f"call to {name}() with no lock held on this path: "
                    "the *_locked suffix means the callee expects its "
                    "lock already held",
                )
        # 205: blocking call while holding a lock
        if self.held:
            blocking = None
            if name in _BLOCKING_EXACT:
                blocking = name
            elif tail in _BLOCKING_TAIL:
                blocking = name
            elif tail == "wait" and "." in name:
                recv_text = name.rsplit(".", 1)[0]
                if all(h.text != recv_text for h in self.held) and any(
                    hint in recv_text.lower()
                    for hint in _WAIT_RECEIVER_HINTS
                ):
                    blocking = name
            elif tail == "result" and "." in name:
                recv_text = name.rsplit(".", 1)[0].lower()
                if any(h in recv_text for h in ("fut", "future")):
                    blocking = name
            if blocking is not None:
                locks = ", ".join(
                    h.ident or h.text for h in self.held
                )
                self._emit(
                    node, "DFTPU205",
                    f"blocking call {blocking}() while holding {locks}: "
                    "every other thread contending that lock stalls "
                    "behind this RPC/wait/compile; move the slow work "
                    "outside the critical section",
                )

    # -- rule 204 -----------------------------------------------------------
    def _check_204(self, val, node) -> None:
        if val is None or self.cls is None:
            return
        vals = val.elts if isinstance(val, ast.Tuple) else [val]
        for v in vals:
            if isinstance(v, ast.Attribute) and isinstance(
                v.value, ast.Name
            ) and v.value.id == "self":
                attr = v.attr
                if attr in self.cls.guarded and (
                    attr in self.cls.mutable_fields
                ):
                    self._emit(
                        node, "DFTPU204",
                        f"returns/yields a direct reference to guarded "
                        f"mutable container self.{attr}: the reference "
                        "escapes the lock and callers iterate/mutate it "
                        "unprotected; hand out a snapshot copy "
                        f"(e.g. dict(self.{attr}) / list(self.{attr}))",
                    )

    # -- graph edges --------------------------------------------------------
    def _acquire(self, h: _Held, node) -> None:
        if not h.ident:
            return
        held_ids = [x for x in self.held if x.ident]
        if held_ids:
            src = held_ids[-1].ident
            if src != h.ident:
                self.an.edges.setdefault(
                    (src, h.ident),
                    (self.relpath, node.lineno, self._qual()),
                )
            elif self.an.lock_kinds.get(h.ident) == "lock":
                self._emit(
                    node, "DFTPU207",
                    f"re-acquires non-reentrant {h.text} already held on "
                    "this path: guaranteed self-deadlock",
                )
        if self.func_rec is not None:
            self.func_rec.acquires.add(h.ident)


# ---------------------------------------------------------------------------
# pass 3: cross-class call closure -> edges, cycles, re-entry
# ---------------------------------------------------------------------------


def _resolve_call(name: str, rec: FuncRecord, an: Analysis):
    """-> FuncRecord of the callee, or None."""
    parts = name.split(".")
    cls = rec.cls
    if parts[0] == "self" and cls is not None:
        if len(parts) == 2:
            return cls.methods.get(parts[1])
        if len(parts) == 3:
            target = cls.attr_types.get(parts[1])
            if target is not None:
                return target.methods.get(parts[2])
        return None
    if len(parts) == 1:
        hit = an.module_funcs.get((rec.module, parts[0]))
        if hit is not None:
            return hit
        target = an.classes.get(parts[0])
        if target is not None:  # ClassName(...) -> __init__
            return target.methods.get("__init__")
        return None
    # X.m where X is a module-level instance, or Class.m
    target = None
    cname = an.module_types.get((rec.module, parts[-2]))
    if cname is not None:
        target = an.classes.get(cname)
    if target is None:
        target = an.classes.get(parts[-2])
    if target is not None:
        return target.methods.get(parts[-1])
    return None


def _close_graph(an: Analysis, findings: list) -> None:
    recs: list = []
    for ci in an.classes.values():
        recs.extend(ci.methods.values())
    recs.extend(an.module_funcs.values())
    for rec in recs:
        rec.closure = set(rec.acquires)
    changed = True
    rounds = 0
    while changed and rounds < 50:
        changed = False
        rounds += 1
        for rec in recs:
            for _held, name, _ln in rec.calls:
                callee = _resolve_call(name, rec, an)
                if callee is None:
                    continue
                add = callee.closure - rec.closure
                if add:
                    rec.closure |= add
                    changed = True
    # call-derived edges + transitive same-lock re-entry
    for rec in recs:
        for held, name, lineno in rec.calls:
            if held is None:
                continue
            callee = _resolve_call(name, rec, an)
            if callee is None:
                continue
            for dst in sorted(callee.closure):
                if dst == held:
                    if an.lock_kinds.get(held) == "lock":
                        findings.append(Finding(
                            rec.module, lineno, "DFTPU207", rec.qualname,
                            f"holds {held} while calling {name}(), which "
                            f"(transitively) re-acquires {held}: "
                            "guaranteed self-deadlock on a "
                            "non-reentrant Lock",
                        ))
                    continue
                an.edges.setdefault(
                    (held, dst), (rec.module, lineno, rec.qualname)
                )


def _find_cycles(an: Analysis, findings: list) -> None:
    adj: dict = {}
    for (src, dst) in an.edges:
        adj.setdefault(src, set()).add(dst)
    seen_cycles: set = set()
    for start in sorted(adj):
        # DFS from each node looking for a path back to it
        stack = [(start, [start])]
        visited: set = set()
        while stack:
            node, path = stack.pop()
            for nxt in sorted(adj.get(node, ())):
                if nxt == start and len(path) > 1:
                    cyc = tuple(path)
                    canon = min(
                        tuple(cyc[i:] + cyc[:i]) for i in range(len(cyc))
                    )
                    if canon in seen_cycles:
                        continue
                    seen_cycles.add(canon)
                    edges = list(zip(path, path[1:] + [start]))
                    sites = [
                        f"{a}->{b} ({an.edges[(a, b)][0]}:"
                        f"{an.edges[(a, b)][1]})"
                        for a, b in edges if (a, b) in an.edges
                    ]
                    first = an.edges[edges[0]]
                    findings.append(Finding(
                        first[0], first[1], "DFTPU206", first[2],
                        "lock-ordering cycle (potential deadlock): "
                        + "  ".join(sites),
                    ))
                elif nxt not in path and nxt not in visited:
                    visited.add(nxt)
                    stack.append((nxt, path + [nxt]))


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def _package_files() -> list:
    out: list = []
    pkg_root = os.path.join(REPO_ROOT, PACKAGE)
    for dirpath, _dirs, files in os.walk(pkg_root):
        for f in sorted(files):
            if f.endswith(".py"):
                out.append(os.path.join(dirpath, f))
    return sorted(out)


def analyze(files: list) -> tuple:
    """-> (findings, Analysis). Pure function, importable by the runtime
    lock checker (runtime/lockcheck.py loads the static graph this way)."""
    an = Analysis()
    parsed: list = []
    findings: list = []
    for path in files:
        relpath = os.path.relpath(
            os.path.abspath(path), REPO_ROOT
        ).replace(os.sep, "/")
        with open(path, "r", encoding="utf-8") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            findings.append(Finding(relpath, e.lineno or 0, "DFTPU200",
                                    "<module>", f"syntax error: {e.msg}"))
            continue
        lines = src.splitlines()
        _index_module(tree, relpath, lines, an)
        parsed.append((tree, relpath))
    # resolve attr candidate types now every class is indexed
    for ci in an.classes.values():
        for attr, cand in ci.attr_type_raw.items():
            hit = an.classes.get(cand)
            if hit is not None:
                ci.attr_types[attr] = hit
    for tree, relpath in parsed:
        _ModuleChecker(relpath, an, findings).run(tree)
    _close_graph(an, findings)
    _find_cycles(an, findings)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, an


def build_lock_graph(files=None) -> dict:
    """Static nested-acquisition graph as {(src, dst): (path, line,
    qualname)} — the contract runtime/lockcheck.py asserts observed
    acquisition order against."""
    _findings, an = analyze(files or _package_files())
    return dict(an.edges)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("files", nargs="*",
                    help="files to lint (default: the whole package)")
    ap.add_argument("--allowlist", default=DEFAULT_ALLOWLIST)
    ap.add_argument("--json", action="store_true",
                    help="emit findings + lock graph as JSON")
    args = ap.parse_args(argv)

    files = args.files or _package_files()
    for f in files:
        if not os.path.exists(f):
            print(f"no such file: {f}", file=sys.stderr)
            return 2
    findings, an = analyze(files)
    allow = load_allowlist(args.allowlist)
    violations, allowed, stale = apply_allowlist(
        findings, allow, check_stale=not args.files
    )

    if args.json:
        # stdout is the JSON document, nothing else; verdict = exit code
        print(json.dumps({
            "violations": [f.__dict__ for f in violations],
            "allowed": [f.__dict__ for f in allowed],
            "stale_allowlist": [list(k) for k in stale],
            "lock_graph": {
                "nodes": sorted({n for e in an.edges for n in e}),
                "edges": [
                    {"src": s, "dst": d, "path": p, "line": ln,
                     "qualname": q}
                    for (s, d), (p, ln, q) in sorted(an.edges.items())
                ],
            },
            "guarded_classes": {
                ci.name: dict(sorted(ci.guarded.items()))
                for ci in sorted(an.classes.values(),
                                 key=lambda c: c.name)
                if ci.guarded
            },
        }, indent=2))
        return 1 if (violations or stale) else 0
    return report_text(violations, allowed, stale, args.allowlist,
                       REPO_ROOT, "concurrency-safety", len(files))


if __name__ == "__main__":
    sys.exit(main())
