"""Shared helpers for the repo's AST lint gates (tracer safety +
concurrency safety).

Both tools share one allowlist format::

    path::RULE::qualname  # one-line justification (required)

An entry suppresses every finding of that rule in that function. Entries
are LIVE state, not history: an entry whose ``path::RULE::qualname`` no
longer matches any finding is dead weight that can silently mask a future
regression under the same key, so both gates treat stale entries as
ERRORS (exit 1), not warnings.
"""

from __future__ import annotations

import os
import sys
from dataclasses import dataclass


@dataclass(frozen=True)
class Finding:
    path: str  # repo-relative
    line: int
    rule: str
    qualname: str
    message: str

    @property
    def key(self) -> tuple:
        return (self.path, self.rule, self.qualname)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule} [{self.qualname}] "
                f"{self.message}")


def load_allowlist(path: str) -> dict:
    """-> {(path, rule, qualname): justification}. Exits 2 on a malformed
    entry or a missing justification — an unexplained suppression is a
    usage error, not a policy decision."""
    out: dict = {}
    if not os.path.exists(path):
        return out
    with open(path, "r", encoding="utf-8") as f:
        for lineno, raw in enumerate(f, start=1):
            line = raw.split("#", 1)[0].strip()
            justification = (
                raw.split("#", 1)[1].strip() if "#" in raw else ""
            )
            if not line:
                continue
            parts = line.split("::")
            if len(parts) != 3:
                print(
                    f"{path}:{lineno}: malformed allowlist entry {raw!r} "
                    "(expected path::RULE::qualname  # justification)",
                    file=sys.stderr,
                )
                raise SystemExit(2)
            if not justification:
                print(
                    f"{path}:{lineno}: allowlist entry without a "
                    "justification comment",
                    file=sys.stderr,
                )
                raise SystemExit(2)
            out[tuple(p.strip() for p in parts)] = justification
    return out


def apply_allowlist(findings: list, allow: dict,
                    check_stale: bool = True) -> tuple:
    """Split ``findings`` against ``allow``;
    -> (violations, allowed, stale_keys). ``check_stale=False`` (partial
    runs over explicit files) skips staleness — an entry for an unlinted
    file is not stale, merely out of scope."""
    violations = [f for f in findings if f.key not in allow]
    allowed = [f for f in findings if f.key in allow]
    used = {f.key for f in allowed}
    stale = [k for k in allow if k not in used] if check_stale else []
    return violations, allowed, stale


def report_text(violations: list, allowed: list, stale: list,
                allowlist_path: str, repo_root: str, label: str,
                n_files: int) -> int:
    """Print the human report shared by both gates; -> exit code."""
    for f in violations:
        print(f.render())
    if allowed:
        print(f"({len(allowed)} allowlisted finding(s) suppressed; "
              f"see {os.path.relpath(allowlist_path, repo_root)})")
    for k in stale:
        print(f"stale allowlist entry (matches no finding — remove it): "
              f"{'::'.join(k)}")
    if violations or stale:
        why = []
        if violations:
            why.append(f"{len(violations)} {label} violation(s)")
        if stale:
            why.append(f"{len(stale)} stale allowlist entr"
                       + ("y" if len(stale) == 1 else "ies"))
        print("LINT FAILED: " + ", ".join(why))
        return 1
    print(f"{label} lint clean "
          f"({n_files} file(s), {len(allowed)} allowlisted)")
    return 0
