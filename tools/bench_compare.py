#!/usr/bin/env python
"""Diff two bench result JSON files (BENCH_DETAIL.json shape) and flag
regressions — the machine-checkable half of the bench trajectory.

Compares, wherever both files carry them:

- per-query wall seconds (``per_query_s``; ``--queries`` restricts)
- suite total (``total_s``)
- warm-repeat walls (``warm_repeat_s``)
- peak staged bytes (``peak_staged_bytes``, direction-aware: LOWER is
  better — memory regressions are flagged even when walls hold)
- serving metrics folded into ``meta.serving`` by `bench.py --serving`
  (qps: HIGHER is better; cheap/straggler p99 ms: LOWER is better; SLO
  latency attainment: HIGHER is better)
- micro_bench cases under ``micro`` (a {case: record} map or the raw
  benchmarks/micro_bench.py JSONL record list): per-metric direction —
  ms/copied_mb/peak_staged_mb LOWER, gbps/mb_per_s HIGHER; a case
  marked "skipped" (e.g. data_plane_wire_lz4 without the lz4 module)
  never reads as a regression

A comparison REGRESSES when the current value is worse than baseline by
more than ``--threshold`` (relative, default 0.10 = 10%); values under
``--min-seconds`` are skipped for per-query walls (sub-threshold noise
on a 50 ms query is not signal). Exit code: 0 = no regression, 1 =
regression(s), 2 = usage/IO error. ``--json`` prints the full
machine-readable comparison document on stdout.

Usage:
  python tools/bench_compare.py BASELINE.json CURRENT.json
  python tools/bench_compare.py a.json b.json --threshold 0.25 --json
  python tools/bench_compare.py a.json b.json --queries q1,q6
"""

from __future__ import annotations

import argparse
import json
import sys


def _load(path: str) -> dict:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"bench_compare: cannot read {path}: {e}", file=sys.stderr)
        raise SystemExit(2)


def _rel_change(base: float, cur: float) -> float:
    """(cur - base) / base; 0 for a zero/degenerate baseline."""
    if not base:
        return 0.0
    return (cur - base) / base


def _compare_value(name: str, base, cur, threshold: float,
                   higher_is_better: bool = False,
                   min_value: float = 0.0) -> dict:
    entry = {
        "name": name,
        "baseline": base,
        "current": cur,
        "higher_is_better": higher_is_better,
    }
    try:
        b, c = float(base), float(cur)
    except (TypeError, ValueError):
        entry["status"] = "skipped"
        return entry
    if max(abs(b), abs(c)) < min_value:
        entry["status"] = "skipped"  # below the noise floor
        return entry
    change = _rel_change(b, c)
    if b == 0 and c != 0:
        # zero baseline: any growth is infinite relative change. A
        # lower-is-better metric that was 0 (copied_mb on the shm plane)
        # regressing to nonzero must flag, not hide behind the
        # degenerate division. Finite sentinel keeps --json standard.
        change = 1e9 if c > 0 else -1e9
    entry["rel_change"] = round(change, 4)
    worse = (-change if higher_is_better else change) > threshold
    better = (change if higher_is_better else -change) > threshold
    entry["status"] = ("regression" if worse
                       else "improvement" if better else "ok")
    return entry


#: micro_bench case metric -> direction (True = higher is better).
#: Metrics not listed here are informational and never compared.
_MICRO_DIRECTIONS = {
    "ms": False,
    "gbps": True,
    "mb_per_s": True,
    "copied_mb": False,   # bytes a socket carried: the shm-vs-copy axis
    "payload_mb": False,
    "peak_staged_mb": False,
    "ratio": False,
    "speedup_vs_copy": True,
    # runtime-adaptivity axes (skew_shuffle_* / partial_agg_bailout_*):
    # per-task tail + the static/adaptive wall ratio. Adaptation COUNTS
    # (skew_splits, bailed_out, replan totals in BENCH_DETAIL meta) stay
    # unlisted on purpose — they are informational context, and "fired
    # more often" is neither a regression nor an improvement by itself.
    "task_p99_ms": False,
    "speedup_vs_static": True,
    "overhead_vs_off": False,
    # multiway-join fusion / global hash aggregation axes: wall ("ms"
    # above) plus the measured exchange-byte reduction and the fused-vs-
    # baseline ratios. exchange_mb lower = fewer bytes crossed a stage
    # boundary (deleted identity re-shuffles); the *_saved and speedup
    # axes higher = better.
    "exchange_mb": False,
    "exchange_mb_saved": True,
    "speedup_vs_chain": True,
    "speedup_vs_merge": True,
}


def _micro_cases(doc: dict) -> dict:
    """A document's `micro` section as {case: record}. Accepts either
    that map directly or the raw benchmarks/micro_bench.py JSONL record
    list (each record self-names via its "bench" field)."""
    m = doc.get("micro")
    if isinstance(m, list):
        m = {r.get("bench"): r for r in m
             if isinstance(r, dict) and r.get("bench")}
    return m if isinstance(m, dict) else {}


def compare(baseline: dict, current: dict, threshold: float = 0.10,
            queries=None, min_seconds: float = 0.02) -> dict:
    """-> {"comparisons": [...], "regressions": [...],
    "improvements": [...], "threshold": t}. Pure function of the two
    documents (unit-testable without files)."""
    comparisons: list = []

    def section(base_map, cur_map, prefix, **kw) -> None:
        if not isinstance(base_map, dict) or not isinstance(cur_map, dict):
            return
        keys = sorted(set(base_map) & set(cur_map))
        if queries is not None:
            keys = [k for k in keys if k in queries]
        for k in keys:
            comparisons.append(_compare_value(
                f"{prefix}{k}", base_map[k], cur_map[k], threshold, **kw
            ))

    section(baseline.get("per_query_s"), current.get("per_query_s"),
            "per_query_s:", min_value=min_seconds)
    section(baseline.get("warm_repeat_s"), current.get("warm_repeat_s"),
            "warm_repeat_s:", min_value=min_seconds)
    # direction-aware memory column: peak staged bytes per query/arm
    # (LOWER is better — a growing staged peak is a data-plane
    # regression even when walls hold)
    section(baseline.get("peak_staged_bytes"),
            current.get("peak_staged_bytes"), "peak_staged_bytes:")
    if baseline.get("total_s") is not None and (
        current.get("total_s") is not None
    ):
        comparisons.append(_compare_value(
            "total_s", baseline["total_s"], current["total_s"], threshold
        ))
    bs = (baseline.get("meta") or {}).get("serving") or {}
    cs = (current.get("meta") or {}).get("serving") or {}
    #: serving metric -> direction (True = higher is better)
    serving_metrics = {
        "qps": True,
        "cheap_p99_ms": False,
        "cheap_p50_ms": False,
        "straggler_p99_ms_on": False,
        "slo_latency_attainment": True,
        "peak_staged_bytes": False,
        "burst_p99_ms_cache_off": False,
        "burst_p99_ms_cache_on": False,
        "cache_hit_rate": True,
    }
    for name, hib in serving_metrics.items():
        if bs.get(name) is not None and cs.get(name) is not None:
            comparisons.append(_compare_value(
                f"serving:{name}", bs[name], cs[name], threshold,
                higher_is_better=hib,
            ))
    # leak-harness totals (runtime/leakcheck.py via DFTPU_LEAK_CHECK):
    # resources still live at query-end sweeps, folded into BENCH_DETAIL
    # meta by bench.py. A missing key means "harness off or zero leaks" —
    # both read as 0, so any nonzero current total flags as a regression
    # even against a baseline that predates the harness.
    bl = (baseline.get("meta") or {}).get("leaked_resources_total") or 0
    cl = (current.get("meta") or {}).get("leaked_resources_total") or 0
    if bl or cl:
        comparisons.append(_compare_value(
            "leaked_resources_total", bl, cl, threshold,
            higher_is_better=False,
        ))
    # micro_bench cases (data_plane_copy/view/shm, wire roundtrips, ...):
    # intersection of both documents' case sets, per-metric direction
    # from _MICRO_DIRECTIONS. A case either side marked "skipped" (e.g.
    # data_plane_wire_lz4 on an image without lz4) compares as skipped —
    # "not run" must never read as "regressed".
    bm, cm = _micro_cases(baseline), _micro_cases(current)
    for case in sorted(set(bm) & set(cm)):
        b, c = bm[case], cm[case]
        if not isinstance(b, dict) or not isinstance(c, dict):
            continue
        if b.get("skipped") or c.get("skipped"):
            comparisons.append({
                "name": f"micro:{case}",
                "baseline": b.get("skipped", "ran"),
                "current": c.get("skipped", "ran"),
                "higher_is_better": False,
                "status": "skipped",
            })
            continue
        for metric, hib in _MICRO_DIRECTIONS.items():
            if b.get(metric) is not None and c.get(metric) is not None:
                comparisons.append(_compare_value(
                    f"micro:{case}:{metric}", b[metric], c[metric],
                    threshold, higher_is_better=hib,
                ))
    return {
        "threshold": threshold,
        "comparisons": comparisons,
        "regressions": [c for c in comparisons
                        if c["status"] == "regression"],
        "improvements": [c for c in comparisons
                         if c["status"] == "improvement"],
        "compared": len([c for c in comparisons
                         if c["status"] != "skipped"]),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0]
    )
    ap.add_argument("baseline", help="baseline BENCH_*.json")
    ap.add_argument("current", help="current BENCH_*.json")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="relative regression threshold (default 0.10)")
    ap.add_argument("--min-seconds", type=float, default=0.02,
                    help="ignore per-query walls under this (noise "
                         "floor, default 0.02s)")
    ap.add_argument("--queries", default=None,
                    help="comma list restricting per-query comparisons")
    ap.add_argument("--json", action="store_true",
                    help="emit the comparison document as JSON")
    args = ap.parse_args(argv)
    if args.threshold < 0:
        print("bench_compare: --threshold must be >= 0", file=sys.stderr)
        return 2

    queries = None
    if args.queries:
        queries = {q.strip() for q in args.queries.split(",") if q.strip()}
    result = compare(
        _load(args.baseline), _load(args.current),
        threshold=args.threshold, queries=queries,
        min_seconds=args.min_seconds,
    )
    if args.json:
        print(json.dumps(result, indent=2))
    else:
        for c in result["comparisons"]:
            if c["status"] == "skipped":
                continue
            arrow = {"regression": "WORSE", "improvement": "better",
                     "ok": "ok"}[c["status"]]
            print(f"{c['name']:<40} {c['baseline']:>12} -> "
                  f"{c['current']:>12}  "
                  f"{c.get('rel_change', 0) * 100:+7.1f}%  {arrow}")
        n = len(result["regressions"])
        print(f"{result['compared']} compared, {n} regression(s), "
              f"{len(result['improvements'])} improvement(s) at "
              f"threshold {args.threshold * 100:.0f}%")
    return 1 if result["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
