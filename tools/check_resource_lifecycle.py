#!/usr/bin/env python
"""Resource-lifecycle lint for datafusion_distributed_tpu (dftpu-leaks).

The Rust reference gets resource cleanup for free from ownership and
`Drop`: a RecordBatch buffer, Flight stream, or spill file cannot
outlive its last owner. This runtime re-implements those lifecycles by
hand (refcounted TableStore entries, spill slots, /dev/shm segments with
cross-process refcount tokens, stream puller threads, checkpoint
slices), so the equivalent discipline is DECLARED and statically
enforced, exactly like the guarded-by/concurrency model
(tools/check_concurrency.py) and tracer-safety rules.

Declarations (the resource model)
---------------------------------
A manager class annotates its lifecycle methods with a trailing comment
on the ``def`` line, or equivalently a class-level ``_RESOURCES`` map::

    class SpillManager:
        def write_spill(self, table, nbytes):  # acquires: spill-slot
            ...
        def release(self, slot):  # releases: spill-slot
            ...

    # or:  _RESOURCES = {"write_spill": "acquires: spill-slot",
    #                    "release":     "releases: spill-slot"}

``# acquires: <kind> (managed)`` declares a MANAGER-OWNED kind: callers
are not path-checked because release is owned by the runtime sweep
(TableStore entries are released by refcount + the query-end sweep, not
by every put() caller). Unqualified ``acquires:`` kinds are
CALLER-OWNED: every acquisition site is held to the path rules below.
``# transfers: <kind>`` on a function declares that returning/yielding a
held handle is an ownership TRANSFER to the caller, not an escape.

Call-site matching is name + receiver based: ``h = pool.publish(...)``
matches ``SegmentPool.publish`` because the receiver text contains a
word of the declaring class's name (``pool``). That keeps generic method
names (``acquire``, ``release``) from matching unrelated objects
(``lock.acquire()``, ``gate.release()``).

Per-query state (rule DFTPU307) is declared on the field assignment::

    self._calls = {}  # per-query: swept-by sweep_query
    self._query_peak = {}  # per-query: bounded 512

Rules (DFTPU3xx; 0xx = plan verifier, 1xx = tracer safety,
2xx = concurrency)
------------------
  DFTPU301  leak-on-path        a caller-owned acquired handle reaches
                                a return / the end of the function with
                                no release on that path (early returns
                                included); also an acquisition whose
                                result is discarded
  DFTPU302  release-not-exception-safe  an intervening call between
                                acquire and release can raise while the
                                release is outside try/finally (or the
                                handle is live across a bare ``raise``)
  DFTPU303  double-release      the same handle released twice on one
                                path
  DFTPU304  escape-without-transfer  an acquired handle escapes via
                                return/yield and the function carries no
                                ``# transfers: <kind>`` annotation
  DFTPU305  leak-on-cancel-path  DFTPU301 where the leaking exit sits on
                                a cancel / retry / hedge-loser branch of
                                coordinator dispatch — the branches the
                                chaos schedules exercise
  DFTPU306  unregistered-file-creation  spill/shm-style file creation
                                (write-mode open, os.open, tempfile.*,
                                os.link) in runtime/ outside a class or
                                function that declares a resource
                                lifecycle — every data-plane file must
                                be registered with its manager
  DFTPU307  unswept-per-query-growth  a per-query-keyed dict field
                                (key expression mentions
                                query/qid/qscope) with no
                                ``per-query: swept-by <method>`` hook
                                (the named sweeper must exist and touch
                                the field) or ``per-query: bounded <N>``
                                cap

Intentional exceptions go in tools/resource_allowlist.txt
(path::RULE::qualname  # justification — shared lint_common.py format;
stale entries fail the gate). ``--json`` additionally emits the declared
resource model, which runtime/leakcheck.py merges with its observed
acquire/release log into the DFTPU_LEAK_CHECK_ARTIFACT dump.

Pure stdlib AST — no jax, no device, no package import; sub-second.
"""

from __future__ import annotations

import ast
import json
import os
import re
import sys
from dataclasses import dataclass, field

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from lint_common import (  # noqa: E402
    Finding,
    apply_allowlist,
    load_allowlist,
    report_text,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO_ROOT, "datafusion_distributed_tpu")
DEFAULT_ALLOWLIST = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "resource_allowlist.txt"
)

# the annotation regexes run on TRAILING-COMMENT text only (the part
# after '#' on a def/assign line), so they compose with guarded-by:
#   self._x = {}  # guarded-by: _lock; per-query: bounded 512
_ACQ_RE = re.compile(
    r"\bacquires:\s*([a-z0-9][a-z0-9-]*)(\s*\(\s*managed\s*\))?"
)
_REL_RE = re.compile(r"\breleases:\s*([a-z0-9][a-z0-9-]*)")
_TRANS_RE = re.compile(r"\btransfers:\s*([a-z0-9][a-z0-9-]*)")
_PQ_RE = re.compile(
    r"\bper-query:\s*(?:swept-by\s+(\w+)|bounded\s+(\d+))"
)
#: branch flavors whose leaked exits report as DFTPU305 (the dispatch
#: branches seeded chaos/hedging schedules exercise) instead of 301
_CANCELISH_RE = re.compile(
    r"cancel|hedge|retry|loser|abandon|preempt", re.IGNORECASE
)
#: per-query key heuristic: the subscript key's source text names the
#: query id space (PR 13 ids: query_id / qid; chaos query scopes: qscope)
_QKEY_RE = re.compile(r"query|qid|qscope", re.IGNORECASE)


def _camel_words(name: str) -> frozenset:
    return frozenset(
        w.lower() for w in re.findall(r"[A-Z][a-z0-9]+|[A-Z]+(?![a-z])", name)
    ) or frozenset({name.lower()})


@dataclass(frozen=True)
class Acquirer:
    kind: str
    managed: bool
    hints: frozenset  # receiver-name words that select this declaration
    owner: str  # "Class.method" or module-level "func"


@dataclass(frozen=True)
class Releaser:
    kind: str
    hints: frozenset
    owner: str


@dataclass
class Model:
    """The declared package-wide resource model (pass 1 output)."""

    acquirers: dict = field(default_factory=dict)  # method -> [Acquirer]
    releasers: dict = field(default_factory=dict)  # method -> [Releaser]
    transfers: dict = field(default_factory=dict)  # qualname(+path) -> kind
    #: classes/functions that declared ANY lifecycle method — the
    #: surfaces allowed to create data-plane files (DFTPU306)
    manager_classes: set = field(default_factory=set)
    manager_funcs: set = field(default_factory=set)

    def add_acquirer(self, method: str, a: Acquirer) -> None:
        self.acquirers.setdefault(method, []).append(a)

    def add_releaser(self, method: str, r: Releaser) -> None:
        self.releasers.setdefault(method, []).append(r)


def _def_line_comment(src_lines: list, node) -> str:
    """The trailing comment text of a def/assign line (annotations ride
    the line the statement starts on)."""
    line = src_lines[node.lineno - 1]
    return line.split("#", 1)[1] if "#" in line else ""


def _seg(src_lines: list, node) -> str:
    """Best-effort source text of an expression (single line is the
    overwhelmingly common case for keys/conditions/receivers)."""
    try:
        if node.lineno == node.end_lineno:
            return src_lines[node.lineno - 1][
                node.col_offset:node.end_col_offset
            ]
        return "\n".join(
            src_lines[node.lineno - 1:node.end_lineno]
        )
    except Exception:
        return ""


# --------------------------------------------------------------------------
# Pass 1: index the declared model
# --------------------------------------------------------------------------

def _parse_lifecycle_comment(text: str):
    """-> ("acquires", kind, managed) | ("releases", kind, False) | None"""
    m = _ACQ_RE.search(text)
    if m:
        return ("acquires", m.group(1), bool(m.group(2)))
    m = _REL_RE.search(text)
    if m:
        return ("releases", m.group(1), False)
    return None


def _index_module(relpath: str, tree: ast.Module, src_lines: list,
                  model: Model) -> None:
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            hints = _camel_words(node.name)
            declared = False
            # class-level _RESOURCES = {"method": "acquires: kind"} map
            res_map: dict = {}
            for stmt in node.body:
                if (
                    isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == "_RESOURCES"
                    and isinstance(stmt.value, ast.Dict)
                ):
                    for k, v in zip(stmt.value.keys, stmt.value.values):
                        if (
                            isinstance(k, ast.Constant)
                            and isinstance(v, ast.Constant)
                            and isinstance(k.value, str)
                            and isinstance(v.value, str)
                        ):
                            res_map[k.value] = "# " + v.value
            for stmt in node.body:
                if not isinstance(
                    stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                owner = f"{node.name}.{stmt.name}"
                texts = [_def_line_comment(src_lines, stmt)]
                if stmt.name in res_map:
                    texts.append(res_map[stmt.name])
                for text in texts:
                    parsed = _parse_lifecycle_comment(text)
                    if parsed is None:
                        continue
                    verb, kind, managed = parsed
                    declared = True
                    if verb == "acquires":
                        model.add_acquirer(
                            stmt.name,
                            Acquirer(kind, managed, hints, owner),
                        )
                    else:
                        model.add_releaser(
                            stmt.name, Releaser(kind, hints, owner)
                        )
                    m = _TRANS_RE.search(text)
                    if m:
                        model.transfers[(relpath, owner)] = m.group(1)
            if declared:
                model.manager_classes.add((relpath, node.name))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            text = _def_line_comment(src_lines, node)
            parsed = _parse_lifecycle_comment(text)
            if parsed is not None:
                verb, kind, managed = parsed
                model.manager_funcs.add((relpath, node.name))
                if verb == "acquires":
                    model.add_acquirer(
                        node.name,
                        Acquirer(kind, managed, frozenset(), node.name),
                    )
                else:
                    model.add_releaser(
                        node.name, Releaser(kind, frozenset(), node.name)
                    )
            m = _TRANS_RE.search(text)
            if m:
                model.transfers[(relpath, node.name)] = m.group(1)


# --------------------------------------------------------------------------
# Pass 2: per-function path discipline (DFTPU301-305)
# --------------------------------------------------------------------------

def _call_attr(call: ast.Call):
    """-> (method_name, receiver_source_node|None) for a call."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr, f.value
    if isinstance(f, ast.Name):
        return f.id, None
    return None, None


class _FuncChecker:
    """Path-sensitive acquire/release walker over ONE function body.

    Approximations (deliberate — this is a lint, not an abstract
    interpreter): loop bodies run 0-or-1 times; acquisitions inside a
    ``try`` are assumed to have happened when a handler runs; nested
    ``def``/``lambda`` bodies are opaque. The allowlist absorbs the
    residue; the seeded fixtures in tests/test_resource_lifecycle.py pin
    what must fire."""

    MAX_STATES = 32

    def __init__(self, model: Model, relpath: str, qualname: str,
                 func, src_lines: list, findings: list):
        self.model = model
        self.relpath = relpath
        self.qualname = qualname
        self.func = func
        self.src = src_lines
        self.findings = findings
        self.next_rid = 0
        self.rid_kind: dict = {}
        self.rid_line: dict = {}
        self.name_rid: dict = {}  # handle name -> rid (last binding)
        self.scoped: set = set()  # rids managed by a `with` block
        self.finally_released: set = set()  # handle NAMES released in finally
        self.reported: set = set()  # (rid, rule) dedup
        self.transfer_kind = model.transfers.get((relpath, qualname))
        if self.transfer_kind is None and "." in qualname:
            self.transfer_kind = model.transfers.get(
                (relpath, qualname.split(".", 1)[1])
            )
        # a declared acquirer IS the acquiring surface for its kind: the
        # inner acquire-call (e.g. a wrapper delegating to a module-level
        # acquirer) hands ownership to OUR caller, who the walker checks
        # at every call site instead
        self.self_kinds = frozenset(
            a.kind
            for acqs in model.acquirers.values()
            for a in acqs
            if a.owner == qualname
        )
        self._rid_by_node: dict = {}

    # -- model matching ----------------------------------------------------

    def _recv_matches(self, recv, hints: frozenset) -> bool:
        if not hints:
            return recv is None  # module-level declaration: bare call
        if recv is None:
            return False
        text = _seg(self.src, recv).lower()
        return any(h in text for h in hints)

    def match_acquire(self, call: ast.Call):
        name, recv = _call_attr(call)
        for a in self.model.acquirers.get(name, ()):
            if a.kind in self.self_kinds:
                continue
            if self._recv_matches(recv, a.hints):
                return a
        return None

    def match_release(self, call: ast.Call):
        name, recv = _call_attr(call)
        for r in self.model.releasers.get(name, ()):
            if self._recv_matches(recv, r.hints):
                return r
        return None

    # -- precompute --------------------------------------------------------

    def _arg_names(self, call: ast.Call) -> list:
        out = []
        for a in list(call.args) + [k.value for k in call.keywords]:
            for n in ast.walk(a):
                if isinstance(n, ast.Name):
                    out.append(n.id)
        return out

    def _precompute_finally(self) -> None:
        for node in ast.walk(self.func):
            if not isinstance(node, ast.Try) or not node.finalbody:
                continue
            for stmt in node.finalbody:
                for call in ast.walk(stmt):
                    if isinstance(call, ast.Call) and (
                        self.match_release(call) is not None
                    ):
                        self.finally_released.update(self._arg_names(call))

    # -- findings ----------------------------------------------------------

    def _emit(self, rid, rule: str, line: int, msg: str) -> None:
        if (rid, rule) in self.reported:
            return
        self.reported.add((rid, rule))
        self.findings.append(Finding(
            self.relpath, line, rule, self.qualname, msg
        ))

    # -- path walk ---------------------------------------------------------

    def run(self) -> None:
        # fast scan: does this function bind any caller-owned acquire?
        tracked = False
        for node in ast.walk(self.func):
            if isinstance(node, ast.Call):
                a = self.match_acquire(node)
                if a is not None and not a.managed:
                    tracked = True
                    break
        if not tracked:
            return
        self._precompute_finally()
        self._check_exception_safety()
        state = {"held": frozenset(), "released": frozenset()}
        falls = self._walk(self.func.body, [state], flavor=None)
        for st in falls:
            self._check_exit(st, self.func.body[-1].end_lineno or 0,
                             flavor=None, returned=None)

    # DFTPU302, structural half: a release exists but sits outside any
    # try/finally while calls between acquire and release can raise.
    def _check_exception_safety(self) -> None:
        acquires = []  # (line, names, kind)
        releases = {}  # name -> [line]
        calls = []  # (line) of every call
        for node in ast.walk(self.func):
            if not isinstance(node, ast.Call):
                continue
            calls.append(node.lineno)
            a = self.match_acquire(node)
            if a is not None and not a.managed:
                names = self._binding_names(node)
                if names:
                    acquires.append((node.lineno, names, a.kind))
                continue
            r = self.match_release(node)
            if r is not None:
                for n in self._arg_names(node):
                    releases.setdefault(n, []).append(node.lineno)
        for line, names, kind in acquires:
            if any(n in self.finally_released for n in names):
                continue
            rel_lines = sorted(
                ln for n in names for ln in releases.get(n, ())
            )
            if not rel_lines:
                continue  # no release at all: the path walker owns it
            first_rel = rel_lines[0]
            if any(line < c < first_rel for c in calls):
                self.findings.append(Finding(
                    self.relpath, first_rel, "DFTPU302", self.qualname,
                    f"release of {kind} handle "
                    f"{'/'.join(sorted(set(names)))} is not "
                    "exception-safe: calls between the acquisition "
                    f"(line {line}) and this release can raise and skip "
                    "it — move the release into try/finally (or a with "
                    "block)",
                ))

    def _binding_names(self, call: ast.Call) -> list:
        """Names an ``x = recv.acquire(...)`` / ``a, b = ...`` statement
        binds to the acquired handle (computed from the parent map)."""
        parent = self._parents.get(call)
        names: list = []
        if isinstance(parent, ast.Assign):
            for t in parent.targets:
                if isinstance(t, ast.Name):
                    names.append(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    names.extend(
                        e.id for e in t.elts if isinstance(e, ast.Name)
                    )
        elif isinstance(parent, ast.withitem):
            v = parent.optional_vars
            if isinstance(v, ast.Name):
                names.append(v.id)
            elif isinstance(v, (ast.Tuple, ast.List)):
                names.extend(
                    e.id for e in v.elts if isinstance(e, ast.Name)
                )
        return names

    @property
    def _parents(self) -> dict:
        p = getattr(self, "_parent_map", None)
        if p is None:
            p = {}
            for node in ast.walk(self.func):
                for child in ast.iter_child_nodes(node):
                    p[child] = node
                    # withitem context exprs: map the call to the item
                    if isinstance(node, ast.With):
                        for item in node.items:
                            p[item.context_expr] = item
            self._parent_map = p
        return p

    def _new_rid(self, kind: str, line: int, node=None) -> int:
        # one rid per acquire SITE (not per path state) so a call reached
        # by several merged paths yields one finding, not one per state
        if node is not None and node in self._rid_by_node:
            return self._rid_by_node[node]
        self.next_rid += 1
        self.rid_kind[self.next_rid] = kind
        self.rid_line[self.next_rid] = line
        if node is not None:
            self._rid_by_node[node] = self.next_rid
        return self.next_rid

    def _stmt_events(self, stmt, state, flavor):
        """Apply acquire/release events of ONE simple statement to
        ``state`` (returns the new state)."""
        held = set(state["held"])
        released = set(state["released"])
        # aliasing / escape-to-structure: ``x = handle`` re-binds the
        # handle; ``obj.attr = handle`` / ``d[k] = handle`` parks it in a
        # structure whose owner takes over the lifecycle (the runtime
        # harness's job, not the path walker's)
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Name):
            rid = self.name_rid.get(stmt.value.id)
            if rid is not None and rid in held:
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        self.name_rid[t.id] = rid
                    elif isinstance(t, (ast.Attribute, ast.Subscript)):
                        self.scoped.add(rid)
        for node in ast.walk(stmt):
            # ``yield handle``: the handle escapes to the consumer —
            # fine under a transfers: declaration, DFTPU304 otherwise
            if isinstance(node, (ast.Yield, ast.YieldFrom)):
                val = getattr(node, "value", None)
                if val is not None:
                    for n in ast.walk(val):
                        if not isinstance(n, ast.Name):
                            continue
                        rid = self.name_rid.get(n.id)
                        if rid is None or rid not in held:
                            continue
                        kind = self.rid_kind[rid]
                        if self.transfer_kind == kind:
                            self.scoped.add(rid)
                        else:
                            self._emit(
                                rid, "DFTPU304", node.lineno,
                                f"acquired {kind} handle {n.id} escapes "
                                "via return/yield without a "
                                f"'# transfers: {kind}' annotation "
                                "(ownership is ambiguous: neither this "
                                "function nor the caller provably "
                                "releases it)",
                            )
                continue
            if not isinstance(node, ast.Call):
                continue
            a = self.match_acquire(node)
            if a is not None and not a.managed:
                names = self._binding_names(node)
                rid = self._new_rid(a.kind, node.lineno, node)
                if not names:
                    self._emit(
                        rid, "DFTPU301", node.lineno,
                        f"acquired {a.kind} is discarded (call result "
                        "not bound — nothing can ever release it)",
                    )
                    continue
                for n in names:
                    self.name_rid[n] = rid
                held.add(rid)
                continue
            r = self.match_release(node)
            if r is not None:
                for n in self._arg_names(node):
                    rid = self.name_rid.get(n)
                    if rid is None:
                        continue
                    if rid in released:
                        self._emit(
                            rid, "DFTPU303", node.lineno,
                            f"double release of {self.rid_kind[rid]} "
                            f"handle {n} (first release already ran on "
                            "this path)",
                        )
                    released.add(rid)
                    held.discard(rid)
        return {"held": frozenset(held), "released": frozenset(released)}

    def _check_exit(self, state, line: int, flavor, returned) -> None:
        """A path leaves the function: flag every still-held rid."""
        ret_names: set = set()
        if returned is not None:
            for n in ast.walk(returned):
                if isinstance(n, ast.Name):
                    ret_names.add(n.id)
        for rid in state["held"]:
            if rid in self.scoped:
                continue
            kind = self.rid_kind[rid]
            names = sorted(
                n for n, r in self.name_rid.items() if r == rid
            )
            if any(n in self.finally_released for n in names):
                continue
            if any(n in ret_names for n in names):
                if self.transfer_kind == kind:
                    continue  # declared ownership transfer
                self._emit(
                    rid, "DFTPU304", line,
                    f"acquired {kind} handle {'/'.join(names)} escapes "
                    "via return/yield without a '# transfers: "
                    f"{kind}' annotation (ownership is ambiguous: "
                    "neither this function nor the caller provably "
                    "releases it)",
                )
                continue
            rule = "DFTPU305" if flavor else "DFTPU301"
            extra = (
                f" on the {flavor} branch (the path seeded "
                "chaos/hedging schedules exercise)" if flavor else ""
            )
            self._emit(
                rid, rule, line,
                f"{kind} acquired at line {self.rid_line[rid]} is not "
                f"released on this path{extra}",
            )

    def _flavor_of(self, node) -> str:
        text = _seg(self.src, node)
        m = _CANCELISH_RE.search(text)
        return m.group(0).lower() if m else ""

    def _walk(self, stmts, states, flavor):
        """-> list of fall-through states after executing ``stmts``."""
        for stmt in stmts:
            if len(states) > self.MAX_STATES:
                held = frozenset().union(*(s["held"] for s in states))
                rel = frozenset().union(*(s["released"] for s in states))
                states = [{"held": held, "released": rel}]
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested scopes are opaque
            if isinstance(stmt, ast.Return):
                for st in states:
                    st = self._stmt_events(stmt, st, flavor)
                    self._check_exit(st, stmt.lineno, flavor, stmt.value)
                return []
            if isinstance(stmt, ast.Raise):
                # a raise with held, finally-unprotected handles leaks on
                # the exception path — the structural 302 check reports
                # the release shape; here flag only never-released rids
                for st in states:
                    st = self._stmt_events(stmt, st, flavor)
                    self._check_exit(st, stmt.lineno,
                                     flavor or "raise", None)
                return []
            if isinstance(stmt, ast.If):
                f2 = self._flavor_of(stmt.test) or flavor
                out = []
                for st in states:
                    st = self._stmt_events(stmt.test, st, flavor)
                    out.extend(self._walk(list(stmt.body), [st], f2))
                    out.extend(
                        self._walk(list(stmt.orelse), [st], flavor)
                    )
                states = out
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                out = []
                for st in states:
                    out.append(st)  # zero iterations
                    out.extend(self._walk(list(stmt.body), [st], flavor))
                states = out
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                pre = []
                for st in states:
                    scoped_rids = []
                    for item in stmt.items:
                        call = item.context_expr
                        if isinstance(call, ast.Call):
                            a = self.match_acquire(call)
                            if a is not None and not a.managed:
                                names = self._binding_names(call)
                                rid = self._new_rid(a.kind, call.lineno,
                                                    call)
                                for n in names:
                                    self.name_rid[n] = rid
                                self.scoped.add(rid)
                                scoped_rids.append(rid)
                                st = {
                                    "held": st["held"] | {rid},
                                    "released": st["released"],
                                }
                    body_out = self._walk(list(stmt.body), [st], flavor)
                    for b in body_out:
                        pre.append({
                            "held": frozenset(
                                b["held"] - set(scoped_rids)
                            ),
                            "released": frozenset(
                                b["released"] | set(scoped_rids)
                            ),
                        })
                states = pre
                continue
            if isinstance(stmt, ast.Try):
                out = []
                for st in states:
                    body_out = self._walk(list(stmt.body), [st], flavor)
                    handler_out = []
                    for h in stmt.handlers:
                        hf = (
                            self._flavor_of(h.type) if h.type else ""
                        ) or flavor
                        handler_out.extend(
                            self._walk(list(h.body), [dict(st)], hf)
                        )
                    merged = body_out + handler_out
                    if stmt.orelse:
                        merged = (
                            self._walk(list(stmt.orelse), body_out,
                                       flavor)
                            + handler_out
                        )
                    if stmt.finalbody:
                        fin = []
                        for m in merged:
                            fin.extend(
                                self._walk(list(stmt.finalbody), [m],
                                           flavor)
                            )
                        merged = fin
                    out.extend(merged)
                states = out
                continue
            # simple statement: apply its calls
            states = [
                self._stmt_events(stmt, st, flavor) for st in states
            ]
        return states


# --------------------------------------------------------------------------
# Pass 2b: DFTPU306 — file creation outside a declared manager
# --------------------------------------------------------------------------

_FILE_CREATORS = ("mkstemp", "mkdtemp", "NamedTemporaryFile",
                  "TemporaryFile", "SpooledTemporaryFile")


def _is_file_creation(call: ast.Call, src_lines: list) -> str:
    name, recv = _call_attr(call)
    recv_text = _seg(src_lines, recv).lower() if recv is not None else ""
    if name == "open" and recv is None:
        mode = None
        if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
            mode = call.args[1].value
        for k in call.keywords:
            if k.arg == "mode" and isinstance(k.value, ast.Constant):
                mode = k.value.value
        if isinstance(mode, str) and any(c in mode for c in "wxa+"):
            return f"open(..., {mode!r})"
        return ""
    if recv_text == "os" and name in ("open", "link"):
        return f"os.{name}"
    if recv_text == "tempfile" and name in _FILE_CREATORS:
        return f"tempfile.{name}"
    if name in _FILE_CREATORS and recv is None:
        return name
    return ""


def _check_file_creation(relpath: str, tree: ast.Module, src_lines: list,
                         model: Model, findings: list) -> None:
    if f"runtime{os.sep}" not in relpath and "/runtime/" not in relpath:
        return

    def scan_func_body(func, qualname, managed):
        for call in ast.walk(func):
            if isinstance(call, ast.Call):
                what = _is_file_creation(call, src_lines)
                if what and not managed:
                    findings.append(Finding(
                        relpath, call.lineno, "DFTPU306", qualname,
                        f"{what} creates a file outside a declared "
                        "resource manager — register it with its "
                        "manager (or annotate the owning surface with "
                        "an acquires:/releases: lifecycle)",
                    ))
                    return  # one finding per function is enough

    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            managed = (relpath, node.name) in model.manager_classes
            if managed:
                continue
            for stmt in node.body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    scan_func_body(
                        stmt, f"{node.name}.{stmt.name}", managed
                    )
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            managed = (relpath, node.name) in model.manager_funcs
            if not managed:
                scan_func_body(node, node.name, managed)


# --------------------------------------------------------------------------
# Pass 2c: DFTPU307 — per-query dict growth without a sweep hook
# --------------------------------------------------------------------------

def _field_annotations(cls: ast.ClassDef, src_lines: list) -> dict:
    """-> {field: ("swept-by", method) | ("bounded", n)} from trailing
    comments on ``self.<field> = ...`` / ``self.<field>: T = ...``
    assignments anywhere in the class, plus class-level (dataclass)
    field declarations like ``spans: dict = field(default_factory=dict)``."""
    out: dict = {}
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        for t in targets:
            name = None
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                name = t.attr
            elif isinstance(t, ast.Name) and node in cls.body:
                # class-level declaration (dataclass field)
                name = t.id
            if name is None:
                continue
            m = _PQ_RE.search(_def_line_comment(src_lines, node))
            if m:
                if m.group(1):
                    out[name] = ("swept-by", m.group(1))
                else:
                    out[name] = ("bounded", int(m.group(2)))
    return out


def _sweeper_touches(sweeper, field_name: str, methods: dict,
                     _seen=None) -> bool:
    """Does ``sweeper`` (or any same-class method it calls through
    ``self.<m>(...)``) reference ``self.<field_name>``? Delegation to a
    ``_locked`` helper is the dominant idiom."""
    if _seen is None:
        _seen = set()
    if sweeper.name in _seen:
        return False
    _seen.add(sweeper.name)
    for n in ast.walk(sweeper):
        if isinstance(n, ast.Attribute) and n.attr == field_name:
            return True
        # defensive access idiom: getattr(self, "field", None)
        if (
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Name)
            and n.func.id == "getattr"
            and len(n.args) >= 2
            and isinstance(n.args[1], ast.Constant)
            and n.args[1].value == field_name
        ):
            return True
        if (
            isinstance(n, ast.Call)
            and isinstance(n.func, ast.Attribute)
            and isinstance(n.func.value, ast.Name)
            and n.func.value.id == "self"
            and n.func.attr in methods
        ):
            if _sweeper_touches(methods[n.func.attr], field_name,
                                methods, _seen):
                return True
    return False


def _check_per_query_growth(relpath: str, tree: ast.Module,
                            src_lines: list, findings: list) -> None:
    if f"runtime{os.sep}" not in relpath and "/runtime/" not in relpath:
        return
    for cls in tree.body:
        if not isinstance(cls, ast.ClassDef):
            continue
        annos = _field_annotations(cls, src_lines)
        methods = {
            s.name: s for s in cls.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        seen: set = set()
        for meth in methods.values():
            for node in ast.walk(meth):
                field_name = None
                key_node = None
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if (
                            isinstance(t, ast.Subscript)
                            and isinstance(t.value, ast.Attribute)
                            and isinstance(t.value.value, ast.Name)
                            and t.value.value.id == "self"
                        ):
                            field_name = t.value.attr
                            key_node = t.slice
                elif (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "setdefault"
                    and isinstance(node.func.value, ast.Attribute)
                    and isinstance(node.func.value.value, ast.Name)
                    and node.func.value.value.id == "self"
                    and node.args
                ):
                    field_name = node.func.value.attr
                    key_node = node.args[0]
                if field_name is None or key_node is None:
                    continue
                if not _QKEY_RE.search(_seg(src_lines, key_node)):
                    continue
                if field_name in seen:
                    continue
                seen.add(field_name)
                qualname = f"{cls.name}.{meth.name}"
                anno = annos.get(field_name)
                if anno is None:
                    findings.append(Finding(
                        relpath, node.lineno, "DFTPU307", qualname,
                        f"per-query keyed growth of self.{field_name} "
                        "with no declared sweep hook or bound — a "
                        "long-lived serving process grows it forever; "
                        "annotate the field '# per-query: swept-by "
                        "<method>' (and sweep it) or '# per-query: "
                        "bounded <N>'",
                    ))
                elif anno[0] == "swept-by":
                    sweeper = methods.get(anno[1])
                    ok = sweeper is not None and _sweeper_touches(
                        sweeper, field_name, methods
                    )
                    if not ok:
                        findings.append(Finding(
                            relpath, node.lineno, "DFTPU307", qualname,
                            f"self.{field_name} declares 'per-query: "
                            f"swept-by {anno[1]}' but "
                            f"{cls.name}.{anno[1]} "
                            + ("does not exist"
                               if sweeper is None
                               else "never touches the field")
                            + " — the sweep hook is a dead annotation",
                        ))


# --------------------------------------------------------------------------
# Driver
# --------------------------------------------------------------------------

def _package_files() -> list:
    out = []
    for root, _dirs, files in os.walk(PACKAGE):
        for f in sorted(files):
            if f.endswith(".py"):
                out.append(os.path.join(root, f))
    return sorted(out)


def _parse_all(files=None) -> list:
    """-> [(relpath, tree, src_lines)] parsed ONCE and shared by both
    passes (parsing dominates the lint's runtime)."""
    out = []
    for path in files or _package_files():
        relpath = os.path.relpath(path, REPO_ROOT)
        try:
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src, filename=path)
        except (OSError, SyntaxError):
            continue
        out.append((relpath, tree, src.splitlines()))
    return out


def build_model(files=None, parsed=None) -> Model:
    """Pass-1 only: the declared resource model (runtime/leakcheck.py
    loads this for its merged static-vs-observed artifact)."""
    model = Model()
    for relpath, tree, src_lines in parsed or _parse_all(files):
        _index_module(relpath, tree, src_lines, model)
    return model


def declared_model_json(model: Model = None) -> dict:
    model = model or build_model()
    kinds: dict = {}
    for lst in model.acquirers.values():
        for a in lst:
            k = kinds.setdefault(
                a.kind,
                {"acquirers": [], "releasers": [], "managed": False},
            )
            k["acquirers"].append(a.owner)
            k["managed"] = k["managed"] or a.managed
    for lst in model.releasers.values():
        for r in lst:
            kinds.setdefault(
                r.kind,
                {"acquirers": [], "releasers": [], "managed": False},
            )["releasers"].append(r.owner)
    for k in kinds.values():
        k["acquirers"] = sorted(set(k["acquirers"]))
        k["releasers"] = sorted(set(k["releasers"]))
    return kinds


def analyze(files=None):
    """-> (findings, model). Pure — no allowlist, no I/O besides reads."""
    parsed = _parse_all(files)
    model = build_model(parsed=parsed)
    findings: list = []
    for relpath, tree, src_lines in parsed:

        def check_func(func, qualname):
            _FuncChecker(
                model, relpath, qualname, func, src_lines, findings
            ).run()

        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                check_func(node, node.name)
            elif isinstance(node, ast.ClassDef):
                for stmt in node.body:
                    if isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        check_func(stmt, f"{node.name}.{stmt.name}")
        _check_file_creation(relpath, tree, src_lines, model, findings)
        _check_per_query_growth(relpath, tree, src_lines, findings)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, model


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="Resource-lifecycle lint (DFTPU301-307)"
    )
    ap.add_argument("files", nargs="*",
                    help="specific files (default: whole package)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings + declared model")
    ap.add_argument("--allowlist", default=DEFAULT_ALLOWLIST)
    args = ap.parse_args(argv)

    files = [os.path.abspath(f) for f in args.files] or None
    findings, model = analyze(files)
    allow = load_allowlist(args.allowlist)
    violations, allowed, stale = apply_allowlist(
        findings, allow, check_stale=not args.files
    )
    if args.json:
        print(json.dumps({
            "violations": [f.__dict__ for f in violations],
            "allowed": [f.__dict__ for f in allowed],
            "stale": ["::".join(k) for k in stale],
            "model": declared_model_json(model),
        }, indent=2, sort_keys=True))
        return 1 if (violations or stale) else 0
    n_files = len(files) if files else len(_package_files())
    return report_text(
        violations, allowed, stale, args.allowlist, REPO_ROOT,
        "resource-lifecycle", n_files,
    )


if __name__ == "__main__":
    raise SystemExit(main())
