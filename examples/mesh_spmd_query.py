"""A whole distributed query as ONE SPMD program over a device mesh.

This is the TPU-native execution tier with no Rust counterpart: the staged
plan (scan -> partial agg -> all_to_all shuffle -> final agg -> broadcast
join -> coalesce) traces into a single XLA program where the exchanges are
ICI collectives — zero per-stage host round-trips. On a CPU box this runs
over 8 virtual devices; on a TPU slice the identical code uses the chips.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# DFTPU_EXAMPLE_DEVICE=tpu uses the real chips; default is the virtual mesh
_DEVICE = os.environ.get("DFTPU_EXAMPLE_DEVICE", "cpu")
if _DEVICE == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax

if _DEVICE == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np
import pyarrow as pa

from datafusion_distributed_tpu.sql.context import SessionContext


def main() -> None:
    print("devices:", jax.devices())
    rng = np.random.default_rng(2)
    n = 50_000
    ctx = SessionContext()
    ctx.register_arrow("sales", pa.table({
        "store": rng.integers(0, 50, n),
        "item": rng.integers(0, 500, n),
        "qty": rng.integers(1, 20, n).astype(np.int32),
    }))
    ctx.register_arrow("stores", pa.table({
        "store_id": np.arange(50),
        "state": rng.integers(0, 10, 50),
    }))

    df = ctx.sql(
        "select s.state, sum(x.qty) total "
        "from sales x, stores s where x.store = s.store_id "
        "group by s.state order by total desc"
    )
    print("-- staged plan --")
    print(df.explain_distributed(num_tasks=8))
    out = df._strip_quals(df.collect_distributed_table(num_tasks=8))
    print("-- result (computed by one SPMD program) --")
    print(out.to_pandas().to_string(index=False))


if __name__ == "__main__":
    main()
