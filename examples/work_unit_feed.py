"""Runtime work-unit feeding of a scan.

The reference's `examples/work_unit_feed.rs`: the coordinator discovers
units of work (here: parquet file paths) WHILE the query runs and streams
them to worker tasks in chunks of 256; only the feed's UUID crosses the
wire with the plan. Each unit carries the four lifecycle timestamps
(created/sent/received/processed).
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from datafusion_distributed_tpu.io.parquet import schema_from_arrow
from datafusion_distributed_tpu.plan.physical import execute_plan
from datafusion_distributed_tpu.runtime.work_unit_feed import (
    RemoteWorkUnitFeedRegistry,
    WorkUnitFeedRegistry,
    WorkUnitScanExec,
    stream_feed,
)


def main() -> None:
    # "discovered" inputs: four parquet files written over time
    tmp = tempfile.mkdtemp(prefix="wuf_")
    paths = []
    for i in range(4):
        p = os.path.join(tmp, f"part{i}.parquet")
        pq.write_table(
            pa.table({"x": np.arange(i * 25, (i + 1) * 25)}), p
        )
        paths.append(p)

    registry = WorkUnitFeedRegistry()
    feed_id = registry.register(lambda: iter(paths))
    remote = RemoteWorkUnitFeedRegistry()

    arrow_schema = pq.read_schema(paths[0])
    schema = schema_from_arrow(arrow_schema)
    scan = WorkUnitScanExec(feed_id, schema, capacity=128,
                            remote_registry=remote)

    # coordinator side: route units round-robin to 1 task and close the feed
    sent = stream_feed(
        registry, remote, feed_id,
        task_router=lambda unit, n: 0, task_count=1,
    )
    print(f"streamed {sent} work units")

    out = execute_plan(scan)
    print("rows fed:", int(out.num_rows))
    print("sum(x) =", int(np.asarray(out.to_numpy()["x"]).sum()),
          "(expected", sum(range(100)), ")")


if __name__ == "__main__":
    main()
