"""Hand-built distributed plan: a progressive partial-reduction tree.

The reference's `examples/custom_distributed_partial_reduction_tree.rs`:
exchange nodes are public, constructible operators — if a plan ALREADY
contains boundaries when it reaches the distributed planner, the planner
does not re-distribute it; it only finalizes what you placed
(`distributed_query_planner.rs:78-99`). Here that is used to build a
GROUP BY reduction tree that shrinks data at every level instead of one
wide gather:

    Final               (1 task)    <- finishes the aggregation
      CoalesceExchange  M -> 1
    PartialReduce       (M tasks)   <- merges partial STATES (fewer states
      CoalesceExchange  N -> M         cross each hop; avg merges its
    Partial             (N tasks)      (sum, count) pair correctly)
      MemoryScan        N slices

`HashAggregateExec(mode="partial_reduce")` is the key node: unlike a plain
coalesce (which only concatenates), it re-groups and merges accumulator
columns while KEEPING them in state form, so a later final stage can finish
the job (`ops/aggregate.py` partial_reduce mode; the reference's
AggregateMode::PartialReduce).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_DEVICE = os.environ.get("DFTPU_EXAMPLE_DEVICE", "cpu")
if _DEVICE == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax

if _DEVICE == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np
import pyarrow as pa

from datafusion_distributed_tpu.io.parquet import arrow_to_table
from datafusion_distributed_tpu.ops.aggregate import AggSpec
from datafusion_distributed_tpu.ops.sort import SortKey
from datafusion_distributed_tpu.plan.exchanges import CoalesceExchangeExec
from datafusion_distributed_tpu.plan.physical import (
    HashAggregateExec,
    MemoryScanExec,
    SortExec,
)
from datafusion_distributed_tpu.planner.distributed import (
    DistributedConfig,
    distribute_plan,
)
from datafusion_distributed_tpu.parallel.exchange import partition_table
from datafusion_distributed_tpu.runtime.mesh_executor import (
    execute_on_mesh,
    make_mesh,
)

N_TASKS = 8  # leaf fan-in
M_GROUPS = 2  # intermediate reduction width


def main() -> None:
    rng = np.random.default_rng(5)
    n = 80_000
    # "weather": station-keyed readings, like the reference example's table
    arrow = pa.table({
        "station": rng.integers(0, 12, n),
        "temp_c": np.round(rng.normal(15, 9, n), 2),
    })
    t = arrow_to_table(arrow)

    scan = MemoryScanExec(partition_table(t, N_TASKS), t.schema())
    aggs = [
        AggSpec("avg", "temp_c", "avg_temp"),
        AggSpec("max", "temp_c", "max_temp"),
        AggSpec("count_star", None, "readings"),
    ]
    partial = HashAggregateExec("partial", ["station"], aggs, scan)
    narrow = CoalesceExchangeExec(partial, N_TASKS, num_consumers=M_GROUPS)
    reduce_ = HashAggregateExec("partial_reduce", ["station"], aggs, narrow)
    gather = CoalesceExchangeExec(reduce_, N_TASKS)
    final = HashAggregateExec("final", ["station"], aggs, gather)
    plan = SortExec([SortKey("station")], final)

    # the planner sees the hand-placed boundaries and only finalizes them
    staged = distribute_plan(plan, DistributedConfig(num_tasks=N_TASKS))
    print("-- hand-built reduction tree (as finalized by the planner) --")
    print(staged.display_tree())

    mesh = make_mesh(N_TASKS)
    out = execute_on_mesh(staged, mesh).to_pandas()
    print("\n-- result (one SPMD program over the mesh) --")
    print(out.to_string(index=False))

    # oracle check: the tree must agree with plain pandas
    exp = (
        arrow.to_pandas().groupby("station")
        .agg(avg_temp=("temp_c", "mean"), max_temp=("temp_c", "max"),
             readings=("temp_c", "size"))
        .reset_index().sort_values("station").reset_index(drop=True)
    )
    np.testing.assert_allclose(out["avg_temp"], exp["avg_temp"], rtol=1e-5)
    np.testing.assert_allclose(out["max_temp"], exp["max_temp"], rtol=1e-6)
    np.testing.assert_array_equal(out["readings"], exp["readings"])
    print("\nmatches the pandas oracle ✓")


if __name__ == "__main__":
    main()
