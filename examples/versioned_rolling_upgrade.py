"""Rolling upgrade with REAL drain on an elastic cluster.

The reference's `examples/localhost_versioned_run` pair: workers advertise a
version via GetWorkerInfo, and a coordinator built `with_version` refuses to
ship plans to a mixed-version cluster (`worker_service.rs:175-179`). The
membership layer underneath is the reference's dynamic `WorkerResolver`
(SURVEY §1) — here `DynamicCluster`: each worker is upgraded by DRAINING it
(no new tasks; in-flight work finishes; removed only when empty), then
adding its upgraded replacement, which becomes routable immediately. The
cluster serves queries through the whole roll; the version-pinned
coordinator is the safety rail that refuses the mixed-fleet window.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pyarrow as pa

from datafusion_distributed_tpu.io.parquet import arrow_to_table
from datafusion_distributed_tpu.ops.aggregate import AggSpec
from datafusion_distributed_tpu.ops.sort import SortKey
from datafusion_distributed_tpu.plan.physical import (
    HashAggregateExec,
    MemoryScanExec,
    SortExec,
)
from datafusion_distributed_tpu.planner.distributed import (
    DistributedConfig,
    distribute_plan,
)
from datafusion_distributed_tpu.runtime.coordinator import (
    Coordinator,
    DynamicCluster,
)
from datafusion_distributed_tpu.runtime.errors import WorkerError
from datafusion_distributed_tpu.runtime.worker import Worker

OLD, NEW = "1.0.3", "1.1.0"


def main() -> None:
    rng = np.random.default_rng(3)
    n = 5_000
    arrow = pa.table({
        "shard": rng.integers(0, 6, n),
        "latency_ms": rng.exponential(20.0, n),
    })
    t = arrow_to_table(arrow)
    plan = SortExec(
        [SortKey("shard")],
        HashAggregateExec(
            "single", ["shard"],
            [AggSpec("avg", "latency_ms", "avg_ms"),
             AggSpec("count_star", None, "n")],
            MemoryScanExec([t], t.schema()),
        ),
    )
    dplan = distribute_plan(plan, DistributedConfig(num_tasks=3))

    cluster = DynamicCluster()
    for i in range(3):
        cluster.add_worker(Worker(f"mem://w{i}-{OLD}", version=OLD))

    serving = Coordinator(resolver=cluster, channels=cluster)
    pinned_new = Coordinator(
        resolver=cluster, channels=cluster, expected_version=NEW,
    )

    print(f"-- fleet on {OLD}, epoch {cluster.membership_epoch} --")
    print(serving.execute(dplan).to_pandas().head(3).to_string(index=False))

    print("\n-- rolling upgrade, one worker at a time (drain -> replace) --")
    for i, url in enumerate(cluster.get_urls()):
        cluster.drain_worker(url)
        assert cluster.wait_drained(url, timeout_s=10.0), (
            f"{url} did not drain"
        )
        print(f"drained+removed {url} "
              f"(in-flight at removal: {cluster.in_flight(url)})")
        cluster.add_worker(Worker(f"mem://w{i}-{NEW}", version=NEW))
        # the cluster keeps serving mid-roll: routing sees live membership
        out = serving.execute(dplan).to_pandas()
        assert len(out) == 6
        if i == 0:
            # mixed-fleet window: the version-pinned coordinator refuses
            print("mixed fleet: ", end="")
            try:
                pinned_new.execute(dplan)
                raise AssertionError("version skew not detected")
            except WorkerError as e:
                print(f"pinned coordinator rejected ({e})")

    snap = cluster.membership_snapshot()
    print(f"\n-- roll complete: epoch {snap['epoch']}, "
          f"active={snap['active']} --")
    out = pinned_new.execute(dplan).to_pandas()
    print(out.to_string(index=False))
    assert len(out) == 6


if __name__ == "__main__":
    main()
