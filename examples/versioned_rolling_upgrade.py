"""Rolling-upgrade version safety across a worker fleet.

The reference's `examples/localhost_versioned_run` pair: workers advertise a
version via GetWorkerInfo, and a coordinator built `with_version` refuses to
ship plans to a mixed-version cluster (`worker_service.rs:175-179`) —
protecting a rolling upgrade from silently running one query across two
incompatible plan codecs.

Here: a 3-worker in-memory cluster where one worker is mid-upgrade. The
version-pinned coordinator rejects the query with a structured WorkerError
naming the skewed worker; after the "upgrade" completes, the same query runs.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pyarrow as pa

from datafusion_distributed_tpu.io.parquet import arrow_to_table
from datafusion_distributed_tpu.ops.aggregate import AggSpec
from datafusion_distributed_tpu.ops.sort import SortKey
from datafusion_distributed_tpu.plan.physical import (
    HashAggregateExec,
    MemoryScanExec,
    SortExec,
)
from datafusion_distributed_tpu.planner.distributed import (
    DistributedConfig,
    distribute_plan,
)
from datafusion_distributed_tpu.runtime.coordinator import (
    Coordinator,
    InMemoryCluster,
)
from datafusion_distributed_tpu.runtime.errors import WorkerError


def main() -> None:
    rng = np.random.default_rng(3)
    n = 5_000
    arrow = pa.table({
        "shard": rng.integers(0, 6, n),
        "latency_ms": rng.exponential(20.0, n),
    })
    t = arrow_to_table(arrow)
    plan = SortExec(
        [SortKey("shard")],
        HashAggregateExec(
            "single", ["shard"],
            [AggSpec("avg", "latency_ms", "avg_ms"),
             AggSpec("count_star", None, "n")],
            MemoryScanExec([t], t.schema()),
        ),
    )
    dplan = distribute_plan(plan, DistributedConfig(num_tasks=3))

    cluster = InMemoryCluster(num_workers=3)
    # one worker is still on the old release
    workers = list(cluster.workers.values())
    workers[0].version = "1.1.0"
    workers[1].version = "1.1.0"
    workers[2].version = "1.0.3"

    coord = Coordinator(
        resolver=cluster, channels=cluster, expected_version="1.1.0",
    )
    print("-- mixed-version cluster: the coordinator refuses the query --")
    try:
        coord.execute(dplan)
        raise AssertionError("version skew not detected")
    except WorkerError as e:
        print(f"rejected: {e}")

    # the upgrade finishes...
    workers[2].version = "1.1.0"
    print("\n-- fleet upgraded: same coordinator, same plan --")
    out = coord.execute(dplan).to_pandas()
    print(out.to_string(index=False))
    assert len(out) == 6


if __name__ == "__main__":
    main()
