"""A user-defined ExecutionPlan operator running distributed.

The reference's `examples/custom_execution_plan.rs`: implement a custom
physical operator, register a codec for it, and watch it survive the full
distributed lifecycle — plan staging, serialization, shipment to workers,
decode, and execution inside each task's traced XLA program.

The operator here is `WinsorizeExec`: clamps a numeric column to the
[lo, hi] quantile band estimated from each task's local shard. It is a
single-child, capacity-preserving node — the simplest shape of custom
operator — and composes with the engine's own exchanges (the plan below
shuffles by key after winsorizing, then aggregates).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from datafusion_distributed_tpu.io.parquet import arrow_to_table
from datafusion_distributed_tpu.ops.aggregate import AggSpec
from datafusion_distributed_tpu.ops.table import Column, Table
from datafusion_distributed_tpu.plan.physical import (
    ExecContext,
    ExecutionPlan,
    HashAggregateExec,
    MemoryScanExec,
    SortExec,
)
from datafusion_distributed_tpu.ops.sort import SortKey
from datafusion_distributed_tpu.planner.distributed import (
    DistributedConfig,
    distribute_plan,
)
from datafusion_distributed_tpu.runtime.codec import register_codec
from datafusion_distributed_tpu.runtime.coordinator import (
    Coordinator,
    InMemoryCluster,
)


class WinsorizeExec(ExecutionPlan):
    """Clamp `column` to its local [q, 1-q] quantile band.

    Everything a custom node must provide: the tree contract
    (children / with_new_children), schema + output_capacity (static shapes
    are what make the node XLA-traceable), and `_execute`, which runs at
    TRACE time — jnp ops only, no data-dependent Python control flow."""

    codec_kind = "winsorize"  # ties the node to its registered codec

    def __init__(self, child: ExecutionPlan, column: str, q: float):
        super().__init__()
        self.child = child
        self.column = column
        self.q = q

    def children(self):
        return [self.child]

    def with_new_children(self, children):
        return WinsorizeExec(children[0], self.column, self.q)

    def schema(self):
        return self.child.schema()

    def output_capacity(self):
        return self.child.output_capacity()

    def label(self):
        return f"Winsorize({self.column}, q={self.q})"

    def _execute(self, ctx: ExecContext) -> Table:
        t = self.child.execute(ctx)
        i = t.names.index(self.column)
        col = t.columns[i]
        live = t.row_mask()
        # quantiles over live rows only (padding is masked to NaN)
        vals = jnp.where(live, col.data, jnp.nan)
        lo = jnp.nanquantile(vals, self.q)
        hi = jnp.nanquantile(vals, 1.0 - self.q)
        clamped = jnp.clip(col.data, lo, hi)
        cols = list(t.columns)
        cols[i] = Column(clamped, col.validity, col.dtype, col.dictionary)
        # a custom metric, visible in explain_analyze / coordinator metrics
        ctx.record_metric(self, "clamped_rows",
                          jnp.sum((col.data != clamped) & live))
        return Table(t.names, tuple(cols), t.num_rows)


# The codec pair: encode -> JSON-able dict, decode -> node. Registered once
# per process; workers decoding a shipped plan look the kind up in the same
# registry (`runtime/codec.py` register_codec, the user-codec registry
# analogue of `src/protobuf/user_codec.rs`).
register_codec(
    "winsorize",
    lambda p, store: {
        "column": p.column,
        "q": p.q,
        "c": __import__(
            "datafusion_distributed_tpu.runtime.codec", fromlist=["encode_plan"]
        ).encode_plan(p.child, store),
    },
    lambda o, store: WinsorizeExec(
        __import__(
            "datafusion_distributed_tpu.runtime.codec", fromlist=["decode_plan"]
        ).decode_plan(o["c"], store),
        o["column"],
        o["q"],
    ),
)


def main() -> None:
    rng = np.random.default_rng(7)
    n = 20_000
    # heavy-tailed values: winsorizing changes the group sums visibly
    arrow = pa.table({
        "k": rng.integers(0, 8, n),
        "v": rng.standard_t(df=2, size=n) * 100,
    })
    t = arrow_to_table(arrow)

    scan = MemoryScanExec([t], t.schema())
    custom = WinsorizeExec(scan, "v", q=0.01)
    agg = HashAggregateExec(
        "single", ["k"],
        [AggSpec("sum", "v", "winsorized_sum"),
         AggSpec("count_star", None, "n")],
        custom,
    )
    plan = SortExec([SortKey("k")], agg)

    dplan = distribute_plan(plan, DistributedConfig(num_tasks=4))
    print("-- staged plan (custom node inside the task pipeline) --")
    print(dplan.display_tree())

    cluster = InMemoryCluster(num_workers=3)
    coord = Coordinator(resolver=cluster, channels=cluster)
    out = coord.execute(dplan).to_pandas()
    print("\n-- result (winsorized group sums) --")
    print(out.to_string(index=False))

    clamped = sum(
        m.get("clamped_rows", 0)
        for task in coord.metrics.values()
        for m in task.get("nodes", {}).values()
        if isinstance(m, dict)
    )
    print(f"\nrows clamped across all tasks: {clamped}")
    assert len(out) == 8


if __name__ == "__main__":
    main()
