"""Meshes-as-workers: each worker owns a device mesh; stage task spans run
as ONE SPMD program per worker, and the host peer-to-peer data plane moves
partitions between the meshes.

This is SURVEY.md §2.10's "same-mesh = collective, off-mesh = host RPC"
topology — the reference's cluster of multi-threaded workers
(`/root/reference/src/worker/worker_service.rs:42-52`) with each worker's
intra-node parallelism provided by a TPU mesh slice instead of a thread
pool. On one host this runs over the 8-device virtual CPU mesh:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/mesh_workers_cluster.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pyarrow as pa

from datafusion_distributed_tpu.runtime.coordinator import Coordinator
from datafusion_distributed_tpu.runtime.mesh_worker import InMemoryMeshCluster
from datafusion_distributed_tpu.sql.context import SessionContext


def main() -> None:
    rng = np.random.default_rng(0)
    n = 50_000
    ctx = SessionContext()
    ctx.register_arrow("orders", pa.table({
        "custkey": rng.integers(0, 1000, n),
        "total": rng.uniform(1, 1000, n).round(2),
    }))
    ctx.register_arrow("customers", pa.table({
        "custkey": np.arange(1000),
        "segment": np.asarray(
            [f"segment-{i % 5}" for i in range(1000)], dtype=object
        ),
    }))
    ctx.config.distributed_options["bytes_per_task"] = 1  # force fan-out

    # two "hosts", each owning half the devices as its private mesh
    cluster = InMemoryMeshCluster(num_workers=2, devices_per_worker=4)
    coord = Coordinator(resolver=cluster, channels=cluster)

    df = ctx.sql(
        "select c.segment, count(*) n, sum(o.total) revenue "
        "from orders o join customers c on o.custkey = c.custkey "
        "group by c.segment order by revenue desc"
    )
    out = df._strip_quals(
        df.collect_coordinated_table(coordinator=coord, num_tasks=8)
    ).to_pandas()
    print(out.to_string(index=False))

    # each worker ran its stage spans as single SPMD programs:
    for url, w in cluster.workers.items():
        print(f"{url}: mesh width {w.mesh_width}, "
              f"{len(w._spans)} span programs executed")
    peer = [m for m in coord.stream_metrics.values()
            if m.get("plane") == "peer"]
    print(f"peer-plane boundaries: {len(peer)} "
          f"(coordinator row bytes: {sum(m['coordinator_bytes'] for m in peer)})")

    single = df.to_pandas()
    assert np.allclose(
        out["revenue"].to_numpy(), single["revenue"].to_numpy(), rtol=1e-4
    ), "distributed result diverged from single-node"
    print("matches single-node execution")


if __name__ == "__main__":
    main()
