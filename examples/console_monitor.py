"""Live cluster console against a running cluster.

The reference's `console/` TUI: worker discovery + task progress at a poll
interval. This example starts an in-process cluster, runs a query, and
renders a few console frames (point `python -m
datafusion_distributed_tpu.console grpc://host:port` at a real cluster).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pyarrow as pa

from datafusion_distributed_tpu.console import Console
from datafusion_distributed_tpu.runtime.coordinator import (
    Coordinator,
    InMemoryCluster,
)
from datafusion_distributed_tpu.sql.context import SessionContext


def main() -> None:
    cluster = InMemoryCluster(3)
    coordinator = Coordinator(resolver=cluster, channels=cluster)
    rng = np.random.default_rng(4)
    ctx = SessionContext()
    ctx.register_arrow("t", pa.table({
        "k": rng.integers(0, 20, 8000), "v": rng.normal(size=8000),
    }))
    df = ctx.sql("select k, avg(v) from t group by k")
    df.collect_coordinated_table(coordinator=coordinator, num_tasks=4)

    console = Console(cluster, cluster, poll_s=0.2)
    console.track(list(coordinator.metrics.keys())[:5])
    console.run(frames=3)


if __name__ == "__main__":
    main()
