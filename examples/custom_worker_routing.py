"""User-controlled task->worker routing.

The reference's `examples/custom_worker_url_routing.rs`: by default tasks
round-robin over workers; a `route_tasks` hook pins them (data locality,
heterogeneous hardware, tenancy). Here even stages go to worker 0, odd to
worker 1, and the routing log proves it.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pyarrow as pa

from datafusion_distributed_tpu.runtime.coordinator import (
    Coordinator,
    InMemoryCluster,
)
from datafusion_distributed_tpu.sql.context import SessionContext

ROUTES = []


def route_by_stage(query_id, stage_id, task_number, urls):
    url = urls[abs(stage_id) % len(urls)]
    ROUTES.append((stage_id, task_number, url))
    return url


def main() -> None:
    rng = np.random.default_rng(3)
    ctx = SessionContext()
    ctx.register_arrow("t", pa.table({
        "k": rng.integers(0, 30, 5000), "v": rng.normal(size=5000),
    }))
    cluster = InMemoryCluster(2)
    coordinator = Coordinator(
        resolver=cluster, channels=cluster, route_tasks=route_by_stage
    )
    df = ctx.sql("select k, sum(v) sv from t group by k order by sv desc")
    out = df._strip_quals(
        df.collect_coordinated_table(coordinator=coordinator, num_tasks=4)
    ).to_pandas()
    print(out.head(5).to_string(index=False))
    print("\nrouting decisions (stage, task) -> worker:")
    for stage, task, url in ROUTES:
        print(f"  ({stage}, {task}) -> {url}")


if __name__ == "__main__":
    main()
