"""Real gRPC workers on localhost ports.

The reference's `examples/localhost_run/worker.rs`: every worker is a real
network service; plans ship as compressed binary frames and results stream
back chunked (zstd Arrow IPC — see runtime/transport.py). The same code
deploys multi-host by starting `serve_worker` on each machine and pointing
the resolver at their URLs.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pyarrow as pa

from datafusion_distributed_tpu.runtime.coordinator import Coordinator
from datafusion_distributed_tpu.runtime.grpc_worker import (
    start_localhost_cluster,
)
from datafusion_distributed_tpu.sql.context import SessionContext


def main() -> None:
    cluster = start_localhost_cluster(num_workers=2)
    print("workers:", cluster.get_urls())
    try:
        rng = np.random.default_rng(1)
        n = 20_000
        ctx = SessionContext()
        ctx.register_arrow("events", pa.table({
            "kind": rng.integers(0, 8, n),
            "ms": rng.exponential(20.0, n),
        }))
        coordinator = Coordinator(resolver=cluster, channels=cluster)
        df = ctx.sql(
            "select kind, count(*) n, avg(ms) avg_ms, max(ms) worst "
            "from events group by kind order by kind"
        )
        out = df._strip_quals(
            df.collect_coordinated_table(coordinator=coordinator,
                                         num_tasks=4)
        ).to_pandas()
        print(out.to_string(index=False))
    finally:
        cluster.shutdown()


if __name__ == "__main__":
    main()
