"""SQL over an in-process multi-worker cluster.

The reference's `examples/in_memory_cluster.rs`: a full coordinator/worker
topology faked inside one process (its InMemoryChannelResolver). Useful as
the first rung of distributed debugging — same planner, codec, and task
lifecycle as a real cluster, no sockets.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pyarrow as pa

from datafusion_distributed_tpu.runtime.coordinator import (
    Coordinator,
    InMemoryCluster,
)
from datafusion_distributed_tpu.sql.context import SessionContext


def main() -> None:
    rng = np.random.default_rng(0)
    n = 10_000
    ctx = SessionContext()
    ctx.register_arrow("orders", pa.table({
        "o_id": np.arange(n),
        "region": rng.integers(0, 5, n),
        "amount": np.round(rng.uniform(1, 500, n), 2),
    }))

    cluster = InMemoryCluster(num_workers=3)
    coordinator = Coordinator(resolver=cluster, channels=cluster)

    df = ctx.sql(
        "select region, count(*) as orders, sum(amount) as revenue "
        "from orders group by region order by revenue desc"
    )
    print("-- staged plan --")
    print(df.explain_distributed(num_tasks=4))
    out = df._strip_quals(
        df.collect_coordinated_table(coordinator=coordinator, num_tasks=4)
    ).to_pandas()
    print("-- result --")
    print(out.to_string(index=False))
    print(f"\nworker task metrics collected: {len(coordinator.metrics)}")


if __name__ == "__main__":
    main()
