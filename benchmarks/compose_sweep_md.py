"""Compose SWEEP_r05.md from the JSONL emitted by benchmarks/sweep_sf.py.

Usage: python benchmarks/compose_sweep_md.py [--in .sweep_r05.jsonl] [--out SWEEP_r05.md]
"""

from __future__ import annotations

import argparse
import json
from collections import defaultdict


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--inp", default="/root/repo/.sweep_r05.jsonl")
    ap.add_argument("--out", default="/root/repo/SWEEP_r05.md")
    args = ap.parse_args()

    rows = [json.loads(l) for l in open(args.inp) if l.strip()]
    datagen = next((r for r in rows if r.get("stage") == "datagen"), None)
    per = defaultdict(dict)   # query -> tier -> record
    tiers_seen: list[str] = []
    for r in rows:
        if "tier" not in r or r.get("stage"):
            continue
        q, tier = r["query"], r["tier"]
        per[q][tier] = r
        if tier not in tiers_seen:
            tiers_seen.append(tier)

    # stream / adaptive evidence aggregated across queries
    n_retries = sum(r.get("retries") or 0
                    for byt in per.values() for r in byt.values())
    partials = sum(1 for byt in per.values()
                   for r in byt.values() if r.get("partial_decisions"))
    resized = sum(1 for byt in per.values() for r in byt.values()
                  for (sid, planned, got) in (r.get("task_count_decisions") or [])
                  if got != planned)
    multi_chunk = 0
    for byt in per.values():
        for r in byt.values():
            for m in r.get("streams") or []:
                if (m.get("chunks") or 0) > 1:
                    multi_chunk += 1

    def qkey(q: str) -> int:
        return int(q[1:])

    lines = ["# SWEEP r05 — scale-up TPC-H parity (non-trivial data)", ""]
    if datagen:
        rws = datagen.get("rows", {})
        lines += [
            f"Data: TPC-H SF {datagen['sf']} generated in "
            f"{datagen['seconds']}s — lineitem {rws.get('lineitem', '?'):,} rows, "
            f"orders {rws.get('orders', '?'):,}, customer {rws.get('customer', '?'):,}.",
            "",
            "Every tier is checked for multiset equality against the single-node"
            " result (float rtol 5e-4). `bytes_per_task=1` forces maximum"
            " distribution, the forced-heavy-distribution intent of the"
            " reference's `tpch_correctness_test.rs:23-80`.",
            "",
        ]
    hdr = "| query | " + " | ".join(
        f"{t} (s)" for t in tiers_seen) + " | parity |"
    lines += [hdr, "|" + "---|" * (len(tiers_seen) + 2)]
    n_ok = n_bad = 0
    for q in sorted(per, key=qkey):
        cells, all_ok = [], True
        for t in tiers_seen:
            r = per[q].get(t)
            if r is None:
                cells.append("—")
            elif r.get("ok"):
                cells.append(f"{r['seconds']}")
            else:
                all_ok = False
                cells.append(f"FAIL: {r.get('mismatch') or r.get('error', '?')[:60]}")
        n_ok += all_ok
        n_bad += not all_ok
        lines.append(f"| {q} | " + " | ".join(cells)
                     + (" | ok |" if all_ok else " | MISMATCH |"))
    lines += [
        "",
        f"**{n_ok} queries match across all tiers; {n_bad} mismatch.**",
        "",
        "## Machinery exercised at this scale",
        "",
        f"- overflow retries observed: {n_retries}",
        f"- mid-execution partial-sample decisions frozen: {partials}",
        f"- adaptive task-count resizes (got != planned): {resized}",
        f"- multi-chunk producer streams: {multi_chunk}",
        "",
    ]
    open(args.out, "w").write("\n".join(lines))
    print(f"wrote {args.out}: {n_ok} ok / {n_bad} bad")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
