#!/usr/bin/env python
"""Kernel/exchange micro-benchmarks (the criterion-bench analogue:
`/root/reference/benchmarks/benches/{shuffle,transport,local_repartition,
broadcast_cache_scenarios}.rs`).

Measures the engine's hot primitives in isolation so hot-path regressions
are visible without a full TPC run:

    agg      claim-loop hash aggregate (build + segmented reduce)
    join     hash join build + probe + expand
    sort     multi-key lexicographic sort
    shuffle  mesh all_to_all hash shuffle (8 virtual devices on CPU)
    coalesce group coalesce (ppermute rounds) vs all_gather
    wire     transport frame pack/unpack (zstd vs none)

Prints one JSON line per bench: {"bench", "rows_per_s", "ms"}.

Run: python benchmarks/micro_bench.py [--rows N] [--device cpu|tpu]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _timeit(fn, *args, repeats: int = 3):
    import jax

    out = fn(*args)  # compile + warm
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1 << 18)
    ap.add_argument("--device", default="cpu", choices=["cpu", "tpu"])
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()

    if args.device == "cpu":
        os.environ.setdefault(
            "XLA_FLAGS",
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8",
        )
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if args.device == "cpu":
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np
    import pyarrow as pa

    from datafusion_distributed_tpu.io.parquet import arrow_to_table
    from datafusion_distributed_tpu.ops.aggregate import (
        AggSpec, hash_aggregate,
    )
    from datafusion_distributed_tpu.ops.join import build_join_table, hash_join
    from datafusion_distributed_tpu.ops.sort import SortKey, sort_table
    from datafusion_distributed_tpu.ops.table import round_up_pow2

    n = args.rows
    rng = np.random.default_rng(0)
    results = []

    def report(name: str, seconds: float, rows: int = n):
        results.append({
            "bench": name,
            "ms": round(seconds * 1e3, 3),
            "rows_per_s": round(rows / seconds) if seconds > 0 else None,
        })
        print(json.dumps(results[-1]), flush=True)

    # ---- hash aggregate ---------------------------------------------------
    t = arrow_to_table(pa.table({
        "k": rng.integers(0, n // 16, n),
        "v": rng.normal(size=n),
    }))
    slots = round_up_pow2(max(n // 8, 16))
    agg = jax.jit(lambda tt: hash_aggregate(
        tt, ["k"], [AggSpec("sum", "v", "sv"),
                    AggSpec("count_star", None, "c")], slots,
    ))
    report("agg_claim_loop", _timeit(agg, t, repeats=args.repeats))

    # ---- hash join --------------------------------------------------------
    nb = n // 4
    build = arrow_to_table(pa.table({
        "k": rng.permutation(nb), "bv": rng.normal(size=nb),
    }))
    probe = arrow_to_table(pa.table({
        "k": rng.integers(0, nb, n), "pv": rng.normal(size=n),
    }))
    out_cap = round_up_pow2(n)

    def join(p, b):
        bs = build_join_table(b, ["k"], round_up_pow2(2 * nb))
        return hash_join(p, bs, ["k"], "inner", out_cap,
                         build_prefix="b_")

    report("join_build_probe", _timeit(jax.jit(join), probe, build,
                                       repeats=args.repeats))

    # ---- sort -------------------------------------------------------------
    st = arrow_to_table(pa.table({
        "a": rng.integers(0, 1000, n), "b": rng.normal(size=n),
    }))
    srt = jax.jit(lambda tt: sort_table(
        tt, [SortKey("a"), SortKey("b", ascending=False)]
    ))
    report("sort_two_keys", _timeit(srt, st, repeats=args.repeats))

    # ---- mesh exchanges ---------------------------------------------------
    if len(jax.devices()) >= 8:
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        from datafusion_distributed_tpu.parallel.exchange import (
            broadcast_exchange,
            group_coalesce_exchange,
            partition_table,
            range_shuffle_exchange,
            shuffle_exchange,
        )
        from datafusion_distributed_tpu.runtime.mesh_executor import (
            AXIS, make_mesh,
        )

        nt = 8
        mesh = make_mesh(nt)
        et = arrow_to_table(pa.table({
            "k": rng.integers(0, n // 16, n),
            "v": rng.normal(size=n),
            "w": rng.normal(size=n),
        }))
        parts = partition_table(et, nt)
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *parts)
        per_dest = round_up_pow2(max(2 * n // (nt * nt), 64))

        def mk(fn):
            def step(s):
                local = jax.tree.map(lambda x: x[0], s)
                out = fn(local)
                return jax.tree.map(
                    lambda x: x[None] if hasattr(x, "ndim") else x, out
                )
            return jax.jit(shard_map(
                step, mesh=mesh, in_specs=(P(AXIS),), out_specs=P(AXIS),
                check_rep=False,
            ))

        shuf = mk(lambda t_: shuffle_exchange(t_, ["k"], AXIS, nt, per_dest))
        report("shuffle_all_to_all", _timeit(shuf, stacked,
                                             repeats=args.repeats))
        bcast = mk(lambda t_: broadcast_exchange(t_, AXIS, nt))
        report("broadcast_all_gather", _timeit(bcast, stacked,
                                               repeats=args.repeats))
        gco = mk(lambda t_: group_coalesce_exchange(t_, AXIS, nt, 2))
        report("coalesce_n_to_2_ppermute", _timeit(gco, stacked,
                                                   repeats=args.repeats))
        rs_per_dest = round_up_pow2(max(4 * n // (nt * nt), 64))
        rsh = mk(lambda t_: range_shuffle_exchange(
            t_, [SortKey("v")], AXIS, nt, rs_per_dest))
        report("range_shuffle_sample_sort", _timeit(rsh, stacked,
                                                    repeats=args.repeats))

    # ---- pallas claim-loop vs XLA claim loop ------------------------------
    from datafusion_distributed_tpu.ops.pallas_hash import (
        pallas_available, pallas_build_group_ids,
    )

    from datafusion_distributed_tpu.ops import pallas_hash as _ph

    hb_slots = round_up_pow2(max(n // 16, 64))
    # gate on the partitioned-table bound: the row-blocked multi-pass
    # kernel handles any row count and up to _MAX_PARTITIONS sub-tables
    if pallas_available() and hb_slots <= _ph._MAX_TABLE_SLOTS:
        from datafusion_distributed_tpu.ops.aggregate import (
            build_group_table,
        )
        from datafusion_distributed_tpu.ops.hash import hash_columns

        hk = rng.integers(0, n // 64, n).astype(np.int32)
        slots = hb_slots
        keys = [jnp.asarray(hk)]
        h0 = hash_columns(keys, [None])
        slot0 = (h0 & np.uint32(slots - 1)).astype(jnp.int32)
        live_all = jnp.ones(n, dtype=jnp.bool_)
        keys_mat = jnp.asarray(hk)[:, None]

        # force the XLA path regardless of DFTPU_PALLAS so the comparison
        # is never pallas-vs-pallas
        saved = os.environ.pop("DFTPU_PALLAS", None)
        try:
            xla_build = jax.jit(lambda: build_group_table(
                keys, [None], live_all, slots
            ).group_ids)
            report("hashbuild_xla_claimloop", _timeit(xla_build,
                                                      repeats=args.repeats))
        finally:
            if saved is not None:
                os.environ["DFTPU_PALLAS"] = saved
        interp = jax.devices()[0].platform != "tpu"
        pl_build = jax.jit(lambda: pallas_build_group_ids(
            keys_mat, slot0, live_all, slots, interpret=interp
        )[0])
        report(
            "hashbuild_pallas" + ("_interpret" if interp else ""),
            _timeit(pl_build, repeats=args.repeats),
        )
    elif pallas_available():
        print(json.dumps({"bench": "hashbuild_pallas",
                          "skipped": "rows/slots exceed the VMEM gate"}),
              flush=True)

    # ---- stage-DAG scheduler overlap --------------------------------------
    # Bushy TPC-H q5 over a 4-worker in-memory cluster: sequential stage
    # scheduling (SET distributed.stage_parallelism = 1, the pre-scheduler
    # depth-first order) vs the concurrent stage-DAG scheduler (= 4). A
    # uniform injected per-execute delay (runtime/chaos.py kind="delay")
    # stands in for the device/DCN latency a single-process in-memory
    # cluster does not have — exactly the per-stage idle time the
    # scheduler exists to overlap; both schedulers pay it identically per
    # task, so the wall-clock ratio isolates scheduling. Results are
    # byte-identical by design (tests/test_stage_scheduler.py pins that);
    # this case measures the wall clock + the explain_analyze overlap
    # factor (sum of stage walls / query wall, >1.0 = real overlap).
    from datafusion_distributed_tpu.data.tpchgen import gen_tpch
    from datafusion_distributed_tpu.runtime.chaos import (
        FaultPlan,
        FaultSpec,
        wrap_cluster,
    )
    from datafusion_distributed_tpu.runtime.coordinator import (
        Coordinator,
        InMemoryCluster,
    )
    from datafusion_distributed_tpu.sql.context import SessionContext

    q5 = """
    select n_name, sum(l_extendedprice * (1 - l_discount)) as revenue
    from customer, orders, lineitem, supplier, nation, region
    where c_custkey = o_custkey and l_orderkey = o_orderkey
      and l_suppkey = s_suppkey and c_nationkey = s_nationkey
      and s_nationkey = n_nationkey and n_regionkey = r_regionkey
      and r_name = 'ASIA' and o_orderdate >= date '1994-01-01'
      and o_orderdate < date '1995-01-01'
    group by n_name order by revenue desc
    """
    sctx = SessionContext()
    sctx.config.distributed_options["bytes_per_task"] = 1  # force fan-out
    # the coordinator-streamed planes execute stages EAGERLY at
    # materialization, so stage scheduling governs their wall clock
    sctx.config.distributed_options["peer_shuffle"] = False
    for tname, arrow in gen_tpch(sf=0.002, seed=7).items():
        sctx.register_arrow(tname, arrow)

    def run_staged(par: int, delay_s: float):
        cluster: object = InMemoryCluster(4)
        if delay_s > 0:
            cluster = wrap_cluster(cluster, FaultPlan(0, [
                FaultSpec(site="execute", kind="delay", delay_s=delay_s,
                          rate=1.0),
            ]))
        coord = Coordinator(
            resolver=cluster, channels=cluster,
            config_options={"stage_parallelism": par,
                            "peer_shuffle": False},
        )
        df = sctx.sql(q5)
        t0 = time.perf_counter()
        df.collect_coordinated_table(coordinator=coord, num_tasks=4)
        return time.perf_counter() - t0, coord

    run_staged(4, 0.0)  # warm the XLA compile caches once
    # the delay must DOMINATE per-stage compute for the ratio to isolate
    # scheduling on a CPU-starved box (concurrent stages still contend
    # for the same cores here; on real hardware compute overlaps too)
    delay_ms = 250.0
    t_seq = min(run_staged(1, delay_ms / 1e3)[0] for _ in range(2))
    conc_runs = [run_staged(4, delay_ms / 1e3) for _ in range(2)]
    t_conc, coord = min(conc_runs, key=lambda r: r[0])
    overlap = coord.overlap_factor()
    results.append({"bench": "stage_overlap_sequential",
                    "ms": round(t_seq * 1e3, 1)})
    print(json.dumps(results[-1]), flush=True)
    results.append({
        "bench": "stage_overlap_concurrent",
        "ms": round(t_conc * 1e3, 1),
        "speedup_vs_sequential": round(t_seq / t_conc, 2),
        "overlap_factor": round(overlap, 2) if overlap else None,
        "workers": 4,
        "injected_delay_ms": delay_ms,
    })
    print(json.dumps(results[-1]), flush=True)

    # ---- memory pressure: enforced worker budget + host spill -------------
    # The q5 fan-out shape (same plan as the stage-overlap case) run
    # twice on ONE cluster: an unconstrained warm-up + measured arm
    # (reset_peak between them isolates the per-phase peak from the
    # warm-up's), then the SAME cluster re-budgeted at 0.5x the measured
    # per-worker peak — the spill path must absorb the difference.
    # Reported: per-arm wall + peak staged MB, spilled MB, spill GB/s.
    def mem_cluster():
        cluster = InMemoryCluster(4)
        coord = Coordinator(
            resolver=cluster, channels=cluster,
            config_options={"stage_parallelism": 4, "peer_shuffle": False},
        )
        return cluster, coord

    def mem_run(cluster, coord):
        df = sctx.sql(q5)
        t0 = time.perf_counter()
        df.collect_coordinated_table(coordinator=coord, num_tasks=4)
        return time.perf_counter() - t0

    def mem_stores(cluster):
        return [
            cluster.get_worker(u).table_store for u in cluster.get_urls()
        ]

    mp_cluster, mp_coord = mem_cluster()
    mem_run(mp_cluster, mp_coord)  # warm the compile caches
    for s in mem_stores(mp_cluster):
        s.reset_peak()  # per-phase peak: the warm-up's must not leak in
    t_unbounded = mem_run(mp_cluster, mp_coord)
    peaks = [s.stats()["peak_nbytes"] for s in mem_stores(mp_cluster)]
    peak_worker = max(peaks)
    results.append({
        "bench": "memory_pressure_unbounded",
        "ms": round(t_unbounded * 1e3, 1),
        "peak_staged_mb": round(sum(peaks) / 1e6, 2),
        "peak_worker_mb": round(peak_worker / 1e6, 2),
        "workers": 4,
    })
    print(json.dumps(results[-1]), flush=True)
    mp_budget = max(peak_worker // 2, 1)
    for s in mem_stores(mp_cluster):
        s.reset_peak()
        s.set_budget(mp_budget)
    t_budgeted = mem_run(mp_cluster, mp_coord)
    mp_stats = [s.stats() for s in mem_stores(mp_cluster)]
    spilled = sum(st["spilled_total_bytes"] for st in mp_stats)
    results.append({
        "bench": "memory_pressure_budgeted",
        "ms": round(t_budgeted * 1e3, 1),
        "budget_mb": round(mp_budget / 1e6, 2),
        "peak_staged_mb": round(
            sum(st["peak_nbytes"] for st in mp_stats) / 1e6, 2
        ),
        "spilled_mb": round(spilled / 1e6, 2),
        "spills": sum(st["spills"] for st in mp_stats),
        "refaults": sum(st["refaults"] for st in mp_stats),
        "spill_gbps": round(spilled / max(t_budgeted, 1e-9) / 1e9, 3),
        "slowdown_vs_unbounded": round(
            t_budgeted / max(t_unbounded, 1e-9), 2
        ),
        "workers": 4,
    })
    print(json.dumps(results[-1]), flush=True)
    for s in mem_stores(mp_cluster):
        s.set_budget(0)  # unconstrain: later cases share the process

    # ---- pipelined streaming shuffle --------------------------------------
    # q5-shaped two-stage shuffle (peerless coordinator tier, DAG
    # scheduler): a fact table hash-shuffled to 8 consumer tasks over 4
    # workers, aggregated per partition, coalesced. Two injected costs
    # stand in for what an in-process cluster lacks: a per-chunk wire
    # delay on the partition streams (DCN latency) and a per-execute
    # delay on the CONSUMER stage (device latency). Both planes pay both
    # identically and produce byte-identical results (the gate test pins
    # that); the MATERIALIZED plane serializes [stream the whole
    # boundary] -> [two waves of delayed consumer executes], while the
    # PIPELINED plane starts consumer task j the moment partition j
    # closes — the first wave of consumer executes overlaps the later
    # partitions' streaming, which is the pipeline-parallelism claim
    # this case measures.
    from datafusion_distributed_tpu.ops.aggregate import AggSpec as _Agg
    from datafusion_distributed_tpu.parallel.exchange import (
        partition_table as _ptab,
    )
    from datafusion_distributed_tpu.plan.exchanges import (
        ShuffleExchangeExec as _Shuf,
    )
    from datafusion_distributed_tpu.plan.physical import (
        HashAggregateExec as _HAgg,
        MemoryScanExec as _MScan,
    )
    from datafusion_distributed_tpu.planner.distributed import (
        DistributedConfig as _DCfg,
        distribute_plan as _dplan,
    )
    from datafusion_distributed_tpu.runtime.worker import Worker as _Wkr

    wire_ms = 3.0
    consumer_delay_ms = 120.0

    class _SlowWireWorker(_Wkr):
        def execute_task_partitions(self, *a, **kw):
            for item in super().execute_task_partitions(*a, **kw):
                time.sleep(wire_ms / 1e3)
                yield item

    class _SlowWireCluster:
        def __init__(self, n):
            self.workers = {
                f"mem://wire-{i}": _SlowWireWorker(f"mem://wire-{i}")
                for i in range(n)
            }
            for w in self.workers.values():
                w.peer_channels = self

        def get_urls(self):
            return list(self.workers.keys())

        def get_worker(self, url):
            return self.workers[url]

    ps_n = 1 << 17
    ps_ndv = 1 << 12
    ps_t = arrow_to_table(pa.table({
        "k": rng.integers(0, ps_ndv, ps_n), "v": rng.normal(size=ps_n),
    }))

    def two_stage_shuffle_plan():
        scan = _MScan(_ptab(ps_t, 4), ps_t.schema())
        # per-dest sized at 4x the expected rows-per-(producer, dest):
        # the boundary cost, not padded compute, must dominate this case
        ex = _Shuf(scan, ["k"], 8,
                   round_up_pow2(max(4 * ps_n // (8 * 4), 8)))
        agg = _HAgg("single", ["k"], [_Agg("sum", "v", "sv")], ex,
                    num_slots=round_up_pow2(4 * ps_ndv))
        agg.est_rows = ps_ndv
        return _dplan(agg, _DCfg(num_tasks=8))

    ps_plan = two_stage_shuffle_plan()
    # the consumer stage's tasks run while materializing the SECOND
    # boundary (the coalesce above the aggregate)
    consumer_sid = max(
        e.stage_id for e in ps_plan.collect(
            lambda n: getattr(n, "is_exchange", False)
        )
    )

    def run_pipelined(pipelined: bool):
        cluster = wrap_cluster(_SlowWireCluster(4), FaultPlan(0, [
            FaultSpec(site="execute", kind="delay",
                      delay_s=consumer_delay_ms / 1e3, rate=1.0,
                      stages=[consumer_sid]),
        ]))
        coord = Coordinator(
            resolver=cluster, channels=cluster,
            config_options={"stage_parallelism": 4,
                            "peer_shuffle": False,
                            "stream_chunk_rows": 1024,
                            "pipelined_shuffle": pipelined},
        )
        t0 = time.perf_counter()
        coord.execute(ps_plan)
        return time.perf_counter() - t0, coord

    run_pipelined(True)  # warm the XLA compile caches once
    t_mat = min(run_pipelined(False)[0] for _ in range(2))
    pl_runs = [run_pipelined(True) for _ in range(2)]
    t_pipe, pl_coord = min(pl_runs, key=lambda r: r[0])
    pl_bytes = sum(
        v.get("exchange_bytes", 0)
        for v in pl_coord.stream_metrics.values()
        if v.get("plane") == "pipelined"
    )
    results.append({"bench": "pipelined_shuffle_materialized",
                    "ms": round(t_mat * 1e3, 1)})
    print(json.dumps(results[-1]), flush=True)
    results.append({
        "bench": "pipelined_shuffle_pipelined",
        "ms": round(t_pipe * 1e3, 1),
        "speedup_vs_materialized": round(t_mat / max(t_pipe, 1e-9), 2),
        "exchange_bytes": pl_bytes,
        "workers": 4,
        "consumer_tasks": 8,
        "wire_delay_per_chunk_ms": wire_ms,
        "consumer_delay_ms": consumer_delay_ms,
        "rows": ps_n,
    })
    print(json.dumps(results[-1]), flush=True)

    # partial-aggregate push-down arm: an aggregate-over-shuffle plan
    # (hand-placed boundary — raw rows cross the wire) with the
    # statistics-driven push-down off vs on; the measured number is the
    # exchange bytes the pre-shuffle partial states save.
    pa_n = 1 << 16
    pa_t = arrow_to_table(pa.table({
        "k": rng.integers(0, 64, pa_n), "v": rng.normal(size=pa_n),
    }))

    def agg_over_shuffle(pushdown: bool):
        scan = _MScan(_ptab(pa_t, 4), pa_t.schema())
        ex = _Shuf(scan, ["k"], 4, round_up_pow2(max(4 * pa_n // 4, 8)))
        agg = _HAgg("single", ["k"],
                    [_Agg("sum", "v", "sv"),
                     _Agg("count_star", None, "c")], ex)
        agg.est_rows = 64
        return _dplan(agg, _DCfg(num_tasks=4,
                                 partial_agg_pushdown=pushdown))

    def run_pushdown(pushdown: bool):
        cluster = InMemoryCluster(4)
        coord = Coordinator(
            resolver=cluster, channels=cluster,
            config_options={"stage_parallelism": 4,
                            "peer_shuffle": False},
        )
        plan = agg_over_shuffle(pushdown)
        coord.execute(plan)  # warm
        t0 = time.perf_counter()
        coord.execute(plan)
        dt = time.perf_counter() - t0
        xbytes = sum(
            v.get("exchange_bytes", 0)
            for v in coord.stream_metrics.values()
            if "exchange_bytes" in v
        ) // 2  # two executes recorded
        return dt, xbytes

    t_pd_off, b_off = run_pushdown(False)
    t_pd_on, b_on = run_pushdown(True)
    results.append({
        "bench": "pipelined_shuffle_pushdown_off",
        "ms": round(t_pd_off * 1e3, 2),
        "exchange_bytes": b_off,
    })
    print(json.dumps(results[-1]), flush=True)
    results.append({
        "bench": "pipelined_shuffle_pushdown_on",
        "ms": round(t_pd_on * 1e3, 2),
        "exchange_bytes": b_on,
        "bytes_reduction_vs_off": round(1 - b_on / max(b_off, 1), 4),
    })
    print(json.dumps(results[-1]), flush=True)

    # ---- skew-aware shuffle splitting -------------------------------------
    # An 80/20-hot shuffle against a per-row-cost cluster (each task
    # sleeps GIL-released in proportion to its rows — standing in for
    # the per-row partition/wire cost a real worker pays): wall + the
    # per-task p99 with the skew splitter off vs on. The split fans the
    # hot producer slice out as contiguous row-range views, so any win
    # is pure scheduling — bytes and results stay identical
    # (tests/test_adaptivity.py pins that).
    from datafusion_distributed_tpu.plan.exchanges import (
        CoalesceExchangeExec as _Coal,
    )

    sk_hot, sk_cold, sk_per_row_s = 8000, 500, 20e-6
    sk_durations: list = []

    class _PerRowCostWorker(_Wkr):
        def execute_task(self, key, *a, **kw):
            out = super().execute_task(key, *a, **kw)
            dt = int(out.num_rows) * sk_per_row_s
            sk_durations.append(dt)
            time.sleep(dt)
            return out

    class _PerRowCostCluster:
        def __init__(self, n):
            self.workers = {
                f"mem://skew-{i}": _PerRowCostWorker(f"mem://skew-{i}")
                for i in range(n)
            }
            for w in self.workers.values():
                w.peer_channels = self

        def get_urls(self):
            return list(self.workers.keys())

        def get_worker(self, url):
            return self.workers[url]

    def skewed_plan():
        def mk(nrows, seed):
            r = np.random.default_rng(seed)
            return arrow_to_table(pa.table({
                "k": r.integers(0, 64, nrows),
                "v": r.normal(size=nrows),
            }))

        tasks = [mk(sk_hot, 0)] + [mk(sk_cold, i) for i in (1, 2, 3)]
        scan = _MScan(tasks, tasks[0].schema())
        ex = _Shuf(scan, ["k"], 4,
                   round_up_pow2(max(2 * (sk_hot + 3 * sk_cold), 8)))
        ex.producer_tasks = 4
        ex.stage_id = 1
        root = _Coal(ex, 4)
        root.stage_id = 2
        return root

    def run_skew(split: bool):
        sk_durations.clear()
        cluster = _PerRowCostCluster(4)
        coord = Coordinator(
            resolver=cluster, channels=cluster,
            config_options={
                # hand-assigned stage ids: sequential scheduler; the
                # splitter engages on the BULK plane only
                "stage_parallelism": 1,
                "pipelined_shuffle": False,
                "data_plane": "unary",
                "skew_split_factor": 2.0 if split else 0.0,
                "skew_split_min_rows": 64,
            },
        )
        t0 = time.perf_counter()
        coord.execute(skewed_plan())
        wall = time.perf_counter() - t0
        p99 = (float(np.percentile(sk_durations, 99))
               if sk_durations else 0.0)
        n_splits = sum(v.get("skew_splits", 0)
                       for v in coord.stream_metrics.values())
        return wall, p99, n_splits

    run_skew(False)  # warm the XLA compile caches once
    t_sk_off, p99_off, _ = min((run_skew(False) for _ in range(2)),
                               key=lambda r: r[0])
    t_sk_on, p99_on, n_splits = min((run_skew(True) for _ in range(2)),
                                    key=lambda r: r[0])
    results.append({
        "bench": "skew_shuffle_static",
        "ms": round(t_sk_off * 1e3, 1),
        "task_p99_ms": round(p99_off * 1e3, 1),
    })
    print(json.dumps(results[-1]), flush=True)
    results.append({
        "bench": "skew_shuffle_adaptive",
        "ms": round(t_sk_on * 1e3, 1),
        "task_p99_ms": round(p99_on * 1e3, 1),
        "speedup_vs_static": round(t_sk_off / max(t_sk_on, 1e-9), 2),
        "skew_splits": n_splits,
        "hot_rows": sk_hot,
        "cold_rows": sk_cold,
        "per_row_cost_us": sk_per_row_s * 1e6,
        "workers": 4,
    })
    print(json.dumps(results[-1]), flush=True)

    # ---- partial-aggregate bail-out ---------------------------------------
    # Worst case for the push-down: NDV ~= rows, so the pre-shuffle
    # partial reduces nothing and pure push-down pays the partial-state
    # machinery for zero byte savings. With the bail-out armed the
    # coordinator probes task 0, measures the ~1.0 reduction ratio, and
    # swaps the remaining tasks to passthrough — the arm should land
    # within ~10% of running with push-down disabled outright, which is
    # what lets partial_agg_pushdown default ON.
    ab_n = 1 << 15
    ab_t = arrow_to_table(pa.table({
        "k": np.arange(ab_n, dtype=np.int64),
        "v": rng.normal(size=ab_n),
    }))

    def bailout_plan(pushdown: bool):
        scan = _MScan(_ptab(ab_t, 4), ab_t.schema())
        ex = _Shuf(scan, ["k"], 4, round_up_pow2(max(4 * ab_n // 4, 8)))
        agg = _HAgg("single", ["k"], [_Agg("sum", "v", "sv")], ex,
                    num_slots=round_up_pow2(4 * ab_n))
        # est_rows left unset: the sampled-NDV heuristic (sqrt) lies low
        # on all-distinct keys, so the planner wrongly pushes down —
        # exactly the misprediction the probe corrects
        return _dplan(agg, _DCfg(num_tasks=4,
                                 partial_agg_pushdown=pushdown))

    def run_bailout(pushdown: bool, ratio: float):
        cluster = InMemoryCluster(4)
        coord = Coordinator(
            resolver=cluster, channels=cluster,
            config_options={"stage_parallelism": 4,
                            "peer_shuffle": False,
                            "pipelined_shuffle": False,
                            "data_plane": "unary",
                            "partial_agg_bailout_ratio": ratio},
        )
        plan = bailout_plan(pushdown)
        coord.execute(plan)  # warm
        t0 = time.perf_counter()
        coord.execute(plan)
        dt = time.perf_counter() - t0
        bailed = any(v.get("partial_agg_bailout")
                     for v in coord.stream_metrics.values())
        return dt, bailed

    t_ab_off, _ = run_bailout(False, 0.0)
    t_ab_on, ab_bailed = run_bailout(True, 0.5)
    results.append({
        "bench": "partial_agg_bailout_pushdown_off",
        "ms": round(t_ab_off * 1e3, 2),
    })
    print(json.dumps(results[-1]), flush=True)
    results.append({
        "bench": "partial_agg_bailout_adaptive",
        "ms": round(t_ab_on * 1e3, 2),
        "bailed_out": ab_bailed,
        "overhead_vs_off": round(t_ab_on / max(t_ab_off, 1e-9) - 1, 4),
        "ndv": ab_n,
        "rows": ab_n,
    })
    print(json.dumps(results[-1]), flush=True)

    # ---- multi-query serving throughput -----------------------------------
    # Closed-loop serving bench (runtime/serving.py): N clients each
    # submit-and-wait over a mixed workload — cheap q6-shaped aggregates
    # plus the bushy q5 — against ONE shared 4-worker cluster. Three arms
    # on identical workloads: serialized (max_concurrent_queries=1), the
    # fair-share global scheduler, and FIFO. The same injected execute
    # delay as the stage_overlap case stands in for device/DCN latency;
    # all arms pay it identically per task, so the qps ratio isolates the
    # cross-query scheduling. Reported: qps + p50/p99 per arm, and the
    # cheap-query p99 under fair vs FIFO (the "heavy query must not
    # starve cheap ones" number).
    from datafusion_distributed_tpu.runtime.serving import ServingSession

    q6 = """
    select sum(l_extendedprice * l_discount) as revenue
    from lineitem
    where l_shipdate >= date '1994-01-01'
      and l_shipdate < date '1995-01-01'
      and l_discount between 0.05 and 0.07 and l_quantity < 24
    """
    serve_delay_ms = 60.0
    n_clients = 4

    def serve_cluster():
        return wrap_cluster(InMemoryCluster(4), FaultPlan(0, [
            FaultSpec(site="execute", kind="delay",
                      delay_s=serve_delay_ms / 1e3, rate=1.0),
        ], query_scoped=True))

    def run_serving(max_conc, fair):
        from datafusion_distributed_tpu.runtime.serving import (
            percentile_ms,
            run_closed_loop,
        )

        srv = ServingSession(
            sctx, cluster=serve_cluster(), num_tasks=4,
            max_concurrent_queries=max_conc, fair_share=fair,
        )
        # one heavy client (q5), the rest cheap (q6): the starvation
        # scenario the fair-share policy exists for
        workloads = [[q5] * 2] + [[q6] * 4] * (n_clients - 1)
        res = run_closed_loop(
            srv, workloads,
            classify=lambda ci: "heavy" if ci == 0 else "cheap",
        )
        srv.close()
        cheap = res["walls"].get("cheap", [])
        heavy = res["walls"].get("heavy", [])
        return {
            "qps": round(res["queries"] / res["wall_s"], 2),
            "wall_ms": round(res["wall_s"] * 1e3, 1),
            "cheap_p50_ms": percentile_ms(cheap, 0.50),
            "cheap_p99_ms": percentile_ms(cheap, 0.99),
            "heavy_max_ms": percentile_ms(heavy, 1.0),
            "errors": res["errors"],
        }

    run_serving(n_clients, True)  # warm every compile cache once
    seq = run_serving(1, True)
    fair = run_serving(n_clients, True)
    fifo = run_serving(n_clients, False)
    results.append({"bench": "serving_throughput_sequential", **seq})
    print(json.dumps(results[-1]), flush=True)
    results.append({
        "bench": "serving_throughput_fair",
        **fair,
        "speedup_vs_sequential": round(
            fair["qps"] / max(seq["qps"], 1e-9), 2
        ),
        "clients": n_clients,
        "injected_delay_ms": serve_delay_ms,
    })
    print(json.dumps(results[-1]), flush=True)
    results.append({
        "bench": "serving_throughput_fifo",
        **fifo,
        "cheap_p99_fair_over_fifo": (
            round(fair["cheap_p99_ms"] / fifo["cheap_p99_ms"], 3)
            if fair["cheap_p99_ms"] and fifo["cheap_p99_ms"] else None
        ),
    })
    print(json.dumps(results[-1]), flush=True)

    # ---- zero-copy data plane ---------------------------------------------
    # Stage->stage hop through the worker partition plane: one producer
    # task hash-fans its output to 4 destinations (the per-dest slice
    # fan-out), each destination's chunk stream is pulled and reassembled
    # — the copying plane (eager device slices + scatter concat) vs the
    # view plane (one destination-major gather, numpy views, view/memcpy
    # reassembly). Reported: GB/s per arm + the worker store's peak staged
    # bytes (identity-dedup'd, view-aware accounting).
    from datafusion_distributed_tpu.ops.table import concat_tables
    from datafusion_distributed_tpu.plan.physical import MemoryScanExec
    from datafusion_distributed_tpu.runtime.codec import encode_plan
    from datafusion_distributed_tpu.runtime.tracing import table_nbytes
    from datafusion_distributed_tpu.runtime.worker import TaskKey, Worker

    dp_t = arrow_to_table(pa.table({
        "k": rng.integers(0, 1 << 16, n), "v": rng.normal(size=n),
    }))
    dp_bytes = table_nbytes(dp_t)
    N_DEST = 4

    def dp_arm(zero_copy: bool):
        # pin the env override per arm: DFTPU_ZERO_COPY takes priority
        # over task config, and a whole-suite A/B run exporting it must
        # not silently collapse this comparison into view-vs-view
        os.environ["DFTPU_ZERO_COPY"] = "1" if zero_copy else "0"
        w = Worker(url=f"mem://dp-{int(zero_copy)}")
        cfg = {"zero_copy": zero_copy}
        best = float("inf")
        for rep in range(args.repeats + 1):  # rep 0 warms the compile
            # vary the QUERY id per rep: TaskKey's third field is the
            # task INDEX — rep as task index made every rep>0 scan an
            # empty range of this 1-task plan (timing an empty hop)
            key = TaskKey(f"dp{int(zero_copy)}r{rep}", 0, 0)
            plan_obj = encode_plan(
                MemoryScanExec([dp_t], dp_t.schema()), w.table_store
            )
            w.set_plan(key, plan_obj, 1, config=cfg)
            t0 = time.perf_counter()
            parts = [[] for _ in range(N_DEST)]
            for p, piece, _est in w.execute_task_partitions(
                key, ["k"], N_DEST, 0, N_DEST,
                per_dest_capacity=n, chunk_rows=65536,
            ):
                parts[p].append(piece)
            outs = [concat_tables(c, capacity=n) for c in parts if c]
            for o in outs:  # materialize (the consumer scan would)
                np.asarray(o.columns[0].data)
            dt = time.perf_counter() - t0
            if rep:
                best = min(best, dt)
        return best, w.table_store.peak_nbytes

    dp_env_saved = os.environ.get("DFTPU_ZERO_COPY")
    try:
        t_dp_copy, peak_copy = dp_arm(False)
        t_dp_view, peak_view = dp_arm(True)
    finally:
        if dp_env_saved is None:
            os.environ.pop("DFTPU_ZERO_COPY", None)
        else:
            os.environ["DFTPU_ZERO_COPY"] = dp_env_saved
    results.append({
        "bench": "data_plane_copy",
        "ms": round(t_dp_copy * 1e3, 2),
        "gbps": round(dp_bytes / t_dp_copy / 1e9, 3),
        "peak_staged_mb": round(peak_copy / 1e6, 2),
        "fanout": N_DEST,
    })
    print(json.dumps(results[-1]), flush=True)
    results.append({
        "bench": "data_plane_view",
        "ms": round(t_dp_view * 1e3, 2),
        "gbps": round(dp_bytes / t_dp_view / 1e9, 3),
        "peak_staged_mb": round(peak_view / 1e6, 2),
        "fanout": N_DEST,
        "speedup_vs_copy": round(t_dp_copy / max(t_dp_view, 1e-9), 2),
    })
    print(json.dumps(results[-1]), flush=True)

    # ---- shm segment plane ------------------------------------------------
    # The same producer fan-out, but each partition piece crosses a
    # process boundary BY REFERENCE: DFSP-framed into a SegmentPool
    # segment (tmpfs), the consumer opens + decodes it from the
    # producer's pool dir. `copied_mb` is what a socket would have
    # carried — zero here; the unary plane ships the full payload — and
    # is the number tools/bench_compare.py tracks against the copy arm.
    from datafusion_distributed_tpu.runtime.codec import (
        decode_table,
        encode_table,
    )
    from datafusion_distributed_tpu.runtime.shm_plane import SegmentPool

    def dp_shm_arm():
        os.environ["DFTPU_ZERO_COPY"] = "0"
        w = Worker(url="mem://dp-shm")
        pool = SegmentPool()
        pdir = pool.descriptor()["dir"]
        best = float("inf")
        payload_bytes = 0
        try:
            for rep in range(args.repeats + 1):  # rep 0 warms the compile
                key = TaskKey(f"dpshm{rep}", 0, 0)
                plan_obj = encode_plan(
                    MemoryScanExec([dp_t], dp_t.schema()), w.table_store
                )
                w.set_plan(key, plan_obj, 1, config={"zero_copy": False})
                t0 = time.perf_counter()
                parts = [[] for _ in range(N_DEST)]
                payload_bytes = 0
                for p, piece, _est in w.execute_task_partitions(
                    key, ["k"], N_DEST, 0, N_DEST,
                    per_dest_capacity=n, chunk_rows=65536,
                ):
                    # producer side: frame + publish by reference
                    blob = encode_table(piece)
                    payload_bytes += len(blob)
                    name, token = pool.publish(blob)
                    # consumer side: open from the pool dir, decode
                    from datafusion_distributed_tpu.runtime import (
                        shm_plane,
                    )
                    data, _cap = shm_plane.open_segment_at(pdir, name)
                    parts[p].append(decode_table(data))
                    shm_plane.release_at(pdir, name, token)
                outs = [concat_tables(c, capacity=n) for c in parts if c]
                for o in outs:
                    np.asarray(o.columns[0].data)
                dt = time.perf_counter() - t0
                if rep:
                    best = min(best, dt)
        finally:
            pool.shutdown()
            if dp_env_saved is None:
                os.environ.pop("DFTPU_ZERO_COPY", None)
            else:
                os.environ["DFTPU_ZERO_COPY"] = dp_env_saved
        return best, payload_bytes

    t_dp_shm, shm_payload = dp_shm_arm()
    results.append({
        "bench": "data_plane_shm",
        "ms": round(t_dp_shm * 1e3, 2),
        "gbps": round(dp_bytes / t_dp_shm / 1e9, 3),
        "copied_mb": 0.0,  # segments cross by reference, not by socket
        "payload_mb": round(shm_payload / 1e6, 2),
        "fanout": N_DEST,
    })
    print(json.dumps(results[-1]), flush=True)
    # the copy plane's socket bytes for the same hop: the full payload
    results.append({
        "bench": "data_plane_copy_wire",
        "copied_mb": round(shm_payload / 1e6, 2),
        "fanout": N_DEST,
    })
    print(json.dumps(results[-1]), flush=True)

    # ---- transport framing ------------------------------------------------
    from datafusion_distributed_tpu.runtime import transport

    blob = encode_table(t)
    for codec in ("zstd", "none"):
        t0 = time.perf_counter()
        frame = transport.pack_frame({"k": 1}, {"t": blob}, codec=codec)
        _, blobs = transport.unpack_frame(frame)
        dt = time.perf_counter() - t0
        results.append({
            "bench": f"wire_roundtrip_{codec}",
            "ms": round(dt * 1e3, 3),
            "mb_per_s": round(len(blob) / dt / 1e6, 1),
            "ratio": round(len(frame) / max(len(blob), 1), 3),
        })
        print(json.dumps(results[-1]), flush=True)

    # ---- lz4 wire arm -----------------------------------------------------
    # lz4 is an OPTIONAL codec (absent from some images, including this
    # one's default build): when importable, measure the same framed
    # roundtrip; when not, emit a skipped record so bench_compare can
    # tell "not run" from "regressed" across baselines.
    if "lz4" in transport.supported_codecs():
        t0 = time.perf_counter()
        frame = transport.pack_frame({"k": 1}, {"t": blob}, codec="lz4")
        _, blobs = transport.unpack_frame(frame)
        dt = time.perf_counter() - t0
        results.append({
            "bench": "data_plane_wire_lz4",
            "ms": round(dt * 1e3, 3),
            "mb_per_s": round(len(blob) / dt / 1e6, 1),
            "ratio": round(len(frame) / max(len(blob), 1), 3),
        })
    else:
        results.append({
            "bench": "data_plane_wire_lz4",
            "skipped": "lz4 module unavailable on this image",
        })
    print(json.dumps(results[-1]), flush=True)

    # ---- multiway join fusion ---------------------------------------------
    # Three co-shuffled joins on ONE shared key (the q21 shape): every
    # probe re-shuffle between them re-hashes the same column, so the
    # fusion pass (SET distributed.multiway_join) deletes the two
    # interior identity exchanges and runs one fused stage. Fused vs
    # binary-chain wall + measured exchange bytes (stream_metrics sums)
    # on the same 4-worker cluster; results are byte-identical by
    # construction (tests/test_multiway_join.py pins that). The data
    # plane is pinned to the coordinator bulk path ("unary") because
    # only that plane records exchange_bytes — peer/stream bytes never
    # cross the coordinator and would read as zero on both arms.
    mw_n = 1 << 15
    mw_nd = 1 << 12
    mw_rng = np.random.default_rng(11)
    mw_ctx = SessionContext()
    mw_ctx.config.distributed_options["bytes_per_task"] = 1
    mw_ctx.config.distributed_options["broadcast_joins"] = False
    mw_ctx.register_arrow("fact", pa.table({
        "k": mw_rng.integers(0, mw_nd, mw_n), "v": mw_rng.integers(0, 100, mw_n),
    }))
    for i in (1, 2, 3):
        mw_ctx.register_arrow(f"dim{i}", pa.table({
            "k": np.arange(mw_nd), f"a{i}": mw_rng.integers(0, 100, mw_nd),
        }))
    mw_sql = """
    select count(*) as c, sum(a1 + a2 + a3) as s
    from fact
    join dim1 on fact.k = dim1.k
    join dim2 on fact.k = dim2.k
    join dim3 on fact.k = dim3.k
    """

    mw_opts = {"bytes_per_task": 1, "data_plane": "unary"}

    def mw_run(fused: bool):
        mw_ctx.config.distributed_options["multiway_join"] = fused
        cluster = InMemoryCluster(4)
        coord = Coordinator(resolver=cluster, channels=cluster,
                            config_options=dict(mw_opts))
        df = mw_ctx.sql(mw_sql)
        df.collect_coordinated_table(coordinator=coord, num_tasks=4)  # warm
        coord2 = Coordinator(resolver=cluster, channels=cluster,
                             config_options=dict(mw_opts))
        df = mw_ctx.sql(mw_sql)
        t0 = time.perf_counter()
        df.collect_coordinated_table(coordinator=coord2, num_tasks=4)
        dt = time.perf_counter() - t0
        ex_bytes = sum(
            int(sm.get("exchange_bytes", 0))
            for sm in coord2.stream_metrics.values()
        )
        return dt, ex_bytes

    t_chain, bytes_chain = mw_run(fused=False)
    t_fused, bytes_fused = mw_run(fused=True)
    results.append({
        "bench": "multiway_join_chain", "ms": round(t_chain * 1e3, 1),
        "exchange_mb": round(bytes_chain / 1e6, 3), "workers": 4,
    })
    print(json.dumps(results[-1]), flush=True)
    results.append({
        "bench": "multiway_join_fused", "ms": round(t_fused * 1e3, 1),
        "exchange_mb": round(bytes_fused / 1e6, 3),
        "exchange_mb_saved": round((bytes_chain - bytes_fused) / 1e6, 3),
        "speedup_vs_chain": round(t_chain / max(t_fused, 1e-9), 2),
        "workers": 4,
    })
    print(json.dumps(results[-1]), flush=True)

    # ---- global hash aggregation ------------------------------------------
    # High-NDV group-by (every key nearly distinct): the partial+final
    # shape shuffles partial STATES that are barely smaller than the raw
    # rows, so the merge pass is pure overhead. SET distributed.
    # global_hash_agg shuffles the raw rows once and aggregates each
    # disjoint key range in ONE shared table — no merge stage. Exact
    # integer aggregates both ways (tests pin equality).
    ga_n = 1 << 16
    ga_rng = np.random.default_rng(13)
    ga_ctx = SessionContext()
    ga_ctx.config.distributed_options["bytes_per_task"] = 1
    ga_ctx.register_arrow("events", pa.table({
        "id": ga_rng.permutation(ga_n),
        "v": ga_rng.integers(0, 1000, ga_n),
    }))
    ga_sql = ("select id, count(*) as c, sum(v) as s, min(v) as mn, "
              "max(v) as mx from events group by id")

    def ga_run(enabled: bool):
        ga_ctx.config.distributed_options["global_hash_agg"] = enabled
        cluster = InMemoryCluster(4)
        coord = Coordinator(resolver=cluster, channels=cluster,
                            config_options={"bytes_per_task": 1})
        df = ga_ctx.sql(ga_sql)
        df.collect_coordinated_table(coordinator=coord, num_tasks=4)  # warm
        coord2 = Coordinator(resolver=cluster, channels=cluster,
                             config_options={"bytes_per_task": 1})
        df = ga_ctx.sql(ga_sql)
        t0 = time.perf_counter()
        df.collect_coordinated_table(coordinator=coord2, num_tasks=4)
        return time.perf_counter() - t0

    t_merge = ga_run(enabled=False)
    t_global = ga_run(enabled=True)
    results.append({
        "bench": "global_hash_agg_merge", "ms": round(t_merge * 1e3, 1),
        "rows": ga_n, "workers": 4,
    })
    print(json.dumps(results[-1]), flush=True)
    results.append({
        "bench": "global_hash_agg_single", "ms": round(t_global * 1e3, 1),
        "speedup_vs_merge": round(t_merge / max(t_global, 1e-9), 2),
        "rows": ga_n, "workers": 4,
    })
    print(json.dumps(results[-1]), flush=True)

    summary = {
        "metric": "micro_bench_suite",
        "value": len(results),
        "unit": "benches",
        "device": jax.devices()[0].platform,
    }
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
