"""Scale-up TPC-H parity sweep: all 22 queries, every distributed tier,
non-trivial data (default SF 0.5 — ~3M lineitem rows).

The toy-scale matrix (tests/test_tpch_distributed.py, SF 0.002) proves
semantics; this sweep proves the machinery at a scale where capacity
sizing, overflow-retry, range sample sort, and multi-chunk streaming
actually engage — the forced-heavy-distribution intent of the reference's
`tpch_correctness_test.rs:23-80`.

Usage:
    python benchmarks/sweep_sf.py [--sf 0.5] [--tiers static,adaptive,mesh8]
                                  [--queries q1,q3,...] [--out sweep.jsonl]

Each completed (tier, query) appends one JSON line so an interrupted sweep
still reports; compose SWEEP_r05.md from the JSONL afterwards.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
# spec-load the shared host-env helper: a package import HERE would run
# __init__ before DFTPU_COMPILE_CACHE below exists, and __init__ reads
# that env var exactly once
import importlib.util as _ilu

_spec = _ilu.spec_from_file_location(
    "_dftpu_hostenv",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "datafusion_distributed_tpu", "hostenv.py"),
)
_hostenv = _ilu.module_from_spec(_spec)
_spec.loader.exec_module(_hostenv)

# single-core box: give mesh collectives starvation headroom (see helper)
_hostenv.ensure_collective_timeout_flags()

# Persistent compile cache so a resumed/restarted sweep skips recompiling
# the same 66+ stage/mesh programs (mesh q1 reload: 21 s -> 4.4 s).
# Fingerprinted per CPU like tests/conftest.py: XLA:CPU AOT entries embed
# host machine features, and loading them on a different host risks SIGILL.
if "DFTPU_COMPILE_CACHE" not in os.environ:
    os.environ["DFTPU_COMPILE_CACHE"] = os.path.join(
        os.path.expanduser("~"), ".cache",
        f"dftpu_sweep_xla_{_hostenv.cpu_fingerprint()}",
    )
    os.makedirs(os.environ["DFTPU_COMPILE_CACHE"], exist_ok=True)

import jax

jax.config.update("jax_platforms", "cpu")

# Aged-process guard: the cache-WRITE budget now lives in the package
# (__init__.py, behind DFTPU_COMPILE_CACHE_WRITES) so every long-lived
# process is protected; the sweep just opts in before the package import
# below. DFTPU_SWEEP_CACHE_WRITES kept as the sweep-specific alias.
os.environ.setdefault(
    "DFTPU_COMPILE_CACHE_WRITES",
    os.environ.get("DFTPU_SWEEP_CACHE_WRITES", "150"),
)

QUERIES_DIR = "/root/reference/testdata/tpch/queries"


def _frames_match(dist, single) -> str | None:
    """Multiset equality with float tolerance; -> None or a mismatch note."""
    import numpy as np
    import pandas as pd

    if len(dist) != len(single):
        return f"row count {len(dist)} vs {len(single)}"
    if len(single) == 0:
        return None
    ds = dist.sort_values(list(dist.columns)).reset_index(drop=True)
    ss = single.sort_values(list(single.columns)).reset_index(drop=True)
    for col in single.columns:
        a, b = ds[col], ss[col]
        if pd.api.types.is_float_dtype(b) or pd.api.types.is_float_dtype(a):
            try:
                np.testing.assert_allclose(
                    a.to_numpy(dtype=float), b.to_numpy(dtype=float),
                    rtol=5e-4, atol=1e-6,
                )
            except AssertionError:
                return f"float mismatch in {col}"
        else:
            if not (
                a.reset_index(drop=True).astype(str)
                == b.reset_index(drop=True).astype(str)
            ).all():
                return f"value mismatch in {col}"
    return None


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", type=float, default=0.5)
    ap.add_argument("--seed", type=int, default=13)
    ap.add_argument("--tiers", default="static,adaptive,mesh8")
    ap.add_argument("--queries", default=",".join(f"q{i}" for i in range(1, 23)))
    ap.add_argument("--out", default="/root/repo/.sweep_r05.jsonl")
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--tasks", type=int, default=8)
    ap.add_argument("--rlimit-gb", type=float, default=96.0,
                    help="RLIMIT_AS cap so a capacity/compile blowup "
                         "raises MemoryError instead of OOM-killing")
    args = ap.parse_args()

    if args.rlimit_gb > 0:
        import resource

        cap = int(args.rlimit_gb * (1 << 30))
        resource.setrlimit(resource.RLIMIT_AS, (cap, cap))

    # Resumability: one OOM-kill/segfault must only cost the in-flight
    # pair. Completed (tier, query) pairs are skipped on relaunch. A pair
    # with ONE dangling `started` marker gets retried (an interrupt is
    # not a poison pair); TWO dangling markers mean it crashed the
    # process twice — record it as crashed and skip, else a poison pair
    # would crash every relaunch forever.
    done_pairs: set = set()
    started_counts: dict = {}
    if os.path.exists(args.out):
        for line in open(args.out):
            if not line.strip():
                continue
            try:
                r = json.loads(line)
            except ValueError:
                continue
            if r.get("stage") == "started":
                key = (r["tier"], r["query"])
                started_counts[key] = started_counts.get(key, 0) + 1
            elif "tier" in r and r["tier"] != "single":
                done_pairs.add((r["tier"], r["query"]))
                started_counts.pop((r["tier"], r["query"]), None)
    crashed = {k for k, n in started_counts.items() if n >= 2}

    from datafusion_distributed_tpu.data.tpchgen import gen_tpch
    from datafusion_distributed_tpu.runtime.coordinator import (
        AdaptiveCoordinator,
        Coordinator,
        InMemoryCluster,
    )
    from datafusion_distributed_tpu.sql.context import SessionContext

    # the crash being recovered from may have torn the final line; a
    # leading newline isolates it so resumes and the composer stay parseable
    if os.path.exists(args.out):
        with open(args.out, "rb+") as f:
            f.seek(0, 2)
            if f.tell() > 0:
                f.seek(-1, 2)
                if f.read(1) != b"\n":
                    f.write(b"\n")

    def log(**kw):
        kw["ts"] = round(time.time(), 1)
        with open(args.out, "a") as f:
            f.write(json.dumps(kw) + "\n")

    t0 = time.perf_counter()
    tables = gen_tpch(sf=args.sf, seed=args.seed)
    log(stage="datagen", sf=args.sf, seconds=round(time.perf_counter() - t0, 1),
        rows={k: t.num_rows for k, t in tables.items()})

    ctx = SessionContext()
    ctx.config.distributed_options["bytes_per_task"] = 1  # force distribution
    for name, arrow in tables.items():
        ctx.register_arrow(name, arrow)

    tiers = args.tiers.split(",")
    queries = args.queries.split(",")
    single_cache: dict = {}

    def run_single(q, df):
        if q not in single_cache:
            t = time.perf_counter()
            single_cache[q] = df._strip_quals(df.collect_table()).to_pandas()
            log(tier="single", query=q, ok=True,
                seconds=round(time.perf_counter() - t, 2),
                rows=len(single_cache[q]))
        return single_cache[q]

    cluster = InMemoryCluster(args.workers)
    for q in queries:
        path = os.path.join(QUERIES_DIR, f"{q}.sql")
        if not os.path.exists(path):
            continue
        sql = open(path).read()
        for tier in tiers:
            if (tier, q) in done_pairs:
                continue
            if (tier, q) in crashed:
                log(tier=tier, query=q, ok=False,
                    error="crashed previous sweep process (OOM/abort); "
                          "skipped on resume")
                continue
            log(stage="started", tier=tier, query=q)
            t = time.perf_counter()
            try:
                df = ctx.sql(sql)
                single = run_single(q, df)
                extra: dict = {}
                if tier == "mesh8":
                    got = df._strip_quals(
                        df.collect_distributed_table(num_tasks=args.tasks)
                    ).to_pandas()
                elif tier == "static":
                    coord = Coordinator(resolver=cluster, channels=cluster)
                    got = df._strip_quals(df.collect_coordinated_table(
                        coordinator=coord, num_tasks=args.tasks
                    )).to_pandas()
                    extra["streams"] = [
                        {k: v for k, v in m.items()}
                        for m in coord.stream_metrics.values()
                    ]
                elif tier == "adaptive":
                    coord = AdaptiveCoordinator(
                        resolver=cluster, channels=cluster
                    )
                    got = df._strip_quals(df.collect_coordinated_table(
                        coordinator=coord, num_tasks=args.tasks
                    )).to_pandas()
                    extra["task_count_decisions"] = coord.task_count_decisions
                    extra["partial_decisions"] = {
                        str(k): v for k, v in coord.partial_decisions.items()
                    }
                else:
                    raise ValueError(tier)
                mism = _frames_match(got, single)
                retries = getattr(df, "last_retry_count", None)
                log(tier=tier, query=q, ok=mism is None, mismatch=mism,
                    seconds=round(time.perf_counter() - t, 2),
                    rows=len(got), retries=retries, **extra)
            except Exception as e:  # keep sweeping
                log(tier=tier, query=q, ok=False,
                    error=f"{type(e).__name__}: {e}"[:300],
                    seconds=round(time.perf_counter() - t, 2))
        # Aged-process guard #2: compiled executables accumulate per
        # process (jax's jit caches plus this repo's program caches) and
        # after ~2 h of SF0.5 queries the address space exhausts — observed
        # as 32-128 MiB allocation failures on late queries. Dropping every
        # compiled-program cache between queries bounds the growth;
        # recompiles for later queries reload from the persistent cache.
        import datafusion_distributed_tpu as _dftpu

        _dftpu.clear_compile_caches()
    log(stage="done")
    return 0


if __name__ == "__main__":
    sys.exit(main())
