SELECT "SearchPhrase", MIN("URL") AS mn, MIN("Title") AS mt, COUNT(*) AS c,
       COUNT(DISTINCT "UserID") AS u
FROM hits
WHERE "Title" LIKE '%Google%' AND "URL" NOT LIKE '%.google.%'
  AND "SearchPhrase" <> ''
GROUP BY "SearchPhrase" ORDER BY c DESC LIMIT 10
