SELECT "AdvEngineID", COUNT(*) AS c FROM hits WHERE "AdvEngineID" <> 0
GROUP BY "AdvEngineID" ORDER BY c DESC
