SELECT COUNT(*) AS c FROM hits WHERE "AdvEngineID" <> 0
