SELECT "URL", COUNT(*) AS c FROM hits
WHERE "CounterID" = 62 AND "EventDate" >= date '2013-07-01'
  AND "EventDate" <= date '2013-07-31' AND "DontCountHits" = 0
  AND "IsRefresh" = 0 AND "URL" <> ''
GROUP BY "URL" ORDER BY c DESC LIMIT 10
