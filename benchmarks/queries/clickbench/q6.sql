SELECT MIN("EventDate") AS mn, MAX("EventDate") AS mx FROM hits
