SELECT "SearchPhrase", MIN("URL") AS mn, COUNT(*) AS c FROM hits
WHERE "URL" LIKE '%google%' AND "SearchPhrase" <> ''
GROUP BY "SearchPhrase" ORDER BY c DESC LIMIT 10
