SELECT * FROM hits WHERE "URL" LIKE '%google%' ORDER BY "EventTime" LIMIT 10
