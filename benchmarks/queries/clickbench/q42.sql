SELECT date_trunc('minute', to_timestamp_seconds("EventTime")) AS m,
       COUNT(*) AS c
FROM hits
WHERE "CounterID" = 62 AND "EventDate" >= date '2013-07-01'
  AND "EventDate" <= date '2013-07-02' AND "IsRefresh" = 0
  AND "DontCountHits" = 0
GROUP BY m ORDER BY m LIMIT 10
