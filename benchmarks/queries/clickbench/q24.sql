SELECT "SearchPhrase" FROM hits WHERE "SearchPhrase" <> ''
ORDER BY "EventTime" LIMIT 10
