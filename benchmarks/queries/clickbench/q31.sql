SELECT "WatchID", "ClientIP", COUNT(*) AS c, SUM("IsRefresh") AS r,
       AVG("ResolutionWidth") AS a
FROM hits WHERE "SearchPhrase" <> ''
GROUP BY "WatchID", "ClientIP" ORDER BY c DESC LIMIT 10
