SELECT COUNT(*) AS c FROM hits WHERE "URL" LIKE '%google%'
