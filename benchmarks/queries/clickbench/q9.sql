SELECT "RegionID", SUM("AdvEngineID") AS s, COUNT(*) AS c,
       AVG("ResolutionWidth") AS a, COUNT(DISTINCT "UserID") AS u
FROM hits GROUP BY "RegionID" ORDER BY c DESC LIMIT 10
