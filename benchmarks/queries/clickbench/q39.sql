SELECT "TraficSourceID", "SearchEngineID", "AdvEngineID", COUNT(*) AS c
FROM hits WHERE "IsRefresh" = 0
GROUP BY "TraficSourceID", "SearchEngineID", "AdvEngineID"
ORDER BY c DESC LIMIT 10
