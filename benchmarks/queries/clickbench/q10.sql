SELECT "MobilePhoneModel", COUNT(DISTINCT "UserID") AS u FROM hits
WHERE "MobilePhoneModel" <> '' GROUP BY "MobilePhoneModel"
ORDER BY u DESC LIMIT 10
