SELECT "MobilePhone", "MobilePhoneModel", COUNT(DISTINCT "UserID") AS u
FROM hits WHERE "MobilePhoneModel" <> ''
GROUP BY "MobilePhone", "MobilePhoneModel" ORDER BY u DESC LIMIT 10
