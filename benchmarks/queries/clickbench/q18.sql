SELECT "UserID", extract(minute FROM to_timestamp_seconds("EventTime")) AS m,
       "SearchPhrase", COUNT(*) AS c
FROM hits GROUP BY "UserID", m, "SearchPhrase" ORDER BY c DESC LIMIT 10
