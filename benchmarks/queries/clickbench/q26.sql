SELECT "SearchPhrase" FROM hits WHERE "SearchPhrase" <> ''
ORDER BY "EventTime", "SearchPhrase" LIMIT 10
