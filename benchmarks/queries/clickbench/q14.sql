SELECT "SearchEngineID", "SearchPhrase", COUNT(*) AS c FROM hits
WHERE "SearchPhrase" <> '' GROUP BY "SearchEngineID", "SearchPhrase"
ORDER BY c DESC LIMIT 10
