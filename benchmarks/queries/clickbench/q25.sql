SELECT "SearchPhrase" FROM hits WHERE "SearchPhrase" <> ''
ORDER BY "SearchPhrase" LIMIT 10
