SELECT regexp_replace("Referer", '^https?://([^/]+)/.*$', '\1') AS k,
       AVG(length("Referer")) AS l, COUNT(*) AS c, MIN("Referer") AS mn
FROM hits WHERE "Referer" <> ''
GROUP BY k HAVING COUNT(*) > 10 ORDER BY l DESC LIMIT 25
