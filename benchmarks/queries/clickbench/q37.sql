SELECT "Title", COUNT(*) AS c FROM hits
WHERE "CounterID" = 62 AND "EventDate" >= date '2013-07-01'
  AND "EventDate" <= date '2013-07-31' AND "DontCountHits" = 0
  AND "IsRefresh" = 0 AND "Title" <> ''
GROUP BY "Title" ORDER BY c DESC LIMIT 10
