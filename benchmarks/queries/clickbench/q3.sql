SELECT AVG("UserID") AS a FROM hits
