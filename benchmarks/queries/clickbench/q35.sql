SELECT "ClientIP", "ClientIP" - 1 AS c1, "ClientIP" - 2 AS c2,
       "ClientIP" - 3 AS c3, COUNT(*) AS c
FROM hits GROUP BY "ClientIP" ORDER BY c DESC LIMIT 10
