SELECT "UserID", "SearchPhrase", COUNT(*) AS c FROM hits
GROUP BY "UserID", "SearchPhrase" LIMIT 10
