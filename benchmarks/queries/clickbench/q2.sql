SELECT SUM("AdvEngineID") AS s, COUNT(*) AS c, AVG("ResolutionWidth") AS a FROM hits
