SELECT "UserID", "SearchPhrase", COUNT(*) AS c FROM hits
GROUP BY "UserID", "SearchPhrase" ORDER BY c DESC LIMIT 10
