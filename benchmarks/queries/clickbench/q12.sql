SELECT "SearchPhrase", COUNT(*) AS c FROM hits WHERE "SearchPhrase" <> ''
GROUP BY "SearchPhrase" ORDER BY c DESC LIMIT 10
