SELECT "URLHash", "EventDate", COUNT(*) AS c FROM hits
WHERE "IsRefresh" = 0 AND "TraficSourceID" IN (-1, 6)
  AND "RefererHash" = 123456
GROUP BY "URLHash", "EventDate" ORDER BY c DESC LIMIT 10
