SELECT SUM("ResolutionWidth") AS s0, SUM("ResolutionWidth" + 1) AS s1,
       SUM("ResolutionWidth" + 2) AS s2, SUM("ResolutionWidth" + 3) AS s3,
       SUM("ResolutionWidth" + 4) AS s4
FROM hits
