SELECT "WindowClientWidth", "WindowClientHeight", COUNT(*) AS c FROM hits
WHERE "IsRefresh" = 0 AND "DontCountHits" = 0 AND "URLHash" = 123456
GROUP BY "WindowClientWidth", "WindowClientHeight"
ORDER BY c DESC LIMIT 10
