select l_returnflag, l_linestatus,
       sum(l_quantity) as sum_qty,
       sum(l_extendedprice) as sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
       avg(l_quantity) as avg_qty,
       avg(l_extendedprice) as avg_price,
       avg(l_discount) as avg_disc,
       count(*) as count_order
from lineitem
where l_shipdate <= date '1998-09-02'
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus
