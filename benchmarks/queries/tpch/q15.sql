with revenue as (
  select l_suppkey as supplier_no,
         sum(l_extendedprice * (1 - l_discount)) as total_revenue
  from lineitem
  where l_shipdate >= date '1996-01-01'
    and l_shipdate < date '1996-04-01'
  group by l_suppkey
)
select s_suppkey, s_name, s_address, s_phone, total_revenue
from supplier, revenue
where s_suppkey = supplier_no
  and total_revenue = (select max(total_revenue) from revenue)
order by s_suppkey
