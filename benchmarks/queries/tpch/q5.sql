select n_name, sum(l_extendedprice * (1 - l_discount)) as revenue
from customer, orders, lineitem, supplier, nation, region
where c_custkey = o_custkey
  and l_orderkey = o_orderkey
  and l_suppkey = s_suppkey
  and c_nationkey = s_nationkey
  and s_nationkey = n_nationkey
  and n_regionkey = r_regionkey
  and r_name = 'ASIA'
  and o_orderdate >= date '1994-01-01'
  and o_orderdate < date '1995-01-01'
group by n_name
order by revenue desc
