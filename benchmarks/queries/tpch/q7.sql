select supp_nation, cust_nation, l_year, sum(volume) as revenue
from (
  select n1.n_name as supp_nation, n2.n_name as cust_nation,
         extract(year from l_shipdate) as l_year,
         l_extendedprice * (1 - l_discount) as volume
  from supplier, lineitem, orders, customer, nation n1, nation n2
  where s_suppkey = l_suppkey
    and o_orderkey = l_orderkey
    and c_custkey = o_custkey
    and s_nationkey = n1.n_nationkey
    and c_nationkey = n2.n_nationkey
    and ((n1.n_name = 'FRANCE' and n2.n_name = 'GERMANY')
         or (n1.n_name = 'GERMANY' and n2.n_name = 'FRANCE'))
    and l_shipdate between date '1995-01-01' and date '1996-12-31'
) shipping
group by supp_nation, cust_nation, l_year
order by supp_nation, cust_nation, l_year
