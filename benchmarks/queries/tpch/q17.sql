select sum(l_extendedprice) / 7.0 as avg_yearly
from lineitem, part
where p_partkey = l_partkey
  and p_brand = 'Brand#23'
  and p_container = 'MED BOX'
  and l_quantity < (
    select 0.2 * avg(l_quantity) from lineitem
    where l_partkey = p_partkey
  )
