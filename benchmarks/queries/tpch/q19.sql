select sum(l_extendedprice * (1 - l_discount)) as revenue
from lineitem, part
where (p_partkey = l_partkey
       and p_brand = 'Brand#12'
       and p_container in ('SM CASE', 'SM BOX', 'SM PACK', 'SM PKG')
       and l_quantity >= 1 and l_quantity <= 11
       and p_size between 1 and 5
       and l_shipmode in ('AIR', 'AIR REG')
       and l_shipinstruct = 'DELIVER IN PERSON')
   or (p_partkey = l_partkey
       and p_brand = 'Brand#23'
       and p_container in ('MED BAG', 'MED BOX', 'MED PKG', 'MED PACK')
       and l_quantity >= 10 and l_quantity <= 20
       and p_size between 1 and 10
       and l_shipmode in ('AIR', 'AIR REG')
       and l_shipinstruct = 'DELIVER IN PERSON')
   or (p_partkey = l_partkey
       and p_brand = 'Brand#34'
       and p_container in ('LG CASE', 'LG BOX', 'LG PACK', 'LG PKG')
       and l_quantity >= 20 and l_quantity <= 30
       and p_size between 1 and 15
       and l_shipmode in ('AIR', 'AIR REG')
       and l_shipinstruct = 'DELIVER IN PERSON')
