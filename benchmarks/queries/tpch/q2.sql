select s_acctbal, s_name, n_name, p_partkey, p_mfgr, s_address, s_phone,
       s_comment
from part, supplier, partsupp, nation, region
where p_partkey = ps_partkey
  and s_suppkey = ps_suppkey
  and p_size = 15
  and p_type like '%BRASS'
  and s_nationkey = n_nationkey
  and n_regionkey = r_regionkey
  and r_name = 'EUROPE'
  and ps_supplycost = (
    select min(ps_supplycost)
    from partsupp, supplier, nation, region
    where p_partkey = ps_partkey
      and s_suppkey = ps_suppkey
      and s_nationkey = n_nationkey
      and n_regionkey = r_regionkey
      and r_name = 'EUROPE'
  )
order by s_acctbal desc, n_name, s_name, p_partkey
limit 100
