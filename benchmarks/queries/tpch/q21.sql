select s_name, count(*) as numwait
from supplier, lineitem l1, orders, nation
where s_suppkey = l1.l_suppkey
  and o_orderkey = l1.l_orderkey
  and o_orderstatus = 'F'
  and l1.l_receiptdate > l1.l_commitdate
  and exists (
    select * from lineitem l2
    where l2.l_orderkey = l1.l_orderkey
      and l2.l_suppkey <> l1.l_suppkey
  )
  and not exists (
    select * from lineitem l3
    where l3.l_orderkey = l1.l_orderkey
      and l3.l_suppkey <> l1.l_suppkey
      and l3.l_receiptdate > l3.l_commitdate
  )
  and s_nationkey = n_nationkey
  and n_name = 'SAUDI ARABIA'
group by s_name
order by numwait desc, s_name
limit 100
