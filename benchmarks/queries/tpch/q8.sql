select o_year,
       sum(case when nation = 'BRAZIL' then volume else 0 end)
         / sum(volume) as mkt_share
from (
  select extract(year from o_orderdate) as o_year,
         l_extendedprice * (1 - l_discount) as volume,
         n2.n_name as nation
  from part, supplier, lineitem, orders, customer,
       nation n1, nation n2, region
  where p_partkey = l_partkey
    and s_suppkey = l_suppkey
    and l_orderkey = o_orderkey
    and o_custkey = c_custkey
    and c_nationkey = n1.n_nationkey
    and n1.n_regionkey = r_regionkey
    and r_name = 'AMERICA'
    and s_nationkey = n2.n_nationkey
    and o_orderdate between date '1995-01-01' and date '1996-12-31'
    and p_type = 'ECONOMY ANODIZED STEEL'
) all_nations
group by o_year
order by o_year
