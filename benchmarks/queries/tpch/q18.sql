select c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
       sum(l_quantity)
from customer, orders, lineitem
where o_orderkey in (
    select l_orderkey from lineitem
    group by l_orderkey having sum(l_quantity) > 300
  )
  and c_custkey = o_custkey
  and o_orderkey = l_orderkey
group by c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
order by o_totalprice desc, o_orderdate
limit 100
