select c_custkey, c_name,
       sum(l_extendedprice * (1 - l_discount)) as revenue,
       c_acctbal, n_name, c_address, c_phone, c_comment
from customer, orders, lineitem, nation
where c_custkey = o_custkey
  and l_orderkey = o_orderkey
  and o_orderdate >= date '1993-10-01'
  and o_orderdate < date '1994-01-01'
  and l_returnflag = 'R'
  and c_nationkey = n_nationkey
group by c_custkey, c_name, c_acctbal, c_phone, n_name, c_address,
         c_comment
order by revenue desc
limit 20
