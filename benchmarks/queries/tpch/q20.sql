select s_name, s_address
from supplier, nation
where s_suppkey in (
    select ps_suppkey from partsupp
    where ps_partkey in (
        select p_partkey from part where p_name like 'forest%'
      )
      and ps_availqty > (
        select 0.5 * sum(l_quantity) from lineitem
        where l_partkey = ps_partkey
          and l_suppkey = ps_suppkey
          and l_shipdate >= date '1994-01-01'
          and l_shipdate < date '1995-01-01'
      )
  )
  and s_nationkey = n_nationkey
  and n_name = 'CANADA'
order by s_name
