select c_count, count(*) as custdist
from (
  select c_custkey, count(o_orderkey) as c_count
  from customer left join orders
    on c_custkey = o_custkey
       and o_comment not like '%special%requests%'
  group by c_custkey
) c_orders
group by c_count
order by custdist desc, c_count desc
