select 100.00 * sum(case when p_type like 'PROMO%'
                         then l_extendedprice * (1 - l_discount)
                         else 0 end)
       / sum(l_extendedprice * (1 - l_discount)) as promo_revenue
from lineitem, part
where l_partkey = p_partkey
  and l_shipdate >= date '1995-09-01'
  and l_shipdate < date '1995-10-01'
