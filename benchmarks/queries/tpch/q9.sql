select nation, o_year, sum(amount) as sum_profit
from (
  select n_name as nation,
         extract(year from o_orderdate) as o_year,
         l_extendedprice * (1 - l_discount)
           - ps_supplycost * l_quantity as amount
  from part, supplier, lineitem, partsupp, orders, nation
  where s_suppkey = l_suppkey
    and ps_suppkey = l_suppkey
    and ps_partkey = l_partkey
    and p_partkey = l_partkey
    and o_orderkey = l_orderkey
    and s_nationkey = n_nationkey
    and p_name like '%green%'
) profit
group by nation, o_year
order by nation, o_year desc
