select cntrycode, count(*) as numcust, sum(c_acctbal) as totacctbal
from (
  select substring(c_phone from 1 for 2) as cntrycode, c_acctbal
  from customer
  where substring(c_phone from 1 for 2) in
        ('13', '31', '23', '29', '30', '18', '17')
    and c_acctbal > (
      select avg(c_acctbal) from customer
      where c_acctbal > 0.00
        and substring(c_phone from 1 for 2) in
            ('13', '31', '23', '29', '30', '18', '17')
    )
    and not exists (
      select * from orders where o_custkey = c_custkey
    )
) custsale
group by cntrycode
order by cntrycode
