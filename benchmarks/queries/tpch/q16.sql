select p_brand, p_type, p_size,
       count(distinct ps_suppkey) as supplier_cnt
from partsupp, part
where p_partkey = ps_partkey
  and p_brand <> 'Brand#45'
  and p_type not like 'MEDIUM POLISHED%'
  and p_size in (49, 14, 23, 45, 19, 3, 36, 9)
  and ps_suppkey not in (
    select s_suppkey from supplier
    where s_comment like '%Customer%Complaints%'
  )
group by p_brand, p_type, p_size
order by supplier_cnt desc, p_brand, p_type, p_size
