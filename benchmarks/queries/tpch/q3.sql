select l_orderkey,
       sum(l_extendedprice * (1 - l_discount)) as revenue,
       o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING'
  and c_custkey = o_custkey
  and l_orderkey = o_orderkey
  and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate
limit 10
