select o_orderpriority, count(*) as order_count
from orders
where o_orderdate >= date '1993-07-01'
  and o_orderdate < date '1993-10-01'
  and exists (
    select * from lineitem
    where l_orderkey = o_orderkey and l_commitdate < l_receiptdate
  )
group by o_orderpriority
order by o_orderpriority
