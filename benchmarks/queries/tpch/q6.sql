select sum(l_extendedprice * l_discount) as revenue
from lineitem
where l_shipdate >= date '1994-01-01'
  and l_shipdate < date '1995-01-01'
  and l_discount between 0.05 and 0.07
  and l_quantity < 24
