select ps_partkey, sum(ps_supplycost * ps_availqty) as value
from partsupp, supplier, nation
where ps_suppkey = s_suppkey
  and s_nationkey = n_nationkey
  and n_name = 'GERMANY'
group by ps_partkey
having sum(ps_supplycost * ps_availqty) > (
  select sum(ps_supplycost * ps_availqty) * 0.0001
  from partsupp, supplier, nation
  where ps_suppkey = s_suppkey
    and s_nationkey = n_nationkey
    and n_name = 'GERMANY'
)
order by value desc
