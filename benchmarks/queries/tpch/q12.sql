select l_shipmode,
       sum(case when o_orderpriority = '1-URGENT'
                  or o_orderpriority = '2-HIGH'
                then 1 else 0 end) as high_line_count,
       sum(case when o_orderpriority <> '1-URGENT'
                 and o_orderpriority <> '2-HIGH'
                then 1 else 0 end) as low_line_count
from orders, lineitem
where o_orderkey = l_orderkey
  and l_shipmode in ('MAIL', 'SHIP')
  and l_commitdate < l_receiptdate
  and l_shipdate < l_commitdate
  and l_receiptdate >= date '1994-01-01'
  and l_receiptdate < date '1995-01-01'
group by l_shipmode
order by l_shipmode
