#!/usr/bin/env python
"""Benchmark entry point (driver-run on real TPU hardware).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Architecture: a PARENT process that never touches JAX orchestrates a
disposable CHILD process that does device init + query execution. The
axon TPU tunnel can block indefinitely inside PJRT client init (observed
rounds 1-2, and the tunnel is single-client: a killed init wedges the
lease for minutes). A hung child is killed (SIGINT first so PJRT can
release the claim, then SIGKILL) and retried with backoff; per-query
results stream from child to parent through a JSONL event file, so a
late wedge still reports every completed query.

Per-query detail (stderr + BENCH_DETAIL.json): wall seconds, input bytes
touched, achieved GB/s, and % of the chip's HBM roofline — so "fast" is
judgeable against hardware limits, not just the reference's wall-clock.

Metric: TPC-H total wall-clock (sum of per-query best-of-2 latencies) at
the given scale factor. Baseline (BASELINE.md): the reference engine's
TPC-H SF10 total on a 12-node CPU cluster is 10 s. vs_baseline scales
the nearest published reference point to this SF per-query (see
_BASELINES).

Env knobs:
  BENCH_SUITE    tpch (default) | tpcds | clickbench
  BENCH_SF       scale factor (default 0.05)
  BENCH_QUERIES  comma list (default: the suite's full set, first-light
                 queries ordered first)
  BENCH_TASKS    mesh size for distributed mode (default 1 = single chip)
  BENCH_BUDGET_S wall-clock budget in seconds (default 420)
  BENCH_HBM_GBPS override the HBM roofline (GB/s) if device_kind unknown
"""

import json
import os
import signal
import subprocess
import sys
import time

_EVENTS = os.environ.get("BENCH_EVENTS_FILE", "/root/repo/.bench_events.jsonl")
_DETAIL = "/root/repo/BENCH_DETAIL.json"

# Reference totals (README.md benchmarks table, BASELINE.md) for
# vs_baseline: per suite, the PUBLISHED (sf, total_seconds, query_count)
# points — tpch SF1 = 7 s / SF10 = 10 s / SF100 = 42 s over 19 q;
# tpcds SF1 = 29 s over 67 q. The comparison picks the nearest published
# SF (log distance) and scales linearly from there PER QUERY: the
# reference's fixed per-query overhead does not shrink with data size.
_BASELINES = {
    "tpch": [(1.0, 7.0, 22), (10.0, 10.0, 22), (100.0, 42.0, 19)],
    "tpcds": [(1.0, 29.0, 67)],
}

_SUITES = {
    "tpch": ("/root/reference/testdata/tpch/queries",
             [f"q{i}" for i in range(1, 23)], ["q1", "q6"]),
    "tpcds": ("/root/reference/testdata/tpcds/queries",
              [f"q{i}" for i in range(1, 100)], ["q3", "q7"]),
    "clickbench": ("/root/reference/testdata/clickbench/queries",
                   [f"q{i}" for i in range(0, 43)], ["q0", "q1"]),
}

# Known HBM bandwidth rooflines by TPU device_kind substring, GB/s.
# (Public spec sheets; used only for %-of-roofline reporting.)
_HBM_GBPS = [
    ("v6e", 1640.0), ("v6", 1640.0), ("v5p", 2765.0),
    ("v5 lite", 819.0), ("v5e", 819.0), ("v5litepod", 819.0),
    ("v4", 1228.0), ("v3", 900.0), ("v2", 700.0),
]


def _vs_baseline(suite: str, sf: float, per_query: dict, total: float) -> float:
    points = _BASELINES.get(suite)
    if not (points and total > 0 and per_query):
        return 0.0
    import math

    base_sf, base_total, base_q = min(
        points, key=lambda p: abs(math.log(sf / p[0]))
    )
    per_q = base_total / base_q
    return (per_q * len(per_query) * (sf / base_sf)) / total


def _report(suite: str, sf: float, per_query: dict, total: float,
            suffix: str = "") -> None:
    print(
        json.dumps(
            {
                "metric": f"{suite}_sf{sf}_total_wall_clock_"
                          f"{len(per_query)}q{suffix}",
                "value": round(total, 4) if per_query else -1,
                "unit": "seconds",
                "vs_baseline": round(_vs_baseline(suite, sf, per_query, total), 4),
            }
        ),
        flush=True,
    )


# --------------------------------------------------------------------------
# Child: owns JAX. Streams events (one JSON object per line) to _EVENTS.
# --------------------------------------------------------------------------

def _emit(fh, **kw):
    kw["ts"] = round(time.time(), 3)
    fh.write(json.dumps(kw) + "\n")
    fh.flush()
    os.fsync(fh.fileno())


def _child_main() -> None:
    suite = os.environ.get("BENCH_SUITE", "tpch").lower()
    sf = float(os.environ.get("BENCH_SF", "0.05"))
    tasks = int(os.environ.get("BENCH_TASKS", "1"))
    deadline = float(os.environ["BENCH_DEADLINE_TS"])
    qdir, default_queries, _first = _SUITES[suite]
    queries = os.environ.get("BENCH_QUERIES", "")
    qlist = ([q.strip() for q in queries.split(",") if q.strip()]
             if queries else default_queries)

    fh = open(_EVENTS, "a")
    # a predecessor child may have been SIGKILLed mid-write, leaving a torn
    # line; a leading newline isolates it (blank lines are skipped on read)
    fh.write("\n")
    os.environ.setdefault("DFTPU_COMPILE_CACHE", "/root/repo/.xla_cache")

    import jax  # noqa: E402

    # the axon plugin force-selects jax_platforms="axon,cpu" at registration
    # time, overriding the env var; pin it back when a platform is requested
    # (BENCH_PLATFORM=cpu for harness self-tests)
    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    t0 = time.perf_counter()
    devs = jax.devices()
    kind = getattr(devs[0], "device_kind", str(devs[0]))
    _emit(fh, event="init", init_s=round(time.perf_counter() - t0, 2),
          devices=len(devs), device_kind=str(kind))

    hbm_gbps = None
    if os.environ.get("BENCH_HBM_GBPS"):
        hbm_gbps = float(os.environ["BENCH_HBM_GBPS"])
    else:
        low = str(kind).lower()
        for sub, bw in _HBM_GBPS:
            if sub in low:
                hbm_gbps = bw
                break

    import jax.numpy as jnp  # noqa: E402

    from datafusion_distributed_tpu.plan.physical import MemoryScanExec
    from datafusion_distributed_tpu.sql.context import SessionContext

    def sync_fetch(table):
        """One device->host scalar fetch that depends on the tail of the
        computation. On this backend block_until_ready does NOT block;
        only a fetch truly synchronizes, and fetching full (padded)
        buffers over the tunnel would swamp the measurement."""
        acc = jnp.asarray(table.num_rows, dtype=jnp.float32)
        for c in table.columns:
            if c.data.size:
                acc = acc + c.data.ravel()[0].astype(jnp.float32)
        return float(acc)

    def plan_input_bytes(plan) -> int:
        total = 0
        for leaf in plan.collect(lambda p: isinstance(p, MemoryScanExec)):
            for t in leaf.tasks:
                for c in t.columns:
                    total += int(c.data.nbytes)
                    if c.validity is not None:
                        total += int(c.validity.nbytes)
        return total

    t0 = time.perf_counter()
    ctx = SessionContext()
    if suite == "tpch":
        from datafusion_distributed_tpu.data.tpchgen import register_tpch

        register_tpch(ctx, sf=sf, seed=0)
    elif suite == "tpcds":
        from datafusion_distributed_tpu.data.tpcdsgen import register_tpcds

        register_tpcds(ctx, sf=sf, seed=0)
    else:
        from datafusion_distributed_tpu.data.clickbenchgen import (
            register_clickbench,
        )

        register_clickbench(ctx, rows=max(int(100_000 * sf / 0.05), 1000),
                            seed=0)
    # force the host->device transfer into the registration measurement:
    # touch one element of every registered column
    reg_sync = 0.0
    for name, t in ctx.catalog.tables.items():
        for c in t.columns:
            if c.data.size:
                reg_sync += float(c.data.ravel()[0])
    _emit(fh, event="registered", secs=round(time.perf_counter() - t0, 2),
          tables=len(ctx.catalog.tables))

    for q in qlist:
        now = time.time()
        if now > deadline - 10:
            _emit(fh, event="budget_stop", remaining=q)
            break
        path = os.path.join(qdir, f"{q}.sql")
        if not os.path.exists(path):
            _emit(fh, event="query_skipped", q=q, reason="no such file")
            continue
        sql = open(path).read()
        try:
            df = ctx.sql(sql)
            runs = []
            best = float("inf")
            # warm-up run compiles; second run measures steady-state
            # latency (the reference reports p50 of repeat runs)
            for _attempt in range(2):
                t0 = time.perf_counter()
                if tasks > 1:
                    tbl = df.collect_distributed_table(num_tasks=tasks)
                else:
                    tbl = df.collect_table()
                sync_fetch(tbl)
                dt = time.perf_counter() - t0
                runs.append(round(dt, 4))
                best = min(best, dt)
                if time.time() > deadline - 5:
                    break
            try:
                # after collect the memoized plan reflects any overflow-
                # widened replan; planning here (vs before the timed runs)
                # also keeps plan-time subquery overflows inside
                # collect_table's retry loop
                bytes_in = plan_input_bytes(df.physical_plan())
            except Exception:
                bytes_in = 0
            gbps = bytes_in / best / 1e9 if best > 0 else 0.0
            ev = {
                "event": "query", "q": q, "secs": round(best, 4),
                "runs": runs, "bytes_in": bytes_in,
                "gbps": round(gbps, 2),
            }
            if hbm_gbps:
                ev["pct_hbm_roofline"] = round(100.0 * gbps / hbm_gbps, 2)
            _emit(fh, **ev)
        except Exception as e:  # a failing query must not eat the report
            _emit(fh, event="query_failed", q=q,
                  error=f"{type(e).__name__}: {e}"[:300])
    _emit(fh, event="done", hbm_gbps=hbm_gbps)


# --------------------------------------------------------------------------
# Parent: no JAX. Spawns/monitors children, aggregates, reports.
# --------------------------------------------------------------------------

_INIT_STALL_S = 210.0   # no init event -> child is wedged in PJRT init
_QUERY_STALL_S = 300.0  # no progress mid-run (compiles can take ~40s)
_BACKOFFS = [45.0, 90.0]  # tunnel lease needs time to expire after a kill


def _read_events(path: str, offset: int):
    """-> (events, new_offset); tolerant of a torn final line."""
    try:
        with open(path) as f:
            f.seek(offset)
            data = f.read()
    except FileNotFoundError:
        return [], offset
    events = []
    consumed = 0
    for line in data.splitlines(keepends=True):
        if not line.endswith("\n"):
            break
        consumed += len(line)
        line = line.strip()
        if line:
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    return events, offset + consumed


def _kill_child(proc: subprocess.Popen) -> None:
    """SIGINT first: a KeyboardInterrupt lets the PJRT client release the
    single-client tunnel claim; SIGKILL mid-init wedges it for minutes."""
    if proc.poll() is not None:
        return
    try:
        proc.send_signal(signal.SIGINT)
        proc.wait(timeout=15)
    except (subprocess.TimeoutExpired, ProcessLookupError):
        try:
            proc.kill()
            proc.wait(timeout=10)
        except Exception:
            pass


def main() -> None:
    if os.environ.get("BENCH_CHILD") == "1":
        _child_main()
        return

    suite = os.environ.get("BENCH_SUITE", "tpch").lower()
    if suite not in _SUITES:
        print(json.dumps({
            "metric": f"invalid_suite_{suite}", "value": -1,
            "unit": "seconds", "vs_baseline": 0.0,
        }), flush=True)
        sys.exit(2)
    sf = float(os.environ.get("BENCH_SF", "0.05"))
    budget = float(os.environ.get("BENCH_BUDGET_S", "420"))
    started = time.time()
    deadline = started + budget

    _qdir, default_queries, first_light = _SUITES[suite]
    if os.environ.get("BENCH_QUERIES"):
        qlist = [q.strip() for q in os.environ["BENCH_QUERIES"].split(",")
                 if q.strip()]
    else:
        # first-light queries run first: a late wedge still yields numbers
        qlist = first_light + [q for q in default_queries
                               if q not in first_light]

    # the parent's own last line of defense: always print the one JSON line
    state = {"per_query": {}, "failed": {}, "meta": {}}

    def final_report(suffix=""):
        total = sum(state["per_query"].values())
        _report(suite, sf, state["per_query"], total, suffix=suffix)
        detail = {
            "suite": suite, "sf": sf, "per_query_s": state["per_query"],
            "failed": state["failed"], "meta": state["meta"],
            "total_s": round(total, 4),
        }
        try:
            with open(_DETAIL, "w") as f:
                json.dump(detail, f, indent=1)
        except OSError:
            pass
        print(json.dumps(detail), file=sys.stderr, flush=True)

    import threading

    def watchdog():
        final_report(suffix="_watchdog")
        os._exit(3)

    wd = threading.Timer(budget + 90.0, watchdog)
    wd.daemon = True
    wd.start()

    try:
        os.unlink(_EVENTS)
    except FileNotFoundError:
        pass

    attempt = 0
    offset = 0
    while time.time() < deadline - 30:
        remaining = [q for q in qlist
                     if q not in state["per_query"]
                     and q not in state["failed"]]
        if not remaining:
            break
        env = dict(os.environ)
        env["BENCH_CHILD"] = "1"
        env["BENCH_QUERIES"] = ",".join(remaining)
        env["BENCH_DEADLINE_TS"] = str(deadline)
        env.setdefault("JAX_PLATFORMS", "axon")
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            env=env, stdout=sys.stderr, stderr=sys.stderr,
            start_new_session=True,
        )
        print(f"bench child attempt {attempt}: pid {proc.pid}, "
              f"{len(remaining)} queries", file=sys.stderr, flush=True)
        saw_init = False
        child_done = False
        last_progress = time.time()
        while True:
            events, offset = _read_events(_EVENTS, offset)
            for ev in events:
                last_progress = time.time()
                kind = ev.get("event")
                if kind == "init":
                    saw_init = True
                    state["meta"].update(
                        {k: ev[k] for k in
                         ("init_s", "devices", "device_kind") if k in ev})
                elif kind == "registered":
                    state["meta"]["register_s"] = ev.get("secs")
                elif kind == "query":
                    state["per_query"][ev["q"]] = ev["secs"]
                    state["meta"].setdefault("queries", {})[ev["q"]] = {
                        k: ev[k] for k in
                        ("runs", "bytes_in", "gbps", "pct_hbm_roofline")
                        if k in ev}
                    print(f"  {ev['q']}: {ev['secs']}s "
                          f"({ev.get('gbps', '?')} GB/s, "
                          f"{ev.get('pct_hbm_roofline', '?')}% roofline)",
                          file=sys.stderr, flush=True)
                elif kind == "query_failed":
                    state["failed"][ev["q"]] = ev.get("error", "")
                elif kind == "done":
                    state["meta"]["hbm_gbps"] = ev.get("hbm_gbps")
                    child_done = True
            if child_done:
                # all results are in hand; don't let a wedged PJRT teardown
                # burn the remaining budget waiting for a clean exit
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    _kill_child(proc)
                break
            if proc.poll() is not None:
                # child died without a done event (crash / OOM): drain any
                # events written after the last poll before moving on
                events, offset = _read_events(_EVENTS, offset)
                for ev in events:
                    if ev.get("event") == "query":
                        state["per_query"][ev["q"]] = ev["secs"]
                    elif ev.get("event") == "query_failed":
                        state["failed"][ev["q"]] = ev.get("error", "")
                    elif ev.get("event") == "done":
                        child_done = True
                print(f"bench child exited rc={proc.returncode}",
                      file=sys.stderr, flush=True)
                break
            stall = _QUERY_STALL_S if saw_init else _INIT_STALL_S
            if time.time() - last_progress > stall:
                print(f"bench child stalled ({'run' if saw_init else 'init'}"
                      f" {stall}s); killing", file=sys.stderr, flush=True)
                _kill_child(proc)
                break
            if time.time() > deadline - 5:
                _kill_child(proc)
                break
            time.sleep(2.0)
        if child_done:
            break
        backoff = _BACKOFFS[min(attempt, len(_BACKOFFS) - 1)]
        attempt += 1
        if attempt > 3 or time.time() + backoff > deadline - 60:
            break
        print(f"backoff {backoff}s before retry", file=sys.stderr, flush=True)
        time.sleep(backoff)

    wd.cancel()
    final_report()
    if not state["per_query"]:
        sys.exit(4 if not state["meta"].get("init_s") else 2)


if __name__ == "__main__":
    main()
