#!/usr/bin/env python
"""Benchmark entry point (driver-run on real TPU hardware).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: TPC-H total wall-clock (sum of per-query best-of-2 latencies) at the
given scale factor, on the available accelerator. Baseline (BASELINE.md): the
reference engine's TPC-H SF10 total on a 12-node CPU cluster is 10 s.
vs_baseline = (10 s * SF/10) / our_total — i.e. the baseline linearly
extrapolated to the benchmarked scale factor. At SF=10 this is the true
ratio (>1.0 = faster than the reference cluster); at other SFs it is an
approximation that ignores the reference's fixed per-query overhead, so
treat it as a trend indicator until SF10 runs land.

Env knobs:
  BENCH_SUITE    tpch (default) | tpcds | clickbench
  BENCH_SF       scale factor (default 0.05; raise on real HBM); for
                 clickbench this scales the 100k-row default (SF 1 = 2M rows)
  BENCH_QUERIES  comma list (default: the suite's full set)
  BENCH_TASKS    mesh size for distributed mode (default 1 = single chip)
  BENCH_BUDGET_S wall-clock budget in seconds (default 420). XLA compilation
                 of 22 distinct query programs dominates cold runs; the
                 harness stops admitting queries near the budget and always
                 prints its JSON line with however many completed (the query
                 count is part of the metric name).
"""

import json
import os
import sys
import time


_PROGRESS = {"per_query": {}, "total": 0.0}  # shared with the watchdog


# Reference totals (README.md benchmarks table, BASELINE.md) for
# vs_baseline: per suite, the PUBLISHED (sf, total_seconds, query_count)
# points — tpch SF1 = 7 s / SF10 = 10 s / SF100 = 42 s over 19 q;
# tpcds SF1 = 29 s over 67 q; clickbench has no published number ->
# vs_baseline 0.0. The comparison picks the nearest published SF (log
# distance) and scales linearly from there, PER QUERY: linear-from-SF10
# alone would credit the reference with a fictitious 50 ms/query at SF1
# when its own published SF1 number is 318 ms/query (fixed per-query
# overhead does not shrink with data size).
_BASELINES = {
    "tpch": [(1.0, 7.0, 22), (10.0, 10.0, 22), (100.0, 42.0, 19)],
    "tpcds": [(1.0, 29.0, 67)],
}


def _report(sf: float, per_query: dict, total: float, suffix: str = "",
            suite: str = "tpch") -> None:
    points = _BASELINES.get(suite)
    if points and total > 0 and per_query:
        import math

        base_sf, base_total, base_q = min(
            points, key=lambda p: abs(math.log(sf / p[0]))
        )
        per_q = base_total / base_q
        vs_baseline = (per_q * len(per_query) * (sf / base_sf)) / total
    else:
        vs_baseline = 0.0
    print(
        json.dumps(
            {
                "metric": f"{suite}_sf{sf}_total_wall_clock_"
                          f"{len(per_query)}q{suffix}",
                "value": round(total, 4) if per_query else -1,
                "unit": "seconds",
                "vs_baseline": round(vs_baseline, 4),
            }
        ),
        flush=True,
    )


def _start_watchdog(deadline_s: float, sf: float, suite: str = "tpch") -> None:
    """The TPU-tunnel backend can block indefinitely inside PJRT client init
    (observed in this environment); a watchdog guarantees the driver still
    receives one JSON line, reporting whatever queries completed."""
    import threading

    def fire():
        _report(sf, _PROGRESS["per_query"], _PROGRESS["total"],
                suffix="_incomplete", suite=suite)
        os._exit(3)

    t = threading.Timer(deadline_s, fire)
    t.daemon = True
    t.start()


def _probe_devices(timeout_s: float, sf: float) -> None:
    """PJRT client init over the TPU tunnel can block forever (observed in
    rounds 1-2). Probe it on a side thread; on timeout, report a distinct
    metric so a wedged tunnel is distinguishable from slow queries."""
    import threading

    import jax

    done = threading.Event()
    info = {}

    def probe():
        t0 = time.perf_counter()
        try:
            info["devices"] = [str(d) for d in jax.devices()]
            info["init_s"] = round(time.perf_counter() - t0, 1)
        except Exception as e:  # pragma: no cover
            info["error"] = f"{type(e).__name__}: {e}"
        done.set()

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    if not done.wait(timeout_s):
        print(
            json.dumps(
                {
                    "metric": f"tpch_sf{sf}_device_init_timeout",
                    "value": -1,
                    "unit": "seconds",
                    "vs_baseline": 0.0,
                }
            ),
            flush=True,
        )
        os._exit(4)
    print(f"device init: {info}", file=sys.stderr, flush=True)


_SUITES = {
    "tpch": ("/root/reference/testdata/tpch/queries",
             [f"q{i}" for i in range(1, 23)]),
    "tpcds": ("/root/reference/testdata/tpcds/queries",
              [f"q{i}" for i in range(1, 100)]),
    "clickbench": ("/root/reference/testdata/clickbench/queries",
                   [f"q{i}" for i in range(0, 43)]),
}


def main() -> None:
    suite = os.environ.get("BENCH_SUITE", "tpch").lower()
    if suite not in _SUITES:
        # validate BEFORE the watchdog exists: a typo must fail loudly, not
        # strand the driver without its one guaranteed JSON line
        print(json.dumps({
            "metric": f"invalid_suite_{suite}", "value": -1,
            "unit": "seconds", "vs_baseline": 0.0,
        }), flush=True)
        sys.exit(2)
    sf = float(os.environ.get("BENCH_SF", "0.05"))
    queries = os.environ.get("BENCH_QUERIES", "")
    tasks = int(os.environ.get("BENCH_TASKS", "1"))
    budget = float(os.environ.get("BENCH_BUDGET_S", "420"))
    _start_watchdog(budget + 120.0, sf, suite)

    # Persistent XLA compile cache: 22 cold query compiles dominate the first
    # run on a fresh chip; cached programs make repeat runs near-instant.
    os.environ.setdefault("DFTPU_COMPILE_CACHE", "/root/repo/.xla_cache")

    from datafusion_distributed_tpu.sql.context import SessionContext

    _probe_devices(min(180.0, budget / 2), sf)

    qdir, default_queries = _SUITES[suite]
    qlist = (
        [q.strip() for q in queries.split(",") if q.strip()]
        if queries
        else default_queries
    )

    started = time.perf_counter()

    ctx = SessionContext()
    if suite == "tpch":
        from datafusion_distributed_tpu.data.tpchgen import register_tpch

        register_tpch(ctx, sf=sf, seed=0)
    elif suite == "tpcds":
        from datafusion_distributed_tpu.data.tpcdsgen import register_tpcds

        register_tpcds(ctx, sf=sf, seed=0)
    else:
        from datafusion_distributed_tpu.data.clickbenchgen import (
            register_clickbench,
        )

        register_clickbench(ctx, rows=max(int(100_000 * sf / 0.05), 1000),
                            seed=0)
    total = 0.0
    failed = 0
    per_query = {}
    for q in qlist:
        if time.perf_counter() - started > budget * 0.85:
            break  # leave room to report
        path = os.path.join(qdir, f"{q}.sql")
        if not os.path.exists(path):
            continue
        sql = open(path).read()
        try:
            df = ctx.sql(sql)
            # warm-up run compiles; second run measures steady-state latency
            # (the reference reports p50 of multiple runs the same way)
            best = float("inf")
            for _attempt in range(2):
                t0 = time.perf_counter()
                if tasks > 1:
                    df.collect_distributed_table(num_tasks=tasks)
                else:
                    df.collect_table()
                dt = time.perf_counter() - t0
                print(
                    f"{q} attempt {_attempt}: {dt:.3f}s", file=sys.stderr,
                    flush=True,
                )
                best = min(best, dt)
                if time.perf_counter() - started > budget:
                    break
            # note: a query whose second (steady-state) run was cut by the
            # budget reports its compile-inclusive first run — conservative
            per_query[q] = best
            total += best
            _PROGRESS["per_query"] = dict(per_query)
            _PROGRESS["total"] = total
        except Exception as e:  # a failing query must not eat the report
            failed += 1
            print(f"{q} failed: {type(e).__name__}: {e}", file=sys.stderr)

    # vs_baseline scales the reference's published totals to this SF (see
    # _BASELINES / module docstring for caveats).
    _report(sf, per_query, total, suite=suite)
    if os.environ.get("BENCH_VERBOSE"):
        print(
            json.dumps({k: round(v, 4) for k, v in per_query.items()}),
            file=sys.stderr,
        )
    if failed and not per_query:
        sys.exit(2)  # every query failed: not a valid 0-second run


if __name__ == "__main__":
    main()
