#!/usr/bin/env python
"""Benchmark entry point (driver-run on real TPU hardware).

Prints a JSON metric line {"metric", "value", "unit", "vs_baseline"};
the LAST such line on stdout is authoritative (it is re-printed after
every completed query so a late wedge still reports all finished work).

Architecture, shaped by three rounds of fighting the axon TPU tunnel:

- The tunnel is SINGLE-CLIENT and fails init two ways: a ~25-min
  in-plugin claim timeout that ends in an ordinary UNAVAILABLE
  exception, or an indefinite hang when a previous client was killed
  mid-init (the kill wedges the server-side claim for ~30 min).
  Therefore the parent NEVER kills a child: a pending init either
  resolves, raises (child retries), or the child's own deadline
  watchdog ends it after the parent has already reported.
- A PARENT process that never touches JAX orchestrates children and
  aggregates their progressively-written JSONL events.
- At startup the parent terminates leftover tunnel holders from the
  build session (.tpu_probe / orphaned bench children) — round 3's
  zero-result run traces to exactly such a leftover starving init.
- If the TPU child hasn't initialized by (deadline - BENCH_CPU_S), a
  CPU-fallback child (JAX_PLATFORMS=cpu; never touches the tunnel)
  runs the same queries so the round still records a real wall-clock,
  clearly labeled `_cpu_fallback`. The report is per-PLATFORM: any TPU
  results win (the metric's Nq count discloses partial coverage);
  fallback numbers are reported only when no TPU query completed, and
  always ride along in BENCH_DETAIL.json.

Per-query detail (stderr + BENCH_DETAIL.json): wall seconds, input
bytes touched, achieved GB/s, and % of the chip's HBM roofline.

Metric: suite total wall-clock (sum of per-query best-of-2 latencies).
Baseline (BASELINE.md): reference TPC-H SF10 total on a 12-node CPU
cluster is 10 s; vs_baseline scales the nearest published reference
point to this SF per-query (see _BASELINES).

Env knobs:
  BENCH_SUITE    tpch (default) | tpcds | clickbench
  BENCH_SF       scale factor (default 0.05)
  BENCH_QUERIES  comma list (default: the suite's full set, first-light
                 queries ordered first)
  BENCH_TASKS    mesh size for distributed mode (default 1 = single chip)
  BENCH_BUDGET_S wall-clock budget in seconds (default 1740)
  BENCH_CPU_S    budget reserved for the CPU fallback (default 420;
                 0 disables the fallback)
  BENCH_HBM_GBPS override the HBM roofline (GB/s) if device_kind unknown
"""

import json
import os
import random
import signal
import subprocess
import sys
import time

_EVENTS = os.environ.get("BENCH_EVENTS_FILE", "/root/repo/.bench_events.jsonl")
_DETAIL = "/root/repo/BENCH_DETAIL.json"
# accumulating record of (suite, sf, query) known compile-cached on the
# TPU (survives across runs alongside .xla_cache; lets a fresh run order
# warm queries first and reserve compile headroom only for cold ones)
_WARM_FILE = "/root/repo/.bench_warm_tpu.json"


def _load_warm(suite: str, sf: float) -> set:
    try:
        with open(_WARM_FILE) as f:
            return set(json.load(f).get(f"{suite}@{sf}", []))
    except (OSError, ValueError):
        return set()


def _save_warm(suite: str, sf: float, queries) -> None:
    try:
        with open(_WARM_FILE) as f:
            data = json.load(f)
    except (OSError, ValueError):
        data = {}
    key = f"{suite}@{sf}"
    data[key] = sorted(set(data.get(key, [])) | set(queries))
    try:
        with open(_WARM_FILE, "w") as f:
            json.dump(data, f)
    except OSError:
        pass

# Reference totals (README.md benchmarks table, BASELINE.md) for
# vs_baseline: per suite, the PUBLISHED (sf, total_seconds, query_count)
# points — tpch SF1 = 7 s / SF10 = 10 s / SF100 = 42 s over 19 q;
# tpcds SF1 = 29 s over 67 q. The comparison picks the nearest published
# SF (log distance) and scales linearly from there PER QUERY: the
# reference's fixed per-query overhead does not shrink with data size.
_BASELINES = {
    "tpch": [(1.0, 7.0, 22), (10.0, 10.0, 22), (100.0, 42.0, 19)],
    "tpcds": [(1.0, 29.0, 67)],
}

def _qdir(suite: str) -> str:
    """Query-text directory: the reference checkout when present, else
    the in-repo set (`benchmarks/queries/<suite>/`) — containers without
    /root/reference previously skipped EVERY query ("no such file"),
    leaving the bench trajectory empty and tools/bench_compare.py with
    no seed to diff against. The tpch and clickbench sets ship in-repo
    (the latter dialect-adapted to the synthetic `hits` schema);
    tpcds still needs the reference checkout."""
    ref = f"/root/reference/testdata/{suite}/queries"
    local = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "benchmarks", "queries", suite)
    return ref if os.path.isdir(ref) else local


_SUITES = {
    "tpch": (_qdir("tpch"),
             [f"q{i}" for i in range(1, 23)], ["q1", "q6"]),
    "tpcds": (_qdir("tpcds"),
              [f"q{i}" for i in range(1, 100)], ["q3", "q7"]),
    "clickbench": (_qdir("clickbench"),
                   [f"q{i}" for i in range(0, 43)], ["q0", "q1"]),
}

# Known HBM bandwidth rooflines by TPU device_kind substring, GB/s.
# (Public spec sheets; used only for %-of-roofline reporting.)
_HBM_GBPS = [
    ("v6e", 1640.0), ("v6", 1640.0), ("v5p", 2765.0),
    ("v5 lite", 819.0), ("v5e", 819.0), ("v5litepod", 819.0),
    ("v4", 1228.0), ("v3", 900.0), ("v2", 700.0),
]


def _vs_baseline(suite: str, sf: float, per_query: dict, total: float) -> float:
    points = _BASELINES.get(suite)
    if not (points and total > 0 and per_query):
        return 0.0
    import math

    base_sf, base_total, base_q = min(
        points, key=lambda p: abs(math.log(sf / p[0]))
    )
    per_q = base_total / base_q
    return (per_q * len(per_query) * (sf / base_sf)) / total


# --------------------------------------------------------------------------
# Child: owns JAX. Streams events (one JSON object per line) to _EVENTS.
# --------------------------------------------------------------------------

def _wire_counter_totals():
    """Summed `dftpu_wire_bytes` / `dftpu_wire_bytes_saved` across data
    planes — sampled before/after each query so the per-query event can
    carry the wire delta. Best-effort: 0s when telemetry isn't up."""
    try:
        from datafusion_distributed_tpu.runtime.telemetry import (
            DEFAULT_REGISTRY,
        )

        wire = DEFAULT_REGISTRY.counter(
            "dftpu_wire_bytes",
            "Payload bytes that crossed the wire, by data plane",
            labels=("plane",),
        )
        saved = DEFAULT_REGISTRY.counter(
            "dftpu_wire_bytes_saved",
            "Wire bytes avoided (shm references, compression delta)",
            labels=("plane",),
        )
        return (sum(v for _labels, v in wire.samples()),
                sum(v for _labels, v in saved.samples()))
    except Exception:
        return (0.0, 0.0)


def _adaptivity_counter_totals():
    """Summed runtime-adaptivity counters (skew splits, partial-agg
    bail-outs, mid-query replans) — sampled before/after each query so
    the per-query event can say which adaptations fired. Best-effort:
    0s when the adaptivity module was never imported."""
    try:
        from datafusion_distributed_tpu.runtime.telemetry import (
            DEFAULT_REGISTRY,
        )

        snap = DEFAULT_REGISTRY.snapshot()
        return tuple(
            sum(v for _labels, v in (snap.get(fam) or {}).get("samples", []))
            for fam in ("dftpu_skew_splits", "dftpu_partial_agg_bailouts",
                        "dftpu_replans", "dftpu_joins_fused",
                        "dftpu_exchanges_deleted",
                        "dftpu_global_agg_selected")
        )
    except Exception:
        return (0.0, 0.0, 0.0, 0.0, 0.0, 0.0)


def _leak_counter_totals():
    """Summed `dftpu_leaked_resources` across kinds — sampled before/after
    each query so a leak surfaced by a query-end sweep (runtime/leakcheck.py,
    armed via DFTPU_LEAK_CHECK=1) lands in that query's event. Best-effort:
    0 when the harness is off or telemetry never came up."""
    try:
        from datafusion_distributed_tpu.runtime.telemetry import (
            DEFAULT_REGISTRY,
        )

        snap = DEFAULT_REGISTRY.snapshot()
        fam = (snap.get("dftpu_leaked_resources") or {}).get("samples", [])
        return sum(v for _labels, v in fam)
    except Exception:
        return 0.0


def _emit(fh, **kw):
    kw["ts"] = round(time.time(), 3)
    fh.write(json.dumps(kw) + "\n")
    fh.flush()
    os.fsync(fh.fileno())


def _child_main() -> None:
    suite = os.environ.get("BENCH_SUITE", "tpch").lower()
    sf = float(os.environ.get("BENCH_SF", "0.05"))
    tasks = int(os.environ.get("BENCH_TASKS", "1"))
    deadline = float(os.environ["BENCH_DEADLINE_TS"])
    platform = os.environ.get("BENCH_PLATFORM", "axon")
    qdir, default_queries, _first = _SUITES[suite]
    queries = os.environ.get("BENCH_QUERIES", "")
    qlist = ([q.strip() for q in queries.split(",") if q.strip()]
             if queries else default_queries)

    fh = open(_EVENTS, "a")
    # a predecessor child may have died mid-write, leaving a torn line; a
    # leading newline isolates it (blank lines are skipped on read)
    fh.write("\n")
    os.environ.setdefault("DFTPU_COMPILE_CACHE", "/root/repo/.xla_cache")

    # last line of defense: results are already flushed to the events
    # file, so a child hung inside a single jax call past the deadline
    # self-destructs AFTER the parent has reported (deadline + 60)
    import threading

    def _self_destruct():
        _emit(fh, event="self_destruct", platform=platform)
        os._exit(5)

    t_left = max(deadline + 60 - time.time(), 1.0)
    wd = threading.Timer(t_left, _self_destruct)
    wd.daemon = True
    wd.start()

    import jax  # noqa: E402

    # the axon plugin force-selects jax_platforms="axon,cpu" at
    # registration time, overriding the env var; pin it back when a
    # specific platform is requested (the CPU-fallback child must never
    # touch the single-client tunnel)
    if platform != "axon":
        jax.config.update("jax_platforms", platform)

    # Init, with retry-on-exception: the tunnel's observed failure mode
    # is an UNAVAILABLE raised after the plugin's ~25-min internal claim
    # timeout. Each failed attempt is logged; retry while budget remains.
    devs = None
    attempt = 0
    while devs is None:
        t0 = time.perf_counter()
        try:
            devs = jax.devices()
        except Exception as e:
            _emit(fh, event="init_failed", attempt=attempt,
                  secs=round(time.perf_counter() - t0, 1),
                  platform=platform, error=f"{type(e).__name__}: {e}"[:200])
            attempt += 1
            # Retry ONLY with enough budget for a full ~25-min claim
            # window: a retry that is still claim-waiting when the
            # watchdog fires dies mid-claim and wedges the tunnel for
            # the NEXT bench run (observed r05: each self-destruct cost
            # the following run its first 25-min attempt). Exiting
            # cleanly here releases the claim request.
            if deadline - time.time() < 1600:
                _emit(fh, event="init_gave_up", platform=platform)
                sys.exit(4)
            try:  # jax caches the failed backend; clear to allow retry
                jax._src.xla_bridge._clear_backends()
            except Exception:
                pass
            time.sleep(30)
    kind = getattr(devs[0], "device_kind", str(devs[0]))
    _emit(fh, event="init", init_s=round(time.perf_counter() - t0, 2),
          devices=len(devs), device_kind=str(kind), platform=platform)

    hbm_gbps = None
    if os.environ.get("BENCH_HBM_GBPS"):
        hbm_gbps = float(os.environ["BENCH_HBM_GBPS"])
    else:
        low = str(kind).lower()
        for sub, bw in _HBM_GBPS:
            if sub in low:
                hbm_gbps = bw
                break

    import jax.numpy as jnp  # noqa: E402

    from datafusion_distributed_tpu.plan.physical import MemoryScanExec
    from datafusion_distributed_tpu.sql.context import SessionContext

    def sync_fetch(table):
        """One device->host scalar fetch that depends on the tail of the
        computation. On this backend block_until_ready does NOT block;
        only a fetch truly synchronizes, and fetching full (padded)
        buffers over the tunnel would swamp the measurement."""
        acc = jnp.asarray(table.num_rows, dtype=jnp.float32)
        for c in table.columns:
            if c.data.size:
                acc = acc + c.data.ravel()[0].astype(jnp.float32)
        return float(acc)

    def plan_input_bytes(plan) -> int:
        total = 0
        for leaf in plan.collect(lambda p: isinstance(p, MemoryScanExec)):
            for t in leaf.tasks:
                for c in t.columns:
                    total += int(c.data.nbytes)
                    if c.validity is not None:
                        total += int(c.validity.nbytes)
        return total

    t0 = time.perf_counter()
    ctx = SessionContext()
    if suite == "tpch":
        from datafusion_distributed_tpu.data.tpchgen import register_tpch

        register_tpch(ctx, sf=sf, seed=0)
    elif suite == "tpcds":
        from datafusion_distributed_tpu.data.tpcdsgen import register_tpcds

        register_tpcds(ctx, sf=sf, seed=0)
    else:
        from datafusion_distributed_tpu.data.clickbenchgen import (
            register_clickbench,
        )

        register_clickbench(ctx, rows=max(int(100_000 * sf / 0.05), 1000),
                            seed=0)
    # force the host->device transfer into the registration measurement:
    # touch one element of every registered column
    reg_sync = 0.0
    for name, t in ctx.catalog.tables.items():
        for c in t.columns:
            if c.data.size:
                reg_sync += float(c.data.ravel()[0])
    _emit(fh, event="registered", secs=round(time.perf_counter() - t0, 2),
          tables=len(ctx.catalog.tables), platform=platform)

    # queries whose executables are already in the persistent compile
    # cache (completed on this platform in a prior run, parent-tracked):
    # these need seconds; anything else may need a full cold compile,
    # which on the axon tunnel has been observed to take 100-900 s. A
    # cold query started without that much headroom dies mid-compile at
    # the watchdog — and a mid-compile death wedges the single-client
    # tunnel for the NEXT run. Stop cleanly instead.
    warm = {q.strip() for q in os.environ.get("BENCH_WARM", "").split(",")
            if q.strip()}
    compile_reserve = float(os.environ.get("BENCH_COMPILE_RESERVE_S", "900"))
    for q in qlist:
        now = time.time()
        # XLA:CPU compiles in seconds — the reserve is a tunnel-only issue
        need = compile_reserve if (platform == "axon" and q not in warm) \
            else 10.0
        if now > deadline - need:
            _emit(fh, event="budget_stop", remaining=q,
                  need_s=need, platform=platform)
            break
        path = os.path.join(qdir, f"{q}.sql")
        if not os.path.exists(path):
            _emit(fh, event="query_skipped", q=q, reason="no such file",
                  platform=platform)
            continue
        sql = open(path).read()
        try:
            df = ctx.sql(sql)
            runs = []
            best = float("inf")
            wire0, saved0 = _wire_counter_totals()
            adapt0 = _adaptivity_counter_totals()
            leaks0 = _leak_counter_totals()
            # warm-up run compiles; second run measures steady-state
            # latency (the reference reports p50 of repeat runs)
            for _attempt in range(2):
                t0 = time.perf_counter()
                if tasks > 1:
                    tbl = df.collect_distributed_table(num_tasks=tasks)
                else:
                    tbl = df.collect_table()
                sync_fetch(tbl)
                dt = time.perf_counter() - t0
                runs.append(round(dt, 4))
                best = min(best, dt)
                if time.time() > deadline - 5:
                    break
            # warm-submission latency: a FRESH ctx.sql() of the same text —
            # the serving hot path. Exercises the full resubmission stack
            # (parse -> bind -> session plan cache -> fingerprint-keyed
            # compile cache); with cross-query program reuse this should be
            # execute-bound, not compile-bound.
            warm_s = None
            if time.time() < deadline - 10:
                t0 = time.perf_counter()
                df_w = ctx.sql(sql)
                if tasks > 1:
                    tbl = df_w.collect_distributed_table(num_tasks=tasks)
                else:
                    tbl = df_w.collect_table()
                sync_fetch(tbl)
                warm_s = round(time.perf_counter() - t0, 4)
            try:
                # after collect the memoized plan reflects any overflow-
                # widened replan; planning here (vs before the timed runs)
                # also keeps plan-time subquery overflows inside
                # collect_table's retry loop
                bytes_in = plan_input_bytes(df.physical_plan())
            except Exception:
                bytes_in = 0
            gbps = bytes_in / best / 1e9 if best > 0 else 0.0
            ev = {
                "event": "query", "q": q, "secs": round(best, 4),
                "runs": runs, "bytes_in": bytes_in,
                "gbps": round(gbps, 2), "platform": platform,
            }
            # per-query wire accounting (summed across planes): bytes a
            # socket actually carried vs bytes the shm plane / adaptive
            # compression kept off it. Zero for single-process runs —
            # the counters only move on the cross-process planes.
            wire1, saved1 = _wire_counter_totals()
            if wire1 > wire0 or saved1 > saved0:
                ev["wire_bytes"] = int(wire1 - wire0)
                ev["wire_bytes_saved"] = int(saved1 - saved0)
            # which runtime adaptations fired on this query (deltas of
            # the closed-loop counters). Absent keys mean "none fired" —
            # on well-estimated plans all three stay 0 and the event
            # stays as small as before.
            adapt1 = _adaptivity_counter_totals()
            for key, b0, b1 in zip(
                ("adapt_skew_splits", "adapt_bailouts", "adapt_replans",
                 "joins_fused", "exchanges_deleted",
                 "global_agg_selected"),
                adapt0, adapt1,
            ):
                if b1 > b0:
                    ev[key] = int(b1 - b0)
            # resources the leak harness flagged at this query's end sweep
            # (only moves under DFTPU_LEAK_CHECK=1; any nonzero delta is a
            # regression bench_compare surfaces)
            leaks1 = _leak_counter_totals()
            if leaks1 > leaks0:
                ev["leaked_resources"] = int(leaks1 - leaks0)
            if warm_s is not None:
                ev["warm_s"] = warm_s
            if hbm_gbps:
                ev["pct_hbm_roofline"] = round(100.0 * gbps / hbm_gbps, 2)
            _emit(fh, **ev)
            if (os.environ.get("BENCH_TRACE") == "1"
                    and time.time() < deadline - 30):
                _export_query_trace(ctx, sql, suite, sf, q, platform, fh)
        except Exception as e:  # a failing query must not eat the report
            _emit(fh, event="query_failed", q=q, platform=platform,
                  error=f"{type(e).__name__}: {e}"[:300])
    _emit(fh, event="done", hbm_gbps=hbm_gbps, platform=platform)


_TRACE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          ".bench_traces")


def _export_query_trace(ctx, sql, suite, sf, q, platform, fh) -> None:
    """`bench.py --trace` artifact: one coordinated run of the query with
    distributed tracing on, exported as Chrome trace-event JSON (load in
    Perfetto) plus a per-stage GB/s summary in the events stream — so
    BENCH_r*.json runs carry data-plane attribution, not just totals.
    Best-effort by design: a trace-export failure must never eat the
    query's timing."""
    try:
        from datafusion_distributed_tpu.runtime.tracing import (
            DEFAULT_TRACE_STORE,
            stage_data_rates,
            to_chrome_trace,
            trace_coverage,
        )

        saved = ctx.config.distributed_options.get("tracing")
        ctx.config.distributed_options["tracing"] = "on"
        try:
            ctx.sql(sql).collect_coordinated_table(
                num_workers=2, num_tasks=4
            )
        finally:
            if saved is None:
                ctx.config.distributed_options.pop("tracing", None)
            else:
                ctx.config.distributed_options["tracing"] = saved
        trace = DEFAULT_TRACE_STORE.last()
        if trace is None:
            return
        os.makedirs(_TRACE_DIR, exist_ok=True)
        path = os.path.join(_TRACE_DIR, f"{suite}_sf{sf}_{q}.json")
        with open(path, "w") as tf:
            json.dump(to_chrome_trace(trace), tf)
        cov, _gap = trace_coverage(trace)
        rates = stage_data_rates(trace)
        stage_gbps = {
            str(sid): round((slot.get("bytes_per_s") or 0.0) / 1e9, 4)
            for sid, slot in rates.items()
        }
        # one aggregate data-plane rate per query: the BYTES-WEIGHTED mean
        # of the per-stage rates ("at what rate did the typical byte
        # move"), over byte-carrying exchange stages — the root consumer
        # (wall == the whole query) and compile-dominated zero-byte lanes
        # would only dilute a plain bytes/wall quotient. Emitted as its
        # own metric line by the parent.
        carrying = [
            s for sid, s in rates.items()
            if sid != -1 and s.get("bytes") and s.get("bytes_per_s")
        ]
        tot_bytes = sum(s["bytes"] for s in carrying)
        dp_gbps = (
            sum(s["bytes"] * s["bytes_per_s"] for s in carrying)
            / tot_bytes / 1e9
        ) if tot_bytes else 0.0
        _emit(fh, event="trace", q=q, platform=platform, path=path,
              coverage=round(cov, 4), stage_gbps=stage_gbps,
              data_plane_gbps=round(dp_gbps, 4))
    except Exception as e:
        _emit(fh, event="trace_failed", q=q, platform=platform,
              error=f"{type(e).__name__}: {e}"[:200])


# --------------------------------------------------------------------------
# Serving bench (`bench.py --serving`): closed-loop multi-query throughput.
# Runs IN-PROCESS on the CPU backend by default (BENCH_PLATFORM overrides)
# — this measures the serving tier's concurrency arbitration, not the
# tunnel. N clients each submit-and-wait over a mixed workload against one
# shared cluster; one client is a HEAVY analytical query (q21) so the
# fair-share-vs-FIFO comparison shows whether cheap q1/q6 latency stays
# bounded next to it. A uniform injected execute delay stands in for
# device/DCN latency (the micro_bench stage_overlap precedent; both the
# sequential baseline and the concurrent arms pay it identically per
# task). Emits BENCH metric lines; the LAST is the authoritative qps.
#
# Env knobs: BENCH_SERVING_CLIENTS (8), BENCH_SERVING_ITERS (2),
# BENCH_SF (0.002), BENCH_SERVING_DELAY_MS (80; 0 disables).
#
# Default regime is DELAY-dominated (small SF, 80 ms per execute): the
# tier arbitrates stage placement, so its wins show where per-stage
# latency is device/DCN wait — the production regime. On this 2-core
# container a COMPUTE-dominated workload (large SF) measures core
# contention instead: one-at-a-time execution is then near-optimal for
# makespan and fair share trades heavy-query completion for cheap-query
# latency (observed sf0.005: fair cheap-p50 2.8s vs 17.1s serialized,
# but aggregate qps 0.21 vs 0.46 — the classic fairness/throughput
# tradeoff, amplified by 2 cores). Both regimes are one env var away.
# --------------------------------------------------------------------------

_SERVING_Q1 = """
select l_returnflag, l_linestatus,
       sum(l_quantity) as sum_qty,
       sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
       count(*) as count_order
from lineitem
where l_shipdate <= date '1998-09-02'
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus
"""

_SERVING_Q6 = """
select sum(l_extendedprice * l_discount) as revenue
from lineitem
where l_shipdate >= date '1994-01-01'
  and l_shipdate < date '1995-01-01'
  and l_discount between 0.05 and 0.07
  and l_quantity < 24
"""

_SERVING_Q21 = """
select s_name, count(*) as numwait
from supplier, lineitem l1, orders, nation
where s_suppkey = l1.l_suppkey
  and o_orderkey = l1.l_orderkey
  and o_orderstatus = 'F'
  and l1.l_receiptdate > l1.l_commitdate
  and exists (
    select * from lineitem l2
    where l2.l_orderkey = l1.l_orderkey
      and l2.l_suppkey <> l1.l_suppkey
  )
  and not exists (
    select * from lineitem l3
    where l3.l_orderkey = l1.l_orderkey
      and l3.l_suppkey <> l1.l_suppkey
      and l3.l_receiptdate > l3.l_commitdate
  )
  and s_nationkey = n_nationkey
  and n_name = 'SAUDI ARABIA'
group by s_name
order by numwait desc, s_name
limit 100
"""


def _merge_serving_detail(serving: dict) -> None:
    """Upsert ``meta.serving`` into BENCH_DETAIL.json (creating a
    minimal document when the suite bench has not run) so `bench.py
    --serving` results are diffable by tools/bench_compare.py alongside
    the per-query walls."""
    try:
        with open(_DETAIL) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        doc = {"suite": "serving-only", "per_query_s": {}, "failed": {},
               "meta": {}}
    doc.setdefault("meta", {})["serving"] = serving
    try:
        with open(_DETAIL, "w") as f:
            json.dump(doc, f, indent=1)
    except OSError:
        pass


def _serving_bench() -> None:
    platform = os.environ.get("BENCH_PLATFORM", "cpu")
    os.environ.setdefault("JAX_PLATFORMS", platform)
    import jax

    if jax.config.jax_platforms != platform:
        jax.config.update("jax_platforms", platform)

    from datafusion_distributed_tpu.data.tpchgen import gen_tpch
    from datafusion_distributed_tpu.runtime.chaos import (
        FaultPlan,
        FaultSpec,
        wrap_cluster,
    )
    from datafusion_distributed_tpu.runtime.coordinator import (
        InMemoryCluster,
    )
    from datafusion_distributed_tpu.runtime.serving import ServingSession
    from datafusion_distributed_tpu.sql.context import SessionContext

    sf = float(os.environ.get("BENCH_SF", "0.002"))
    clients = int(os.environ.get("BENCH_SERVING_CLIENTS", "8"))
    iters = int(os.environ.get("BENCH_SERVING_ITERS", "2"))
    delay_ms = float(os.environ.get("BENCH_SERVING_DELAY_MS", "80"))
    straggler_ms = float(os.environ.get("BENCH_STRAGGLER_MS", "800"))
    # SLO target for the closed-loop arms (runtime/telemetry.py
    # SloTracker): attainment against this p99 target rides into
    # BENCH_DETAIL meta.serving so bench_compare.py can diff it
    slo_p99_ms = float(os.environ.get("BENCH_SLO_P99_MS", "2000"))
    workers = 4

    t0 = time.perf_counter()
    ctx = SessionContext()
    ctx.config.distributed_options["bytes_per_task"] = 1
    ctx.config.distributed_options["broadcast_joins"] = False
    ctx.config.distributed_options["slo_p99_ms"] = slo_p99_ms
    for name, arrow in gen_tpch(sf=sf, seed=0).items():
        ctx.register_arrow(name, arrow)
    print(f"serving bench: registered tpch sf{sf} in "
          f"{time.perf_counter() - t0:.1f}s", file=sys.stderr, flush=True)

    def cluster():
        inner = InMemoryCluster(workers)
        if delay_ms <= 0:
            return inner
        return wrap_cluster(inner, FaultPlan(0, [
            FaultSpec(site="execute", kind="delay",
                      delay_s=delay_ms / 1e3, rate=1.0),
        ], query_scoped=True))

    def client_workload(ci: int) -> list:
        # client 0 runs the heavy q21; everyone else a q1/q6 mix
        if ci == 0:
            return [_SERVING_Q21] * iters
        return [(_SERVING_Q1 if (ci + i) % 2 else _SERVING_Q6)
                for i in range(iters)]

    def run_arm(max_conc: int, fair: bool) -> dict:
        from datafusion_distributed_tpu.runtime.serving import (
            percentile_ms,
            run_closed_loop,
        )

        srv = ServingSession(
            ctx, cluster=cluster(), num_tasks=workers,
            max_concurrent_queries=max_conc, fair_share=fair,
        )
        res = run_closed_loop(
            srv, [client_workload(i) for i in range(clients)],
            classify=lambda ci: "heavy" if ci == 0 else "cheap",
            timeout=1800.0,
        )
        slo = srv.slo_snapshot()
        # aggregate staged-byte peak across the arm's worker stores
        # (bench_compare's direction-aware peak_staged_bytes column;
        # each arm builds a fresh cluster, so this is the arm's own peak)
        try:
            peak_staged = sum(
                s.get("peak_nbytes", 0)
                for s in srv.stats()["memory"]["workers"].values()
            )
        except Exception:
            peak_staged = None
        srv.close()
        if res["errors"]:
            print(f"serving bench errors: {res['errors']}",
                  file=sys.stderr, flush=True)
        cheap = res["walls"].get("cheap", [])
        heavy = res["walls"].get("heavy", [])
        return {
            "qps": round(res["queries"] / res["wall_s"], 3),
            "wall_s": round(res["wall_s"], 2),
            "queries": res["queries"],
            "cheap_p50_ms": percentile_ms(cheap, 0.50),
            "cheap_p99_ms": percentile_ms(cheap, 0.99),
            "heavy_max_ms": percentile_ms(heavy, 0.99),
            "errors": len(res["errors"]),
            # rolling SLO attainment vs BENCH_SLO_P99_MS (telemetry.py)
            "slo_latency_attainment": slo.get("latency_attainment"),
            "slo_p99_ok": slo.get("p99_ok"),
            "peak_staged_bytes": peak_staged,
        }

    # ---- injected-straggler arm (the ROADMAP serving-hardening gate):
    # ONE seeded sticky-slow worker (chaos kind="straggler") on top of
    # the uniform delay, all-cheap clients (no q21 — the tail must be
    # straggler-driven, not heavy-query-driven), hedging off vs on.
    # Hedging speculatively re-dispatches any attempt outliving
    # max(sketch-p99, hedge_floor_s) to a healthy worker; the floor sits
    # above a normal task's injected wall and far below the straggler's,
    # so exactly the straggler-routed attempts hedge.
    def run_straggler_arm(hedge: bool) -> dict:
        from datafusion_distributed_tpu.runtime.serving import (
            percentile_ms,
            run_closed_loop,
        )

        opts = ctx.config.distributed_options
        prev = {k: opts.get(k) for k in ("hedging", "hedge_floor_s",
                                         "hedge_budget")}
        opts["hedging"] = hedge
        opts["hedge_floor_s"] = max(1.5 * delay_ms, 50.0) / 1e3
        opts["hedge_budget"] = workers
        try:
            specs = [FaultSpec(site="execute", kind="straggler",
                               delay_s=straggler_ms / 1e3,
                               workers=["worker-0"], rate=1.0)]
            if delay_ms > 0:
                specs.append(FaultSpec(site="execute", kind="delay",
                                       delay_s=delay_ms / 1e3, rate=1.0))
            srv = ServingSession(
                ctx,
                cluster=wrap_cluster(
                    InMemoryCluster(workers),
                    FaultPlan(1, specs, query_scoped=True),
                ),
                num_tasks=workers, max_concurrent_queries=clients,
                fair_share=True,
            )
            res = run_closed_loop(
                srv,
                [[(_SERVING_Q1 if (ci + i) % 2 else _SERVING_Q6)
                  for i in range(iters)] for ci in range(clients)],
                classify=lambda ci: "all", timeout=1800.0,
            )
            srv.close()
        finally:
            for k, v in prev.items():
                if v is None:
                    opts.pop(k, None)
                else:
                    opts[k] = v
        if res["errors"]:
            print(f"straggler arm errors: {res['errors']}",
                  file=sys.stderr, flush=True)
        walls = res["walls"].get("all", [])
        return {
            "p50_ms": percentile_ms(walls, 0.50),
            "p99_ms": percentile_ms(walls, 0.99),
            "qps": round(res["queries"] / res["wall_s"], 3),
            "queries": res["queries"],
            "errors": len(res["errors"]),
        }

    # ---- bursty open-loop arm (the result-cache serving gate): Poisson
    # arrivals at a fixed rate REGARDLESS of completions (open loop — the
    # queue builds under burst, unlike the closed-loop arms above), over
    # a repeated + literal-variant mix. Cache off vs on: repeats of the
    # same literal vector hit the whole-result cache's zero-copy fast
    # path before admission costing, so the on-arm's p99 reflects
    # cache-served queue drain, not just faster execution.
    def run_burst_arm(cache_on: bool) -> dict:
        from datafusion_distributed_tpu.runtime.serving import (
            percentile_ms,
        )

        opts = ctx.config.distributed_options
        prev = opts.get("result_cache")
        opts["result_cache"] = cache_on
        n = int(os.environ.get("BENCH_BURST_QUERIES", "32"))
        arrival_qps = float(os.environ.get("BENCH_BURST_QPS", "10"))
        rng = random.Random(11)
        # q1 repeated + three q6 discount variants: repeats exercise the
        # whole-result hit path, variants prove per-literal-vector keys
        # (a variant must never be served another variant's rows)
        mix = [_SERVING_Q1] + [
            _SERVING_Q6.replace("between 0.05", f"between 0.0{d}")
            for d in (4, 5, 6)
        ]
        workload = [mix[rng.randrange(len(mix))] for _ in range(n)]
        srv = ServingSession(
            ctx, cluster=cluster(), num_tasks=workers,
            max_concurrent_queries=clients, fair_share=True,
        )
        handles: list = []
        errors: list = []
        walls: list = []
        cache_stats: dict = {}
        try:
            for sql in workload:
                try:
                    handles.append(srv.submit(sql))
                except Exception as e:
                    errors.append(f"{type(e).__name__}: {e}")
                time.sleep(rng.expovariate(arrival_qps))
            for h in handles:
                try:
                    h.result(timeout=1800.0)
                    walls.append(h.finished_s - h.submitted_s)
                except Exception as e:
                    errors.append(f"{type(e).__name__}: {e}")
            cache_stats = srv.stats().get("result_cache") or {}
        finally:
            srv.close()
            if prev is None:
                opts.pop("result_cache", None)
            else:
                opts["result_cache"] = prev
            # drop the arm's entries so the NEXT arm (and the closed-loop
            # arms below) starts from a cold, knob-consistent slate
            rc = getattr(ctx, "_result_cache", None)
            if rc is not None:
                rc.clear()
        if errors:
            print(f"burst arm errors: {errors}", file=sys.stderr,
                  flush=True)
        return {
            "p50_ms": percentile_ms(walls, 0.50),
            "p99_ms": percentile_ms(walls, 0.99),
            "queries": len(walls),
            "errors": len(errors),
            "hit_rate": cache_stats.get("hit_rate"),
            "hits": cache_stats.get("hits"),
            "misses": cache_stats.get("misses"),
        }

    # warm every compile cache (templates + stage programs) off-clock
    run_arm(clients, True)
    burst_off = run_burst_arm(False)
    burst_on = run_burst_arm(True)
    print(json.dumps({"serving_burst_detail": {
        "off": burst_off, "on": burst_on,
    }}), file=sys.stderr, flush=True)
    print(json.dumps({
        "metric": "serving_burst_p99_ms_cache_off",
        "value": burst_off["p99_ms"],
        "unit": "milliseconds",
    }), flush=True)
    # result cache on vs off under the same Poisson burst: vs_baseline =
    # off/on (>1 means cache-served repeats drained the burst queue
    # faster; the acceptance gate asks on < off)
    if burst_on["p99_ms"]:
        print(json.dumps({
            "metric": "serving_burst_p99_ms_cache_on",
            "value": burst_on["p99_ms"],
            "unit": "milliseconds",
            "vs_baseline": round(
                (burst_off["p99_ms"] or 0) / burst_on["p99_ms"], 4,
            ),
        }), flush=True)
    if burst_on["hit_rate"] is not None:
        print(json.dumps({
            "metric": "serving_cache_hit_rate",
            "value": round(burst_on["hit_rate"], 4),
            "unit": "fraction",
        }), flush=True)
    straggler_off = run_straggler_arm(False)
    straggler_on = run_straggler_arm(True)
    print(json.dumps({"serving_straggler_detail": {
        "off": straggler_off, "on": straggler_on,
        "straggler_ms": straggler_ms, "delay_ms": delay_ms,
        "clients": clients,
    }}), file=sys.stderr, flush=True)
    print(json.dumps({
        "metric": "serving_straggler_p99_ms_off",
        "value": straggler_off["p99_ms"],
        "unit": "milliseconds",
    }), flush=True)
    # hedging on vs off under one seeded sticky straggler: vs_baseline =
    # off/on (>1 means hedging cut the closed-loop p99; the acceptance
    # gate asks >= 1.5x)
    if straggler_on["p99_ms"]:
        print(json.dumps({
            "metric": "serving_straggler_p99_ms_on",
            "value": straggler_on["p99_ms"],
            "unit": "milliseconds",
            "vs_baseline": round(
                (straggler_off["p99_ms"] or 0)
                / straggler_on["p99_ms"], 4,
            ),
        }), flush=True)
    seq = run_arm(1, True)  # serialized: the pre-serving baseline
    fifo = run_arm(clients, False)
    fair = run_arm(clients, True)
    detail = {"sequential": seq, "fifo": fifo, "fair": fair,
              "clients": clients, "sf": sf, "delay_ms": delay_ms,
              "platform": platform}
    print(json.dumps({"serving_detail": detail}), file=sys.stderr,
          flush=True)
    # fold the comparable numbers into BENCH_DETAIL meta.serving (flat,
    # bench_compare.py's serving section reads these keys) instead of
    # living only in stdout metric lines — the bench trajectory becomes
    # machine-diffable run over run
    _merge_serving_detail({
        "qps": fair["qps"],
        "qps_sequential": seq["qps"],
        "qps_fifo": fifo["qps"],
        "cheap_p50_ms": fair["cheap_p50_ms"],
        "cheap_p99_ms": fair["cheap_p99_ms"],
        "cheap_p99_ms_fifo": fifo["cheap_p99_ms"],
        "heavy_max_ms": fair["heavy_max_ms"],
        "straggler_p99_ms_off": straggler_off["p99_ms"],
        "straggler_p99_ms_on": straggler_on["p99_ms"],
        "burst_p99_ms_cache_off": burst_off["p99_ms"],
        "burst_p99_ms_cache_on": burst_on["p99_ms"],
        "cache_hit_rate": burst_on["hit_rate"],
        "slo_p99_target_ms": slo_p99_ms,
        "slo_latency_attainment": fair["slo_latency_attainment"],
        "peak_staged_bytes": fair["peak_staged_bytes"],
        "clients": clients, "sf": sf, "delay_ms": delay_ms,
        "straggler_ms": straggler_ms, "platform": platform,
        # just the arm dicts: the config scalars live at the top
        # level only (one copy, nothing for consumers to special-case)
        "arms": {"sequential": seq, "fifo": fifo, "fair": fair,
                 "burst_cache_off": burst_off,
                 "burst_cache_on": burst_on},
    })
    if fair["slo_latency_attainment"] is not None:
        print(json.dumps({
            "metric": f"serving_slo_attainment_{clients}clients",
            "value": round(fair["slo_latency_attainment"], 4),
            "unit": "fraction",
            "vs_baseline": 0.0,
        }), flush=True)
    # cheap-query p99 with the heavy q21 alongside: fair share must keep
    # it bounded vs FIFO (lower is better; vs_baseline = fifo/fair, >1
    # means fair share improved tail latency)
    if fair["cheap_p99_ms"] and fifo["cheap_p99_ms"]:
        print(json.dumps({
            "metric": f"serving_cheap_p99_ms_fair_{clients}clients",
            "value": fair["cheap_p99_ms"],
            "unit": "milliseconds",
            "vs_baseline": round(
                fifo["cheap_p99_ms"] / fair["cheap_p99_ms"], 4),
        }), flush=True)
    # authoritative line LAST: aggregate throughput at N clients;
    # vs_baseline = speedup over the serialized one-query-at-a-time
    # baseline (>1.0 = cross-query stage overlap is real)
    print(json.dumps({
        "metric": f"serving_qps_{clients}clients_sf{sf}",
        "value": fair["qps"],
        "unit": "qps",
        "vs_baseline": round(fair["qps"] / max(seq["qps"], 1e-9), 4),
    }), flush=True)


# --------------------------------------------------------------------------
# Parent: no JAX. Spawns/monitors children, aggregates, reports.
# Never kills a child (a kill mid-init wedges the single-client tunnel);
# children own their lifecycle via deadline watchdogs.
# --------------------------------------------------------------------------


def _read_events(path: str, offset: int):
    """-> (events, new_offset); tolerant of a torn final line."""
    try:
        with open(path) as f:
            f.seek(offset)
            data = f.read()
    except FileNotFoundError:
        return [], offset
    events = []
    consumed = 0
    for line in data.splitlines(keepends=True):
        if not line.endswith("\n"):
            break
        consumed += len(line)
        line = line.strip()
        if line:
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    return events, offset + consumed


def _terminate_stale_tunnel_holders() -> None:
    """Kill leftover processes from the BUILD session that may hold the
    single-client tunnel (probe scripts, orphaned bench children).

    Round 3 post-mortem: a `.tpu_probe.py` left running by the build
    session was still retrying init hours later when the driver's bench
    ran — the bench never got the tunnel. These processes are long past
    init (or failing it), so terminating them releases, not wedges."""
    me = os.getpid()
    for pid_s in os.listdir("/proc"):
        if not pid_s.isdigit() or int(pid_s) == me:
            continue
        try:
            with open(f"/proc/{pid_s}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\0", b" ").decode(errors="replace")
            if ".tpu_probe" in cmd:
                os.kill(int(pid_s), signal.SIGTERM)
                print(f"bench: terminated stale probe pid {pid_s}",
                      file=sys.stderr, flush=True)
                continue
            if "python" in cmd and "bench.py" in cmd:
                with open(f"/proc/{pid_s}/environ", "rb") as f:
                    env = f.read().replace(b"\0", b" ").decode(errors="replace")
                if "BENCH_CHILD=1" in env:
                    os.kill(int(pid_s), signal.SIGTERM)
                    print(f"bench: terminated orphan bench child {pid_s}",
                          file=sys.stderr, flush=True)
        except (OSError, ValueError):
            continue


def _spawn_child(remaining_queries, deadline, platform):
    env = dict(os.environ)
    env["BENCH_CHILD"] = "1"
    env["BENCH_QUERIES"] = ",".join(remaining_queries)
    env["BENCH_DEADLINE_TS"] = str(deadline)
    env["BENCH_PLATFORM"] = platform
    env["BENCH_WARM"] = ",".join(sorted(_load_warm(
        os.environ.get("BENCH_SUITE", "tpch").lower(),
        float(os.environ.get("BENCH_SF", "0.05")))))
    if platform == "axon":
        env.setdefault("JAX_PLATFORMS", "axon")
    else:
        env["JAX_PLATFORMS"] = platform
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__)],
        env=env, stdout=sys.stderr, stderr=sys.stderr,
        start_new_session=True,
    )
    print(f"bench child [{platform}]: pid {proc.pid}, "
          f"{len(remaining_queries)} queries", file=sys.stderr, flush=True)
    return proc


def main() -> None:
    if "--suite" in sys.argv:
        # CLI alias for BENCH_SUITE (tpch | tpcds | clickbench); the env
        # var still wins inside the re-exec'd child, so set it here
        i = sys.argv.index("--suite")
        if i + 1 < len(sys.argv):
            os.environ["BENCH_SUITE"] = sys.argv[i + 1].lower()
    if "--serving" in sys.argv:
        _serving_bench()
        return
    if "--trace" in sys.argv:
        # distributed-tracing artifacts: each query additionally runs
        # once through the coordinated tier with `SET distributed.
        # tracing = on`, exporting a Chrome trace-event JSON (Perfetto)
        # with per-stage data-plane GB/s next to the timings
        os.environ["BENCH_TRACE"] = "1"
    if os.environ.get("BENCH_CHILD") == "1":
        _child_main()
        return

    suite = os.environ.get("BENCH_SUITE", "tpch").lower()
    if suite not in _SUITES:
        print(json.dumps({
            "metric": f"invalid_suite_{suite}", "value": -1,
            "unit": "seconds", "vs_baseline": 0.0,
        }), flush=True)
        sys.exit(2)
    sf = float(os.environ.get("BENCH_SF", "0.05"))
    budget = float(os.environ.get("BENCH_BUDGET_S", "1740"))
    cpu_reserve = float(os.environ.get("BENCH_CPU_S", "420"))
    # Bound on how long an un-initialized TPU may gate the CPU fallback:
    # if no successful init within this window, the CPU child spawns NOW
    # (the TPU child keeps trying and its results still take precedence).
    # BENCH_r05: a failed axon init burned 1508s before the reserve-point
    # fallback ran the entire 5.7s CPU suite it was gating. 0 disables.
    init_timeout = float(os.environ.get("DFTPU_TPU_INIT_TIMEOUT_S", "120"))
    started = time.time()
    deadline = started + budget
    cpu_start_at = deadline - cpu_reserve if cpu_reserve > 0 else None

    _qdir, default_queries, first_light = _SUITES[suite]
    if os.environ.get("BENCH_QUERIES"):
        qlist = [q.strip() for q in os.environ["BENCH_QUERIES"].split(",")
                 if q.strip()]
    else:
        # order: first-light, then other compile-cached (warm) queries,
        # then cold ones — a late wedge still yields maximal coverage
        warm = _load_warm(suite, sf)
        rest = [q for q in default_queries if q not in first_light]
        qlist = (first_light
                 + [q for q in rest if q in warm]
                 + [q for q in rest if q not in warm])

    # "tpu" slot = the requested primary platform (axon for driver runs,
    # cpu for BENCH_PLATFORM=cpu self-tests — those are NOT fallbacks and
    # keep the unsuffixed metric name); "cpu" slot = the fallback child
    state = {"tpu": {}, "cpu": {}, "tpu_warm": {}, "cpu_warm": {},
             "failed": {}, "meta": {}}
    # carry the previous run's meta.serving forward: the suite bench
    # rewrites BENCH_DETAIL.json wholesale, and losing the serving block
    # `bench.py --serving` upserted would silently skip every serving
    # comparison in tools/bench_compare.py
    try:
        with open(_DETAIL) as f:
            _prev_meta = json.load(f).get("meta")
        if isinstance(_prev_meta, dict) and "serving" in _prev_meta:
            state["meta"]["serving"] = _prev_meta["serving"]
    except (OSError, json.JSONDecodeError):
        pass

    def current_report():
        if state["tpu"]:
            per_query, suffix = state["tpu"], ""
            warm = state["tpu_warm"]
        else:
            per_query, suffix = state["cpu"], "_cpu_fallback"
            warm = state["cpu_warm"]
        total = sum(per_query.values())
        return per_query, suffix, total, warm

    def print_metric():
        per_query, suffix, total, warm = current_report()
        # warm-repeat (second-submission wall clock): tracked as its own
        # metric line so BENCH_r* follows serving latency, not just cold
        # totals. Printed BEFORE the main metric — the LAST line stays the
        # authoritative suite total.
        if warm:
            print(json.dumps({
                "metric": f"{suite}_sf{sf}_warm_repeat_"
                          f"{len(warm)}q{suffix}",
                "value": round(sum(warm.values()), 4),
                "unit": "seconds",
                "vs_baseline": 0.0,
            }), flush=True)
        # data-plane rate (bench --trace runs): mean per-query aggregate
        # stage GB/s from the trace byte attribution — the zero-copy
        # plane's measured rate next to the per-stage breakdown in
        # BENCH_DETAIL meta.traces
        traced = [
            v["data_plane_gbps"]
            for v in state["meta"].get("traces", {}).values()
            if v.get("data_plane_gbps")
        ]
        if traced:
            print(json.dumps({
                "metric": f"{suite}_sf{sf}_data_plane_gbps",
                "value": round(sum(traced) / len(traced), 4),
                "unit": "GB/s",
                "vs_baseline": 0.0,
            }), flush=True)
        print(json.dumps({
            "metric": f"{suite}_sf{sf}_total_wall_clock_"
                      f"{len(per_query)}q{suffix}",
            "value": round(total, 4) if per_query else -1,
            "unit": "seconds",
            "vs_baseline": round(
                _vs_baseline(suite, sf, per_query, total), 4),
        }), flush=True)

    def write_detail():
        per_query, suffix, total, warm = current_report()
        try:
            with open(_DETAIL, "w") as f:
                json.dump({
                    "suite": suite, "sf": sf,
                    "platform": ("cpu_fallback" if suffix
                                 else ("tpu" if primary == "axon"
                                       else primary)),
                    "per_query_s": per_query,
                    "cpu_per_query_s": state["cpu"],
                    "warm_repeat_s": warm,
                    "cpu_warm_repeat_s": state["cpu_warm"],
                    "failed": state["failed"], "meta": state["meta"],
                    "total_s": round(total, 4),
                }, f, indent=1)
        except OSError:
            pass

    import threading

    def watchdog():
        # the parent's own last line of defense (should never fire: the
        # main loop exits at deadline): report, then leave — children
        # are NOT killed; their own watchdogs end them
        write_detail()
        print_metric()
        os._exit(3)

    wd = threading.Timer(budget + 90.0, watchdog)
    wd.daemon = True
    wd.start()

    _terminate_stale_tunnel_holders()

    try:
        os.unlink(_EVENTS)
    except FileNotFoundError:
        pass

    offset = 0
    primary = os.environ.get("BENCH_PLATFORM", "axon")  # cpu for self-tests
    tpu_child = _spawn_child(qlist, deadline, primary)
    cpu_child = None
    cpu_spawned = False
    tpu_pending = True   # False once the primary child exits or is done
    tpu_done = False     # primary child emitted its done event
    tpu_init_seen = False  # primary child emitted a successful init event

    while time.time() < deadline - 5:
        events, offset = _read_events(_EVENTS, offset)
        progressed = False
        for ev in events:
            kind = ev.get("event")
            plat = "tpu" if ev.get("platform", "axon") == primary else "cpu"
            if kind == "init":
                if plat == "tpu":
                    tpu_init_seen = True
                state["meta"][f"{plat}_init"] = {
                    k: ev[k] for k in
                    ("init_s", "devices", "device_kind") if k in ev}
            elif kind == "init_failed":
                state["meta"].setdefault(f"{plat}_init_failures", []).append(
                    {"secs": ev.get("secs"), "error": ev.get("error")})
                print(f"  [{plat}] init attempt failed after "
                      f"{ev.get('secs')}s: {ev.get('error', '')[:120]}",
                      file=sys.stderr, flush=True)
            elif kind == "registered":
                state["meta"][f"{plat}_register_s"] = ev.get("secs")
            elif kind == "query":
                state[plat][ev["q"]] = ev["secs"]
                if "warm_s" in ev:
                    state[f"{plat}_warm"][ev["q"]] = ev["warm_s"]
                if plat == "tpu" and primary == "axon":
                    # executables now in the persistent compile cache —
                    # record immediately so a later wedge can't lose it
                    _save_warm(suite, sf, [ev["q"]])
                state["meta"].setdefault(f"{plat}_queries", {})[ev["q"]] = {
                    k: ev[k] for k in
                    ("runs", "warm_s", "bytes_in", "gbps",
                     "pct_hbm_roofline", "wire_bytes",
                     "wire_bytes_saved", "adapt_skew_splits",
                     "adapt_bailouts", "adapt_replans",
                     "joins_fused", "exchanges_deleted",
                     "global_agg_selected", "leaked_resources")
                    if k in ev}
                if ev.get("leaked_resources"):
                    state["meta"]["leaked_resources_total"] = (
                        state["meta"].get("leaked_resources_total", 0)
                        + int(ev["leaked_resources"]))
                print(f"  [{plat}] {ev['q']}: {ev['secs']}s "
                      f"({ev.get('gbps', '?')} GB/s, "
                      f"{ev.get('pct_hbm_roofline', '?')}% roofline)",
                      file=sys.stderr, flush=True)
                progressed = True
            elif kind == "query_failed":
                state["failed"][f"{plat}:{ev['q']}"] = ev.get("error", "")
            elif kind == "trace":
                # --trace artifact: Perfetto JSON path + per-stage GB/s
                # attribution rides into BENCH_DETAIL meta
                state["meta"].setdefault("traces", {})[ev["q"]] = {
                    k: ev[k] for k in
                    ("path", "coverage", "stage_gbps", "data_plane_gbps")
                    if k in ev}
            elif kind == "done":
                if ev.get("hbm_gbps") is not None:
                    state["meta"]["hbm_gbps"] = ev["hbm_gbps"]
                if plat == "tpu":
                    tpu_done = True
                    tpu_pending = False
        if progressed:
            write_detail()
            print_metric()
        # a TPU child that exited (crash after init, init gave up, or
        # normal teardown) has nothing more coming
        if tpu_child is not None and tpu_child.poll() is not None:
            if tpu_child.returncode not in (0, None):
                print(f"bench tpu child exited rc={tpu_child.returncode}",
                      file=sys.stderr, flush=True)
            tpu_child = None
            tpu_pending = False
        if cpu_child is not None and cpu_child.poll() is not None:
            cpu_child = None
        # finish early once nothing is pending: the primary resolved and
        # any spawned fallback exited
        if not tpu_pending and cpu_child is None:
            if (tpu_done or state["tpu"] or cpu_spawned
                    or cpu_start_at is None or primary != "axon"):
                break
        # fallback trigger: no successful TPU init within
        # DFTPU_TPU_INIT_TIMEOUT_S (the bounded init window — the CPU
        # suite must not sit behind a wedged/failing tunnel claim), no
        # TPU init by the reserve point, or the TPU child conclusively
        # failed without completing the suite
        if (cpu_start_at is not None and not cpu_spawned
                and primary == "axon" and not tpu_done
                and (time.time() >= cpu_start_at
                     or not tpu_pending
                     or (init_timeout > 0 and not tpu_init_seen
                         and time.time() >= started + init_timeout))):
            cpu_child = _spawn_child(qlist, deadline, "cpu")
            cpu_spawned = True
        time.sleep(2.0)

    # final drain + report
    events, offset = _read_events(_EVENTS, offset)
    for ev in events:
        plat = "tpu" if ev.get("platform", "axon") == "axon" else "cpu"
        if ev.get("event") == "query":
            state[plat][ev["q"]] = ev["secs"]
            if "warm_s" in ev:
                state[f"{plat}_warm"][ev["q"]] = ev["warm_s"]
        elif ev.get("event") == "query_failed":
            state["failed"][f"{plat}:{ev['q']}"] = ev.get("error", "")
    wd.cancel()
    write_detail()
    print_metric()
    per_query, _suffix, _total, _warm = current_report()
    if not per_query:
        sys.exit(4)


if __name__ == "__main__":
    main()
