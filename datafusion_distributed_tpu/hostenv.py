"""Host-environment helpers shared by the test suite and benchmark runners.

XLA:CPU's persistent compile cache stores AOT executables whose code paths
assume the COMPILING host's CPU features, while jax's cache key does not
include them — loading an entry compiled on a different physical CPU warns
"could lead to execution errors such as SIGILL" and sporadically delivers
exactly that. Environments that land on heterogeneous machines (this VM
does) must therefore fingerprint the cache directory per CPU so a migration
misses the cache instead of executing foreign machine code.
"""

from __future__ import annotations

import hashlib
import os


def ensure_collective_timeout_flags(warn_stuck_s: int = 120,
                                    terminate_s: int = 1200) -> None:
    """Append XLA:CPU collective-timeout flags to XLA_FLAGS unless the
    caller already set them (each flag guarded by its own name, so a
    user-supplied value for one is never clobbered by the other's
    default). Must run before the first jax backend init.

    Why: 8 virtual devices time-share this box's single core; inside a
    large mesh program one participant thread can legitimately be starved
    past XLA:CPU's default 40 s collective rendezvous termination
    timeout, which F-aborts the whole process mid-collective (observed:
    all_gather rendezvous abort in the SF0.5 sweep's mesh tier)."""
    flags = os.environ.get("XLA_FLAGS", "")
    for flag, val in (
        ("--xla_cpu_collective_call_warn_stuck_timeout_seconds",
         warn_stuck_s),
        ("--xla_cpu_collective_call_terminate_timeout_seconds",
         terminate_s),
    ):
        if flag not in flags:
            flags = f"{flags} {flag}={val}"
    os.environ["XLA_FLAGS"] = flags.strip()


def cpu_fingerprint() -> str:
    """Short stable id of the host CPU's feature set (x86: the
    /proc/cpuinfo flags line; elsewhere the platform processor string)."""
    try:
        with open("/proc/cpuinfo") as f:
            flags = next(
                (line for line in f if line.startswith("flags")), ""
            )
    except OSError:
        import platform

        flags = platform.processor()
    return hashlib.sha1(flags.encode()).hexdigest()[:12]
