"""Host-environment helpers shared by the test suite and benchmark runners.

XLA:CPU's persistent compile cache stores AOT executables whose code paths
assume the COMPILING host's CPU features, while jax's cache key does not
include them — loading an entry compiled on a different physical CPU warns
"could lead to execution errors such as SIGILL" and sporadically delivers
exactly that. Environments that land on heterogeneous machines (this VM
does) must therefore fingerprint the cache directory per CPU so a migration
misses the cache instead of executing foreign machine code.
"""

from __future__ import annotations

import hashlib
import os


def xla_flag_supported(flag: str) -> bool:
    """Whether this jaxlib's XLA knows ``flag`` (name with or without the
    leading ``--``). XLA F-aborts the WHOLE process on any unknown flag in
    XLA_FLAGS (parse_flags_from_env.cc), so a flag name must never be set
    speculatively: probe the jaxlib binary — registered flag names are
    embedded as strings — before appending anything."""
    return xla_flags_supported([flag])[flag]


def xla_flags_supported(flags) -> dict:
    """Batch form of `xla_flag_supported`: {flag: bool} in ONE scan of the
    jaxlib binaries. The negative case (old jaxlib missing every probed
    flag — exactly the environment the guard exists for) must read the
    multi-hundred-MB jaxlib tree once, not once per flag."""
    names = {f.lstrip("-").split("=")[0].encode(): f for f in flags}
    cache = xla_flags_supported.__dict__.setdefault("_cache", {})
    missing = [n for n in names if n not in cache]
    if missing:
        cache.update(_jaxlib_binaries_contain(missing))
    return {f: cache[n] for n, f in names.items()}


def _jaxlib_binaries_contain(needles) -> dict:
    import glob
    import mmap

    out = {n: False for n in needles}
    try:
        import jaxlib

        root = os.path.dirname(jaxlib.__file__)
    except Exception:
        return out
    pending = set(out)
    for path in sorted(glob.glob(os.path.join(root, "**", "*.so"),
                                 recursive=True),
                       key=os.path.getsize, reverse=True):
        try:
            with open(path, "rb") as f:
                with mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ) as m:
                    for n in list(pending):
                        if m.find(n) != -1:
                            out[n] = True
                            pending.discard(n)
        except (OSError, ValueError):
            continue
        if not pending:
            break
    return out


def ensure_collective_timeout_flags(warn_stuck_s: int = 120,
                                    terminate_s: int = 1200) -> None:
    """Append XLA:CPU collective-timeout flags to XLA_FLAGS unless the
    caller already set them (each flag guarded by its own name, so a
    user-supplied value for one is never clobbered by the other's
    default). Must run before the first jax backend init.

    Why: 8 virtual devices time-share this box's single core; inside a
    large mesh program one participant thread can legitimately be starved
    past XLA:CPU's default 40 s collective rendezvous termination
    timeout, which F-aborts the whole process mid-collective (observed:
    all_gather rendezvous abort in the SF0.5 sweep's mesh tier).

    Each flag is probed against the installed jaxlib first: on older
    jaxlibs (0.4.x) these flags do not exist and XLA aborts every process
    that inherits them — strictly worse than the starvation they guard."""
    flags = os.environ.get("XLA_FLAGS", "")
    wanted = {
        "--xla_cpu_collective_call_warn_stuck_timeout_seconds":
            warn_stuck_s,
        "--xla_cpu_collective_call_terminate_timeout_seconds":
            terminate_s,
    }
    supported = xla_flags_supported(
        [f for f in wanted if f not in flags]
    )
    for flag, ok in supported.items():
        if ok:
            flags = f"{flags} {flag}={wanted[flag]}"
    os.environ["XLA_FLAGS"] = flags.strip()


def cpu_fingerprint() -> str:
    """Short stable id of the host CPU's feature set (x86: the
    /proc/cpuinfo flags line; elsewhere the platform processor string)."""
    try:
        with open("/proc/cpuinfo") as f:
            flags = next(
                (line for line in f if line.startswith("flags")), ""
            )
    except OSError:
        import platform

        flags = platform.processor()
    return hashlib.sha1(flags.encode()).hexdigest()[:12]
