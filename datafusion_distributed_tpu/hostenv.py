"""Host-environment helpers shared by the test suite and benchmark runners.

XLA:CPU's persistent compile cache stores AOT executables whose code paths
assume the COMPILING host's CPU features, while jax's cache key does not
include them — loading an entry compiled on a different physical CPU warns
"could lead to execution errors such as SIGILL" and sporadically delivers
exactly that. Environments that land on heterogeneous machines (this VM
does) must therefore fingerprint the cache directory per CPU so a migration
misses the cache instead of executing foreign machine code.
"""

from __future__ import annotations

import hashlib


def cpu_fingerprint() -> str:
    """Short stable id of the host CPU's feature set (x86: the
    /proc/cpuinfo flags line; elsewhere the platform processor string)."""
    try:
        with open("/proc/cpuinfo") as f:
            flags = next(
                (line for line in f if line.startswith("flags")), ""
            )
    except OSError:
        import platform

        flags = platform.processor()
    return hashlib.sha1(flags.encode()).hexdigest()[:12]
