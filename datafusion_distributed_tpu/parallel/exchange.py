"""Exchange collectives: the TPU-native data plane.

The reference moves rows between stage tasks over gRPC/Arrow-Flight streams
(`NetworkShuffleExec`/`NetworkCoalesceExec`/`NetworkBroadcastExec`,
`/root/reference/src/execution_plans/`, and the WorkerConnectionPool demux,
SURVEY.md §2.10). On a TPU pod the equivalent fabric is ICI, and the idiomatic
primitive set is XLA collectives inside one `shard_map`ped program:

    hash shuffle (N:M re-shard)  -> `lax.all_to_all`   (NetworkShuffleExec)
    broadcast (replicate build)  -> `lax.all_gather`   (NetworkBroadcastExec)
    coalesce (N -> 1 concat)     -> `lax.all_gather`   (NetworkCoalesceExec)

Everything here runs *inside* shard_map: `table` holds this task's local
shard (padded capacity C, traced num_rows), and `axis` is the mesh axis name.
Whole multi-stage queries therefore compile into ONE XLA program where
compute fuses around the collectives — there is no per-stage host round-trip
at all inside a mesh (the reference's per-batch Flight encode/decode loop
disappears).

Each function returns (table, overflow_flag): the fixed per-destination
buffer bound replaces the reference's 64 MiB connection buffer budget
(worker_connection_pool.rs backpressure); exceeding it is reported, and the
planner re-plans with a bigger bound — the pending->ready analogue.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from datafusion_distributed_tpu.ops.hash import hash_columns
from datafusion_distributed_tpu.ops.table import Column, Table


def shuffle_exchange(
    table: Table,
    key_names: Sequence[str],
    axis: str,
    num_tasks: int,
    per_dest_capacity: int,
) -> tuple[Table, jnp.ndarray]:
    """Hash-repartition rows across all tasks of the mesh axis.

    Row -> destination task = hash(keys) % num_tasks (the arithmetic of the
    reference's hash RepartitionExec + partition-range reads,
    `network_shuffle.rs`: consumer i reads partition range [i*P,(i+1)*P) of
    every producer — here the all_to_all does exactly that swap in one ICI
    step). Output capacity = num_tasks * per_dest_capacity.
    """
    cap = table.capacity
    live = table.row_mask()
    cols = [table.column(k).data for k in key_names]
    valids = [table.column(k).validity for k in key_names]
    h = hash_columns(cols, valids)
    dest = (h % np.uint32(num_tasks)).astype(jnp.int32)
    dest = jnp.where(live, dest, num_tasks)  # dead rows go nowhere

    # position of each row within its destination bucket
    onehot = (
        dest[:, None] == jnp.arange(num_tasks, dtype=jnp.int32)[None, :]
    )  # [C, T]
    within = jnp.cumsum(onehot.astype(jnp.int32), axis=0) - onehot.astype(
        jnp.int32
    )
    pos_in_bucket = jnp.sum(within * onehot, axis=1)  # [C]
    bucket_counts = jnp.sum(onehot, axis=0, dtype=jnp.int32)  # [T]
    overflow = jnp.any(bucket_counts > per_dest_capacity)

    # scatter rows into the [T, per_dest_capacity] send buffer
    flat_idx = dest * per_dest_capacity + jnp.minimum(
        pos_in_bucket, per_dest_capacity - 1
    )
    flat_idx = jnp.where(
        (dest < num_tasks) & (pos_in_bucket < per_dest_capacity),
        flat_idx,
        num_tasks * per_dest_capacity,  # dropped
    )

    new_cols = []
    for col in table.columns:
        send = jnp.zeros(
            num_tasks * per_dest_capacity, dtype=col.data.dtype
        ).at[flat_idx].set(col.data, mode="drop")
        send = send.reshape(num_tasks, per_dest_capacity)
        recv = jax.lax.all_to_all(send, axis, 0, 0, tiled=False)
        # recv: [T_src, per_dest_capacity] rows this task received
        data = recv.reshape(num_tasks * per_dest_capacity)
        if col.validity is not None:
            vsend = jnp.zeros(
                num_tasks * per_dest_capacity, dtype=jnp.bool_
            ).at[flat_idx].set(col.validity, mode="drop")
            vrecv = jax.lax.all_to_all(
                vsend.reshape(num_tasks, per_dest_capacity), axis, 0, 0
            )
            validity = vrecv.reshape(num_tasks * per_dest_capacity)
        else:
            validity = None
        new_cols.append(Column(data, validity, col.dtype, col.dictionary))

    # received per-source counts -> liveness mask + compaction
    my_counts = jax.lax.all_to_all(
        bucket_counts.reshape(num_tasks, 1), axis, 0, 0
    ).reshape(num_tasks)  # rows from each source task
    local = jnp.arange(per_dest_capacity, dtype=jnp.int32)
    live_mask = (local[None, :] < my_counts[:, None]).reshape(-1)
    out = Table(table.names, tuple(new_cols), jnp.sum(my_counts))
    out = _compact_with_mask(out, live_mask)
    overflow = jax.lax.pmax(overflow.astype(jnp.int32), axis) > 0
    return out, overflow


def broadcast_exchange(table: Table, axis: str, num_tasks: int) -> Table:
    """Replicate every task's rows to all tasks (build sides of broadcast
    joins — the reference's BroadcastExec + NetworkBroadcastExec pair)."""
    new_cols = []
    for col in table.columns:
        g = jax.lax.all_gather(col.data, axis)  # [T, C]
        data = g.reshape(-1)
        if col.validity is not None:
            validity = jax.lax.all_gather(col.validity, axis).reshape(-1)
        else:
            validity = None
        new_cols.append(Column(data, validity, col.dtype, col.dictionary))
    counts = jax.lax.all_gather(table.num_rows, axis)  # [T]
    cap = table.capacity
    local = jnp.arange(cap, dtype=jnp.int32)
    live_mask = (local[None, :] < counts[:, None]).reshape(-1)
    out = Table(table.names, tuple(new_cols), jnp.sum(counts))
    return _compact_with_mask(out, live_mask)


def coalesce_exchange(table: Table, axis: str, num_tasks: int) -> Table:
    """N tasks -> one logical table (replicated on every task; the consumer
    stage usually runs at task count 1, others see identical data — SPMD).
    The reference's NetworkCoalesceExec concatenates producer task streams."""
    return broadcast_exchange(table, axis, num_tasks)


def _compact_with_mask(table: Table, keep: jnp.ndarray) -> Table:
    """Pack rows where keep==True to the front (keep already excludes
    padding)."""
    cap = table.capacity
    (idx,) = jnp.nonzero(keep, size=cap, fill_value=0)
    n = jnp.sum(keep, dtype=jnp.int32)
    cols = tuple(c.gather(idx) for c in table.columns)
    return Table(table.names, cols, n)


def partition_table(table: Table, num_parts: int) -> list[Table]:
    """Host-side: split a Table into row-range slices with equal padded
    capacity (the scale_up_leaf_node analogue for in-memory data)."""
    n = int(table.num_rows)
    per = (n + num_parts - 1) // num_parts if num_parts else 0
    from datafusion_distributed_tpu.ops.table import round_up_pow2

    cap = max(round_up_pow2(max(per, 1)), 8)
    out = []
    for i in range(num_parts):
        lo = min(i * per, n)
        hi = min(lo + per, n)
        cols = {}
        for name, col in zip(table.names, table.columns):
            data = jnp.zeros(cap, dtype=col.data.dtype)
            data = data.at[: hi - lo].set(col.data[lo:hi])
            validity = None
            if col.validity is not None:
                validity = jnp.zeros(cap, dtype=jnp.bool_)
                validity = validity.at[: hi - lo].set(col.validity[lo:hi])
            cols[name] = Column(data, validity, col.dtype, col.dictionary)
        out.append(Table(table.names, tuple(cols.values()), hi - lo))
    return out
