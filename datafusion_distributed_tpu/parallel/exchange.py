"""Exchange collectives: the TPU-native data plane.

The reference moves rows between stage tasks over gRPC/Arrow-Flight streams
(`NetworkShuffleExec`/`NetworkCoalesceExec`/`NetworkBroadcastExec`,
`/root/reference/src/execution_plans/`, and the WorkerConnectionPool demux,
SURVEY.md §2.10). On a TPU pod the equivalent fabric is ICI, and the idiomatic
primitive set is XLA collectives inside one `shard_map`ped program:

    hash shuffle (N:M re-shard)  -> `lax.all_to_all`   (NetworkShuffleExec)
    broadcast (replicate build)  -> `lax.all_gather`   (NetworkBroadcastExec)
    coalesce (N -> 1 concat)     -> `lax.all_gather`   (NetworkCoalesceExec)

Everything here runs *inside* shard_map: `table` holds this task's local
shard (padded capacity C, traced num_rows), and `axis` is the mesh axis name.
Whole multi-stage queries therefore compile into ONE XLA program where
compute fuses around the collectives — there is no per-stage host round-trip
at all inside a mesh (the reference's per-batch Flight encode/decode loop
disappears).

Each function returns (table, overflow_flag): the fixed per-destination
buffer bound replaces the reference's 64 MiB connection buffer budget
(worker_connection_pool.rs backpressure); exceeding it is reported, and the
planner re-plans with a bigger bound — the pending->ready analogue.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from datafusion_distributed_tpu.ops.hash import hash_columns
from datafusion_distributed_tpu.ops.table import Column, Table


def shuffle_exchange(
    table: Table,
    key_names: Sequence[str],
    axis: str,
    num_tasks: int,
    per_dest_capacity: int,
) -> tuple[Table, jnp.ndarray]:
    """Hash-repartition rows across all tasks of the mesh axis.

    Row -> destination task = hash(keys) % num_tasks (the arithmetic of the
    reference's hash RepartitionExec + partition-range reads,
    `network_shuffle.rs`: consumer i reads partition range [i*P,(i+1)*P) of
    every producer — here the all_to_all does exactly that swap in one ICI
    step). Output capacity = num_tasks * per_dest_capacity.

    Bucketing is SORT-based (one stable argsort by destination), not the
    O(C*T) one-hot cumsum matrix, so cost is ~flat in task count; and the
    wire payload is ONE fused all_to_all per element-width class (every
    column bit-cast to uint lanes and stacked), not one collective per
    column — latency is ~flat in column count.
    """
    live = table.row_mask()
    cols = [table.column(k).data for k in key_names]
    valids = [table.column(k).validity for k in key_names]
    h = hash_columns(cols, valids)
    dest = (h % np.uint32(num_tasks)).astype(jnp.int32)
    dest = jnp.where(live, dest, num_tasks)  # dead rows go nowhere
    return _route_by_dest(table, dest, axis, num_tasks, per_dest_capacity)


def _route_by_dest(
    table: Table,
    dest: jnp.ndarray,
    axis: str,
    num_tasks: int,
    per_dest_capacity: int,
) -> tuple[Table, jnp.ndarray]:
    """Move each live row to mesh task `dest[row]` (dead rows carry
    dest == num_tasks). Shared routing core of the hash and range shuffles:
    sort-based bucketing + ONE fused all_to_all per element-width class."""
    cap = table.capacity

    # sort-based bucketing: rows grouped by destination, dead rows last
    order = jnp.argsort(dest, stable=True).astype(jnp.int32)  # [C]
    sorted_dest = dest[order]
    # start offset of each destination bucket in the sorted order
    starts = jnp.searchsorted(
        sorted_dest, jnp.arange(num_tasks + 1, dtype=jnp.int32)
    ).astype(jnp.int32)  # [T+1]
    bucket_counts = starts[1:] - starts[:-1]  # [T]
    overflow = jnp.any(bucket_counts > per_dest_capacity)
    ranks = jnp.arange(cap, dtype=jnp.int32) - starts[
        jnp.clip(sorted_dest, 0, num_tasks)
    ]  # position within own bucket, for rows in sorted order
    flat_idx = jnp.where(
        (sorted_dest < num_tasks) & (ranks < per_dest_capacity),
        sorted_dest * per_dest_capacity
        + jnp.minimum(ranks, per_dest_capacity - 1),
        num_tasks * per_dest_capacity,  # dropped
    )

    # fuse every column (and validity lane) into stacked uint payloads,
    # grouped by element width; ONE all_to_all per width class
    lanes: list[tuple[int, jnp.ndarray]] = []  # (width, u-lane in sorted order)
    layout: list[tuple[str, int, int]] = []  # (kind, col_idx, lane_idx)
    for ci, col in enumerate(table.columns):
        u = _bitcast_unsigned(col.data)[order]
        layout.append(("data", ci, len(lanes)))
        lanes.append((u.dtype.itemsize, u))
        if col.validity is not None:
            v = col.validity[order].astype(jnp.uint8)
            layout.append(("valid", ci, len(lanes)))
            lanes.append((1, v))

    recv_by_lane: dict[int, jnp.ndarray] = {}
    for width in sorted({w for w, _ in lanes}):
        idxs = [i for i, (w, _) in enumerate(lanes) if w == width]
        stack = jnp.stack([lanes[i][1] for i in idxs], axis=1)  # [C, L]
        nl = len(idxs)
        send = jnp.zeros(
            (num_tasks * per_dest_capacity, nl), dtype=stack.dtype
        ).at[flat_idx].set(stack, mode="drop")
        send = send.reshape(num_tasks, per_dest_capacity, nl)
        recv = jax.lax.all_to_all(send, axis, 0, 0, tiled=False)
        recv = recv.reshape(num_tasks * per_dest_capacity, nl)
        for li, i in enumerate(idxs):
            recv_by_lane[i] = recv[:, li]

    new_cols = []
    data_of: dict[int, jnp.ndarray] = {}
    valid_of: dict[int, jnp.ndarray] = {}
    for kind, ci, lane_idx in layout:
        if kind == "data":
            data_of[ci] = recv_by_lane[lane_idx]
        else:
            valid_of[ci] = recv_by_lane[lane_idx].astype(jnp.bool_)
    for ci, col in enumerate(table.columns):
        data = _bitcast_back(data_of[ci], col.data.dtype)
        validity = valid_of.get(ci)
        new_cols.append(Column(data, validity, col.dtype, col.dictionary))

    # received per-source counts -> liveness mask + compaction
    my_counts = jax.lax.all_to_all(
        bucket_counts.reshape(num_tasks, 1), axis, 0, 0
    ).reshape(num_tasks)  # rows from each source task
    local = jnp.arange(per_dest_capacity, dtype=jnp.int32)
    live_mask = (local[None, :] < my_counts[:, None]).reshape(-1)
    out = Table(table.names, tuple(new_cols), jnp.sum(my_counts))
    out = _compact_with_mask(out, live_mask)
    overflow = jax.lax.pmax(overflow.astype(jnp.int32), axis) > 0
    return out, overflow


def _bitcast_unsigned(a: jnp.ndarray) -> jnp.ndarray:
    """Bit-preserving view as a same-width unsigned integer lane."""
    w = a.dtype.itemsize
    if a.dtype == jnp.bool_:
        return a.astype(jnp.uint8)
    target = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}[w]
    if a.dtype == target:
        return a
    return a.view(target)


def _bitcast_back(u: jnp.ndarray, dtype) -> jnp.ndarray:
    if dtype == jnp.bool_:
        return u.astype(jnp.bool_)
    if u.dtype == dtype:
        return u
    return u.view(dtype)


def _order_encode(col: Column, ascending: bool, nulls_first: bool):
    """Order-isomorphic unsigned encoding of a sort-key column: for the
    TRUE sort order (incl. direction and null placement), a < b implies
    e(a) <= e(b). Nulls map to the dtype's extremes, so a null can only
    FALSE-TIE with an extreme value — which merely coarsens range
    partitioning (ties route to one task), never reorders. String columns
    compare by dictionary code (dictionaries are sorted)."""
    d = col.data
    nan_mask = None
    if d.dtype == jnp.bool_:
        u = d.astype(jnp.uint32)
    elif jnp.issubdtype(d.dtype, jnp.floating):
        w = d.dtype.itemsize
        ut = {2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}[w]
        b = d.view(ut)
        sign = jnp.asarray(1, ut) << (8 * w - 1)
        # IEEE radix trick: negatives flip all bits, positives flip sign
        u = jnp.where((b & sign) != 0, ~b, b ^ sign)
        nan_mask = jnp.isnan(d)
    elif jnp.issubdtype(d.dtype, jnp.signedinteger):
        w = d.dtype.itemsize
        ut = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}[w]
        u = d.view(ut) ^ (jnp.asarray(1, ut) << (8 * w - 1))
    else:
        u = d
    if not ascending:
        u = ~u
    if nan_mask is not None:
        # the local sort kernel (argsort) and the host regroup both place
        # NaN LAST regardless of direction; route it the same way (after
        # the direction flip, before the null override)
        u = jnp.where(nan_mask, ~jnp.zeros((), u.dtype), u)
    if col.validity is not None:
        lo = jnp.zeros((), u.dtype)
        hi = ~jnp.zeros((), u.dtype)
        u = jnp.where(col.validity, u, lo if nulls_first else hi)
    return u


def range_shuffle_exchange(
    table: Table,
    keys,  # list[ops.sort.SortKey]
    axis: str,
    num_tasks: int,
    per_dest_capacity: int,
    samples_per_task: int = 64,
) -> tuple[Table, jnp.ndarray]:
    """Range-partition rows across the mesh axis by the composite sort key
    (classic distributed sample sort): after this exchange + a LOCAL sort,
    concatenating task outputs in axis order IS the global sort order — no
    device ever holds or re-sorts the full dataset, unlike the previous
    coalesce-then-sort plan whose every device sorted all T*C gathered
    rows. The splitters come from an all_gathered per-task sample (the
    small gather is the only global communication besides the row routing
    itself, which rides the same fused all_to_all as the hash shuffle).
    """
    cap = table.capacity
    live = table.row_mask()
    enc = [
        _order_encode(table.column(k.name), k.ascending, k.nulls_first)
        for k in keys
    ]

    # --- per-task sample: evenly spaced live rows -----------------------
    s = min(samples_per_task, cap)
    n = table.num_rows
    pos = (jnp.arange(s, dtype=jnp.int32) * jnp.maximum(n, 1)) // s
    pos = jnp.clip(pos, 0, cap - 1)
    samp_live = jnp.arange(s, dtype=jnp.int32) < n
    samp = [e[pos] for e in enc]

    # gather all tasks' samples: [T*s] per key lane, dead samples sort last
    g_live = jax.lax.all_gather(samp_live, axis).reshape(-1)
    g = [jax.lax.all_gather(e, axis).reshape(-1) for e in samp]
    order = jnp.argsort(~g_live, stable=True).astype(jnp.int32)
    for lane in reversed(g):
        # stable composition, least-significant first; dead-last applied
        # as the final (most significant) pass
        order = order[jnp.argsort(lane[order], stable=True)]
    order = order[jnp.argsort(~g_live[order], stable=True)]
    total_live = jnp.sum(g_live.astype(jnp.int32))

    # T-1 splitters at the live-sample quantiles
    ranks = (
        jnp.arange(1, num_tasks, dtype=jnp.int32) * total_live
    ) // num_tasks
    ranks = jnp.clip(ranks, 0, jnp.maximum(total_live - 1, 0))
    split_idx = order[ranks]  # [T-1] indices into gathered samples
    splitters = [lane[split_idx] for lane in g]  # per key: [T-1]

    # --- dest = number of splitters <= row (lexicographic) --------------
    dest = jnp.zeros(cap, dtype=jnp.int32)
    for j in range(num_tasks - 1):
        gt = jnp.zeros(cap, dtype=jnp.bool_)
        eq = jnp.ones(cap, dtype=jnp.bool_)
        for lane, spl in zip(enc, splitters):
            sj = spl[j]
            gt = gt | (eq & (lane > sj))
            eq = eq & (lane == sj)
        dest = dest + (gt | eq).astype(jnp.int32)
    dest = jnp.where(total_live > 0, dest, 0)
    dest = jnp.where(live, dest, num_tasks)  # dead rows go nowhere
    return _route_by_dest(table, dest, axis, num_tasks, per_dest_capacity)


def broadcast_exchange(table: Table, axis: str, num_tasks: int) -> Table:
    """Replicate every task's rows to all tasks (build sides of broadcast
    joins — the reference's BroadcastExec + NetworkBroadcastExec pair)."""
    new_cols = []
    for col in table.columns:
        g = jax.lax.all_gather(col.data, axis)  # [T, C]
        data = g.reshape(-1)
        if col.validity is not None:
            validity = jax.lax.all_gather(col.validity, axis).reshape(-1)
        else:
            validity = None
        new_cols.append(Column(data, validity, col.dtype, col.dictionary))
    counts = jax.lax.all_gather(table.num_rows, axis)  # [T]
    cap = table.capacity
    local = jnp.arange(cap, dtype=jnp.int32)
    live_mask = (local[None, :] < counts[:, None]).reshape(-1)
    out = Table(table.names, tuple(new_cols), jnp.sum(counts))
    return _compact_with_mask(out, live_mask)


def coalesce_exchange(table: Table, axis: str, num_tasks: int) -> Table:
    """N tasks -> one logical table (replicated on every task; the consumer
    stage usually runs at task count 1, others see identical data — SPMD).
    The reference's NetworkCoalesceExec concatenates producer task streams."""
    return broadcast_exchange(table, axis, num_tasks)


def group_coalesce_exchange(
    table: Table, axis: str, num_tasks: int, num_consumers: int
) -> Table:
    """True N:M coalesce: consumer task j receives the CONTIGUOUS producer
    group [j*g, (j+1)*g), g = ceil(N/M) — the reference's
    `network_coalesce.rs:83-99` div_ceil group arithmetic; short groups
    contribute empty streams and tasks >= M end up empty.

    Implementation: g ppermute rounds (round r routes producer j*g+r ->
    consumer j — an injective permutation, so it rides ICI point-to-point
    links). Peak buffer is g*C per task instead of the all_gather's T*C, so
    memory no longer scales with total task count when M > 1.
    """
    g = -(-num_tasks // num_consumers)  # div_ceil
    if g == 1:
        return table  # M >= N: every producer is its own (only) group member
    me = jax.lax.axis_index(axis)
    cap = table.capacity

    recv_parts: list[Table] = []
    for r in range(g):
        # producer p = j*g + r sends to consumer j (skip out-of-range p)
        perm = []
        used_src = set()
        for j in range(num_consumers):
            src = j * g + r
            if src < num_tasks:
                perm.append((src, j))
                used_src.add(src)
        # ppermute requires nothing of unlisted tasks; their recv is zeros
        part_cols = []
        for col in table.columns:
            data = jax.lax.ppermute(col.data, axis, perm)
            validity = (
                jax.lax.ppermute(col.validity, axis, perm)
                if col.validity is not None else None
            )
            part_cols.append(Column(data, validity, col.dtype, col.dictionary))
        nrows = jax.lax.ppermute(table.num_rows, axis, perm)
        # tasks that received nothing this round hold zeroed buffers with
        # nrows == 0 (ppermute zero-fills unaddressed receivers)
        recv_parts.append(Table(table.names, tuple(part_cols), nrows))

    from datafusion_distributed_tpu.ops.table import concat_tables

    out = concat_tables(recv_parts, capacity=g * cap)
    # tasks >= num_consumers received no group: force empty
    is_consumer = me < num_consumers
    out = Table(
        out.names, out.columns,
        jnp.where(is_consumer, out.num_rows, 0).astype(jnp.int32),
    )
    return out


def _compact_with_mask(table: Table, keep: jnp.ndarray) -> Table:
    """Pack rows where keep==True to the front (keep already excludes
    padding)."""
    cap = table.capacity
    (idx,) = jnp.nonzero(keep, size=cap, fill_value=0)
    n = jnp.sum(keep, dtype=jnp.int32)
    cols = tuple(c.gather(idx) for c in table.columns)
    return Table(table.names, cols, n)


def partition_table(table: Table, num_parts: int) -> list[Table]:
    """Host-side: split a Table into row-range slices with equal padded
    capacity (the scale_up_leaf_node analogue for in-memory data)."""
    n = int(table.num_rows)
    per = (n + num_parts - 1) // num_parts if num_parts else 0
    from datafusion_distributed_tpu.ops.table import round_up_pow2

    cap = max(round_up_pow2(max(per, 1)), 8)
    out = []
    for i in range(num_parts):
        lo = min(i * per, n)
        hi = min(lo + per, n)
        cols = {}
        for name, col in zip(table.names, table.columns):
            data = jnp.zeros(cap, dtype=col.data.dtype)
            data = data.at[: hi - lo].set(col.data[lo:hi])
            validity = None
            if col.validity is not None:
                validity = jnp.zeros(cap, dtype=jnp.bool_)
                validity = validity.at[: hi - lo].set(col.validity[lo:hi])
            cols[name] = Column(data, validity, col.dtype, col.dictionary)
        out.append(Table(table.names, tuple(cols.values()), hi - lo))
    return out
