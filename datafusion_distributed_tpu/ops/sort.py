"""Sorting kernels: multi-key lexicographic sort, limit, top-k.

The reference uses DataFusion's `SortExec`/`SortPreservingMergeExec`
(SURVEY.md L0; the distributed planner treats a sort above a stage as a
coalesce point, `inject_network_boundaries.rs` sort/coalesce case). XLA has a
high-quality parallel sort, so the TPU design is: stable argsort per key from
least- to most-significant (radix-style composition), with dead/padding rows
forced to the tail so `num_rows` semantics survive.

String keys sort by dictionary code (dictionaries are sorted => code order is
lexicographic). Nulls order via a separate flag pass (no in-band sentinel).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from datafusion_distributed_tpu.ops.table import Table


@dataclass(frozen=True)
class SortKey:
    name: str
    ascending: bool = True
    nulls_first: bool = False


def sort_permutation(table: Table, keys: list[SortKey]) -> jnp.ndarray:
    """[capacity] permutation: live rows in key order first, dead rows last."""
    cap = table.capacity
    perm = jnp.arange(cap, dtype=jnp.int32)
    # Least-significant key first; stable sorts compose lexicographically.
    for key in reversed(keys):
        col = table.column(key.name)
        vals = col.data
        if vals.dtype == jnp.bool_:
            vals = vals.astype(jnp.int32)
        if not key.ascending:
            if jnp.issubdtype(vals.dtype, jnp.floating):
                vals = -vals
            else:
                # avoid signed overflow on INT_MIN: flip via complement
                vals = ~vals if jnp.issubdtype(vals.dtype, jnp.integer) else -vals
        perm = perm[jnp.argsort(vals[perm], stable=True)]
        if col.validity is not None:
            # null-flag pass dominates the value pass for this key
            flag = (
                col.validity if key.nulls_first else ~col.validity
            )  # False sorts first
            perm = perm[jnp.argsort(flag[perm].astype(jnp.int32), stable=True)]
    # Dead rows to the tail (most significant pass of all).
    dead = ~table.row_mask()
    perm = perm[jnp.argsort(dead[perm].astype(jnp.int32), stable=True)]
    return perm


def sort_table(table: Table, keys: list[SortKey]) -> Table:
    return table.gather(sort_permutation(table, keys), table.num_rows)


def limit_table(table: Table, fetch, skip=0) -> Table:
    """LIMIT fetch OFFSET skip over an ordered table (jit-safe)."""
    cap = table.capacity
    skip = jnp.asarray(skip, dtype=jnp.int32)
    fetch = jnp.asarray(fetch, dtype=jnp.int32)
    remaining = jnp.maximum(table.num_rows - skip, 0)
    n = jnp.minimum(remaining, fetch)
    idx = jnp.clip(jnp.arange(cap, dtype=jnp.int32) + skip, 0, cap - 1)
    return table.gather(idx, n)
