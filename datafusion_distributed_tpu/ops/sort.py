"""Sorting kernels: multi-key lexicographic sort, limit, top-k.

The reference uses DataFusion's `SortExec`/`SortPreservingMergeExec`
(SURVEY.md L0; the distributed planner treats a sort above a stage as a
coalesce point, `inject_network_boundaries.rs` sort/coalesce case). XLA has a
high-quality parallel sort, so the TPU design is: stable argsort per key from
least- to most-significant (radix-style composition), with dead/padding rows
forced to the tail so `num_rows` semantics survive.

String keys sort by dictionary code (dictionaries are sorted => code order is
lexicographic). Nulls order via a separate flag pass (no in-band sentinel).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from datafusion_distributed_tpu.ops.table import Table


@dataclass(frozen=True)
class SortKey:
    name: str
    ascending: bool = True
    nulls_first: bool = False


def sort_permutation(table: Table, keys: list[SortKey]) -> jnp.ndarray:
    """[capacity] permutation: live rows in key order first, dead rows last.

    ONE `lax.sort` call with a lexicographic operand list — most-significant
    first: [dead-row flag, key1 null flag, key1 values, key2 ...] — instead
    of composing per-key stable argsorts. The composed form paid up to
    2 sorts per key + a dead-row pass over the FULL padded capacity (a
    2-key sort over a 1M-capacity aggregate output ran 5 million-row
    argsorts: ~2.5 s of TPC-H q3's 2.8 s wall on the CPU tier); the fused
    form pays exactly one."""
    import jax

    cap = table.capacity
    operands: list[jnp.ndarray] = [~table.row_mask()]  # live rows first
    for key in keys:
        col = table.column(key.name)
        if col.validity is not None:
            # null placement dominates this key's value order
            flag = col.validity if key.nulls_first else ~col.validity
            operands.append(flag)  # False sorts first
        vals = col.data
        if vals.dtype == jnp.bool_:
            vals = vals.astype(jnp.int32)
        if not key.ascending:
            if jnp.issubdtype(vals.dtype, jnp.floating):
                vals = -vals
            else:
                # avoid signed overflow on INT_MIN: flip via complement
                vals = ~vals if jnp.issubdtype(vals.dtype, jnp.integer) else -vals
        operands.append(vals)
    perm0 = jnp.arange(cap, dtype=jnp.int32)
    out = jax.lax.sort(
        tuple(operands) + (perm0,), num_keys=len(operands), is_stable=True
    )
    return out[-1]


def sort_table(table: Table, keys: list[SortKey]) -> Table:
    return table.gather(sort_permutation(table, keys), table.num_rows)


def limit_table(table: Table, fetch, skip=0) -> Table:
    """LIMIT fetch OFFSET skip over an ordered table (jit-safe)."""
    cap = table.capacity
    skip = jnp.asarray(skip, dtype=jnp.int32)
    fetch = jnp.asarray(fetch, dtype=jnp.int32)
    remaining = jnp.maximum(table.num_rows - skip, 0)
    n = jnp.minimum(remaining, fetch)
    idx = jnp.clip(jnp.arange(cap, dtype=jnp.int32) + skip, 0, cap - 1)
    return table.gather(idx, n)
