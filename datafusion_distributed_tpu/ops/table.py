"""Device-resident columnar batches (the Arrow `RecordBatch` analogue).

The reference engine streams Arrow `RecordBatch`es between operators and across
the network (see `/root/reference/src/worker/impl_execute_task.rs` Flight
encode loop). On TPU, XLA requires static shapes, so the equivalent unit here
is a **padded** columnar batch:

- every column is a fixed-`capacity` device array (power-of-two friendly),
- the number of live rows is a *traced* scalar ``num_rows`` (so filters and
  joins can change it under ``jit`` without recompiling),
- rows at index >= num_rows are garbage and masked out by ``row_mask()``,
- null semantics ride in per-column validity bitmaps (bool arrays),
- strings live as int32 dictionary codes; the dictionaries themselves stay on
  the host in a registry keyed by small ints so they never enter jit cache
  keys (the analogue of the reference's dictionary GC before the wire,
  `impl_execute_task.rs:244-274`: the device only ever sees compact codes).

`Table` and `Column` are registered pytrees, so they flow through ``jit``,
``shard_map``, ``lax.scan`` etc. unchanged.
"""

from __future__ import annotations

import itertools
import os
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from datafusion_distributed_tpu.schema import DataType, Field, Schema

# ---------------------------------------------------------------------------
# Host-side dictionary registry
# ---------------------------------------------------------------------------

import threading
import weakref

_DICT_COUNTER = itertools.count()
# Weak registry: a Dictionary lives as long as some Column references it
# (the analogue of the reference's dictionary GC before the wire — unused
# dictionaries must not accumulate in a long-running worker process).
_DICT_REGISTRY: "weakref.WeakValueDictionary[int, Dictionary]" = (
    weakref.WeakValueDictionary()
)
# (sorted input dict ids) -> union Dictionary; see unify_dictionaries
_UNION_DICT_CACHE: dict = {}
_DICT_CACHE_LOCK = threading.Lock()


_PIN_DEPTH = 0  # guarded by _DICT_CACHE_LOCK
_PINNED: dict = {}  # id(cache) -> set of keys untouchable by eviction


import contextlib


@contextlib.contextmanager
def pin_dictionary_caches():
    """Entries touched while ANY pin context is active are exempt from LRU
    eviction until the last context exits. IsolatedArmExec wraps its probe +
    lax.cond branch traces in this: LRU recency alone cannot protect an
    entry from heavy cross-thread churn between the two traces, and a
    re-minted Dictionary diverges the branches' pytree metadata (loud trace
    error). Nesting-safe; caches may transiently exceed their cap while
    everything in them is pinned."""
    global _PIN_DEPTH
    with _DICT_CACHE_LOCK:
        _PIN_DEPTH += 1
    try:
        yield
    finally:
        with _DICT_CACHE_LOCK:
            _PIN_DEPTH -= 1
            if _PIN_DEPTH == 0:
                _PINNED.clear()


def lru_get_or_create(cache: dict, key, mint, cap: int):
    """Thread-safe get-or-mint with LRU eviction (python dicts preserve
    insertion order; move-to-end on hit). Shared by the dictionary
    memoization caches: identity stability across re-traces requires that
    a hit NEVER returns a different object than a concurrent or recent
    call for the same key, and that eviction only removes cold entries
    (never one pinned by an in-progress trace, see pin_dictionary_caches)."""
    with _DICT_CACHE_LOCK:
        if key in cache:
            val = cache.pop(key)
            cache[key] = val  # move to end = most recently used
        else:
            val = mint()
            cache[key] = val
        if _PIN_DEPTH > 0:
            _PINNED.setdefault(id(cache), set()).add(key)
        pinned = _PINNED.get(id(cache), ())
        while len(cache) > cap:
            victim = next((k for k in cache if k not in pinned), None)
            if victim is None:
                break  # everything live-pinned: transient over-cap is fine
            cache.pop(victim)
        return val


class Dictionary:
    """A host-side sorted string dictionary, identified by a small int.

    Identity (and therefore jit-cache equality) is by ``dict_id``, so huge
    dictionaries cost nothing at trace time. Dictionaries are sorted at
    construction so that code order == lexicographic order; this lets ORDER
    BY / MIN / MAX / comparisons run directly on int32 codes on device.
    """

    __slots__ = ("dict_id", "values", "_index", "__weakref__")

    def __init__(self, values: np.ndarray):
        values = np.asarray(values, dtype=object)
        if values.ndim != 1:
            raise ValueError("dictionary must be 1-D")
        self.dict_id = next(_DICT_COUNTER)
        self.values = values
        self._index: Optional[dict] = None
        _DICT_REGISTRY[self.dict_id] = self

    @staticmethod
    def from_strings(values: Iterable[str]) -> "Dictionary":
        return Dictionary(np.asarray(list(values), dtype=object))

    def __len__(self) -> int:
        return len(self.values)

    def code_of(self, value: str) -> int:
        """Host-side lookup: string -> code, or -1 if absent."""
        return self.index().get(value, -1)

    def index(self) -> dict:
        """Cached str -> code map."""
        if self._index is None:
            self._index = {v: i for i, v in enumerate(self.values)}
        return self._index

    def decode(self, codes: np.ndarray) -> np.ndarray:
        out = np.empty(len(codes), dtype=object)
        valid = (codes >= 0) & (codes < len(self.values))
        out[valid] = self.values[codes[valid]]
        out[~valid] = None
        return out

    def is_sorted(self) -> bool:
        if len(self.values) < 2:
            return True
        v = self.values.astype(str)
        return bool(np.all(v[:-1] <= v[1:]))

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Dictionary) and other.dict_id == self.dict_id

    def __hash__(self) -> int:
        return hash(("Dictionary", self.dict_id))

    def __repr__(self) -> str:
        return f"Dictionary(id={self.dict_id}, n={len(self.values)})"


def get_dictionary(dict_id: int) -> Dictionary:
    return _DICT_REGISTRY[dict_id]


def build_sorted_dictionary(values: Iterable[str]) -> tuple[Dictionary, dict]:
    """Build a sorted dictionary from unique values; returns (dict, str->code)."""
    uniq = sorted(set(values))
    d = Dictionary.from_strings(uniq)
    return d, {v: i for i, v in enumerate(uniq)}


# ---------------------------------------------------------------------------
# Column
# ---------------------------------------------------------------------------


@dataclass
class Column:
    """A single padded device column.

    ``data``: [capacity] jnp array (dtype per DataType; strings = int32 codes)
    ``validity``: [capacity] bool jnp array, or None when non-nullable.
    ``dtype``/``dictionary``: static metadata (pytree aux).
    """

    data: jnp.ndarray
    validity: Optional[jnp.ndarray]
    dtype: DataType
    dictionary: Optional[Dictionary] = None

    # -- pytree protocol ----------------------------------------------------
    def tree_flatten(self):
        return (self.data, self.validity), (self.dtype, self.dictionary)

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, validity = children
        dtype, dictionary = aux
        return cls(data=data, validity=validity, dtype=dtype, dictionary=dictionary)

    # -- constructors -------------------------------------------------------
    @staticmethod
    def from_numpy(
        values: np.ndarray,
        dtype: DataType,
        capacity: int,
        validity: Optional[np.ndarray] = None,
        dictionary: Optional[Dictionary] = None,
    ) -> "Column":
        n = len(values)
        if n > capacity:
            raise ValueError(f"{n} values > capacity {capacity}")
        np_dtype = np.dtype(dtype.np_dtype)
        vals = np.asarray(values)
        # tpu precision mode stores logical 64-bit ints as int32; narrowing
        # must be loud, never a silent wrap (join keys at huge scale factors
        # are the realistic overflow case — see precision.py).
        if (
            n
            and np.issubdtype(vals.dtype, np.integer)
            and np.issubdtype(np_dtype, np.integer)
            and vals.dtype.itemsize > np_dtype.itemsize
        ):
            info = np.iinfo(np_dtype)
            lo, hi = vals.min(), vals.max()
            if lo < info.min or hi > info.max:
                raise OverflowError(
                    f"int values [{lo}, {hi}] exceed {np_dtype} device "
                    "storage; run with DFTPU_PRECISION=x64 for 64-bit keys"
                )
        if n == capacity and vals.ndim == 1 and vals.dtype == np_dtype:
            # a buffer that already satisfies the capacity (the wire decode
            # path when table_caps == live rows) enters the device as-is —
            # no zero-fill + pad copy; `to_device` hands it over via dlpack
            # where the backend allows (ownership transfers: the caller
            # must not mutate it afterwards)
            data = to_device(np.ascontiguousarray(vals))
        else:
            buf = np.zeros(capacity, dtype=np_dtype)
            buf[:n] = vals
            data = to_device(buf)
        col_validity = None
        if validity is not None:
            v = np.zeros(capacity, dtype=np.bool_)
            v[:n] = validity
            col_validity = to_device(v)
        return Column(data, col_validity, dtype, dictionary)

    @property
    def capacity(self) -> int:
        return int(self.data.shape[0])

    def valid_mask(self, capacity: Optional[int] = None) -> jnp.ndarray:
        """Per-row null mask (True = non-null). Does NOT account for num_rows."""
        if self.validity is not None:
            return self.validity
        return jnp.ones(capacity or self.capacity, dtype=jnp.bool_)

    def gather(self, idx: jnp.ndarray) -> "Column":
        data = jnp.take(self.data, idx, axis=0)
        validity = (
            jnp.take(self.validity, idx, axis=0) if self.validity is not None else None
        )
        return Column(data, validity, self.dtype, self.dictionary)

    def with_validity(self, validity: Optional[jnp.ndarray]) -> "Column":
        return Column(self.data, validity, self.dtype, self.dictionary)


jax.tree_util.register_pytree_node(
    Column,
    lambda c: c.tree_flatten(),
    Column.tree_unflatten,
)


# ---------------------------------------------------------------------------
# Table
# ---------------------------------------------------------------------------


@dataclass
class Table:
    """A padded columnar batch: named columns + traced live-row count."""

    names: tuple[str, ...]
    columns: tuple[Column, ...]
    num_rows: jnp.ndarray  # traced int32 scalar

    # -- pytree protocol ----------------------------------------------------
    def tree_flatten(self):
        return (self.columns, self.num_rows), self.names

    @classmethod
    def tree_unflatten(cls, names, children):
        columns, num_rows = children
        return cls(names=names, columns=tuple(columns), num_rows=num_rows)

    # -- construction -------------------------------------------------------
    @staticmethod
    def make(columns: dict[str, Column], num_rows) -> "Table":
        names = tuple(columns.keys())
        cols = tuple(columns.values())
        caps = {c.capacity for c in cols}
        if len(caps) > 1:
            raise ValueError(f"column capacities differ: {caps}")
        return Table(names, cols, jnp.asarray(num_rows, dtype=jnp.int32))

    @staticmethod
    def from_numpy(
        data: dict[str, np.ndarray],
        schema: Schema,
        capacity: Optional[int] = None,
        validity: Optional[dict[str, np.ndarray]] = None,
        dictionaries: Optional[dict[str, Dictionary]] = None,
    ) -> "Table":
        """Build a device Table from host arrays (string columns must already
        be int32 codes with a matching entry in ``dictionaries``)."""
        if not data:
            raise ValueError("from_numpy needs at least one column")
        n = len(next(iter(data.values())))
        cap = capacity if capacity is not None else max(1, _round_up(n))
        cols: dict[str, Column] = {}
        for f in schema.fields:
            vals = data[f.name]
            if len(vals) != n:
                raise ValueError(f"column {f.name} length {len(vals)} != {n}")
            v = validity.get(f.name) if validity else None
            d = dictionaries.get(f.name) if dictionaries else None
            if f.dtype == DataType.STRING and d is None:
                raise ValueError(f"string column {f.name} needs a dictionary")
            cols[f.name] = Column.from_numpy(vals, f.dtype, cap, v, d)
        return Table.make(cols, n)

    @staticmethod
    def empty(schema: Schema, capacity: int, dictionaries=None) -> "Table":
        cols = {}
        for f in schema.fields:
            d = dictionaries.get(f.name) if dictionaries else None
            cols[f.name] = Column(
                jnp.zeros(capacity, dtype=f.dtype.np_dtype),
                jnp.zeros(capacity, dtype=jnp.bool_) if f.nullable else None,
                f.dtype,
                d,
            )
        return Table.make(cols, 0)

    # -- introspection ------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self.columns[0].capacity if self.columns else 0

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def column(self, name: str) -> Column:
        try:
            return self.columns[self.names.index(name)]
        except ValueError:
            raise KeyError(f"no column {name!r}; have {list(self.names)}") from None

    def __contains__(self, name: str) -> bool:
        return name in self.names

    def as_dict(self) -> dict[str, Column]:
        return dict(zip(self.names, self.columns))

    def schema(self) -> Schema:
        return Schema(
            [
                Field(n, c.dtype, nullable=c.validity is not None)
                for n, c in zip(self.names, self.columns)
            ]
        )

    def row_mask(self) -> jnp.ndarray:
        """[capacity] bool: True for live (non-padding) rows."""
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.num_rows

    # -- transforms (all jit-safe) ------------------------------------------
    def select(self, names: Sequence[str]) -> "Table":
        return Table(
            tuple(names), tuple(self.column(n) for n in names), self.num_rows
        )

    def rename(self, mapping: dict[str, str]) -> "Table":
        names = tuple(mapping.get(n, n) for n in self.names)
        return Table(names, self.columns, self.num_rows)

    def with_column(self, name: str, col: Column) -> "Table":
        d = self.as_dict()
        d[name] = col
        return Table(tuple(d.keys()), tuple(d.values()), self.num_rows)

    def gather(self, idx: jnp.ndarray, num_rows) -> "Table":
        cols = tuple(c.gather(idx) for c in self.columns)
        return Table(self.names, cols, jnp.asarray(num_rows, dtype=jnp.int32))

    def compact(self, keep: jnp.ndarray) -> "Table":
        """Select rows where ``keep`` is True, packed to the front (jit-safe).

        ``keep`` is a [capacity] bool mask; padding rows must already be False
        in it. This is the TPU analogue of Arrow's ``filter`` kernel: a
        static-size ``nonzero`` + gather keeps shapes fixed while num_rows
        becomes the popcount.
        """
        keep = keep & self.row_mask()
        (idx,) = jnp.nonzero(keep, size=self.capacity, fill_value=0)
        n = jnp.sum(keep, dtype=jnp.int32)
        t = self.gather(idx, n)
        # Rows past n were filled from index 0; mark them invalid via validity
        # where present (data beyond num_rows is garbage by contract anyway).
        return t

    def head(self, limit: int | jnp.ndarray) -> "Table":
        n = jnp.minimum(self.num_rows, jnp.asarray(limit, dtype=jnp.int32))
        return Table(self.names, self.columns, n)

    def slice_rows(self, lo: int, count: int) -> "Table":
        """Row-range slice [lo, lo+count) as a compact table (NOT jit-safe:
        static python offsets). The chunking primitive of the streaming
        data plane — each chunk's buffers are views of this table, so
        slicing is free until a consumer materializes the chunk."""
        n = int(self.num_rows)
        lo = max(0, min(lo, n))
        count = max(0, min(count, n - lo))
        cap = max(_round_up(count), 8)
        cols = tuple(
            Column(
                c.data[lo:lo + cap],
                c.validity[lo:lo + cap] if c.validity is not None else None,
                c.dtype, c.dictionary,
            )
            for c in self.columns
        )
        # short tail: buffer views may be < cap; pad via head-room contract
        # (rows past num_rows are garbage by contract, so a short buffer is
        # only a problem for fixed-capacity consumers; re-pad those lazily)
        return Table(self.names, cols, jnp.asarray(count, dtype=jnp.int32))

    # -- host materialization (NOT jit-safe) --------------------------------
    def to_numpy(self, decode_strings: bool = True) -> dict[str, np.ndarray]:
        n = int(self.num_rows)
        out: dict[str, np.ndarray] = {}
        for name, col in zip(self.names, self.columns):
            vals = np.asarray(col.data[:n])
            if col.dtype == DataType.STRING and decode_strings:
                assert col.dictionary is not None
                vals = col.dictionary.decode(vals)
            if col.validity is not None:
                mask = np.asarray(col.validity[:n])
                if vals.dtype == object:
                    vals = vals.copy()
                    vals[~mask] = None
                elif np.issubdtype(vals.dtype, np.floating):
                    vals = vals.astype(np.float64, copy=True)
                    vals[~mask] = np.nan
                else:
                    vals = np.ma.masked_array(vals, mask=~mask)
            out[name] = vals
        return out

    def to_pandas(self):
        import pandas as pd

        n = int(self.num_rows)
        cols = {}
        for name, col in zip(self.names, self.columns):
            vals = np.asarray(col.data[:n])
            if col.dtype == DataType.STRING:
                assert col.dictionary is not None
                vals = col.dictionary.decode(vals)
            s = pd.Series(vals)
            if col.validity is not None:
                mask = np.asarray(col.validity[:n])
                s = s.where(pd.Series(mask), other=None)
            cols[name] = s
        return pd.DataFrame(cols)

    def __repr__(self) -> str:
        cols = ", ".join(
            f"{n}:{c.dtype.value}" for n, c in zip(self.names, self.columns)
        )
        return f"Table(capacity={self.capacity}, cols=[{cols}])"


jax.tree_util.register_pytree_node(
    Table,
    lambda t: t.tree_flatten(),
    Table.tree_unflatten,
)


def _round_up(n: int, multiple: int = 8) -> int:
    """Round up to a TPU-lane-friendly size (min sublane granularity)."""
    return ((n + multiple - 1) // multiple) * multiple


def round_up_pow2(n: int, minimum: int = 8) -> int:
    cap = minimum
    while cap < n:
        cap *= 2
    return cap


# ---------------------------------------------------------------------------
# Zero-copy host data plane: view-based staging primitives
# ---------------------------------------------------------------------------
#
# The distributed data plane moves tables between stages as host-side slices
# (chunk streams, per-destination shuffle partitions, broadcast fan-out).
# Doing that with eager jax ops costs one device dispatch + buffer copy per
# slice; these primitives instead rebind staged tables to HOST numpy buffers
# once (`host_view` — zero-copy on the CPU backend, one unavoidable D2H on a
# real accelerator), after which every slice is a numpy VIEW (`slice_view`)
# and contiguous views of one buffer reassemble without a copy
# (`concat_tables`' host fast path). Buffers are immutable by contract —
# every consumer shares them by reference.


def parse_bool_knob(value) -> bool:
    """One parser for boolean `SET distributed.*` knobs ("off"/"false"/
    "0"/"" are false, everything else truthy) — SET-time validation
    (sql/context.py) and runtime interpretation share it so the accepted
    spellings cannot drift apart."""
    if isinstance(value, str):
        return value.strip().lower() not in ("0", "false", "off", "")
    return bool(value)


def zero_copy_enabled(config: Optional[dict] = None) -> bool:
    """Effective `SET distributed.zero_copy` (default ON). The env override
    ``DFTPU_ZERO_COPY`` wins over session config — the A/B escape hatch for
    whole-suite comparison runs without touching session options."""
    env = os.environ.get("DFTPU_ZERO_COPY")
    if env is not None:
        return parse_bool_knob(env)
    return parse_bool_knob((config or {}).get("zero_copy", True))


def to_device(arr) -> jnp.ndarray:
    """Host buffer -> device array. The dlpack import
    (`jax.dlpack.from_dlpack`) is the zero-copy entry on backends whose
    runtime can adopt an Arrow-layout host buffer — but it is OPT-IN
    (``DFTPU_DLPACK=1``): on this jax/CPU build the import both copies AND
    commits the result to one device, and a committed leaf breaks the
    in-mesh tier's contract that scan inputs are uncommitted (shard_map
    re-places them freely). The default `jnp.asarray` stays uncommitted and
    costs the same single H2D copy. Callers hand over OWNERSHIP of the
    buffer either way: it must not be mutated afterwards."""
    if (
        isinstance(arr, np.ndarray)
        and arr.flags.c_contiguous
        and os.environ.get("DFTPU_DLPACK") == "1"
    ):
        try:
            import jax.dlpack as _jdl

            return _jdl.from_dlpack(arr)
        except Exception:
            pass
    return jnp.asarray(arr)


def is_host_backed(table: Table) -> bool:
    """True when every buffer is a host numpy array and num_rows is
    concrete — the staging representation the view-based data plane can
    slice and reassemble without device dispatches or copies."""
    if isinstance(table.num_rows, jax.core.Tracer):
        return False
    for c in table.columns:
        if not isinstance(c.data, np.ndarray):
            return False
        if c.validity is not None and not isinstance(c.validity, np.ndarray):
            return False
    return True


def host_view(table: Table) -> Table:
    """Rebind a table's buffers to host numpy arrays WITHOUT copying where
    the backend allows (a jax CPU array shares its buffer with numpy —
    `np.asarray` returns a readonly view; an accelerator pays its one
    unavoidable D2H here, once, instead of per slice)."""
    if isinstance(table.num_rows, jax.core.Tracer):
        raise ValueError("host_view of a traced table")
    if is_host_backed(table):
        return table
    cols = tuple(
        Column(
            np.asarray(c.data),
            np.asarray(c.validity) if c.validity is not None else None,
            c.dtype,
            c.dictionary,
        )
        for c in table.columns
    )
    return Table(table.names, cols, np.int32(int(table.num_rows)))


def slice_view(table: Table, lo: int, count: int) -> Table:
    """Zero-copy row-range view [lo, lo+count) of a table: numpy views of
    the same buffers, capacity == count exactly (no pad copy). Device-backed
    tables are host-rebound first (free on CPU); traced tables fall back to
    the copying `slice_rows`."""
    if not is_host_backed(table):
        if isinstance(table.num_rows, jax.core.Tracer):
            return table.slice_rows(lo, count)
        table = host_view(table)
    n = int(table.num_rows)
    lo = max(0, min(lo, n))
    count = max(0, min(count, n - lo))
    cols = tuple(
        Column(
            c.data[lo:lo + count],
            c.validity[lo:lo + count] if c.validity is not None else None,
            c.dtype,
            c.dictionary,
        )
        for c in table.columns
    )
    return Table(table.names, cols, np.int32(count))


def _base_buffer(arr: np.ndarray):
    """Walk the numpy view chain to the owning object (an ndarray, or the
    memoryview a jax CPU buffer exports)."""
    base = arr
    while isinstance(base, np.ndarray) and base.base is not None:
        base = base.base
    return base


def _buffer_ptr(arr: np.ndarray) -> int:
    return arr.__array_interface__["data"][0]


def _buffer_extent(base) -> Optional[tuple[int, int]]:
    """(start pointer, nbytes) of an owning buffer, or None if unknowable."""
    if isinstance(base, np.ndarray):
        return _buffer_ptr(base), int(base.nbytes)
    if isinstance(base, memoryview):
        flat = np.frombuffer(base, dtype=np.uint8)
        return _buffer_ptr(flat), int(flat.nbytes)
    return None


def _merge_views(arrs: list, want_len: int):
    """Exact-length contiguous 1-D views that abut in ONE base buffer ->
    a single view of length ``want_len`` over that buffer (reading past the
    last view only if the base has the room), else None."""
    nz = [a for a in arrs if len(a)]
    if not nz:
        return None
    base = _base_buffer(nz[0])
    start = _buffer_ptr(nz[0])
    end = start + nz[0].nbytes
    for a in nz[1:]:
        if _base_buffer(a) is not base or _buffer_ptr(a) != end:
            return None
        end += a.nbytes
    itemsize = nz[0].itemsize
    have = (end - start) // itemsize
    if want_len > have:
        extent = _buffer_extent(base)
        if extent is None:
            return None
        b_start, b_nbytes = extent
        if start + want_len * itemsize > b_start + b_nbytes:
            return None  # base buffer too short for the requested capacity
    return np.lib.stride_tricks.as_strided(
        nz[0], shape=(want_len,), strides=nz[0].strides
    )


def _concat_host(tables: Sequence[Table], names, total_cap: int):
    """Host (numpy) concat fast path: one memcpy per column at memory
    bandwidth instead of one eager device scatter per input — and when the
    inputs are contiguous views of ONE base buffer (the chunk streams of the
    zero-copy data plane), NO copy at all: the result is a view of the base.
    Returns None when any input is device-backed (the caller's jax path
    handles those)."""
    for t in tables:
        if not is_host_backed(t):
            return None
    ns = [int(t.num_rows) for t in tables]
    total = sum(ns)
    ncols = len(names)
    unified = [
        unify_dictionaries([t.columns[ci].dictionary for t in tables])
        for ci in range(ncols)
    ]
    view = _concat_contiguous(tables, names, ns, unified, total_cap)
    if view is not None:
        return view
    out_cols = []
    for ci in range(ncols):
        cols = [t.columns[ci] for t in tables]
        dtype = cols[0].dtype
        d, luts = unified[ci]
        has_validity = any(c.validity is not None for c in cols)
        data = np.zeros(total_cap, dtype=dtype.np_dtype)
        validity = (
            np.zeros(total_cap, dtype=np.bool_) if has_validity else None
        )
        off = 0
        for t, c, lut, n in zip(tables, cols, luts, ns):
            if n:
                vals = c.data[:n]
                if lut is not None:
                    lut = np.asarray(lut)
                    if len(lut) == 0:
                        vals = np.zeros(n, dtype=data.dtype)
                    else:
                        vals = lut[np.clip(vals, 0, len(lut) - 1)]
                data[off:off + n] = vals
                if has_validity:
                    validity[off:off + n] = (
                        c.validity[:n] if c.validity is not None else True
                    )
            off += n
        # same pad semantics as the device path: zeros (data) / False
        # (validity) beyond the live rows
        out_cols.append(Column(data, validity, dtype, d))
    return Table(tuple(names), tuple(out_cols), np.int32(total))


def _concat_contiguous(tables, names, ns, unified, total_cap: int):
    """Pure-view reassembly: every column of every chunk is an exact-length
    contiguous view, consecutive chunks abut in the same base buffer, no
    dictionary re-encode is needed, and the base can honor the requested
    capacity — then concat is a VIEW of the base buffer (rows past num_rows
    are garbage by the Table contract)."""
    total = sum(ns)
    if total == 0:
        return None
    for _d, luts in unified:
        if any(lut is not None for lut in luts):
            return None
    out_cols = []
    for ci in range(len(names)):
        cols = [t.columns[ci] for t in tables]
        if len({c.validity is not None for c in cols}) > 1:
            return None
        for c, n in zip(cols, ns):
            if len(c.data) != n or not c.data.flags.c_contiguous:
                return None  # not an exact-length contiguous view
            if c.validity is not None and (
                len(c.validity) != n or not c.validity.flags.c_contiguous
            ):
                return None
        merged = _merge_views([c.data for c in cols], total_cap)
        if merged is None:
            return None
        merged_validity = None
        if cols[0].validity is not None:
            merged_validity = _merge_views(
                [c.validity for c in cols], total_cap
            )
            if merged_validity is None:
                return None
        d, _ = unified[ci]
        out_cols.append(Column(merged, merged_validity, cols[0].dtype, d))
    return Table(tuple(names), tuple(out_cols), np.int32(total))


def concat_tables(tables: Sequence[Table], capacity: Optional[int] = None) -> Table:
    """Concatenate same-schema tables into one padded table (jit-safe when
    ``capacity`` is given; rows are packed via cumulative offsets)."""
    if not tables:
        raise ValueError("concat of zero tables")
    first = tables[0]
    total_cap = capacity or sum(t.capacity for t in tables)
    names = first.names
    for t in tables[1:]:
        if t.names != names:
            raise ValueError(f"concat schema mismatch: {t.names} vs {names}")
        for ci in range(len(names)):
            a, b = first.columns[ci], t.columns[ci]
            if a.dtype != b.dtype:
                raise ValueError(
                    f"concat dtype mismatch on {names[ci]!r}: {a.dtype} vs {b.dtype}"
                )
    # Overflow check when row counts are concrete (host path); under jit the
    # caller owns capacity sizing, as everywhere else in the engine.
    concrete = [t.num_rows for t in tables if not isinstance(t.num_rows, jax.core.Tracer)]
    if len(concrete) == len(tables):
        total = int(sum(int(n) for n in concrete))
        if total > total_cap:
            raise ValueError(f"concat overflow: {total} rows > capacity {total_cap}")
        # zero-copy data plane: host-backed inputs (chunk views, staged
        # slices) concat in numpy — one memcpy per column, or NO copy when
        # the chunks are contiguous views of one base buffer
        host = _concat_host(tables, names, total_cap)
        if host is not None:
            return host
        # Meshes-as-workers: inputs committed to DIFFERENT device sets
        # (slices pulled from two worker-owned meshes) cannot feed one op;
        # rebase through host first — the DCN hop a real multi-host
        # deployment pays at exactly this merge point.
        device_sets = set()
        for t in tables:
            for c in t.columns:
                s = getattr(c.data, "sharding", None)
                if s is not None:
                    device_sets.add(frozenset(s.device_set))
        if len(device_sets) > 1:
            tables = [_rebase_to_host(t) for t in tables]
            first = tables[0]
    out_cols = []
    # Destination index for each source row: offset of its table + local idx.
    offsets = []
    acc = jnp.asarray(0, dtype=jnp.int32)
    for t in tables:
        offsets.append(acc)
        acc = acc + t.num_rows
    total_rows = acc
    for ci, name in enumerate(names):
        src_dtype = first.columns[ci].dtype
        dictionary, luts = unify_dictionaries(
            [t.columns[ci].dictionary for t in tables]
        )
        has_validity = any(t.columns[ci].validity is not None for t in tables)
        data = jnp.zeros(total_cap, dtype=src_dtype.np_dtype)
        validity = jnp.zeros(total_cap, dtype=jnp.bool_) if has_validity else None
        for t, off, lut in zip(tables, offsets, luts):
            col = t.columns[ci]
            live = t.row_mask()
            dst = jnp.where(live, off + jnp.arange(t.capacity, dtype=jnp.int32), total_cap)
            vals = col.data
            if lut is not None:
                vals = jnp.asarray(lut)[jnp.clip(vals, 0, len(lut) - 1)]
            data = data.at[dst].set(vals, mode="drop")
            if has_validity:
                v = col.valid_mask()
                validity = validity.at[dst].set(v, mode="drop")
        out_cols.append(Column(data, validity, src_dtype, dictionary))
    return Table(names, tuple(out_cols), total_rows)


def _rebase_to_host(t: Table) -> Table:
    """Detach a table's arrays from their committed devices (host round
    trip); the next consumer places them wherever it computes."""
    import numpy as _np

    def move(x):
        return jnp.asarray(_np.asarray(x))

    return Table(
        t.names,
        tuple(
            Column(
                move(c.data),
                move(c.validity) if c.validity is not None else None,
                c.dtype,
                c.dictionary,
            )
            for c in t.columns
        ),
        move(t.num_rows),
    )


def unify_dictionaries(dicts):
    """Pick a common dictionary for a set of string columns and per-input
    code-remap LUTs (None = codes pass through). The union is SORTED, so
    remapped codes preserve lexicographic order — callers use this for
    concat, cross-dictionary comparison, and COALESCE alike.

    Different Dictionary objects arise legitimately: each worker task's
    SUBSTRING/UPPER/CONCAT evaluation derives its own dictionary, and SQL
    NULL literals (ROLLUP arms, FULL OUTER padding) carry none at all. Codes
    only compare under one vocabulary, so concat re-encodes into the sorted
    union (the host-side analogue of the reference's dictionary re-encode
    before the wire, `impl_execute_task.rs:244-274`)."""
    present = [d for d in dicts if d is not None]
    if not present:
        return None, [None] * len(dicts)
    unique = {d.dict_id: d for d in present}
    if len(unique) == 1:
        return present[0], [None] * len(dicts)
    vals = [d.values.astype(str) for d in unique.values()]
    if all(np.array_equal(v, vals[0]) for v in vals[1:]):
        # same vocabulary, distinct objects (per-task derivations): codes
        # already agree
        return present[0], [None] * len(dicts)
    union_vals = np.unique(np.concatenate(vals))
    # memoize by input dict ids: re-tracing the same concat (e.g. the arm
    # probe + lax.cond branch of IsolatedArmExec) must see the SAME union
    # Dictionary object, or the traces' pytree metadata diverges
    union = lru_get_or_create(
        _UNION_DICT_CACHE, tuple(sorted(unique)),
        lambda: Dictionary(union_vals.astype(object)), cap=256,
    )
    luts = []
    for d in dicts:
        if d is None or len(d) == 0:
            luts.append(None if d is None else np.zeros(1, dtype=np.int32))
            continue
        luts.append(
            np.searchsorted(union_vals, d.values.astype(str)).astype(np.int32)
        )
    return union, luts
