"""Hash join kernels (inner / left / semi / anti / mark), fully vectorized.

The reference uses DataFusion's `HashJoinExec` (CollectLeft or Partitioned,
chosen by the distributed planner's broadcast pass,
`/root/reference/src/distributed_planner/insert_broadcast.rs`). A TPU can't
chase per-row hash chains, so this kernel decomposes the join into dense
array passes:

1. BUILD: group build-side rows by key with the shared claim-loop hash table
   (ops/aggregate.build_group_table) -> every build row gets a group id; a
   CSR layout (counts + offsets + rows sorted by group) enumerates duplicates.
2. PROBE: a lookup-only probe loop resolves each probe row to its key's group
   id (or none) in O(max probe chain) vectorized rounds.
3. EXPAND: pair output positions come from an exclusive cumsum of per-probe
   match counts; each output row finds its probe row by searchsorted and its
   duplicate ordinal by subtraction — a static-capacity gather/gather, no
   dynamic shapes (SURVEY.md §7 hard part (f) analogue for join fan-out).

Semi/anti/mark avoid expansion entirely: they only need the per-probe match
count (optionally after a residual predicate pass over expanded pairs).
Output capacity is a static bound from the planner; overflow is reported as a
jit-safe flag like the aggregate kernel.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from datafusion_distributed_tpu import precision
from datafusion_distributed_tpu.ops.aggregate import GroupTable, build_group_table
from datafusion_distributed_tpu.ops.hash import fold_payload, hash_columns
from datafusion_distributed_tpu.ops.table import Column, Table
from datafusion_distributed_tpu.schema import DataType


def _fold_keys(cols, valids, lane_plan):
    """Payload folding with a FIXED lane layout shared by build and probe:
    ``lane_plan[i]`` == True adds a validity lane for key column i (required
    when EITHER side of the join is nullable, so the compare matrices always
    have matching shapes)."""
    lane = precision.LANE_INT
    lanes = []
    for c, v in zip(cols, valids):
        payload = fold_payload(c, lane)
        if v is not None:
            payload = jnp.where(v, payload, 0)
        lanes.append(payload)
    n = cols[0].shape[0]
    for v, want in zip(valids, lane_plan):
        if want:
            lanes.append(
                v.astype(lane) if v is not None
                else jnp.ones(n, dtype=lane)
            )
    return jnp.stack(lanes, axis=1)  # [N, lanes]


def probe_group_table(
    gt_slot_keys_raw: jnp.ndarray,  # [H, lanes] LANE_INT (raw matrix)
    slot_used: jnp.ndarray,  # [H] bool
    probe_cols: Sequence[jnp.ndarray],
    probe_valids: Sequence[Optional[jnp.ndarray]],
    live: jnp.ndarray,
    lane_plan: Sequence[bool],
    max_rounds: int = 512,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Find each probe row's slot in a built table; -1 when absent.
    Returns (found, overflow): overflow=True when the probe loop exhausted
    max_rounds with rows still unresolved — matches must then be treated as
    unreliable, like the build side's overflow flag.

    SQL join semantics: a NULL key never matches, so rows with any null key
    column are resolved to -1 up front.
    """
    num_slots = slot_used.shape[0]
    mask = np.uint32(num_slots - 1)
    n = probe_cols[0].shape[0]
    keys_mat = _fold_keys(probe_cols, probe_valids, lane_plan)
    h0 = hash_columns(list(probe_cols), list(probe_valids))
    slot = (h0 & mask).astype(jnp.int32)

    has_null = jnp.zeros(n, dtype=jnp.bool_)
    for v in probe_valids:
        if v is not None:
            has_null = has_null | ~v
    active0 = live & ~has_null
    found0 = jnp.full(n, -1, dtype=jnp.int32)

    def cond(state):
        active, *_rest, rounds = state
        return jnp.any(active) & (rounds < max_rounds)

    def body(state):
        active, slot, found, rounds = state
        used = slot_used[slot]
        mine = gt_slot_keys_raw[slot]
        match = used & jnp.all(mine == keys_mat, axis=1)
        found = jnp.where(active & match, slot, found)
        # empty slot => key absent; stop. mismatch on used slot => next slot.
        still = active & used & ~match
        slot = jnp.where(
            still, ((slot + 1).astype(jnp.uint32) & mask).astype(jnp.int32), slot
        )
        return still, slot, found, rounds + 1

    still, _, found, _ = jax.lax.while_loop(
        cond, body, (active0, slot, found0, jnp.asarray(0, dtype=jnp.int32))
    )
    return found, jnp.any(still)


@dataclass
class BuildSide:
    """Build-side hash table + CSR duplicate layout, reusable across probes."""

    raw_slot_keys: jnp.ndarray  # [H, lanes]
    slot_used: jnp.ndarray  # [H]
    counts: jnp.ndarray  # [H] rows per group
    offsets: jnp.ndarray  # [H] exclusive start into rows_by_group
    rows_by_group: jnp.ndarray  # [M] build row indices sorted by group
    table: Table
    overflow: jnp.ndarray
    lane_plan: tuple  # per key col: validity lane present?
    has_null_key: jnp.ndarray  # scalar bool: any live build row had a null key


def build_join_table(
    build: Table,
    key_names: Sequence[str],
    num_slots: int,
    lane_plan: Optional[Sequence[bool]] = None,
) -> BuildSide:
    live = build.row_mask()
    cols = [build.column(k).data for k in key_names]
    valids = [build.column(k).validity for k in key_names]
    if lane_plan is None:
        lane_plan = [v is not None for v in valids]
    lane_plan = tuple(lane_plan)
    # SQL join: null keys on the build side can never match; treat as dead.
    # (NOT IN needs to know they existed: has_null_key.)
    has_null = jnp.zeros(build.capacity, dtype=jnp.bool_)
    for v in valids:
        if v is not None:
            has_null = has_null | ~v
    has_null_key = jnp.any(live & has_null)
    live = live & ~has_null
    gt = build_group_table(cols, valids, live, num_slots, lane_plan=lane_plan)
    m = build.capacity
    gid = jnp.where(live, gt.group_ids, num_slots)
    counts = (
        jnp.zeros(num_slots, dtype=jnp.int32)
        .at[gid]
        .add(jnp.ones(m, dtype=jnp.int32), mode="drop")
    )
    offsets = jnp.cumsum(counts) - counts  # exclusive
    rows_by_group = jnp.argsort(gid, stable=True).astype(jnp.int32)
    raw = _raw_slot_keys(gt, cols, lane_plan)
    return BuildSide(
        raw_slot_keys=raw,
        slot_used=gt.slot_used,
        counts=counts,
        offsets=offsets,
        rows_by_group=rows_by_group,
        table=build,
        overflow=gt.overflow,
        lane_plan=lane_plan,
        has_null_key=has_null_key,
    )


def _raw_slot_keys(gt: GroupTable, cols, lane_plan) -> jnp.ndarray:
    """Re-fold the group table's per-slot keys into the raw lane matrix the
    probe compares against (same lane layout as _fold_keys)."""
    lane = precision.LANE_INT
    lanes = []
    h = gt.slot_used.shape[0]
    for keys, kv in zip(gt.slot_keys, gt.slot_key_valid):
        payload = fold_payload(keys, lane)
        if kv is not None:
            payload = jnp.where(kv, payload, 0)
        lanes.append(payload)
    for kv, want in zip(gt.slot_key_valid, lane_plan):
        if want:
            lanes.append(
                kv.astype(lane) if kv is not None
                else jnp.ones(h, dtype=lane)
            )
    return jnp.stack(lanes, axis=1)


def hash_join(
    probe: Table,
    build_side: BuildSide,
    probe_keys: Sequence[str],
    join_type: str,  # inner | left | semi | anti | mark
    out_capacity: int,
    probe_prefix: str = "",
    build_prefix: str = "",
    precomputed: Optional[tuple] = None,
) -> tuple[Table, jnp.ndarray]:
    """Join probe against a built side. Returns (result, overflow flag).

    For inner/left the result concatenates probe columns then build columns
    (optionally name-prefixed). For semi/anti the result is probe rows
    filtered by match. For mark it is probe plus a BOOL `__mark` column.
    `left` marks unmatched probe rows' build columns invalid (SQL LEFT JOIN).

    ``precomputed=(found, probe_overflow)`` short-circuits the probe loop
    with slots resolved elsewhere (the multiway cascaded kernel probes all
    tables of a fused join chain in one pass): ``found`` is [probe.capacity]
    i32, the build-table slot per probe row or -1, with dead/padded rows
    re-masked here so garbage lookups from expanded intermediates are
    harmless.
    """
    live = probe.row_mask()
    if precomputed is not None:
        g, probe_overflow = precomputed
        g = jnp.where(live, g, -1)
    else:
        cols = [probe.column(k).data for k in probe_keys]
        valids = [probe.column(k).validity for k in probe_keys]
        g, probe_overflow = probe_group_table(
            build_side.raw_slot_keys, build_side.slot_used, cols, valids,
            live, build_side.lane_plan,
        )
    table_overflow = build_side.overflow | probe_overflow
    found = g >= 0
    g_safe = jnp.where(found, g, 0)
    match_count = jnp.where(found & live, build_side.counts[g_safe], 0)

    if join_type in ("semi", "anti", "mark"):
        has_match = match_count > 0
        if join_type == "semi":
            return probe.compact(has_match), table_overflow
        if join_type == "anti":
            return probe.compact(live & ~has_match), table_overflow
        mark = Column(has_match, None, DataType.BOOL)
        return probe.with_column("__mark", mark), table_overflow

    if join_type == "left":
        out_rows = jnp.where(live, jnp.maximum(match_count, 1), 0)
    elif join_type == "inner":
        out_rows = match_count
    else:
        raise NotImplementedError(f"join type {join_type}")

    cum = jnp.cumsum(out_rows)
    total = cum[-1] if out_rows.shape[0] > 0 else jnp.asarray(0, jnp.int32)
    starts = cum - out_rows
    overflow = table_overflow | (total > out_capacity)

    j = jnp.arange(out_capacity, dtype=jnp.int32)
    # probe row for output j: first row whose cumulative end exceeds j
    l_idx = jnp.searchsorted(cum, j, side="right").astype(jnp.int32)
    l_idx = jnp.clip(l_idx, 0, probe.capacity - 1)
    k = j - starts[l_idx]  # duplicate ordinal within the match group
    lg = g_safe[l_idx]
    matched = (k < match_count[l_idx])
    pos = jnp.clip(
        build_side.offsets[lg] + k, 0, build_side.rows_by_group.shape[0] - 1
    )
    r_idx = build_side.rows_by_group[pos]
    r_idx = jnp.where(matched, r_idx, 0)

    out_cols: dict[str, Column] = {}
    for name, col in zip(probe.names, probe.columns):
        c = col.gather(l_idx)
        out_cols[probe_prefix + name] = c
    for name, col in zip(build_side.table.names, build_side.table.columns):
        c = col.gather(r_idx)
        if join_type == "left":
            v = c.valid_mask(out_capacity) & matched
            c = Column(c.data, v, c.dtype, c.dictionary)
        out_cols[build_prefix + name] = c
    result = Table(tuple(out_cols.keys()), tuple(out_cols.values()), total)
    return result, overflow
