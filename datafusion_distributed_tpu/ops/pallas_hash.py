"""Pallas claim-loop hash-table build (experimental TPU kernel).

SURVEY.md §7 hard part (b): the XLA claim loop (ops/aggregate.py
build_group_table) runs O(probe-chain) ROUNDS, each a full HBM pass over all
rows plus scatters into the [H, lanes] table. This kernel is the
VMEM-resident alternative: one sequential pass over the rows with the whole
table held in VMEM, so each probe is an on-chip read instead of an HBM
round.

Trade-off being measured (benchmarks/micro_bench.py hashbuild_* rows):
- XLA claim loop: massively parallel per round, ~rounds × N × lanes HBM
  traffic; great when chains are short (table ≥ 2×NDV).
- This kernel: ZERO HBM traffic per probe (table in VMEM, ≤ ~1M slots),
  but row processing is sequential on the scalar unit — throughput is
  bounded by probe-chain length × scalar-op latency, not bandwidth.

The engine uses the XLA path by default; DFTPU_PALLAS=1 switches
build_group_table's group-id assignment to this kernel where legal
(single-device, table fits VMEM). On CPU the kernel runs in interpret mode
(correctness tests); perf claims are only meaningful on a real chip — the
micro-bench prints both paths so BENCH notes can record the verdict either
way.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

# VMEM is ~16 MiB/core. This kernel stages EVERYTHING as single VMEM
# blocks — the [H, L] table AND the [N, L] keys / [N] slot0/live/gid rows
# (row blocking over a grid is future work), so both dimensions are gated.
_MAX_VMEM_SLOTS = 1 << 16
_MAX_VMEM_ROWS = 1 << 18  # ~4 MiB of i32 rows at 2 lanes + gid/slot0/live


def pallas_available() -> bool:
    try:
        from jax.experimental import pallas as pl  # noqa: F401

        return True
    except Exception:  # pragma: no cover
        return False


def use_pallas_hash() -> bool:
    return os.environ.get("DFTPU_PALLAS", "0") == "1" and pallas_available()


@partial(jax.jit, static_argnames=("num_slots", "interpret"))
def pallas_build_group_ids(
    keys_mat: jnp.ndarray,  # [N, L] int32 folded key lanes
    slot0: jnp.ndarray,  # [N] int32 initial probe slot (hash & mask)
    live: jnp.ndarray,  # [N] bool
    num_slots: int,
    interpret: bool = False,
):
    """-> (gid [N] i32, slot_keys [H, L] i32, slot_used [H] bool,
    overflow bool). Sequential insertion semantics: the first live row of a
    key claims a slot along its probe chain. Grouping is consistent with
    the XLA claim loop but slot layout may differ (see module docstring)."""
    from jax.experimental import pallas as pl

    n, lanes = keys_mat.shape
    h = num_slots
    assert h & (h - 1) == 0
    if h > _MAX_VMEM_SLOTS:
        raise ValueError(f"{h} slots exceed the VMEM budget")
    if n > _MAX_VMEM_ROWS:
        raise ValueError(f"{n} rows exceed the VMEM budget (no row blocking)")

    def kernel(keys_ref, slot0_ref, live_ref, gid_ref, tkeys_ref, used_ref,
               over_ref):
        # init table
        tkeys_ref[:, :] = jnp.zeros((h, lanes), jnp.int32)
        used_ref[:] = jnp.zeros((h,), jnp.int32)
        over_ref[0] = jnp.int32(0)

        def row(i, _):
            is_live = live_ref[i] != 0

            # PURE probe: walk the chain reading the table; all mutation
            # happens once, after the loop (stateful ops inside while
            # bodies do not discharge reliably into pallas refs)
            def probe_body(state):
                slot, done, steps = state
                occupied = used_ref[slot] != 0
                match = jnp.bool_(True)
                for l in range(lanes):
                    match = match & (tkeys_ref[slot, l] == keys_ref[i, l])
                resolved = jnp.logical_not(occupied) | (occupied & match)
                nxt = jnp.where(
                    resolved, slot, (slot + 1) & jnp.int32(h - 1)
                )
                return nxt, resolved, steps + 1

            def probe_cond(state):
                _, done, steps = state
                return jnp.logical_not(done) & (steps < h)

            slot, done, _ = jax.lax.while_loop(
                probe_cond, probe_body,
                (slot0_ref[i], jnp.bool_(False), jnp.int32(0)),
            )
            claim = is_live & done & (used_ref[slot] == 0)

            @pl.when(claim)
            def _():
                for l in range(lanes):
                    tkeys_ref[slot, l] = keys_ref[i, l]
                used_ref[slot] = jnp.int32(1)

            @pl.when(is_live & done)
            def _():
                gid_ref[i] = slot

            @pl.when(is_live & jnp.logical_not(done))
            def _():
                over_ref[0] = jnp.int32(1)

            return _

        jax.lax.fori_loop(0, n, row, None)

    gid, tkeys, used, over = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((n,), jnp.int32),
            jax.ShapeDtypeStruct((h, lanes), jnp.int32),
            jax.ShapeDtypeStruct((h,), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ],
        interpret=interpret,
    )(keys_mat.astype(jnp.int32), slot0.astype(jnp.int32),
      live.astype(jnp.int32))
    return gid, tkeys, used.astype(jnp.bool_), over[0].astype(jnp.bool_)


def build_group_ids_reference(keys_mat, slot0, live, num_slots):
    """Pure-numpy oracle for the kernel's sequential-insert semantics."""
    keys_mat = np.asarray(keys_mat)
    slot0 = np.asarray(slot0)
    live = np.asarray(live)
    n, lanes = keys_mat.shape
    tkeys = np.zeros((num_slots, lanes), np.int32)
    used = np.zeros(num_slots, bool)
    gid = np.zeros(n, np.int32)
    overflow = False
    for i in range(n):
        if not live[i]:
            continue
        slot = int(slot0[i])
        for _ in range(num_slots):
            if not used[slot]:
                tkeys[slot] = keys_mat[i]
                used[slot] = True
                gid[i] = slot
                break
            if (tkeys[slot] == keys_mat[i]).all():
                gid[i] = slot
                break
            slot = (slot + 1) & (num_slots - 1)
        else:
            overflow = True
    return gid, tkeys, used, overflow
