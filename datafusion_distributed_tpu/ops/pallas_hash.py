"""Pallas claim-loop hash-table build (TPU kernel).

SURVEY.md §7 hard part (b): the XLA claim loop (ops/aggregate.py
build_group_table) runs O(probe-chain) ROUNDS, each a full HBM pass over all
rows plus scatters into the [H, lanes] table. This kernel is the
VMEM-resident alternative: sequential passes over the rows with the (sub-)
table held in VMEM, so each probe is an on-chip read instead of an HBM
round.

Production shape (round 5; the round-4 version staged everything as single
VMEM blocks and was gated to 2^16 slots / 2^18 rows):

- **Row blocking.** Rows stream through a grid dimension in blocks of
  2^15; the table lives in VMEM *scratch*, which persists across grid
  steps (TPU grids execute sequentially), so row count is unbounded.
- **Tables > VMEM: hash-partitioned multi-pass.** A table of H slots is
  split into P = H / 2^16 contiguous partitions; pass p holds only
  partition p in VMEM and processes only the rows whose initial probe slot
  falls in it (same hash => same partition, so a key's whole chain is
  confined to one partition). Cost: P sequential passes over the row
  stream — the classic partitioned hash build, trading row-stream reads
  (sequential HBM bandwidth) for table residency. **Collision strategy**:
  linear probing WITHIN the partition (slot = base + ((local0 + k) mod
  H/P)); a full partition raises the overflow flag (the session's
  capacity-retry loop widens the table, exactly as for the XLA path —
  hash uniformity keeps per-partition skew < a few % at the 2x load
  factor the planner sizes for).

Trade-off being measured (benchmarks/micro_bench.py hashbuild_* rows):
- XLA claim loop: massively parallel per round, ~rounds x N x lanes HBM
  traffic; great when chains are short (table >= 2x NDV).
- This kernel: ZERO HBM traffic per probe (sub-table in VMEM), but row
  processing is sequential on the scalar unit — throughput is bounded by
  probe-chain length x scalar-op latency, not bandwidth.

The engine uses the XLA path by default; DFTPU_PALLAS=1 switches
build_group_table's group-id assignment to this kernel where legal
(single-device, table <= _MAX_TABLE_SLOTS). On CPU the kernel runs in
interpret mode (correctness tests); perf claims are only meaningful on a
real chip — the micro-bench prints both paths so BENCH notes can record
the verdict either way.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

# One VMEM-resident table partition: [2^16, lanes] i32 + used flags is
# ~1.5 MiB at 4 lanes, comfortably inside the ~16 MiB/core budget next to
# a 2^15-row key block.
_MAX_VMEM_SLOTS = 1 << 16
_ROW_BLOCK = 1 << 15
# Beyond 16 partitions the P full row passes stop paying for residency;
# the XLA claim loop takes over (its rounds scale with chain length, not
# table size).
_MAX_PARTITIONS = 16
_MAX_TABLE_SLOTS = _MAX_VMEM_SLOTS * _MAX_PARTITIONS

# (the legacy _MAX_VMEM_ROWS row gate is gone: row blocking removed it)

# Probe chains longer than this are treated as table-too-small, matching
# ops/join.probe_group_table's max_rounds so the pallas and XLA probe paths
# report overflow on exactly the same inputs.
_PROBE_ROUNDS = 512


class PallasCapacityError(ValueError):
    """A requested table cannot be laid out within the kernel's VMEM
    partition budget. Typed (instead of a bare ValueError) so planners can
    degrade to the XLA path and so the session's capacity-retry loop — which
    keys on the word "overflow" — does NOT spin widening a table that can
    never fit. Surfaced statically as verifier diagnostic DFTPU025."""


def pallas_available() -> bool:
    try:
        from jax.experimental import pallas as pl  # noqa: F401

        return True
    except Exception:  # pragma: no cover
        return False


def use_pallas_hash() -> bool:
    return os.environ.get("DFTPU_PALLAS", "0") == "1" and pallas_available()


@partial(jax.jit, static_argnames=("num_slots", "interpret"))
def pallas_build_group_ids(
    keys_mat: jnp.ndarray,  # [N, L] int32 folded key lanes
    slot0: jnp.ndarray,  # [N] int32 initial probe slot (hash & mask)
    live: jnp.ndarray,  # [N] bool
    num_slots: int,
    interpret: bool = False,
):
    """-> (gid [N] i32, slot_keys [H, L] i32, slot_used [H] bool,
    overflow bool). Sequential insertion semantics: the first live row of a
    key claims a slot along its (partition-confined) probe chain. Grouping
    is consistent with the XLA claim loop but slot layout may differ (see
    module docstring)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, lanes = keys_mat.shape
    h = num_slots
    assert h & (h - 1) == 0
    if h > _MAX_TABLE_SLOTS:
        raise PallasCapacityError(
            f"{h} slots exceed {_MAX_PARTITIONS} VMEM partitions"
        )
    hp = min(h, _MAX_VMEM_SLOTS)
    num_parts = h // hp
    block = min(_ROW_BLOCK, max(
        8, 1 << max(int(np.ceil(np.log2(max(n, 1)))), 3)
    ))
    n_pad = -(-n // block) * block
    nb = n_pad // block

    keys_p = jnp.zeros((n_pad, lanes), jnp.int32).at[:n].set(
        keys_mat.astype(jnp.int32)
    )
    slot0_p = jnp.zeros((n_pad,), jnp.int32).at[:n].set(
        slot0.astype(jnp.int32)
    )
    live_p = jnp.zeros((n_pad,), jnp.int32).at[:n].set(live.astype(jnp.int32))

    def partition_pass(part: int):
        """One pallas_call per table partition: rows stream through the
        grid in blocks while the partition's sub-table persists in VMEM
        scratch (TPU grids run sequentially). A separate call per
        partition keeps each pass's state machine trivial — no
        cross-partition output aliasing semantics to get wrong."""

        def kernel(keys_ref, slot0_ref, live_ref, gid_ref,
                   tkeys_ref, used_ref, over_ref, tk_s, used_s, over_s):
            b = pl.program_id(0)

            @pl.when(b == 0)
            def _():
                tk_s[:, :] = jnp.zeros((hp, lanes), jnp.int32)
                used_s[:] = jnp.zeros((hp,), jnp.int32)
                over_s[0] = jnp.int32(0)

            def row(i, _):
                s0 = slot0_ref[i]
                in_part = (s0 // hp) == part
                is_live = (live_ref[i] != 0) & in_part
                local0 = s0 % hp

                # PURE probe: walk the chain reading the sub-table; all
                # mutation happens once, after the loop (stateful ops
                # inside while bodies do not discharge reliably into
                # pallas refs)
                def probe_body(state):
                    slot, done, steps = state
                    occupied = used_s[slot] != 0
                    match = jnp.bool_(True)
                    for lane in range(lanes):
                        match = match & (
                            tk_s[slot, lane] == keys_ref[i, lane]
                        )
                    resolved = (
                        jnp.logical_not(occupied) | (occupied & match)
                    )
                    nxt = jnp.where(
                        resolved, slot, (slot + 1) % jnp.int32(hp)
                    )
                    return nxt, resolved, steps + 1

                def probe_cond(state):
                    _, done, steps = state
                    return jnp.logical_not(done) & (steps < hp) & is_live

                slot, done, _ = jax.lax.while_loop(
                    probe_cond, probe_body,
                    (local0, jnp.logical_not(is_live), jnp.int32(0)),
                )
                claim = is_live & done & (used_s[slot] == 0)

                @pl.when(claim)
                def _():
                    for lane in range(lanes):
                        tk_s[slot, lane] = keys_ref[i, lane]
                    used_s[slot] = jnp.int32(1)

                @pl.when(is_live & done)
                def _():
                    gid_ref[i] = jnp.int32(part * hp) + slot

                @pl.when(is_live & jnp.logical_not(done))
                def _():
                    over_s[0] = jnp.int32(1)

                @pl.when(jnp.logical_not(is_live))
                def _():
                    gid_ref[i] = jnp.int32(0)  # full block write, no alias

                return _

            jax.lax.fori_loop(0, block, row, None)

            @pl.when(b == nb - 1)
            def _():
                tkeys_ref[:, :] = tk_s[:, :]
                used_ref[:] = used_s[:]

            over_ref[0] = over_s[0]

        return pl.pallas_call(
            kernel,
            grid=(nb,),
            in_specs=[
                pl.BlockSpec((block, lanes), lambda b: (b, 0)),
                pl.BlockSpec((block,), lambda b: (b,)),
                pl.BlockSpec((block,), lambda b: (b,)),
            ],
            out_specs=[
                pl.BlockSpec((block,), lambda b: (b,)),
                pl.BlockSpec((hp, lanes), lambda b: (0, 0)),
                pl.BlockSpec((hp,), lambda b: (0,)),
                pl.BlockSpec((1,), lambda b: (0,)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((n_pad,), jnp.int32),
                jax.ShapeDtypeStruct((hp, lanes), jnp.int32),
                jax.ShapeDtypeStruct((hp,), jnp.int32),
                jax.ShapeDtypeStruct((1,), jnp.int32),
            ],
            scratch_shapes=[
                pltpu.VMEM((hp, lanes), jnp.int32),
                pltpu.VMEM((hp,), jnp.int32),
                pltpu.SMEM((1,), jnp.int32),
            ],
            interpret=interpret,
        )(keys_p, slot0_p, live_p)

    gid = jnp.zeros((n_pad,), jnp.int32)
    part_of_row = slot0_p // hp
    tkeys_parts = []
    used_parts = []
    over = jnp.asarray(False)
    for part in range(num_parts):
        gid_p, tk_p, used_p, over_p = partition_pass(part)
        gid = jnp.where(part_of_row == part, gid_p, gid)
        tkeys_parts.append(tk_p)
        used_parts.append(used_p)
        over = over | (over_p[0] != 0)
    tkeys = jnp.concatenate(tkeys_parts, axis=0)
    used = jnp.concatenate(used_parts, axis=0)
    return gid[:n], tkeys, used.astype(jnp.bool_), over


@partial(jax.jit, static_argnames=("table_slots", "interpret"))
def pallas_multiway_probe(
    keys_mat: jnp.ndarray,  # [N, K, Lmax] int32 per-table folded key lanes
    slot0_mat: jnp.ndarray,  # [N, K] int32 LOCAL initial slot per table
    active_mat: jnp.ndarray,  # [N, K] bool-ish: live row with non-null keys
    tkeys_packed: jnp.ndarray,  # [sum(H_k), Lmax] int32 tables, concatenated
    used_packed: jnp.ndarray,  # [sum(H_k)] int32 occupancy, concatenated
    table_slots: tuple,  # static per-table slot counts (pow2, <= one VMEM part)
    interpret: bool = False,
):
    """Cascaded multi-table probe: ONE grid pass where every row walks all
    K open-addressed tables back to back (the multiway-join formulation of
    *Efficient Multiway Hash Join on Reconfigurable Hardware* — the K
    tables play the role of the K pipelined CAM stages). All K tables are
    VMEM-resident simultaneously, so the cascade costs one row-stream read
    where K binary probes cost K.

    -> (found [N, K] i32 local slot or -1, over [K] bool). Semantics are
    exactly ops/join.probe_group_table per table: linear probing from
    slot0, stop at an empty slot (absent) or a full-lane match, overflow
    after _PROBE_ROUNDS unresolved steps. Lanes beyond a table's true lane
    count must be zero-padded on BOTH sides (zero == zero keeps the
    compare neutral).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, ntab, lanes = keys_mat.shape
    assert ntab == len(table_slots)
    offsets = []
    total = 0
    for hk in table_slots:
        assert hk & (hk - 1) == 0
        if hk > _MAX_VMEM_SLOTS:
            raise PallasCapacityError(
                f"multiway probe table of {hk} slots exceeds one VMEM "
                f"partition ({_MAX_VMEM_SLOTS})"
            )
        offsets.append(total)
        total += hk
    if total != tkeys_packed.shape[0]:
        raise ValueError(
            f"packed tables hold {tkeys_packed.shape[0]} slots, "
            f"table_slots sums to {total}"
        )

    block = min(_ROW_BLOCK, max(
        8, 1 << max(int(np.ceil(np.log2(max(n, 1)))), 3)
    ))
    n_pad = -(-n // block) * block
    nb = n_pad // block

    keys_p = jnp.zeros((n_pad, ntab, lanes), jnp.int32).at[:n].set(
        keys_mat.astype(jnp.int32)
    )
    slot0_p = jnp.zeros((n_pad, ntab), jnp.int32).at[:n].set(
        slot0_mat.astype(jnp.int32)
    )
    active_p = jnp.zeros((n_pad, ntab), jnp.int32).at[:n].set(
        active_mat.astype(jnp.int32)
    )

    def kernel(keys_ref, slot0_ref, active_ref, tkeys_ref, used_ref,
               found_ref, over_ref, over_s):
        b = pl.program_id(0)

        @pl.when(b == 0)
        def _():
            for k in range(ntab):
                over_s[k] = jnp.int32(0)

        def row(i, _):
            for k in range(ntab):  # static cascade across the K tables
                off = offsets[k]
                hk = table_slots[k]
                is_act = active_ref[i, k] != 0

                def probe_body(state, _off=off, _hk=hk, _k=k):
                    slot, done, found, steps = state
                    occupied = used_ref[_off + slot] != 0
                    match = occupied
                    for lane in range(lanes):
                        match = match & (
                            tkeys_ref[_off + slot, lane]
                            == keys_ref[i, _k, lane]
                        )
                    found = jnp.where(match, slot, found)
                    resolved = jnp.logical_not(occupied) | match
                    nxt = jnp.where(
                        resolved, slot, (slot + 1) % jnp.int32(_hk)
                    )
                    return nxt, resolved, found, steps + 1

                def probe_cond(state, _is_act=is_act):
                    _slot, done, _found, steps = state
                    return (jnp.logical_not(done)
                            & (steps < _PROBE_ROUNDS) & _is_act)

                _, done, found, _ = jax.lax.while_loop(
                    probe_cond, probe_body,
                    (slot0_ref[i, k], jnp.logical_not(is_act),
                     jnp.int32(-1), jnp.int32(0)),
                )

                @pl.when(is_act & jnp.logical_not(done))
                def _(_k=k):
                    over_s[_k] = jnp.int32(1)

                found_ref[i, k] = found
            return _

        jax.lax.fori_loop(0, block, row, None)

        for k in range(ntab):
            over_ref[k] = over_s[k]

    found, over = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((block, ntab, lanes), lambda b: (b, 0, 0)),
            pl.BlockSpec((block, ntab), lambda b: (b, 0)),
            pl.BlockSpec((block, ntab), lambda b: (b, 0)),
            pl.BlockSpec((total, lanes), lambda b: (0, 0)),
            pl.BlockSpec((total,), lambda b: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((block, ntab), lambda b: (b, 0)),
            pl.BlockSpec((ntab,), lambda b: (0,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, ntab), jnp.int32),
            jax.ShapeDtypeStruct((ntab,), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.SMEM((ntab,), jnp.int32),
        ],
        interpret=interpret,
    )(keys_p, slot0_p, active_p,
      tkeys_packed.astype(jnp.int32), used_packed.astype(jnp.int32))
    return found[:n], over.astype(jnp.bool_)


def multiway_probe_reference(keys_mat, slot0_mat, active_mat,
                             tkeys_packed, used_packed, table_slots):
    """Pure-numpy oracle for pallas_multiway_probe (same per-table
    semantics as ops/join.probe_group_table)."""
    keys_mat = np.asarray(keys_mat)
    slot0_mat = np.asarray(slot0_mat)
    active_mat = np.asarray(active_mat).astype(bool)
    tkeys_packed = np.asarray(tkeys_packed)
    used_packed = np.asarray(used_packed).astype(bool)
    n, ntab, _lanes = keys_mat.shape
    offsets = np.concatenate([[0], np.cumsum(table_slots)])[:-1]
    found = np.full((n, ntab), -1, np.int32)
    over = np.zeros(ntab, bool)
    for i in range(n):
        for k in range(ntab):
            if not active_mat[i, k]:
                continue
            off, hk = int(offsets[k]), int(table_slots[k])
            slot = int(slot0_mat[i, k])
            for _ in range(_PROBE_ROUNDS):
                if not used_packed[off + slot]:
                    break
                if (tkeys_packed[off + slot] == keys_mat[i, k]).all():
                    found[i, k] = slot
                    break
                slot = (slot + 1) % hk
            else:
                over[k] = True
    return found, over


@partial(jax.jit, static_argnames=("num_slots", "ops", "interpret"))
def pallas_global_hash_aggregate(
    keys_mat: jnp.ndarray,  # [N, L] int32 folded group-key lanes
    slot0: jnp.ndarray,  # [N] int32 initial probe slot (hash & mask)
    live: jnp.ndarray,  # [N] bool
    values: jnp.ndarray,  # [N, A] int32, identity-mapped where invalid
    num_slots: int,
    ops: tuple,  # static, per accumulator column: 'sum' | 'min' | 'max'
    interpret: bool = False,
):
    """Global-hash-table aggregation (*Global Hash Tables Strike Back!*):
    ONE shared open-addressed table builds groups AND folds accumulators in
    the same VMEM-resident pass — no per-partition tables, no merge step.
    Same partition-pass machinery as pallas_build_group_ids (a table wider
    than one VMEM partition runs P sequential passes, a key's chain
    confined to its partition).

    Callers pre-map invalid rows' values to each op's identity (sum -> 0,
    min -> INT32_MAX, max -> INT32_MIN) so the kernel needs no validity
    lanes. Accumulation is int32: callers gate on value domains that fit.

    -> (gid [N] i32 slot per live row, rep [H] i32 claiming row index,
    used [H] bool, acc [H, A] i32, overflow bool). gid lets the caller
    run follow-up per-group scatters (e.g. the int32 sum-range guard)
    without a second build pass.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, lanes = keys_mat.shape
    _n2, na = values.shape
    assert na == len(ops)
    h = num_slots
    assert h & (h - 1) == 0
    if h > _MAX_TABLE_SLOTS:
        raise PallasCapacityError(
            f"{h} slots exceed {_MAX_PARTITIONS} VMEM partitions"
        )
    hp = min(h, _MAX_VMEM_SLOTS)
    num_parts = h // hp
    block = min(_ROW_BLOCK, max(
        8, 1 << max(int(np.ceil(np.log2(max(n, 1)))), 3)
    ))
    n_pad = -(-n // block) * block
    nb = n_pad // block

    _IDENT = {
        "sum": 0,
        "min": np.iinfo(np.int32).max,
        "max": np.iinfo(np.int32).min,
    }
    ident_list = [_IDENT[op] for op in ops]  # static: inlined in-kernel
    ident_row = jnp.asarray(ident_list, jnp.int32)

    keys_p = jnp.zeros((n_pad, lanes), jnp.int32).at[:n].set(
        keys_mat.astype(jnp.int32)
    )
    slot0_p = jnp.zeros((n_pad,), jnp.int32).at[:n].set(
        slot0.astype(jnp.int32)
    )
    live_p = jnp.zeros((n_pad,), jnp.int32).at[:n].set(live.astype(jnp.int32))
    vals_p = jnp.broadcast_to(ident_row, (n_pad, na)).at[:n].set(
        values.astype(jnp.int32)
    )

    def partition_pass(part: int):
        def kernel(keys_ref, slot0_ref, live_ref, vals_ref,
                   gid_ref, rep_ref, used_ref, acc_ref, over_ref,
                   tk_s, used_s, rep_s, acc_s, over_s):
            b = pl.program_id(0)

            @pl.when(b == 0)
            def _():
                tk_s[:, :] = jnp.zeros((hp, lanes), jnp.int32)
                used_s[:] = jnp.zeros((hp,), jnp.int32)
                rep_s[:] = jnp.zeros((hp,), jnp.int32)
                for a in range(na):  # scalar fills: no vector constant
                    acc_s[:, a] = jnp.full((hp,), ident_list[a], jnp.int32)
                over_s[0] = jnp.int32(0)

            def row(i, _):
                s0 = slot0_ref[i]
                in_part = (s0 // hp) == part
                is_live = (live_ref[i] != 0) & in_part
                local0 = s0 % hp

                def probe_body(state):
                    slot, done, steps = state
                    occupied = used_s[slot] != 0
                    match = jnp.bool_(True)
                    for lane in range(lanes):
                        match = match & (
                            tk_s[slot, lane] == keys_ref[i, lane]
                        )
                    resolved = (
                        jnp.logical_not(occupied) | (occupied & match)
                    )
                    nxt = jnp.where(
                        resolved, slot, (slot + 1) % jnp.int32(hp)
                    )
                    return nxt, resolved, steps + 1

                def probe_cond(state):
                    _, done, steps = state
                    return jnp.logical_not(done) & (steps < hp) & is_live

                slot, done, _ = jax.lax.while_loop(
                    probe_cond, probe_body,
                    (local0, jnp.logical_not(is_live), jnp.int32(0)),
                )
                claim = is_live & done & (used_s[slot] == 0)

                @pl.when(claim)
                def _():
                    for lane in range(lanes):
                        tk_s[slot, lane] = keys_ref[i, lane]
                    used_s[slot] = jnp.int32(1)
                    rep_s[slot] = jnp.int32(b * block) + i

                @pl.when(is_live & done)
                def _():
                    gid_ref[i] = jnp.int32(part * hp) + slot
                    for a in range(na):  # static accumulator plan
                        if ops[a] == "sum":
                            acc_s[slot, a] = acc_s[slot, a] + vals_ref[i, a]
                        elif ops[a] == "min":
                            acc_s[slot, a] = jnp.minimum(
                                acc_s[slot, a], vals_ref[i, a]
                            )
                        else:
                            acc_s[slot, a] = jnp.maximum(
                                acc_s[slot, a], vals_ref[i, a]
                            )

                @pl.when(is_live & jnp.logical_not(done))
                def _():
                    over_s[0] = jnp.int32(1)

                @pl.when(jnp.logical_not(is_live))
                def _():
                    gid_ref[i] = jnp.int32(0)  # full block write, no alias

                return _

            jax.lax.fori_loop(0, block, row, None)

            @pl.when(b == nb - 1)
            def _():
                rep_ref[:] = rep_s[:]
                used_ref[:] = used_s[:]
                acc_ref[:, :] = acc_s[:, :]

            over_ref[0] = over_s[0]

        return pl.pallas_call(
            kernel,
            grid=(nb,),
            in_specs=[
                pl.BlockSpec((block, lanes), lambda b: (b, 0)),
                pl.BlockSpec((block,), lambda b: (b,)),
                pl.BlockSpec((block,), lambda b: (b,)),
                pl.BlockSpec((block, na), lambda b: (b, 0)),
            ],
            out_specs=[
                pl.BlockSpec((block,), lambda b: (b,)),
                pl.BlockSpec((hp,), lambda b: (0,)),
                pl.BlockSpec((hp,), lambda b: (0,)),
                pl.BlockSpec((hp, na), lambda b: (0, 0)),
                pl.BlockSpec((1,), lambda b: (0,)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((n_pad,), jnp.int32),
                jax.ShapeDtypeStruct((hp,), jnp.int32),
                jax.ShapeDtypeStruct((hp,), jnp.int32),
                jax.ShapeDtypeStruct((hp, na), jnp.int32),
                jax.ShapeDtypeStruct((1,), jnp.int32),
            ],
            scratch_shapes=[
                pltpu.VMEM((hp, lanes), jnp.int32),
                pltpu.VMEM((hp,), jnp.int32),
                pltpu.VMEM((hp,), jnp.int32),
                pltpu.VMEM((hp, na), jnp.int32),
                pltpu.SMEM((1,), jnp.int32),
            ],
            interpret=interpret,
        )(keys_p, slot0_p, live_p, vals_p)

    gid = jnp.zeros((n_pad,), jnp.int32)
    part_of_row = slot0_p // hp
    rep_parts, used_parts, acc_parts = [], [], []
    over = jnp.asarray(False)
    for part in range(num_parts):
        gid_p, rep_p, used_p, acc_p, over_p = partition_pass(part)
        gid = jnp.where(part_of_row == part, gid_p, gid)
        rep_parts.append(rep_p)
        used_parts.append(used_p)
        acc_parts.append(acc_p)
        over = over | (over_p[0] != 0)
    rep = jnp.concatenate(rep_parts, axis=0)
    used = jnp.concatenate(used_parts, axis=0)
    acc = jnp.concatenate(acc_parts, axis=0)
    return gid[:n], rep, used.astype(jnp.bool_), acc, over


def global_hash_aggregate_reference(keys_mat, slot0, live, values,
                                    num_slots, ops):
    """Pure-numpy oracle for pallas_global_hash_aggregate (same
    partition-confined sequential-insert semantics as
    build_group_ids_reference, plus the accumulator fold)."""
    gid, _tkeys, used, overflow = build_group_ids_reference(
        keys_mat, slot0, live, num_slots
    )
    values = np.asarray(values)
    live = np.asarray(live).astype(bool)
    n, na = values.shape
    _IDENT = {
        "sum": 0,
        "min": np.iinfo(np.int32).max,
        "max": np.iinfo(np.int32).min,
    }
    acc = np.tile(
        np.asarray([_IDENT[op] for op in ops], np.int32), (num_slots, 1)
    )
    rep = np.zeros(num_slots, np.int32)
    seen = np.zeros(num_slots, bool)
    for i in range(n):
        if not live[i]:
            continue
        s = int(gid[i])
        if not seen[s]:
            rep[s] = i
            seen[s] = True
        for a, op in enumerate(ops):
            if op == "sum":
                acc[s, a] = np.int32(acc[s, a] + values[i, a])
            elif op == "min":
                acc[s, a] = min(acc[s, a], values[i, a])
            else:
                acc[s, a] = max(acc[s, a], values[i, a])
    return gid, rep, used, acc, overflow


def build_group_ids_reference(keys_mat, slot0, live, num_slots):
    """Pure-numpy oracle for the kernel's sequential-insert semantics
    (partition-confined linear probing, partition width = _MAX_VMEM_SLOTS)."""
    keys_mat = np.asarray(keys_mat)
    slot0 = np.asarray(slot0)
    live = np.asarray(live)
    n, lanes = keys_mat.shape
    hp = min(num_slots, _MAX_VMEM_SLOTS)
    tkeys = np.zeros((num_slots, lanes), np.int32)
    used = np.zeros(num_slots, bool)
    gid = np.zeros(n, np.int32)
    overflow = False
    for i in range(n):
        if not live[i]:
            continue
        base = (int(slot0[i]) // hp) * hp
        local = int(slot0[i]) % hp
        for _ in range(hp):
            slot = base + local
            if not used[slot]:
                tkeys[slot] = keys_mat[i]
                used[slot] = True
                gid[i] = slot
                break
            if (tkeys[slot] == keys_mat[i]).all():
                gid[i] = slot
                break
            local = (local + 1) % hp
        else:
            overflow = True
    return gid, tkeys, used, overflow
