"""Pallas claim-loop hash-table build (TPU kernel).

SURVEY.md §7 hard part (b): the XLA claim loop (ops/aggregate.py
build_group_table) runs O(probe-chain) ROUNDS, each a full HBM pass over all
rows plus scatters into the [H, lanes] table. This kernel is the
VMEM-resident alternative: sequential passes over the rows with the (sub-)
table held in VMEM, so each probe is an on-chip read instead of an HBM
round.

Production shape (round 5; the round-4 version staged everything as single
VMEM blocks and was gated to 2^16 slots / 2^18 rows):

- **Row blocking.** Rows stream through a grid dimension in blocks of
  2^15; the table lives in VMEM *scratch*, which persists across grid
  steps (TPU grids execute sequentially), so row count is unbounded.
- **Tables > VMEM: hash-partitioned multi-pass.** A table of H slots is
  split into P = H / 2^16 contiguous partitions; pass p holds only
  partition p in VMEM and processes only the rows whose initial probe slot
  falls in it (same hash => same partition, so a key's whole chain is
  confined to one partition). Cost: P sequential passes over the row
  stream — the classic partitioned hash build, trading row-stream reads
  (sequential HBM bandwidth) for table residency. **Collision strategy**:
  linear probing WITHIN the partition (slot = base + ((local0 + k) mod
  H/P)); a full partition raises the overflow flag (the session's
  capacity-retry loop widens the table, exactly as for the XLA path —
  hash uniformity keeps per-partition skew < a few % at the 2x load
  factor the planner sizes for).

Trade-off being measured (benchmarks/micro_bench.py hashbuild_* rows):
- XLA claim loop: massively parallel per round, ~rounds x N x lanes HBM
  traffic; great when chains are short (table >= 2x NDV).
- This kernel: ZERO HBM traffic per probe (sub-table in VMEM), but row
  processing is sequential on the scalar unit — throughput is bounded by
  probe-chain length x scalar-op latency, not bandwidth.

The engine uses the XLA path by default; DFTPU_PALLAS=1 switches
build_group_table's group-id assignment to this kernel where legal
(single-device, table <= _MAX_TABLE_SLOTS). On CPU the kernel runs in
interpret mode (correctness tests); perf claims are only meaningful on a
real chip — the micro-bench prints both paths so BENCH notes can record
the verdict either way.
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

# One VMEM-resident table partition: [2^16, lanes] i32 + used flags is
# ~1.5 MiB at 4 lanes, comfortably inside the ~16 MiB/core budget next to
# a 2^15-row key block.
_MAX_VMEM_SLOTS = 1 << 16
_ROW_BLOCK = 1 << 15
# Beyond 16 partitions the P full row passes stop paying for residency;
# the XLA claim loop takes over (its rounds scale with chain length, not
# table size).
_MAX_PARTITIONS = 16
_MAX_TABLE_SLOTS = _MAX_VMEM_SLOTS * _MAX_PARTITIONS

# (the legacy _MAX_VMEM_ROWS row gate is gone: row blocking removed it)


def pallas_available() -> bool:
    try:
        from jax.experimental import pallas as pl  # noqa: F401

        return True
    except Exception:  # pragma: no cover
        return False


def use_pallas_hash() -> bool:
    return os.environ.get("DFTPU_PALLAS", "0") == "1" and pallas_available()


@partial(jax.jit, static_argnames=("num_slots", "interpret"))
def pallas_build_group_ids(
    keys_mat: jnp.ndarray,  # [N, L] int32 folded key lanes
    slot0: jnp.ndarray,  # [N] int32 initial probe slot (hash & mask)
    live: jnp.ndarray,  # [N] bool
    num_slots: int,
    interpret: bool = False,
):
    """-> (gid [N] i32, slot_keys [H, L] i32, slot_used [H] bool,
    overflow bool). Sequential insertion semantics: the first live row of a
    key claims a slot along its (partition-confined) probe chain. Grouping
    is consistent with the XLA claim loop but slot layout may differ (see
    module docstring)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, lanes = keys_mat.shape
    h = num_slots
    assert h & (h - 1) == 0
    if h > _MAX_TABLE_SLOTS:
        raise ValueError(
            f"{h} slots exceed {_MAX_PARTITIONS} VMEM partitions"
        )
    hp = min(h, _MAX_VMEM_SLOTS)
    num_parts = h // hp
    block = min(_ROW_BLOCK, max(
        8, 1 << max(int(np.ceil(np.log2(max(n, 1)))), 3)
    ))
    n_pad = -(-n // block) * block
    nb = n_pad // block

    keys_p = jnp.zeros((n_pad, lanes), jnp.int32).at[:n].set(
        keys_mat.astype(jnp.int32)
    )
    slot0_p = jnp.zeros((n_pad,), jnp.int32).at[:n].set(
        slot0.astype(jnp.int32)
    )
    live_p = jnp.zeros((n_pad,), jnp.int32).at[:n].set(live.astype(jnp.int32))

    def partition_pass(part: int):
        """One pallas_call per table partition: rows stream through the
        grid in blocks while the partition's sub-table persists in VMEM
        scratch (TPU grids run sequentially). A separate call per
        partition keeps each pass's state machine trivial — no
        cross-partition output aliasing semantics to get wrong."""

        def kernel(keys_ref, slot0_ref, live_ref, gid_ref,
                   tkeys_ref, used_ref, over_ref, tk_s, used_s, over_s):
            b = pl.program_id(0)

            @pl.when(b == 0)
            def _():
                tk_s[:, :] = jnp.zeros((hp, lanes), jnp.int32)
                used_s[:] = jnp.zeros((hp,), jnp.int32)
                over_s[0] = jnp.int32(0)

            def row(i, _):
                s0 = slot0_ref[i]
                in_part = (s0 // hp) == part
                is_live = (live_ref[i] != 0) & in_part
                local0 = s0 % hp

                # PURE probe: walk the chain reading the sub-table; all
                # mutation happens once, after the loop (stateful ops
                # inside while bodies do not discharge reliably into
                # pallas refs)
                def probe_body(state):
                    slot, done, steps = state
                    occupied = used_s[slot] != 0
                    match = jnp.bool_(True)
                    for lane in range(lanes):
                        match = match & (
                            tk_s[slot, lane] == keys_ref[i, lane]
                        )
                    resolved = (
                        jnp.logical_not(occupied) | (occupied & match)
                    )
                    nxt = jnp.where(
                        resolved, slot, (slot + 1) % jnp.int32(hp)
                    )
                    return nxt, resolved, steps + 1

                def probe_cond(state):
                    _, done, steps = state
                    return jnp.logical_not(done) & (steps < hp) & is_live

                slot, done, _ = jax.lax.while_loop(
                    probe_cond, probe_body,
                    (local0, jnp.logical_not(is_live), jnp.int32(0)),
                )
                claim = is_live & done & (used_s[slot] == 0)

                @pl.when(claim)
                def _():
                    for lane in range(lanes):
                        tk_s[slot, lane] = keys_ref[i, lane]
                    used_s[slot] = jnp.int32(1)

                @pl.when(is_live & done)
                def _():
                    gid_ref[i] = jnp.int32(part * hp) + slot

                @pl.when(is_live & jnp.logical_not(done))
                def _():
                    over_s[0] = jnp.int32(1)

                @pl.when(jnp.logical_not(is_live))
                def _():
                    gid_ref[i] = jnp.int32(0)  # full block write, no alias

                return _

            jax.lax.fori_loop(0, block, row, None)

            @pl.when(b == nb - 1)
            def _():
                tkeys_ref[:, :] = tk_s[:, :]
                used_ref[:] = used_s[:]

            over_ref[0] = over_s[0]

        return pl.pallas_call(
            kernel,
            grid=(nb,),
            in_specs=[
                pl.BlockSpec((block, lanes), lambda b: (b, 0)),
                pl.BlockSpec((block,), lambda b: (b,)),
                pl.BlockSpec((block,), lambda b: (b,)),
            ],
            out_specs=[
                pl.BlockSpec((block,), lambda b: (b,)),
                pl.BlockSpec((hp, lanes), lambda b: (0, 0)),
                pl.BlockSpec((hp,), lambda b: (0,)),
                pl.BlockSpec((1,), lambda b: (0,)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((n_pad,), jnp.int32),
                jax.ShapeDtypeStruct((hp, lanes), jnp.int32),
                jax.ShapeDtypeStruct((hp,), jnp.int32),
                jax.ShapeDtypeStruct((1,), jnp.int32),
            ],
            scratch_shapes=[
                pltpu.VMEM((hp, lanes), jnp.int32),
                pltpu.VMEM((hp,), jnp.int32),
                pltpu.SMEM((1,), jnp.int32),
            ],
            interpret=interpret,
        )(keys_p, slot0_p, live_p)

    gid = jnp.zeros((n_pad,), jnp.int32)
    part_of_row = slot0_p // hp
    tkeys_parts = []
    used_parts = []
    over = jnp.asarray(False)
    for part in range(num_parts):
        gid_p, tk_p, used_p, over_p = partition_pass(part)
        gid = jnp.where(part_of_row == part, gid_p, gid)
        tkeys_parts.append(tk_p)
        used_parts.append(used_p)
        over = over | (over_p[0] != 0)
    tkeys = jnp.concatenate(tkeys_parts, axis=0)
    used = jnp.concatenate(used_parts, axis=0)
    return gid[:n], tkeys, used.astype(jnp.bool_), over


def build_group_ids_reference(keys_mat, slot0, live, num_slots):
    """Pure-numpy oracle for the kernel's sequential-insert semantics
    (partition-confined linear probing, partition width = _MAX_VMEM_SLOTS)."""
    keys_mat = np.asarray(keys_mat)
    slot0 = np.asarray(slot0)
    live = np.asarray(live)
    n, lanes = keys_mat.shape
    hp = min(num_slots, _MAX_VMEM_SLOTS)
    tkeys = np.zeros((num_slots, lanes), np.int32)
    used = np.zeros(num_slots, bool)
    gid = np.zeros(n, np.int32)
    overflow = False
    for i in range(n):
        if not live[i]:
            continue
        base = (int(slot0[i]) // hp) * hp
        local = int(slot0[i]) % hp
        for _ in range(hp):
            slot = base + local
            if not used[slot]:
                tkeys[slot] = keys_mat[i]
                used[slot] = True
                gid[i] = slot
                break
            if (tkeys[slot] == keys_mat[i]).all():
                gid[i] = slot
                break
            local = (local + 1) % hp
        else:
            overflow = True
    return gid, tkeys, used, overflow
