"""Vectorized hashing utilities for group-by / join / shuffle partitioning.

The reference gets hashing from DataFusion's `create_hashes` (ahash over Arrow
arrays) for both `RepartitionExec(Hash)` and the hash join/aggregate operators
(SURVEY.md L0). The TPU analogue below is a branch-free 32-bit multiply-xor
mixer evaluated on the VPU over whole columns at once; multi-column keys are
combined with a distinct odd multiplier per column.

All functions operate on [capacity]-shaped int arrays and are jit-safe.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# murmur3-style finalizer constants (public domain)
_C1 = np.uint32(0x85EBCA6B)
_C2 = np.uint32(0xC2B2AE35)


def _mix32(h: jnp.ndarray) -> jnp.ndarray:
    """murmur3 fmix32: avalanche a uint32 lane."""
    h = h ^ (h >> 16)
    h = h * _C1
    h = h ^ (h >> 13)
    h = h * _C2
    h = h ^ (h >> 16)
    return h


def fold_to_u32(col: jnp.ndarray) -> jnp.ndarray:
    """Fold an int/bool/date column to uint32 lanes (hi^lo for 64-bit)."""
    if col.dtype in (jnp.int64, jnp.uint64):
        u = col.astype(jnp.uint64)
        return (u ^ (u >> np.uint64(32))).astype(jnp.uint32)
    if col.dtype in (jnp.float64,):
        u = col.view(jnp.uint64)
        return (u ^ (u >> np.uint64(32))).astype(jnp.uint32)
    if col.dtype in (jnp.float32,):
        return col.view(jnp.uint32)
    return col.astype(jnp.uint32)


def fold_payload(col: jnp.ndarray, lane_dtype) -> jnp.ndarray:
    """Fold a key column to a fixed-width integer lane for exact equality
    compares (claim-loop hash table / join probe). Floats are bit-cast so
    +0.0/-0.0 and NaN payloads compare bitwise, matching the hash."""
    if col.dtype == jnp.float64:  # x64 mode only; lane_dtype is int64 there
        return col.view(jnp.int64).astype(lane_dtype)
    if col.dtype == jnp.float32:
        return col.view(jnp.int32).astype(lane_dtype)
    return col.astype(lane_dtype)


def hash_columns(cols: list[jnp.ndarray], valids: list[jnp.ndarray | None]) -> jnp.ndarray:
    """Combined uint32 hash of multiple key columns (nulls hash as a fixed
    tag so SQL's null-equal-null grouping works)."""
    assert cols
    h = jnp.full(cols[0].shape, np.uint32(0x9E3779B9), dtype=jnp.uint32)
    for i, (c, v) in enumerate(zip(cols, valids)):
        lane = fold_to_u32(c)
        if v is not None:
            lane = jnp.where(v, lane, np.uint32(0xDEADBEEF))
        # distinct odd multiplier per column index keeps (a,b) != (b,a)
        mult = np.uint32(0x01000193 + 2 * i)
        h = (h ^ _mix32(lane)) * mult
    return _mix32(h)
