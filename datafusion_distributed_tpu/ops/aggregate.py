"""Hash GROUP BY aggregation, fully vectorized for the TPU.

The reference relies on DataFusion's `AggregateExec` (Partial / Final /
PartialReduce modes — the PartialReduce shuffle-volume optimization is
`/root/reference/src/distributed_planner/partial_reduce_below_network_shuffles.rs`).
A row-wise hash table doesn't map to a SIMD machine, so this kernel builds the
group table with *vectorized claim rounds* instead of per-row probing:

  round := every unresolved row scatter-mins its row-id into its candidate
  slot ("claim"); winners write their keys; every row gathers its slot's keys
  and either resolves (match) or advances to the next probe slot (linear
  probing). Each round is O(N) scatter/gather on the VPU; the number of rounds
  is bounded by the longest probe chain, so for a table sized >= 2x NDV it
  converges in a handful of rounds (cf. "Global Hash Tables Strike Back!",
  PAPERS.md).

Aggregates then reduce by slot id with `segment_sum` / scatter-min/max, which
XLA lowers to deterministic TPU scatters — giving run-to-run identical float
results (the bit-parity requirement of SURVEY.md §7 hard part (d)).

Group keys may be any fixed-width device dtype (dict codes included); nulls
group together (SQL semantics), tracked via a folded-in validity lane.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from datafusion_distributed_tpu import precision
from datafusion_distributed_tpu.ops.hash import fold_payload, hash_columns
from datafusion_distributed_tpu.ops.table import Column, Table
from datafusion_distributed_tpu.schema import DataType

_LANE = precision.LANE_INT
_ACC_INT = precision.ACC_INT

@dataclass(frozen=True)
class AggSpec:
    """One aggregate: func in {sum,count,count_star,min,max,avg}."""

    func: str
    input_name: Optional[str]  # None for count_star
    output_name: str


#: Aggregate functions whose partial states merge LOSSLESSLY through the
#: partial -> (exchange) -> final mode chain above: sum/count/min/max
#: merge as themselves, avg decomposes into a (sum, count) pair the
#: final stage recombines. This is the eligibility set the planner's
#: partial-aggregate push-down consults (planner/distributed.py
#: `_partial_agg_pushdown_pass`) — one source of truth next to the
#: kernel that implements the merges, so a new aggregate function only
#: becomes push-down-eligible when its merge modes actually exist here.
#: (The variance family also decomposes — see _VARIANCE_FUNCS — but is
#: kept out of the push-down set: the ISSUE scope is sum/count/min/max
#: + avg, and variance's (sum, sumsq, count) triple WIDENS the exchange
#: payload 3x, defeating the bytes-reduction goal at low NDV gains.)
PUSHDOWN_DECOMPOSABLE_FUNCS = frozenset(
    {"sum", "count", "count_star", "min", "max", "avg"}
)


@dataclass
class GroupTable:
    """Result of the claim loop: per-row group ids + per-slot key columns."""

    group_ids: jnp.ndarray  # [N] int32 slot index per row (garbage for dead rows)
    slot_used: jnp.ndarray  # [H] bool
    slot_keys: list[jnp.ndarray]  # per key column: [H] values
    slot_key_valid: list[Optional[jnp.ndarray]]  # per key column: [H] bool or None
    num_groups: jnp.ndarray  # scalar int32
    overflow: jnp.ndarray  # scalar bool: table too small, results invalid


def _fold_key_lanes(key_cols, key_valids, lane_plan, n):
    """Keys folded to fixed-width integer lanes (int32 in tpu precision
    mode, int64 in x64 mode). Nullability is an explicit extra lane in the
    compare matrix (not an in-band sentinel, which a real key value could
    collide with): column i with lane_plan[i] contributes lanes
    [payload-with-nulls-zeroed, is_valid]. Shared by the claim loop and
    the pallas kernel dispatches so every path compares identical lanes.
    Returns (lanes list, per-key-column validity lane index or None)."""
    keys64 = []
    valid_lane_of: list[Optional[int]] = []  # per key col: its validity lane idx
    for c, v in zip(key_cols, key_valids):
        payload = fold_payload(c, _LANE)
        if v is not None:
            payload = jnp.where(v, payload, 0)
        keys64.append(payload)
        valid_lane_of.append(None)
    for i, (v, want) in enumerate(zip(key_valids, lane_plan)):
        if want:
            valid_lane_of[i] = len(keys64)
            keys64.append(
                v.astype(_LANE) if v is not None
                else jnp.ones(n, dtype=_LANE)
            )
    return keys64, valid_lane_of


def build_group_table(
    key_cols: Sequence[jnp.ndarray],
    key_valids: Sequence[Optional[jnp.ndarray]],
    live: jnp.ndarray,
    num_slots: int,
    max_rounds: int = 512,
    lane_plan: Optional[Sequence[bool]] = None,
) -> GroupTable:
    """Assign each live row a group id (a slot in a power-of-two table).

    ``lane_plan`` fixes which key columns carry a validity lane (True).
    Joins pass the union of build+probe nullability so both sides fold to
    identical compare-matrix shapes; by default it mirrors ``key_valids``.
    """
    assert num_slots & (num_slots - 1) == 0, "num_slots must be a power of two"
    n = key_cols[0].shape[0]
    k = len(key_cols)
    mask = np.uint32(num_slots - 1)
    if lane_plan is None:
        lane_plan = [v is not None for v in key_valids]

    keys64, valid_lane_of = _fold_key_lanes(key_cols, key_valids, lane_plan, n)

    h0 = hash_columns(list(key_cols), list(key_valids))
    slot0 = (h0 & mask).astype(jnp.int32)

    n_lanes = len(keys64)
    slot_keys0 = jnp.zeros((num_slots, n_lanes), dtype=_LANE)
    slot_used0 = jnp.zeros(num_slots, dtype=jnp.bool_)
    keys_mat = jnp.stack(keys64, axis=1)  # [N, k]

    if np.dtype(_LANE).itemsize == 4:
        from datafusion_distributed_tpu.ops import pallas_hash

        if (
            pallas_hash.use_pallas_hash()
            and num_slots <= pallas_hash._MAX_TABLE_SLOTS
        ):
            # VMEM-resident build (DFTPU_PALLAS=1): row-blocked grid,
            # partitioned multi-pass for tables beyond one VMEM block.
            # Grouping is consistent with the claim loop below, but the
            # slot LAYOUT may differ (sequential partition-confined vs
            # min-row-id claim resolution) — see ops/pallas_hash.py for
            # the trade-off being measured
            interpret = jax.default_backend() != "tpu"
            gid_p, tkeys_p, used_p, over_p = (
                pallas_hash.pallas_build_group_ids(
                    keys_mat, slot0, live, num_slots, interpret=interpret
                )
            )
            return _group_table_from_raw(
                gid_p, tkeys_p.astype(_LANE), used_p, over_p,
                key_cols, key_valids, valid_lane_of,
            )

    # Dead rows are born resolved and never claim a slot.
    resolved0 = ~live
    gid0 = jnp.zeros(n, dtype=jnp.int32)

    def cond(state):
        resolved, *_ , rounds = state
        return (~jnp.all(resolved)) & (rounds < max_rounds)

    def body(state):
        resolved, slot, gid, slot_keys, slot_used, rounds = state
        # 1. unresolved rows claim their candidate slot (min row-id wins)
        claim_slot = jnp.where(resolved, num_slots, slot)  # drop resolved
        owner = jnp.full(num_slots, n, dtype=jnp.int32)
        owner = owner.at[claim_slot].min(
            jnp.arange(n, dtype=jnp.int32), mode="drop"
        )
        # Only claims on EMPTY slots count; occupied slots keep their keys.
        claimable = ~slot_used
        winner = (~resolved) & (owner[slot] == jnp.arange(n, dtype=jnp.int32)) & (
            claimable[slot]
        )
        # 2. winners write their keys and mark slots used
        wslot = jnp.where(winner, slot, num_slots)
        slot_keys = slot_keys.at[wslot].set(keys_mat, mode="drop")
        slot_used = slot_used.at[wslot].set(True, mode="drop")
        # 3. everyone gathers; match -> resolve, mismatch on used slot -> probe
        mine = slot_keys[slot]  # [N, k]
        used = slot_used[slot]
        match = used & jnp.all(mine == keys_mat, axis=1)
        newly = (~resolved) & match
        gid = jnp.where(newly, slot, gid)
        resolved = resolved | newly
        advance = (~resolved) & used & ~match
        slot = jnp.where(
            advance, ((slot + 1).astype(jnp.uint32) & mask).astype(jnp.int32), slot
        )
        return resolved, slot, gid, slot_keys, slot_used, rounds + 1

    state = (
        resolved0, slot0, gid0, slot_keys0, slot_used0,
        jnp.asarray(0, dtype=jnp.int32),
    )
    resolved, slot, gid, slot_keys, slot_used, _ = jax.lax.while_loop(
        cond, body, state
    )
    overflow = ~jnp.all(resolved)
    return _group_table_from_raw(
        gid, slot_keys, slot_used, overflow, key_cols, key_valids,
        valid_lane_of,
    )


def _group_table_from_raw(gid, slot_keys, slot_used, overflow, key_cols,
                          key_valids, valid_lane_of) -> GroupTable:
    """Unfold the raw [H, lanes] table back into per-key-column arrays."""
    out_keys = []
    out_valid = []
    for i, (c, v) in enumerate(zip(key_cols, key_valids)):
        payload = slot_keys[:, i]
        lane = valid_lane_of[i]
        if lane is not None:
            key_valid = slot_keys[:, lane] != 0
            out_valid.append(key_valid)
        else:
            out_valid.append(None)
        if c.dtype == jnp.float64:  # x64 mode only
            out_keys.append(payload.view(jnp.float64))
        elif c.dtype == jnp.float32:
            out_keys.append(payload.astype(jnp.int32).view(jnp.float32))
        else:
            out_keys.append(payload.astype(c.dtype))
    return GroupTable(
        group_ids=gid,
        slot_used=slot_used,
        slot_keys=out_keys,
        slot_key_valid=out_valid,
        num_groups=jnp.sum(slot_used, dtype=jnp.int32),
        overflow=overflow,
    )


def hash_aggregate(
    table: Table,
    group_names: Sequence[str],
    aggs: Sequence[AggSpec],
    num_slots: int,
    mode: str = "single",  # "single" | "partial" | "final" | "partial_reduce"
    prec_flags: Optional[list] = None,
    out_capacity: Optional[int] = None,
) -> tuple[Table, jnp.ndarray]:
    """GROUP BY aggregation. Returns (result table, overflow flag).

    ``prec_flags``, when given, collects traced bools flagging integer SUM
    results that left int32's exact range (tpu precision mode only; the
    executor raises a non-retryable error for these).

    Modes mirror DataFusion's AggregateMode as used by the reference planner:
      partial        -> emits sum/count/min/max accumulator columns per agg
      final          -> consumes accumulator columns (re-groups, merges)
      single         -> full aggregation in one step
      partial_reduce -> consumes accumulator columns and emits MERGED
                        accumulator columns (AggregateMode::PartialReduce,
                        `partial_reduce_below_network_shuffles.rs` /
                        the progressive reduction-tree example): fewer
                        partial states cross each exchange hop
    The result table has capacity == num_slots, groups packed to the front.
    """
    if mode == "single" and group_names and aggs:
        fused = _try_global_hash_aggregate(
            table, group_names, aggs, num_slots, out_capacity, prec_flags
        )
        if fused is not None:
            return fused
    live = table.row_mask()
    key_cols = [table.column(g).data for g in group_names]
    key_valids = [table.column(g).validity for g in group_names]
    gt = build_group_table(key_cols, key_valids, live, num_slots)
    gid = jnp.where(live, gt.group_ids, num_slots)  # dead rows drop out

    out_cols: dict[str, Column] = {}
    for g, keys, kv in zip(group_names, gt.slot_keys, gt.slot_key_valid):
        src = table.column(g)
        out_cols[g] = Column(keys, kv, src.dtype, src.dictionary)

    def seg_sum(vals, dtype=None):
        z = jnp.zeros(num_slots, dtype=dtype or vals.dtype)
        return z.at[gid].add(vals, mode="drop")

    for spec in aggs:
        out_cols.update(
            _eval_agg(spec, table, gid, live, num_slots, mode, seg_sum,
                      prec_flags)
        )

    # Pack used slots to the front — into a TIGHTER capacity when the
    # caller supplies one. The hash table stays wide for short probe
    # chains, but the OUTPUT (which downstream sorts/joins pay capacity-
    # proportional work for) only needs to hold the groups: group count is
    # bounded by live input rows, so a bound of pow2(input capacity) can
    # never overflow, and an NDV-derived bound folds into the overflow
    # flag (the session retry widens it like any other capacity).
    packed = Table.make(out_cols, gt.num_groups)
    keep = gt.slot_used
    out_cap = min(out_capacity or num_slots, num_slots)
    (idx,) = jnp.nonzero(keep, size=out_cap, fill_value=0)
    packed = packed.gather(idx, gt.num_groups)
    overflow = gt.overflow
    if out_cap < num_slots:
        overflow = overflow | (gt.num_groups > out_cap)
    return packed, overflow


def _try_global_hash_aggregate(
    table: Table,
    group_names: Sequence[str],
    aggs: Sequence[AggSpec],
    num_slots: int,
    out_capacity: Optional[int],
    prec_flags: Optional[list],
) -> Optional[tuple[Table, jnp.ndarray]]:
    """Fused single-pass global-hash-table aggregation (DFTPU_PALLAS=1):
    one VMEM-resident kernel builds the group table AND folds the
    accumulators, replacing build + per-agg XLA scatters ("Global Hash
    Tables Strike Back!", PAPERS.md). Engages only where it is exact:
    sum/min/max/count over 4-byte integer inputs (the kernel accumulates
    int32, matching the XLA path's narrowed scatter-adds — integer adds
    and min/max are order-independent, so slot-insertion order cannot
    change any value). Under DFTPU_PALLAS=1 the slot layout equals
    pallas_build_group_ids' sequential-insert layout, so output row order
    is unchanged vs the unfused pallas path. Returns None when
    ineligible (including kernel capacity refusal) -> reference path."""
    from datafusion_distributed_tpu.ops import pallas_hash

    if not pallas_hash.use_pallas_hash():
        return None
    if np.dtype(_LANE).itemsize != 4:
        return None
    if num_slots > pallas_hash._MAX_TABLE_SLOTS:
        return None
    for spec in aggs:
        if spec.func == "count_star":
            continue
        if spec.func not in ("count", "sum", "min", "max"):
            return None
        col = table.column(spec.input_name)
        if not col.dtype.is_integer:
            return None
        if np.dtype(col.data.dtype).itemsize != 4:
            return None

    live = table.row_mask()
    n = table.capacity
    i32 = jnp.int32
    int32_max = np.iinfo(np.int32).max
    int32_min = np.iinfo(np.int32).min

    # accumulator plan: per agg, value columns pre-mapped so invalid rows
    # carry the op identity (the kernel has no validity lanes)
    ops: list[str] = []
    vcols: list[jnp.ndarray] = []
    plan: list[tuple] = []
    for spec in aggs:
        if spec.func == "count_star":
            idx = len(ops)
            ops.append("sum")
            vcols.append(jnp.where(live, 1, 0).astype(i32))
            plan.append(("count", spec.output_name, idx, None))
            continue
        col = table.column(spec.input_name)
        valid = col.valid_mask() & live
        cnt_idx = len(ops)
        ops.append("sum")
        vcols.append(jnp.where(valid, 1, 0).astype(i32))
        if spec.func == "count":
            plan.append(("count", spec.output_name, cnt_idx, None))
            continue
        vidx = len(ops)
        if spec.func == "sum":
            ops.append("sum")
            vcols.append(jnp.where(valid, col.data, 0).astype(i32))
        elif spec.func == "min":
            ops.append("min")
            vcols.append(jnp.where(valid, col.data, int32_max).astype(i32))
        else:
            ops.append("max")
            vcols.append(jnp.where(valid, col.data, int32_min).astype(i32))
        plan.append((spec.func, spec.output_name, vidx, (cnt_idx, col)))

    key_cols = [table.column(g).data for g in group_names]
    key_valids = [table.column(g).validity for g in group_names]
    lane_plan = [v is not None for v in key_valids]
    keys64, _ = _fold_key_lanes(key_cols, key_valids, lane_plan, n)
    h0 = hash_columns(list(key_cols), list(key_valids))
    slot0 = (h0 & np.uint32(num_slots - 1)).astype(i32)

    interpret = jax.default_backend() != "tpu"
    try:
        gid, rep, used, acc, overflow = (
            pallas_hash.pallas_global_hash_aggregate(
                jnp.stack(keys64, axis=1).astype(i32),
                slot0, live, jnp.stack(vcols, axis=1), num_slots,
                tuple(ops), interpret=interpret,
            )
        )
    except pallas_hash.PallasCapacityError:
        return None

    # group key columns: gather the claiming representative row — for
    # every used slot that row holds exactly the slot's key values
    safe_rep = jnp.where(used, rep, 0)
    out_cols: dict[str, Column] = {}
    for g in group_names:
        src = table.column(g)
        kv = None
        if src.validity is not None:
            kv = src.validity[safe_rep] & used
        out_cols[g] = Column(src.data[safe_rep], kv, src.dtype,
                             src.dictionary)

    vgid = jnp.where(live, gid, num_slots)

    def seg_sum(vals, dtype=None):
        z = jnp.zeros(num_slots, dtype=dtype or vals.dtype)
        return z.at[vgid].add(vals, mode="drop")

    i64 = DataType.INT64.np_dtype
    for kind, name, idx, extra in plan:
        if kind == "count":
            out_cols[name] = Column(acc[:, idx].astype(i64), None,
                                    DataType.INT64)
        elif kind == "sum":
            cnt_idx, col = extra
            nonempty = acc[:, cnt_idx]
            out_cols[name] = Column(acc[:, idx].astype(i64), nonempty > 0,
                                    DataType.INT64)
            _check_int32_sum_range(vcols[idx], seg_sum, prec_flags)
        else:  # min / max
            cnt_idx, col = extra
            nonempty = acc[:, cnt_idx]
            out_cols[name] = Column(acc[:, idx].astype(col.data.dtype),
                                    nonempty > 0, col.dtype, col.dictionary)

    num_groups = jnp.sum(used, dtype=i32)
    packed = Table.make(out_cols, num_groups)
    out_cap = min(out_capacity or num_slots, num_slots)
    (pack_idx,) = jnp.nonzero(used, size=out_cap, fill_value=0)
    packed = packed.gather(pack_idx, num_groups)
    if out_cap < num_slots:
        overflow = overflow | (num_groups > out_cap)
    return packed, overflow


def global_aggregate(table: Table, aggs: Sequence[AggSpec], mode: str = "single",
                     prec_flags: Optional[list] = None) -> Table:
    """Aggregation with no GROUP BY: one output row (capacity 8 keeps the
    result TPU-lane-friendly). Shares the per-aggregate evaluation with
    hash_aggregate, with every live row mapped to group 0."""
    live = table.row_mask()
    cap = 8
    gid = jnp.zeros(table.capacity, dtype=jnp.int32)

    def seg_sum(vals, dtype=None):
        z = jnp.zeros(cap, dtype=dtype or vals.dtype)
        return z.at[gid].add(vals, mode="drop")

    cols: dict[str, Column] = {}
    for spec in aggs:
        cols.update(_eval_agg(spec, table, gid, live, cap, mode, seg_sum,
                              prec_flags))
    return Table(tuple(cols.keys()), tuple(cols.values()),
                 jnp.asarray(1, dtype=jnp.int32))


def _mean_shifted_seg_sum(vals, valid, seg_sum, group_counts):
    """Per-group float sum as seg_sum(x - m) + m*n_g (f32 storage mode).

    A raw f32 scatter-add over millions of same-sign values drifts
    ~sqrt(N)*eps relative — enough that two task layouts of the SAME data
    disagree beyond 5e-4 (seen at TPC-H SF0.5, q1 avg_disc). The identity
    is algebraically exact for ANY scalar center m; centering residuals
    near zero makes the scatter-add cancel instead of accumulate (probe:
    3M rows, max rel err vs f64 truth 8e-8). m only needs to be a rough
    center, so a plain f32 mean is fine — but it must be FINITE: a
    non-finite m (any Inf/NaN in the data) would poison every group, so
    fall back to m=0 (the raw scatter-add, which confines Inf/NaN to the
    group containing it)."""
    m = jnp.sum(vals) / jnp.maximum(jnp.sum(valid), 1)
    m = jnp.where(jnp.isfinite(m), m, jnp.zeros_like(m))
    z = seg_sum(jnp.where(valid, vals - m, 0))
    # optimization_barrier: on this image's XLA:CPU, letting the compiler
    # fuse the `+ m*n_g` add with the two scatters corrupts the scatter's
    # contribution entirely (observed: result off by the full residual sum
    # plus more, ~1e-3 relative, vs ~1e-5 with the pieces computed
    # separately — reproduced with a python-constant m and bitwise-equal
    # inputs, so it is a fusion bug, not accumulation noise). The barrier
    # pins the scatter result before the elementwise add.
    z = jax.lax.optimization_barrier(z)
    return z + m * group_counts.astype(vals.dtype)


def _eval_agg(spec, table, gid, live, num_slots, mode, seg_sum,
              prec_flags=None):
    """Produce the output column(s) for one AggSpec in the given mode."""
    name = spec.output_name
    if spec.func == "count_star":
        if mode in ("final", "partial_reduce"):
            acc = table.column(f"{name}")
            vals = jnp.where(live, acc.data, 0)
            return {name: Column(seg_sum(vals), None, DataType.INT64)}
        cnt = seg_sum(jnp.where(live, 1, 0).astype(DataType.INT64.np_dtype))
        return {name: Column(cnt, None, DataType.INT64)}

    # sum/count/min/max: the merged accumulator IS the final value, so
    # partial_reduce and final share one merge (the output column stays a
    # valid partial state for a later final stage)
    if mode in ("final", "partial_reduce") and spec.func in (
        "sum", "count", "min", "max",
    ):
        # merge accumulator column produced by a partial stage
        acc = table.column(name)
        valid = acc.valid_mask() & live
        if spec.func in ("sum", "count"):
            vals = jnp.where(valid, acc.data, 0)
            merged = seg_sum(vals)
            if spec.func == "sum":
                _check_int32_sum_range(vals, seg_sum, prec_flags)
        elif spec.func == "min":
            init = jnp.full(num_slots, _dtype_max(acc.data.dtype), acc.data.dtype)
            merged = init.at[jnp.where(valid, gid, num_slots)].min(
                acc.data, mode="drop"
            )
        else:
            init = jnp.full(num_slots, _dtype_min(acc.data.dtype), acc.data.dtype)
            merged = init.at[jnp.where(valid, gid, num_slots)].max(
                acc.data, mode="drop"
            )
        nonempty = seg_sum(jnp.where(valid, 1, 0).astype(_ACC_INT))
        if spec.func == "count":
            return {name: Column(merged, None, DataType.INT64)}
        out_valid = nonempty > 0
        return {name: Column(merged, out_valid, _col_dtype(acc), acc.dictionary)}

    if mode in ("final", "partial_reduce") and spec.func == "avg":
        s = table.column(f"{name}__sum")
        c = table.column(f"{name}__count")
        valid = live & s.valid_mask()
        ssum = seg_sum(jnp.where(valid, s.data, 0.0))
        scnt = seg_sum(jnp.where(live, c.data, 0))
        out_valid = scnt > 0
        if mode == "partial_reduce":  # keep the (sum, count) state form
            return {
                f"{name}__sum": Column(ssum, out_valid, DataType.FLOAT64),
                f"{name}__count": Column(scnt, None, DataType.INT64),
            }
        avg = ssum / jnp.where(scnt == 0, 1, scnt)
        return {name: Column(avg, out_valid, DataType.FLOAT64)}

    if spec.func in _VARIANCE_FUNCS and mode in ("final", "partial_reduce"):
        s = table.column(f"{name}__sum")
        sq = table.column(f"{name}__sumsq")
        c = table.column(f"{name}__count")
        valid = live & s.valid_mask()
        ssum = seg_sum(jnp.where(valid, s.data, 0.0))
        ssumsq = seg_sum(jnp.where(valid, sq.data, 0.0))
        scnt = seg_sum(jnp.where(live, c.data, 0))
        if mode == "partial_reduce":  # keep the (sum, sumsq, count) state
            nz = scnt > 0
            return {
                f"{name}__sum": Column(ssum, nz, DataType.FLOAT64),
                f"{name}__sumsq": Column(ssumsq, nz, DataType.FLOAT64),
                f"{name}__count": Column(scnt, None, DataType.INT64),
            }
        return {name: _variance_result(spec.func, ssum, ssumsq, scnt)}

    # partial/single over raw input
    col = table.column(spec.input_name)
    valid = col.valid_mask() & live
    vgid = jnp.where(valid, gid, num_slots)

    if spec.func in _VARIANCE_FUNCS:
        f = DataType.FLOAT64.np_dtype
        vals = jnp.where(valid, col.data, 0).astype(f)
        s = seg_sum(vals)
        sq = seg_sum(vals * vals)
        cnt = seg_sum(jnp.where(valid, 1, 0).astype(DataType.INT64.np_dtype))
        if mode == "partial":
            return {
                f"{name}__sum": Column(s, cnt > 0, DataType.FLOAT64),
                f"{name}__sumsq": Column(sq, cnt > 0, DataType.FLOAT64),
                f"{name}__count": Column(cnt, None, DataType.INT64),
            }
        return {name: _variance_result(spec.func, s, sq, cnt)}

    if spec.func == "count":
        cnt = seg_sum(jnp.where(valid, 1, 0).astype(DataType.INT64.np_dtype))
        return {name: Column(cnt, None, DataType.INT64)}

    if spec.func == "sum" or (spec.func == "avg" and mode == "partial"):
        acc_dtype = (
            DataType.FLOAT64.np_dtype if col.dtype.is_float
            else DataType.INT64.np_dtype
        )
        vals = jnp.where(valid, col.data, 0).astype(acc_dtype)
        nonempty = seg_sum(jnp.where(valid, 1, 0).astype(_ACC_INT))
        if col.dtype.is_float and jnp.dtype(acc_dtype) == jnp.float32:
            s = _mean_shifted_seg_sum(vals, valid, seg_sum, nonempty)
        else:
            s = seg_sum(vals)
            _check_int32_sum_range(vals, seg_sum, prec_flags)
        sum_dtype = DataType.FLOAT64 if col.dtype.is_float else DataType.INT64
        if spec.func == "sum":
            return {name: Column(s, nonempty > 0, sum_dtype)}
        # partial avg: emit sum + count pair
        return {
            f"{name}__sum": Column(
                s.astype(DataType.FLOAT64.np_dtype), nonempty > 0, DataType.FLOAT64
            ),
            f"{name}__count": Column(nonempty, None, DataType.INT64),
        }

    if spec.func == "avg":  # single
        vals = jnp.where(valid, col.data, 0).astype(DataType.FLOAT64.np_dtype)
        cnt = seg_sum(jnp.where(valid, 1, 0).astype(_ACC_INT))
        if jnp.dtype(vals.dtype) == jnp.float32:
            s = _mean_shifted_seg_sum(vals, valid, seg_sum, cnt)
        else:
            s = seg_sum(vals)
        avg = s / jnp.where(cnt == 0, 1, cnt)
        return {name: Column(avg, cnt > 0, DataType.FLOAT64)}

    if spec.func in ("min", "max"):
        if spec.func == "min":
            init = jnp.full(num_slots, _dtype_max(col.data.dtype), col.data.dtype)
            red = init.at[vgid].min(col.data, mode="drop")
        else:
            init = jnp.full(num_slots, _dtype_min(col.data.dtype), col.data.dtype)
            red = init.at[vgid].max(col.data, mode="drop")
        nonempty = seg_sum(jnp.where(valid, 1, 0).astype(_ACC_INT))
        return {
            name: Column(red, nonempty > 0, col.dtype, col.dictionary)
        }

    raise NotImplementedError(f"aggregate function {spec.func}")


#: SQL variance family. Computed via the (sum, sumsq, count) decomposition —
#: mergeable across partial/final stages like avg's (sum, count). The naive
#: formula cancels catastrophically when stddev << mean; acceptable for the
#: benchmark domains (quantities/prices), exact-enough in x64 mode.
_VARIANCE_FUNCS = {"stddev", "stddev_samp", "stddev_pop", "var_samp",
                   "var_pop"}


def _variance_result(func: str, s, sq, cnt):
    """(sum, sumsq, count) -> variance/stddev Column with SQL null rules
    (samp needs n>=2, pop needs n>=1)."""
    f = DataType.FLOAT64.np_dtype
    pop = func.endswith("_pop")
    sqrt = func.startswith("stddev")
    n = cnt.astype(f)
    safe_n = jnp.maximum(n, 1.0)
    mean = s.astype(f) / safe_n
    m2 = sq.astype(f) - n * mean * mean  # sum((x-mean)^2), up to rounding
    m2 = jnp.maximum(m2, 0.0)
    denom = safe_n if pop else jnp.maximum(n - 1.0, 1.0)
    var = m2 / denom
    out = jnp.sqrt(var) if sqrt else var
    valid = cnt >= (1 if pop else 2)
    return Column(out, valid, DataType.FLOAT64)


def singleton_partial_states(table: Table, group_names, aggs) -> Table:
    """Per-row singleton partial-aggregation states: for each input row,
    the accumulator a partial aggregate would emit for a one-row group.
    Schema-identical to (and mergeable by the same final stage as)
    ``hash_aggregate(mode="partial")`` over the same input — the runtime
    bail-out (runtime/adaptivity.py) swaps a non-reducing pushed-down
    partial for this pure elementwise pass, which costs no hash table
    and no claim loop. Column recipes mirror the partial-mode arms of
    `_eval_agg` with group count == 1; padding rows past ``num_rows``
    carry garbage like every other elementwise operator."""
    i64 = DataType.INT64.np_dtype
    f64 = DataType.FLOAT64.np_dtype
    cols: dict = {}
    for g in group_names:
        cols[g] = table.column(g)
    for spec in aggs:
        name = spec.output_name
        if spec.func == "count_star":
            cols[name] = Column(
                jnp.ones(table.capacity, dtype=i64), None, DataType.INT64
            )
            continue
        col = table.column(spec.input_name)
        valid = col.valid_mask()
        one = jnp.where(valid, 1, 0).astype(i64)
        if spec.func == "count":
            cols[name] = Column(one, None, DataType.INT64)
        elif spec.func == "sum":
            acc_dtype = f64 if col.dtype.is_float else i64
            vals = jnp.where(valid, col.data, 0).astype(acc_dtype)
            sum_dtype = (DataType.FLOAT64 if col.dtype.is_float
                         else DataType.INT64)
            cols[name] = Column(vals, valid, sum_dtype)
        elif spec.func == "avg":
            vals = jnp.where(valid, col.data, 0).astype(f64)
            cols[f"{name}__sum"] = Column(vals, valid, DataType.FLOAT64)
            cols[f"{name}__count"] = Column(one, None, DataType.INT64)
        elif spec.func in _VARIANCE_FUNCS:
            vals = jnp.where(valid, col.data, 0).astype(f64)
            cols[f"{name}__sum"] = Column(vals, valid, DataType.FLOAT64)
            cols[f"{name}__sumsq"] = Column(
                vals * vals, valid, DataType.FLOAT64
            )
            cols[f"{name}__count"] = Column(one, None, DataType.INT64)
        elif spec.func in ("min", "max"):
            cols[name] = Column(col.data, valid, col.dtype, col.dictionary)
        else:
            raise NotImplementedError(
                f"no singleton partial state for {spec.func}"
            )
    return Table(tuple(cols.keys()), tuple(cols.values()), table.num_rows)


def _check_int32_sum_range(vals, seg_sum, prec_flags):
    """tpu precision mode: int32 scatter-add wraps silently past 2^31, so
    estimate each group's sum in float32 alongside and flag when any group's
    magnitude approaches the boundary (conservative 0.995 factor covers the
    ~1e-7 relative error of the f32 estimate). No-op in x64 mode."""
    if prec_flags is None:
        return
    if not (
        jnp.issubdtype(vals.dtype, jnp.integer)
        and np.dtype(vals.dtype).itemsize == 4
    ):
        return
    est = seg_sum(vals.astype(jnp.float32), dtype=jnp.float32)
    prec_flags.append(jnp.any(jnp.abs(est) > np.float32(2.0**31 * 0.995)))


def _col_dtype(col: Column) -> DataType:
    return col.dtype


def _dtype_max(dt):
    if jnp.issubdtype(dt, jnp.floating):
        return np.inf
    return np.iinfo(np.dtype(dt)).max


def _dtype_min(dt):
    if jnp.issubdtype(dt, jnp.floating):
        return -np.inf
    return np.iinfo(np.dtype(dt)).min
