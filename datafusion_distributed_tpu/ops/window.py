"""Window function kernels (rank / row_number / running + partition aggregates).

DataFusion's WindowAggExec (used by the TPC-DS suite via the reference's L0)
processes partitions row-by-row. The TPU formulation: one stable sort by
(partition keys, order keys), then every window quantity becomes a
*segmented scan* over the sorted view — `lax.associative_scan` with a
reset-flag combine — and results scatter back to the original row order.
Default SQL framing is honored: with ORDER BY, aggregates use the RANGE
UNBOUNDED-PRECEDING..CURRENT-ROW frame (peers included) via a
broadcast-to-peer-group pass; without ORDER BY they cover the whole
partition.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from datafusion_distributed_tpu.ops.sort import SortKey, sort_permutation
from datafusion_distributed_tpu.ops.table import Column, Table
from datafusion_distributed_tpu.schema import DataType


@dataclass(frozen=True)
class WindowFunc:
    func: str  # rank|dense_rank|row_number|sum|avg|min|max|count|count_star
    input_name: Optional[str]
    output_name: str
    frame: str = "range"  # "range": peers share frame-end; "rows": per row


def _segmented_scan(vals: jnp.ndarray, resets: jnp.ndarray, op: str) -> jnp.ndarray:
    """Inclusive scan of ``vals`` restarting wherever resets[i] is True."""

    def combine(a, b):
        av, af = a
        bv, bf = b
        if op == "sum":
            v = jnp.where(bf, bv, av + bv)
        elif op == "min":
            v = jnp.where(bf, bv, jnp.minimum(av, bv))
        elif op == "max":
            v = jnp.where(bf, bv, jnp.maximum(av, bv))
        else:
            raise NotImplementedError(op)
        return v, af | bf

    out, _ = jax.lax.associative_scan(combine, (vals, resets))
    return out


def window_compute(
    table: Table,
    partition_names: Sequence[str],
    order_keys: Sequence[SortKey],
    funcs: Sequence[WindowFunc],
) -> dict[str, Column]:
    """-> {output_name: Column} aligned with the table's ORIGINAL row order."""
    cap = table.capacity
    keys = [SortKey(p) for p in partition_names] + list(order_keys)
    perm = (
        sort_permutation(table, keys) if keys
        else jnp.arange(cap, dtype=jnp.int32)
    )
    idx = jnp.arange(cap, dtype=jnp.int32)
    live = table.row_mask()
    live_sorted = live[perm]

    # partition / peer boundaries in sorted order
    new_part = jnp.zeros(cap, dtype=jnp.bool_).at[0].set(True)
    for p in partition_names:
        col = table.column(p)
        d = col.data[perm]
        changed = jnp.concatenate([jnp.ones(1, jnp.bool_), d[1:] != d[:-1]])
        if col.validity is not None:
            v = col.validity[perm]
            changed = changed | jnp.concatenate(
                [jnp.zeros(1, jnp.bool_), v[1:] != v[:-1]]
            )
        new_part = new_part | changed
    new_order = new_part
    for k in order_keys:
        col = table.column(k.name)
        d = col.data[perm]
        changed = jnp.concatenate([jnp.ones(1, jnp.bool_), d[1:] != d[:-1]])
        if col.validity is not None:
            v = col.validity[perm]
            changed = changed | jnp.concatenate(
                [jnp.zeros(1, jnp.bool_), v[1:] != v[:-1]]
            )
        new_order = new_order | changed
    # dead rows sort last; give them their own partition so they don't bleed
    new_part = new_part | jnp.concatenate(
        [jnp.zeros(1, jnp.bool_), live_sorted[1:] != live_sorted[:-1]]
    )
    new_order = new_order | new_part

    # lax.cummax, not jnp.maximum.accumulate: ufunc methods are jax 0.5+
    # and this must run on 0.4.x jaxlibs too
    seg_start = jax.lax.cummax(jnp.where(new_part, idx, 0), axis=0)
    rn = idx - seg_start  # 0-based row_number within partition
    rank0 = jax.lax.cummax(jnp.where(new_order, idx, 0), axis=0) - seg_start
    dense_cum = jnp.cumsum(new_order.astype(DataType.INT64.np_dtype))
    dense0 = dense_cum - dense_cum[seg_start]

    # peer-group end index (for RANGE ..CURRENT ROW frames): the largest
    # sorted index sharing this row's peer group
    peer_gid = (jnp.cumsum(new_order.astype(jnp.int32)) - 1).astype(jnp.int32)
    last_of_gid = (
        jnp.zeros(cap, dtype=jnp.int32).at[peer_gid].max(idx, mode="drop")
    )
    peer_end = last_of_gid[peer_gid]

    inv_scatter = perm  # result[perm[i]] = computed[i]

    out: dict[str, Column] = {}
    for f in funcs:
        if f.func == "row_number":
            res = (rn + 1).astype(DataType.INT64.np_dtype)
            validity = None
        elif f.func == "rank":
            res = (rank0 + 1).astype(DataType.INT64.np_dtype)
            validity = None
        elif f.func == "dense_rank":
            res = (dense0 + 1).astype(DataType.INT64.np_dtype)
            validity = None
        elif f.func in ("sum", "avg", "min", "max", "count", "count_star"):
            if f.func == "count_star":
                vals = live_sorted.astype(DataType.INT64.np_dtype)
                valid_sorted = live_sorted
            else:
                col = table.column(f.input_name)
                vals = col.data[perm]
                valid_sorted = col.valid_mask()[perm] & live_sorted
            if f.func in ("count", "count_star"):
                scan_vals = valid_sorted.astype(DataType.INT64.np_dtype)
                op = "sum"
            elif f.func == "avg":
                scan_vals = jnp.where(valid_sorted, vals, 0).astype(DataType.FLOAT64.np_dtype)
                op = "sum"
            elif f.func == "sum":
                acc = (
                    DataType.FLOAT64.np_dtype
                    if jnp.issubdtype(vals.dtype, jnp.floating)
                    else DataType.INT64.np_dtype
                )
                scan_vals = jnp.where(valid_sorted, vals, 0).astype(acc)
                op = "sum"
            elif f.func == "min":
                big = _identity(vals.dtype, "min")
                scan_vals = jnp.where(valid_sorted, vals, big)
                op = "min"
            else:
                small = _identity(vals.dtype, "max")
                scan_vals = jnp.where(valid_sorted, vals, small)
                op = "max"
            running = _segmented_scan(scan_vals, new_part, op)
            cnt_running = _segmented_scan(
                valid_sorted.astype(DataType.INT64.np_dtype), new_part, "sum"
            )
            if order_keys and f.frame == "rows":
                # ROWS frame: strictly per-row running values
                res = running
                cnt = cnt_running
            elif order_keys and f.frame != "full":
                # RANGE frame: value at the END of the peer group
                res = running[peer_end]
                cnt = cnt_running[peer_end]
            else:
                # whole partition: value at the END of the partition
                part_gid = (jnp.cumsum(new_part.astype(jnp.int32)) - 1).astype(
                    jnp.int32
                )
                last_of_part = (
                    jnp.zeros(cap, dtype=jnp.int32)
                    .at[part_gid]
                    .max(idx, mode="drop")
                )
                end = last_of_part[part_gid]
                res = running[end]
                cnt = cnt_running[end]
            if f.func == "avg":
                res = res / jnp.where(cnt == 0, 1, cnt)
            if f.func in ("count", "count_star"):
                validity = None
            else:
                validity_sorted = cnt > 0
                validity = jnp.zeros(cap, dtype=jnp.bool_).at[
                    inv_scatter
                ].set(validity_sorted)
        else:
            raise NotImplementedError(f"window function {f.func}")

        data = jnp.zeros(cap, dtype=res.dtype).at[inv_scatter].set(res)
        dtype = _out_dtype(f, table)
        out[f.output_name] = Column(data.astype(dtype.np_dtype), validity, dtype)
    return out


def _identity(dt, op: str):
    import numpy as np

    if jnp.issubdtype(dt, jnp.floating):
        return np.inf if op == "min" else -np.inf
    info = np.iinfo(np.dtype(dt))
    return info.max if op == "min" else info.min


def window_output_dtype(func: str, input_dtype: "DataType | None") -> DataType:
    """Single source of truth for window result dtypes (used by the kernel
    and the logical schema)."""
    if func in ("rank", "dense_rank", "row_number", "count", "count_star"):
        return DataType.INT64
    if func == "avg":
        return DataType.FLOAT64
    if func == "sum":
        return (
            DataType.FLOAT64 if input_dtype is not None and input_dtype.is_float
            else DataType.INT64
        )
    return input_dtype


def _out_dtype(f: WindowFunc, table: Table) -> DataType:
    input_dtype = (
        table.column(f.input_name).dtype if f.input_name is not None else None
    )
    return window_output_dtype(f.func, input_dtype)
