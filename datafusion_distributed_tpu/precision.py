"""Compute-precision policy: 32-bit TPU-native compute by default.

TPU hardware has no native f64/i64: XLA emulates both (an order of magnitude
slower) and they double HBM traffic. Round 1 globally forced
``jax_enable_x64`` and never completed a query on the chip (BENCH_r01); this
module replaces that with an explicit policy, resolved once at import time
from the ``DFTPU_PRECISION`` environment variable:

- ``tpu`` (default): ``jax_enable_x64`` stays OFF. Every device array —
  columns, accumulators, temporaries — is 32-bit; JAX itself guarantees no
  64-bit op can appear in a jaxpr (tests/test_precision.py audits this).
  Logical INT64/FLOAT64 schema types are stored as int32/float32 on device;
  the host->device boundary range-checks integer narrowing
  (`ops/table.py Column.from_numpy`), so silent truncation is impossible.
  Float aggregation accumulates in f32; result parity vs the f64 oracle is
  validated at a documented tolerance (`oracle_rtol`, ~eps_f32*sqrt(N)).
- ``x64``: exact mode — the round-1 behavior (f64/i64 device columns,
  bit-exact parity with the pandas oracle). Useful on CPU and for parity
  debugging; hostile to TPU.

The reference has no analogue (CPU f64 is free there); the closest concept
is its per-datatype byte-width cost table
(`/root/reference/src/distributed_planner/statistics/default_bytes_for_datatype.rs`),
which likewise treats precision/width as an engine-level policy.

The mode is import-time only: flipping ``jax_enable_x64`` after arrays exist
corrupts dtype invariants, so ``set_mode`` intentionally does not exist.
Tests that need the other mode run in a subprocess.
"""

from __future__ import annotations

import os

import jax
import numpy as np

MODE = os.environ.get("DFTPU_PRECISION", "tpu").strip().lower()
if MODE not in ("tpu", "x64"):
    raise ValueError(
        f"DFTPU_PRECISION must be 'tpu' or 'x64', got {MODE!r}"
    )

if MODE == "x64":
    jax.config.update("jax_enable_x64", True)

#: Device storage dtype per logical DataType value (see schema.DataType).
#: Narrowed entries apply in tpu mode only.
_NARROW = {
    "int64": np.int32,
    "float64": np.float32,
}


def narrow_np_dtype(wide: np.dtype) -> np.dtype:
    """Map a logical numpy dtype to its device storage dtype for this mode."""
    if MODE == "x64":
        return np.dtype(wide)
    return np.dtype(_NARROW.get(np.dtype(wide).name, wide))


#: dtype for folded key lanes in the claim-loop hash table / join probe
#: (ops/aggregate.py, ops/join.py). 32-bit halves compare-matrix HBM traffic.
LANE_INT = np.int64 if MODE == "x64" else np.int32
#: integer accumulator (counts, rank numbering, metric counters)
ACC_INT = np.int64 if MODE == "x64" else np.int32
#: float accumulator (SUM/AVG); see oracle_rtol for the f32 error model
ACC_FLOAT = np.float64 if MODE == "x64" else np.float32


def oracle_rtol() -> float:
    """Float tolerance for result-parity comparison against an f64 oracle.

    tpu mode: f32 scatter-add over N addends accumulates ~eps_f32*sqrt(N)
    relative error (random-sign model); 5e-4 covers N up to ~10^7 with
    safety margin while still catching real logic errors (which deviate
    by orders of magnitude more).
    """
    return 1e-6 if MODE == "x64" else 5e-4


def oracle_atol() -> float:
    return 1e-6 if MODE == "x64" else 1e-4


def test_rtol() -> float:
    """Tolerance for engine-vs-engine or engine-vs-small-oracle comparisons
    in unit tests (smaller inputs than the TPC-H suite, so tighter than
    oracle_rtol)."""
    return 1e-12 if MODE == "x64" else 2e-5
