"""Distributed planner: single-node physical plan -> staged SPMD plan.

The reference's `DistributedQueryPlanner` pipeline (SURVEY.md §2.1,
`/root/reference/src/distributed_planner/distributed_query_planner.rs`):
shape -> insert broadcasts -> inject network boundaries (task-count lattice)
-> prepare (elide 1:1, stamp stage ids). This module is the TPU re-design of
those passes over our ExecutionPlan IR:

- `inject_boundaries` walks bottom-up tracking each subtree's *distribution*
  (PARTITIONED across tasks vs REPLICATED on all), rewriting:
    aggregate  -> partial agg | shuffle(keys) | final agg
                  (global agg -> partial | coalesce | final)
    hash join  -> shuffle both sides on the join keys, or broadcast the
                  build side when it is small (`insert_broadcast.rs`
                  CollectLeft analogue; `broadcast_threshold` config)
    sort/limit -> local sort/top-k | coalesce | final sort/limit
                  (the push_fetch_into_network_coalesce fetch pushdown)
- leaf scale-up splits scans into per-task slices
  (`task_estimator.rs` scale_up_leaf_node / DistributedLeafExec analogue)
- `prepare` elides boundaries whose producer and consumer distributions
  already agree and stamps stage ids (`prepare_network_boundaries.rs`).

Task counts: the Desired/Maximum annotation lattice of the reference
(`task_estimator.rs`) is wired through `_inject`: each leaf contributes an
annotation (user TaskEstimator > bytes-based sizing > Desired(num_tasks)),
annotations merge up the open stage, `_seal_stage` resolves the stage's
count (honoring max_tasks_per_stage) and splits its scans, and boundary
consumer counts come from the cardinality scale-factor walk. The mesh tier
pins every stage to the axis width (`uniform_stage_tasks`: collectives are
axis-wide); the host/coordinator tier schedules the per-stage counts.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional

from datafusion_distributed_tpu.ops.aggregate import AggSpec
from datafusion_distributed_tpu.ops.table import round_up_pow2
from datafusion_distributed_tpu.parallel.exchange import partition_table
from datafusion_distributed_tpu.plan.exchanges import (
    BroadcastExchangeExec,
    RangeShuffleExchangeExec,
    CoalesceExchangeExec,
    ShuffleExchangeExec,
)
from datafusion_distributed_tpu.plan.joins import (
    CrossJoinExec,
    HashJoinExec,
    MultiwayHashJoinExec,
    MultiwayJoinStep,
    UnionExec,
)
from datafusion_distributed_tpu.plan.physical import (
    CoalescePartitionsExec,
    ExecutionPlan,
    FilterExec,
    HashAggregateExec,
    LimitExec,
    MemoryScanExec,
    ParquetScanExec,
    ProjectionExec,
    SortExec,
)


class Distribution(enum.Enum):
    PARTITIONED = "partitioned"  # each task owns a disjoint row slice
    REPLICATED = "replicated"  # every task holds the full data


@dataclass(frozen=True)
class TaskCountAnnotation:
    """Desired/Maximum lattice (reference `task_estimator.rs:20-59`):
    merge(Desired a, Desired b) = Desired max(a,b); Maximum dominates
    Desired; merge(Maximum a, Maximum b) = Maximum min(a,b)."""

    count: int
    maximum: bool = False

    def merge(self, other: "TaskCountAnnotation") -> "TaskCountAnnotation":
        if self.maximum and other.maximum:
            return TaskCountAnnotation(min(self.count, other.count), True)
        if self.maximum:
            return self  # Maximum dominates: the desired count is discarded
        if other.maximum:
            return other
        return TaskCountAnnotation(max(self.count, other.count), False)


class TaskEstimator:
    """User extension point for per-leaf task-count estimation (the
    reference's `TaskEstimator` trait, `task_estimator.rs:110-148`).
    Register via ``DistributedConfig.task_estimator``. Estimators are
    consulted leaf-by-leaf; a ``None`` return falls through to the built-in
    bytes-based estimation."""

    def task_estimation(self, leaf: ExecutionPlan,
                        cfg: "DistributedConfig") -> Optional[TaskCountAnnotation]:
        """Desired/Maximum task-count hint for the stage containing
        ``leaf``, or None to defer to other estimators / the default."""
        return None

    def scale_up_leaf_node(self, leaf: ExecutionPlan, task_count: int,
                           cfg: "DistributedConfig") -> Optional[ExecutionPlan]:
        """Replace ``leaf`` once the stage's final ``task_count`` is known
        (reference `scale_up_leaf_node`); None keeps the default split."""
        return None


@dataclass
class DistributedConfig:
    """Knobs (subset-parity with `distributed_config.rs`)."""

    num_tasks: int = 8
    broadcast_joins: bool = True
    broadcast_threshold_rows: int = 1 << 17  # build sides smaller: broadcast
    shuffle_skew_factor: int = 4
    # hard per-stage task-count cap (Maximum semantics applied to every
    # stage's lattice resolution); 0 = uncapped (num_tasks)
    max_tasks_per_stage: int = 0
    # wire-format knobs (reference: distributed_config.rs compression=lz4,
    # worker_connection_buffer_budget_bytes=64MiB; zstd here — lz4 is not in
    # this image)
    compression: str = "zstd"  # "zstd" | "none"
    worker_connection_buffer_budget_bytes: int = 64 << 20
    shuffle_chunk_bytes: int = 1 << 20
    # task-count estimation (reference: file_scan_config_bytes_per_partition
    # 16MiB + dynamic_task_count): leaves sized by bytes, not mesh size
    bytes_per_task: int = 16 << 20
    dynamic_task_count: bool = False
    # scale factor applied per cardinality-affecting node when sizing a
    # boundary's consumer task count (CardinalityBasedNetworkBoundaryBuilder,
    # `inject_network_boundaries.rs:595-623`): shrinking nodes divide,
    # growing nodes multiply; 1.0 = consumers inherit the producer count
    cardinality_task_count_factor: float = 1.0
    # size leaf-stage task counts from leaf bytes (FileScanConfigTaskEstimator
    # semantics, task_estimator.rs:235-258): tasks = ceil(bytes /
    # bytes_per_task), capped at num_tasks. Host/coordinator tier only —
    # a mesh SPMD program's task count is the physical device count.
    size_tasks_to_data: bool = False
    # user TaskEstimator consulted before the built-in leaf estimation
    task_estimator: Optional[TaskEstimator] = None
    # insert partial_reduce aggregates below hash shuffles (the reference's
    # `partial_reduce` knob, default off; see _partial_reduce_pass)
    partial_reduce: bool = False
    # statistics-driven partial-aggregate push-down (`SET
    # distributed.partial_agg_pushdown`): push decomposable aggregates
    # (sum/count/min/max, avg via sum+count) BELOW hash shuffles when the
    # sampled key-distribution statistics (catalog NDV -> est_rows)
    # predict the partial states shrink the exchange payload, and stamp
    # `predicted_exchange_bytes` on the rewritten shuffles so the
    # coordinator can record predicted-vs-measured bytes (see
    # _partial_agg_pushdown_pass; grounding: *Chasing Similarity* /
    # *Partial Partial Aggregates*, PAPERS.md). Default ON: the runtime
    # bail-out (runtime/adaptivity.py partial_agg_bailout_ratio) caps
    # the cost of a wrong NDV prediction at one probed task, so the
    # push-down no longer needs opt-in caution.
    partial_agg_pushdown: bool = True
    # minimum predicted BYTES reduction (0..1) for the push-down to fire:
    # below it the pre-exchange aggregate is pure compute overhead (the
    # high-NDV regime where distribution-aware placement says "aggregate
    # after the exchange")
    partial_agg_pushdown_min_reduction: float = 0.2
    # multiway join-chain fusion (`SET distributed.multiway_join`): rewrite
    # chains of >= 2 key-compatible binary hash joins into ONE
    # MultiwayHashJoinExec stage, deleting the intermediate probe-side
    # shuffles where re-hashing the same keys to the same task count is an
    # identity re-partition (see _multiway_fusion_pass; grounding:
    # *Efficient Multiway Hash Join on Reconfigurable Hardware*,
    # PAPERS.md). Default off until parity is pinned per deployment.
    multiway_join: bool = False
    # combined resident build-side byte budget for one fused stage: every
    # build table of the chain is live in the same program at once, so the
    # statistics gate (planner/statistics.multiway_fusion_allowed) bounds
    # their padded sum
    multiway_build_bytes_max: int = 1 << 26
    # stamp the statistics-chosen probe order (smallest estimated build
    # first) as the `probe_order_hint` annotation. Hint only: steps always
    # EXECUTE in plan order — reordering would permute output columns
    multiway_probe_reorder: bool = False
    # global-hash-table aggregation (`SET distributed.global_hash_agg`):
    # when sampled NDV predicts partial states will NOT shrink the
    # exchange (the high-NDV regime of *Global Hash Tables Strike
    # Back!*), plan shuffle-raw-rows + one single-mode aggregate per task
    # — one shared table, no per-partition tables + merge. Default off.
    global_hash_agg: bool = False
    # unlimited ORDER BY over data larger than this (global row capacity)
    # plans as a distributed sample sort (range shuffle + local sorts);
    # smaller sorts keep the cheaper coalesce-then-sort shape (two fewer
    # stages, and one device trivially sorts a post-aggregate result)
    range_sort_threshold_rows: int = 8192
    # force every stage to exactly num_tasks (the mesh tier sets this: one
    # SPMD program's exchanges are axis-wide collectives, so stage width is
    # the physical mesh width regardless of scheduling-tier knobs)
    uniform_stage_tasks: bool = False

    def _lattice_active(self) -> bool:
        """Whether any knob makes per-stage task counts diverge from
        num_tasks. When inactive, resolution short-circuits to num_tasks so
        default plans (and the mesh tier's axis-wide collectives) keep
        uniform stage widths."""
        return not self.uniform_stage_tasks and (
            self.size_tasks_to_data
            or self.max_tasks_per_stage > 0
            or self.cardinality_task_count_factor != 1.0
            or self.task_estimator is not None
        )


def estimate_leaf_bytes(plan: ExecutionPlan) -> int:
    """Total estimated input bytes across the plan's leaves."""
    import os as _os

    from datafusion_distributed_tpu.planner.statistics import row_width

    total = 0
    for leaf in plan.collect(lambda n: not n.children()):
        if isinstance(leaf, MemoryScanExec):
            rows = sum(int(t.num_rows) for t in leaf.tasks)
            total += rows * row_width(leaf.schema())
        elif isinstance(leaf, ParquetScanExec):
            for group in leaf.file_groups:
                for f in group:
                    try:
                        total += _os.path.getsize(f)
                    except OSError:
                        pass
    return total


def effective_num_tasks(plan: ExecutionPlan, config: DistributedConfig) -> int:
    """Bytes-based task count (the reference's ceil(total_bytes /
    bytes_per_partition) leaf estimation), clamped to [1, num_tasks]."""
    if not config.size_tasks_to_data or config.bytes_per_task <= 0:
        return config.num_tasks
    bytes_total = estimate_leaf_bytes(plan)
    want = -(-bytes_total // config.bytes_per_task) if bytes_total else 1
    return max(1, min(int(want), config.num_tasks))


def distribute_plan(
    plan: ExecutionPlan, config: DistributedConfig
) -> ExecutionPlan:
    """Rewrite a single-node plan into a staged distributed plan whose root
    output is replicated (safe to read from any task).

    If the plan ALREADY contains exchange nodes, the user has hand-placed
    the network boundaries (e.g. a custom partial-reduction tree): the
    planner does not distribute further — it only finalizes what was placed
    (stage stamping + 1:1 elision), mirroring the reference's pre-injected
    boundary handling (`distributed_query_planner.rs:78-99`). The
    replicated-root contract still holds: a hand-built tree whose root is
    partitioned gets the same trailing coalesce the automatic path adds."""
    if plan.collect(lambda n: getattr(n, "is_exchange", False)):
        if _root_distribution(plan) == Distribution.PARTITIONED:
            plan = CoalesceExchangeExec(plan, config.num_tasks)
        plan = _partial_agg_pushdown_pass(plan, config)
        plan = _multiway_fusion_pass(plan, config)
        return _prepare(plan)
    out, dist, ann = _inject(plan, config)
    if dist == Distribution.PARTITIONED:
        out, t_root = _seal_stage(out, ann, config)
        out = CoalesceExchangeExec(out, t_root)
    out = _partial_reduce_pass(out, config)
    out = _partial_agg_pushdown_pass(out, config)
    out = _multiway_fusion_pass(out, config)
    out = _prepare(out)
    return out


def _root_distribution(plan: ExecutionPlan) -> Distribution:
    """Distribution of a pre-injected plan's root output. Exchanges pin it
    (shuffle / N:M coalesce / replicated->partitioned split = partitioned;
    N:1 coalesce / broadcast = replicated); compute nodes are deterministic
    SPMD, so they preserve replication iff every child is replicated."""
    if isinstance(plan, ShuffleExchangeExec):
        return Distribution.PARTITIONED
    if isinstance(plan, CoalesceExchangeExec):
        return (
            Distribution.REPLICATED if plan.num_consumers == 1
            else Distribution.PARTITIONED
        )
    if isinstance(plan, BroadcastExchangeExec):
        return Distribution.REPLICATED
    if getattr(plan, "is_exchange", False):  # PartitionReplicated etc.
        return Distribution.PARTITIONED
    from datafusion_distributed_tpu.plan.exchanges import IsolatedArmExec

    if isinstance(plan, IsolatedArmExec):  # runs on one assigned task only
        return Distribution.PARTITIONED
    children = plan.children()
    if not children:
        if isinstance(plan, MemoryScanExec):
            return (
                Distribution.REPLICATED
                if plan.replicated or len(plan.tasks) == 1
                else Distribution.PARTITIONED
            )
        return Distribution.PARTITIONED
    dists = [_root_distribution(c) for c in children]
    return (
        Distribution.REPLICATED
        if all(d == Distribution.REPLICATED for d in dists)
        else Distribution.PARTITIONED
    )


# ---------------------------------------------------------------------------
# task-count lattice
# ---------------------------------------------------------------------------


def _resolve_count(ann: TaskCountAnnotation, cfg: DistributedConfig) -> int:
    """Annotation -> concrete stage task count. Inactive lattice (all knobs
    at defaults, or the mesh tier's uniform flag) resolves to num_tasks so
    stage widths stay uniform."""
    if not cfg._lattice_active():
        return cfg.num_tasks
    cap = cfg.num_tasks
    if cfg.max_tasks_per_stage > 0:
        cap = min(cap, cfg.max_tasks_per_stage)
    return max(1, min(ann.count, cap))


def _stage_cap(cfg: DistributedConfig) -> int:
    """Upper bound any stage may run at (for arm assignment spread)."""
    if cfg._lattice_active() and cfg.max_tasks_per_stage > 0:
        return max(1, min(cfg.num_tasks, cfg.max_tasks_per_stage))
    return cfg.num_tasks


def _leaf_annotation(leaf: ExecutionPlan, cfg: DistributedConfig,
                     replicated: bool = False) -> TaskCountAnnotation:
    """Task-count hint contributed by one leaf to its stage's lattice.
    Order mirrors the reference's estimator chain (`task_estimator.rs`):
    user estimator first, then the built-in bytes-based estimation, then
    Desired(num_tasks). Replicated leaves are neutral (Desired(1))."""
    if cfg.task_estimator is not None:
        est = cfg.task_estimator.task_estimation(leaf, cfg)
        if est is not None:
            return est
    if replicated:
        return TaskCountAnnotation(1)
    if cfg.size_tasks_to_data and cfg.bytes_per_task > 0:
        b = estimate_leaf_bytes(leaf)
        want = -(-b // cfg.bytes_per_task) if b else 1
        return TaskCountAnnotation(max(1, int(want)))
    return TaskCountAnnotation(cfg.num_tasks)


def _cardinality_scale(plan: ExecutionPlan, cfg: DistributedConfig) -> float:
    """Consumer-stage scale factor over one producer stage (the reference's
    CardinalityBasedNetworkBoundaryBuilder walk,
    `inject_network_boundaries.rs:595-623`): max over children, divided by
    the factor at cardinality-shrinking nodes, multiplied at growing ones."""
    if getattr(plan, "is_exchange", False):
        return 1.0
    sf = max(
        (_cardinality_scale(c, cfg) for c in plan.children()), default=1.0
    )
    f = cfg.cardinality_task_count_factor
    if not f or f == 1.0:
        return sf
    shrinks = isinstance(plan, (FilterExec, LimitExec, HashAggregateExec)) or (
        isinstance(plan, HashJoinExec)
        and plan.join_type in ("semi", "anti")
    )
    grows = isinstance(plan, (CrossJoinExec, UnionExec))
    if shrinks:
        return sf / f
    if grows:
        return sf * f
    return sf


def _consumer_count(stage: ExecutionPlan, t_producer: int,
                    cfg: DistributedConfig,
                    *siblings) -> int:
    """Task count for the stage consuming ``stage``'s boundary: Desired(
    ceil(scale_factor * producer_tasks)), merged across sibling producer
    stages feeding the same consumer (co-shuffled join sides must agree)."""
    import math

    ann = TaskCountAnnotation(
        max(1, math.ceil(_cardinality_scale(stage, cfg) * t_producer))
    )
    for sib_stage, sib_t in siblings:
        ann = ann.merge(TaskCountAnnotation(max(1, math.ceil(
            _cardinality_scale(sib_stage, cfg) * sib_t
        ))))
    return _resolve_count(ann, cfg)


def _seal_stage(sub: ExecutionPlan, ann: TaskCountAnnotation,
                cfg: DistributedConfig) -> tuple[ExecutionPlan, int]:
    """Finalize a producer stage: resolve its task count from the lattice
    and split its still-unsplit scans into that many slices (the deferred
    scale_up_leaf_node step). Hard floors: a stage can never run fewer
    tasks than an existing partitioned scan's slice count (slices beyond
    the task count would be dropped) or an isolated arm's pinned index."""
    from datafusion_distributed_tpu.plan.exchanges import IsolatedArmExec

    t = _resolve_count(ann, cfg)
    for n in _stage_nodes(sub):
        if isinstance(n, MemoryScanExec) and not n.replicated:
            if len(n.tasks) > 1:
                t = max(t, len(n.tasks))
        elif isinstance(n, ParquetScanExec) and len(n.file_groups) > 1:
            t = max(t, len(n.file_groups))
        elif isinstance(n, IsolatedArmExec):
            t = max(t, n.assigned_task + 1)
    return _split_leaves(sub, t, cfg), t


def _stage_nodes(plan: ExecutionPlan) -> list:
    """Nodes of the stage rooted at ``plan`` (stops at boundaries: deeper
    stages are already sealed)."""
    out = [plan]
    if not getattr(plan, "is_exchange", False):
        for c in plan.children():
            out.extend(_stage_nodes(c))
    return out


def _split_leaves(plan: ExecutionPlan, t: int,
                  cfg: DistributedConfig) -> ExecutionPlan:
    """Split this stage's unsplit scans into ``t`` per-task slices (the
    reference's scale_up_leaf_node applied with the stage's final count)."""
    if getattr(plan, "is_exchange", False):
        return plan
    if isinstance(plan, (MemoryScanExec, ParquetScanExec)):
        if cfg.task_estimator is not None:
            repl = cfg.task_estimator.scale_up_leaf_node(plan, t, cfg)
            if repl is not None:
                return repl
    if isinstance(plan, MemoryScanExec):
        if not plan.replicated and len(plan.tasks) == 1 and t > 1:
            return MemoryScanExec(
                partition_table(plan.tasks[0], t), plan.schema()
            )
        return plan
    if isinstance(plan, ParquetScanExec):
        if len(plan.file_groups) == 1 and t > 1:
            files = list(plan.file_groups[0])
            groups = [files[i::t] for i in range(t)]
            # per-task capacity: whole-file granularity keeps it conservative
            per_task_cap = round_up_pow2(
                max(plan.capacity * (len(files) // t + 1)
                    // max(len(files), 1), 8)
            )
            return ParquetScanExec(
                groups, plan._schema, per_task_cap, plan.projection,
                plan.dictionaries,
            )
        return plan
    children = [_split_leaves(c, t, cfg) for c in plan.children()]
    return plan.with_new_children(children) if children else plan


# ---------------------------------------------------------------------------
# boundary injection
# ---------------------------------------------------------------------------


def _inject(plan: ExecutionPlan, cfg: DistributedConfig):
    """-> (plan, distribution, TaskCountAnnotation of the open stage).

    Leaves are NOT split here: splitting waits until the stage's boundary
    resolves its final task count from the merged lattice (`_seal_stage`),
    mirroring the reference's estimate-then-scale_up_leaf_node order."""
    t = cfg.num_tasks

    # -- leaves: contribute lattice annotations; split deferred ------------
    if isinstance(plan, MemoryScanExec):
        if len(plan.tasks) == 1 and t > 1 and not plan.replicated:
            return (plan, Distribution.PARTITIONED,
                    _leaf_annotation(plan, cfg))
        replicated = plan.replicated or len(plan.tasks) == 1
        return plan, (
            Distribution.REPLICATED if replicated
            else Distribution.PARTITIONED
        ), _leaf_annotation(plan, cfg, replicated=replicated)
    if isinstance(plan, ParquetScanExec):
        return plan, Distribution.PARTITIONED, _leaf_annotation(plan, cfg)

    # -- elementwise: keep child distribution ------------------------------
    if isinstance(plan, (FilterExec, ProjectionExec, CoalescePartitionsExec)):
        child, dist, ann = _inject(plan.children()[0], cfg)
        return plan.with_new_children([child]), dist, ann

    if isinstance(plan, HashAggregateExec):
        return _inject_aggregate(plan, cfg)

    if isinstance(plan, HashJoinExec):
        return _inject_join(plan, cfg)

    if isinstance(plan, CrossJoinExec):
        left, ldist, lann = _inject(plan.left, cfg)
        right, rdist, rann = _inject(plan.right, cfg)
        if rdist == Distribution.PARTITIONED:
            right, _tb = _seal_stage(right, rann, cfg)
            right = BroadcastExchangeExec(right, t)
            # the build stage was sealed into _tb slices; without the stamp
            # the coordinator would dispatch cfg.num_tasks producer tasks
            right.producer_tasks = _tb
        return plan.with_new_children([left, right]), ldist, lann

    from datafusion_distributed_tpu.plan.window_exec import WindowExec

    if isinstance(plan, WindowExec):
        child, dist, ann = _inject(plan.child, cfg)
        if dist == Distribution.REPLICATED:
            return plan.with_new_children([child]), dist, ann
        if plan.partition_names:
            # rows of one window partition must land on one task
            child, t_p = _seal_stage(child, ann, cfg)
            t_c = _consumer_count(child, t_p, cfg)
            if t_c <= 1:
                gathered = CoalesceExchangeExec(child, t_p)
                return (plan.with_new_children([gathered]),
                        Distribution.REPLICATED, TaskCountAnnotation(1))
            shuffled = _mk_shuffle(child, plan.partition_names, cfg, t_c, t_p)
            return (plan.with_new_children([shuffled]),
                    Distribution.PARTITIONED, TaskCountAnnotation(t_c))
        child, t_p = _seal_stage(child, ann, cfg)
        gathered = CoalesceExchangeExec(child, t_p)
        return (plan.with_new_children([gathered]), Distribution.REPLICATED,
                TaskCountAnnotation(1))

    if isinstance(plan, SortExec):
        child, dist, ann = _inject(plan.child, cfg)
        if dist == Distribution.REPLICATED:
            return plan.with_new_children([child]), dist, ann
        if plan.fetch is None:
            # unlimited ORDER BY: distributed sample sort — range-shuffle
            # on the sort key, sort locally, gather in axis order (which IS
            # the global order). The old coalesce-then-sort shape made
            # every device re-sort the full gathered dataset.
            child, t_p = _seal_stage(child, ann, cfg)
            t_c = _consumer_count(child, t_p, cfg)
            # prefer the planner-stamped row ESTIMATE over padded capacity:
            # capacity is an upper bound, and pow2-padded small-but-wide
            # inputs (post-aggregate results) would otherwise take the
            # 3-stage distributed sample sort where coalesce-then-sort is
            # cheaper (ADVICE r4)
            est_total = child.est_rows
            size = (est_total if est_total is not None
                    else child.output_capacity() * max(t_p, 1))
            big = size > cfg.range_sort_threshold_rows
            if t_c > 1 and big:
                per_dest = round_up_pow2(max(
                    cfg.shuffle_skew_factor * child.output_capacity()
                    // max(t_c, 1), 8,
                ))
                rs = RangeShuffleExchangeExec(child, plan.keys, t_c, per_dest)
                rs.producer_tasks = t_p
                local = SortExec(plan.keys, rs)
                gathered = CoalesceExchangeExec(local, t_c)
                return (gathered, Distribution.REPLICATED,
                        TaskCountAnnotation(1))
            gathered = CoalesceExchangeExec(child, t_p)
            final = SortExec(plan.keys, gathered)
            return final, Distribution.REPLICATED, TaskCountAnnotation(1)
        # fetch-limited: local top-k sort -> coalesce -> final sort; fetch
        # pushdown is the push_fetch_into_network_coalesce analogue
        local = SortExec(plan.keys, child, fetch=plan.fetch)
        local, t_p = _seal_stage(local, ann, cfg)
        gathered = CoalesceExchangeExec(local, t_p)
        final = SortExec(plan.keys, gathered, fetch=plan.fetch)
        return final, Distribution.REPLICATED, TaskCountAnnotation(1)

    if isinstance(plan, LimitExec):
        child, dist, ann = _inject(plan.child, cfg)
        if dist == Distribution.REPLICATED:
            return plan.with_new_children([child]), dist, ann
        # local limit bounds rows crossing the exchange (fetch+skip of them)
        local = LimitExec(child, plan.fetch + plan.skip, 0)
        local, t_p = _seal_stage(local, ann, cfg)
        gathered = CoalesceExchangeExec(local, t_p)
        # the streaming data plane stops pulling chunks once this many rows
        # arrived — ANY fetch+skip rows satisfy an unordered LIMIT
        gathered.consumer_fetch = plan.fetch + plan.skip
        return (LimitExec(gathered, plan.fetch, plan.skip),
                Distribution.REPLICATED, TaskCountAnnotation(1))

    if isinstance(plan, UnionExec):
        from datafusion_distributed_tpu.plan.exchanges import (
            IsolatedArmExec,
            assign_arms_to_tasks,
        )

        children = []
        anns = []
        replicated_idx = []
        for i, c in enumerate(plan.children()):
            cc, cdist, cann = _inject(c, cfg)
            if cdist == Distribution.REPLICATED:
                replicated_idx.append(len(children))
            children.append(cc)
            anns.append(cann)
        ann = TaskCountAnnotation(1)
        for i, a in enumerate(anns):
            if i not in replicated_idx:
                ann = ann.merge(a)
        if replicated_idx:
            # child isolation (ChildrenIsolatorUnionExec analogue): each
            # replicated arm is COMPUTED on exactly one task — weighted
            # greedy assignment; running it everywhere and row-slicing after
            # the fact (round-1's PartitionReplicated) pays the arm's FLOPs
            # T times
            weights = [
                float(children[i].output_capacity()) for i in replicated_idx
            ]
            assigned = assign_arms_to_tasks(weights, _stage_cap(cfg))
            for i, task in zip(replicated_idx, assigned):
                children[i] = IsolatedArmExec(children[i], task)
            ann = ann.merge(TaskCountAnnotation(1 + max(assigned)))
        return UnionExec(children), Distribution.PARTITIONED, ann

    if not plan.children():
        return plan, Distribution.REPLICATED, TaskCountAnnotation(1)

    # default: single child passthrough
    children = []
    dist = Distribution.REPLICATED
    ann = TaskCountAnnotation(1)
    for c in plan.children():
        cc, cdist, cann = _inject(c, cfg)
        children.append(cc)
        if cdist == Distribution.PARTITIONED:
            dist = Distribution.PARTITIONED
        ann = ann.merge(cann)
    return plan.with_new_children(children), dist, ann


def _inject_aggregate(plan: HashAggregateExec, cfg: DistributedConfig):
    child, dist, ann = _inject(plan.child, cfg)
    if dist == Distribution.REPLICATED:
        return plan.with_new_children([child]), dist, ann
    if plan.mode != "single":
        # already split by a previous pass
        return plan.with_new_children([child]), dist, ann

    if not plan.group_names:
        partial = HashAggregateExec(
            "partial", [], plan.aggs, child, plan.num_slots
        )
        partial, t_p = _seal_stage(partial, ann, cfg)
        gathered = CoalesceExchangeExec(partial, t_p)
        final = HashAggregateExec(
            "final", [], plan.aggs, gathered, plan.num_slots
        )
        return final, Distribution.REPLICATED, TaskCountAnnotation(1)

    if cfg.global_hash_agg:
        rewritten = _inject_global_agg(plan, child, ann, cfg)
        if rewritten is not None:
            return rewritten

    partial = HashAggregateExec(
        "partial", plan.group_names, plan.aggs, child, plan.num_slots
    )
    partial.est_rows = plan.est_rows  # NDV estimate survives the split
    partial, t_p = _seal_stage(partial, ann, cfg)
    t_c = _consumer_count(partial, t_p, cfg)
    if t_c <= 1:
        # one consumer: gather instead of shuffle (keys co-locate trivially;
        # the coalesced output is replicated, not partitioned)
        gathered = CoalesceExchangeExec(partial, t_p)
        final = HashAggregateExec(
            "final", plan.group_names, plan.aggs, gathered, plan.num_slots
        )
        final.est_rows = plan.est_rows
        return final, Distribution.REPLICATED, TaskCountAnnotation(1)
    shuffle = _mk_shuffle(partial, plan.group_names, cfg, t_c, t_p)
    final = HashAggregateExec(
        "final", plan.group_names, plan.aggs, shuffle,
        min(plan.num_slots, round_up_pow2(max(shuffle.output_capacity(), 16))),
    )
    final.est_rows = plan.est_rows
    return final, Distribution.PARTITIONED, TaskCountAnnotation(t_c)


def _inject_global_agg(plan: HashAggregateExec, child, ann,
                       cfg: DistributedConfig):
    """Global-hash-table aggregation shape (`SET distributed.global_hash_agg`
    — *Global Hash Tables Strike Back!*): when sampled NDV predicts the
    partial-state rows will NOT meaningfully undercut the raw rows (the
    high-NDV regime where per-partition tables + merge is pure overhead),
    shuffle the RAW rows on the group keys and run ONE single-mode
    aggregate per task over its disjoint key range — one shared table, no
    merge step. Under DFTPU_PALLAS=1 that single-mode aggregate lowers to
    the fused build+accumulate kernel (ops/pallas_hash.
    pallas_global_hash_aggregate). Returns the (plan, dist, annotation)
    triple or None to keep the partial+final shape."""
    from datafusion_distributed_tpu.planner.statistics import (
        estimate_rows,
        predict_partial_agg_reduction,
    )

    sealed, t_p = _seal_stage(child, ann, cfg)
    t_c = _consumer_count(sealed, t_p, cfg)
    if t_c <= 1:
        return None  # one consumer: the gather shape is already merge-free
    rows_in = estimate_rows(child)
    ndv = (max(float(plan.est_rows), 1.0) if plan.est_rows is not None
           else max(rows_in ** 0.5, 1.0))
    pred = predict_partial_agg_reduction(rows_in, ndv, t_p)
    if pred.reduction >= cfg.partial_agg_pushdown_min_reduction:
        return None  # low NDV: partial states collapse; keep partial+final
    shuffle = _mk_shuffle(sealed, plan.group_names, cfg, t_c, t_p)
    # the shared table is NDV-sized upstream (plan.num_slots comes from the
    # catalog's sampled NDV), capped by what the exchange can deliver to
    # one task — capacity-safe: groups <= delivered rows
    single = HashAggregateExec(
        "single", plan.group_names, plan.aggs, shuffle,
        min(plan.num_slots,
            round_up_pow2(max(shuffle.output_capacity(), 16))),
    )
    single.est_rows = plan.est_rows
    single.global_agg_selected = True
    from datafusion_distributed_tpu.runtime.adaptivity import (
        note_global_agg_selected,
    )

    note_global_agg_selected()
    return single, Distribution.PARTITIONED, TaskCountAnnotation(t_c)


def _mk_shuffle(child, keys, cfg: DistributedConfig,
                t_consumer: Optional[int] = None,
                t_producer: Optional[int] = None) -> ShuffleExchangeExec:
    t = t_consumer if t_consumer is not None else cfg.num_tasks
    per_dest = round_up_pow2(
        max(cfg.shuffle_skew_factor * child.output_capacity() // max(t, 1), 8)
    )
    ex = ShuffleExchangeExec(child, keys, t, per_dest)
    if t_producer is not None:
        ex.producer_tasks = t_producer
    return ex


def _repack_slots(partial: HashAggregateExec) -> int:
    """Slot count for a partial_reduce re-pack: one task's slice can
    hold at most `slice_capacity` distinct keys, so
    min(global_slots, pow2(2 * slice_capacity)) keeps the load factor
    <= 0.5 without the global table's padding (capacity-safe: groups
    <= slice rows <= slice capacity, so this can never overflow)."""
    return min(
        partial.num_slots,
        round_up_pow2(max(2 * partial.child.output_capacity(), 16)),
    )


def _repack_partial_shuffle(
    node: ShuffleExchangeExec, cfg: DistributedConfig,
    cap_per_dest: bool = False,
) -> ShuffleExchangeExec:
    """Insert a `partial_reduce` re-group between ``node``'s partial
    aggregate and the shuffle, re-sizing the per-destination capacity
    from the tighter slot count. ONE rewrite shared by
    `_partial_reduce_pass` (unconditional, knob-gated) and the
    stats-gated shape of `_partial_agg_pushdown_pass` — the capacity
    arithmetic must not drift between them. ``cap_per_dest`` bounds the
    new per-destination capacity by the original shuffle's (the
    push-down pass never widens an exchange)."""
    partial = node.child
    slots = _repack_slots(partial)
    reduce_node = HashAggregateExec(
        "partial_reduce", partial.group_names, partial.aggs, partial,
        slots,
    )
    per_dest = round_up_pow2(max(
        cfg.shuffle_skew_factor * slots // max(node.num_tasks, 1), 8
    ))
    if cap_per_dest:
        per_dest = min(node.per_dest_capacity, per_dest)
    ex = ShuffleExchangeExec(
        reduce_node, node.key_names, node.num_tasks, per_dest
    )
    ex.stage_id = node.stage_id
    ex.producer_tasks = getattr(node, "producer_tasks", None)
    ex.consumer_fetch = node.consumer_fetch
    ex.predicted_exchange_bytes = node.predicted_exchange_bytes
    return ex


def _partial_reduce_pass(plan: ExecutionPlan,
                         cfg: DistributedConfig) -> ExecutionPlan:
    """Insert `mode=partial_reduce` between a producer stage's partial
    aggregate and its hash shuffle (the reference's
    `partial_reduce_below_network_shuffles.rs`, gated off by default by
    `DistributedConfig.partial_reduce` exactly like the reference knob).

    TPU rationale: exchange payloads are PADDED capacity buffers, and a
    partial aggregate is sized for the GLOBAL group cardinality while one
    task's slice can only hold `slice_capacity` distinct keys. The inserted
    re-group re-packs partial states into `min(global_slots,
    2*slice_capacity)` slots, shrinking the all_to_all payload for
    high-cardinality GROUP BYs (the merge itself is the same accumulator
    merge the reference performs post-repartition)."""
    if not cfg.partial_reduce:
        return plan

    def walk(node: ExecutionPlan) -> ExecutionPlan:
        children = [walk(c) for c in node.children()]
        if children:
            node = node.with_new_children(children)
        if not (
            isinstance(node, ShuffleExchangeExec)
            and isinstance(node.child, HashAggregateExec)
            and node.child.mode == "partial"
            and node.child.group_names
            and list(node.key_names) == list(node.child.group_names)
        ):
            return node
        return _repack_partial_shuffle(node, cfg)

    return walk(plan)


def _partial_agg_pushdown_pass(plan: ExecutionPlan,
                               cfg: DistributedConfig) -> ExecutionPlan:
    """Statistics-driven partial-aggregate push-down below hash shuffles
    (`DistributedConfig.partial_agg_pushdown`, default off).

    Two shapes, both decided from the SAMPLED key-distribution
    statistics the planner already carries (catalog NDV samples stamped
    as `est_rows` — planner/statistics.py):

    1. ``agg(single) over shuffle over raw rows`` (pre-injected /
       hand-placed boundaries, where the SQL planner's eager split never
       ran): rewrite to ``agg(final) over shuffle over agg(partial)``
       when the predicted partial-state bytes undercut the raw-row bytes
       by at least `partial_agg_pushdown_min_reduction`. Eligibility:
       decomposable aggregates only (sum/count/min/max, avg via its
       sum+count decomposition — ops/aggregate.py
       PUSHDOWN_DECOMPOSABLE_FUNCS) and shuffle keys ⊆ group keys (same
       group ⇒ same partition, so the final merge is partition-local).
       The rewritten shuffle's per-destination capacity and the final
       aggregate's merge-table sizing come from the same prediction —
       the consumer-side merge schedule follows the statistics instead
       of the raw-row capacities.

    2. ``shuffle over agg(partial)`` (the SQL planner's eager split):
       the exchange already carries partial states; stamp the predicted
       exchange bytes (so the coordinator can record
       predicted-vs-measured through the telemetry registry) and insert
       a `partial_reduce` re-pack — the `_partial_reduce_pass` rewrite —
       only where the statistics predict it pays (per-task groups well
       under the padded slice capacity), instead of unconditionally.

    The decision is the distribution-aware placement of *Chasing
    Similarity*: low-NDV keys collapse under pre-exchange aggregation
    (q1's handful of groups), high-NDV keys gain nothing and skip the
    extra aggregate. Prediction math: `expected_distinct` /
    `predict_partial_agg_reduction` (planner/statistics.py)."""
    if not cfg.partial_agg_pushdown:
        return plan
    from datafusion_distributed_tpu.ops.aggregate import (
        PUSHDOWN_DECOMPOSABLE_FUNCS,
    )
    from datafusion_distributed_tpu.planner.statistics import (
        estimate_rows,
        predict_partial_agg_reduction,
        row_width,
    )

    threshold = max(min(cfg.partial_agg_pushdown_min_reduction, 1.0), 0.0)

    def agg_ndv(agg: HashAggregateExec, rows_in: float) -> float:
        if agg.est_rows is not None:
            return max(float(agg.est_rows), 1.0)
        return max(rows_in ** 0.5, 1.0)

    def walk(node: ExecutionPlan) -> ExecutionPlan:
        children = [walk(c) for c in node.children()]
        if children:
            node = node.with_new_children(children)
        # -- shape 1: single aggregate directly above a raw-row shuffle --
        if (
            isinstance(node, HashAggregateExec)
            and node.mode == "single"
            and node.group_names
            and type(node.child) is ShuffleExchangeExec
            and not isinstance(node.child.child, HashAggregateExec)
            and set(node.child.key_names) <= set(node.group_names)
            and all(a.func in PUSHDOWN_DECOMPOSABLE_FUNCS
                    for a in node.aggs)
            # the global-hash-agg shape IS single-over-raw-shuffle by
            # design — never rewrite it back to partial+final
            and not getattr(node, "global_agg_selected", False)
        ):
            ex = node.child
            t_prod = (ex.producer_tasks if ex.producer_tasks is not None
                      else ex.num_tasks)
            rows_in = estimate_rows(ex.child)
            ndv = agg_ndv(node, rows_in)
            pred = predict_partial_agg_reduction(rows_in, ndv, t_prod)
            partial = HashAggregateExec(
                "partial", node.group_names, node.aggs, ex.child,
            )
            partial.est_rows = node.est_rows
            # runtime bail-out candidacy (runtime/adaptivity.py): the
            # coordinator probes the first task's measured reduction and
            # swaps the partial for a passthrough when this prediction
            # was wrong. Coordinator-side annotation only — never
            # fingerprinted, never serialized.
            partial.bailout_candidate = True
            partial.predicted_partial_rows = int(pred.rows_out)
            w_raw = row_width(ex.child.schema())
            w_partial = row_width(partial.schema())
            bytes_in = rows_in * w_raw
            bytes_out = pred.rows_out * w_partial
            if bytes_in <= 0 or (
                1.0 - bytes_out / bytes_in
            ) < threshold:
                return node  # high-NDV regime: aggregate after the wire
            per_dest = min(
                ex.per_dest_capacity,
                round_up_pow2(max(
                    cfg.shuffle_skew_factor
                    * int(pred.rows_per_task + 1) // max(ex.num_tasks, 1),
                    8,
                )),
            )
            new_ex = ShuffleExchangeExec(
                partial, ex.key_names, ex.num_tasks, per_dest
            )
            new_ex.stage_id = ex.stage_id
            new_ex.producer_tasks = ex.producer_tasks
            new_ex.consumer_fetch = ex.consumer_fetch
            new_ex.predicted_exchange_bytes = int(bytes_out)
            # consumer-side merge sizing mirrors _inject_aggregate's
            # final stage: bounded by what the rewritten exchange can
            # actually deliver (never an overflow the session retry
            # could not already handle)
            final = HashAggregateExec(
                "final", node.group_names, node.aggs, new_ex,
                min(node.num_slots,
                    round_up_pow2(max(new_ex.output_capacity(), 16))),
            )
            final.est_rows = node.est_rows
            return final
        # -- shape 2: shuffle already over an eager partial aggregate ----
        if (
            type(node) is ShuffleExchangeExec
            and isinstance(node.child, HashAggregateExec)
            and node.child.mode == "partial"
            and node.child.group_names
            and list(node.key_names) == list(node.child.group_names)
        ):
            partial = node.child
            t_prod = (node.producer_tasks
                      if node.producer_tasks is not None
                      else node.num_tasks)
            rows_in = estimate_rows(partial.child)
            ndv = agg_ndv(partial, rows_in)
            pred = predict_partial_agg_reduction(rows_in, ndv, t_prod)
            node.predicted_exchange_bytes = int(
                pred.rows_out * row_width(partial.schema())
            )
            partial.bailout_candidate = True
            partial.predicted_partial_rows = int(pred.rows_out)
            # stats-gated partial_reduce re-pack (the SAME rewrite the
            # partial_reduce knob applies unconditionally —
            # _repack_partial_shuffle): only when a task's slice
            # capacity bounds its groups far tighter than the global
            # table AND the key distribution actually collapses
            if (_repack_slots(partial) < partial.num_slots
                    and pred.reduction >= threshold
                    and not isinstance(partial.child,
                                       HashAggregateExec)):
                return _repack_partial_shuffle(node, cfg,
                                               cap_per_dest=True)
        return node

    return walk(plan)


def _multiway_fusion_pass(
    plan: ExecutionPlan, cfg: DistributedConfig
) -> ExecutionPlan:
    """Fuse chains of >= 2 key-compatible binary hash joins into one
    MultiwayHashJoinExec stage (`SET distributed.multiway_join`).

    Two link shapes extend a chain downward through a join's probe side:

    - **same-stage link** (broadcast build): the probe child IS another
      hash join — no exchange separates them, fusing just packs both probes
      into one node (one compiled program instead of two kernel subtrees).
    - **shuffle link**: the probe child is a shuffle S over a join whose
      OWN probe arrived through a shuffle S2 with the SAME key names and
      the SAME task count. Probe-side key columns pass through a join
      unchanged, so re-hashing them sends every row back to the task it is
      already on — S is an identity re-partition and is DELETED. Name
      safety: each key must resolve on the probe stream and be unshadowed
      by any build-side column, otherwise the "same columns" premise
      breaks.

    Gates: the statistics module bounds the fused stage's combined
    resident build bytes (every build table is live in one program), and
    kept build-side shuffles must match the base layout's task count. The
    fused node is marked `multiway_bailout_candidate` so the coordinator
    can swap it back to the binary chain when measured build sizes diverge
    (runtime/coordinator._bailout_multiway).

    Runs AFTER the push-down pass (so aggregate rewrites see the original
    exchanges) and BEFORE _prepare (stage ids are stamped on whatever
    exchanges survive).
    """
    if not cfg.multiway_join:
        return plan

    from datafusion_distributed_tpu.planner.statistics import (
        choose_probe_order,
        multiway_fusion_allowed,
    )

    def build_schemas(j):
        if isinstance(j, MultiwayHashJoinExec):
            return [b.schema() for b in j.builds]
        return [j.build.schema()]

    def fusible_inner(p):
        """(inner join-or-fused-stage feeding ``p``, shuffle this link
        deletes or None) — or (None, None) when the chain stops here."""
        if isinstance(p, (HashJoinExec, MultiwayHashJoinExec)):
            return p, None  # same-stage link
        if (type(p) is ShuffleExchangeExec
                and isinstance(p.child,
                               (HashJoinExec, MultiwayHashJoinExec))):
            inner = p.child
            s2 = inner.probe
            if (type(s2) is ShuffleExchangeExec
                    and list(p.key_names) == list(s2.key_names)
                    and p.num_tasks == s2.num_tasks):
                probe_names = set(inner.probe.schema().names)
                build_names = set()
                for bs in build_schemas(inner):
                    build_names |= set(bs.names)
                if (set(p.key_names) <= probe_names
                        and not (set(p.key_names) & build_names)):
                    return inner, p
        return None, None

    def try_fuse(outer: ExecutionPlan) -> ExecutionPlan:
        if not isinstance(outer, HashJoinExec):
            return outer
        steps = [MultiwayJoinStep.from_join(outer)]
        builds = [outer.build]
        probe = outer.probe
        deleted = 0
        while True:
            inner, ex = fusible_inner(probe)
            if inner is None:
                break
            if isinstance(inner, MultiwayHashJoinExec):
                steps = list(inner.steps) + steps
                builds = list(inner.builds) + builds
                deleted += inner.multiway_deleted_exchanges or 0
            else:
                steps = [MultiwayJoinStep.from_join(inner)] + steps
                builds = [inner.build] + builds
            if ex is not None:
                deleted += 1
            probe = inner.probe
        if len(steps) < 2:
            return outer
        if deleted:
            # the fused stage runs on the base shuffle's layout; every kept
            # co-shuffled build must agree with it
            t = (probe.num_tasks if type(probe) is ShuffleExchangeExec
                 else None)
            if t is None:
                return outer
            for b in builds:
                if type(b) is ShuffleExchangeExec and b.num_tasks != t:
                    return outer
        if not multiway_fusion_allowed(builds, cfg.multiway_build_bytes_max):
            return outer
        mw = MultiwayHashJoinExec(probe, builds, steps)
        mw.multiway_bailout_candidate = True
        mw.est_rows = outer.est_rows
        mw.multiway_deleted_exchanges = deleted
        if cfg.multiway_probe_reorder:
            mw.probe_order_hint = choose_probe_order(builds)
        return mw

    def walk(node: ExecutionPlan) -> ExecutionPlan:
        children = [walk(c) for c in node.children()]
        if children:
            node = node.with_new_children(children)
        return try_fuse(node)

    out = walk(plan)
    fused = 0
    removed = 0
    for n in out.collect(lambda x: isinstance(x, MultiwayHashJoinExec)):
        if getattr(n, "multiway_deleted_exchanges", None) is not None:
            fused += len(n.steps)
            removed += n.multiway_deleted_exchanges
    if fused:
        from datafusion_distributed_tpu.runtime.adaptivity import (
            note_multiway_fusion,
        )

        note_multiway_fusion(fused, removed)
    return out


def _inject_join(plan: HashJoinExec, cfg: DistributedConfig):
    """Join distribution rules. Correctness constraints:

    - preserved-side join types (left/semi/anti/mark) need every build row
      that could match a probe row visible on that probe row's task: either
      broadcast the build, or co-shuffle BOTH sides on the join keys.
    - a REPLICATED input must never be shuffled (every task would inject its
      full copy -> T-fold duplication); replicated probe forces a
      replicated/broadcast build.
    - null-aware anti (NOT IN) needs the global "any NULL build key" fact, so
      the build is always broadcast.
    - co-shuffled sides share ONE consumer task count (`hash % t` must agree
      or co-partitioning breaks), merged from both sides' lattices.
    """
    t = cfg.num_tasks
    probe, pdist, pann = _inject(plan.probe, cfg)
    build, bdist, bann = _inject(plan.build, cfg)
    preserved = plan.join_type in ("left", "semi", "anti", "mark")

    if bdist == Distribution.REPLICATED and pdist == Distribution.REPLICATED:
        return (plan.with_new_children([probe, build]),
                Distribution.REPLICATED, pann.merge(bann))

    if bdist == Distribution.REPLICATED:
        # build already everywhere; partitioned probe joins locally
        return plan.with_new_children([probe, build]), pdist, pann

    small_build = (
        cfg.broadcast_joins
        and build.output_capacity() <= cfg.broadcast_threshold_rows
    )
    must_broadcast = (
        plan.null_aware
        or pdist == Distribution.REPLICATED
    )
    if must_broadcast or small_build:
        build, _tb = _seal_stage(build, bann, cfg)
        b = BroadcastExchangeExec(build, t)
        b.producer_tasks = _tb
        out = plan.with_new_children([probe, b])
        return out, pdist, pann

    # co-shuffle both sides on the join keys (probe is PARTITIONED here;
    # applies to preserved joins and plain inner joins alike)
    probe, t_pp = _seal_stage(probe, pann, cfg)
    build, t_pb = _seal_stage(build, bann, cfg)
    t_c = _consumer_count(probe, t_pp, cfg, (build, t_pb))
    if t_c <= 1:
        # one consumer: gather both sides; the join runs replicated
        p = CoalesceExchangeExec(probe, t_pp)
        b = CoalesceExchangeExec(build, t_pb)
        return (plan.with_new_children([p, b]), Distribution.REPLICATED,
                TaskCountAnnotation(1))
    p = _mk_shuffle(probe, plan.probe_keys, cfg, t_c, t_pp)
    b = _mk_shuffle(build, plan.build_keys, cfg, t_c, t_pb)
    out = plan.with_new_children([p, b])
    return out, Distribution.PARTITIONED, TaskCountAnnotation(t_c)


# ---------------------------------------------------------------------------
# prepare: elide no-op boundaries, stamp stage ids
# ---------------------------------------------------------------------------


def _prepare(plan: ExecutionPlan) -> ExecutionPlan:
    """Stamp stage ids bottom-up (the (query_id, stage_num) of the
    reference's TaskKey) and elide degenerate 1-task exchanges."""
    counter = [0]

    def walk(node: ExecutionPlan) -> ExecutionPlan:
        children = [walk(c) for c in node.children()]
        node = node.with_new_children(children) if children else node
        if getattr(node, "is_exchange", False):
            if node.num_tasks <= 1:
                return node.children()[0]  # 1:1 boundary elision
            node.stage_id = counter[0]
            counter[0] += 1
        return node

    return walk(plan)


@dataclass
class StageDagNode:
    """One schedulable stage: an exchange boundary whose producer subtree
    runs as a worker-task fan-out. ``deps`` are the stage ids of the
    exchanges on the producer subtree's FRONTIER — the stages whose
    materialized output this one consumes (node = stage, edge = data
    dependency; the reference fans all stage work out as concurrent async
    sends, `query_coordinator.rs:140-222`). ``est_bytes`` is the stage's
    OWN device-buffer estimate (output_capacity x row_width summed over
    the nodes between this boundary and its frontier, nested stages
    excluded) — the cost hint the multi-query serving scheduler uses to
    order same-pass stages deterministically (runtime/serving.py)."""

    stage_id: int
    exchange: ExecutionPlan
    deps: tuple = ()
    est_bytes: int = 0
    #: planned output rows of the exchange boundary (capacity upper
    #: bound) — with est_bytes, the planner's cost hints for this stage
    est_rows: int = 0

    def span_attrs(self) -> dict:
        """Planner cost hints as trace-span attributes: the distributed
        tracer (runtime/tracing.py) stamps these onto the stage span so a
        profile can compare planned bytes/rows against the measured
        data-plane counters of the same stage."""
        return {
            "est_bytes": int(self.est_bytes),
            "est_rows": int(self.est_rows),
            "deps": list(self.deps),
            "exchange": type(self.exchange).__name__,
        }


@dataclass
class StageDag:
    """Dependency graph of a staged plan's exchange subtrees. Because each
    exchange has exactly one consumer in the plan tree, the graph is a
    tree of stages — what the concurrent scheduler exploits is SIBLING
    independence: a hash join's build and probe feeds, the 2+ producer
    stages of every co-shuffled group, union branches, independent scans
    share no edges and may run concurrently."""

    nodes: dict  # stage_id -> StageDagNode
    root_deps: tuple  # frontier stage ids of the root consumer stage

    def consumers_map(self) -> dict:
        """stage_id -> sorted stage ids consuming its output (the reverse
        edges). The concurrent scheduler releases these as their feeds
        materialize; because every released stage's task dispatch resolves
        LIVE cluster membership, a worker that joins mid-query starts
        receiving tasks at the next stage released off this map."""
        out: dict = {}
        for sid, n in self.nodes.items():
            for d in n.deps:
                out.setdefault(d, []).append(sid)
        for sids in out.values():
            sids.sort()
        return out

    def schedulable_order(self) -> list:
        """Deterministic topological order (ascending stage_id within each
        ready frontier) — with stage_parallelism=1 this reproduces the
        depth-first recursion's post-order exactly, because `_prepare`
        stamps stage ids in the same post-order walk."""
        waiting = {sid: set(n.deps) for sid, n in self.nodes.items()}
        order: list = []
        while waiting:
            ready = sorted(s for s, deps in waiting.items() if not deps)
            if not ready:  # cycle — cannot happen for tree-shaped plans
                raise ValueError("stage DAG has a cycle")
            for s in ready:
                order.append(s)
                del waiting[s]
            for deps in waiting.values():
                deps.difference_update(ready)
        return order


def exchange_frontier(node: ExecutionPlan) -> list:
    """The exchange nodes reachable from ``node`` without crossing another
    exchange boundary — the stages whose output the stage headed at
    ``node`` directly consumes."""
    out: list = []
    for c in node.children():
        if getattr(c, "is_exchange", False):
            out.append(c)
        else:
            out.extend(exchange_frontier(c))
    return out


def stage_device_bytes(exchange: ExecutionPlan) -> int:
    """Device-buffer estimate for ONE stage: the exchange boundary plus
    its producer subtree up to (not across) nested exchange boundaries —
    the statistics.plan_device_bytes arithmetic scoped to a single
    schedulable unit. Nested stages are their own DAG nodes and carry
    their own estimates."""
    from datafusion_distributed_tpu.planner.statistics import row_width

    total = 0

    def node_bytes(node) -> int:
        try:
            w = row_width(node.schema())
        except Exception:
            w = 8
        try:
            cap = int(node.output_capacity())
        except Exception:
            cap = 0
        return cap * max(w, 1)

    def walk(node, root: bool) -> None:
        nonlocal total
        if not root and getattr(node, "is_exchange", False):
            return  # nested boundary: a different stage's cost
        total += node_bytes(node)
        for c in node.children():
            walk(c, False)

    walk(exchange, True)
    return total


def build_stage_dag(plan: ExecutionPlan) -> Optional[StageDag]:
    """Extract the stage dependency DAG from a staged plan, or None when
    the plan is not DAG-schedulable and the caller must fall back to the
    sequential depth-first recursion: exchanges missing a stamped
    stage_id (hand-built plans that never went through `_prepare`),
    duplicate stage ids, or a shared exchange OBJECT appearing twice in
    the tree (the recursion materializes it once per occurrence; the DAG
    would silently dedupe, changing semantics)."""
    exchanges: list = []
    seen_objs: set = set()
    dup = [False]

    def walk(node: ExecutionPlan) -> None:
        if dup[0]:
            return
        if getattr(node, "is_exchange", False):
            if id(node) in seen_objs:
                dup[0] = True
                return
            seen_objs.add(id(node))
            exchanges.append(node)
        for c in node.children():
            walk(c)

    walk(plan)
    if dup[0]:
        return None
    sids = [e.stage_id for e in exchanges]
    if any(s is None for s in sids) or len(set(sids)) != len(sids):
        return None
    def est_rows_of(e) -> int:
        try:
            return int(e.output_capacity())
        except Exception:
            return 0

    nodes = {
        e.stage_id: StageDagNode(
            e.stage_id, e,
            deps=tuple(f.stage_id
                       for f in exchange_frontier(e.children()[0])),
            est_bytes=stage_device_bytes(e),
            est_rows=est_rows_of(e),
        )
        for e in exchanges
    }
    if getattr(plan, "is_exchange", False):
        root_deps = (plan.stage_id,)
    else:
        root_deps = tuple(f.stage_id for f in exchange_frontier(plan))
    return StageDag(nodes=nodes, root_deps=root_deps)


def collect_stages(plan: ExecutionPlan) -> list:
    """[(stage_id, exchange node)] in bottom-up order, for display/metrics."""
    out = []

    def walk(node):
        for c in node.children():
            walk(c)
        if getattr(node, "is_exchange", False):
            out.append((node.stage_id, node))

    walk(plan)
    return out


def display_staged_plan(plan: ExecutionPlan) -> str:
    """ASCII stage-tree display (the reference's display_plan_ascii stage
    boxes, `stage.rs:266-355`)."""
    lines = []

    def walk(node, indent):
        marker = ""
        if getattr(node, "is_exchange", False):
            marker = f" ── stage {node.stage_id} boundary"
        lines.append("  " * indent + node.display() + marker)
        for c in node.children():
            walk(c, indent + 1)

    walk(plan, 0)
    return "\n".join(lines)


def display_staged_plan_graphviz(plan: ExecutionPlan) -> str:
    """Graphviz DOT rendering with one cluster per stage (the reference's
    display_plan_graphviz, `stage.rs:618-685`). Render with
    `dot -Tsvg plan.dot`."""
    nodes: list[str] = []
    edges: list[str] = []
    clusters: dict[int, list[str]] = {}

    def nid(node) -> str:
        return f"n{node.node_id}"

    def walk(node, stage: int) -> None:
        label = node.display().replace('"', "'")
        this_stage = stage
        if getattr(node, "is_exchange", False) and node.stage_id is not None:
            this_stage = node.stage_id
            nodes.append(
                f'  {nid(node)} [label="{label}", shape=cds, '
                'style=filled, fillcolor=lightsteelblue];'
            )
        else:
            clusters.setdefault(stage, []).append(
                f'    {nid(node)} [label="{label}", shape=box];'
            )
        for c in node.children():
            child_stage = this_stage
            if getattr(node, "is_exchange", False):
                # an exchange's child opens its producer stage
                child_stage = (
                    node.stage_id if node.stage_id is not None else stage
                )
            walk(c, child_stage)
            edges.append(f"  {nid(c)} -> {nid(node)};")

    walk(plan, -1)
    out = ["digraph staged_plan {", "  rankdir=BT;"]
    out.extend(nodes)
    for stage, members in sorted(clusters.items()):
        name = "root" if stage == -1 else f"stage_{stage}"
        out.append(f"  subgraph cluster_{name.replace('-', 'm')} {{")
        out.append(f'    label="{name}";')
        out.extend(members)
        out.append("  }")
    out.extend(edges)
    out.append("}")
    return "\n".join(out)
