"""Distributed planner: single-node physical plan -> staged SPMD plan.

The reference's `DistributedQueryPlanner` pipeline (SURVEY.md §2.1,
`/root/reference/src/distributed_planner/distributed_query_planner.rs`):
shape -> insert broadcasts -> inject network boundaries (task-count lattice)
-> prepare (elide 1:1, stamp stage ids). This module is the TPU re-design of
those passes over our ExecutionPlan IR:

- `inject_boundaries` walks bottom-up tracking each subtree's *distribution*
  (PARTITIONED across tasks vs REPLICATED on all), rewriting:
    aggregate  -> partial agg | shuffle(keys) | final agg
                  (global agg -> partial | coalesce | final)
    hash join  -> shuffle both sides on the join keys, or broadcast the
                  build side when it is small (`insert_broadcast.rs`
                  CollectLeft analogue; `broadcast_threshold` config)
    sort/limit -> local sort/top-k | coalesce | final sort/limit
                  (the push_fetch_into_network_coalesce fetch pushdown)
- leaf scale-up splits scans into per-task slices
  (`task_estimator.rs` scale_up_leaf_node / DistributedLeafExec analogue)
- `prepare` elides boundaries whose producer and consumer distributions
  already agree and stamps stage ids (`prepare_network_boundaries.rs`).

Task counts: stages run at the mesh size. The Desired/Maximum annotation
lattice of the reference drives *task routing* when meshes are larger than
useful parallelism; carried in TaskCountAnnotation for parity.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional

from datafusion_distributed_tpu.ops.aggregate import AggSpec
from datafusion_distributed_tpu.ops.table import round_up_pow2
from datafusion_distributed_tpu.parallel.exchange import partition_table
from datafusion_distributed_tpu.plan.exchanges import (
    BroadcastExchangeExec,
    CoalesceExchangeExec,
    ShuffleExchangeExec,
)
from datafusion_distributed_tpu.plan.joins import (
    CrossJoinExec,
    HashJoinExec,
    UnionExec,
)
from datafusion_distributed_tpu.plan.physical import (
    CoalescePartitionsExec,
    ExecutionPlan,
    FilterExec,
    HashAggregateExec,
    LimitExec,
    MemoryScanExec,
    ParquetScanExec,
    ProjectionExec,
    SortExec,
)


class Distribution(enum.Enum):
    PARTITIONED = "partitioned"  # each task owns a disjoint row slice
    REPLICATED = "replicated"  # every task holds the full data


@dataclass(frozen=True)
class TaskCountAnnotation:
    """Desired/Maximum lattice (reference `task_estimator.rs:20-59`):
    merge(Desired a, Desired b) = Desired max(a,b); Maximum dominates
    Desired; merge(Maximum a, Maximum b) = Maximum min(a,b)."""

    count: int
    maximum: bool = False

    def merge(self, other: "TaskCountAnnotation") -> "TaskCountAnnotation":
        if self.maximum and other.maximum:
            return TaskCountAnnotation(min(self.count, other.count), True)
        if self.maximum:
            return self  # Maximum dominates: the desired count is discarded
        if other.maximum:
            return other
        return TaskCountAnnotation(max(self.count, other.count), False)


@dataclass
class DistributedConfig:
    """Knobs (subset-parity with `distributed_config.rs`)."""

    num_tasks: int = 8
    broadcast_joins: bool = True
    broadcast_threshold_rows: int = 1 << 17  # build sides smaller: broadcast
    shuffle_skew_factor: int = 4
    max_tasks_per_stage: int = 0  # 0 = num_tasks
    # wire-format knobs (reference: distributed_config.rs compression=lz4,
    # worker_connection_buffer_budget_bytes=64MiB; zstd here — lz4 is not in
    # this image)
    compression: str = "zstd"  # "zstd" | "none"
    worker_connection_buffer_budget_bytes: int = 64 << 20
    shuffle_chunk_bytes: int = 1 << 20
    # task-count estimation (reference: file_scan_config_bytes_per_partition
    # 16MiB + dynamic_task_count): leaves sized by bytes, not mesh size
    bytes_per_task: int = 16 << 20
    dynamic_task_count: bool = False
    # cost multiplier applied per cardinality-affecting node when scaling
    # consumer task counts (cardinality_task_count_factor analogue)
    cardinality_task_count_factor: float = 1.0
    # size task counts from leaf bytes (FileScanConfigTaskEstimator
    # semantics, task_estimator.rs:235-258): tasks = ceil(bytes /
    # bytes_per_task), capped at num_tasks. Host/coordinator tier only —
    # a mesh SPMD program's task count is the physical device count.
    size_tasks_to_data: bool = False


def estimate_leaf_bytes(plan: ExecutionPlan) -> int:
    """Total estimated input bytes across the plan's leaves."""
    import os as _os

    from datafusion_distributed_tpu.planner.statistics import row_width

    total = 0
    for leaf in plan.collect(lambda n: not n.children()):
        if isinstance(leaf, MemoryScanExec):
            rows = sum(int(t.num_rows) for t in leaf.tasks)
            total += rows * row_width(leaf.schema())
        elif isinstance(leaf, ParquetScanExec):
            for group in leaf.file_groups:
                for f in group:
                    try:
                        total += _os.path.getsize(f)
                    except OSError:
                        pass
    return total


def effective_num_tasks(plan: ExecutionPlan, config: DistributedConfig) -> int:
    """Bytes-based task count (the reference's ceil(total_bytes /
    bytes_per_partition) leaf estimation), clamped to [1, num_tasks]."""
    if not config.size_tasks_to_data or config.bytes_per_task <= 0:
        return config.num_tasks
    bytes_total = estimate_leaf_bytes(plan)
    want = -(-bytes_total // config.bytes_per_task) if bytes_total else 1
    return max(1, min(int(want), config.num_tasks))


def distribute_plan(
    plan: ExecutionPlan, config: DistributedConfig
) -> ExecutionPlan:
    """Rewrite a single-node plan into a staged distributed plan whose root
    output is replicated (safe to read from any task).

    If the plan ALREADY contains exchange nodes, the user has hand-placed
    the network boundaries (e.g. a custom partial-reduction tree): the
    planner does not distribute further — it only finalizes what was placed
    (stage stamping + 1:1 elision), mirroring the reference's pre-injected
    boundary handling (`distributed_query_planner.rs:78-99`). The
    replicated-root contract still holds: a hand-built tree whose root is
    partitioned gets the same trailing coalesce the automatic path adds."""
    if plan.collect(lambda n: getattr(n, "is_exchange", False)):
        if _root_distribution(plan) == Distribution.PARTITIONED:
            plan = CoalesceExchangeExec(plan, config.num_tasks)
        return _prepare(plan)
    t_eff = effective_num_tasks(plan, config)
    if t_eff != config.num_tasks:
        from dataclasses import replace as _replace

        config = _replace(config, num_tasks=t_eff)
    out, dist = _inject(plan, config)
    if dist == Distribution.PARTITIONED:
        out = CoalesceExchangeExec(out, config.num_tasks)
    out = _prepare(out)
    return out


def _root_distribution(plan: ExecutionPlan) -> Distribution:
    """Distribution of a pre-injected plan's root output. Exchanges pin it
    (shuffle / N:M coalesce / replicated->partitioned split = partitioned;
    N:1 coalesce / broadcast = replicated); compute nodes are deterministic
    SPMD, so they preserve replication iff every child is replicated."""
    if isinstance(plan, ShuffleExchangeExec):
        return Distribution.PARTITIONED
    if isinstance(plan, CoalesceExchangeExec):
        return (
            Distribution.REPLICATED if plan.num_consumers == 1
            else Distribution.PARTITIONED
        )
    if isinstance(plan, BroadcastExchangeExec):
        return Distribution.REPLICATED
    if getattr(plan, "is_exchange", False):  # PartitionReplicated etc.
        return Distribution.PARTITIONED
    from datafusion_distributed_tpu.plan.exchanges import IsolatedArmExec

    if isinstance(plan, IsolatedArmExec):  # runs on one assigned task only
        return Distribution.PARTITIONED
    children = plan.children()
    if not children:
        if isinstance(plan, MemoryScanExec):
            return (
                Distribution.REPLICATED
                if plan.replicated or len(plan.tasks) == 1
                else Distribution.PARTITIONED
            )
        return Distribution.PARTITIONED
    dists = [_root_distribution(c) for c in children]
    return (
        Distribution.REPLICATED
        if all(d == Distribution.REPLICATED for d in dists)
        else Distribution.PARTITIONED
    )


# ---------------------------------------------------------------------------
# boundary injection
# ---------------------------------------------------------------------------


def _inject(plan: ExecutionPlan, cfg: DistributedConfig):
    t = cfg.num_tasks

    # -- leaves: scale up into per-task slices -----------------------------
    if isinstance(plan, MemoryScanExec):
        if len(plan.tasks) == 1 and t > 1:
            slices = partition_table(plan.tasks[0], t)
            return MemoryScanExec(slices, plan.schema()), Distribution.PARTITIONED
        return plan, (
            Distribution.PARTITIONED if len(plan.tasks) > 1
            else Distribution.REPLICATED
        )
    if isinstance(plan, ParquetScanExec):
        if len(plan.file_groups) == 1 and t > 1:
            files = list(plan.file_groups[0])
            groups = [files[i::t] for i in range(t)]
            # per-task capacity: whole-file granularity keeps it conservative
            per_task_cap = round_up_pow2(
                max(plan.capacity * (len(files) // t + 1) // max(len(files), 1), 8)
            )
            return (
                ParquetScanExec(
                    groups, plan._schema, per_task_cap, plan.projection,
                    plan.dictionaries,
                ),
                Distribution.PARTITIONED,
            )
        return plan, Distribution.PARTITIONED

    # -- elementwise: keep child distribution ------------------------------
    if isinstance(plan, (FilterExec, ProjectionExec, CoalescePartitionsExec)):
        child, dist = _inject(plan.children()[0], cfg)
        return plan.with_new_children([child]), dist

    if isinstance(plan, HashAggregateExec):
        return _inject_aggregate(plan, cfg)

    if isinstance(plan, HashJoinExec):
        return _inject_join(plan, cfg)

    if isinstance(plan, CrossJoinExec):
        left, ldist = _inject(plan.left, cfg)
        right, rdist = _inject(plan.right, cfg)
        if rdist == Distribution.PARTITIONED:
            right = BroadcastExchangeExec(right, t)
        return plan.with_new_children([left, right]), ldist

    from datafusion_distributed_tpu.plan.window_exec import WindowExec

    if isinstance(plan, WindowExec):
        child, dist = _inject(plan.child, cfg)
        if dist == Distribution.REPLICATED:
            return plan.with_new_children([child]), dist
        if plan.partition_names:
            # rows of one window partition must land on one task
            shuffled = _mk_shuffle(child, plan.partition_names, cfg)
            return plan.with_new_children([shuffled]), Distribution.PARTITIONED
        gathered = CoalesceExchangeExec(child, t)
        return plan.with_new_children([gathered]), Distribution.REPLICATED

    if isinstance(plan, SortExec):
        child, dist = _inject(plan.child, cfg)
        if dist == Distribution.REPLICATED:
            return plan.with_new_children([child]), dist
        # local (top-k) sort -> coalesce -> final sort; fetch pushdown is the
        # push_fetch_into_network_coalesce analogue
        local = SortExec(plan.keys, child, fetch=plan.fetch)
        gathered = CoalesceExchangeExec(local, t)
        final = SortExec(plan.keys, gathered, fetch=plan.fetch)
        return final, Distribution.REPLICATED

    if isinstance(plan, LimitExec):
        child, dist = _inject(plan.child, cfg)
        if dist == Distribution.REPLICATED:
            return plan.with_new_children([child]), dist
        # local limit bounds rows crossing the exchange (fetch+skip of them)
        local = LimitExec(child, plan.fetch + plan.skip, 0)
        gathered = CoalesceExchangeExec(local, t)
        return LimitExec(gathered, plan.fetch, plan.skip), Distribution.REPLICATED

    if isinstance(plan, UnionExec):
        from datafusion_distributed_tpu.plan.exchanges import (
            IsolatedArmExec,
            assign_arms_to_tasks,
        )

        children = []
        replicated_idx = []
        for i, c in enumerate(plan.children()):
            cc, cdist = _inject(c, cfg)
            if cdist == Distribution.REPLICATED:
                replicated_idx.append(len(children))
            children.append(cc)
        if replicated_idx:
            # child isolation (ChildrenIsolatorUnionExec analogue): each
            # replicated arm is COMPUTED on exactly one task — weighted
            # greedy assignment; running it everywhere and row-slicing after
            # the fact (round-1's PartitionReplicated) pays the arm's FLOPs
            # T times
            weights = [
                float(children[i].output_capacity()) for i in replicated_idx
            ]
            assigned = assign_arms_to_tasks(weights, t)
            for i, task in zip(replicated_idx, assigned):
                children[i] = IsolatedArmExec(children[i], task)
        return UnionExec(children), Distribution.PARTITIONED

    if not plan.children():
        return plan, Distribution.REPLICATED

    # default: single child passthrough
    children = []
    dist = Distribution.REPLICATED
    for c in plan.children():
        cc, cdist = _inject(c, cfg)
        children.append(cc)
        if cdist == Distribution.PARTITIONED:
            dist = Distribution.PARTITIONED
    return plan.with_new_children(children), dist


def _inject_aggregate(plan: HashAggregateExec, cfg: DistributedConfig):
    t = cfg.num_tasks
    child, dist = _inject(plan.child, cfg)
    if dist == Distribution.REPLICATED:
        return plan.with_new_children([child]), dist
    if plan.mode != "single":
        # already split by a previous pass
        return plan.with_new_children([child]), dist

    if not plan.group_names:
        partial = HashAggregateExec(
            "partial", [], plan.aggs, child, plan.num_slots
        )
        gathered = CoalesceExchangeExec(partial, t)
        final = HashAggregateExec(
            "final", [], plan.aggs, gathered, plan.num_slots
        )
        return final, Distribution.REPLICATED

    partial = HashAggregateExec(
        "partial", plan.group_names, plan.aggs, child, plan.num_slots
    )
    shuffle = _mk_shuffle(partial, plan.group_names, cfg)
    final = HashAggregateExec(
        "final", plan.group_names, plan.aggs, shuffle,
        min(plan.num_slots, round_up_pow2(max(shuffle.output_capacity(), 16))),
    )
    return final, Distribution.PARTITIONED


def _mk_shuffle(child, keys, cfg: DistributedConfig) -> ShuffleExchangeExec:
    t = cfg.num_tasks
    per_dest = round_up_pow2(
        max(cfg.shuffle_skew_factor * child.output_capacity() // max(t, 1), 8)
    )
    return ShuffleExchangeExec(child, keys, t, per_dest)


def _inject_join(plan: HashJoinExec, cfg: DistributedConfig):
    """Join distribution rules. Correctness constraints:

    - preserved-side join types (left/semi/anti/mark) need every build row
      that could match a probe row visible on that probe row's task: either
      broadcast the build, or co-shuffle BOTH sides on the join keys.
    - a REPLICATED input must never be shuffled (every task would inject its
      full copy -> T-fold duplication); replicated probe forces a
      replicated/broadcast build.
    - null-aware anti (NOT IN) needs the global "any NULL build key" fact, so
      the build is always broadcast.
    """
    t = cfg.num_tasks
    probe, pdist = _inject(plan.probe, cfg)
    build, bdist = _inject(plan.build, cfg)
    preserved = plan.join_type in ("left", "semi", "anti", "mark")

    if bdist == Distribution.REPLICATED and pdist == Distribution.REPLICATED:
        return plan.with_new_children([probe, build]), Distribution.REPLICATED

    if bdist == Distribution.REPLICATED:
        # build already everywhere; partitioned probe joins locally
        return plan.with_new_children([probe, build]), pdist

    small_build = (
        cfg.broadcast_joins
        and build.output_capacity() <= cfg.broadcast_threshold_rows
    )
    must_broadcast = (
        plan.null_aware
        or pdist == Distribution.REPLICATED
    )
    if must_broadcast or small_build:
        b = BroadcastExchangeExec(build, t)
        out = plan.with_new_children([probe, b])
        return out, pdist

    if preserved:
        # co-shuffle both sides on the join keys (probe is PARTITIONED here)
        p = _mk_shuffle(probe, plan.probe_keys, cfg)
        b = _mk_shuffle(build, plan.build_keys, cfg)
        return plan.with_new_children([p, b]), Distribution.PARTITIONED

    # inner join, partitioned probe: co-shuffle both sides
    p = _mk_shuffle(probe, plan.probe_keys, cfg)
    b = _mk_shuffle(build, plan.build_keys, cfg)
    out = plan.with_new_children([p, b])
    return out, Distribution.PARTITIONED


# ---------------------------------------------------------------------------
# prepare: elide no-op boundaries, stamp stage ids
# ---------------------------------------------------------------------------


def _prepare(plan: ExecutionPlan) -> ExecutionPlan:
    """Stamp stage ids bottom-up (the (query_id, stage_num) of the
    reference's TaskKey) and elide degenerate 1-task exchanges."""
    counter = [0]

    def walk(node: ExecutionPlan) -> ExecutionPlan:
        children = [walk(c) for c in node.children()]
        node = node.with_new_children(children) if children else node
        if getattr(node, "is_exchange", False):
            if node.num_tasks <= 1:
                return node.children()[0]  # 1:1 boundary elision
            node.stage_id = counter[0]
            counter[0] += 1
        return node

    return walk(plan)


def collect_stages(plan: ExecutionPlan) -> list:
    """[(stage_id, exchange node)] in bottom-up order, for display/metrics."""
    out = []

    def walk(node):
        for c in node.children():
            walk(c)
        if getattr(node, "is_exchange", False):
            out.append((node.stage_id, node))

    walk(plan)
    return out


def display_staged_plan(plan: ExecutionPlan) -> str:
    """ASCII stage-tree display (the reference's display_plan_ascii stage
    boxes, `stage.rs:266-355`)."""
    lines = []

    def walk(node, indent):
        marker = ""
        if getattr(node, "is_exchange", False):
            marker = f" ── stage {node.stage_id} boundary"
        lines.append("  " * indent + node.display() + marker)
        for c in node.children():
            walk(c, indent + 1)

    walk(plan, 0)
    return "\n".join(lines)


def display_staged_plan_graphviz(plan: ExecutionPlan) -> str:
    """Graphviz DOT rendering with one cluster per stage (the reference's
    display_plan_graphviz, `stage.rs:618-685`). Render with
    `dot -Tsvg plan.dot`."""
    nodes: list[str] = []
    edges: list[str] = []
    clusters: dict[int, list[str]] = {}

    def nid(node) -> str:
        return f"n{node.node_id}"

    def walk(node, stage: int) -> None:
        label = node.display().replace('"', "'")
        this_stage = stage
        if getattr(node, "is_exchange", False) and node.stage_id is not None:
            this_stage = node.stage_id
            nodes.append(
                f'  {nid(node)} [label="{label}", shape=cds, '
                'style=filled, fillcolor=lightsteelblue];'
            )
        else:
            clusters.setdefault(stage, []).append(
                f'    {nid(node)} [label="{label}", shape=box];'
            )
        for c in node.children():
            child_stage = this_stage
            if getattr(node, "is_exchange", False):
                # an exchange's child opens its producer stage
                child_stage = (
                    node.stage_id if node.stage_id is not None else stage
                )
            walk(c, child_stage)
            edges.append(f"  {nid(c)} -> {nid(node)};")

    walk(plan, -1)
    out = ["digraph staged_plan {", "  rankdir=BT;"]
    out.extend(nodes)
    for stage, members in sorted(clusters.items()):
        name = "root" if stage == -1 else f"stage_{stage}"
        out.append(f"  subgraph cluster_{name.replace('-', 'm')} {{")
        out.append(f'    label="{name}";')
        out.extend(members)
        out.append("  }")
    out.extend(edges)
    out.append("}")
    return "\n".join(out)
