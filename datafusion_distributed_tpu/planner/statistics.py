"""Cost model: symbolic per-operator complexity evaluated against statistics.

The reference's `src/distributed_planner/statistics/` builds symbolic
complexity expressions per operator (Constant/Linear/Log/Plus/Multiply,
`complexity.rs:3-33`), evaluates them against plan statistics into a
`Cost{cpu, memory, network}` in bytes (`cost.rs`), with Trino-style
per-datatype width estimates (`default_bytes_for_datatype.rs`). The adaptive
planner sizes stage task counts from that cost (`prepare_dynamic_plan.rs`).

Same architecture here, adapted to the TPU operator set: the CPU dimension
becomes "device work" (rows processed through fused kernels), memory is
padded HBM bytes (capacity-based, matching our static-shape model), and
network is ICI/DCN bytes crossing exchanges (broadcast multiplies by the
consumer task count exactly like `complexity_network.rs`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from datafusion_distributed_tpu.plan.exchanges import (
    BroadcastExchangeExec,
    CoalesceExchangeExec,
    PartitionReplicatedExec,
    ShuffleExchangeExec,
)
from datafusion_distributed_tpu.plan.joins import (
    CrossJoinExec,
    HashJoinExec,
    MultiwayHashJoinExec,
    UnionExec,
)
from datafusion_distributed_tpu.plan.physical import (
    ExecutionPlan,
    FilterExec,
    HashAggregateExec,
    LimitExec,
    MemoryScanExec,
    ParquetScanExec,
    ProjectionExec,
    SortExec,
)
from datafusion_distributed_tpu.schema import DataType, Schema


# Trino-style per-datatype byte widths (default_bytes_for_datatype.rs)
_BYTES = {
    DataType.INT32: 4,
    DataType.INT64: 8,
    DataType.FLOAT32: 4,
    DataType.FLOAT64: 8,
    DataType.BOOL: 1,
    DataType.DATE32: 4,
    DataType.STRING: 16,  # dictionary code + amortized dictionary share
}


def row_width(schema: Schema) -> int:
    return sum(_BYTES[f.dtype] + (1 if f.nullable else 0) for f in schema.fields)


@dataclass
class Complexity:
    """Symbolic complexity: cost = constant + linear*n + nlogn*n*log2(n)."""

    constant: float = 0.0
    linear: float = 0.0
    nlogn: float = 0.0

    def evaluate(self, n: float) -> float:
        import math

        logn = math.log2(max(n, 2.0))
        return self.constant + self.linear * n + self.nlogn * n * logn

    def __add__(self, other: "Complexity") -> "Complexity":
        return Complexity(
            self.constant + other.constant,
            self.linear + other.linear,
            self.nlogn + other.nlogn,
        )


@dataclass
class Cost:
    """Device work / HBM / interconnect, all in bytes (cost.rs analogue)."""

    compute: float = 0.0
    memory: float = 0.0
    network: float = 0.0

    def __add__(self, other: "Cost") -> "Cost":
        return Cost(
            self.compute + other.compute,
            self.memory + other.memory,
            self.network + other.network,
        )


@dataclass
class PlanStatistics:
    """Estimated (or sampled) row counts per node, keyed by node_id; the
    runtime-statistics attachment point for the adaptive planner."""

    rows: dict  # node_id -> float estimated rows

    def rows_of(self, node: ExecutionPlan, default: float) -> float:
        return self.rows.get(node.node_id, default)


def estimate_rows(plan: ExecutionPlan, stats: Optional[PlanStatistics] = None) -> float:
    """Bottom-up cardinality estimate (CardinalityEffect analogue: filters
    shrink, joins keep the probe side, aggregates dedupe). Planner-stamped
    NDV statistics (`ExecutionPlan.est_rows` / `.est_selectivity`, from the
    catalog's sampled NDV — the same statistics that drive join/agg hash
    sizing) take precedence over the blanket heuristics."""
    if stats is not None and plan.node_id in stats.rows:
        return stats.rows[plan.node_id]
    if isinstance(plan, (MemoryScanExec,)):
        return float(sum(int(t.num_rows) for t in plan.tasks))
    if isinstance(plan, ParquetScanExec):
        return float(plan.capacity)
    if isinstance(plan, FilterExec):
        n = estimate_rows(plan.child, stats)
        sel = plan.est_selectivity
        return n * sel if sel is not None else n / 3.0
    if isinstance(plan, (ProjectionExec, LimitExec)):
        child = plan.children()[0]
        n = estimate_rows(child, stats)
        if isinstance(plan, LimitExec):
            return min(n, float(plan.fetch))
        return n
    if isinstance(plan, HashAggregateExec):
        n = estimate_rows(plan.child, stats)
        if not plan.group_names:
            return 1.0
        if plan.est_rows is not None:
            return max(min(plan.est_rows, n), 1.0)
        return max(n ** 0.5, 1.0)
    if isinstance(plan, HashJoinExec):
        p = estimate_rows(plan.probe, stats)
        if plan.join_type in ("semi", "anti"):
            return p / 2.0
        # expanding joins (many-to-many keys) emit more than probe rows;
        # the planner's expansion_factor is the sizing hint for exactly
        # that fanout — ignoring it here would systematically undercut
        # row-estimate-capped hash sizing above such joins
        return p * max(float(getattr(plan, "expansion_factor", 1.0)), 1.0)
    if isinstance(plan, MultiwayHashJoinExec):
        p = estimate_rows(plan.probe, stats)
        for s in plan.steps:
            if s.join_type in ("semi", "anti"):
                p = p / 2.0
            else:
                p = p * max(float(s.expansion_factor), 1.0)
        return p
    if isinstance(plan, CrossJoinExec):
        return estimate_rows(plan.left, stats) * estimate_rows(plan.right, stats)
    if isinstance(plan, UnionExec):
        return sum(estimate_rows(c, stats) for c in plan.children())
    if isinstance(plan, SortExec):
        n = estimate_rows(plan.child, stats)
        return min(n, float(plan.fetch)) if plan.fetch else n
    if plan.children():
        return max(estimate_rows(c, stats) for c in plan.children())
    return 1000.0


def operator_complexity(plan: ExecutionPlan) -> Complexity:
    """Per-operator symbolic device-work model in terms of OUTPUT rows
    (complexity_cpu.rs analogue for the single-input shape). Multi-input
    operators (joins) get their exact input-row shapes in
    `operator_compute_rows` — this single-n view remains for callers that
    only carry one cardinality."""
    if isinstance(plan, (MemoryScanExec, ParquetScanExec)):
        return Complexity(linear=1.0)
    if isinstance(plan, (FilterExec, ProjectionExec, LimitExec)):
        return Complexity(linear=1.0)
    if isinstance(plan, HashAggregateExec):
        return Complexity(linear=3.0)  # hash + claim rounds + scatter
    if isinstance(plan, HashJoinExec):
        return Complexity(linear=4.0)  # build + probe + expand + gather
    if isinstance(plan, CrossJoinExec):
        return Complexity(linear=8.0)
    if isinstance(plan, SortExec):
        return Complexity(nlogn=1.0)
    return Complexity(linear=1.0)


def operator_compute_rows(
    plan: ExecutionPlan, stats: Optional[PlanStatistics] = None
) -> float:
    """Row-ops this operator performs, shaped per the reference's per-op
    CPU model (`complexity_cpu.rs:5-20` cites the DataFusion internals the
    shapes come from):

      hash join   O(n_build + n_probe)   build pass + probe pass
      NLJ/cross   O(n_left * n_right)    every pair compared
      hash agg    rounds * n             claim-loop rounds over the input
      sort        n log2 n               bitonic/radix device sort
      window      n log2 n               partition sort dominates
      elementwise n                      filter/project/limit/scan
    """
    import math

    if isinstance(plan, HashJoinExec):
        b = estimate_rows(plan.build, stats)
        p = estimate_rows(plan.probe, stats)
        return b + p
    if isinstance(plan, MultiwayHashJoinExec):
        # one row-stream pass resolves every table: probe once + K builds
        p = estimate_rows(plan.probe, stats)
        return p + sum(estimate_rows(b, stats) for b in plan.builds)
    if isinstance(plan, CrossJoinExec):
        return (estimate_rows(plan.left, stats)
                * estimate_rows(plan.right, stats))
    if isinstance(plan, HashAggregateExec):
        n = estimate_rows(plan.child, stats)
        # claim-loop rounds grow with load factor: ~3 passes in the
        # steady state (hash, claim, scatter) — see ops/aggregate.py
        return 3.0 * n
    if isinstance(plan, SortExec):
        n = estimate_rows(plan.child, stats)
        return n * math.log2(max(n, 2.0))
    from datafusion_distributed_tpu.plan.window_exec import WindowExec

    if isinstance(plan, WindowExec):
        n = estimate_rows(plan.child, stats)
        return n * math.log2(max(n, 2.0))
    if isinstance(plan, UnionExec):
        return sum(estimate_rows(c, stats) for c in plan.children())
    if plan.children():
        return max(estimate_rows(c, stats) for c in plan.children())
    return estimate_rows(plan, stats)


def calculate_cost(
    plan: ExecutionPlan, stats: Optional[PlanStatistics] = None
) -> Cost:
    """Total cost of a (sub)plan: the `calculate_cost` entry point
    (cost.rs:27) — compute from the per-op input-row shapes
    (operator_compute_rows), memory from padded HBM capacities, network
    from exchange bytes; broadcast multiplies by consumer task count
    (complexity_network.rs:2-22)."""
    total = Cost()
    for c in plan.children():
        total = total + calculate_cost(c, stats)
    n = estimate_rows(plan, stats)
    width = row_width(plan.schema())
    work = operator_compute_rows(plan, stats) * width
    mem = float(plan.output_capacity()) * width
    net = 0.0
    if isinstance(plan, ShuffleExchangeExec):
        net = n * width
    elif isinstance(plan, BroadcastExchangeExec):
        net = n * width * plan.num_tasks
    elif isinstance(plan, (CoalesceExchangeExec,)):
        net = n * width * plan.num_tasks  # all_gather implementation
    elif isinstance(plan, PartitionReplicatedExec):
        net = 0.0
    return total + Cost(compute=work, memory=mem, network=net)


def stage_cost(
    head: ExecutionPlan, stats: Optional[PlanStatistics] = None
) -> Cost:
    """Cost of ONE stage: the subtree under ``head`` truncated at exchange
    boundaries — nodes below a boundary belong to producer stages and were
    already paid for (the per-stage cost of
    `prepare_dynamic_plan.rs:40-59`). The boundary's own network
    contribution is included; attach measured runtime rows for boundary
    nodes via ``stats`` (LoadInfo -> statistics, `:111-141`)."""
    total = Cost()

    def node_cost(node: ExecutionPlan) -> Cost:
        n = estimate_rows(node, stats)
        width = row_width(node.schema())
        work = operator_compute_rows(node, stats) * width
        try:
            mem = float(node.output_capacity()) * width
        except Exception:
            mem = n * width
        net = 0.0
        if isinstance(node, ShuffleExchangeExec):
            net = n * width
        elif isinstance(node, BroadcastExchangeExec):
            net = n * width * node.num_tasks
        elif isinstance(node, CoalesceExchangeExec):
            net = n * width * node.num_tasks
        return Cost(compute=work, memory=mem, network=net)

    def walk(node: ExecutionPlan) -> None:
        nonlocal total
        total = total + node_cost(node)
        if getattr(node, "is_exchange", False) and node is not head:
            return  # producer stage: costed when ITS stage was decided
        for c in node.children():
            walk(c)

    walk(head)
    return total


def compute_based_task_count(
    cost: Cost,
    bytes_per_task_per_second: float,
    max_tasks: int,
    target_seconds: float = 1.0,
) -> int:
    """Adaptive task sizing (prepare_dynamic_plan.rs:60-69 analogue):
    tasks = ceil(compute_bytes / bytes_per_task_per_second / target) clamped
    to [1, max_tasks]."""
    import math

    t = math.ceil(cost.compute / max(bytes_per_task_per_second, 1.0) / target_seconds)
    return max(1, min(t, max_tasks))


@dataclass
class ExchangeReduction:
    """Predicted effect of aggregating BELOW an exchange instead of above
    it, from sampled key-distribution statistics (the decision input of
    the partial-aggregate push-down — *Chasing Similarity*'s
    distribution-aware aggregation placement)."""

    rows_in: float  # raw rows that would cross without the push-down
    rows_out: float  # partial-state rows that cross with it
    rows_per_task: float  # expected distinct groups per producer task
    reduction: float  # 1 - rows_out/rows_in (0 = no win, ->1 = collapse)


def expected_distinct(n: float, ndv: float) -> float:
    """Expected number of DISTINCT values observed in ``n`` draws from a
    uniform domain of ``ndv`` values: ndv * (1 - (1 - 1/ndv)^n) — the
    standard coupon-collector partial-coverage estimate. This is what
    makes the push-down *distribution-aware*: a producer task holding
    rows/t raw rows emits at most this many partial groups, so low-NDV
    keys collapse (q1's 4 groups) while high-NDV keys barely shrink and
    the push-down is skipped (pure compute overhead)."""
    import math

    n = max(float(n), 0.0)
    ndv = max(float(ndv), 1.0)
    if n <= 0:
        return 0.0
    # log-space for numerical stability at large n/ndv
    return ndv * -math.expm1(n * math.log1p(-1.0 / ndv)) if ndv > 1 \
        else 1.0


def predict_partial_agg_reduction(
    rows_in: float, ndv: float, t_producer: int
) -> ExchangeReduction:
    """Rows crossing a shuffle with vs without a pre-exchange partial
    aggregate: each of ``t_producer`` tasks holds ~rows_in/t raw rows and
    emits `expected_distinct(rows_in/t, ndv)` partial states. The NDV
    comes from the catalog's sampled statistics (the `est_rows` the
    planner stamps on aggregates) — the same NDV samples that size hash
    tables."""
    t = max(int(t_producer), 1)
    rows_in = max(float(rows_in), 0.0)
    per_task = expected_distinct(rows_in / t, ndv)
    rows_out = min(per_task * t, rows_in)
    reduction = 1.0 - (rows_out / rows_in) if rows_in > 0 else 0.0
    return ExchangeReduction(
        rows_in=rows_in, rows_out=rows_out, rows_per_task=per_task,
        reduction=max(reduction, 0.0),
    )


def multiway_build_bytes(builds) -> int:
    """Padded byte footprint of a fused join chain's build sides — they are
    ALL resident in one stage's program at once (the cost the binary chain
    amortizes across stages), so the fusion pass gates on their sum against
    DistributedConfig.multiway_build_bytes_max."""
    total = 0
    for b in builds:
        try:
            w = row_width(b.schema())
        except Exception:
            w = 8
        try:
            cap = int(b.output_capacity())
        except Exception:
            cap = 0
        total += cap * max(w, 1)
    return total


def multiway_fusion_allowed(builds, max_bytes: int) -> bool:
    """Statistics gate for the multiway fusion pass: every build side must
    carry a usable size AND their combined resident footprint must fit the
    configured budget. (Per-step NDV bounds ride on each step's captured
    num_slots, checked by the verifier's DFTPU025 pass.)"""
    if not builds:
        return False
    return multiway_build_bytes(builds) <= max_bytes


def choose_probe_order(builds, stats: Optional[PlanStatistics] = None):
    """Estimated probe order for a fused chain: most selective (smallest
    estimated build) first, the classic multiway-join heuristic. Returned
    as a tuple of step indices; the planner stamps it as the
    ``probe_order_hint`` annotation ONLY — actually reordering steps would
    permute the fused stage's output columns, which is illegal without a
    restoring projection."""
    est = [(estimate_rows(b, stats), i) for i, b in enumerate(builds)]
    return tuple(i for _, i in sorted(est, key=lambda t: (t[0], t[1])))


def plan_device_bytes(plan) -> int:
    """Coarse upper bound on one program's device-buffer footprint:
    sum over nodes of output_capacity * row_width. Used by the
    overflow-retry guard: each retry widens capacity factors 4x, and a
    few compounding retries can plan buffers beyond physical memory —
    the guard abandons the retry with a clear overflow error instead of
    letting dispatch fail with an opaque allocator error (observed: q2
    SF0.5 adaptive tier, ~100GB planned after two widenings)."""
    total = 0
    for node in plan.collect(lambda _n: True):
        try:
            w = row_width(node.schema())
        except Exception:
            w = 8
        try:
            cap = int(node.output_capacity())
        except Exception:
            cap = 0
        total += cap * max(w, 1)
    return total
