"""Adaptive (dynamic) planning: size stages from runtime statistics.

The reference's `dynamic_task_count` mode re-runs boundary injection during
execution: each stage ships immediately, `SamplerExec` streams LoadInfo
(rows/bytes ready + velocity, NDV%, null%) back to the coordinator, and the
next stage's task count comes from the cost model over those sampled stats
(`/root/reference/src/coordinator/prepare_dynamic_plan.rs`,
`src/execution_plans/sampler.rs`).

TPU adaptation: the host-runtime coordinator materializes stage outputs
between meshes anyway, so runtime statistics are EXACT there — after a
producer stage lands, the consumer subtree's capacities (hash slots, join
fan-out, shuffle buckets) are re-sized from the observed LoadInfo before it
executes. That converts the static path's overflow-retry into a single
forward pass (pending -> ready with real statistics), and shrinks padded
capacities, which is pure device-time savings. `SamplerExec` still exists
for the in-mesh path, recording rows/bytes as traced metrics.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from datafusion_distributed_tpu.ops.table import Table, round_up_pow2
from datafusion_distributed_tpu.plan.exchanges import ShuffleExchangeExec
from datafusion_distributed_tpu.plan.joins import HashJoinExec
from datafusion_distributed_tpu.plan.physical import (
    ExecContext,
    ExecutionPlan,
    HashAggregateExec,
    MemoryScanExec,
)
from datafusion_distributed_tpu.planner.statistics import row_width


@dataclass
class LoadInfo:
    """Observed stage-output statistics (the worker.proto LoadInfo analogue:
    rows/bytes ready plus per-column NDV and null fractions, and the
    rows/bytes-per-second velocity the reference's SamplerExec streams,
    `sampler.rs:30-42`)."""

    rows: int
    bytes: int
    ndv: dict = field(default_factory=dict)  # column -> distinct estimate
    null_frac: dict = field(default_factory=dict)  # column -> null fraction
    rows_per_s: float = 0.0
    bytes_per_s: float = 0.0
    #: producer-coverage extrapolation factor for PARTIAL-sample freezes
    #: (total/done). Applied once to the group-key TUPLE product in
    #: resize_for_inputs — per-column application would compound it.
    ndv_scale: float = 1.0


class ColumnStreamSampler:
    """Incremental per-column NDV/null sampler over IN-FLIGHT stage output
    (chunks on the streaming plane, task outputs on the bulk plane) — the
    mid-stage half of the reference's SamplerExec: statistics exist while
    the stage is still producing, so the consumer's sizing decision
    (partial-sample freeze) can use real column shapes instead of
    post-materialization measurement."""

    def __init__(self, sample_limit: int = 100_000):
        import time

        self.sample_limit = sample_limit
        self.seen: dict = {}
        self.nulls: dict = {}
        self.sampled = 0
        self.rows = 0
        self._t0 = time.perf_counter()

    def observe(self, table: Table) -> None:
        from datafusion_distributed_tpu.schema import DataType

        n = int(table.num_rows)
        self.rows += n
        if self.sampled >= self.sample_limit or n == 0:
            return
        take = min(n, self.sample_limit - self.sampled)
        for name, col in zip(table.names, table.columns):
            vals = np.asarray(col.data[:take])
            if col.validity is not None:
                mask = np.asarray(col.validity[:take])
                self.nulls[name] = self.nulls.get(name, 0) + int(
                    (~mask).sum()
                )
                vals = vals[mask]
            s = self.seen.setdefault(name, set())
            if col.dtype == DataType.STRING and col.dictionary is not None:
                # distinct VALUES, not dictionary codes: in-flight chunks
                # from different producers carry different dictionaries
                # (unified only later, at concat) — their code spaces
                # overlap, and a code-based union would under-count NDV
                # badly enough to size consumers into guaranteed overflow
                decoded = col.dictionary.decode(vals.astype(np.int64))
                s.update(v for v in decoded.tolist() if v is not None)
            else:
                s.update(np.unique(vals).tolist())
        self.sampled += take

    def load_info(self, rows: int, width: int,
                  ndv_scale: float = 1.0) -> LoadInfo:
        """``ndv_scale`` records the producer-coverage factor (total/done)
        of a PARTIAL-sample freeze. Observed per-column NDVs stay RAW; the
        scale is applied ONCE to the group-key TUPLE estimate by
        resize_for_inputs — shuffle outputs are hash-partitioned by that
        tuple, so unseen producers contribute DISJOINT tuples and the
        observed count understates the total by the coverage factor
        (q11 at SF0.1: 815 distinct seen in 2/8 producers vs 3,940 true —
        2048 slots sized from the raw count overflowed on every retry).
        Scaling each column independently would compound the factor across
        multi-key groups (coverage^n_keys) and inflate non-key columns."""
        import time

        elapsed = max(time.perf_counter() - self._t0, 1e-9)
        return LoadInfo(
            rows=rows,
            bytes=rows * width,
            ndv={k: len(v) for k, v in self.seen.items()},
            null_frac={
                k: self.nulls.get(k, 0) / max(self.sampled, 1)
                for k in self.seen
            },
            rows_per_s=self.rows / elapsed,
            bytes_per_s=self.rows * width / elapsed,
            ndv_scale=max(ndv_scale, 1.0),
        )


def collect_load_info(tables: list[Table], sample_limit: int = 100_000) -> LoadInfo:
    """Exact rows/bytes; NDV/null%% from a bounded sample (the reference
    samples 20%% and short-circuits, `prepare_dynamic_plan.rs:206-331`).
    One sampling implementation serves both the post-materialization path
    (here) and the mid-stream path (`ColumnStreamSampler` fed by in-flight
    chunks)."""
    rows = sum(int(t.num_rows) for t in tables)
    if not tables:
        return LoadInfo(0, 0)
    width = row_width(tables[0].schema())
    sampler = ColumnStreamSampler(sample_limit)
    for t in tables:
        sampler.observe(t)
    return sampler.load_info(rows, width)


class SamplerExec(ExecutionPlan):
    """Pass-through that records rows/bytes as traced metrics at a stage head
    (the in-mesh stand-in for the reference's batch-peeking SamplerExec)."""

    def __init__(self, child: ExecutionPlan):
        super().__init__()
        self.child = child

    def children(self):
        return [self.child]

    def with_new_children(self, children):
        return SamplerExec(children[0])

    def schema(self):
        return self.child.schema()

    def output_capacity(self):
        return self.child.output_capacity()

    def _execute(self, ctx: ExecContext) -> Table:
        t = self.child.execute(ctx)
        ctx.record_metric(self, "sampled_rows", t.num_rows)
        ctx.record_metric(
            self, "sampled_bytes", t.num_rows * row_width(t.schema())
        )
        return t

    def display(self):
        return "Sampler"


def resize_for_inputs(
    plan: ExecutionPlan,
    input_info: LoadInfo,
    skew_headroom: float = 2.0,
) -> ExecutionPlan:
    """Re-size capacity knobs of a consumer stage given its actual input
    statistics (the adaptive `inject_network_boundaries`-with-real-stats
    analogue). Only nodes BELOW the next exchange boundary are touched."""

    def walk(node: ExecutionPlan) -> ExecutionPlan:
        if getattr(node, "is_exchange", False):
            return node  # next stage's problem
        children = [walk(c) for c in node.children()]
        node = node.with_new_children(children) if children else node
        if isinstance(node, HashAggregateExec) and node.group_names:
            # NDV of a derived/renamed group column isn't in the LoadInfo;
            # the exact input row count is always a safe upper bound
            ndv = 1
            for g in node.group_names:
                ndv *= max(
                    input_info.ndv.get(g, max(input_info.rows, 1)), 1
                )
            # partial-sample freezes undercount the group tuple by the
            # producer-coverage factor (hash-partitioned on this tuple →
            # disjoint across producers); applied ONCE here, not per column
            ndv *= max(getattr(input_info, "ndv_scale", 1.0), 1.0)
            ndv = min(int(ndv), max(input_info.rows, 1))
            node = HashAggregateExec(
                node.mode, node.group_names, node.aggs, node.child,
                num_slots=round_up_pow2(
                    max(int(ndv * skew_headroom), 16)
                ),
            )
        elif isinstance(node, HashJoinExec):
            # honor the node's expansion_factor: it encodes the planner's
            # fanout knowledge AND the overflow-retry's 4x widening — with
            # a bare skew_headroom the retry loop replans wider and this
            # resize immediately shrinks back to the same overflowing
            # capacity (observed: q95's order-number self-join never
            # converged in adaptive mode)
            from datafusion_distributed_tpu.plan.joins import (
                _MAX_DERIVED_JOIN_CAPACITY,
            )

            grow = max(skew_headroom, node.expansion_factor)
            # same derived-capacity ceiling as the constructor: widened
            # retry factors must not demand terabyte buffers
            ceiling = max(
                _MAX_DERIVED_JOIN_CAPACITY,
                round_up_pow2(max(int(input_info.rows), 8)),
            )
            node = HashJoinExec(
                node.probe, node.build, node.probe_keys, node.build_keys,
                node.join_type, node.residual,
                out_capacity=min(round_up_pow2(
                    max(int(input_info.rows * grow), 16)
                ), ceiling),
                num_slots=node.num_slots,
                mark_name=node.mark_name,
                expansion_factor=node.expansion_factor,
                null_aware=node.null_aware,
            )
        return node

    return walk(plan)


def insert_samplers(plan: ExecutionPlan) -> ExecutionPlan:
    """Put a SamplerExec directly under every exchange boundary (the
    reference inserts them at stage heads, `network_boundary.rs
    insert_sampler`)."""

    def walk(node: ExecutionPlan) -> ExecutionPlan:
        children = [walk(c) for c in node.children()]
        node = node.with_new_children(children) if children else node
        if getattr(node, "is_exchange", False):
            inner = node.children()[0]
            if not isinstance(inner, SamplerExec):
                node = node.with_new_children([SamplerExec(inner)])
        return node

    return walk(plan)
