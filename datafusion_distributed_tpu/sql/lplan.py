"""Logical plan nodes + catalog protocol (split out of logical.py).

The reference gets its logical plan types from DataFusion (SURVEY.md L0);
these are the original TPU-build equivalents. See `sql/logical.py` for the
binder that produces them.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Optional

from datafusion_distributed_tpu.ops.aggregate import _VARIANCE_FUNCS
from datafusion_distributed_tpu.plan import expressions as pe
from datafusion_distributed_tpu.schema import DataType, Field, Schema


# ---------------------------------------------------------------------------
# Logical nodes
# ---------------------------------------------------------------------------


class LogicalPlan:
    def schema(self) -> Schema:
        raise NotImplementedError

    def children(self) -> list["LogicalPlan"]:
        raise NotImplementedError

    def display_tree(self, indent=0) -> str:
        lines = ["  " * indent + self.display()]
        for c in self.children():
            lines.append(c.display_tree(indent + 1))
        return "\n".join(lines)

    def display(self) -> str:
        return type(self).__name__


@dataclass
class LScan(LogicalPlan):
    table: str
    alias: str
    table_schema: Schema  # original column names
    flat_schema: Schema  # alias.column names

    def schema(self):
        return self.flat_schema

    def children(self):
        return []

    def display(self):
        return f"Scan {self.table} AS {self.alias}"


@dataclass
class LFilter(LogicalPlan):
    predicate: pe.PhysicalExpr
    child: LogicalPlan

    def schema(self):
        return self.child.schema()

    def children(self):
        return [self.child]

    def display(self):
        return f"Filter {self.predicate.display()}"


@dataclass
class LProject(LogicalPlan):
    exprs: list  # [(PhysicalExpr, out_name)]
    child: LogicalPlan

    def schema(self):
        cs = self.child.schema()
        return Schema(
            [Field(n, e.output_field(cs).dtype, e.output_field(cs).nullable)
             for e, n in self.exprs]
        )

    def children(self):
        return [self.child]

    def display(self):
        return "Project " + ", ".join(n for _, n in self.exprs)


@dataclass
class AggCall:
    func: str  # sum|count|count_star|min|max|avg
    arg: Optional[pe.PhysicalExpr]
    name: str
    distinct: bool = False


@dataclass
class LAggregate(LogicalPlan):
    groups: list  # [(PhysicalExpr, name)]
    aggs: list  # [AggCall]
    child: LogicalPlan

    def schema(self):
        cs = self.child.schema()
        fields = []
        for e, n in self.groups:
            f = e.output_field(cs)
            fields.append(Field(n, f.dtype, f.nullable))
        for a in self.aggs:
            fields.append(Field(a.name, _agg_dtype(a, cs), True))
        return Schema(fields)

    def children(self):
        return [self.child]

    def display(self):
        gs = ", ".join(n for _, n in self.groups)
        as_ = ", ".join(f"{a.func}({a.arg.display() if a.arg else '*'})"
                        for a in self.aggs)
        return f"Aggregate gby=[{gs}] aggs=[{as_}]"


def _agg_dtype(a: AggCall, cs: Schema) -> DataType:
    if a.func in ("count", "count_star"):
        return DataType.INT64
    if a.func == "avg" or a.func in _VARIANCE_FUNCS:
        return DataType.FLOAT64
    f = a.arg.output_field(cs)
    if a.func == "sum":
        return DataType.FLOAT64 if f.dtype.is_float else DataType.INT64
    return f.dtype


@dataclass
class LJoin(LogicalPlan):
    left: LogicalPlan
    right: LogicalPlan
    how: str  # inner|left|semi|anti|mark|cross
    left_keys: list  # [PhysicalExpr]
    right_keys: list
    residual: Optional[pe.PhysicalExpr] = None  # evaluated on joined schema
    mark_name: Optional[str] = None
    null_aware: bool = False  # NOT IN semantics for anti joins
    # estimated output rows per probe row (the join orderer's NDV-based
    # fan-out; sizes the physical join's output capacity so many-to-many
    # joins do not start at 1x and burn overflow retries)
    fanout_hint: float = 1.0

    def schema(self):
        if self.how in ("semi", "anti"):
            return self.left.schema()
        if self.how == "mark":
            return Schema(
                list(self.left.schema().fields)
                + [Field(self.mark_name or "__mark", DataType.BOOL, False)]
            )
        left = self.left.schema().fields
        right = [
            Field(f.name, f.dtype, True if self.how == "left" else f.nullable)
            for f in self.right.schema().fields
        ]
        return Schema(list(left) + right)

    def children(self):
        return [self.left, self.right]

    def display(self):
        ks = ", ".join(
            f"{l.display()}={r.display()}"
            for l, r in zip(self.left_keys, self.right_keys)
        )
        res = f" residual={self.residual.display()}" if self.residual else ""
        return f"Join {self.how} on [{ks}]{res}"


@dataclass
class LWindowExpr:
    func: str  # rank|dense_rank|row_number|sum|avg|min|max|count|count_star
    arg: Optional[pe.PhysicalExpr]
    partition_by: list  # [PhysicalExpr]
    order_by: list  # [(PhysicalExpr, ascending, nulls_first|None)]
    name: str
    frame: str = "range"


@dataclass
class LWindow(LogicalPlan):
    """Window evaluation: appends one column per LWindowExpr (post-GROUP BY,
    pre-final-projection — standard SQL evaluation order)."""

    exprs: list  # [LWindowExpr]
    child: LogicalPlan

    def schema(self):
        fields = list(self.child.schema().fields)
        cs = self.child.schema()
        for w in self.exprs:
            fields.append(Field(w.name, _window_dtype(w, cs), True))
        return Schema(fields)

    def children(self):
        return [self.child]

    def display(self):
        inner = ", ".join(f"{w.func}() AS {w.name}" for w in self.exprs)
        return f"Window [{inner}]"


def _window_dtype(w: LWindowExpr, cs: Schema) -> DataType:
    from datafusion_distributed_tpu.ops.window import window_output_dtype

    input_dtype = w.arg.output_field(cs).dtype if w.arg is not None else None
    return window_output_dtype(w.func, input_dtype)


@dataclass
class LSort(LogicalPlan):
    keys: list  # [(PhysicalExpr, ascending, nulls_first|None)]
    child: LogicalPlan
    fetch: Optional[int] = None

    def schema(self):
        return self.child.schema()

    def children(self):
        return [self.child]

    def display(self):
        ks = ", ".join(
            f"{e.display()} {'ASC' if asc else 'DESC'}" for e, asc, _ in self.keys
        )
        return f"Sort [{ks}]" + (f" fetch={self.fetch}" if self.fetch else "")


@dataclass
class LLimit(LogicalPlan):
    child: LogicalPlan
    fetch: Optional[int]
    skip: int = 0

    def schema(self):
        return self.child.schema()

    def children(self):
        return [self.child]

    def display(self):
        return f"Limit fetch={self.fetch} skip={self.skip}"


@dataclass
class LDistinct(LogicalPlan):
    child: LogicalPlan

    def schema(self):
        return self.child.schema()

    def children(self):
        return [self.child]


@dataclass
class LSetOp(LogicalPlan):
    op: str  # union|intersect|except
    all: bool
    left: LogicalPlan
    right: LogicalPlan

    def schema(self):
        return self.left.schema()

    def children(self):
        return [self.left, self.right]

    def display(self):
        return f"{self.op.upper()}{' ALL' if self.all else ''}"


# ---------------------------------------------------------------------------
# Catalog protocol
# ---------------------------------------------------------------------------


class CatalogProtocol:
    """What the binder needs: schema lookup + view/CTE resolution."""

    def table_schema(self, name: str) -> Schema:
        raise NotImplementedError

    def has_table(self, name: str) -> bool:
        raise NotImplementedError

    def table_rows(self, name: str) -> int:
        """Row-count estimate for join ordering; override when known."""
        return 1000

    def column_ndv(self, table: str, column: str) -> Optional[int]:
        """Distinct-count estimate for a column (join fan-out estimation);
        None when unknown."""
        return None
