"""Logical plan + binder (AST -> resolved logical tree).

The reference gets logical planning from DataFusion (SURVEY.md L0). This is an
original binder covering what the TPC suites need:

- name resolution over qualified scopes (columns get flat names
  ``alias.column`` so self-joins like TPC-H q21's lineitem l1/l2/l3 stay
  unambiguous all the way into the physical Table),
- implicit comma joins: WHERE conjuncts are classified into single-relation
  filters (pushed down), equi-join edges (drive a greedy left-deep join
  order), and residual post-join filters,
- aggregate extraction (SELECT/HAVING/ORDER BY aggregate calls become
  LAggregate outputs; COUNT(DISTINCT x) rewrites to a two-level aggregate),
- subquery handling: uncorrelated scalar subqueries become lazily-executed
  scalar expressions; correlated scalar-aggregate subqueries decorrelate into
  GROUP BY + LEFT JOIN (TPC-H q2/q17/q20 shape); [NOT] EXISTS and [NOT] IN
  become semi/anti joins with optional residual predicates (q4/q21/q22).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field as dc_field
from typing import Any, Callable, Optional, Sequence

from datafusion_distributed_tpu.plan import expressions as pe
from datafusion_distributed_tpu.schema import DataType, Field, Schema
from datafusion_distributed_tpu.sql import parser as ast

# mark-join column namer: process-wide so two filters in one query can't
# collide, resettable (like planner._TMP) so plan snapshots are reproducible
_MARK_SEQ = itertools.count()


# ---------------------------------------------------------------------------
# Logical nodes
# ---------------------------------------------------------------------------


class LogicalPlan:
    def schema(self) -> Schema:
        raise NotImplementedError

    def children(self) -> list["LogicalPlan"]:
        raise NotImplementedError

    def display_tree(self, indent=0) -> str:
        lines = ["  " * indent + self.display()]
        for c in self.children():
            lines.append(c.display_tree(indent + 1))
        return "\n".join(lines)

    def display(self) -> str:
        return type(self).__name__


@dataclass
class LScan(LogicalPlan):
    table: str
    alias: str
    table_schema: Schema  # original column names
    flat_schema: Schema  # alias.column names

    def schema(self):
        return self.flat_schema

    def children(self):
        return []

    def display(self):
        return f"Scan {self.table} AS {self.alias}"


@dataclass
class LFilter(LogicalPlan):
    predicate: pe.PhysicalExpr
    child: LogicalPlan

    def schema(self):
        return self.child.schema()

    def children(self):
        return [self.child]

    def display(self):
        return f"Filter {self.predicate.display()}"


@dataclass
class LProject(LogicalPlan):
    exprs: list  # [(PhysicalExpr, out_name)]
    child: LogicalPlan

    def schema(self):
        cs = self.child.schema()
        return Schema(
            [Field(n, e.output_field(cs).dtype, e.output_field(cs).nullable)
             for e, n in self.exprs]
        )

    def children(self):
        return [self.child]

    def display(self):
        return "Project " + ", ".join(n for _, n in self.exprs)


@dataclass
class AggCall:
    func: str  # sum|count|count_star|min|max|avg
    arg: Optional[pe.PhysicalExpr]
    name: str
    distinct: bool = False


@dataclass
class LAggregate(LogicalPlan):
    groups: list  # [(PhysicalExpr, name)]
    aggs: list  # [AggCall]
    child: LogicalPlan

    def schema(self):
        cs = self.child.schema()
        fields = []
        for e, n in self.groups:
            f = e.output_field(cs)
            fields.append(Field(n, f.dtype, f.nullable))
        for a in self.aggs:
            fields.append(Field(a.name, _agg_dtype(a, cs), True))
        return Schema(fields)

    def children(self):
        return [self.child]

    def display(self):
        gs = ", ".join(n for _, n in self.groups)
        as_ = ", ".join(f"{a.func}({a.arg.display() if a.arg else '*'})"
                        for a in self.aggs)
        return f"Aggregate gby=[{gs}] aggs=[{as_}]"


def _agg_dtype(a: AggCall, cs: Schema) -> DataType:
    if a.func in ("count", "count_star"):
        return DataType.INT64
    if a.func == "avg" or a.func in _VARIANCE_FUNCS:
        return DataType.FLOAT64
    f = a.arg.output_field(cs)
    if a.func == "sum":
        return DataType.FLOAT64 if f.dtype.is_float else DataType.INT64
    return f.dtype


@dataclass
class LJoin(LogicalPlan):
    left: LogicalPlan
    right: LogicalPlan
    how: str  # inner|left|semi|anti|mark|cross
    left_keys: list  # [PhysicalExpr]
    right_keys: list
    residual: Optional[pe.PhysicalExpr] = None  # evaluated on joined schema
    mark_name: Optional[str] = None
    null_aware: bool = False  # NOT IN semantics for anti joins
    # estimated output rows per probe row (the join orderer's NDV-based
    # fan-out; sizes the physical join's output capacity so many-to-many
    # joins do not start at 1x and burn overflow retries)
    fanout_hint: float = 1.0

    def schema(self):
        if self.how in ("semi", "anti"):
            return self.left.schema()
        if self.how == "mark":
            return Schema(
                list(self.left.schema().fields)
                + [Field(self.mark_name or "__mark", DataType.BOOL, False)]
            )
        left = self.left.schema().fields
        right = [
            Field(f.name, f.dtype, True if self.how == "left" else f.nullable)
            for f in self.right.schema().fields
        ]
        return Schema(list(left) + right)

    def children(self):
        return [self.left, self.right]

    def display(self):
        ks = ", ".join(
            f"{l.display()}={r.display()}"
            for l, r in zip(self.left_keys, self.right_keys)
        )
        res = f" residual={self.residual.display()}" if self.residual else ""
        return f"Join {self.how} on [{ks}]{res}"


@dataclass
class LWindowExpr:
    func: str  # rank|dense_rank|row_number|sum|avg|min|max|count|count_star
    arg: Optional[pe.PhysicalExpr]
    partition_by: list  # [PhysicalExpr]
    order_by: list  # [(PhysicalExpr, ascending, nulls_first|None)]
    name: str
    frame: str = "range"


@dataclass
class LWindow(LogicalPlan):
    """Window evaluation: appends one column per LWindowExpr (post-GROUP BY,
    pre-final-projection — standard SQL evaluation order)."""

    exprs: list  # [LWindowExpr]
    child: LogicalPlan

    def schema(self):
        fields = list(self.child.schema().fields)
        cs = self.child.schema()
        for w in self.exprs:
            fields.append(Field(w.name, _window_dtype(w, cs), True))
        return Schema(fields)

    def children(self):
        return [self.child]

    def display(self):
        inner = ", ".join(f"{w.func}() AS {w.name}" for w in self.exprs)
        return f"Window [{inner}]"


def _window_dtype(w: LWindowExpr, cs: Schema) -> DataType:
    from datafusion_distributed_tpu.ops.window import window_output_dtype

    input_dtype = w.arg.output_field(cs).dtype if w.arg is not None else None
    return window_output_dtype(w.func, input_dtype)


@dataclass
class LSort(LogicalPlan):
    keys: list  # [(PhysicalExpr, ascending, nulls_first|None)]
    child: LogicalPlan
    fetch: Optional[int] = None

    def schema(self):
        return self.child.schema()

    def children(self):
        return [self.child]

    def display(self):
        ks = ", ".join(
            f"{e.display()} {'ASC' if asc else 'DESC'}" for e, asc, _ in self.keys
        )
        return f"Sort [{ks}]" + (f" fetch={self.fetch}" if self.fetch else "")


@dataclass
class LLimit(LogicalPlan):
    child: LogicalPlan
    fetch: Optional[int]
    skip: int = 0

    def schema(self):
        return self.child.schema()

    def children(self):
        return [self.child]

    def display(self):
        return f"Limit fetch={self.fetch} skip={self.skip}"


@dataclass
class LDistinct(LogicalPlan):
    child: LogicalPlan

    def schema(self):
        return self.child.schema()

    def children(self):
        return [self.child]


@dataclass
class LSetOp(LogicalPlan):
    op: str  # union|intersect|except
    all: bool
    left: LogicalPlan
    right: LogicalPlan

    def schema(self):
        return self.left.schema()

    def children(self):
        return [self.left, self.right]

    def display(self):
        return f"{self.op.upper()}{' ALL' if self.all else ''}"


# ---------------------------------------------------------------------------
# Catalog protocol
# ---------------------------------------------------------------------------


class CatalogProtocol:
    """What the binder needs: schema lookup + view/CTE resolution."""

    def table_schema(self, name: str) -> Schema:
        raise NotImplementedError

    def has_table(self, name: str) -> bool:
        raise NotImplementedError

    def table_rows(self, name: str) -> int:
        """Row-count estimate for join ordering; override when known."""
        return 1000

    def column_ndv(self, table: str, column: str) -> Optional[int]:
        """Distinct-count estimate for a column (join fan-out estimation);
        None when unknown."""
        return None


# ---------------------------------------------------------------------------
# Binder
# ---------------------------------------------------------------------------

_ANON = itertools.count()


class BindError(ValueError):
    pass


@dataclass
class Scope:
    """In-scope relations: [(alias, original Schema)] resolving to flat names."""

    entries: list  # [(alias, Schema)]
    parent: Optional["Scope"] = None

    def resolve(self, ident: ast.Ident) -> tuple[str, Field, int]:
        """-> (flat_name, field, depth); depth 0 = local, 1+ = outer scope."""
        depth = 0
        scope: Optional[Scope] = self
        while scope is not None:
            hits = []
            for alias, schema in scope.entries:
                if ident.qualifier is not None and ident.qualifier != alias:
                    continue
                if ident.name in schema:
                    hits.append((alias, schema.field(ident.name)))
            if len(hits) > 1:
                raise BindError(f"ambiguous column {ident.key()!r}")
            if hits:
                alias, f = hits[0]
                flat = f"{alias}.{ident.name}" if alias else ident.name
                return flat, f, depth
            scope = scope.parent
            depth += 1
        raise BindError(f"unknown column {ident.key()!r}")


@dataclass
class OuterRef:
    """Recorded reference from a subquery into an enclosing scope."""

    flat_name: str
    field: Field


class Binder:
    def __init__(self, catalog: CatalogProtocol, ctes: Optional[dict] = None):
        self.catalog = catalog
        self.ctes: dict[str, LogicalPlan] = dict(ctes or {})

    # -- public -------------------------------------------------------------
    def bind(self, q) -> LogicalPlan:
        return self._bind_query(q, parent_scope=None)

    # -- query --------------------------------------------------------------
    def _bind_query(self, q, parent_scope: Optional[Scope]) -> LogicalPlan:
        if isinstance(q, ast.SetOp):
            return self._bind_setop(q, parent_scope)
        if q.group_by and any(_is_rollup(g) for g in q.group_by):
            return self._bind_query(_expand_rollup(q), parent_scope)
        saved_ctes = dict(self.ctes)
        for name, sub in q.ctes:
            self.ctes[name] = self._bind_query(sub, parent_scope)
        try:
            return self._bind_select(q, parent_scope)
        finally:
            self.ctes = saved_ctes

    def _bind_setop(self, q: ast.SetOp, parent_scope) -> LogicalPlan:
        saved = dict(self.ctes)
        for name, sub in q.ctes:
            self.ctes[name] = self._bind_query(sub, parent_scope)
        try:
            left = self._bind_query(q.left, parent_scope)
            right = self._bind_query(q.right, parent_scope)
        finally:
            self.ctes = saved
        if len(left.schema()) != len(right.schema()):
            raise BindError("set operation arity mismatch")
        # align right's column names to left's, coercing numeric dtypes to
        # the promoted common type (SQL set-op column typing)
        rs = right.schema()
        ls = left.schema()
        right_exprs = []
        left_casts = []
        for rf, lf in zip(rs.fields, ls.fields):
            re_: pe.PhysicalExpr = pe.Col(rf.name)
            if rf.dtype != lf.dtype:
                common = pe._promote(lf.dtype, rf.dtype)
                if rf.dtype != common:
                    re_ = pe.Cast(re_, common)
                if lf.dtype != common:
                    left_casts.append((lf.name, common))
            right_exprs.append((re_, lf.name))
        right = LProject(right_exprs, right)
        if left_casts:
            need = dict(left_casts)
            left = LProject(
                [(pe.Cast(pe.Col(f.name), need[f.name])
                  if f.name in need else pe.Col(f.name), f.name)
                 for f in ls.fields],
                left,
            )
        plan: LogicalPlan = LSetOp(q.op, q.all, left, right)
        if q.op == "union" and not q.all:
            plan = LDistinct(plan)
        if q.order_by:
            scope = Scope([("", plan.schema())])
            keys = []
            for o in q.order_by:
                if isinstance(o.expr, ast.NumberLit) and isinstance(
                    o.expr.value, int
                ):
                    e: pe.PhysicalExpr = pe.Col(
                        plan.schema().fields[o.expr.value - 1].name
                    )
                else:
                    e = self._bind_expr(o.expr, scope, None)
                keys.append((e, o.ascending, o.nulls_first))
            plan = LSort(keys, plan, fetch=_sort_fetch(q))
        if q.limit is not None or q.offset is not None:
            plan = LLimit(plan, q.limit, q.offset or 0)
        return plan

    # -- FROM / joins ---------------------------------------------------------
    def _bind_relation(self, ref, parent_scope) -> tuple[LogicalPlan, str, Schema]:
        """-> (plan with flat names, alias, original-name schema)."""
        if isinstance(ref, ast.SubqueryRef):
            sub = self._bind_query(ref.query, parent_scope)
            names = [f.name.split(".")[-1] for f in sub.schema().fields]
            if ref.column_aliases:
                if len(ref.column_aliases) != len(names):
                    raise BindError("derived table column alias arity mismatch")
                names = list(ref.column_aliases)
            orig = Schema(
                [Field(n, f.dtype, f.nullable)
                 for n, f in zip(names, sub.schema().fields)]
            )
            flat = LProject(
                [(pe.Col(f.name), f"{ref.alias}.{n}")
                 for n, f in zip(names, sub.schema().fields)],
                sub,
            )
            return flat, ref.alias, orig
        assert isinstance(ref, ast.TableRef)
        alias = ref.alias or ref.name
        if ref.name in self.ctes:
            sub = self.ctes[ref.name]
            names = [f.name.split(".")[-1] for f in sub.schema().fields]
            orig = Schema(
                [Field(n, f.dtype, f.nullable)
                 for n, f in zip(names, sub.schema().fields)]
            )
            flat = LProject(
                [(pe.Col(f.name), f"{alias}.{n}")
                 for n, f in zip(names, sub.schema().fields)],
                sub,
            )
            return flat, alias, orig
        if not self.catalog.has_table(ref.name):
            raise BindError(f"unknown table {ref.name!r}")
        schema = self.catalog.table_schema(ref.name)
        flat_schema = Schema(
            [Field(f"{alias}.{f.name}", f.dtype, f.nullable) for f in schema.fields]
        )
        return LScan(ref.name, alias, schema, flat_schema), alias, schema

    # -- SELECT ---------------------------------------------------------------
    def _bind_select(self, q: ast.Query, parent_scope) -> LogicalPlan:
        # 1. relations. A from_ref group with outer joins is folded in its
        # written order into a single "unit" (outer joins are not freely
        # reorderable); inner/cross-only groups flatten into the greedy pool.
        relations: list[tuple[LogicalPlan, str, Schema]] = []  # (plan, alias, orig)
        groups: list = []  # ("rel", alias) | ("outer", base_alias, [(jc, ralias)])
        inner_on_conjuncts: list = []
        if not q.from_refs:
            raise BindError("SELECT without FROM is not supported yet")
        protected: set = set()  # null-supplying sides: no WHERE pushdown
        for base, joins in q.from_refs:
            triple = self._bind_relation(base, parent_scope)
            relations.append(triple)
            if not joins:
                groups.append(("rel", triple[1]))
                continue
            kinds = {jc.kind for jc in joins}
            rtriples = []
            for jc in joins:
                rt = self._bind_relation(jc.right, parent_scope)
                relations.append(rt)
                rtriples.append(rt)
            if kinds <= {"inner", "cross"}:
                groups.append(("rel", triple[1]))
                for jc, rt in zip(joins, rtriples):
                    groups.append(("rel", rt[1]))
                    if jc.on is not None:
                        inner_on_conjuncts.extend(_split_conjuncts(jc.on))
            else:
                groups.append(
                    ("outer", triple[1], list(zip(joins, [t[1] for t in rtriples])))
                )
                for jc, rt in zip(joins, rtriples):
                    if jc.kind == "left":
                        protected.add(rt[1])
                    elif jc.kind == "right":
                        protected.add(triple[1])
                    elif jc.kind == "full":
                        protected.add(rt[1])
                        protected.add(triple[1])

        scope = Scope([(alias, orig) for _, alias, orig in relations],
                      parent=parent_scope)
        outer_refs: list[OuterRef] = []

        # 2. classify WHERE conjuncts (+ inner-join ON conjuncts)
        conjuncts = _split_conjuncts(q.where) if q.where is not None else []
        conjuncts = conjuncts + inner_on_conjuncts

        per_rel: dict[str, list] = {alias: [] for _, alias, _ in relations}
        equi_edges: list = []  # (alias_a, expr_a, alias_b, expr_b)
        residuals: list = []  # bound later against joined scope
        subquery_preds: list = []  # AST conjuncts containing subqueries

        # q19 shape: a top-level OR where every branch repeats the same
        # equi-join conjunct — hoist the common conjuncts so the pair of
        # relations joins hash-wise instead of as a cross product.
        hoisted: list = []
        for c in conjuncts:
            if isinstance(c, ast.Binary) and c.op == "or":
                common = _common_or_conjuncts(c)
                hoisted.extend(common)
        conjuncts = conjuncts + hoisted

        for c in conjuncts:
            if _contains_subquery(c):
                subquery_preds.append(c)
                continue
            aliases = self._aliases_of(c, scope)
            if len(aliases) == 1 and not (aliases & protected):
                per_rel[next(iter(aliases))].append(c)
            elif (
                len(aliases) == 2
                and isinstance(c, ast.Binary)
                and c.op == "=="
                and not (aliases & protected)
            ):
                la = self._aliases_of(c.left, scope)
                ra = self._aliases_of(c.right, scope)
                if len(la) == 1 and len(ra) == 1 and la != ra:
                    equi_edges.append((next(iter(la)), c.left,
                                       next(iter(ra)), c.right))
                else:
                    residuals.append(c)
            else:
                residuals.append(c)

        # 3. apply per-relation filters
        rel_plans: dict[str, LogicalPlan] = {}
        rel_rows: dict[str, int] = {}
        for plan, alias, orig in relations:
            rel_rows[alias] = self._relation_rows(alias, plan)
            for c in per_rel[alias]:
                pred = self._bind_expr(c, scope, outer_refs)
                plan = LFilter(pred, plan)
                rel_rows[alias] = max(rel_rows[alias] // 3, 1)
            rel_plans[alias] = plan

        # 3b. fold outer-join groups into unit plans (written order)
        units: list = []  # [plan, alias_set, rows]
        for g in groups:
            if g[0] == "rel":
                alias = g[1]
                units.append([rel_plans[alias], {alias}, rel_rows[alias]])
            else:
                _, base_alias, jpairs = g
                uplan = rel_plans[base_alias]
                ualiases = {base_alias}
                urows = rel_rows[base_alias]
                for jc, ralias in jpairs:
                    uplan = self._fold_explicit_join(
                        uplan, ualiases, jc, ralias, rel_plans[ralias],
                        scope, outer_refs,
                    )
                    ualiases.add(ralias)
                    urows = max(urows, rel_rows[ralias])
                units.append([uplan, ualiases, urows])

        # 4. greedy left-deep join order over units connected by equi edges
        alias_tables = {
            alias: (rplan.table if isinstance(rplan, LScan) else None)
            for rplan, alias, _ in relations
        }
        plan = self._order_joins(units, equi_edges, scope, outer_refs,
                                 alias_tables)

        # 5. residual predicates after joins
        for c in residuals:
            plan = LFilter(self._bind_expr(c, scope, outer_refs), plan)

        # 6. subquery predicates (EXISTS/IN/scalar comparisons)
        for c in subquery_preds:
            plan = self._apply_subquery_pred(c, plan, scope, outer_refs)

        # 7. aggregates
        plan = self._bind_projection_and_aggregates(q, plan, scope, outer_refs)

        if outer_refs and parent_scope is None:
            raise BindError(
                f"unresolved outer references: {[r.flat_name for r in outer_refs]}"
            )
        return plan

    # -- join ordering --------------------------------------------------------
    def _fold_explicit_join(self, uplan, ualiases, jc, ralias, rplan, scope,
                            outer_refs):
        """Fold one explicit [OUTER] JOIN clause in written order (outer joins
        must not be reordered; the preserved side is the accumulated left)."""
        if jc.kind == "cross":
            return LJoin(uplan, rplan, "cross", [], [])
        on_conjuncts = _split_conjuncts(jc.on) if jc.on is not None else []
        lkeys, rkeys = [], []
        post: list = []
        for c in on_conjuncts:
            aliases = self._aliases_of(c, scope)
            if (
                isinstance(c, ast.Binary) and c.op == "=="
                and len(aliases) == 2
            ):
                la = self._aliases_of(c.left, scope)
                ra = self._aliases_of(c.right, scope)
                if la <= ualiases and ra == {ralias}:
                    lkeys.append(self._bind_expr(c.left, scope, outer_refs))
                    rkeys.append(self._bind_expr(c.right, scope, outer_refs))
                    continue
                if ra <= ualiases and la == {ralias}:
                    lkeys.append(self._bind_expr(c.right, scope, outer_refs))
                    rkeys.append(self._bind_expr(c.left, scope, outer_refs))
                    continue
            if aliases == {ralias} and jc.kind in ("left", "inner"):
                # null-supplying-side-only conjunct: pre-filtering that side
                # is equivalent for LEFT (and INNER) joins
                rplan = LFilter(self._bind_expr(c, scope, outer_refs), rplan)
                continue
            post.append(c)
        if post:
            if jc.kind != "inner":
                raise BindError(
                    f"unsupported non-equi ON conjunct for {jc.kind.upper()} "
                    f"JOIN: {post[0]!r}"
                )
        if not lkeys:
            raise BindError(
                f"{jc.kind.upper()} JOIN without an equi ON condition"
            )
        kind = jc.kind
        fanout = self._scan_fanout(rplan, rkeys)
        if kind == "right":
            # preserved side must be the probe: swap
            out = LJoin(rplan, uplan, "left", rkeys, lkeys)
        elif kind == "full":
            # FULL OUTER = LEFT JOIN  UNION ALL  (right rows with no match,
            # left columns padded with typed NULLs) — the mirror of the
            # reference's HashJoinExec Full mode, built from the primitives
            # the TPU kernels already have (left + anti).
            lj = LJoin(uplan, rplan, "left", lkeys, rkeys)
            anti = LJoin(rplan, uplan, "anti", rkeys, lkeys)
            null_left = LProject(
                [(pe.Literal(None, f.dtype), f.name)
                 for f in uplan.schema().fields]
                + [(pe.Col(f.name), f.name) for f in rplan.schema().fields],
                anti,
            )
            out = LSetOp("union", True, lj, null_left)
        else:
            out = LJoin(uplan, rplan, kind, lkeys, rkeys,
                        fanout_hint=fanout)
        for c in post:
            out = LFilter(self._bind_expr(c, scope, outer_refs), out)
        return out

    def _scan_fanout(self, rplan: LogicalPlan, rkeys: list) -> float:
        """Estimated matches per probe row for a join against ``rplan`` on
        ``rkeys`` (bound Cols): rows(build) / ndv(build key). Explicit JOINs
        (q72's catalog_sales x inventory on item_sk) can be many-to-many;
        starting the output capacity at the NDV-implied expansion avoids
        burning every overflow retry on a 1x initial guess."""
        scans: dict[str, LScan] = {}

        def walk(n):
            if isinstance(n, LScan):
                scans[n.alias] = n
            for c in n.children():
                walk(c)

        walk(rplan)
        if not scans:
            return 1.0
        fanouts = []
        for k in rkeys:
            if not isinstance(k, pe.Col) or "." not in k.name:
                continue
            alias, _, col = k.name.partition(".")
            scan = scans.get(alias)
            if scan is None:
                continue
            try:
                # filter-discounted build rows (same heuristic as
                # _relation_rows: /3 per filter above the scan) — the full
                # table row count would overstate the fan-out by the build
                # side's selectivity
                rows = self._relation_rows(alias, rplan)
                ndv = self.catalog.column_ndv(scan.table, col)
            except Exception:
                continue
            if ndv:
                fanouts.append(max(float(rows) / float(ndv), 1.0))
        # several equi keys bound the fan-out by the most selective one
        return min(fanouts) if fanouts else 1.0

    def _join_fanout(self, edge, ualiases, urows, alias_tables) -> float:
        """Estimated output rows per probe row if this edge attaches the
        unit: rows(new) / ndv(new-side key). FK->PK joins (unique key on the
        new side) give ~1; low-cardinality keys (nationkey=nationkey) give a
        blow-up factor the orderer must avoid."""
        la, le, ra, re_ = edge
        inner_ast = le if la in ualiases else re_
        if not isinstance(inner_ast, ast.Ident):
            return 1.0
        # resolve alias for the ident within the unit
        alias = inner_ast.qualifier
        if alias is None:
            alias = la if la in ualiases else ra
        table = alias_tables.get(alias)
        if table is None:
            return 1.0
        ndv = self.catalog.column_ndv(table, inner_ast.name)
        if not ndv:
            return 1.0
        return max(float(urows) / float(ndv), 1.0)

    def _order_joins(self, units, equi_edges, scope, outer_refs,
                     alias_tables=None):
        """Greedily join units (relations or pre-folded outer-join groups):
        probe side = the largest unit (the fact table keeps output
        cardinality bounded by the probe side, which is what the static
        output-capacity model wants); among connected candidates, attach the
        one with the smallest estimated fan-out first (FK->PK dimension
        joins before many-to-many edges), breaking ties by unit size."""
        alias_tables = alias_tables or {}
        units = [list(u) for u in units]
        if len(units) == 1:
            return units[0][0]
        start = max(range(len(units)), key=lambda i: units[i][2])
        plan, joined, _rows = units[start]
        remaining = [u for i, u in enumerate(units) if i != start]
        edges = list(equi_edges)
        while remaining:
            candidates = []
            for ui, u in enumerate(remaining):
                _, ualiases, urows = u
                fanouts = []
                for e in edges:
                    la, _, ra, _ = e
                    if (la in joined and ra in ualiases) or (
                        ra in joined and la in ualiases
                    ):
                        fanouts.append(
                            self._join_fanout(e, ualiases, urows, alias_tables)
                        )
                if fanouts:
                    # several edges bound the fan-out by the most selective
                    candidates.append((min(fanouts), urows, ui))
            if not candidates:
                u = remaining.pop(0)
                plan = LJoin(plan, u[0], "cross", [], [])
                joined |= u[1]
                continue
            candidates.sort()
            best_fanout, _, ui = candidates[0]
            u = remaining.pop(ui)
            _, ualiases, _ = u
            lkeys, rkeys, rest = [], [], []
            for e in edges:
                la, le, ra, re_ = e
                if la in joined and ra in ualiases:
                    lkeys.append(self._bind_expr(le, scope, outer_refs))
                    rkeys.append(self._bind_expr(re_, scope, outer_refs))
                elif ra in joined and la in ualiases:
                    lkeys.append(self._bind_expr(re_, scope, outer_refs))
                    rkeys.append(self._bind_expr(le, scope, outer_refs))
                else:
                    rest.append(e)
            edges = rest
            plan = LJoin(plan, u[0], "inner", lkeys, rkeys,
                         fanout_hint=float(best_fanout))
            joined |= ualiases
        # edges whose endpoints ended up in the same unit: residual filters
        for la, le, ra, re_ in edges:
            pred = pe.BinaryOp(
                "==",
                self._bind_expr(le, scope, outer_refs),
                self._bind_expr(re_, scope, outer_refs),
            )
            plan = LFilter(pred, plan)
        return plan

    def _relation_rows(self, alias: str, plan: LogicalPlan) -> int:
        """Estimate rows under a relation's plan (scan size, filter discount)."""
        if isinstance(plan, LFilter):
            return max(self._relation_rows(alias, plan.child) // 3, 1)
        if isinstance(plan, LScan):
            try:
                return self.catalog.table_rows(plan.table)
            except Exception:
                return 1000
        if plan.children():
            return max(self._relation_rows(alias, c) for c in plan.children())
        return 1000

    # -- subquery predicates ----------------------------------------------------
    def _apply_subquery_pred(self, c, plan, scope, outer_refs) -> LogicalPlan:
        if isinstance(c, ast.Exists):
            return self._bind_exists(c.query, c.negated, plan, scope)
        if isinstance(c, ast.Unary) and c.op == "not" and isinstance(
            c.child, ast.Exists
        ):
            return self._bind_exists(c.child.query, not c.child.negated, plan, scope)
        if isinstance(c, ast.InSubquery):
            return self._bind_in_subquery(c, plan, scope, outer_refs)
        if isinstance(c, ast.Between) and not c.negated:
            # BETWEEN with subquery bounds (TPC-DS q54): split into the two
            # comparisons and route each through the right binder
            for shard in (
                ast.Binary(">=", c.expr, c.low),
                ast.Binary("<=", c.expr, c.high),
            ):
                if _contains_subquery(shard):
                    plan = self._apply_subquery_pred(
                        shard, plan, scope, outer_refs
                    )
                else:
                    plan = LFilter(
                        self._bind_expr(shard, scope, outer_refs), plan
                    )
            return plan
        if isinstance(c, ast.Binary) and c.op == "and":
            for side in (c.left, c.right):
                if _contains_subquery(side):
                    plan = self._apply_subquery_pred(
                        side, plan, scope, outer_refs
                    )
                else:
                    plan = LFilter(
                        self._bind_expr(side, scope, outer_refs), plan
                    )
            return plan
        if isinstance(c, ast.Binary) and c.op == "or":
            # disjunction containing EXISTS/IN-subquery (TPC-DS q35/q45):
            # each subquery becomes a MARK join; the disjunction then
            # evaluates over the mark columns as a plain filter
            return self._apply_disjunctive_subquery(c, plan, scope, outer_refs)
        # scalar subquery inside a comparison
        return self._bind_scalar_pred(c, plan, scope, outer_refs)

    def _apply_disjunctive_subquery(self, c, plan, scope, outer_refs):
        """Rewrite a boolean expression whose leaves include EXISTS /
        IN-subquery into mark joins + a boolean filter over the mark columns
        (the reference gets this from DataFusion's subquery decorrelation,
        which lowers to the same mark-join shape)."""
        plan_box = [plan]

        def walk(node):
            if isinstance(node, ast.Binary) and node.op in ("and", "or"):
                l = walk(node.left)
                r = walk(node.right)
                return pe.BooleanOp(node.op, l, r)
            if isinstance(node, ast.Unary) and node.op == "not":
                return pe.Not(walk(node.child))
            if isinstance(node, ast.Exists):
                mark = self._mark_join_exists(node, plan_box, scope)
                return pe.Not(mark) if node.negated else mark
            if isinstance(node, ast.InSubquery):
                mark = self._mark_join_in(node, plan_box, scope, outer_refs)
                return pe.Not(mark) if node.negated else mark
            return self._bind_expr(node, scope, outer_refs)

        def _mark_name():
            # process-wide monotonic counter: unique across every mark join
            # in the query AND deterministic (resettable) for plan snapshots
            return f"__mark_{next(_MARK_SEQ)}"

        self.__mark_name = _mark_name  # shared with helpers below
        pred = walk(c)
        return LFilter(pred, plan_box[0])

    def _mark_join_exists(self, node: ast.Exists, plan_box, scope):
        sub_binder = Binder(self.catalog, self.ctes)
        sub_refs: list = []
        sub_plan, corr_pairs, residual = sub_binder._bind_correlated(
            node.query, scope, sub_refs
        )
        if not corr_pairs:
            raise BindError("uncorrelated EXISTS not supported yet")
        name = self.__mark_name()
        plan_box[0] = LJoin(
            plan_box[0], sub_plan, "mark",
            [pe.Col(outer) for outer, _ in corr_pairs],
            [inner for _, inner in corr_pairs],
            residual=residual, mark_name=name,
        )
        return pe.Col(name)

    def _mark_join_in(self, node: ast.InSubquery, plan_box, scope, outer_refs):
        expr = self._bind_expr(node.expr, scope, outer_refs)
        sub_binder = Binder(self.catalog, self.ctes)
        sub_refs: list = []
        sub_plan, corr_pairs, residual = sub_binder._bind_correlated(
            node.query, scope, sub_refs
        )
        out_cols = sub_plan.schema()
        if len(out_cols) - len(corr_pairs) != 1 and len(out_cols) != 1:
            raise BindError("IN subquery must produce one column")
        name = self.__mark_name()
        plan_box[0] = LJoin(
            plan_box[0], sub_plan, "mark",
            [expr] + [pe.Col(outer) for outer, _ in corr_pairs],
            [pe.Col(out_cols.fields[0].name)] + [
                inner for _, inner in corr_pairs
            ],
            residual=residual, mark_name=name,
        )
        return pe.Col(name)

    def _bind_exists(self, subq: ast.Query, negated: bool, plan, scope):
        sub_binder = Binder(self.catalog, self.ctes)
        sub_refs: list[OuterRef] = []
        sub_plan, corr_pairs, residual = sub_binder._bind_correlated(
            subq, scope, sub_refs
        )
        if not corr_pairs:
            raise BindError("uncorrelated EXISTS not supported yet")
        lkeys = [pe.Col(outer) for outer, _ in corr_pairs]
        rkeys = [inner for _, inner in corr_pairs]
        how = "anti" if negated else "semi"
        return LJoin(plan, sub_plan, how, lkeys, rkeys, residual=residual)

    def _bind_in_subquery(self, c: ast.InSubquery, plan, scope, outer_refs):
        expr = self._bind_expr(c.expr, scope, outer_refs)
        sub_binder = Binder(self.catalog, self.ctes)
        sub_refs: list[OuterRef] = []
        sub_plan, corr_pairs, residual = sub_binder._bind_correlated(
            c.query, scope, sub_refs
        )
        out_cols = sub_plan.schema()
        if len(out_cols) - len(corr_pairs) != 1 and len(out_cols) != 1:
            raise BindError("IN subquery must produce one column")
        value_col = pe.Col(out_cols.fields[0].name)
        lkeys = [expr] + [pe.Col(outer) for outer, _ in corr_pairs]
        rkeys = [value_col] + [inner for _, inner in corr_pairs]
        how = "anti" if c.negated else "semi"
        return LJoin(plan, sub_plan, how, lkeys, rkeys, residual=residual,
                     null_aware=c.negated)

    def _bind_scalar_pred(self, c, plan, scope, outer_refs):
        """Comparison against a scalar subquery (correlated or not)."""
        if not (isinstance(c, ast.Binary) and c.op in ("==", "!=", "<", "<=",
                                                       ">", ">=")):
            raise BindError(
                f"unsupported subquery predicate shape: {type(c).__name__}"
            )
        # The subquery may sit anywhere inside the comparison (TPC-DS q6:
        # `price > 1.2 * (select avg(...))`): locate it, bind it, splice the
        # bound scalar back in, then bind the whole comparison normally.
        found: list = []

        def hunt(node):
            if isinstance(node, ast.ScalarSubquery):
                found.append(node)
                return node  # do not descend further
            return None

        _ast_substitute(c, hunt)
        if len(found) != 1:
            raise BindError("expected scalar subquery in comparison")
        sub_ast = found[0]

        sub_binder = Binder(self.catalog, self.ctes)
        sub_refs: list[OuterRef] = []
        sub_plan, corr_pairs, residual = sub_binder._bind_correlated(
            sub_ast.query, scope, sub_refs
        )
        if residual is not None:
            raise BindError("non-equi correlation in scalar subquery")

        if not corr_pairs:
            # uncorrelated: evaluate eagerly at execution time
            spliced = _ast_substitute(
                c, lambda n: ast.PreBound(ScalarSubqueryExpr(sub_plan))
                if n is sub_ast else None,
            )
            return LFilter(self._bind_expr(spliced, scope, outer_refs), plan)

        # correlated scalar aggregate: sub_plan is Aggregate(groups=corr keys)
        scalar_col = pe.Col(sub_plan.schema().fields[-1].name)
        lkeys = [pe.Col(outer) for outer, _ in corr_pairs]
        rkeys = [inner for _, inner in corr_pairs]
        joined = LJoin(plan, sub_plan, "left", lkeys, rkeys)
        spliced = _ast_substitute(
            c, lambda n: ast.PreBound(scalar_col) if n is sub_ast else None,
        )
        filtered = LFilter(
            self._bind_expr(spliced, scope, outer_refs), joined
        )
        # project away subquery columns
        keep = [
            (pe.Col(f.name), f.name) for f in plan.schema().fields
        ]
        return LProject(keep, filtered)

    def _bind_correlated(self, subq: ast.Query, outer_scope, sub_refs):
        """Bind a subquery that may reference the outer scope.

        Returns (plan, corr_pairs, residual) where corr_pairs are
        (outer_flat_name, inner key PhysicalExpr) equi correlations hoisted
        out of the subquery's WHERE, and residual is a bound predicate over
        the [outer columns joined with subquery output] schema for non-equi
        correlated conjuncts (EXISTS with <> as in TPC-H q21).
        """
        q = subq
        conjuncts = _split_conjuncts(q.where) if q.where is not None else []
        # surface correlations hidden inside OR branches (q41 shape)
        conjuncts = [x for c in conjuncts for x in _hoist_common_or(c)]
        corr: list[tuple[str, ast.Ident]] = []  # (outer flat, inner ast)
        residual_asts: list = []
        local: list = []
        probe_scope = self._subquery_scope(q, outer_scope)
        for c in conjuncts:
            side = self._correlation_side(c, probe_scope)
            if side == "local":
                local.append(c)
            elif side == "equi":
                outer_ast, inner_ast = self._split_correlation(c, probe_scope)
                corr.append((outer_ast, inner_ast))
            else:  # residual correlated
                residual_asts.append(c)

        q2 = ast.Query(
            select_items=q.select_items,
            from_refs=q.from_refs,
            where=_join_conjuncts(local),
            group_by=q.group_by,
            having=q.having,
            order_by=q.order_by,
            limit=q.limit,
            offset=q.offset,
            distinct=q.distinct,
            ctes=q.ctes,
        )

        if corr and _has_aggregates(q2):
            # correlated scalar aggregate -> group by correlation keys
            inner_group_asts = [inner for _, inner in corr]
            q2 = ast.Query(
                select_items=list(q2.select_items)
                + [ast.SelectItem(a, f"__corr{i}") for i, a in
                   enumerate(inner_group_asts)],
                from_refs=q2.from_refs,
                where=q2.where,
                group_by=list(q2.group_by) + inner_group_asts,
                having=q2.having,
                order_by=[],
                limit=None,
                offset=None,
                distinct=False,
                ctes=q2.ctes,
            )
            plan = self._bind_query(q2, None)
            fields = plan.schema().fields
            ncorr = len(corr)
            pairs = []
            for (outer_flat, _), f in zip(corr, fields[-ncorr:]):
                pairs.append((outer_flat, pe.Col(f.name)))
            # keep scalar as last col before corr keys: re-project so schema =
            # [corr keys..., scalar]
            scalar_field = fields[-ncorr - 1]
            proj = [(pe.Col(f.name), f.name) for f in fields[-ncorr:]]
            proj.append((pe.Col(scalar_field.name), scalar_field.name))
            plan = LProject(proj, plan)
            return plan, pairs, None

        plan = self._bind_query(q2, None)
        pairs = []
        for outer_flat, inner_ast in corr:
            inner_scope = self._subquery_scope(q2, None)
            inner_bound = Binder(self.catalog, self.ctes)._bind_expr(
                inner_ast, inner_scope, None
            )
            # the subquery's output schema must expose the key column; ensure
            # it by projecting the join keys alongside existing outputs
            pairs.append((outer_flat, inner_bound))
        residual = None
        if residual_asts:
            # bind residual against outer+inner: inner entries SHADOW outer
            # ones (an unqualified name over two `item` relations must pick
            # the subquery's own, q41), while outer names stay reachable —
            # qualified or via the parent scope
            combined = Scope(
                self._subquery_scope(q2, None).entries, parent=outer_scope
            )
            shadow_refs: list = []
            bound = [
                self._bind_expr(a, combined, shadow_refs)
                for a in residual_asts
            ]
            residual = bound[0]
            for b in bound[1:]:
                residual = pe.BooleanOp("and", residual, b)
        if pairs or residual is not None:
            # Expose referenced inner columns through the subquery's output
            # projection. Outer-side names in the residual stay out — they
            # resolve against the probe side of the join at execution.
            inner_aliases = {
                alias for alias, _ in self._subquery_scope(q2, None).entries
            }
            needed = _collect_col_names(
                [p for _, p in pairs] + ([residual] if residual is not None else [])
            )
            existing = set(f.name for f in plan.schema().fields)
            missing = [
                n for n in needed
                if n not in existing and n.split(".")[0] in inner_aliases
            ]
            if missing:
                exprs = [(pe.Col(f.name), f.name) for f in plan.schema().fields]
                exprs += [(pe.Col(n), n) for n in missing]
                plan = _project_through(plan, exprs)
        return plan, pairs, residual

    def _subquery_scope(self, q: ast.Query, outer_scope) -> Scope:
        entries = []
        for base, joins in q.from_refs:
            for ref in [base] + [j.right for j in joins]:
                if isinstance(ref, ast.TableRef):
                    alias = ref.alias or ref.name
                    if ref.name in self.ctes:
                        sub = self.ctes[ref.name]
                        names = [f.name.split(".")[-1] for f in sub.schema().fields]
                        entries.append(
                            (alias, Schema([Field(n, f.dtype, f.nullable)
                                            for n, f in zip(names, sub.schema().fields)]))
                        )
                    else:
                        entries.append((alias, self.catalog.table_schema(ref.name)))
                else:
                    sub_binder = Binder(self.catalog, self.ctes)
                    sub = sub_binder._bind_query(ref.query, None)
                    names = ref.column_aliases or [
                        f.name.split(".")[-1] for f in sub.schema().fields
                    ]
                    entries.append(
                        (ref.alias, Schema([Field(n, f.dtype, f.nullable)
                                            for n, f in zip(names, sub.schema().fields)]))
                    )
        return Scope(entries, parent=outer_scope)

    def _combined_scope(self, q: ast.Query, outer_scope) -> Scope:
        inner = self._subquery_scope(q, None)
        entries = list(inner.entries) + (
            list(outer_scope.entries) if outer_scope else []
        )
        return Scope(entries)

    def _correlation_side(self, c, probe_scope: Scope) -> str:
        """'local' (no outer refs) | 'equi' (outer = inner) | 'residual'."""
        refs = self._outer_ref_names(c, probe_scope)
        if not refs:
            return "local"
        if isinstance(c, ast.Binary) and c.op == "==":
            lrefs = self._outer_ref_names(c.left, probe_scope)
            rrefs = self._outer_ref_names(c.right, probe_scope)
            if (
                isinstance(c.left, ast.Ident)
                and lrefs
                and not rrefs
                or isinstance(c.right, ast.Ident)
                and rrefs
                and not lrefs
            ):
                return "equi"
        return "residual"

    def _split_correlation(self, c: ast.Binary, probe_scope: Scope):
        lrefs = self._outer_ref_names(c.left, probe_scope)
        if lrefs and isinstance(c.left, ast.Ident):
            outer_ast, inner_ast = c.left, c.right
        else:
            outer_ast, inner_ast = c.right, c.left
        flat, _, _ = probe_scope.parent.resolve(outer_ast) if probe_scope.parent else (
            None, None, None
        )
        if flat is None:
            raise BindError("failed to resolve correlation")
        return flat, inner_ast

    def _outer_ref_names(self, node, probe_scope: Scope) -> list[str]:
        out = []

        def walk(n):
            if isinstance(n, ast.Ident):
                try:
                    _, _, depth = probe_scope.resolve(n)
                    if depth > 0:
                        out.append(n.key())
                except BindError:
                    pass
                return
            for ch in _ast_children(n):
                walk(ch)

        walk(node)
        return out

    def _aliases_of(self, node, scope: Scope) -> set:
        out: set = set()

        def walk(n):
            if isinstance(n, ast.Ident):
                try:
                    flat, _, depth = scope.resolve(n)
                    if depth == 0:
                        out.add(flat.split(".")[0])
                except BindError:
                    pass
                return
            for ch in _ast_children(n):
                walk(ch)

        walk(node)
        return out

    # -- projection & aggregation ------------------------------------------
    def _bind_projection_and_aggregates(self, q: ast.Query, plan, scope,
                                        outer_refs) -> LogicalPlan:
        agg_calls = []
        window_calls = []
        for item in q.select_items:
            _collect_agg_calls(item.expr, agg_calls)
            _collect_window_calls(item.expr, window_calls)
        if q.having is not None:
            _collect_agg_calls(q.having, agg_calls)
        for o in q.order_by:
            _collect_agg_calls(o.expr, agg_calls)
            _collect_window_calls(o.expr, window_calls)

        has_group = bool(q.group_by)
        has_aggs = bool(agg_calls)

        select_aliases = {
            item.alias: item.expr for item in q.select_items if item.alias
        }

        if has_group or has_aggs:
            # group expressions: resolve alias/positional references
            group_asts = []
            for g in q.group_by:
                g = self._resolve_output_ref(g, q.select_items, select_aliases)
                group_asts.append(g)
            groups = []
            for i, g in enumerate(group_asts):
                e = self._bind_expr(g, scope, outer_refs)
                groups.append((e, f"__g{i}"))
            # aggregate calls
            aggs = []
            agg_map: dict[int, str] = {}
            distinct_rewrites = []
            for j, call in enumerate(agg_calls):
                func, arg_ast, distinct = _agg_parts(call)
                name = f"__a{j}"
                if func == "count" and isinstance(arg_ast, ast.Star):
                    aggs.append(AggCall("count_star", None, name))
                else:
                    arg = self._bind_expr(arg_ast, scope, outer_refs)
                    if distinct and func == "count":
                        distinct_rewrites.append((j, arg, name))
                        aggs.append(AggCall("count", arg, name, distinct=True))
                    else:
                        aggs.append(AggCall(func, arg, name))
                agg_map[id(call)] = name
            agg_plan = LAggregate(groups, aggs, plan)

            # post-aggregation scope: group exprs + agg outputs
            group_lookup = {
                _ast_fingerprint(g): f"__g{i}" for i, g in enumerate(group_asts)
            }

            def rebind(e):
                return self._bind_post_agg(
                    e, scope, group_lookup, agg_map, select_aliases
                )

            result: LogicalPlan = agg_plan
            if q.having is not None:
                result = LFilter(rebind(q.having), result)
            self._window_map = {}
            if window_calls:
                result = self._build_windows(window_calls, result, rebind)

            out_exprs = []
            out_names = []
            for idx, item in enumerate(q.select_items):
                if isinstance(item.expr, ast.Star):
                    raise BindError("SELECT * with GROUP BY is not supported")
                name = item.alias or _display_name(item.expr, idx)
                out_exprs.append(rebind(item.expr))
                out_names.append(name)
            # structural fingerprints of select items -> output names
            out_fps = {
                _ast_fingerprint(item.expr): name
                for item, name in zip(q.select_items, out_names)
            }
            proj_exprs = list(zip(out_exprs, out_names))
            sort_keys = []
            hidden: list = []
            if q.order_by:
                for o in q.order_by:
                    e = self._bind_order_expr_agg(
                        o.expr, scope, group_lookup, agg_map, select_aliases,
                        proj_exprs, out_fps,
                    )
                    # keys referencing agg-internal columns must ride through
                    # the projection as hidden columns
                    for cname in _collect_col_names([e]):
                        if cname not in out_names and cname not in (
                            n for _, n in hidden
                        ):
                            hidden.append((pe.Col(cname), cname))
                    sort_keys.append((e, o.ascending, o.nulls_first))
            plan2: LogicalPlan = LProject(proj_exprs + hidden, result)
            if sort_keys:
                plan2 = LSort(sort_keys, plan2, fetch=_sort_fetch(q))
            if hidden:
                plan2 = LProject(
                    [(pe.Col(n), n) for n in out_names], plan2
                )
            if q.distinct:
                plan2 = LDistinct(plan2)
            if q.limit is not None or q.offset is not None:
                plan2 = LLimit(plan2, q.limit, q.offset or 0)
            return plan2

        # no aggregation
        self._window_map = {}
        star_schema = plan.schema()  # pre-window: __wN stays internal
        if window_calls:
            plan = self._build_windows(
                window_calls, plan,
                lambda e: self._bind_expr(e, scope, outer_refs),
            )
        out = []
        for idx, item in enumerate(q.select_items):
            if isinstance(item.expr, ast.Star):
                for f in star_schema.fields:
                    short = f.name.split(".")[-1]
                    if item.expr.qualifier and not f.name.startswith(
                        item.expr.qualifier + "."
                    ):
                        continue
                    out.append((pe.Col(f.name), short))
                continue
            name = item.alias or _display_name(item.expr, idx)
            out.append((self._bind_expr(item.expr, scope, outer_refs), name))
        out_names = [n for _, n in out]
        sort_keys = []
        hidden: list = []
        if q.order_by:
            for o in q.order_by:
                e = self._bind_order_expr_plain(
                    o.expr, scope, outer_refs, out, select_aliases
                )
                # sort keys referencing columns (incl. window __wN) that the
                # projection would drop ride through as hidden columns
                for cname in _collect_col_names([e]):
                    if cname not in out_names and cname not in (
                        n for _, n in hidden
                    ):
                        hidden.append((pe.Col(cname), cname))
                sort_keys.append((e, o.ascending, o.nulls_first))
        result = LProject(out + hidden, plan)
        if sort_keys:
            result = LSort(sort_keys, result, fetch=_sort_fetch(q))
        if hidden:
            result = LProject([(pe.Col(n), n) for n in out_names], result)
        if q.distinct:
            result = LDistinct(result)
        if q.limit is not None or q.offset is not None:
            result = LLimit(result, q.limit, q.offset or 0)
        return result

    def _build_windows(self, window_calls, plan, bind_fn) -> LogicalPlan:
        """Materialize window calls as __wN columns via an LWindow node;
        records id(call) -> name in self._window_map for later rebinding."""
        wexprs = []
        for j, wc in enumerate(window_calls):
            name = f"__w{j}"
            func = wc.name
            if func not in _AGG_FUNCS | _WINDOW_ONLY_FUNCS:
                raise BindError(f"unsupported window function {func}")
            if wc.distinct:
                raise BindError(
                    f"DISTINCT is not supported in window function {func}"
                )
            arg = None
            if func in _AGG_FUNCS:
                if wc.args and isinstance(wc.args[0], ast.Star):
                    func = "count_star"
                elif not wc.args:
                    if func == "count":
                        func = "count_star"
                    else:
                        raise BindError(f"window {func} needs an argument")
                else:
                    arg = bind_fn(wc.args[0])
            partitions = [bind_fn(p) for p in wc.over.partition_by]
            orders = [
                (bind_fn(o.expr), o.ascending, o.nulls_first)
                for o in wc.over.order_by
            ]
            wexprs.append(
                LWindowExpr(func, arg, partitions, orders, name,
                            frame=wc.over.frame)
            )
            self._window_map[id(wc)] = name
        return LWindow(wexprs, plan)

    def _bind_order_by(self, q, plan, bind_fn) -> LogicalPlan:
        keys = []
        for o in q.order_by:
            e = bind_fn(o.expr)
            keys.append((e, o.ascending, o.nulls_first))
        return LSort(keys, plan, fetch=_sort_fetch(q))

    def _bind_order_expr_plain(self, e, scope, outer_refs, out_exprs,
                               select_aliases):
        # positional reference
        if isinstance(e, ast.NumberLit) and isinstance(e.value, int):
            expr, name = out_exprs[e.value - 1]
            return pe.Col(name)
        if isinstance(e, ast.Ident) and e.qualifier is None:
            for expr, name in out_exprs:
                if name == e.name:
                    return pe.Col(name)
        return self._bind_expr(e, scope, outer_refs)

    def _bind_order_expr_agg(self, e, scope, group_lookup, agg_map,
                             select_aliases, out_exprs, out_fps):
        if isinstance(e, ast.NumberLit) and isinstance(e.value, int):
            _, name = out_exprs[e.value - 1]
            return pe.Col(name)
        if isinstance(e, ast.Ident) and e.qualifier is None:
            for _, name in out_exprs:
                if name == e.name:
                    return pe.Col(name)
        # structural match against a select item (ORDER BY t.k when SELECT
        # t.k ... GROUP BY t.k)
        fp = _ast_fingerprint(e)
        if fp in out_fps:
            return pe.Col(out_fps[fp])
        return self._bind_post_agg(e, scope, group_lookup, agg_map,
                                   select_aliases)

    def _resolve_output_ref(self, g, select_items, select_aliases):
        """GROUP BY may reference select aliases or positions."""
        if isinstance(g, ast.NumberLit) and isinstance(g.value, int):
            return select_items[g.value - 1].expr
        if isinstance(g, ast.Ident) and g.qualifier is None and g.name in (
            select_aliases
        ):
            return select_aliases[g.name]
        return g

    def _bind_post_agg(self, e, scope, group_lookup, agg_map, select_aliases):
        """Bind an expression over the aggregate's output: aggregate calls map
        to their output columns, group-expr subtrees map to group columns."""
        if isinstance(e, ast.NullOf):
            _, field, _ = scope.resolve(e.ident)
            return pe.Literal(None, field.dtype)
        wm = getattr(self, "_window_map", {})
        if id(e) in wm:
            return pe.Col(wm[id(e)])
        fp = _ast_fingerprint(e)
        if fp in group_lookup:
            return pe.Col(group_lookup[fp])
        if id(e) in agg_map:
            return pe.Col(agg_map[id(e)])
        # the same aggregate may appear in several clauses as distinct AST
        # objects: match structurally
        matched = self._match_agg_by_fingerprint(e, agg_map)
        if matched is not None:
            return pe.Col(matched)
        if isinstance(e, ast.Ident) and e.qualifier is None and e.name in (
            select_aliases
        ):
            return self._bind_post_agg(
                select_aliases[e.name], scope, group_lookup, agg_map,
                select_aliases,
            )
        # recurse structurally
        return self._rebind_children(
            e, lambda ch: self._bind_post_agg(ch, scope, group_lookup, agg_map,
                                              select_aliases)
        )

    def _match_agg_by_fingerprint(self, e, agg_map):
        if not (isinstance(e, ast.FuncCall) and e.name in _AGG_FUNCS):
            return None
        fp = _ast_fingerprint(e)
        for call_id, name in agg_map.items():
            call = _AGG_ID_REGISTRY.get(call_id)
            if call is not None and _ast_fingerprint(call) == fp:
                return name
        return None

    def _rebind_children(self, e, f: Callable):
        """Rebuild an AST expression bottom-up into a PhysicalExpr, using f for
        sub-expressions. Leaf idents must resolve via group/agg maps (handled
        in f); anything else binds as scalar structure."""
        if isinstance(e, ast.NumberLit):
            return _literal_expr(e.value)
        if isinstance(e, ast.StringLit):
            return pe.Literal(e.value, DataType.STRING)
        if isinstance(e, ast.DateLit):
            return pe.Literal(e.days, DataType.DATE32)
        if isinstance(e, ast.Binary):
            if e.op in ("and", "or"):
                return pe.BooleanOp(e.op, f(e.left), f(e.right))
            return pe.BinaryOp(e.op, f(e.left), f(e.right))
        if isinstance(e, ast.Unary):
            if e.op == "not":
                return pe.Not(f(e.child))
            return pe.Negate(f(e.child))
        if isinstance(e, ast.CaseAst):
            branches = tuple((f(c), f(v)) for c, v in e.whens)
            return pe.Case(branches, f(e.else_) if e.else_ else None)
        if isinstance(e, ast.Between):
            lo = pe.BinaryOp(">=", f(e.expr), f(e.low))
            hi = pe.BinaryOp("<=", f(e.expr), f(e.high))
            both = pe.BooleanOp("and", lo, hi)
            return pe.Not(both) if e.negated else both
        if isinstance(e, ast.CastAst):
            to = _cast_type(e.type_name)
            if isinstance(e.expr, ast.StringLit) and to == DataType.DATE32:
                return pe.Literal(pe.parse_date(e.expr.value), DataType.DATE32)
            return pe.Cast(f(e.expr), to)
        if isinstance(e, ast.ScalarSubquery):
            # e.g. HAVING sum(x) > (select ... ) — TPC-H q11
            sub = Binder(self.catalog, self.ctes)._bind_query(e.query, None)
            return ScalarSubqueryExpr(sub)
        if isinstance(e, ast.InListAst):
            return self._bind_in_list(e, f)
        if isinstance(e, ast.LikeAst):
            return pe.Like(f(e.expr), e.pattern, e.negated)
        if isinstance(e, ast.IsNullAst):
            return pe.IsNull(f(e.expr), e.negated)
        if isinstance(e, ast.ExtractAst):
            return pe.Extract(e.part, f(e.expr))
        if isinstance(e, ast.SubstringAst):
            start = e.start.value if isinstance(e.start, ast.NumberLit) else None
            length = (
                e.length.value if isinstance(e.length, ast.NumberLit) else None
            )
            if start is None:
                raise BindError("SUBSTRING start must be a literal")
            return pe.Substring(f(e.expr), start, length)
        if isinstance(e, ast.FuncCall) and e.over is None:
            bound = self._bind_scalar_func(e, f)
            if bound is not None:
                return bound
        raise BindError(
            f"cannot rebind {type(e).__name__} over aggregate output"
        )

    def _bind_in_list(self, e: ast.InListAst, f) -> pe.PhysicalExpr:
        values = []
        for item in e.items:
            if isinstance(item, ast.StringLit):
                values.append(item.value)
            elif isinstance(item, ast.NumberLit):
                values.append(item.value)
            elif isinstance(item, ast.DateLit):
                values.append(item.days)
            else:
                d = _as_decimal(item)
                if d is None:
                    raise BindError("IN list items must be literals")
                values.append(int(d) if d == int(d) else float(d))
        return pe.InList(f(e.expr), tuple(values), e.negated)

    def _bind_scalar_func(self, e, f) -> Optional[pe.PhysicalExpr]:
        """Bind a scalar FuncCall using ``f`` for its children; None when
        the name is unknown (callers raise their own error)."""
        name = e.name.lower()
        if name == "coalesce":
            return pe.Coalesce(tuple(f(a) for a in e.args))
        if name == "abs":
            return pe.Abs(f(e.args[0]))
        if name == "round":
            digits = 0
            if len(e.args) > 1 and isinstance(e.args[1], ast.NumberLit):
                digits = int(e.args[1].value)
            return pe.Round(f(e.args[0]), digits)
        if name in ("upper", "lower"):
            return pe.StringCase(f(e.args[0]), name == "upper")
        if name == "concat":
            return pe.ConcatStrings(tuple(f(a) for a in e.args))
        if name in ("length", "char_length", "character_length"):
            return pe.StrLength(f(e.args[0]))
        if name == "regexp_replace":
            pat = e.args[1]
            rep = e.args[2]
            if not (isinstance(pat, ast.StringLit)
                    and isinstance(rep, ast.StringLit)):
                raise BindError(
                    "REGEXP_REPLACE pattern/replacement must be literals"
                )
            return pe.RegexpReplace(f(e.args[0]), pat.value, rep.value)
        if name in ("to_timestamp_seconds", "to_timestamp"):
            # epoch-seconds integers ARE the timestamp representation here
            return f(e.args[0])
        if name == "date_trunc":
            unit = e.args[0]
            if not isinstance(unit, ast.StringLit):
                raise BindError("DATE_TRUNC unit must be a string literal")
            return pe.DateTrunc(unit.value, f(e.args[1]))
        return None

    # -- expression binding ---------------------------------------------------
    def _bind_expr(self, e, scope: Scope, outer_refs) -> pe.PhysicalExpr:
        if isinstance(e, ast.PreBound):
            return e.expr
        if isinstance(e, ast.NullOf):
            _, field, _ = scope.resolve(e.ident)
            return pe.Literal(None, field.dtype)
        if isinstance(e, ast.Ident):
            flat, field, depth = scope.resolve(e)
            if depth > 0:
                if outer_refs is None:
                    raise BindError(f"unexpected outer reference {e.key()}")
                outer_refs.append(OuterRef(flat, field))
            return pe.Col(flat)
        if isinstance(e, ast.NumberLit):
            return _literal_expr(e.value)
        if isinstance(e, ast.StringLit):
            return pe.Literal(e.value, DataType.STRING)
        if isinstance(e, ast.DateLit):
            return pe.Literal(e.days, DataType.DATE32)
        if isinstance(e, ast.IntervalLit):
            raise BindError("bare interval literal outside date arithmetic")
        if isinstance(e, ast.Binary):
            if e.op in ("and", "or"):
                return pe.BooleanOp(
                    e.op,
                    self._bind_expr(e.left, scope, outer_refs),
                    self._bind_expr(e.right, scope, outer_refs),
                )
            # date +/- interval folding
            folded = _fold_date_arith(e)
            if folded is not None:
                return folded if isinstance(folded, pe.PhysicalExpr) else (
                    self._bind_expr(folded, scope, outer_refs)
                )
            # column +/- INTERVAL 'n' DAY: date32 is integer days, so the
            # interval becomes a plain int32 addend (months would need
            # calendar arithmetic per row; unsupported on columns)
            if isinstance(e.right, ast.IntervalLit) and e.op in ("+", "-"):
                if e.right.months != 0:
                    raise BindError(
                        "month intervals on date columns are not supported"
                    )
                base = self._bind_expr(e.left, scope, outer_refs)
                delta = e.right.days if e.op == "+" else -e.right.days
                return pe.BinaryOp(
                    "+", base, pe.Literal(delta, DataType.INT32)
                )
            # exact decimal folding of literal arithmetic: SQL decimals make
            # `.06 - 0.01` exactly 0.05; float64 would give 0.049999...
            dec = _fold_decimal_arith(e)
            if dec is not None:
                return dec
            return pe.BinaryOp(
                e.op,
                self._bind_expr(e.left, scope, outer_refs),
                self._bind_expr(e.right, scope, outer_refs),
            )
        if isinstance(e, ast.Unary):
            if e.op == "not":
                return pe.Not(self._bind_expr(e.child, scope, outer_refs))
            return pe.Negate(self._bind_expr(e.child, scope, outer_refs))
        if isinstance(e, ast.Between):
            x = self._bind_expr(e.expr, scope, outer_refs)
            lo = pe.BinaryOp(">=", x, self._bind_expr(e.low, scope, outer_refs))
            hi = pe.BinaryOp("<=", x, self._bind_expr(e.high, scope, outer_refs))
            both = pe.BooleanOp("and", lo, hi)
            return pe.Not(both) if e.negated else both
        if isinstance(e, ast.InListAst):
            return self._bind_in_list(
                e, lambda a: self._bind_expr(a, scope, outer_refs)
            )
        if isinstance(e, ast.LikeAst):
            return pe.Like(
                self._bind_expr(e.expr, scope, outer_refs), e.pattern, e.negated
            )
        if isinstance(e, ast.IsNullAst):
            return pe.IsNull(
                self._bind_expr(e.expr, scope, outer_refs), e.negated
            )
        if isinstance(e, ast.CaseAst):
            if e.operand is not None:
                operand = self._bind_expr(e.operand, scope, outer_refs)
                branches = tuple(
                    (
                        pe.BinaryOp(
                            "==", operand, self._bind_expr(c, scope, outer_refs)
                        ),
                        self._bind_expr(v, scope, outer_refs),
                    )
                    for c, v in e.whens
                )
            else:
                branches = tuple(
                    (
                        self._bind_expr(c, scope, outer_refs),
                        self._bind_expr(v, scope, outer_refs),
                    )
                    for c, v in e.whens
                )
            otherwise = (
                self._bind_expr(e.else_, scope, outer_refs) if e.else_ else None
            )
            return pe.Case(branches, otherwise)
        if isinstance(e, ast.CastAst):
            to = _cast_type(e.type_name)
            if isinstance(e.expr, ast.StringLit) and to == DataType.DATE32:
                return pe.Literal(pe.parse_date(e.expr.value), DataType.DATE32)
            return pe.Cast(self._bind_expr(e.expr, scope, outer_refs), to)
        if isinstance(e, ast.ExtractAst):
            return pe.Extract(
                e.part, self._bind_expr(e.expr, scope, outer_refs)
            )
        if isinstance(e, ast.SubstringAst):
            start = e.start.value if isinstance(e.start, ast.NumberLit) else None
            length = (
                e.length.value if isinstance(e.length, ast.NumberLit) else None
            )
            if start is None:
                raise BindError("SUBSTRING start must be a literal")
            return pe.Substring(
                self._bind_expr(e.expr, scope, outer_refs), start, length
            )
        if isinstance(e, ast.ScalarSubquery):
            sub = Binder(self.catalog, self.ctes)._bind_query(e.query, None)
            return ScalarSubqueryExpr(sub)
        if isinstance(e, ast.FuncCall):
            wm = getattr(self, "_window_map", {})
            if id(e) in wm:
                return pe.Col(wm[id(e)])
            if e.over is not None:
                raise BindError(
                    f"window function {e.name} not allowed in this context"
                )
            if e.name in _AGG_FUNCS:
                raise BindError(
                    f"aggregate {e.name} not allowed in this context"
                )
            bound = self._bind_scalar_func(
                e, lambda a: self._bind_expr(a, scope, outer_refs)
            )
            if bound is not None:
                return bound
            raise BindError(f"unknown function {e.name}")
        raise BindError(f"cannot bind {type(e).__name__}")


# ---------------------------------------------------------------------------
# Scalar subquery expression (executed lazily by the physical layer)
# ---------------------------------------------------------------------------


class ScalarSubqueryExpr(pe.PhysicalExpr):
    """Placeholder for an uncorrelated scalar subquery; the physical planner
    replaces it with a literal after executing the subplan (the reference
    disables DataFusion's uncorrelated-subquery pushdown and relies on plain
    planning, `session_state_builder_ext.rs:17-27` — here we evaluate it as a
    prepared constant instead)."""

    def __init__(self, logical: LogicalPlan):
        self.logical = logical
        self.physical = None  # filled by the physical planner

    def children(self):
        return []

    def evaluate(self, table):
        raise RuntimeError(
            "ScalarSubqueryExpr must be resolved by the physical planner"
        )

    def output_field(self, schema):
        f = self.logical.schema().fields[0]
        return Field("__scalar_subquery", f.dtype, True)

    def display(self):
        return "(scalar subquery)"


# ---------------------------------------------------------------------------
# AST utilities
# ---------------------------------------------------------------------------

from datafusion_distributed_tpu.ops.aggregate import (  # noqa: E402
    _VARIANCE_FUNCS,
)

_AGG_FUNCS = {"sum", "count", "min", "max", "avg"} | _VARIANCE_FUNCS
_WINDOW_ONLY_FUNCS = {"rank", "dense_rank", "row_number"}


def _collect_window_calls(node, out: list) -> None:
    if isinstance(node, ast.FuncCall) and node.over is not None:
        out.append(node)
        _AGG_ID_REGISTRY[id(node)] = node
        return
    if isinstance(node, (ast.ScalarSubquery, ast.Exists, ast.InSubquery)):
        return
    for ch in _ast_children(node):
        _collect_window_calls(ch, out)
_AGG_ID_REGISTRY: dict[int, Any] = {}


def _agg_parts(call: ast.FuncCall):
    arg = call.args[0] if call.args else ast.Star()
    return call.name, arg, call.distinct


def _collect_agg_calls(node, out: list) -> None:
    if isinstance(node, ast.FuncCall) and node.over is not None:
        # a window call is NOT a group aggregate, but its argument and spec
        # may contain ones (sum(sum(x)) over (partition by ...))
        for a in node.args:
            _collect_agg_calls(a, out)
        for p in node.over.partition_by:
            _collect_agg_calls(p, out)
        for o in node.over.order_by:
            _collect_agg_calls(o.expr, out)
        return
    if isinstance(node, ast.FuncCall) and node.name in _AGG_FUNCS:
        out.append(node)
        _AGG_ID_REGISTRY[id(node)] = node
        return  # nested aggregates are invalid SQL
    if isinstance(node, (ast.ScalarSubquery, ast.Exists, ast.InSubquery)):
        return  # subquery aggregates belong to the subquery
    for ch in _ast_children(node):
        _collect_agg_calls(ch, out)


def _ast_children(node) -> list:
    if isinstance(node, ast.Binary):
        return [node.left, node.right]
    if isinstance(node, ast.Unary):
        return [node.child]
    if isinstance(node, ast.Between):
        return [node.expr, node.low, node.high]
    if isinstance(node, ast.InListAst):
        return [node.expr] + list(node.items)
    if isinstance(node, ast.InSubquery):
        return [node.expr]
    if isinstance(node, ast.LikeAst):
        return [node.expr]
    if isinstance(node, ast.IsNullAst):
        return [node.expr]
    if isinstance(node, ast.CaseAst):
        out = []
        if node.operand is not None:
            out.append(node.operand)
        for c, v in node.whens:
            out += [c, v]
        if node.else_ is not None:
            out.append(node.else_)
        return out
    if isinstance(node, ast.CastAst):
        return [node.expr]
    if isinstance(node, ast.ExtractAst):
        return [node.expr]
    if isinstance(node, ast.SubstringAst):
        return [node.expr]
    if isinstance(node, ast.FuncCall):
        return list(node.args)
    return []


def _is_rollup(g) -> bool:
    return isinstance(g, ast.FuncCall) and g.name.lower() == "rollup"


def _ast_substitute(node, fn):
    """Rebuild an AST bottom-up: fn(node) -> replacement or None (recurse).
    Does NOT descend into nested Query/SetOp (their own scopes own their
    identifiers)."""
    import dataclasses as _dc

    if isinstance(node, (ast.Query, ast.SetOp)):
        return node
    rep = fn(node)
    if rep is not None:
        return rep
    if isinstance(node, list):
        return [_ast_substitute(x, fn) for x in node]
    if isinstance(node, tuple):
        return tuple(_ast_substitute(x, fn) for x in node)
    if _dc.is_dataclass(node) and not isinstance(node, type):
        changes = {}
        for fld in _dc.fields(node):
            v = getattr(node, fld.name)
            nv = _ast_substitute(v, fn)
            if nv is not v:
                changes[fld.name] = nv
        return _dc.replace(node, **changes) if changes else node
    return node


def _expand_rollup(q: "ast.Query"):
    """GROUP BY ROLLUP(a, b, ...) -> UNION ALL of one aggregation per prefix
    of the rollup list (finest to grand total). Rolled-away columns become
    typed NULLs (ast.NullOf) and GROUPING(col) folds to 0/1 per arm — the
    standard lowering (the reference gets it from DataFusion's logical
    planner)."""
    import dataclasses as _dc

    plain = [g for g in q.group_by if not _is_rollup(g)]
    roll = next(g for g in q.group_by if _is_rollup(g)).args
    if sum(1 for g in q.group_by if _is_rollup(g)) > 1:
        raise BindError("multiple ROLLUPs in one GROUP BY")

    arms = []
    for k in range(len(roll), -1, -1):
        dropped = {
            i.name.lower() for i in roll[k:] if isinstance(i, ast.Ident)
        }

        def fn(node, dropped=dropped):
            if isinstance(node, ast.FuncCall) and node.name.lower() == (
                "grouping"
            ):
                arg = node.args[0]
                flag = 1 if (
                    isinstance(arg, ast.Ident) and arg.name.lower() in dropped
                ) else 0
                return ast.NumberLit(flag)
            if isinstance(node, ast.Ident) and node.name.lower() in dropped:
                return ast.NullOf(node)
            return None

        arm = _dc.replace(
            q,
            select_items=_ast_substitute(q.select_items, fn),
            group_by=plain + list(roll[:k]),
            having=_ast_substitute(q.having, fn) if q.having else None,
            order_by=[],
            limit=None,
            offset=None,
            ctes=[],
        )
        arms.append(arm)

    combined = arms[0]
    for arm in arms[1:]:
        combined = ast.SetOp("union", True, combined, arm)

    def order_fn(node):
        # ORDER BY applies to the union result, where the arm is no longer
        # known statically; GROUPING(col) is recovered per row as
        # `CASE WHEN col IS NULL THEN 1 ELSE 0 END` (exact whenever the
        # group column itself is non-null, which holds for the rollup
        # dimensions in the TPC-DS suite).
        if isinstance(node, ast.FuncCall) and node.name.lower() == "grouping":
            return ast.CaseAst(
                None,
                [(ast.IsNullAst(node.args[0], False), ast.NumberLit(1))],
                ast.NumberLit(0),
            )
        return None

    combined.order_by = _ast_substitute(list(q.order_by), order_fn)
    combined.limit = q.limit
    combined.offset = q.offset
    combined.ctes = list(q.ctes)
    return combined


def _contains_subquery(node) -> bool:
    if isinstance(node, (ast.ScalarSubquery, ast.Exists, ast.InSubquery)):
        return True
    if isinstance(node, ast.Unary) and node.op == "not":
        return _contains_subquery(node.child)
    return any(_contains_subquery(ch) for ch in _ast_children(node))


def _common_or_conjuncts(node: ast.Binary) -> list:
    """Conjuncts present (by fingerprint) in every branch of an OR tree."""

    def branches(n):
        if isinstance(n, ast.Binary) and n.op == "or":
            return branches(n.left) + branches(n.right)
        return [n]

    bs = branches(node)
    if len(bs) < 2:
        return []
    sets = []
    by_fp: dict[str, Any] = {}
    for b in bs:
        cs = _split_conjuncts(b)
        fps = set()
        for c in cs:
            fp = _ast_fingerprint(c)
            fps.add(fp)
            by_fp.setdefault(fp, c)
        sets.append(fps)
    common = set.intersection(*sets)
    return [by_fp[fp] for fp in sorted(common)]


def _hoist_common_or(c) -> list:
    """OR whose every branch repeats the same conjuncts ->
    [common..., OR(branches stripped of them)] — an EQUIVALENT rewrite
    (unlike _common_or_conjuncts, which only surfaces the implied
    conjuncts). TPC-DS q41 hides its correlation this way:
    `(corr AND colorsA) OR (corr AND colorsB)`."""
    if not (isinstance(c, ast.Binary) and c.op == "or"):
        return [c]
    common = _common_or_conjuncts(c)
    if not common:
        return [c]
    common_fps = {_ast_fingerprint(x) for x in common}

    def branches(n):
        if isinstance(n, ast.Binary) and n.op == "or":
            return branches(n.left) + branches(n.right)
        return [n]

    stripped = []
    for b in branches(c):
        rest = [
            x for x in _split_conjuncts(b)
            if _ast_fingerprint(x) not in common_fps
        ]
        if not rest:
            # one branch reduces to TRUE -> the whole OR is implied by the
            # common conjuncts
            return list(common)
        stripped.append(_join_conjuncts(rest))
    out = stripped[0]
    for b in stripped[1:]:
        out = ast.Binary("or", out, b)
    return list(common) + [out]


def _sort_fetch(q) -> "int | None":
    """Top-k bound for a sort feeding LIMIT/OFFSET: limit+offset rows."""
    if q.limit is None:
        return None
    return q.limit + (q.offset or 0)


def _split_conjuncts(node) -> list:
    if isinstance(node, ast.Binary) and node.op == "and":
        return _split_conjuncts(node.left) + _split_conjuncts(node.right)
    return [node]


def _join_conjuncts(conjuncts: list):
    if not conjuncts:
        return None
    out = conjuncts[0]
    for c in conjuncts[1:]:
        out = ast.Binary("and", out, c)
    return out


def _has_aggregates(q: ast.Query) -> bool:
    out: list = []
    for item in q.select_items:
        _collect_agg_calls(item.expr, out)
    return bool(out) or bool(q.group_by)


def _ast_fingerprint(node) -> str:
    """Structural fingerprint for matching GROUP BY exprs to SELECT exprs."""
    if isinstance(node, ast.Ident):
        return f"id:{node.qualifier or ''}.{node.name}"
    if isinstance(node, ast.NumberLit):
        return f"n:{node.value}"
    if isinstance(node, ast.StringLit):
        return f"s:{node.value}"
    if isinstance(node, ast.DateLit):
        return f"d:{node.days}"
    if isinstance(node, ast.FuncCall):
        args = ",".join(_ast_fingerprint(a) for a in node.args)
        return f"f:{node.name}({args}){'D' if node.distinct else ''}"
    if isinstance(node, ast.Star):
        return f"*:{node.qualifier or ''}"
    parts = ",".join(_ast_fingerprint(c) for c in _ast_children(node))
    op = getattr(node, "op", "")
    extra = ""
    if isinstance(node, ast.LikeAst):
        extra = f":{node.pattern}:{node.negated}"
    if isinstance(node, ast.CastAst):
        extra = f":{node.type_name}"
    if isinstance(node, ast.ExtractAst):
        extra = f":{node.part}"
    return f"{type(node).__name__}:{op}{extra}({parts})"


def _display_name(e, idx: int) -> str:
    if isinstance(e, ast.Ident):
        return e.name
    return f"col{idx}"


def _literal_expr(v):
    if v is None:
        # untyped NULL: the type comes from context (set-op peer, CASE arm,
        # comparison partner) via _promote's NULL rule
        return pe.Literal(None, DataType.NULL)
    if isinstance(v, bool):
        return pe.Literal(v, DataType.BOOL)
    if isinstance(v, int):
        return pe.Literal(v, DataType.INT64)
    return pe.Literal(float(v), DataType.FLOAT64)


def _cast_type(name: str) -> DataType:
    name = name.strip().lower()
    mapping = {
        "int": DataType.INT32,
        "integer": DataType.INT32,
        "bigint": DataType.INT64,
        "smallint": DataType.INT32,
        "double": DataType.FLOAT64,
        "double precision": DataType.FLOAT64,
        "float": DataType.FLOAT32,
        "real": DataType.FLOAT32,
        "decimal": DataType.FLOAT64,
        "numeric": DataType.FLOAT64,
        "date": DataType.DATE32,
        "boolean": DataType.BOOL,
        "varchar": DataType.STRING,
        "char": DataType.STRING,
        "text": DataType.STRING,
        "string": DataType.STRING,
    }
    if name in mapping:
        return mapping[name]
    raise BindError(f"unsupported cast type {name!r}")


def _fold_date_arith(e: ast.Binary):
    """Fold DATE +/- INTERVAL into a DateLit (TPC-H parameterized dates)."""
    if e.op not in ("+", "-"):
        return None
    l, r = e.left, e.right
    if isinstance(l, ast.DateLit) and isinstance(r, ast.IntervalLit):
        sign = 1 if e.op == "+" else -1
        days = _shift_date(l.days, sign * r.months, sign * r.days)
        return pe.Literal(days, DataType.DATE32)
    if isinstance(l, ast.IntervalLit) and isinstance(r, ast.DateLit) and e.op == "+":
        days = _shift_date(r.days, l.months, l.days)
        return pe.Literal(days, DataType.DATE32)
    return None


def _as_decimal(node):
    """NumberLit (or +/-/*// tree of them) -> decimal.Decimal, else None."""
    import decimal

    if isinstance(node, ast.NumberLit):
        if node.raw is not None:
            return decimal.Decimal(node.raw)
        if isinstance(node.value, int):
            return decimal.Decimal(node.value)
        return None
    if isinstance(node, ast.Unary) and node.op == "-":
        d = _as_decimal(node.child)
        return -d if d is not None else None
    if isinstance(node, ast.Binary) and node.op in ("+", "-", "*", "/"):
        l = _as_decimal(node.left)
        r = _as_decimal(node.right)
        if l is None or r is None:
            return None
        if node.op == "+":
            return l + r
        if node.op == "-":
            return l - r
        if node.op == "*":
            return l * r
        if r == 0:
            return None
        return l / r


def _fold_decimal_arith(e: ast.Binary):
    if e.op not in ("+", "-", "*", "/"):
        return None
    if not (
        isinstance(e.left, (ast.NumberLit, ast.Binary, ast.Unary))
        and isinstance(e.right, (ast.NumberLit, ast.Binary, ast.Unary))
    ):
        return None
    d = _as_decimal(e)
    if d is None:
        return None
    if d == d.to_integral_value() and "." not in str(d):
        return pe.Literal(int(d), DataType.INT64)
    return pe.Literal(float(d), DataType.FLOAT64)


def _shift_date(epoch_days: int, months: int, days: int) -> int:
    import datetime

    d = datetime.date(1970, 1, 1) + datetime.timedelta(days=epoch_days)
    if months:
        total = d.year * 12 + (d.month - 1) + months
        y, m = divmod(total, 12)
        import calendar

        day = min(d.day, calendar.monthrange(y, m + 1)[1])
        d = datetime.date(y, m + 1, day)
    d = d + datetime.timedelta(days=days)
    return (d - datetime.date(1970, 1, 1)).days


def _collect_col_names(exprs) -> list[str]:
    out: list[str] = []

    def walk(x):
        if isinstance(x, pe.Col):
            out.append(x.name)
        for c in x.children():
            walk(c)

    for e in exprs:
        walk(e)
    return out


def _project_through(plan: LogicalPlan, exprs) -> LogicalPlan:
    """Append columns to a plan's output by re-projecting through its top
    projection (used to expose correlation key columns of a subquery)."""
    if isinstance(plan, LProject):
        have = {n for _, n in plan.exprs}
        extra = []
        cs = plan.child.schema()
        for e, n in exprs:
            if n not in have:
                extra.append((e, n))
        return LProject(plan.exprs + extra, plan.child)
    return LProject(exprs, plan)
