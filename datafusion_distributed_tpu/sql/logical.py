"""SQL binder (AST -> resolved logical tree).

The reference gets logical planning from DataFusion (SURVEY.md L0). This is an
original binder covering what the TPC suites need:

- name resolution over qualified scopes (columns get flat names
  ``alias.column`` so self-joins like TPC-H q21's lineitem l1/l2/l3 stay
  unambiguous all the way into the physical Table),
- implicit comma joins: WHERE conjuncts are classified into single-relation
  filters (pushed down), equi-join edges (drive a greedy left-deep join
  order), and residual post-join filters (`binder_joins.py`),
- aggregate extraction (SELECT/HAVING/ORDER BY aggregate calls become
  LAggregate outputs; COUNT(DISTINCT x) rewrites to a two-level aggregate),
- subquery handling (`binder_subqueries.py`): uncorrelated scalar subqueries
  become lazily-executed scalar expressions; correlated scalar-aggregate
  subqueries decorrelate into GROUP BY + LEFT JOIN (TPC-H q2/q17/q20 shape);
  [NOT] EXISTS and [NOT] IN become semi/anti joins with optional residual
  predicates (q4/q21/q22).

The module split (logical plan nodes in `lplan.py`, scopes in `scope.py`,
AST helpers in `ast_utils.py`, join ordering and decorrelation as binder
mixins) keeps each concern independently reviewable; this module re-exports
everything so `sql.logical` remains the single public entry point.
"""

from __future__ import annotations

from typing import Callable, Optional

from datafusion_distributed_tpu.plan import expressions as pe
from datafusion_distributed_tpu.schema import DataType, Field, Schema
from datafusion_distributed_tpu.sql import parser as ast
from datafusion_distributed_tpu.sql.ast_utils import (  # noqa: F401
    _AGG_FUNCS,
    _AGG_ID_REGISTRY,
    _WINDOW_ONLY_FUNCS,
    _agg_parts,
    _as_decimal,
    _ast_children,
    _ast_fingerprint,
    _ast_substitute,
    _cast_type,
    _collect_agg_calls,
    _collect_col_names,
    _collect_window_calls,
    _common_or_conjuncts,
    _contains_subquery,
    _display_name,
    _expand_rollup,
    _fold_date_arith,
    _fold_decimal_arith,
    _has_aggregates,
    _hoist_common_or,
    _is_rollup,
    _join_conjuncts,
    _literal_expr,
    _project_through,
    _shift_date,
    _sort_fetch,
    _split_conjuncts,
)
from datafusion_distributed_tpu.sql.binder_joins import JoinOrderingMixin
from datafusion_distributed_tpu.sql.binder_subqueries import (  # noqa: F401
    ScalarSubqueryExpr,
    SubqueryDecorrelationMixin,
)

# NOTE: _MARK_SEQ deliberately NOT re-exported — rebinding a re-export would
# not affect the mixin's module global; reset it on `sql.binder_subqueries`.
from datafusion_distributed_tpu.sql.lplan import (  # noqa: F401
    AggCall,
    CatalogProtocol,
    LAggregate,
    LDistinct,
    LFilter,
    LJoin,
    LLimit,
    LProject,
    LScan,
    LSetOp,
    LSort,
    LWindow,
    LWindowExpr,
    LogicalPlan,
    _agg_dtype,
    _window_dtype,
)
from datafusion_distributed_tpu.sql.scope import (  # noqa: F401
    BindError,
    OuterRef,
    Scope,
)


class Binder(JoinOrderingMixin, SubqueryDecorrelationMixin):
    def __init__(self, catalog: CatalogProtocol, ctes: Optional[dict] = None):
        self.catalog = catalog
        self.ctes: dict[str, LogicalPlan] = dict(ctes or {})

    # -- public -------------------------------------------------------------
    def bind(self, q) -> LogicalPlan:
        return self._bind_query(q, parent_scope=None)

    # -- query --------------------------------------------------------------
    def _bind_query(self, q, parent_scope: Optional[Scope]) -> LogicalPlan:
        if isinstance(q, ast.SetOp):
            return self._bind_setop(q, parent_scope)
        if q.group_by and any(_is_rollup(g) for g in q.group_by):
            return self._bind_query(_expand_rollup(q), parent_scope)
        saved_ctes = dict(self.ctes)
        for name, sub in q.ctes:
            self.ctes[name] = self._bind_query(sub, parent_scope)
        try:
            return self._bind_select(q, parent_scope)
        finally:
            self.ctes = saved_ctes

    def _bind_setop(self, q: ast.SetOp, parent_scope) -> LogicalPlan:
        saved = dict(self.ctes)
        for name, sub in q.ctes:
            self.ctes[name] = self._bind_query(sub, parent_scope)
        try:
            left = self._bind_query(q.left, parent_scope)
            right = self._bind_query(q.right, parent_scope)
        finally:
            self.ctes = saved
        if len(left.schema()) != len(right.schema()):
            raise BindError("set operation arity mismatch")
        # align right's column names to left's, coercing numeric dtypes to
        # the promoted common type (SQL set-op column typing)
        rs = right.schema()
        ls = left.schema()
        right_exprs = []
        left_casts = []
        for rf, lf in zip(rs.fields, ls.fields):
            re_: pe.PhysicalExpr = pe.Col(rf.name)
            if rf.dtype != lf.dtype:
                common = pe._promote(lf.dtype, rf.dtype)
                if rf.dtype != common:
                    re_ = pe.Cast(re_, common)
                if lf.dtype != common:
                    left_casts.append((lf.name, common))
            right_exprs.append((re_, lf.name))
        right = LProject(right_exprs, right)
        if left_casts:
            need = dict(left_casts)
            left = LProject(
                [(pe.Cast(pe.Col(f.name), need[f.name])
                  if f.name in need else pe.Col(f.name), f.name)
                 for f in ls.fields],
                left,
            )
        plan: LogicalPlan = LSetOp(q.op, q.all, left, right)
        if q.op == "union" and not q.all:
            plan = LDistinct(plan)
        if q.order_by:
            scope = Scope([("", plan.schema())])
            keys = []
            for o in q.order_by:
                if isinstance(o.expr, ast.NumberLit) and isinstance(
                    o.expr.value, int
                ):
                    e: pe.PhysicalExpr = pe.Col(
                        plan.schema().fields[o.expr.value - 1].name
                    )
                else:
                    e = self._bind_expr(o.expr, scope, None)
                keys.append((e, o.ascending, o.nulls_first))
            plan = LSort(keys, plan, fetch=_sort_fetch(q))
        if q.limit is not None or q.offset is not None:
            plan = LLimit(plan, q.limit, q.offset or 0)
        return plan

    # -- FROM / joins ---------------------------------------------------------
    def _bind_relation(self, ref, parent_scope) -> tuple[LogicalPlan, str, Schema]:
        """-> (plan with flat names, alias, original-name schema)."""
        if isinstance(ref, ast.SubqueryRef):
            sub = self._bind_query(ref.query, parent_scope)
            names = [f.name.split(".")[-1] for f in sub.schema().fields]
            if ref.column_aliases:
                if len(ref.column_aliases) != len(names):
                    raise BindError("derived table column alias arity mismatch")
                names = list(ref.column_aliases)
            orig = Schema(
                [Field(n, f.dtype, f.nullable)
                 for n, f in zip(names, sub.schema().fields)]
            )
            flat = LProject(
                [(pe.Col(f.name), f"{ref.alias}.{n}")
                 for n, f in zip(names, sub.schema().fields)],
                sub,
            )
            return flat, ref.alias, orig
        assert isinstance(ref, ast.TableRef)
        alias = ref.alias or ref.name
        if ref.name in self.ctes:
            sub = self.ctes[ref.name]
            names = [f.name.split(".")[-1] for f in sub.schema().fields]
            orig = Schema(
                [Field(n, f.dtype, f.nullable)
                 for n, f in zip(names, sub.schema().fields)]
            )
            flat = LProject(
                [(pe.Col(f.name), f"{alias}.{n}")
                 for n, f in zip(names, sub.schema().fields)],
                sub,
            )
            return flat, alias, orig
        if not self.catalog.has_table(ref.name):
            raise BindError(f"unknown table {ref.name!r}")
        schema = self.catalog.table_schema(ref.name)
        flat_schema = Schema(
            [Field(f"{alias}.{f.name}", f.dtype, f.nullable) for f in schema.fields]
        )
        return LScan(ref.name, alias, schema, flat_schema), alias, schema

    # -- SELECT ---------------------------------------------------------------
    def _bind_select(self, q: ast.Query, parent_scope) -> LogicalPlan:
        # 1. relations. A from_ref group with outer joins is folded in its
        # written order into a single "unit" (outer joins are not freely
        # reorderable); inner/cross-only groups flatten into the greedy pool.
        relations: list[tuple[LogicalPlan, str, Schema]] = []  # (plan, alias, orig)
        groups: list = []  # ("rel", alias) | ("outer", base_alias, [(jc, ralias)])
        inner_on_conjuncts: list = []
        if not q.from_refs:
            raise BindError("SELECT without FROM is not supported yet")
        protected: set = set()  # null-supplying sides: no WHERE pushdown
        for base, joins in q.from_refs:
            triple = self._bind_relation(base, parent_scope)
            relations.append(triple)
            if not joins:
                groups.append(("rel", triple[1]))
                continue
            kinds = {jc.kind for jc in joins}
            rtriples = []
            for jc in joins:
                rt = self._bind_relation(jc.right, parent_scope)
                relations.append(rt)
                rtriples.append(rt)
            if kinds <= {"inner", "cross"}:
                groups.append(("rel", triple[1]))
                for jc, rt in zip(joins, rtriples):
                    groups.append(("rel", rt[1]))
                    if jc.on is not None:
                        inner_on_conjuncts.extend(_split_conjuncts(jc.on))
            else:
                groups.append(
                    ("outer", triple[1], list(zip(joins, [t[1] for t in rtriples])))
                )
                for jc, rt in zip(joins, rtriples):
                    if jc.kind == "left":
                        protected.add(rt[1])
                    elif jc.kind == "right":
                        protected.add(triple[1])
                    elif jc.kind == "full":
                        protected.add(rt[1])
                        protected.add(triple[1])

        scope = Scope([(alias, orig) for _, alias, orig in relations],
                      parent=parent_scope)
        outer_refs: list[OuterRef] = []

        # 2. classify WHERE conjuncts (+ inner-join ON conjuncts)
        conjuncts = _split_conjuncts(q.where) if q.where is not None else []
        conjuncts = conjuncts + inner_on_conjuncts

        per_rel: dict[str, list] = {alias: [] for _, alias, _ in relations}
        equi_edges: list = []  # (alias_a, expr_a, alias_b, expr_b)
        residuals: list = []  # bound later against joined scope
        subquery_preds: list = []  # AST conjuncts containing subqueries

        # q19 shape: a top-level OR where every branch repeats the same
        # equi-join conjunct — hoist the common conjuncts so the pair of
        # relations joins hash-wise instead of as a cross product.
        hoisted: list = []
        for c in conjuncts:
            if isinstance(c, ast.Binary) and c.op == "or":
                common = _common_or_conjuncts(c)
                hoisted.extend(common)
        conjuncts = conjuncts + hoisted

        for c in conjuncts:
            if _contains_subquery(c):
                subquery_preds.append(c)
                continue
            aliases = self._aliases_of(c, scope)
            if len(aliases) == 1 and not (aliases & protected):
                per_rel[next(iter(aliases))].append(c)
            elif (
                len(aliases) == 2
                and isinstance(c, ast.Binary)
                and c.op == "=="
                and not (aliases & protected)
            ):
                la = self._aliases_of(c.left, scope)
                ra = self._aliases_of(c.right, scope)
                if len(la) == 1 and len(ra) == 1 and la != ra:
                    equi_edges.append((next(iter(la)), c.left,
                                       next(iter(ra)), c.right))
                else:
                    residuals.append(c)
            else:
                residuals.append(c)

        # 3. apply per-relation filters
        rel_plans: dict[str, LogicalPlan] = {}
        rel_rows: dict[str, int] = {}
        for plan, alias, orig in relations:
            rel_rows[alias] = self._relation_rows(alias, plan)
            for c in per_rel[alias]:
                pred = self._bind_expr(c, scope, outer_refs)
                plan = LFilter(pred, plan)
                rel_rows[alias] = max(rel_rows[alias] // 3, 1)
            rel_plans[alias] = plan

        # 3b. fold outer-join groups into unit plans (written order)
        units: list = []  # [plan, alias_set, rows]
        for g in groups:
            if g[0] == "rel":
                alias = g[1]
                units.append([rel_plans[alias], {alias}, rel_rows[alias]])
            else:
                _, base_alias, jpairs = g
                uplan = rel_plans[base_alias]
                ualiases = {base_alias}
                urows = rel_rows[base_alias]
                for jc, ralias in jpairs:
                    uplan = self._fold_explicit_join(
                        uplan, ualiases, jc, ralias, rel_plans[ralias],
                        scope, outer_refs,
                    )
                    ualiases.add(ralias)
                    urows = max(urows, rel_rows[ralias])
                units.append([uplan, ualiases, urows])

        # 4. greedy left-deep join order over units connected by equi edges
        alias_tables = {
            alias: (rplan.table if isinstance(rplan, LScan) else None)
            for rplan, alias, _ in relations
        }
        plan = self._order_joins(units, equi_edges, scope, outer_refs,
                                 alias_tables)

        # 5. residual predicates after joins
        for c in residuals:
            plan = LFilter(self._bind_expr(c, scope, outer_refs), plan)

        # 6. subquery predicates (EXISTS/IN/scalar comparisons)
        for c in subquery_preds:
            plan = self._apply_subquery_pred(c, plan, scope, outer_refs)

        # 7. aggregates
        plan = self._bind_projection_and_aggregates(q, plan, scope, outer_refs)

        if outer_refs and parent_scope is None:
            raise BindError(
                f"unresolved outer references: {[r.flat_name for r in outer_refs]}"
            )
        return plan

    # -- projection & aggregation ------------------------------------------
    def _bind_projection_and_aggregates(self, q: ast.Query, plan, scope,
                                        outer_refs) -> LogicalPlan:
        agg_calls = []
        window_calls = []
        for item in q.select_items:
            _collect_agg_calls(item.expr, agg_calls)
            _collect_window_calls(item.expr, window_calls)
        if q.having is not None:
            _collect_agg_calls(q.having, agg_calls)
        for o in q.order_by:
            _collect_agg_calls(o.expr, agg_calls)
            _collect_window_calls(o.expr, window_calls)

        has_group = bool(q.group_by)
        has_aggs = bool(agg_calls)

        select_aliases = {
            item.alias: item.expr for item in q.select_items if item.alias
        }

        if has_group or has_aggs:
            # group expressions: resolve alias/positional references
            group_asts = []
            for g in q.group_by:
                g = self._resolve_output_ref(g, q.select_items, select_aliases)
                group_asts.append(g)
            groups = []
            for i, g in enumerate(group_asts):
                e = self._bind_expr(g, scope, outer_refs)
                groups.append((e, f"__g{i}"))
            # aggregate calls
            aggs = []
            agg_map: dict[int, str] = {}
            distinct_rewrites = []
            for j, call in enumerate(agg_calls):
                func, arg_ast, distinct = _agg_parts(call)
                name = f"__a{j}"
                if func == "count" and isinstance(arg_ast, ast.Star):
                    aggs.append(AggCall("count_star", None, name))
                else:
                    arg = self._bind_expr(arg_ast, scope, outer_refs)
                    if distinct and func == "count":
                        distinct_rewrites.append((j, arg, name))
                        aggs.append(AggCall("count", arg, name, distinct=True))
                    else:
                        aggs.append(AggCall(func, arg, name))
                agg_map[id(call)] = name
            agg_plan = LAggregate(groups, aggs, plan)

            # post-aggregation scope: group exprs + agg outputs
            group_lookup = {
                _ast_fingerprint(g): f"__g{i}" for i, g in enumerate(group_asts)
            }

            def rebind(e):
                return self._bind_post_agg(
                    e, scope, group_lookup, agg_map, select_aliases
                )

            result: LogicalPlan = agg_plan
            if q.having is not None:
                result = LFilter(rebind(q.having), result)
            self._window_map = {}
            if window_calls:
                result = self._build_windows(window_calls, result, rebind)

            out_exprs = []
            out_names = []
            for idx, item in enumerate(q.select_items):
                if isinstance(item.expr, ast.Star):
                    raise BindError("SELECT * with GROUP BY is not supported")
                name = item.alias or _display_name(item.expr, idx)
                out_exprs.append(rebind(item.expr))
                out_names.append(name)
            # structural fingerprints of select items -> output names
            out_fps = {
                _ast_fingerprint(item.expr): name
                for item, name in zip(q.select_items, out_names)
            }
            proj_exprs = list(zip(out_exprs, out_names))
            sort_keys = []
            hidden: list = []
            if q.order_by:
                for o in q.order_by:
                    e = self._bind_order_expr_agg(
                        o.expr, scope, group_lookup, agg_map, select_aliases,
                        proj_exprs, out_fps,
                    )
                    # keys referencing agg-internal columns must ride through
                    # the projection as hidden columns
                    for cname in _collect_col_names([e]):
                        if cname not in out_names and cname not in (
                            n for _, n in hidden
                        ):
                            hidden.append((pe.Col(cname), cname))
                    sort_keys.append((e, o.ascending, o.nulls_first))
            plan2: LogicalPlan = LProject(proj_exprs + hidden, result)
            if sort_keys:
                plan2 = LSort(sort_keys, plan2, fetch=_sort_fetch(q))
            if hidden:
                plan2 = LProject(
                    [(pe.Col(n), n) for n in out_names], plan2
                )
            if q.distinct:
                plan2 = LDistinct(plan2)
            if q.limit is not None or q.offset is not None:
                plan2 = LLimit(plan2, q.limit, q.offset or 0)
            return plan2

        # no aggregation
        self._window_map = {}
        star_schema = plan.schema()  # pre-window: __wN stays internal
        if window_calls:
            plan = self._build_windows(
                window_calls, plan,
                lambda e: self._bind_expr(e, scope, outer_refs),
            )
        out = []
        for idx, item in enumerate(q.select_items):
            if isinstance(item.expr, ast.Star):
                for f in star_schema.fields:
                    short = f.name.split(".")[-1]
                    if item.expr.qualifier and not f.name.startswith(
                        item.expr.qualifier + "."
                    ):
                        continue
                    out.append((pe.Col(f.name), short))
                continue
            name = item.alias or _display_name(item.expr, idx)
            out.append((self._bind_expr(item.expr, scope, outer_refs), name))
        out_names = [n for _, n in out]
        sort_keys = []
        hidden: list = []
        if q.order_by:
            for o in q.order_by:
                e = self._bind_order_expr_plain(
                    o.expr, scope, outer_refs, out, select_aliases
                )
                # sort keys referencing columns (incl. window __wN) that the
                # projection would drop ride through as hidden columns
                for cname in _collect_col_names([e]):
                    if cname not in out_names and cname not in (
                        n for _, n in hidden
                    ):
                        hidden.append((pe.Col(cname), cname))
                sort_keys.append((e, o.ascending, o.nulls_first))
        result = LProject(out + hidden, plan)
        if sort_keys:
            result = LSort(sort_keys, result, fetch=_sort_fetch(q))
        if hidden:
            result = LProject([(pe.Col(n), n) for n in out_names], result)
        if q.distinct:
            result = LDistinct(result)
        if q.limit is not None or q.offset is not None:
            result = LLimit(result, q.limit, q.offset or 0)
        return result

    def _build_windows(self, window_calls, plan, bind_fn) -> LogicalPlan:
        """Materialize window calls as __wN columns via an LWindow node;
        records id(call) -> name in self._window_map for later rebinding."""
        wexprs = []
        for j, wc in enumerate(window_calls):
            name = f"__w{j}"
            func = wc.name
            if func not in _AGG_FUNCS | _WINDOW_ONLY_FUNCS:
                raise BindError(f"unsupported window function {func}")
            if wc.distinct:
                raise BindError(
                    f"DISTINCT is not supported in window function {func}"
                )
            arg = None
            if func in _AGG_FUNCS:
                if wc.args and isinstance(wc.args[0], ast.Star):
                    func = "count_star"
                elif not wc.args:
                    if func == "count":
                        func = "count_star"
                    else:
                        raise BindError(f"window {func} needs an argument")
                else:
                    arg = bind_fn(wc.args[0])
            partitions = [bind_fn(p) for p in wc.over.partition_by]
            orders = [
                (bind_fn(o.expr), o.ascending, o.nulls_first)
                for o in wc.over.order_by
            ]
            wexprs.append(
                LWindowExpr(func, arg, partitions, orders, name,
                            frame=wc.over.frame)
            )
            self._window_map[id(wc)] = name
        return LWindow(wexprs, plan)

    def _bind_order_by(self, q, plan, bind_fn) -> LogicalPlan:
        keys = []
        for o in q.order_by:
            e = bind_fn(o.expr)
            keys.append((e, o.ascending, o.nulls_first))
        return LSort(keys, plan, fetch=_sort_fetch(q))

    def _bind_order_expr_plain(self, e, scope, outer_refs, out_exprs,
                               select_aliases):
        # positional reference
        if isinstance(e, ast.NumberLit) and isinstance(e.value, int):
            expr, name = out_exprs[e.value - 1]
            return pe.Col(name)
        if isinstance(e, ast.Ident) and e.qualifier is None:
            for expr, name in out_exprs:
                if name == e.name:
                    return pe.Col(name)
        return self._bind_expr(e, scope, outer_refs)

    def _bind_order_expr_agg(self, e, scope, group_lookup, agg_map,
                             select_aliases, out_exprs, out_fps):
        if isinstance(e, ast.NumberLit) and isinstance(e.value, int):
            _, name = out_exprs[e.value - 1]
            return pe.Col(name)
        if isinstance(e, ast.Ident) and e.qualifier is None:
            for _, name in out_exprs:
                if name == e.name:
                    return pe.Col(name)
        # structural match against a select item (ORDER BY t.k when SELECT
        # t.k ... GROUP BY t.k)
        fp = _ast_fingerprint(e)
        if fp in out_fps:
            return pe.Col(out_fps[fp])
        return self._bind_post_agg(e, scope, group_lookup, agg_map,
                                   select_aliases)

    def _resolve_output_ref(self, g, select_items, select_aliases):
        """GROUP BY may reference select aliases or positions."""
        if isinstance(g, ast.NumberLit) and isinstance(g.value, int):
            return select_items[g.value - 1].expr
        if isinstance(g, ast.Ident) and g.qualifier is None and g.name in (
            select_aliases
        ):
            return select_aliases[g.name]
        return g

    def _bind_post_agg(self, e, scope, group_lookup, agg_map, select_aliases):
        """Bind an expression over the aggregate's output: aggregate calls map
        to their output columns, group-expr subtrees map to group columns."""
        if isinstance(e, ast.NullOf):
            _, field, _ = scope.resolve(e.ident)
            return pe.Literal(None, field.dtype)
        wm = getattr(self, "_window_map", {})
        if id(e) in wm:
            return pe.Col(wm[id(e)])
        fp = _ast_fingerprint(e)
        if fp in group_lookup:
            return pe.Col(group_lookup[fp])
        if id(e) in agg_map:
            return pe.Col(agg_map[id(e)])
        # the same aggregate may appear in several clauses as distinct AST
        # objects: match structurally
        matched = self._match_agg_by_fingerprint(e, agg_map)
        if matched is not None:
            return pe.Col(matched)
        if isinstance(e, ast.Ident) and e.qualifier is None and e.name in (
            select_aliases
        ):
            return self._bind_post_agg(
                select_aliases[e.name], scope, group_lookup, agg_map,
                select_aliases,
            )
        # recurse structurally
        return self._rebind_children(
            e, lambda ch: self._bind_post_agg(ch, scope, group_lookup, agg_map,
                                              select_aliases)
        )

    def _match_agg_by_fingerprint(self, e, agg_map):
        if not (isinstance(e, ast.FuncCall) and e.name in _AGG_FUNCS):
            return None
        fp = _ast_fingerprint(e)
        for call_id, name in agg_map.items():
            call = _AGG_ID_REGISTRY.get(call_id)
            if call is not None and _ast_fingerprint(call) == fp:
                return name
        return None

    def _rebind_children(self, e, f: Callable):
        """Rebuild an AST expression bottom-up into a PhysicalExpr, using f for
        sub-expressions. Leaf idents must resolve via group/agg maps (handled
        in f); anything else binds as scalar structure."""
        if isinstance(e, ast.NumberLit):
            return _literal_expr(e.value)
        if isinstance(e, ast.StringLit):
            return pe.Literal(e.value, DataType.STRING)
        if isinstance(e, ast.DateLit):
            return pe.Literal(e.days, DataType.DATE32)
        if isinstance(e, ast.Binary):
            if e.op in ("and", "or"):
                return pe.BooleanOp(e.op, f(e.left), f(e.right))
            return pe.BinaryOp(e.op, f(e.left), f(e.right))
        if isinstance(e, ast.Unary):
            if e.op == "not":
                return pe.Not(f(e.child))
            return pe.Negate(f(e.child))
        if isinstance(e, ast.CaseAst):
            branches = tuple((f(c), f(v)) for c, v in e.whens)
            return pe.Case(branches, f(e.else_) if e.else_ else None)
        if isinstance(e, ast.Between):
            lo = pe.BinaryOp(">=", f(e.expr), f(e.low))
            hi = pe.BinaryOp("<=", f(e.expr), f(e.high))
            both = pe.BooleanOp("and", lo, hi)
            return pe.Not(both) if e.negated else both
        if isinstance(e, ast.CastAst):
            to = _cast_type(e.type_name)
            if isinstance(e.expr, ast.StringLit) and to == DataType.DATE32:
                return pe.Literal(pe.parse_date(e.expr.value), DataType.DATE32)
            return pe.Cast(f(e.expr), to)
        if isinstance(e, ast.ScalarSubquery):
            # e.g. HAVING sum(x) > (select ... ) — TPC-H q11
            sub = Binder(self.catalog, self.ctes)._bind_query(e.query, None)
            return ScalarSubqueryExpr(sub)
        if isinstance(e, ast.InListAst):
            return self._bind_in_list(e, f)
        if isinstance(e, ast.LikeAst):
            return pe.Like(f(e.expr), e.pattern, e.negated)
        if isinstance(e, ast.IsNullAst):
            return pe.IsNull(f(e.expr), e.negated)
        if isinstance(e, ast.ExtractAst):
            return pe.Extract(e.part, f(e.expr))
        if isinstance(e, ast.SubstringAst):
            start = e.start.value if isinstance(e.start, ast.NumberLit) else None
            length = (
                e.length.value if isinstance(e.length, ast.NumberLit) else None
            )
            if start is None:
                raise BindError("SUBSTRING start must be a literal")
            return pe.Substring(f(e.expr), start, length)
        if isinstance(e, ast.FuncCall) and e.over is None:
            bound = self._bind_scalar_func(e, f)
            if bound is not None:
                return bound
        raise BindError(
            f"cannot rebind {type(e).__name__} over aggregate output"
        )

    def _bind_in_list(self, e: ast.InListAst, f) -> pe.PhysicalExpr:
        values = []
        for item in e.items:
            if isinstance(item, ast.StringLit):
                values.append(item.value)
            elif isinstance(item, ast.NumberLit):
                values.append(item.value)
            elif isinstance(item, ast.DateLit):
                values.append(item.days)
            else:
                d = _as_decimal(item)
                if d is None:
                    raise BindError("IN list items must be literals")
                values.append(int(d) if d == int(d) else float(d))
        return pe.InList(f(e.expr), tuple(values), e.negated)

    def _bind_scalar_func(self, e, f) -> Optional[pe.PhysicalExpr]:
        """Bind a scalar FuncCall using ``f`` for its children; None when
        the name is unknown (callers raise their own error)."""
        name = e.name.lower()
        if name == "coalesce":
            return pe.Coalesce(tuple(f(a) for a in e.args))
        if name == "abs":
            return pe.Abs(f(e.args[0]))
        if name == "round":
            digits = 0
            if len(e.args) > 1 and isinstance(e.args[1], ast.NumberLit):
                digits = int(e.args[1].value)
            return pe.Round(f(e.args[0]), digits)
        if name in ("upper", "lower"):
            return pe.StringCase(f(e.args[0]), name == "upper")
        if name == "concat":
            return pe.ConcatStrings(tuple(f(a) for a in e.args))
        if name in ("length", "char_length", "character_length"):
            return pe.StrLength(f(e.args[0]))
        if name == "regexp_replace":
            pat = e.args[1]
            rep = e.args[2]
            if not (isinstance(pat, ast.StringLit)
                    and isinstance(rep, ast.StringLit)):
                raise BindError(
                    "REGEXP_REPLACE pattern/replacement must be literals"
                )
            return pe.RegexpReplace(f(e.args[0]), pat.value, rep.value)
        if name in ("to_timestamp_seconds", "to_timestamp"):
            # epoch-seconds integers ARE the timestamp representation here
            return f(e.args[0])
        if name == "date_trunc":
            unit = e.args[0]
            if not isinstance(unit, ast.StringLit):
                raise BindError("DATE_TRUNC unit must be a string literal")
            return pe.DateTrunc(unit.value, f(e.args[1]))
        return None

    # -- expression binding ---------------------------------------------------
    def _bind_expr(self, e, scope: Scope, outer_refs) -> pe.PhysicalExpr:
        if isinstance(e, ast.PreBound):
            return e.expr
        if isinstance(e, ast.NullOf):
            _, field, _ = scope.resolve(e.ident)
            return pe.Literal(None, field.dtype)
        if isinstance(e, ast.Ident):
            flat, field, depth = scope.resolve(e)
            if depth > 0:
                if outer_refs is None:
                    raise BindError(f"unexpected outer reference {e.key()}")
                outer_refs.append(OuterRef(flat, field))
            return pe.Col(flat)
        if isinstance(e, ast.NumberLit):
            return _literal_expr(e.value)
        if isinstance(e, ast.StringLit):
            return pe.Literal(e.value, DataType.STRING)
        if isinstance(e, ast.DateLit):
            return pe.Literal(e.days, DataType.DATE32)
        if isinstance(e, ast.IntervalLit):
            raise BindError("bare interval literal outside date arithmetic")
        if isinstance(e, ast.Binary):
            if e.op in ("and", "or"):
                return pe.BooleanOp(
                    e.op,
                    self._bind_expr(e.left, scope, outer_refs),
                    self._bind_expr(e.right, scope, outer_refs),
                )
            # date +/- interval folding
            folded = _fold_date_arith(e)
            if folded is not None:
                return folded if isinstance(folded, pe.PhysicalExpr) else (
                    self._bind_expr(folded, scope, outer_refs)
                )
            # column +/- INTERVAL 'n' DAY: date32 is integer days, so the
            # interval becomes a plain int32 addend (months would need
            # calendar arithmetic per row; unsupported on columns)
            if isinstance(e.right, ast.IntervalLit) and e.op in ("+", "-"):
                if e.right.months != 0:
                    raise BindError(
                        "month intervals on date columns are not supported"
                    )
                base = self._bind_expr(e.left, scope, outer_refs)
                delta = e.right.days if e.op == "+" else -e.right.days
                return pe.BinaryOp(
                    "+", base, pe.Literal(delta, DataType.INT32)
                )
            # exact decimal folding of literal arithmetic: SQL decimals make
            # `.06 - 0.01` exactly 0.05; float64 would give 0.049999...
            dec = _fold_decimal_arith(e)
            if dec is not None:
                return dec
            return pe.BinaryOp(
                e.op,
                self._bind_expr(e.left, scope, outer_refs),
                self._bind_expr(e.right, scope, outer_refs),
            )
        if isinstance(e, ast.Unary):
            if e.op == "not":
                return pe.Not(self._bind_expr(e.child, scope, outer_refs))
            return pe.Negate(self._bind_expr(e.child, scope, outer_refs))
        if isinstance(e, ast.Between):
            x = self._bind_expr(e.expr, scope, outer_refs)
            lo = pe.BinaryOp(">=", x, self._bind_expr(e.low, scope, outer_refs))
            hi = pe.BinaryOp("<=", x, self._bind_expr(e.high, scope, outer_refs))
            both = pe.BooleanOp("and", lo, hi)
            return pe.Not(both) if e.negated else both
        if isinstance(e, ast.InListAst):
            return self._bind_in_list(
                e, lambda a: self._bind_expr(a, scope, outer_refs)
            )
        if isinstance(e, ast.LikeAst):
            return pe.Like(
                self._bind_expr(e.expr, scope, outer_refs), e.pattern, e.negated
            )
        if isinstance(e, ast.IsNullAst):
            return pe.IsNull(
                self._bind_expr(e.expr, scope, outer_refs), e.negated
            )
        if isinstance(e, ast.CaseAst):
            if e.operand is not None:
                operand = self._bind_expr(e.operand, scope, outer_refs)
                branches = tuple(
                    (
                        pe.BinaryOp(
                            "==", operand, self._bind_expr(c, scope, outer_refs)
                        ),
                        self._bind_expr(v, scope, outer_refs),
                    )
                    for c, v in e.whens
                )
            else:
                branches = tuple(
                    (
                        self._bind_expr(c, scope, outer_refs),
                        self._bind_expr(v, scope, outer_refs),
                    )
                    for c, v in e.whens
                )
            otherwise = (
                self._bind_expr(e.else_, scope, outer_refs) if e.else_ else None
            )
            return pe.Case(branches, otherwise)
        if isinstance(e, ast.CastAst):
            to = _cast_type(e.type_name)
            if isinstance(e.expr, ast.StringLit) and to == DataType.DATE32:
                return pe.Literal(pe.parse_date(e.expr.value), DataType.DATE32)
            return pe.Cast(self._bind_expr(e.expr, scope, outer_refs), to)
        if isinstance(e, ast.ExtractAst):
            return pe.Extract(
                e.part, self._bind_expr(e.expr, scope, outer_refs)
            )
        if isinstance(e, ast.SubstringAst):
            start = e.start.value if isinstance(e.start, ast.NumberLit) else None
            length = (
                e.length.value if isinstance(e.length, ast.NumberLit) else None
            )
            if start is None:
                raise BindError("SUBSTRING start must be a literal")
            return pe.Substring(
                self._bind_expr(e.expr, scope, outer_refs), start, length
            )
        if isinstance(e, ast.ScalarSubquery):
            sub = Binder(self.catalog, self.ctes)._bind_query(e.query, None)
            return ScalarSubqueryExpr(sub)
        if isinstance(e, ast.FuncCall):
            wm = getattr(self, "_window_map", {})
            if id(e) in wm:
                return pe.Col(wm[id(e)])
            if e.over is not None:
                raise BindError(
                    f"window function {e.name} not allowed in this context"
                )
            if e.name in _AGG_FUNCS:
                raise BindError(
                    f"aggregate {e.name} not allowed in this context"
                )
            bound = self._bind_scalar_func(
                e, lambda a: self._bind_expr(a, scope, outer_refs)
            )
            if bound is not None:
                return bound
            raise BindError(f"unknown function {e.name}")
        raise BindError(f"cannot bind {type(e).__name__}")

