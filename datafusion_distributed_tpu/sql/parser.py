"""SQL lexer + recursive-descent parser.

The reference leans on DataFusion's sqlparser-rs for SQL (SURVEY.md L0); this
is an original parser covering the dialect the TPC-H / TPC-DS / ClickBench
suites exercise: SELECT with joins (implicit comma joins and explicit
[INNER|LEFT|RIGHT|FULL] JOIN ... ON), WHERE/GROUP BY/HAVING/ORDER BY/LIMIT,
WITH CTEs, scalar/EXISTS/IN subqueries, BETWEEN/LIKE/CASE/CAST/EXTRACT/
SUBSTRING, date/interval literals and UNION [ALL].

Output is a small AST (dataclasses below); semantic analysis lives in
sql/logical.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------


@dataclass
class Ident:
    name: str
    qualifier: Optional[str] = None

    def key(self) -> str:
        return f"{self.qualifier}.{self.name}" if self.qualifier else self.name


@dataclass
class NumberLit:
    value: Any  # int or float
    raw: Optional[str] = None  # original text, for exact decimal folding


@dataclass
class StringLit:
    value: str


@dataclass
class DateLit:
    days: int  # days since epoch


@dataclass
class IntervalLit:
    months: int
    days: int


@dataclass
class Star:
    qualifier: Optional[str] = None


@dataclass
class WindowSpec:
    partition_by: list
    order_by: list  # [OrderItem]
    frame: str = "range"  # "range" (default, peers share) | "rows"


@dataclass
class FuncCall:
    name: str
    args: list
    distinct: bool = False
    over: Optional["WindowSpec"] = None  # window function when set


@dataclass
class Binary:
    op: str
    left: Any
    right: Any


@dataclass
class Unary:
    op: str  # "-" | "not" | "+"
    child: Any


@dataclass
class Between:
    expr: Any
    low: Any
    high: Any
    negated: bool = False


@dataclass
class InListAst:
    expr: Any
    items: list
    negated: bool = False


@dataclass
class InSubquery:
    expr: Any
    query: "Query"
    negated: bool = False


@dataclass
class Exists:
    query: "Query"
    negated: bool = False


@dataclass
class ScalarSubquery:
    query: "Query"


@dataclass
class LikeAst:
    expr: Any
    pattern: str
    negated: bool = False


@dataclass
class IsNullAst:
    expr: Any
    negated: bool = False


@dataclass
class CaseAst:
    operand: Optional[Any]
    whens: list  # [(cond, value)]
    else_: Optional[Any]


@dataclass
class CastAst:
    expr: Any
    type_name: str


@dataclass
class ExtractAst:
    part: str  # "year" | "month" | "day"
    expr: Any


@dataclass
class SubstringAst:
    expr: Any
    start: Any
    length: Optional[Any]


@dataclass
class PreBound:
    """An already-bound PhysicalExpr spliced into an AST during binder
    rewrites (scalar-subquery extraction); never produced by the parser."""

    expr: Any


@dataclass
class NullOf:
    """Typed NULL standing in for a rolled-away group column (produced by
    the binder's ROLLUP expansion, never by the parser): binds to a NULL
    literal with the referenced column's dtype."""

    ident: "Ident"


@dataclass
class SelectItem:
    expr: Any
    alias: Optional[str] = None


@dataclass
class TableRef:
    name: str
    alias: Optional[str] = None


@dataclass
class SubqueryRef:
    query: "Query"
    alias: str
    column_aliases: Optional[list] = None


@dataclass
class JoinClause:
    right: Any  # TableRef | SubqueryRef
    kind: str  # inner|left|right|full|cross
    on: Optional[Any]


@dataclass
class OrderItem:
    expr: Any
    ascending: bool = True
    nulls_first: Optional[bool] = None


@dataclass
class Query:
    select_items: list
    from_refs: list  # [(TableRef|SubqueryRef, [JoinClause, ...]), ...]
    where: Optional[Any] = None
    group_by: list = field(default_factory=list)
    having: Optional[Any] = None
    order_by: list = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None
    distinct: bool = False
    ctes: list = field(default_factory=list)  # [(name, Query)]


@dataclass
class SetOp:
    """UNION/INTERSECT/EXCEPT chain; ORDER BY/LIMIT apply to the result."""

    op: str  # union|intersect|except
    all: bool
    left: Any  # Query | SetOp
    right: Any  # Query
    order_by: list = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None
    ctes: list = field(default_factory=list)


# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------

_KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "as", "and", "or", "not", "in", "exists", "between", "like",
    "is", "null", "case", "when", "then", "else", "end", "cast", "extract",
    "substring", "distinct", "join", "inner", "left", "right", "full",
    "outer", "cross", "on", "union", "all", "intersect", "except", "with",
    "asc", "desc", "date", "interval", "year", "month", "day", "true",
    "false", "for", "nulls", "first", "last",
}

_SYMBOLS = [
    "<>", "<=", ">=", "!=", "||", "(", ")", ",", "+", "-", "*", "/", "%",
    "<", ">", "=", ".", ";",
]


@dataclass
class Token:
    kind: str  # kw | ident | number | string | sym | eof
    value: str
    pos: int


class SqlLexError(ValueError):
    pass


class SqlParseError(ValueError):
    pass


def tokenize(sql: str) -> list[Token]:
    out: list[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        if sql.startswith("--", i):
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if sql.startswith("/*", i):
            j = sql.find("*/", i + 2)
            if j < 0:
                raise SqlLexError(f"unterminated comment at {i}")
            i = j + 2
            continue
        if c == "'":
            j = i + 1
            buf = []
            while j < n:
                if sql[j] == "'" and j + 1 < n and sql[j + 1] == "'":
                    buf.append("'")
                    j += 2
                elif sql[j] == "'":
                    break
                else:
                    buf.append(sql[j])
                    j += 1
            if j >= n:
                raise SqlLexError(f"unterminated string at {i}")
            out.append(Token("string", "".join(buf), i))
            i = j + 1
            continue
        if c == '"':
            j = sql.find('"', i + 1)
            if j < 0:
                raise SqlLexError(f"unterminated quoted identifier at {i}")
            out.append(Token("ident", sql[i + 1 : j], i))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = False
            seen_exp = False
            while j < n:
                ch = sql[j]
                if ch.isdigit():
                    j += 1
                elif ch == "." and not seen_dot and not seen_exp:
                    seen_dot = True
                    j += 1
                elif ch in "eE" and not seen_exp and j > i:
                    seen_exp = True
                    j += 1
                    if j < n and sql[j] in "+-":
                        j += 1
                else:
                    break
            out.append(Token("number", sql[i:j], i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            word = sql[i:j]
            kind = "kw" if word.lower() in _KEYWORDS else "ident"
            # SQL folds UNQUOTED identifiers (quoted ones, lexed above,
            # stay verbatim) — `FROM (...) CATALOG ... catalog.col` must
            # match (TPC-DS q49 mixes cases freely)
            out.append(Token(kind, word.lower(), i))
            i = j
            continue
        for sym in _SYMBOLS:
            if sql.startswith(sym, i):
                out.append(Token("sym", sym, i))
                i += len(sym)
                break
        else:
            raise SqlLexError(f"unexpected character {c!r} at {i}")
    out.append(Token("eof", "", n))
    return out


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------


class Parser:
    def __init__(self, sql: str):
        self.sql = sql
        self.toks = tokenize(sql)
        self.i = 0

    # -- token helpers ------------------------------------------------------
    def peek(self, ahead: int = 0) -> Token:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def at_kw(self, *words: str) -> bool:
        t = self.peek()
        return t.kind == "kw" and t.value in words

    def at_sym(self, *syms: str) -> bool:
        t = self.peek()
        return t.kind == "sym" and t.value in syms

    def eat_kw(self, *words: str) -> bool:
        if self.at_kw(*words):
            self.next()
            return True
        return False

    def eat_sym(self, *syms: str) -> bool:
        if self.at_sym(*syms):
            self.next()
            return True
        return False

    def expect_kw(self, word: str) -> None:
        if not self.eat_kw(word):
            self.error(f"expected {word.upper()}")

    def expect_sym(self, sym: str) -> None:
        if not self.eat_sym(sym):
            self.error(f"expected {sym!r}")

    def error(self, msg: str):
        t = self.peek()
        ctx = self.sql[max(0, t.pos - 20) : t.pos + 20].replace("\n", " ")
        raise SqlParseError(f"{msg} at position {t.pos} (near ...{ctx}...)")

    # -- entry --------------------------------------------------------------
    def parse_query(self) -> Query:
        q = self._query()
        self.eat_sym(";")
        if self.peek().kind != "eof":
            self.error("trailing input")
        return q

    def _query(self) -> Query:
        ctes = []
        if self.eat_kw("with"):
            while True:
                name = self._ident_name()
                self.expect_kw("as") if self.at_kw("as") else self.error(
                    "expected AS in CTE"
                )
                self.expect_sym("(")
                sub = self._query()
                self.expect_sym(")")
                ctes.append((name, sub))
                if not self.eat_sym(","):
                    break
        q = self._intersect_chain(ctes)
        # UNION/EXCEPT bind looser than INTERSECT (SQL standard precedence)
        while self.at_kw("union", "except"):
            op = self.next().value
            all_ = self.eat_kw("all")
            # a directly-parenthesized arm keeps its own ORDER BY/LIMIT
            arm_paren = self.at_sym("(")
            rhs = self._intersect_chain(ctes)
            q = SetOp(op, all_, q, rhs, ctes=ctes)
            if isinstance(rhs, Query) and not arm_paren:
                q = self._hoist_trailing_clauses(q, rhs)
        # ORDER BY / LIMIT can follow a set op chain
        if self.at_kw("order"):
            q.order_by = self._order_by()
        if self.eat_kw("limit"):
            q.limit = self._int_literal()
        if self.eat_kw("offset"):
            q.offset = self._int_literal()
        if isinstance(q, Query):
            q.ctes = ctes
        return q

    def _consume_frame_bounds(self) -> str:
        """Consume `BETWEEN <bound> AND <bound>` or `<bound>`. Only the
        UNBOUNDED-PRECEDING..CURRENT-ROW shape is supported (the default
        running frame); anything else raises."""

        def bound() -> str:
            t = self.next()
            w = t.value.lower()
            if w == "unbounded":
                d = self.next().value.lower()
                return f"unbounded {d}"
            if w == "current":
                self.next()  # ROW
                return "current row"
            self.error(f"unsupported window frame bound {w!r}")

        if self.eat_kw("between"):
            lo = bound()
            self.expect_kw("and")
            hi = bound()
        else:
            lo, hi = bound(), "current row"
        if lo != "unbounded preceding" or hi not in (
            "current row", "unbounded following",
        ):
            self.error(f"unsupported window frame {lo} .. {hi}")
        return hi

    def _select_or_paren(self):
        """A set-operation arm: SELECT ... or a parenthesized query.
        -> (query, parenthesized): ORDER BY/LIMIT inside parens belong to
        the arm and must NOT be hoisted to the enclosing set op."""
        if self.at_sym("("):
            self.next()
            q = self._query()
            self.expect_sym(")")
            return q, True
        return self._select(), False

    def _intersect_chain(self, ctes):
        q, _ = self._select_or_paren()
        while self.at_kw("intersect"):
            self.next()
            all_ = self.eat_kw("all")
            rhs, paren = self._select_or_paren()
            q = SetOp("intersect", all_, q, rhs, ctes=ctes)
            if isinstance(rhs, Query) and not paren:
                q = self._hoist_trailing_clauses(q, rhs)
        return q

    @staticmethod
    def _hoist_trailing_clauses(q: "SetOp", rhs: "Query") -> "SetOp":
        # a trailing ORDER BY/LIMIT parsed into the last arm belongs to the
        # whole set-op chain (arms can't carry them without parens)
        if rhs.order_by or rhs.limit is not None or rhs.offset is not None:
            q.order_by, rhs.order_by = rhs.order_by, []
            q.limit, rhs.limit = rhs.limit, None
            q.offset, rhs.offset = rhs.offset, None
        return q

    def _select(self) -> Query:
        self.expect_kw("select")
        distinct = self.eat_kw("distinct")
        items = [self._select_item()]
        while self.eat_sym(","):
            items.append(self._select_item())
        from_refs = []
        if self.eat_kw("from"):
            from_refs.append(self._table_with_joins())
            while self.eat_sym(","):
                from_refs.append(self._table_with_joins())
        where = self._expr() if self.eat_kw("where") else None
        group_by = []
        if self.eat_kw("group"):
            self.expect_kw("by")
            group_by.append(self._expr())
            while self.eat_sym(","):
                group_by.append(self._expr())
        having = self._expr() if self.eat_kw("having") else None
        order_by = self._order_by() if self.at_kw("order") else []
        limit = self._int_literal() if self.eat_kw("limit") else None
        offset = self._int_literal() if self.eat_kw("offset") else None
        return Query(
            select_items=items,
            from_refs=from_refs,
            where=where,
            group_by=group_by,
            having=having,
            order_by=order_by,
            limit=limit,
            offset=offset,
            distinct=distinct,
        )

    def _order_by(self) -> list[OrderItem]:
        self.expect_kw("order")
        self.expect_kw("by")
        out = [self._order_item()]
        while self.eat_sym(","):
            out.append(self._order_item())
        return out

    def _order_item(self) -> OrderItem:
        e = self._expr()
        asc = True
        if self.eat_kw("desc"):
            asc = False
        else:
            self.eat_kw("asc")
        nulls_first = None
        if self.eat_kw("nulls"):
            if self.eat_kw("first"):
                nulls_first = True
            elif self.eat_kw("last"):
                nulls_first = False
            else:
                self.error("expected FIRST or LAST")
        return OrderItem(e, asc, nulls_first)

    def _int_literal(self) -> int:
        t = self.peek()
        if t.kind != "number":
            self.error("expected integer literal")
        self.next()
        return int(t.value)

    def _select_item(self) -> SelectItem:
        if self.at_sym("*"):
            self.next()
            return SelectItem(Star())
        # qualified star t.*
        if (
            self.peek().kind == "ident"
            and self.peek(1).kind == "sym"
            and self.peek(1).value == "."
            and self.peek(2).kind == "sym"
            and self.peek(2).value == "*"
        ):
            q = self.next().value
            self.next()
            self.next()
            return SelectItem(Star(qualifier=q))
        e = self._expr()
        alias = None
        if self.eat_kw("as"):
            alias = self._ident_name()
        elif self.peek().kind == "ident":
            alias = self.next().value
        return SelectItem(e, alias)

    def _ident_name(self) -> str:
        t = self.peek()
        if t.kind == "ident":
            self.next()
            return t.value
        # permissive: some keywords double as identifiers (e.g. a column
        # named "year"); accept non-reserved keywords as names.
        if t.kind == "kw" and t.value in ("year", "month", "day", "date",
                                          "first", "last"):
            self.next()
            return t.value
        self.error("expected identifier")

    # -- FROM ---------------------------------------------------------------
    def _table_with_joins(self):
        base = self._table_ref()
        joins = []
        while True:
            kind = None
            if self.at_kw("join"):
                kind = "inner"
            elif self.at_kw("inner") and self.peek(1).value == "join":
                kind = "inner"
                self.next()
            elif self.at_kw("left"):
                kind = "left"
                self.next()
                self.eat_kw("outer")
            elif self.at_kw("right"):
                kind = "right"
                self.next()
                self.eat_kw("outer")
            elif self.at_kw("full"):
                kind = "full"
                self.next()
                self.eat_kw("outer")
            elif self.at_kw("cross"):
                kind = "cross"
                self.next()
            else:
                break
            self.expect_kw("join")
            right = self._table_ref()
            on = None
            if kind != "cross":
                self.expect_kw("on")
                on = self._expr()
            joins.append(JoinClause(right, kind, on))
        return (base, joins)

    def _table_ref(self):
        if self.eat_sym("("):
            sub = self._query()
            self.expect_sym(")")
            self.eat_kw("as")
            alias = self._ident_name()
            col_aliases = None
            if self.eat_sym("("):
                col_aliases = [self._ident_name()]
                while self.eat_sym(","):
                    col_aliases.append(self._ident_name())
                self.expect_sym(")")
            return SubqueryRef(sub, alias, col_aliases)
        name = self._ident_name()
        alias = None
        if self.eat_kw("as"):
            alias = self._ident_name()
        elif self.peek().kind == "ident":
            alias = self.next().value
        return TableRef(name, alias)

    # -- expressions (precedence climbing) ----------------------------------
    def _expr(self):
        return self._or_expr()

    def _or_expr(self):
        left = self._and_expr()
        while self.eat_kw("or"):
            left = Binary("or", left, self._and_expr())
        return left

    def _and_expr(self):
        left = self._not_expr()
        while self.eat_kw("and"):
            left = Binary("and", left, self._not_expr())
        return left

    def _not_expr(self):
        if self.eat_kw("not"):
            return Unary("not", self._not_expr())
        return self._predicate()

    def _predicate(self):
        if self.at_kw("exists"):
            self.next()
            self.expect_sym("(")
            q = self._query()
            self.expect_sym(")")
            return Exists(q)
        left = self._additive()
        while True:
            negated = False
            if self.at_kw("not") and self.peek(1).value in (
                "in", "between", "like",
            ):
                self.next()
                negated = True
            if self.eat_kw("between"):
                low = self._additive()
                self.expect_kw("and")
                high = self._additive()
                left = Between(left, low, high, negated)
                continue
            if self.eat_kw("in"):
                self.expect_sym("(")
                if self.at_kw("select", "with"):
                    q = self._query()
                    self.expect_sym(")")
                    left = InSubquery(left, q, negated)
                else:
                    items = [self._expr()]
                    while self.eat_sym(","):
                        items.append(self._expr())
                    self.expect_sym(")")
                    left = InListAst(left, items, negated)
                continue
            if self.eat_kw("like"):
                t = self.peek()
                if t.kind != "string":
                    self.error("LIKE pattern must be a string literal")
                self.next()
                left = LikeAst(left, t.value, negated)
                continue
            if self.eat_kw("is"):
                neg = self.eat_kw("not")
                self.expect_kw("null")
                left = IsNullAst(left, neg)
                continue
            if self.peek().kind == "sym" and self.peek().value in (
                "=", "<>", "!=", "<", "<=", ">", ">=",
            ):
                op = self.next().value
                op = {"=": "==", "<>": "!=", "!=": "!="}.get(op, op)
                right = self._additive()
                left = Binary(op, left, right)
                continue
            return left

    def _additive(self):
        left = self._multiplicative()
        while self.at_sym("+", "-"):
            op = self.next().value
            left = Binary(op, left, self._multiplicative())
        return left

    def _multiplicative(self):
        left = self._unary()
        while self.at_sym("*", "/", "%"):
            op = self.next().value
            left = Binary(op, left, self._unary())
        return left

    def _unary(self):
        if self.at_sym("-"):
            self.next()
            return Unary("-", self._unary())
        if self.at_sym("+"):
            self.next()
            return self._unary()
        return self._primary()

    def _primary(self):
        t = self.peek()
        if t.kind == "number":
            self.next()
            v = float(t.value) if ("." in t.value or "e" in t.value.lower()) else int(t.value)
            return NumberLit(v, raw=t.value)
        if t.kind == "string":
            self.next()
            return StringLit(t.value)
        if self.at_kw("true"):
            self.next()
            return NumberLit(1)
        if self.at_kw("false"):
            self.next()
            return NumberLit(0)
        if self.at_kw("null"):
            self.next()
            return NumberLit(None)
        if self.at_kw("date"):
            # DATE 'yyyy-mm-dd'
            self.next()
            s = self.peek()
            if s.kind != "string":
                self.error("expected date string literal")
            self.next()
            from datafusion_distributed_tpu.plan.expressions import parse_date

            return DateLit(parse_date(s.value))
        if self.at_kw("interval"):
            self.next()
            s = self.peek()
            if s.kind != "string":
                self.error("expected interval string literal")
            self.next()
            # INTERVAL '90' DAY | INTERVAL '3' MONTH | INTERVAL '1' YEAR
            qty_str = s.value.strip()
            unit = None
            parts = qty_str.split()
            if len(parts) == 2:
                qty_str, unit = parts[0], parts[1].lower().rstrip("s")
            qty = int(qty_str)
            if unit is None:
                if self.at_kw("day", "month", "year"):
                    unit = self.next().value
                else:
                    unit = "day"
            if unit == "day":
                return IntervalLit(0, qty)
            if unit == "month":
                return IntervalLit(qty, 0)
            if unit == "year":
                return IntervalLit(12 * qty, 0)
            self.error(f"unsupported interval unit {unit}")
        if self.at_kw("case"):
            return self._case()
        if self.at_kw("cast"):
            self.next()
            self.expect_sym("(")
            e = self._expr()
            self.expect_kw("as")
            # type name: one or two words (e.g. double precision), optional (p,s)
            words = [self._type_word()]
            while self.peek().kind in ("ident", "kw") and not self.at_sym(")"):
                words.append(self._type_word())
            if self.eat_sym("("):
                self._int_literal()
                if self.eat_sym(","):
                    self._int_literal()
                self.expect_sym(")")
            self.expect_sym(")")
            return CastAst(e, " ".join(words))
        if self.at_kw("extract"):
            self.next()
            self.expect_sym("(")
            part_tok = self.next()
            part = part_tok.value.lower()
            if part not in ("year", "month", "day", "hour", "minute",
                            "second"):
                self.error(f"unsupported EXTRACT part {part}")
            if not self.eat_kw("from"):
                self.error("expected FROM in EXTRACT")
            e = self._expr()
            self.expect_sym(")")
            return ExtractAst(part, e)
        if self.at_kw("substring"):
            self.next()
            self.expect_sym("(")
            e = self._expr()
            if self.eat_kw("from"):
                start = self._expr()
                length = self._expr() if self.eat_kw("for") else None
            else:
                self.expect_sym(",")
                start = self._expr()
                length = self._expr() if self.eat_sym(",") else None
            self.expect_sym(")")
            return SubstringAst(e, start, length)
        if self.eat_sym("("):
            if self.at_kw("select", "with"):
                q = self._query()
                self.expect_sym(")")
                return ScalarSubquery(q)
            e = self._expr()
            self.expect_sym(")")
            return e
        if t.kind == "ident" or (t.kind == "kw" and t.value in (
            "year", "month", "day", "first", "last",
        )):
            name = self.next().value
            # function call?
            if self.at_sym("(") :
                self.next()
                distinct = self.eat_kw("distinct")
                args: list = []
                if self.at_sym("*"):
                    self.next()
                    args = [Star()]
                elif not self.at_sym(")"):
                    args.append(self._expr())
                    while self.eat_sym(","):
                        args.append(self._expr())
                self.expect_sym(")")
                over = None
                if self.peek().kind == "ident" and self.peek().value.lower() == "over":
                    self.next()
                    self.expect_sym("(")
                    partition_by: list = []
                    order_by: list = []
                    if self.peek().kind == "ident" and (
                        self.peek().value.lower() == "partition"
                    ):
                        self.next()
                        self.expect_kw("by")
                        partition_by.append(self._expr())
                        while self.eat_sym(","):
                            partition_by.append(self._expr())
                    if self.at_kw("order"):
                        order_by = self._order_by()
                    frame = "range"
                    if self.peek().kind == "ident" and self.peek().value.lower() in (
                        "rows", "range",
                    ):
                        frame = self.next().value.lower()
                        hi = self._consume_frame_bounds()
                        if hi == "unbounded following":
                            frame = "full"  # whole-partition frame
                    self.expect_sym(")")
                    over = WindowSpec(partition_by, order_by, frame)
                return FuncCall(name.lower(), args, distinct, over)
            # qualified identifier?
            if self.at_sym(".") :
                self.next()
                col = self._ident_name()
                return Ident(col, qualifier=name)
            return Ident(name)
        self.error("unexpected token in expression")

    def _type_word(self) -> str:
        t = self.next()
        return t.value.lower()

    def _case(self):
        self.expect_kw("case")
        operand = None
        if not self.at_kw("when"):
            operand = self._expr()
        whens = []
        while self.eat_kw("when"):
            cond = self._expr()
            self.expect_kw("then")
            val = self._expr()
            whens.append((cond, val))
        else_ = self._expr() if self.eat_kw("else") else None
        self.expect_kw("end")
        return CaseAst(operand, whens, else_)


@dataclass
class CreateView:
    name: str
    query: Any  # Query | SetOp
    column_aliases: Optional[list] = None


@dataclass
class DropView:
    name: str


@dataclass
class SetOption:
    """SET <scope>.<key> = <value> (the reference supports
    `SET distributed.max_tasks_per_stage = 4` via ConfigExtension)."""

    name: str  # dotted, e.g. "distributed.broadcast_joins"
    value: Any


@dataclass
class ExplainVerify:
    """EXPLAIN VERIFY <query>: plan the query, run the static verifier
    (plan/verify.py) and return the annotated plan tree + diagnostics
    instead of executing."""

    query: Any  # Query | SetOp


def parse_sql(sql: str):
    return Parser(sql).parse_query()


def parse_statements(sql: str) -> list:
    """Parse a script of ;-separated statements: SELECT queries plus
    CREATE VIEW <name> AS <query> and DROP VIEW <name> (TPC-H q15 shape)."""
    p = Parser(sql)
    out: list = []
    while p.peek().kind != "eof":
        if p.at_kw("with") or p.at_kw("select"):
            out.append(p._query())
        elif p.peek().kind == "ident" and p.peek().value.lower() == "explain":
            p.next()
            _expect_word(p, "verify")
            out.append(ExplainVerify(p._query()))
        elif p.peek().kind == "ident" and p.peek().value.lower() == "create":
            p.next()
            _expect_word(p, "view")
            name = p._ident_name()
            col_aliases = None
            if p.eat_sym("("):
                col_aliases = [p._ident_name()]
                while p.eat_sym(","):
                    col_aliases.append(p._ident_name())
                p.expect_sym(")")
            p.expect_kw("as")
            out.append(CreateView(name, p._query(), col_aliases))
        elif p.peek().kind == "ident" and p.peek().value.lower() == "drop":
            p.next()
            _expect_word(p, "view")
            out.append(DropView(p._ident_name()))
        elif p.peek().kind == "ident" and p.peek().value.lower() == "set":
            p.next()
            parts = [p._ident_name()]
            while p.eat_sym("."):
                parts.append(p._ident_name())
            p.expect_sym("=")
            t = p.next()
            if t.kind == "number":
                v: Any = float(t.value) if "." in t.value else int(t.value)
            elif t.kind == "string":
                v = t.value
            elif t.kind == "kw" and t.value in ("true", "false"):
                v = t.value == "true"
            elif t.kind == "ident" and t.value.lower() in ("true", "false"):
                v = t.value.lower() == "true"
            elif t.kind == "ident" and parts[-1].lower() in _ENUM_SET_OPTIONS:
                # bare-word enum values (SET distributed.verify_plans =
                # strict); the scope handler validates the domain. Only
                # enum-valued options accept a bare word — everywhere else
                # a stray identifier stays a parse-time error instead of a
                # far-away crash at the option's use site
                v = t.value
            else:
                p.error("expected literal value in SET")
            out.append(SetOption(".".join(parts), v))
        else:
            p.error("expected statement")
        while p.eat_sym(";"):
            pass
    return out


#: SET options whose value is a bare-word enum rather than a literal
#: (kept in sync with the scope handlers in sql/context.py)
_ENUM_SET_OPTIONS = frozenset(
    {"verify_plans", "data_plane", "wire_compression"}
)


def _expect_word(p: Parser, word: str) -> None:
    t = p.peek()
    if t.kind == "ident" and t.value.lower() == word:
        p.next()
        return
    p.error(f"expected {word.upper()}")
