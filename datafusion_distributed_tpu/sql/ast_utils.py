"""AST-level helpers shared by the binder and its mixins (split out of
logical.py): conjunct splitting, aggregate/window call collection, constant
folding over dates/decimals, ROLLUP expansion, fingerprinting."""

from __future__ import annotations

import datetime
from dataclasses import dataclass
from typing import Any

from datafusion_distributed_tpu.plan import expressions as pe
from datafusion_distributed_tpu.schema import DataType
from datafusion_distributed_tpu.sql import parser as ast
from datafusion_distributed_tpu.sql.lplan import LogicalPlan, LProject
from datafusion_distributed_tpu.sql.scope import BindError

# ---------------------------------------------------------------------------
# AST utilities
# ---------------------------------------------------------------------------

from datafusion_distributed_tpu.ops.aggregate import (  # noqa: E402
    _VARIANCE_FUNCS,
)

_AGG_FUNCS = {"sum", "count", "min", "max", "avg"} | _VARIANCE_FUNCS
_WINDOW_ONLY_FUNCS = {"rank", "dense_rank", "row_number"}


def _collect_window_calls(node, out: list) -> None:
    if isinstance(node, ast.FuncCall) and node.over is not None:
        out.append(node)
        _AGG_ID_REGISTRY[id(node)] = node
        return
    if isinstance(node, (ast.ScalarSubquery, ast.Exists, ast.InSubquery)):
        return
    for ch in _ast_children(node):
        _collect_window_calls(ch, out)
_AGG_ID_REGISTRY: dict[int, Any] = {}


def _agg_parts(call: ast.FuncCall):
    arg = call.args[0] if call.args else ast.Star()
    return call.name, arg, call.distinct


def _collect_agg_calls(node, out: list) -> None:
    if isinstance(node, ast.FuncCall) and node.over is not None:
        # a window call is NOT a group aggregate, but its argument and spec
        # may contain ones (sum(sum(x)) over (partition by ...))
        for a in node.args:
            _collect_agg_calls(a, out)
        for p in node.over.partition_by:
            _collect_agg_calls(p, out)
        for o in node.over.order_by:
            _collect_agg_calls(o.expr, out)
        return
    if isinstance(node, ast.FuncCall) and node.name in _AGG_FUNCS:
        out.append(node)
        _AGG_ID_REGISTRY[id(node)] = node
        return  # nested aggregates are invalid SQL
    if isinstance(node, (ast.ScalarSubquery, ast.Exists, ast.InSubquery)):
        return  # subquery aggregates belong to the subquery
    for ch in _ast_children(node):
        _collect_agg_calls(ch, out)


def _ast_children(node) -> list:
    if isinstance(node, ast.Binary):
        return [node.left, node.right]
    if isinstance(node, ast.Unary):
        return [node.child]
    if isinstance(node, ast.Between):
        return [node.expr, node.low, node.high]
    if isinstance(node, ast.InListAst):
        return [node.expr] + list(node.items)
    if isinstance(node, ast.InSubquery):
        return [node.expr]
    if isinstance(node, ast.LikeAst):
        return [node.expr]
    if isinstance(node, ast.IsNullAst):
        return [node.expr]
    if isinstance(node, ast.CaseAst):
        out = []
        if node.operand is not None:
            out.append(node.operand)
        for c, v in node.whens:
            out += [c, v]
        if node.else_ is not None:
            out.append(node.else_)
        return out
    if isinstance(node, ast.CastAst):
        return [node.expr]
    if isinstance(node, ast.ExtractAst):
        return [node.expr]
    if isinstance(node, ast.SubstringAst):
        return [node.expr]
    if isinstance(node, ast.FuncCall):
        return list(node.args)
    return []


def _is_rollup(g) -> bool:
    return isinstance(g, ast.FuncCall) and g.name.lower() == "rollup"


def _ast_substitute(node, fn):
    """Rebuild an AST bottom-up: fn(node) -> replacement or None (recurse).
    Does NOT descend into nested Query/SetOp (their own scopes own their
    identifiers)."""
    import dataclasses as _dc

    if isinstance(node, (ast.Query, ast.SetOp)):
        return node
    rep = fn(node)
    if rep is not None:
        return rep
    if isinstance(node, list):
        return [_ast_substitute(x, fn) for x in node]
    if isinstance(node, tuple):
        return tuple(_ast_substitute(x, fn) for x in node)
    if _dc.is_dataclass(node) and not isinstance(node, type):
        changes = {}
        for fld in _dc.fields(node):
            v = getattr(node, fld.name)
            nv = _ast_substitute(v, fn)
            if nv is not v:
                changes[fld.name] = nv
        return _dc.replace(node, **changes) if changes else node
    return node


def _expand_rollup(q: "ast.Query"):
    """GROUP BY ROLLUP(a, b, ...) -> UNION ALL of one aggregation per prefix
    of the rollup list (finest to grand total). Rolled-away columns become
    typed NULLs (ast.NullOf) and GROUPING(col) folds to 0/1 per arm — the
    standard lowering (the reference gets it from DataFusion's logical
    planner)."""
    import dataclasses as _dc

    plain = [g for g in q.group_by if not _is_rollup(g)]
    roll = next(g for g in q.group_by if _is_rollup(g)).args
    if sum(1 for g in q.group_by if _is_rollup(g)) > 1:
        raise BindError("multiple ROLLUPs in one GROUP BY")

    arms = []
    for k in range(len(roll), -1, -1):
        dropped = {
            i.name.lower() for i in roll[k:] if isinstance(i, ast.Ident)
        }

        def fn(node, dropped=dropped):
            if isinstance(node, ast.FuncCall) and node.name.lower() == (
                "grouping"
            ):
                arg = node.args[0]
                flag = 1 if (
                    isinstance(arg, ast.Ident) and arg.name.lower() in dropped
                ) else 0
                return ast.NumberLit(flag)
            if isinstance(node, ast.Ident) and node.name.lower() in dropped:
                return ast.NullOf(node)
            return None

        arm = _dc.replace(
            q,
            select_items=_ast_substitute(q.select_items, fn),
            group_by=plain + list(roll[:k]),
            having=_ast_substitute(q.having, fn) if q.having else None,
            order_by=[],
            limit=None,
            offset=None,
            ctes=[],
        )
        arms.append(arm)

    combined = arms[0]
    for arm in arms[1:]:
        combined = ast.SetOp("union", True, combined, arm)

    def order_fn(node):
        # ORDER BY applies to the union result, where the arm is no longer
        # known statically; GROUPING(col) is recovered per row as
        # `CASE WHEN col IS NULL THEN 1 ELSE 0 END` (exact whenever the
        # group column itself is non-null, which holds for the rollup
        # dimensions in the TPC-DS suite).
        if isinstance(node, ast.FuncCall) and node.name.lower() == "grouping":
            return ast.CaseAst(
                None,
                [(ast.IsNullAst(node.args[0], False), ast.NumberLit(1))],
                ast.NumberLit(0),
            )
        return None

    combined.order_by = _ast_substitute(list(q.order_by), order_fn)
    combined.limit = q.limit
    combined.offset = q.offset
    combined.ctes = list(q.ctes)
    return combined


def _contains_subquery(node) -> bool:
    if isinstance(node, (ast.ScalarSubquery, ast.Exists, ast.InSubquery)):
        return True
    if isinstance(node, ast.Unary) and node.op == "not":
        return _contains_subquery(node.child)
    return any(_contains_subquery(ch) for ch in _ast_children(node))


def _common_or_conjuncts(node: ast.Binary) -> list:
    """Conjuncts present (by fingerprint) in every branch of an OR tree."""

    def branches(n):
        if isinstance(n, ast.Binary) and n.op == "or":
            return branches(n.left) + branches(n.right)
        return [n]

    bs = branches(node)
    if len(bs) < 2:
        return []
    sets = []
    by_fp: dict[str, Any] = {}
    for b in bs:
        cs = _split_conjuncts(b)
        fps = set()
        for c in cs:
            fp = _ast_fingerprint(c)
            fps.add(fp)
            by_fp.setdefault(fp, c)
        sets.append(fps)
    common = set.intersection(*sets)
    return [by_fp[fp] for fp in sorted(common)]


def _hoist_common_or(c) -> list:
    """OR whose every branch repeats the same conjuncts ->
    [common..., OR(branches stripped of them)] — an EQUIVALENT rewrite
    (unlike _common_or_conjuncts, which only surfaces the implied
    conjuncts). TPC-DS q41 hides its correlation this way:
    `(corr AND colorsA) OR (corr AND colorsB)`."""
    if not (isinstance(c, ast.Binary) and c.op == "or"):
        return [c]
    common = _common_or_conjuncts(c)
    if not common:
        return [c]
    common_fps = {_ast_fingerprint(x) for x in common}

    def branches(n):
        if isinstance(n, ast.Binary) and n.op == "or":
            return branches(n.left) + branches(n.right)
        return [n]

    stripped = []
    for b in branches(c):
        rest = [
            x for x in _split_conjuncts(b)
            if _ast_fingerprint(x) not in common_fps
        ]
        if not rest:
            # one branch reduces to TRUE -> the whole OR is implied by the
            # common conjuncts
            return list(common)
        stripped.append(_join_conjuncts(rest))
    out = stripped[0]
    for b in stripped[1:]:
        out = ast.Binary("or", out, b)
    return list(common) + [out]


def _sort_fetch(q) -> "int | None":
    """Top-k bound for a sort feeding LIMIT/OFFSET: limit+offset rows."""
    if q.limit is None:
        return None
    return q.limit + (q.offset or 0)


def _split_conjuncts(node) -> list:
    if isinstance(node, ast.Binary) and node.op == "and":
        return _split_conjuncts(node.left) + _split_conjuncts(node.right)
    return [node]


def _join_conjuncts(conjuncts: list):
    if not conjuncts:
        return None
    out = conjuncts[0]
    for c in conjuncts[1:]:
        out = ast.Binary("and", out, c)
    return out


def _has_aggregates(q: ast.Query) -> bool:
    out: list = []
    for item in q.select_items:
        _collect_agg_calls(item.expr, out)
    return bool(out) or bool(q.group_by)


def _ast_fingerprint(node) -> str:
    """Structural fingerprint for matching GROUP BY exprs to SELECT exprs."""
    if isinstance(node, ast.Ident):
        return f"id:{node.qualifier or ''}.{node.name}"
    if isinstance(node, ast.NumberLit):
        return f"n:{node.value}"
    if isinstance(node, ast.StringLit):
        return f"s:{node.value}"
    if isinstance(node, ast.DateLit):
        return f"d:{node.days}"
    if isinstance(node, ast.FuncCall):
        args = ",".join(_ast_fingerprint(a) for a in node.args)
        return f"f:{node.name}({args}){'D' if node.distinct else ''}"
    if isinstance(node, ast.Star):
        return f"*:{node.qualifier or ''}"
    parts = ",".join(_ast_fingerprint(c) for c in _ast_children(node))
    op = getattr(node, "op", "")
    extra = ""
    if isinstance(node, ast.LikeAst):
        extra = f":{node.pattern}:{node.negated}"
    if isinstance(node, ast.CastAst):
        extra = f":{node.type_name}"
    if isinstance(node, ast.ExtractAst):
        extra = f":{node.part}"
    return f"{type(node).__name__}:{op}{extra}({parts})"


def _display_name(e, idx: int) -> str:
    if isinstance(e, ast.Ident):
        return e.name
    return f"col{idx}"


def _literal_expr(v):
    if v is None:
        # untyped NULL: the type comes from context (set-op peer, CASE arm,
        # comparison partner) via _promote's NULL rule
        return pe.Literal(None, DataType.NULL)
    if isinstance(v, bool):
        return pe.Literal(v, DataType.BOOL)
    if isinstance(v, int):
        return pe.Literal(v, DataType.INT64)
    return pe.Literal(float(v), DataType.FLOAT64)


def _cast_type(name: str) -> DataType:
    name = name.strip().lower()
    mapping = {
        "int": DataType.INT32,
        "integer": DataType.INT32,
        "bigint": DataType.INT64,
        "smallint": DataType.INT32,
        "double": DataType.FLOAT64,
        "double precision": DataType.FLOAT64,
        "float": DataType.FLOAT32,
        "real": DataType.FLOAT32,
        "decimal": DataType.FLOAT64,
        "numeric": DataType.FLOAT64,
        "date": DataType.DATE32,
        "boolean": DataType.BOOL,
        "varchar": DataType.STRING,
        "char": DataType.STRING,
        "text": DataType.STRING,
        "string": DataType.STRING,
    }
    if name in mapping:
        return mapping[name]
    raise BindError(f"unsupported cast type {name!r}")


def _fold_date_arith(e: ast.Binary):
    """Fold DATE +/- INTERVAL into a DateLit (TPC-H parameterized dates)."""
    if e.op not in ("+", "-"):
        return None
    l, r = e.left, e.right
    if isinstance(l, ast.DateLit) and isinstance(r, ast.IntervalLit):
        sign = 1 if e.op == "+" else -1
        days = _shift_date(l.days, sign * r.months, sign * r.days)
        return pe.Literal(days, DataType.DATE32)
    if isinstance(l, ast.IntervalLit) and isinstance(r, ast.DateLit) and e.op == "+":
        days = _shift_date(r.days, l.months, l.days)
        return pe.Literal(days, DataType.DATE32)
    return None


def _as_decimal(node):
    """NumberLit (or +/-/*// tree of them) -> decimal.Decimal, else None."""
    import decimal

    if isinstance(node, ast.NumberLit):
        if node.raw is not None:
            return decimal.Decimal(node.raw)
        if isinstance(node.value, int):
            return decimal.Decimal(node.value)
        return None
    if isinstance(node, ast.Unary) and node.op == "-":
        d = _as_decimal(node.child)
        return -d if d is not None else None
    if isinstance(node, ast.Binary) and node.op in ("+", "-", "*", "/"):
        l = _as_decimal(node.left)
        r = _as_decimal(node.right)
        if l is None or r is None:
            return None
        if node.op == "+":
            return l + r
        if node.op == "-":
            return l - r
        if node.op == "*":
            return l * r
        if r == 0:
            return None
        return l / r


def _fold_decimal_arith(e: ast.Binary):
    if e.op not in ("+", "-", "*", "/"):
        return None
    if not (
        isinstance(e.left, (ast.NumberLit, ast.Binary, ast.Unary))
        and isinstance(e.right, (ast.NumberLit, ast.Binary, ast.Unary))
    ):
        return None
    d = _as_decimal(e)
    if d is None:
        return None
    if d == d.to_integral_value() and "." not in str(d):
        return pe.Literal(int(d), DataType.INT64)
    return pe.Literal(float(d), DataType.FLOAT64)


def _shift_date(epoch_days: int, months: int, days: int) -> int:
    import datetime

    d = datetime.date(1970, 1, 1) + datetime.timedelta(days=epoch_days)
    if months:
        total = d.year * 12 + (d.month - 1) + months
        y, m = divmod(total, 12)
        import calendar

        day = min(d.day, calendar.monthrange(y, m + 1)[1])
        d = datetime.date(y, m + 1, day)
    d = d + datetime.timedelta(days=days)
    return (d - datetime.date(1970, 1, 1)).days


def _collect_col_names(exprs) -> list[str]:
    out: list[str] = []

    def walk(x):
        if isinstance(x, pe.Col):
            out.append(x.name)
        for c in x.children():
            walk(c)

    for e in exprs:
        walk(e)
    return out


def _project_through(plan: LogicalPlan, exprs) -> LogicalPlan:
    """Append columns to a plan's output by re-projecting through its top
    projection (used to expose correlation key columns of a subquery)."""
    if isinstance(plan, LProject):
        have = {n for _, n in plan.exprs}
        extra = []
        cs = plan.child.schema()
        for e, n in exprs:
            if n not in have:
                extra.append((e, n))
        return LProject(plan.exprs + extra, plan.child)
    return LProject(exprs, plan)
