"""SessionContext: the user-facing API (the DataFusion `SessionContext`
analogue the reference extends via `DistributedExt`,
`/root/reference/src/distributed_ext.rs`).

    ctx = SessionContext()
    ctx.register_parquet("lineitem", "lineitem.parquet")
    df = ctx.sql("select l_returnflag, sum(l_quantity) from lineitem group by 1")
    df.collect()        # -> pyarrow Table
    df.to_pandas()
    df.explain()

Tables are decoded to padded device Tables at registration (host Parquet
decode happens once; every query then runs device-side). String dictionaries
are unified per table column at load.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional, Sequence

from datafusion_distributed_tpu.io.parquet import (
    arrow_to_table,
    schema_from_arrow,
    table_to_arrow,
)
from datafusion_distributed_tpu.ops.table import Table
from datafusion_distributed_tpu.plan.physical import (
    ExecutionPlan,
    MemoryScanExec,
    execute_plan,
)
from datafusion_distributed_tpu.schema import Schema
from datafusion_distributed_tpu.sql import parser as ast
from datafusion_distributed_tpu.sql.logical import Binder, LogicalPlan
from datafusion_distributed_tpu.sql.parser import (
    CreateView,
    DropView,
    ExplainVerify,
    SetOption,
    parse_statements,
)
from datafusion_distributed_tpu.sql.planner import PhysicalPlanner, PlannerConfig


#: distinct sentinel for Catalog._ndv_cache misses (None is a valid
#: cached verdict: "no such column")
_NDV_MISS = object()


class Catalog:
    """Named tables (device-resident) + views. NDV computation and
    registration serialize on a lock: the serving tier plans concurrent
    submissions from N client threads against one catalog."""

    def __init__(self) -> None:
        import threading

        self.tables: dict[str, Table] = {}
        self.views: dict[str, LogicalPlan] = {}
        self._ndv_cache: dict = {}
        self._ndv_lock = threading.Lock()
        # bumped on every (re-)registration: physical plans embed scan
        # Tables and plan-time scalar-subquery results, so the session's
        # plan cache keys on this to drop plans built over replaced data
        self.generation = 0

    def register_table(self, name: str, table: Table) -> None:
        with self._ndv_lock:
            self.tables[name.lower()] = table
            self.generation += 1
            self._ndv_cache = {
                k: v for k, v in self._ndv_cache.items()
                if k[0] != name.lower()
            }

    def has_table(self, name: str) -> bool:
        return name.lower() in self.tables

    def table_schema(self, name: str) -> Schema:
        return self.tables[name.lower()].schema()

    def table_rows(self, name: str) -> int:
        return int(self.tables[name.lower()].num_rows)

    def column_ndv(self, table: str, column: str):
        """Exact distinct count, computed once per column (drives the join
        orderer's fan-out estimates — the statistics the reference gets from
        DataFusion's table providers)."""
        key = (table.lower(), column)
        with self._ndv_lock:
            cached = self._ndv_cache.get(key, _NDV_MISS)
            gen0 = self.generation
        if cached is not _NDV_MISS:
            return cached
        import numpy as np

        t = self.tables.get(table.lower())
        if t is None or column not in t:
            ndv = None
        else:
            # sample-bounded: the heuristic only needs the order of
            # magnitude, and a full 60M-row device->host pull at bind
            # time would eat the benchmark budget. STRIDED, not a prefix:
            # generated keys are clustered (l_orderkey repeats ~4x in a
            # run), so a prefix under-counts distincts and freezes the
            # estimate below the extrapolation threshold.
            total = int(t.num_rows)
            n = min(total, 1 << 20)
            stride = max(1, total // max(n, 1))
            col = t.column(column)
            vals = np.asarray(col.data[:total:stride][:n])
            if col.validity is not None:
                vals = vals[np.asarray(col.validity[:total:stride][:n])]
            sampled = max(len(vals), 1)
            ndv = int(len(np.unique(vals)))
            # distinct-on-sample extrapolates only when near-unique
            # (a saturated sample means the column's true NDV is small)
            if ndv > 0.9 * sampled:
                ndv = min(int(ndv * (total / sampled)), total)
            elif sampled < total:
                # a non-extrapolated sampled count can still undercount
                # the true NDV; pad it so downstream hash-table sizing
                # (which treats this as an upper bound) overflows less
                ndv = min(int(ndv * 1.5) + 16, total)
        # the compute ran OUTSIDE the lock (concurrent planners may race
        # the same cold column; both compute the same deterministic
        # value). Cache only if the catalog generation is unchanged — a
        # re-registration mid-compute means this estimate sampled the
        # REPLACED table and must not be installed for the new one.
        with self._ndv_lock:
            if self.generation != gen0:
                return ndv
            return self._ndv_cache.setdefault(key, ndv)

    def scan_exec(self, name: str, columns: Sequence[str]) -> ExecutionPlan:
        t = self.tables[name.lower()]
        return MemoryScanExec([t.select(columns)], t.schema().select(columns))


@dataclass
class SessionConfig:
    planner: PlannerConfig = None  # type: ignore[assignment]
    overflow_retries: int = 3
    # `SET distributed.<key> = <value>` overrides, applied when building the
    # DistributedConfig (the reference's ConfigExtension with prefix
    # "distributed"; coordinator->worker propagation rides the plan codec).
    # Keys that are not DistributedConfig fields flow verbatim into
    # Coordinator.config_options — that is how the runtime knobs travel:
    # the data-plane ones (peer_shuffle, stream_chunk_rows,
    # worker_connection_buffer_budget_bytes, ...) and the fault-tolerance
    # layer's (max_task_retries, task_retry_backoff_s, task_timeout_s,
    # dispatch_timeout_s, quarantine_threshold, quarantine_seconds — see
    # runtime/coordinator.py FAULT_TOLERANCE_DEFAULTS).
    distributed_options: dict = None  # type: ignore[assignment]
    # user headers forwarded verbatim to workers (auth etc.; the
    # passthrough_headers analogue)
    passthrough_headers: dict = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.planner is None:
            self.planner = PlannerConfig()
        if self.distributed_options is None:
            self.distributed_options = {}
        if self.passthrough_headers is None:
            self.passthrough_headers = {}

    def set_option(self, name: str, value) -> None:
        scope, _, key = name.partition(".")
        if scope == "distributed":
            # compiled-program cache knobs apply process-wide (the caches
            # are module-level); they also stay in distributed_options so
            # EXPLAIN-style introspection and workers see the setting
            if key == "plan_cache_size":
                from datafusion_distributed_tpu.plan.physical import (
                    set_plan_cache_size,
                )

                set_plan_cache_size(int(value))
            elif key == "literal_hoisting":
                from datafusion_distributed_tpu.plan.fingerprint import (
                    set_literal_hoisting,
                )

                set_literal_hoisting(value)
            elif key == "verify_plans":
                from datafusion_distributed_tpu.plan.verify import MODES

                value = str(value).strip().lower()
                if value not in MODES:
                    raise ValueError(
                        f"invalid verify_plans mode {value!r} (expected "
                        f"one of {MODES})"
                    )
            elif key == "data_plane":
                # cross-process data-plane selection (runtime/
                # coordinator.py _data_plane): auto keeps the routing
                # ladder; unary/stream/shm force one plane. Execution
                # routing only — NEVER trace-relevant (toggling planes
                # must recompile nothing; the byte-identity gates in
                # tests/test_shm_plane.py pin that)
                value = str(value).strip().lower()
                if value not in ("auto", "unary", "stream", "shm"):
                    raise ValueError(
                        f"invalid data_plane {value!r} (expected one of "
                        f"('auto', 'unary', 'stream', 'shm'))"
                    )
            elif key == "wire_compression":
                # transfer-RPC wire codec policy: auto = adaptive
                # per-column choice (runtime/codec.py), zstd/lz4 force a
                # codec (still downgraded through per-connection
                # negotiation when an end can't decode it), off ships
                # raw frames
                value = str(value).strip().lower()
                if value not in ("auto", "off", "zstd", "lz4"):
                    raise ValueError(
                        f"invalid wire_compression {value!r} (expected "
                        f"one of ('auto', 'off', 'zstd', 'lz4'))"
                    )
            elif key == "max_concurrent_queries":
                # serving-tier admission knobs (runtime/serving.py) are
                # validated at SET time: a bad value must fail the SET,
                # not wedge admission decisions mid-serve
                value = int(value)
                if value < 1:
                    raise ValueError(
                        "max_concurrent_queries must be >= 1"
                    )
            elif key == "admission_budget_bytes":
                value = float(value)
                if value < 0:
                    raise ValueError(
                        "admission_budget_bytes must be >= 0 (0 = "
                        "unlimited)"
                    )
            elif key == "worker_memory_budget_bytes":
                # enforced per-worker staging budget (runtime/codec.py
                # TableStore + runtime/spill.py): validated at SET time
                # like the admission knobs; 0 = unlimited. Deliberately
                # NOT trace-relevant — flipping it never recompiles.
                value = float(value)
                if value < 0:
                    raise ValueError(
                        "worker_memory_budget_bytes must be >= 0 (0 = "
                        "unlimited)"
                    )
            elif key == "worker_memory_redline":
                # red-line shedding factor (runtime/serving.py): resident
                # bytes over budget x factor preempt the lowest-priority
                # running query; 0 disables shedding
                value = float(value)
                if value != 0 and value < 1.0:
                    raise ValueError(
                        "worker_memory_redline must be 0 (shedding off) "
                        "or >= 1.0 (a red-line below the budget would "
                        "shed before spill/backpressure even engage)"
                    )
            elif key == "checkpoint_budget_bytes":
                # CheckpointStore byte cap (runtime/checkpoint.py):
                # oldest recoverable checkpoints evict past it
                value = float(value)
                if value < 0:
                    raise ValueError(
                        "checkpoint_budget_bytes must be >= 0 (0 = "
                        "uncapped)"
                    )
            elif key == "result_cache_budget_bytes":
                # ResultCache byte budget (runtime/result_cache.py):
                # cold entries past it SPILL (SpillManager) instead of
                # evicting, and refault byte-exactly on the next hit
                value = float(value)
                if value < 0:
                    raise ValueError(
                        "result_cache_budget_bytes must be >= 0 (0 = "
                        "unlimited)"
                    )
            elif key == "serving_stage_slots":
                value = int(value)
                if value < 0:
                    raise ValueError(
                        "serving_stage_slots must be >= 0 (0 = auto: "
                        "the worker count)"
                    )
            elif key in ("fair_share", "zero_copy", "hedging",
                         "checkpointing", "pipelined_shuffle",
                         "partial_agg_pushdown", "multiway_join",
                         "global_hash_agg", "result_cache"):
                # boolean knobs: fair_share (serving scheduler policy),
                # zero_copy (view-based data plane — `off` restores the
                # copying plane everywhere), hedging (straggler
                # speculative re-dispatch), checkpointing (query
                # checkpoint/resume), pipelined_shuffle (streaming
                # first-slice shuffle boundaries — `off` restores the
                # materialized plane), partial_agg_pushdown (statistics-
                # driven pre-exchange partial aggregation), multiway_join
                # (fuse key-compatible join chains into one stage,
                # deleting intermediate shuffles), global_hash_agg
                # (high-NDV aggregation as one shared hash table instead
                # of per-partition tables + merge), result_cache
                # (fingerprint-keyed whole-result + sub-plan reuse —
                # runtime/result_cache.py). One shared parser so
                # SET-time coercion and runtime reads can't drift.
                from datafusion_distributed_tpu.ops.table import (
                    parse_bool_knob,
                )

                value = parse_bool_knob(value)
            elif key == "hedge_quantile":
                # hedging knobs validated at SET time like the serving
                # admission knobs: a bad value must fail the SET, not
                # silently disable (or stampede) the hedger mid-serve
                value = float(value)
                if not 0.0 <= value <= 1.0:
                    raise ValueError("hedge_quantile must be in [0, 1]")
            elif key == "hedge_floor_s":
                value = float(value)
                if value < 0:
                    raise ValueError("hedge_floor_s must be >= 0")
            elif key == "hedge_budget":
                value = int(value)
                if value < 0:
                    raise ValueError(
                        "hedge_budget must be >= 0 (0 disables hedging "
                        "by denying every speculative attempt)"
                    )
            elif key == "slo_p99_ms":
                # SLO targets (runtime/telemetry.py SloTracker, read
                # live by the serving tier's stats/console surfaces):
                # validated at SET time like the other serving knobs
                value = float(value)
                if value <= 0:
                    raise ValueError("slo_p99_ms must be > 0")
            elif key == "slo_error_rate":
                value = float(value)
                if not 0.0 <= value <= 1.0:
                    raise ValueError(
                        "slo_error_rate must be in [0, 1]"
                    )
            elif key == "tracing":
                # distributed-tracing mode (runtime/tracing.py):
                # validated at SET time so a typo fails the SET, not the
                # queries silently running untraced
                from datafusion_distributed_tpu.runtime.tracing import (
                    TRACING_MODES,
                )

                value = str(value).strip().lower()
                if value not in TRACING_MODES:
                    raise ValueError(
                        f"invalid tracing mode {value!r} (expected one "
                        f"of {TRACING_MODES})"
                    )
            elif key == "tracing_sample_rate":
                value = float(value)
                if not 0.0 <= value <= 1.0:
                    raise ValueError(
                        "tracing_sample_rate must be in [0, 1]"
                    )
            elif key == "skew_split_factor":
                # runtime-adaptivity knobs (runtime/adaptivity.py):
                # validated at SET time like the serving knobs, and
                # deliberately NOT trace-relevant — flipping any of them
                # recompiles nothing (pinned in test_recompile_budget.py)
                value = float(value)
                if value != 0 and value < 1.0:
                    raise ValueError(
                        "skew_split_factor must be 0 (splitting off) or "
                        ">= 1.0 (a hot partition is one ABOVE the "
                        "median)"
                    )
            elif key == "skew_split_min_rows":
                value = int(value)
                if value < 0:
                    raise ValueError(
                        "skew_split_min_rows must be >= 0"
                    )
            elif key == "partial_agg_bailout_ratio":
                value = float(value)
                if not 0.0 <= value <= 1.0:
                    raise ValueError(
                        "partial_agg_bailout_ratio must be in [0, 1] "
                        "(0 disables the bail-out; 1 bails only when "
                        "the partial reduces nothing)"
                    )
            elif key == "replan_cardinality_factor":
                value = float(value)
                if value != 0 and value < 1.0:
                    raise ValueError(
                        "replan_cardinality_factor must be 0 (replan "
                        "off) or >= 1.0 (measured/estimated divergence "
                        "factor)"
                    )
            self.distributed_options[key] = value
        elif scope == "planner":
            if not hasattr(self.planner, key):
                raise ValueError(f"unknown planner option {key!r}")
            setattr(self.planner, key, value)
        else:
            raise ValueError(f"unknown option scope {scope!r}")

    def distributed_snapshot(self) -> dict:
        """GIL-atomic copy of `distributed_options`: under the serving
        tier a client thread's first `SET distributed.<new_key>` can
        insert a key while another query's driver copies the dict, and a
        Python-level `dict(d)`/`.items()` iteration racing that insert
        raises "dictionary changed size during iteration" — failing an
        innocent query. `list(d.items())` materializes in one C call
        (no bytecode runs mid-snapshot), so readers always see a
        consistent point-in-time copy."""
        return dict(list(self.distributed_options.items()))


class OverflowRetryAbandoned(RuntimeError):
    """Raised (instead of another widening) when an overflow retry's plan
    would exceed the device-memory budget. A distinct type so the retry
    loops' `"overflow" in str(e)` filter does not catch it and keep
    widening — re-planning at 16x/64x factors executes plan-time scalar
    subqueries at exactly the blown-up capacities the guard exists to
    prevent."""


def _overflow_node_names(err) -> str:
    """The capacity-overflow errors embed the failing program's capacity-
    capable node labels ("... (nodes: ['HashAggregate']); ..."). The flag is
    OR-reduced on device (one tunnel fetch), so the individual culprit is
    unknown — but the candidate SET is, and it bounds which planner knobs a
    retry must widen."""
    import re as _re

    m = _re.search(r"nodes: \[([^\]]*)\]", str(err))
    return m.group(1) if m else ""


def _widen_for_overflow(pcfg: "PlannerConfig", dcfg, err,
                        force_all: bool = False):
    """-> (pcfg, dcfg) with only the capacity knobs implicated by the
    overflow error widened 4x. ``dcfg`` is None for single-process collects
    (no shuffle capacities exist there).

    A global widening compounds across knobs: an undersized aggregate table
    in one stage of q2 (SF0.5, adaptive tier) 4x'd join expansion AND
    shuffle skew query-wide, and two retries planned ~916GB of device
    buffers — tripping the byte-budget guard and failing a query a targeted
    agg widening converges in one retry. If NO knob applicable to the given
    configs is implicated (unparseable list, a future node class's label,
    or shuffle-only with dcfg=None), everything applicable widens: the
    alternative is re-executing the byte-identical plan every retry.

    ``force_all`` (the retry loops pass it on the LAST widening) also
    widens everything: targeting serializes knob discovery — an agg that
    needs two widenings hides a shuffle overflow behind it — so the final
    attempt must not die one knob short of the old global behavior."""
    names = _overflow_node_names(err)
    join = "Join" in names
    agg = "Aggregate" in names
    shuf = "Shuffle" in names and dcfg is not None
    if force_all or not (join or agg or shuf):
        join = agg = True
        shuf = dcfg is not None
    pcfg = replace(
        pcfg,
        join_expansion_factor=pcfg.join_expansion_factor * (4 if join else 1),
        agg_slot_factor=pcfg.agg_slot_factor * (4 if agg else 1),
    )
    if shuf:
        dcfg = replace(dcfg, shuffle_skew_factor=dcfg.shuffle_skew_factor * 4)
    return pcfg, dcfg


def _overflow_retry_guard(plan, attempt: int, last_err) -> None:
    """Abandon an overflow retry whose widened plan would need more device
    memory than the budget (DFTPU_RETRY_BYTES_BUDGET, default 16 GB):
    capacity factors compound 4x per retry, and dispatching a ~100GB plan
    fails with an opaque allocator error (or the OOM killer) instead of
    the overflow error the caller can reason about."""
    if attempt == 0:
        return
    import os as _os

    from datafusion_distributed_tpu.planner.statistics import (
        plan_device_bytes,
    )

    raw = _os.environ.get("DFTPU_RETRY_BYTES_BUDGET", "")
    try:
        budget = float(raw) if raw else 16e9
    except ValueError:
        raise RuntimeError(
            f"DFTPU_RETRY_BYTES_BUDGET={raw!r} is not a number"
        ) from None
    need = plan_device_bytes(plan)
    if need > budget:
        raise OverflowRetryAbandoned(
            f"overflow-retry abandoned: widened plan needs ~{need/1e9:.1f}GB "
            f"device buffers (budget {budget/1e9:.1f}GB, "
            "DFTPU_RETRY_BYTES_BUDGET); original overflow: "
            f"{last_err}"
        )


class DataFrame:
    """A planned (but unexecuted) query."""

    def __init__(self, ctx: "SessionContext", logical: LogicalPlan):
        self.ctx = ctx
        self.logical = logical
        # plan memoization: repeated collect() of the same DataFrame reuses
        # the plan object. Lookups go through the SESSION-level cache keyed
        # by the logical plan's structural fingerprint, so a fresh
        # ctx.sql(same_text) from a distinct submission reuses the planned
        # physical tree too (plan/fingerprint.py); this dict is the
        # fallback for logical plans without a fingerprint.
        self._plan_cache: dict = {}
        self._logical_fp = -1  # lazily computed; None = unfingerprintable

    def _logical_fingerprint(self):
        if self._logical_fp == -1:
            from datafusion_distributed_tpu.plan.fingerprint import (
                logical_fingerprint,
            )

            self._logical_fp = logical_fingerprint(self.logical)
        return self._logical_fp

    def _plan_cache_get(self, key):
        lfp = self._logical_fingerprint()
        if lfp is None:
            return self._plan_cache.get(key)
        return self.ctx._plan_cache_get(
            (lfp, self.ctx.catalog.generation) + key
        )

    def _plan_cache_put(self, key, plan) -> None:
        lfp = self._logical_fingerprint()
        if lfp is None:
            self._plan_cache[key] = plan
        else:
            self.ctx._plan_cache_put(
                (lfp, self.ctx.catalog.generation) + key, plan
            )

    @staticmethod
    def _pcfg_key(cfg: PlannerConfig) -> tuple:
        """EVERY PlannerConfig field keys the plan caches (same rule as the
        DistributedConfig cfg_key below: a hand-picked subset silently
        serves stale plans when e.g. max_slots changes via SET — at
        session-cache scope a fresh ctx.sql() no longer re-plans, so the
        key must carry the full config)."""
        return tuple(
            getattr(cfg, k) for k in type(cfg).__dataclass_fields__
        )

    def physical_plan(self, config: Optional[PlannerConfig] = None,
                      subquery_executor=None) -> ExecutionPlan:
        from datafusion_distributed_tpu.plan.verify import (
            enforce_verification,
        )

        cfg = config or self.ctx.config.planner
        key = ("single", self._pcfg_key(cfg), subquery_executor is not None)
        plan = self._plan_cache_get(key)
        if plan is None:
            planner = PhysicalPlanner(self.ctx.catalog, cfg, subquery_executor)
            plan = planner.plan(self.logical)
            self._plan_cache_put(key, plan)
        # static verification at the cheapest point — before any trace/
        # compile (plan/verify.py; memoized on the plan object, so cache
        # hits and retry-loop re-submissions re-verify for free)
        enforce_verification(
            plan, options=self.ctx.config.distributed_options,
            context="physical plan",
        )
        return plan

    def collect_table(self) -> Table:
        """Execute, with automatic re-plan on hash/join capacity overflow —
        the static-shape analogue of the reference's pending->ready two-phase
        planning: capacities are planned optimistically and revised on
        overflow."""
        cfg = self.ctx.config.planner
        last_err: Optional[Exception] = None
        for _attempt in range(self.ctx.config.overflow_retries + 1):
            try:
                # planning is inside the try: scalar subqueries execute at
                # plan time and their overflows must trigger the same retry
                plan = self.physical_plan(cfg)
                _overflow_retry_guard(plan, _attempt, last_err)
                out = execute_plan(plan)
                self.last_retry_count = _attempt  # observability (sweeps)
                return out
            except RuntimeError as e:
                if isinstance(e, OverflowRetryAbandoned):
                    raise
                if "overflow" not in str(e):
                    raise
                last_err = e
                cfg, _ = _widen_for_overflow(
                    cfg, None, e,
                    force_all=_attempt
                    >= self.ctx.config.overflow_retries - 1,
                )
        raise last_err  # type: ignore[misc]

    def collect(self):
        """-> pyarrow Table with user-facing column names."""
        return table_to_arrow(self._strip_quals(self.collect_table()))

    def to_pandas(self):
        return self._strip_quals(self.collect_table()).to_pandas()

    @staticmethod
    def _strip_quals(t: Table) -> Table:
        names = []
        seen = set()
        for n in t.names:
            short = n.split(".")[-1] if "." in n else n
            # duplicate short names (SELECT c.x, o.x) keep their qualifier
            names.append(n if short in seen else short)
            seen.add(short)
        return Table(tuple(names), t.columns, t.num_rows)

    # -- distributed execution -------------------------------------------------
    def distributed_plan(self, num_tasks: int = 8, config=None,
                         planner_config: Optional[PlannerConfig] = None,
                         mesh=None, eager_subqueries: bool = False,
                         coordinator=None):
        from datafusion_distributed_tpu.planner.distributed import (
            DistributedConfig,
            distribute_plan,
        )

        if config is None:
            opts = {
                k: v
                for k, v in self.ctx.config.distributed_snapshot().items()
                if k in DistributedConfig.__dataclass_fields__
            }
            opts.setdefault("num_tasks", num_tasks)
            config = DistributedConfig(**opts)
        cfg = config
        pcfg = planner_config or self.ctx.config.planner
        # EVERY plan-shaping config field keys the cache (a hand-picked
        # subset silently served stale plans when e.g. max_tasks_per_stage
        # changed via SET); the unhashable estimator keys by identity
        cfg_key = tuple(
            id(v) if k == "task_estimator" else v
            for k, v in (
                (k, getattr(cfg, k))
                for k in type(cfg).__dataclass_fields__
            )
        )
        from datafusion_distributed_tpu.plan.verify import (
            enforce_verification,
        )

        verify_kw = dict(
            options=self.ctx.config.distributed_options,
            mesh_axis_size=(mesh.shape["tasks"] if mesh is not None
                            else None),
            context="distributed plan",
        )
        key = ("dist", cfg_key, self._pcfg_key(pcfg), mesh is not None,
               eager_subqueries, coordinator is not None)
        plan = self._plan_cache_get(key)
        if plan is not None:
            enforce_verification(plan, **verify_kw)
            return plan
        subquery_executor = None
        if mesh is not None:
            from datafusion_distributed_tpu.runtime.mesh_executor import (
                execute_on_mesh,
            )

            def subquery_executor(p):
                return execute_on_mesh(distribute_plan(p, cfg), mesh)
        elif coordinator is not None:
            # Plans shipped to workers must be self-contained, AND the
            # subquery must run through the SAME distributed path as the
            # outer query: f32 sums are only bitwise-reproducible under an
            # identical task split (TPC-H q15 compares them for equality).
            def subquery_executor(p):
                return coordinator.execute(distribute_plan(p, cfg))
        elif eager_subqueries:
            # Plans shipped to workers must be self-contained: lazy
            # ScalarSubqueryExpr nodes cannot cross the wire codec, so
            # uncorrelated scalar subqueries resolve to constants at plan
            # time (single-node — their results are scalars).
            def subquery_executor(p):
                return execute_plan(p)

        planner = PhysicalPlanner(self.ctx.catalog, pcfg, subquery_executor)
        plan = distribute_plan(planner.plan(self.logical), cfg)
        self._plan_cache_put(key, plan)
        enforce_verification(plan, **verify_kw)
        return plan

    def collect_distributed_table(self, num_tasks: Optional[int] = None,
                                  mesh=None) -> Table:
        """Execute over a jax Mesh: the whole staged plan compiles into one
        SPMD program (see runtime/mesh_executor.py). Overflow -> re-plan with
        widened capacities, like collect_table."""
        import jax as _jax

        from datafusion_distributed_tpu.planner.distributed import DistributedConfig
        from datafusion_distributed_tpu.runtime.mesh_executor import (
            execute_on_mesh,
            make_mesh,
        )

        if mesh is None:
            mesh = make_mesh(num_tasks or len(_jax.devices()))
        t = mesh.shape["tasks"]
        pcfg = self.ctx.config.planner
        # uniform_stage_tasks: one SPMD program's exchanges are axis-wide
        # collectives, so every stage runs at the physical mesh width —
        # per-stage lattice knobs apply to the host/coordinator tier
        dcfg = replace(
            self._seeded_distributed_config(t), uniform_stage_tasks=True
        )
        last_err: Optional[Exception] = None
        for _attempt in range(self.ctx.config.overflow_retries + 1):
            try:
                plan = self.distributed_plan(t, dcfg, pcfg, mesh=mesh)
                _overflow_retry_guard(plan, _attempt, last_err)
                out = execute_on_mesh(plan, mesh)
                self.last_retry_count = _attempt
                return out
            except RuntimeError as e:
                if isinstance(e, OverflowRetryAbandoned):
                    raise
                if "overflow" not in str(e):
                    raise
                last_err = e
                # widen in place so every other customized field survives
                # the retry (session SET options, skew factor included)
                pcfg, dcfg = _widen_for_overflow(
                    pcfg, dcfg, e,
                    force_all=_attempt
                    >= self.ctx.config.overflow_retries - 1,
                )
        raise last_err  # type: ignore[misc]

    def _seeded_distributed_config(self, num_tasks: int):
        """DistributedConfig honoring the session's `SET distributed.*`
        options (the reference's ConfigExtension flow; previously
        collect_distributed_table silently bypassed them)."""
        from datafusion_distributed_tpu.planner.distributed import (
            DistributedConfig,
        )

        opts = {
            k: v for k, v in self.ctx.config.distributed_snapshot().items()
            if k in DistributedConfig.__dataclass_fields__
        }
        opts["num_tasks"] = num_tasks
        return DistributedConfig(**opts)

    def _seeded_host_config(self, num_tasks: int):
        """Like _seeded_distributed_config, but for the host/coordinator
        tier where task counts are real scheduling units: bytes-based
        sizing is on by default (SET distributed.size_tasks_to_data=false
        opts out)."""
        cfg = self._seeded_distributed_config(num_tasks)
        if "size_tasks_to_data" not in self.ctx.config.distributed_options:
            cfg = replace(cfg, size_tasks_to_data=True)
        return cfg

    def _result_cache_key(self, num_tasks: int):
        """Whole-result cache key for this query at the session's live
        configuration (plan/fingerprint.py result_cache_key): the
        post-hoist staged-plan fingerprint + literal parameter vectors,
        extended with the full PlannerConfig snapshot, the catalog
        generation, and the task profile (f32 sums are only bitwise-
        reproducible under an identical task split, so a profile change
        must miss). None when caching cannot apply (unfingerprintable
        plan — e.g. unresolved scalar subqueries)."""
        from datafusion_distributed_tpu.plan.fingerprint import (
            result_cache_key,
        )

        try:
            plan = self.distributed_plan(
                num_tasks, self._seeded_host_config(num_tasks),
                self.ctx.config.planner,
            )
            return result_cache_key(plan, extra=(
                self._pcfg_key(self.ctx.config.planner),
                self.ctx.catalog.generation,
                int(num_tasks),
            ))
        except Exception:
            return None

    def collect_coordinated_table(
        self,
        coordinator=None,
        num_workers: int = 2,
        num_tasks: int = 4,
        adaptive: bool = False,
    ) -> Table:
        """Execute through the host Coordinator/Worker runtime (the cross-
        host DCN tier) instead of a single SPMD mesh program. With no
        ``coordinator`` an in-memory cluster of ``num_workers`` is spun up —
        the reference's InMemoryChannelResolver rung its whole TPC suite
        runs on (`tpch_correctness_test.rs:23-80`). ``adaptive=True`` uses
        the AdaptiveCoordinator (dynamic_task_count analogue).

        With `SET distributed.result_cache` on, the whole-result cache
        is consulted FIRST (runtime/result_cache.py): a hit returns the
        staged result by reference — no cluster, no coordinator, no
        execution, zero new XLA traces. Concurrent submissions of one
        key single-flight: one executes, the rest block for its fill."""
        rc = self.ctx.result_cache()
        key = self._result_cache_key(num_tasks) if rc is not None else None
        if key is None:
            return self._collect_coordinated_uncached(
                coordinator, num_workers, num_tasks, adaptive
            )
        state, cached = rc.begin(key)
        if state == "hit":
            return cached
        try:
            out = self._collect_coordinated_uncached(
                coordinator, num_workers, num_tasks, adaptive
            )
        except BaseException:
            rc.fail(key)
            raise
        rc.fill(key, out)
        return out

    def _collect_coordinated_uncached(
        self,
        coordinator=None,
        num_workers: int = 2,
        num_tasks: int = 4,
        adaptive: bool = False,
    ) -> Table:
        from datafusion_distributed_tpu.runtime.coordinator import (
            AdaptiveCoordinator,
            Coordinator,
            InMemoryCluster,
        )

        if coordinator is None:
            cluster = InMemoryCluster(num_workers)
            cls = AdaptiveCoordinator if adaptive else Coordinator
            coordinator = cls(
                resolver=cluster, channels=cluster,
                config_options=self.ctx.config.distributed_snapshot(),
                passthrough_headers=dict(self.ctx.config.passthrough_headers),
            )
        if getattr(coordinator, "result_cache", None) is None:
            # cross-query sub-plan frontier sharing rides the same
            # coordinator hook as checkpoint restore (None when the
            # result_cache knob is off)
            coordinator.result_cache = self.ctx.result_cache()
        pcfg = self.ctx.config.planner
        dcfg = self._seeded_host_config(num_tasks)
        last_err: Optional[Exception] = None
        adaptive_coord = hasattr(coordinator, "pin_overflow_headroom")
        try:
            for _attempt in range(self.ctx.config.overflow_retries + 1):
                if adaptive_coord and _attempt:
                    # widen-and-pin for the retry (see
                    # AdaptiveCoordinator.pin_overflow_headroom: subquery
                    # successes through the same coordinator must not reset
                    # the widened headroom mid-attempt)
                    coordinator.pin_overflow_headroom(_attempt)
                try:
                    plan = self.distributed_plan(
                        num_tasks, dcfg, pcfg, coordinator=coordinator
                    )
                    _overflow_retry_guard(plan, _attempt, last_err)
                    out = coordinator.execute(plan)
                    self.last_retry_count = _attempt
                    return out
                except RuntimeError as e:
                    if isinstance(e, OverflowRetryAbandoned):
                        raise
                    if "overflow" not in str(e):
                        raise
                    last_err = e
                    pcfg, dcfg = _widen_for_overflow(
                        pcfg, dcfg, e,
                        force_all=_attempt
                        >= self.ctx.config.overflow_retries - 1,
                    )
            raise last_err  # type: ignore[misc]
        finally:
            if adaptive_coord:
                coordinator.release_overflow_headroom()

    def collect_coordinated(self, **kw):
        return table_to_arrow(
            self._strip_quals(self.collect_coordinated_table(**kw))
        )

    def collect_distributed(self, num_tasks: Optional[int] = None, mesh=None):
        return table_to_arrow(
            self._strip_quals(self.collect_distributed_table(num_tasks, mesh))
        )

    def explain(self) -> str:
        return self.physical_plan().display_tree()

    def explain_verify(self, num_tasks: Optional[int] = None,
                       mesh_axis_size: Optional[int] = None
                       ) -> "VerifyReport":
        """The `EXPLAIN VERIFY` surface: the STAGED plan annotated with
        every verifier diagnostic per node (plan/verify.py), plus the
        single-node plan's diagnostics when they differ. Never raises on a
        malformed plan — the whole point is to show what strict mode would
        reject."""
        from datafusion_distributed_tpu.plan.verify import (
            render_verified_tree,
            verify_physical_plan,
        )

        t = num_tasks or int(
            self.ctx.config.distributed_options.get("num_tasks", 8)
        )
        plan = self._plan_without_enforce(t)
        result = verify_physical_plan(plan, mesh_axis_size=mesh_axis_size)
        return VerifyReport(render_verified_tree(plan, result), result)

    def _plan_without_enforce(self, num_tasks: int):
        """Build the staged plan with enforcement suppressed: EXPLAIN
        VERIFY must render a strict-mode-rejected plan, not die on it."""
        opts = self.ctx.config.distributed_options
        saved = opts.get("verify_plans")
        opts["verify_plans"] = "off"
        try:
            return self.distributed_plan(
                num_tasks, self._seeded_distributed_config(num_tasks),
                self.ctx.config.planner,
            )
        finally:
            if saved is None:
                opts.pop("verify_plans", None)
            else:
                opts["verify_plans"] = saved

    def explain_distributed(self, num_tasks: int = 8) -> str:
        from datafusion_distributed_tpu.planner.distributed import (
            display_staged_plan,
        )

        return display_staged_plan(self.distributed_plan(num_tasks))

    def logical_display(self) -> str:
        return self.logical.display_tree()


class VerifyReport(str):
    """The result of `EXPLAIN VERIFY` / `DataFrame.explain_verify`: renders
    as the annotated plan tree; `.result` carries the structured
    VerifyResult and `.diagnostics` the raw Diagnostic list."""

    def __new__(cls, text: str, result):
        obj = super().__new__(cls, text)
        obj.result = result
        obj.diagnostics = result.diagnostics
        return obj


class SessionContext:
    def __init__(self, config: Optional[SessionConfig] = None):
        import threading

        self.catalog = Catalog()
        self.config = config or SessionConfig()
        # session-level physical-plan cache, keyed by (logical-plan
        # fingerprint, catalog generation, planner knobs): distinct
        # ctx.sql(text) submissions of the same query reuse the planned
        # tree (and therefore every downstream compiled-program cache
        # entry) instead of re-planning. Bounded LRU: entries pin scan
        # Tables that may since have been de-registered. Locked: the
        # serving tier plans concurrent submissions from N client/driver
        # threads against this one cache.
        self._plans: dict = {}
        self._plans_lock = threading.Lock()
        # fingerprint-keyed whole-result + sub-plan cache (runtime/
        # result_cache.py), created lazily on the first consult with
        # `SET distributed.result_cache` on; _plans_lock guards creation
        self._result_cache = None  # guarded-by: _plans_lock

    _PLAN_CACHE_ENTRIES = 128

    def result_cache(self):
        """The session's ResultCache when `SET distributed.result_cache`
        is on, else None. Every consult reconciles the cache with the
        live catalog generation (lazy invalidation — covers table
        registrations that bypassed SessionContext.register_table) and
        the `result_cache_budget_bytes` knob."""
        from datafusion_distributed_tpu.ops.table import parse_bool_knob

        opts = self.config.distributed_options
        try:
            if not parse_bool_knob(opts.get("result_cache", False)):
                return None
        except ValueError:
            return None
        rc = self._result_cache
        if rc is None:
            from datafusion_distributed_tpu.runtime.result_cache import (
                ResultCache,
            )

            with self._plans_lock:
                rc = self._result_cache
                if rc is None:
                    rc = self._result_cache = ResultCache()
        rc.sync(
            generation=self.catalog.generation,
            budget_bytes=opts.get("result_cache_budget_bytes", 0),
        )
        return rc

    def _plan_cache_get(self, key):
        with self._plans_lock:
            plan = self._plans.get(key)
            if plan is not None:
                self._plans.pop(key)
                self._plans[key] = plan  # move-to-end: LRU
            return plan

    def _plan_cache_put(self, key, plan) -> None:
        with self._plans_lock:
            while len(self._plans) >= self._PLAN_CACHE_ENTRIES:
                self._plans.pop(next(iter(self._plans)))
            self._plans[key] = plan

    # -- registration ---------------------------------------------------------
    def register_parquet(self, name: str, paths, capacity: Optional[int] = None):
        import pyarrow as pa
        import pyarrow.parquet as pq

        if isinstance(paths, (str,)):
            paths = [paths]
        tables = [pq.read_table(p) for p in paths]
        arrow = pa.concat_tables(tables) if len(tables) > 1 else tables[0]
        self.register_table(name, arrow_to_table(arrow, capacity=capacity))

    def register_arrow(self, name: str, arrow_table, capacity=None):
        self.register_table(name, arrow_to_table(arrow_table, capacity))

    def register_table(self, name: str, table: Table):
        self.catalog.register_table(name, table)
        rc = self._result_cache
        if rc is not None:
            # eager half of result-cache invalidation: the generation
            # bump above makes every cached entry (whole-result AND
            # sub-plan frontier) stale — drop them NOW so a post-update
            # query can never be served pre-update rows
            rc.invalidate_generation(self.catalog.generation)

    # -- SQL ------------------------------------------------------------------
    def sql(self, query: str) -> DataFrame:
        stmts = parse_statements(query)
        result: Optional[DataFrame] = None
        views: dict[str, LogicalPlan] = dict(self.catalog.views)
        for stmt in stmts:
            if isinstance(stmt, CreateView):
                binder = Binder(_ViewCatalog(self.catalog, views), views)
                plan = binder.bind(stmt.query)
                if stmt.column_aliases:
                    from datafusion_distributed_tpu.plan import expressions as pe
                    from datafusion_distributed_tpu.sql.logical import LProject

                    fields = plan.schema().fields
                    if len(stmt.column_aliases) != len(fields):
                        raise ValueError("view column alias arity mismatch")
                    plan = LProject(
                        [(pe.Col(f.name), n)
                         for f, n in zip(fields, stmt.column_aliases)],
                        plan,
                    )
                views[stmt.name.lower()] = plan
                self.catalog.views[stmt.name.lower()] = plan
            elif isinstance(stmt, DropView):
                views.pop(stmt.name.lower(), None)
                self.catalog.views.pop(stmt.name.lower(), None)
            elif isinstance(stmt, SetOption):
                self.config.set_option(stmt.name, stmt.value)
            elif isinstance(stmt, ExplainVerify):
                binder = Binder(_ViewCatalog(self.catalog, views), views)
                # keep looping: statements after EXPLAIN VERIFY in a
                # multi-statement script still execute; the report is the
                # script's result only when it is the last statement
                result = DataFrame(self, binder.bind(stmt.query)).explain_verify()
            else:
                binder = Binder(_ViewCatalog(self.catalog, views), views)
                result = DataFrame(self, binder.bind(stmt))
        if result is None:
            if stmts:
                return None  # DDL/SET-only script
            raise ValueError("no SQL statements in input")
        return result

    def last_trace(self):
        """Chrome trace-event JSON dict of the most recently completed
        traced query (load in Perfetto / chrome://tracing), or None when
        nothing ran with `SET distributed.tracing` on. Coordinated
        executions record into the process-wide trace store regardless of
        which coordinator object ran them (runtime/tracing.py)."""
        from datafusion_distributed_tpu.runtime.tracing import (
            DEFAULT_TRACE_STORE,
            to_chrome_trace,
        )

        trace = DEFAULT_TRACE_STORE.last()
        return to_chrome_trace(trace) if trace is not None else None

    def last_trace_profile(self) -> str:
        """Text profile report of the most recent traced query ('' when
        none) — the explain_analyze trace fold, standalone."""
        from datafusion_distributed_tpu.runtime.tracing import (
            DEFAULT_TRACE_STORE,
            render_profile,
        )

        trace = DEFAULT_TRACE_STORE.last()
        return render_profile(trace) if trace is not None else ""

    def prepare(self, template: str) -> PreparedStatement:
        """Prepared-statement API: ``ctx.prepare("... where x < $1")``
        -> a PreparedStatement whose ``execute(params)`` /
        ``submit(serving_session, params)`` bindings share one compiled
        program per stage via the literal-hoisting + fingerprint
        machinery (plan/fingerprint.py) — zero compiles at serving time
        after the first execution."""
        return PreparedStatement(self, template)


def _parse_placeholders(template: str) -> list:
    """-> [(literal_text | None, param_name | None)] segments of a
    prepared-statement template. Placeholders are ``$name`` or ``$1``-style
    (1-based positional); ``$`` inside single-quoted SQL string literals,
    double-quoted identifiers, and ``--`` / ``/* */`` comments is text,
    not a placeholder (standard '' / "" escaping respected)."""
    import re as _re

    out: list = []
    buf: list = []
    i, n = 0, len(template)
    ph = _re.compile(r"\$([A-Za-z_][A-Za-z0-9_]*|[0-9]+)")
    while i < n:
        c = template[i]
        if c in ("'", '"'):
            q = c
            j = i + 1
            while j < n:
                if template[j] == q:
                    if j + 1 < n and template[j + 1] == q:
                        j += 2
                        continue
                    break
                j += 1
            buf.append(template[i:j + 1])
            i = j + 1
        elif c == "-" and template[i:i + 2] == "--":
            j = template.find("\n", i)
            j = n if j < 0 else j
            buf.append(template[i:j])
            i = j
        elif c == "/" and template[i:i + 2] == "/*":
            j = template.find("*/", i + 2)
            j = n if j < 0 else j + 2
            buf.append(template[i:j])
            i = j
        elif c == "$":
            m = ph.match(template, i)
            if m:
                if buf:
                    out.append(("".join(buf), None))
                    buf = []
                out.append((None, m.group(1)))
                i = m.end()
            else:
                buf.append(c)
                i += 1
        else:
            buf.append(c)
            i += 1
    if buf:
        out.append(("".join(buf), None))
    return out


def _format_param(value) -> str:
    """SQL literal text for a bound parameter value. Numeric and date
    parameters become exactly the literals the template author would have
    written — so the PR 2 literal hoist lifts them into the runtime
    parameter vectors and every binding shares one compiled program."""
    import datetime as _dt

    if value is None:
        return "NULL"
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, int):
        return str(value)
    if isinstance(value, float):
        return repr(value)
    if isinstance(value, _dt.datetime):
        # DATE32 is the engine's only temporal type: a datetime binds as
        # its date ONLY when that loses nothing — a nonzero time-of-day
        # silently admitting/excluding a day's rows must be an error
        if (value.hour or value.minute or value.second
                or value.microsecond or value.tzinfo is not None):
            raise TypeError(
                "datetime parameters with a time-of-day (or tzinfo) are "
                "not supported — the engine's temporal type is DATE32; "
                "pass a datetime.date"
            )
        return f"date '{value.date().isoformat()}'"
    if isinstance(value, _dt.date):
        return f"date '{value.isoformat()}'"
    if isinstance(value, str):
        return "'" + value.replace("'", "''") + "'"
    raise TypeError(
        f"unsupported prepared-statement parameter type "
        f"{type(value).__name__}"
    )


class PreparedStatement:
    """A parameterized query template (``ctx.prepare(sql)``) riding the
    cross-query compile-reuse machinery: every ``execute(params)`` binds
    the parameter values as literals, and because the PR 2 literal hoist
    lifts numeric/date comparison literals into runtime parameter vectors
    keyed out of the plan fingerprint, all bindings of one template share
    ONE compiled program per stage — zero new XLA compiles at serving
    time after the first (warming) execution. String parameters bind too,
    but distinct string values fingerprint distinctly (their evaluation
    is trace-time dictionary work) and compile per distinct value.

    Placeholders: ``$name`` (bind with a dict / kwargs) or ``$1..$n``
    (bind with a sequence). `warm()` runs the first (compiling) execution
    eagerly so serving-path submissions are execute-bound from the start.
    """

    def __init__(self, ctx: "SessionContext", template: str):
        self.ctx = ctx
        self.template = template
        self._segments = _parse_placeholders(template)
        names: list[str] = []
        for _text, name in self._segments:
            if name is not None and name not in names:
                names.append(name)
        if not names:
            raise ValueError(
                "prepared statement has no $placeholders — use ctx.sql()"
                " for parameter-free queries"
            )
        self.param_names = names
        self.positional = all(n.isdigit() for n in names)

    def _mapping(self, params, kw) -> dict:
        if params is None:
            mapping = dict(kw)
        elif isinstance(params, dict):
            mapping = {**params, **kw}
        elif isinstance(params, (list, tuple)):
            if not self.positional:
                raise ValueError(
                    "sequence parameters require $1..$n placeholders; "
                    f"this template names {self.param_names}"
                )
            mapping = {str(i + 1): v for i, v in enumerate(params)}
            mapping.update(kw)
        else:
            raise TypeError(
                "params must be a dict, a sequence, or keyword arguments"
            )
        missing = [n for n in self.param_names if n not in mapping]
        if missing:
            raise ValueError(f"missing parameters: {missing}")
        return mapping

    def bind_sql(self, params=None, **kw) -> str:
        """The template with every placeholder bound as a SQL literal."""
        mapping = self._mapping(params, kw)
        return "".join(
            text if name is None else _format_param(mapping[name])
            for text, name in self._segments
        )

    def to_df(self, params=None, **kw) -> "DataFrame":
        """Plan the bound statement (session plan cache applies)."""
        return self.ctx.sql(self.bind_sql(params, **kw))

    def execute(self, params=None, **kw):
        """Single-process execution -> pyarrow Table."""
        return self.to_df(params, **kw).collect()

    def execute_coordinated(self, params=None, coordinator=None,
                            num_workers: int = 2, num_tasks: int = 4,
                            **kw):
        """Distributed (host-runtime tier) execution -> pyarrow Table."""
        return self.to_df(params, **kw).collect_coordinated(
            coordinator=coordinator, num_workers=num_workers,
            num_tasks=num_tasks,
        )

    def submit(self, session, params=None, priority: int = 0, **kw):
        """Submit a binding to a ServingSession -> QueryHandle (the
        serving hot path: parse + bind + plan-cache hit + fingerprint-
        keyed program reuse, no compiles after warm())."""
        return session.submit(self.bind_sql(params, **kw),
                              priority=priority)

    def warm(self, params=None, **kw) -> "PreparedStatement":
        """Run the first (compiling) execution now; subsequent bindings
        are execute-bound. -> self, for chaining."""
        self.execute(params, **kw)
        return self


class _ViewCatalog:
    """Catalog facade that also resolves registered views (as CTEs)."""

    def __init__(self, catalog: Catalog, views: dict):
        self.catalog = catalog
        self.views = views

    def has_table(self, name: str) -> bool:
        return self.catalog.has_table(name) or name.lower() in self.views

    def table_schema(self, name: str) -> Schema:
        if name.lower() in self.views:
            s = self.views[name.lower()].schema()
            from datafusion_distributed_tpu.schema import Field

            return Schema(
                [Field(f.name.split(".")[-1], f.dtype, f.nullable)
                 for f in s.fields]
            )
        return self.catalog.table_schema(name)

    def table_rows(self, name: str) -> int:
        if name.lower() in self.views:
            return 1000
        return self.catalog.table_rows(name)

    def column_ndv(self, table: str, column: str):
        if table.lower() in self.views:
            return None
        return self.catalog.column_ndv(table, column)

    def scan_exec(self, name: str, columns):
        return self.catalog.scan_exec(name, columns)
