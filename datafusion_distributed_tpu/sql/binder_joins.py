"""Join-ordering half of the binder (mixin; split out of logical.py).

Implicit comma joins: WHERE conjuncts are classified into single-relation
filters (pushed down), equi-join edges (drive a greedy left-deep join order
by estimated fan-out), and residual post-join filters. Explicit [OUTER]
JOINs fold in written order (outer joins are never reordered).
"""

from __future__ import annotations

from datafusion_distributed_tpu.plan import expressions as pe
from datafusion_distributed_tpu.sql import parser as ast
from datafusion_distributed_tpu.sql.ast_utils import _split_conjuncts
from datafusion_distributed_tpu.sql.lplan import (
    LFilter,
    LJoin,
    LProject,
    LScan,
    LSetOp,
    LogicalPlan,
)
from datafusion_distributed_tpu.sql.scope import BindError


class JoinOrderingMixin:
    """Binder methods for explicit-join folding and implicit join ordering."""

    # -- join ordering --------------------------------------------------------
    def _fold_explicit_join(self, uplan, ualiases, jc, ralias, rplan, scope,
                            outer_refs):
        """Fold one explicit [OUTER] JOIN clause in written order (outer joins
        must not be reordered; the preserved side is the accumulated left)."""
        if jc.kind == "cross":
            return LJoin(uplan, rplan, "cross", [], [])
        on_conjuncts = _split_conjuncts(jc.on) if jc.on is not None else []
        lkeys, rkeys = [], []
        post: list = []
        for c in on_conjuncts:
            aliases = self._aliases_of(c, scope)
            if (
                isinstance(c, ast.Binary) and c.op == "=="
                and len(aliases) == 2
            ):
                la = self._aliases_of(c.left, scope)
                ra = self._aliases_of(c.right, scope)
                if la <= ualiases and ra == {ralias}:
                    lkeys.append(self._bind_expr(c.left, scope, outer_refs))
                    rkeys.append(self._bind_expr(c.right, scope, outer_refs))
                    continue
                if ra <= ualiases and la == {ralias}:
                    lkeys.append(self._bind_expr(c.right, scope, outer_refs))
                    rkeys.append(self._bind_expr(c.left, scope, outer_refs))
                    continue
            if aliases == {ralias} and jc.kind in ("left", "inner"):
                # null-supplying-side-only conjunct: pre-filtering that side
                # is equivalent for LEFT (and INNER) joins
                rplan = LFilter(self._bind_expr(c, scope, outer_refs), rplan)
                continue
            post.append(c)
        if post:
            if jc.kind != "inner":
                raise BindError(
                    f"unsupported non-equi ON conjunct for {jc.kind.upper()} "
                    f"JOIN: {post[0]!r}"
                )
        if not lkeys:
            raise BindError(
                f"{jc.kind.upper()} JOIN without an equi ON condition"
            )
        kind = jc.kind
        fanout = self._scan_fanout(rplan, rkeys)
        if kind == "right":
            # preserved side must be the probe: swap
            out = LJoin(rplan, uplan, "left", rkeys, lkeys)
        elif kind == "full":
            # FULL OUTER = LEFT JOIN  UNION ALL  (right rows with no match,
            # left columns padded with typed NULLs) — the mirror of the
            # reference's HashJoinExec Full mode, built from the primitives
            # the TPU kernels already have (left + anti).
            lj = LJoin(uplan, rplan, "left", lkeys, rkeys)
            anti = LJoin(rplan, uplan, "anti", rkeys, lkeys)
            null_left = LProject(
                [(pe.Literal(None, f.dtype), f.name)
                 for f in uplan.schema().fields]
                + [(pe.Col(f.name), f.name) for f in rplan.schema().fields],
                anti,
            )
            out = LSetOp("union", True, lj, null_left)
        else:
            out = LJoin(uplan, rplan, kind, lkeys, rkeys,
                        fanout_hint=fanout)
        for c in post:
            out = LFilter(self._bind_expr(c, scope, outer_refs), out)
        return out

    def _scan_fanout(self, rplan: LogicalPlan, rkeys: list) -> float:
        """Estimated matches per probe row for a join against ``rplan`` on
        ``rkeys`` (bound Cols): rows(build) / ndv(build key). Explicit JOINs
        (q72's catalog_sales x inventory on item_sk) can be many-to-many;
        starting the output capacity at the NDV-implied expansion avoids
        burning every overflow retry on a 1x initial guess."""
        scans: dict[str, LScan] = {}

        def walk(n):
            if isinstance(n, LScan):
                scans[n.alias] = n
            for c in n.children():
                walk(c)

        walk(rplan)
        if not scans:
            return 1.0
        fanouts = []
        for k in rkeys:
            if not isinstance(k, pe.Col) or "." not in k.name:
                continue
            alias, _, col = k.name.partition(".")
            scan = scans.get(alias)
            if scan is None:
                continue
            try:
                # filter-discounted build rows (same heuristic as
                # _relation_rows: /3 per filter above the scan) — the full
                # table row count would overstate the fan-out by the build
                # side's selectivity
                rows = self._relation_rows(alias, rplan)
                ndv = self.catalog.column_ndv(scan.table, col)
            except Exception:
                continue
            if ndv:
                fanouts.append(max(float(rows) / float(ndv), 1.0))
        # several equi keys bound the fan-out by the most selective one
        return min(fanouts) if fanouts else 1.0

    def _join_fanout(self, edge, ualiases, urows, alias_tables) -> float:
        """Estimated output rows per probe row if this edge attaches the
        unit: rows(new) / ndv(new-side key). FK->PK joins (unique key on the
        new side) give ~1; low-cardinality keys (nationkey=nationkey) give a
        blow-up factor the orderer must avoid."""
        la, le, ra, re_ = edge
        inner_ast = le if la in ualiases else re_
        if not isinstance(inner_ast, ast.Ident):
            return 1.0
        # resolve alias for the ident within the unit
        alias = inner_ast.qualifier
        if alias is None:
            alias = la if la in ualiases else ra
        table = alias_tables.get(alias)
        if table is None:
            return 1.0
        ndv = self.catalog.column_ndv(table, inner_ast.name)
        if not ndv:
            return 1.0
        return max(float(urows) / float(ndv), 1.0)

    def _order_joins(self, units, equi_edges, scope, outer_refs,
                     alias_tables=None):
        """Greedily join units (relations or pre-folded outer-join groups):
        probe side = the largest unit (the fact table keeps output
        cardinality bounded by the probe side, which is what the static
        output-capacity model wants); among connected candidates, attach the
        one with the smallest estimated fan-out first (FK->PK dimension
        joins before many-to-many edges), breaking ties by unit size."""
        alias_tables = alias_tables or {}
        units = [list(u) for u in units]
        if len(units) == 1:
            return units[0][0]
        start = max(range(len(units)), key=lambda i: units[i][2])
        plan, joined, _rows = units[start]
        remaining = [u for i, u in enumerate(units) if i != start]
        edges = list(equi_edges)
        while remaining:
            candidates = []
            for ui, u in enumerate(remaining):
                _, ualiases, urows = u
                fanouts = []
                for e in edges:
                    la, _, ra, _ = e
                    if (la in joined and ra in ualiases) or (
                        ra in joined and la in ualiases
                    ):
                        fanouts.append(
                            self._join_fanout(e, ualiases, urows, alias_tables)
                        )
                if fanouts:
                    # several edges bound the fan-out by the most selective
                    candidates.append((min(fanouts), urows, ui))
            if not candidates:
                u = remaining.pop(0)
                plan = LJoin(plan, u[0], "cross", [], [])
                joined |= u[1]
                continue
            candidates.sort()
            best_fanout, _, ui = candidates[0]
            u = remaining.pop(ui)
            _, ualiases, _ = u
            lkeys, rkeys, rest = [], [], []
            for e in edges:
                la, le, ra, re_ = e
                if la in joined and ra in ualiases:
                    lkeys.append(self._bind_expr(le, scope, outer_refs))
                    rkeys.append(self._bind_expr(re_, scope, outer_refs))
                elif ra in joined and la in ualiases:
                    lkeys.append(self._bind_expr(re_, scope, outer_refs))
                    rkeys.append(self._bind_expr(le, scope, outer_refs))
                else:
                    rest.append(e)
            edges = rest
            plan = LJoin(plan, u[0], "inner", lkeys, rkeys,
                         fanout_hint=float(best_fanout))
            joined |= ualiases
        # edges whose endpoints ended up in the same unit: residual filters
        for la, le, ra, re_ in edges:
            pred = pe.BinaryOp(
                "==",
                self._bind_expr(le, scope, outer_refs),
                self._bind_expr(re_, scope, outer_refs),
            )
            plan = LFilter(pred, plan)
        return plan

    def _relation_rows(self, alias: str, plan: LogicalPlan) -> int:
        """Estimate rows under a relation's plan (scan size, filter discount)."""
        if isinstance(plan, LFilter):
            return max(self._relation_rows(alias, plan.child) // 3, 1)
        if isinstance(plan, LScan):
            try:
                return self.catalog.table_rows(plan.table)
            except Exception:
                return 1000
        if plan.children():
            return max(self._relation_rows(alias, c) for c in plan.children())
        return 1000
