"""Name-resolution scopes and binder errors (split out of logical.py)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from datafusion_distributed_tpu.schema import Field, Schema
from datafusion_distributed_tpu.sql import parser as ast

# ---------------------------------------------------------------------------
# Binder
# ---------------------------------------------------------------------------



class BindError(ValueError):
    pass


@dataclass
class Scope:
    """In-scope relations: [(alias, original Schema)] resolving to flat names."""

    entries: list  # [(alias, Schema)]
    parent: Optional["Scope"] = None

    def resolve(self, ident: ast.Ident) -> tuple[str, Field, int]:
        """-> (flat_name, field, depth); depth 0 = local, 1+ = outer scope."""
        depth = 0
        scope: Optional[Scope] = self
        while scope is not None:
            hits = []
            for alias, schema in scope.entries:
                if ident.qualifier is not None and ident.qualifier != alias:
                    continue
                if ident.name in schema:
                    hits.append((alias, schema.field(ident.name)))
            if len(hits) > 1:
                raise BindError(f"ambiguous column {ident.key()!r}")
            if hits:
                alias, f = hits[0]
                flat = f"{alias}.{ident.name}" if alias else ident.name
                return flat, f, depth
            scope = scope.parent
            depth += 1
        raise BindError(f"unknown column {ident.key()!r}")


@dataclass
class OuterRef:
    """Recorded reference from a subquery into an enclosing scope."""

    flat_name: str
    field: Field
