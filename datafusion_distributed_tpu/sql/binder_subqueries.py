"""Subquery half of the binder (mixin; split out of logical.py).

Decorrelation machinery: uncorrelated scalar subqueries become lazily
executed ScalarSubqueryExpr placeholders; correlated scalar-aggregate
subqueries decorrelate into GROUP BY + LEFT JOIN (TPC-H q2/q17/q20 shape);
[NOT] EXISTS / [NOT] IN become semi/anti/mark joins with optional residual
predicates (q4/q21/q22); disjunctive subquery predicates lower to mark
joins. The reference gets all of this from DataFusion upstream — this is
original machinery with no reference counterpart.
"""

from __future__ import annotations

import itertools

from datafusion_distributed_tpu.plan import expressions as pe
from datafusion_distributed_tpu.schema import Field, Schema
from datafusion_distributed_tpu.sql import parser as ast
from datafusion_distributed_tpu.sql.ast_utils import (
    _ast_children,
    _ast_substitute,
    _collect_col_names,
    _contains_subquery,
    _has_aggregates,
    _hoist_common_or,
    _join_conjuncts,
    _project_through,
    _split_conjuncts,
)
from datafusion_distributed_tpu.sql.lplan import (
    LFilter,
    LJoin,
    LProject,
    LogicalPlan,
)
from datafusion_distributed_tpu.sql.scope import BindError, OuterRef, Scope

# mark-join column namer: process-wide so two filters in one query can't
# collide, resettable (like planner._TMP) so plan snapshots are reproducible
_MARK_SEQ = itertools.count()


# ---------------------------------------------------------------------------
# Scalar subquery expression (executed lazily by the physical layer)
# ---------------------------------------------------------------------------


class ScalarSubqueryExpr(pe.PhysicalExpr):
    """Placeholder for an uncorrelated scalar subquery; the physical planner
    replaces it with a literal after executing the subplan (the reference
    disables DataFusion's uncorrelated-subquery pushdown and relies on plain
    planning, `session_state_builder_ext.rs:17-27` — here we evaluate it as a
    prepared constant instead)."""

    def __init__(self, logical: LogicalPlan):
        self.logical = logical
        self.physical = None  # filled by the physical planner

    def children(self):
        return []

    def evaluate(self, table):
        raise RuntimeError(
            "ScalarSubqueryExpr must be resolved by the physical planner"
        )

    def output_field(self, schema):
        f = self.logical.schema().fields[0]
        return Field("__scalar_subquery", f.dtype, True)

    def display(self):
        return "(scalar subquery)"



class SubqueryDecorrelationMixin:
    """Binder methods for subquery predicates and decorrelation."""

    # -- subquery predicates ----------------------------------------------------
    def _apply_subquery_pred(self, c, plan, scope, outer_refs) -> LogicalPlan:
        if isinstance(c, ast.Exists):
            return self._bind_exists(c.query, c.negated, plan, scope)
        if isinstance(c, ast.Unary) and c.op == "not" and isinstance(
            c.child, ast.Exists
        ):
            return self._bind_exists(c.child.query, not c.child.negated, plan, scope)
        if isinstance(c, ast.InSubquery):
            return self._bind_in_subquery(c, plan, scope, outer_refs)
        if isinstance(c, ast.Between) and not c.negated:
            # BETWEEN with subquery bounds (TPC-DS q54): split into the two
            # comparisons and route each through the right binder
            for shard in (
                ast.Binary(">=", c.expr, c.low),
                ast.Binary("<=", c.expr, c.high),
            ):
                if _contains_subquery(shard):
                    plan = self._apply_subquery_pred(
                        shard, plan, scope, outer_refs
                    )
                else:
                    plan = LFilter(
                        self._bind_expr(shard, scope, outer_refs), plan
                    )
            return plan
        if isinstance(c, ast.Binary) and c.op == "and":
            for side in (c.left, c.right):
                if _contains_subquery(side):
                    plan = self._apply_subquery_pred(
                        side, plan, scope, outer_refs
                    )
                else:
                    plan = LFilter(
                        self._bind_expr(side, scope, outer_refs), plan
                    )
            return plan
        if isinstance(c, ast.Binary) and c.op == "or":
            # disjunction containing EXISTS/IN-subquery (TPC-DS q35/q45):
            # each subquery becomes a MARK join; the disjunction then
            # evaluates over the mark columns as a plain filter
            return self._apply_disjunctive_subquery(c, plan, scope, outer_refs)
        # scalar subquery inside a comparison
        return self._bind_scalar_pred(c, plan, scope, outer_refs)

    def _apply_disjunctive_subquery(self, c, plan, scope, outer_refs):
        """Rewrite a boolean expression whose leaves include EXISTS /
        IN-subquery into mark joins + a boolean filter over the mark columns
        (the reference gets this from DataFusion's subquery decorrelation,
        which lowers to the same mark-join shape)."""
        plan_box = [plan]

        def walk(node):
            if isinstance(node, ast.Binary) and node.op in ("and", "or"):
                l = walk(node.left)
                r = walk(node.right)
                return pe.BooleanOp(node.op, l, r)
            if isinstance(node, ast.Unary) and node.op == "not":
                return pe.Not(walk(node.child))
            if isinstance(node, ast.Exists):
                mark = self._mark_join_exists(node, plan_box, scope)
                return pe.Not(mark) if node.negated else mark
            if isinstance(node, ast.InSubquery):
                mark = self._mark_join_in(node, plan_box, scope, outer_refs)
                return pe.Not(mark) if node.negated else mark
            return self._bind_expr(node, scope, outer_refs)

        def _mark_name():
            # process-wide monotonic counter: unique across every mark join
            # in the query AND deterministic (resettable) for plan snapshots
            return f"__mark_{next(_MARK_SEQ)}"

        self.__mark_name = _mark_name  # shared with helpers below
        pred = walk(c)
        return LFilter(pred, plan_box[0])

    def _mark_join_exists(self, node: ast.Exists, plan_box, scope):
        sub_binder = type(self)(self.catalog, self.ctes)
        sub_refs: list = []
        sub_plan, corr_pairs, residual = sub_binder._bind_correlated(
            node.query, scope, sub_refs
        )
        if not corr_pairs:
            raise BindError("uncorrelated EXISTS not supported yet")
        name = self.__mark_name()
        plan_box[0] = LJoin(
            plan_box[0], sub_plan, "mark",
            [pe.Col(outer) for outer, _ in corr_pairs],
            [inner for _, inner in corr_pairs],
            residual=residual, mark_name=name,
        )
        return pe.Col(name)

    def _mark_join_in(self, node: ast.InSubquery, plan_box, scope, outer_refs):
        expr = self._bind_expr(node.expr, scope, outer_refs)
        sub_binder = type(self)(self.catalog, self.ctes)
        sub_refs: list = []
        sub_plan, corr_pairs, residual = sub_binder._bind_correlated(
            node.query, scope, sub_refs
        )
        out_cols = sub_plan.schema()
        if len(out_cols) - len(corr_pairs) != 1 and len(out_cols) != 1:
            raise BindError("IN subquery must produce one column")
        name = self.__mark_name()
        plan_box[0] = LJoin(
            plan_box[0], sub_plan, "mark",
            [expr] + [pe.Col(outer) for outer, _ in corr_pairs],
            [pe.Col(out_cols.fields[0].name)] + [
                inner for _, inner in corr_pairs
            ],
            residual=residual, mark_name=name,
        )
        return pe.Col(name)

    def _bind_exists(self, subq: ast.Query, negated: bool, plan, scope):
        sub_binder = type(self)(self.catalog, self.ctes)
        sub_refs: list[OuterRef] = []
        sub_plan, corr_pairs, residual = sub_binder._bind_correlated(
            subq, scope, sub_refs
        )
        if not corr_pairs:
            raise BindError("uncorrelated EXISTS not supported yet")
        lkeys = [pe.Col(outer) for outer, _ in corr_pairs]
        rkeys = [inner for _, inner in corr_pairs]
        how = "anti" if negated else "semi"
        return LJoin(plan, sub_plan, how, lkeys, rkeys, residual=residual)

    def _bind_in_subquery(self, c: ast.InSubquery, plan, scope, outer_refs):
        expr = self._bind_expr(c.expr, scope, outer_refs)
        sub_binder = type(self)(self.catalog, self.ctes)
        sub_refs: list[OuterRef] = []
        sub_plan, corr_pairs, residual = sub_binder._bind_correlated(
            c.query, scope, sub_refs
        )
        out_cols = sub_plan.schema()
        if len(out_cols) - len(corr_pairs) != 1 and len(out_cols) != 1:
            raise BindError("IN subquery must produce one column")
        value_col = pe.Col(out_cols.fields[0].name)
        lkeys = [expr] + [pe.Col(outer) for outer, _ in corr_pairs]
        rkeys = [value_col] + [inner for _, inner in corr_pairs]
        how = "anti" if c.negated else "semi"
        return LJoin(plan, sub_plan, how, lkeys, rkeys, residual=residual,
                     null_aware=c.negated)

    def _bind_scalar_pred(self, c, plan, scope, outer_refs):
        """Comparison against a scalar subquery (correlated or not)."""
        if not (isinstance(c, ast.Binary) and c.op in ("==", "!=", "<", "<=",
                                                       ">", ">=")):
            raise BindError(
                f"unsupported subquery predicate shape: {type(c).__name__}"
            )
        # The subquery may sit anywhere inside the comparison (TPC-DS q6:
        # `price > 1.2 * (select avg(...))`): locate it, bind it, splice the
        # bound scalar back in, then bind the whole comparison normally.
        found: list = []

        def hunt(node):
            if isinstance(node, ast.ScalarSubquery):
                found.append(node)
                return node  # do not descend further
            return None

        _ast_substitute(c, hunt)
        if len(found) != 1:
            raise BindError("expected scalar subquery in comparison")
        sub_ast = found[0]

        sub_binder = type(self)(self.catalog, self.ctes)
        sub_refs: list[OuterRef] = []
        sub_plan, corr_pairs, residual = sub_binder._bind_correlated(
            sub_ast.query, scope, sub_refs
        )
        if residual is not None:
            raise BindError("non-equi correlation in scalar subquery")

        if not corr_pairs:
            # uncorrelated: evaluate eagerly at execution time
            spliced = _ast_substitute(
                c, lambda n: ast.PreBound(ScalarSubqueryExpr(sub_plan))
                if n is sub_ast else None,
            )
            return LFilter(self._bind_expr(spliced, scope, outer_refs), plan)

        # correlated scalar aggregate: sub_plan is Aggregate(groups=corr keys)
        scalar_col = pe.Col(sub_plan.schema().fields[-1].name)
        lkeys = [pe.Col(outer) for outer, _ in corr_pairs]
        rkeys = [inner for _, inner in corr_pairs]
        joined = LJoin(plan, sub_plan, "left", lkeys, rkeys)
        spliced = _ast_substitute(
            c, lambda n: ast.PreBound(scalar_col) if n is sub_ast else None,
        )
        filtered = LFilter(
            self._bind_expr(spliced, scope, outer_refs), joined
        )
        # project away subquery columns
        keep = [
            (pe.Col(f.name), f.name) for f in plan.schema().fields
        ]
        return LProject(keep, filtered)

    def _bind_correlated(self, subq: ast.Query, outer_scope, sub_refs):
        """Bind a subquery that may reference the outer scope.

        Returns (plan, corr_pairs, residual) where corr_pairs are
        (outer_flat_name, inner key PhysicalExpr) equi correlations hoisted
        out of the subquery's WHERE, and residual is a bound predicate over
        the [outer columns joined with subquery output] schema for non-equi
        correlated conjuncts (EXISTS with <> as in TPC-H q21).
        """
        q = subq
        conjuncts = _split_conjuncts(q.where) if q.where is not None else []
        # surface correlations hidden inside OR branches (q41 shape)
        conjuncts = [x for c in conjuncts for x in _hoist_common_or(c)]
        corr: list[tuple[str, ast.Ident]] = []  # (outer flat, inner ast)
        residual_asts: list = []
        local: list = []
        probe_scope = self._subquery_scope(q, outer_scope)
        for c in conjuncts:
            side = self._correlation_side(c, probe_scope)
            if side == "local":
                local.append(c)
            elif side == "equi":
                outer_ast, inner_ast = self._split_correlation(c, probe_scope)
                corr.append((outer_ast, inner_ast))
            else:  # residual correlated
                residual_asts.append(c)

        q2 = ast.Query(
            select_items=q.select_items,
            from_refs=q.from_refs,
            where=_join_conjuncts(local),
            group_by=q.group_by,
            having=q.having,
            order_by=q.order_by,
            limit=q.limit,
            offset=q.offset,
            distinct=q.distinct,
            ctes=q.ctes,
        )

        if corr and _has_aggregates(q2):
            # correlated scalar aggregate -> group by correlation keys
            inner_group_asts = [inner for _, inner in corr]
            q2 = ast.Query(
                select_items=list(q2.select_items)
                + [ast.SelectItem(a, f"__corr{i}") for i, a in
                   enumerate(inner_group_asts)],
                from_refs=q2.from_refs,
                where=q2.where,
                group_by=list(q2.group_by) + inner_group_asts,
                having=q2.having,
                order_by=[],
                limit=None,
                offset=None,
                distinct=False,
                ctes=q2.ctes,
            )
            plan = self._bind_query(q2, None)
            fields = plan.schema().fields
            ncorr = len(corr)
            pairs = []
            for (outer_flat, _), f in zip(corr, fields[-ncorr:]):
                pairs.append((outer_flat, pe.Col(f.name)))
            # keep scalar as last col before corr keys: re-project so schema =
            # [corr keys..., scalar]
            scalar_field = fields[-ncorr - 1]
            proj = [(pe.Col(f.name), f.name) for f in fields[-ncorr:]]
            proj.append((pe.Col(scalar_field.name), scalar_field.name))
            plan = LProject(proj, plan)
            return plan, pairs, None

        plan = self._bind_query(q2, None)
        pairs = []
        for outer_flat, inner_ast in corr:
            inner_scope = self._subquery_scope(q2, None)
            inner_bound = type(self)(self.catalog, self.ctes)._bind_expr(
                inner_ast, inner_scope, None
            )
            # the subquery's output schema must expose the key column; ensure
            # it by projecting the join keys alongside existing outputs
            pairs.append((outer_flat, inner_bound))
        residual = None
        if residual_asts:
            # bind residual against outer+inner: inner entries SHADOW outer
            # ones (an unqualified name over two `item` relations must pick
            # the subquery's own, q41), while outer names stay reachable —
            # qualified or via the parent scope
            combined = Scope(
                self._subquery_scope(q2, None).entries, parent=outer_scope
            )
            shadow_refs: list = []
            bound = [
                self._bind_expr(a, combined, shadow_refs)
                for a in residual_asts
            ]
            residual = bound[0]
            for b in bound[1:]:
                residual = pe.BooleanOp("and", residual, b)
        if pairs or residual is not None:
            # Expose referenced inner columns through the subquery's output
            # projection. Outer-side names in the residual stay out — they
            # resolve against the probe side of the join at execution.
            inner_aliases = {
                alias for alias, _ in self._subquery_scope(q2, None).entries
            }
            needed = _collect_col_names(
                [p for _, p in pairs] + ([residual] if residual is not None else [])
            )
            existing = set(f.name for f in plan.schema().fields)
            missing = [
                n for n in needed
                if n not in existing and n.split(".")[0] in inner_aliases
            ]
            if missing:
                exprs = [(pe.Col(f.name), f.name) for f in plan.schema().fields]
                exprs += [(pe.Col(n), n) for n in missing]
                plan = _project_through(plan, exprs)
        return plan, pairs, residual

    def _subquery_scope(self, q: ast.Query, outer_scope) -> Scope:
        entries = []
        for base, joins in q.from_refs:
            for ref in [base] + [j.right for j in joins]:
                if isinstance(ref, ast.TableRef):
                    alias = ref.alias or ref.name
                    if ref.name in self.ctes:
                        sub = self.ctes[ref.name]
                        names = [f.name.split(".")[-1] for f in sub.schema().fields]
                        entries.append(
                            (alias, Schema([Field(n, f.dtype, f.nullable)
                                            for n, f in zip(names, sub.schema().fields)]))
                        )
                    else:
                        entries.append((alias, self.catalog.table_schema(ref.name)))
                else:
                    sub_binder = type(self)(self.catalog, self.ctes)
                    sub = sub_binder._bind_query(ref.query, None)
                    names = ref.column_aliases or [
                        f.name.split(".")[-1] for f in sub.schema().fields
                    ]
                    entries.append(
                        (ref.alias, Schema([Field(n, f.dtype, f.nullable)
                                            for n, f in zip(names, sub.schema().fields)]))
                    )
        return Scope(entries, parent=outer_scope)

    def _combined_scope(self, q: ast.Query, outer_scope) -> Scope:
        inner = self._subquery_scope(q, None)
        entries = list(inner.entries) + (
            list(outer_scope.entries) if outer_scope else []
        )
        return Scope(entries)

    def _correlation_side(self, c, probe_scope: Scope) -> str:
        """'local' (no outer refs) | 'equi' (outer = inner) | 'residual'."""
        refs = self._outer_ref_names(c, probe_scope)
        if not refs:
            return "local"
        if isinstance(c, ast.Binary) and c.op == "==":
            lrefs = self._outer_ref_names(c.left, probe_scope)
            rrefs = self._outer_ref_names(c.right, probe_scope)
            if (
                isinstance(c.left, ast.Ident)
                and lrefs
                and not rrefs
                or isinstance(c.right, ast.Ident)
                and rrefs
                and not lrefs
            ):
                return "equi"
        return "residual"

    def _split_correlation(self, c: ast.Binary, probe_scope: Scope):
        lrefs = self._outer_ref_names(c.left, probe_scope)
        if lrefs and isinstance(c.left, ast.Ident):
            outer_ast, inner_ast = c.left, c.right
        else:
            outer_ast, inner_ast = c.right, c.left
        flat, _, _ = probe_scope.parent.resolve(outer_ast) if probe_scope.parent else (
            None, None, None
        )
        if flat is None:
            raise BindError("failed to resolve correlation")
        return flat, inner_ast

    def _outer_ref_names(self, node, probe_scope: Scope) -> list[str]:
        out = []

        def walk(n):
            if isinstance(n, ast.Ident):
                try:
                    _, _, depth = probe_scope.resolve(n)
                    if depth > 0:
                        out.append(n.key())
                except BindError:
                    pass
                return
            for ch in _ast_children(n):
                walk(ch)

        walk(node)
        return out

    def _aliases_of(self, node, scope: Scope) -> set:
        out: set = set()

        def walk(n):
            if isinstance(n, ast.Ident):
                try:
                    flat, _, depth = scope.resolve(n)
                    if depth == 0:
                        out.add(flat.split(".")[0])
                except BindError:
                    pass
                return
            for ch in _ast_children(n):
                walk(ch)

        walk(node)
        return out
