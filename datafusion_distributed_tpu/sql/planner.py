"""Logical -> physical planning.

The reference defers single-node physical planning to DataFusion and then
rewrites the tree distributively (SURVEY.md §3.1). Our logical tree lowers to
the TPU ExecutionPlan IR here; the distributed planner (planner/) then splits
that physical tree into stages. Responsibilities:

- scan column pruning (only columns referenced anywhere above reach HBM),
- materializing group/agg/sort/join-key expressions into named columns,
- COUNT(DISTINCT x) -> two-level aggregate rewrite,
- resolving uncorrelated scalar subqueries into lazily-executed constants,
- capacity/slot policy via PlannerConfig (join expansion, agg load factor).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, replace
from typing import Optional, Sequence

from datafusion_distributed_tpu.ops.aggregate import AggSpec
from datafusion_distributed_tpu.ops.sort import SortKey
from datafusion_distributed_tpu.ops.table import round_up_pow2
from datafusion_distributed_tpu.plan import expressions as pe
from datafusion_distributed_tpu.plan.joins import (
    CrossJoinExec,
    HashJoinExec,
    UnionExec,
)
from datafusion_distributed_tpu.plan.physical import (
    ExecutionPlan,
    FilterExec,
    HashAggregateExec,
    LimitExec,
    ProjectionExec,
    SortExec,
)
from datafusion_distributed_tpu.schema import DataType, Field, Schema
from datafusion_distributed_tpu.sql import logical as lg

_TMP = itertools.count()


@dataclass
class PlannerConfig:
    join_expansion_factor: float = 1.0
    agg_slot_factor: float = 2.0
    max_slots: int = 1 << 21
    max_out_capacity: int = 1 << 22


class PhysicalPlanner:
    def __init__(self, catalog, config: Optional[PlannerConfig] = None,
                 subquery_executor=None):
        self.catalog = catalog
        self.config = config or PlannerConfig()
        # Scalar subqueries must run under the SAME execution mode as the
        # main query: float aggregation order differs between single-node and
        # distributed plans, and TPC-H q15's `total_revenue = (select max..)`
        # equality only holds when both sides sum in the same order.
        self.subquery_executor = subquery_executor

    # -- public ---------------------------------------------------------------
    def plan(self, logical: lg.LogicalPlan) -> ExecutionPlan:
        used = _collect_used_columns(logical)
        return self._plan(logical, used)

    # -- dispatch ---------------------------------------------------------------
    def _plan(self, node: lg.LogicalPlan, used: set) -> ExecutionPlan:
        if isinstance(node, lg.LScan):
            return self._plan_scan(node, used)
        if isinstance(node, lg.LFilter):
            child = self._plan(node.child, used)
            self._resolve_subqueries(node.predicate)
            f = FilterExec(node.predicate, child)
            # NDV-backed selectivity (replaces the cost model's blanket 1/3
            # for equality/IN predicates; consumed by statistics.estimate_rows)
            f.est_selectivity = self._predicate_selectivity(
                node.predicate, node.child
            )
            return f
        if isinstance(node, lg.LProject):
            child = self._plan(node.child, used)
            for e, _ in node.exprs:
                self._resolve_subqueries(e)
            return ProjectionExec([(e, n) for e, n in node.exprs], child)
        if isinstance(node, lg.LAggregate):
            return self._plan_aggregate(node, used)
        if isinstance(node, lg.LJoin):
            return self._plan_join(node, used)
        if isinstance(node, lg.LSort):
            return self._plan_sort(node, used)
        if isinstance(node, lg.LLimit):
            child = self._plan(node.child, used)
            return LimitExec(child, node.fetch if node.fetch is not None else
                             child.output_capacity(), node.skip)
        if isinstance(node, lg.LDistinct):
            child = self._plan(node.child, used)
            return self._distinct(child)
        if isinstance(node, lg.LSetOp):
            return self._plan_setop(node, used)
        if isinstance(node, lg.LWindow):
            return self._plan_window(node, used)
        raise NotImplementedError(f"cannot lower {type(node).__name__}")

    def _plan_window(self, node: "lg.LWindow", used: set) -> ExecutionPlan:
        from datafusion_distributed_tpu.ops.sort import SortKey
        from datafusion_distributed_tpu.ops.window import WindowFunc
        from datafusion_distributed_tpu.plan.window_exec import WindowExec
        from datafusion_distributed_tpu.schema import Field

        child = self._plan(node.child, used)
        schema = child.schema()
        passthrough = [(pe.Col(f.name), f.name) for f in schema.fields]
        extra: list = []

        def materialize(e, prefix):
            self._resolve_subqueries(e)
            if isinstance(e, pe.Col):
                return e.name
            nm = f"__{prefix}{next(_TMP)}"
            extra.append((e, nm))
            return nm

        # group window exprs by identical (partition, order) spec: one
        # WindowExec per spec
        groups: dict = {}
        for w in node.exprs:
            part_names = tuple(materialize(p, "wp") for p in w.partition_by)
            order_keys = tuple(
                SortKey(materialize(oe, "wo"), asc,
                        (not asc) if nf is None else nf)
                for oe, asc, nf in w.order_by
            )
            arg_name = None
            if w.arg is not None:
                arg_name = materialize(w.arg, "wa")
            spec = (part_names, order_keys)
            groups.setdefault(spec, []).append((w, arg_name))

        plan: ExecutionPlan = (
            ProjectionExec(passthrough + extra, child) if extra else child
        )
        cs = node.child.schema()
        for (part_names, order_keys), ws in groups.items():
            funcs = [
                WindowFunc(w.func, arg_name, w.name, w.frame)
                for w, arg_name in ws
            ]
            fields = [
                Field(w.name, lg._window_dtype(w, cs), True)
                for w, _ in ws
            ]
            plan = WindowExec(plan, funcs, list(part_names),
                              list(order_keys), fields)
        return plan

    # -- scans ------------------------------------------------------------------
    def _plan_scan(self, node: lg.LScan, used: set) -> ExecutionPlan:
        needed_orig = []
        for f in node.table_schema.fields:
            flat = f"{node.alias}.{f.name}"
            if flat in used:
                needed_orig.append(f.name)
        if not needed_orig:
            needed_orig = [node.table_schema.fields[0].name]
        scan = self.catalog.scan_exec(node.table, needed_orig)
        rename = [
            (pe.Col(orig), f"{node.alias}.{orig}") for orig in needed_orig
        ]
        return ProjectionExec(rename, scan)

    # -- aggregate ----------------------------------------------------------------
    def _plan_aggregate(self, node: lg.LAggregate, used: set) -> ExecutionPlan:
        child = self._plan(node.child, used)
        distinct_aggs = [a for a in node.aggs if a.distinct]
        regular = [a for a in node.aggs if not a.distinct]

        # materialize group + agg input expressions
        mat: list = []
        group_names = []
        for e, name in node.groups:
            self._resolve_subqueries(e)
            mat.append((e, name))
            group_names.append(name)
        specs: list[AggSpec] = []
        for a in node.aggs:
            if a.func == "count_star":
                specs.append(AggSpec("count_star", None, a.name))
                continue
            self._resolve_subqueries(a.arg)
            in_name = f"__in_{a.name}"
            mat.append((a.arg, in_name))
            specs.append(AggSpec(a.func, in_name, a.name))
        proj = ProjectionExec(mat, child) if mat else child

        if distinct_aggs and regular:
            # Mixed DISTINCT + plain aggregates (TPC-DS q28/q94/q95,
            # ClickBench q9/q22): each part aggregates independently over
            # the same child; parts stitch back via a 1:1 join on the group
            # keys (global: cross join of 1-row results). A projection
            # restores the original output order.
            from datafusion_distributed_tpu.plan.joins import (
                CrossJoinExec, HashJoinExec,
            )
            from datafusion_distributed_tpu.plan import expressions as pe

            by_name = dict(zip([a.name for a in node.aggs], specs))
            plain_specs = [by_name[a.name] for a in regular]
            groups_ndv = self._exprs_ndv(node.child,
                                         [e for e, _ in node.groups],
                                         loose=True)
            slots = self._agg_slots(proj.output_capacity(), groups_ndv,
                                    child=proj)
            base_slots = 16 if not group_names else slots
            combined = HashAggregateExec(
                "single", group_names, plain_specs, proj, base_slots
            )
            if groups_ndv and group_names:
                combined.est_rows = float(groups_ndv)
            for i, a in enumerate(distinct_aggs):
                s = by_name[a.name]
                dedup_ndv = self._exprs_ndv(
                    node.child, [e for e, _ in node.groups] + [a.arg],
                    loose=True,
                )
                dedup = HashAggregateExec(
                    "single", group_names + [s.input_name], [], proj,
                    self._agg_slots(proj.output_capacity(), dedup_ndv, child=proj),
                )
                cnt = HashAggregateExec(
                    "single", group_names,
                    [AggSpec("count", s.input_name, s.output_name)],
                    dedup, base_slots,
                )
                if not group_names:
                    combined = CrossJoinExec(combined, cnt, out_capacity=16)
                    continue
                # rename build-side group keys to avoid name collisions in
                # the joined schema
                renamed = ProjectionExec(
                    [(pe.Col(g), f"__dk{i}_{g}") for g in group_names]
                    + [(pe.Col(s.output_name), s.output_name)],
                    cnt,
                )
                combined = HashJoinExec(
                    combined, renamed,
                    group_names, [f"__dk{i}_{g}" for g in group_names],
                    "inner", expansion_factor=1.0,
                    out_capacity=combined.output_capacity(),
                )
            order = [(pe.Col(g), g) for g in group_names] + [
                (pe.Col(a.name), a.name) for a in node.aggs
            ]
            return ProjectionExec(order, combined)

        if distinct_aggs:
            # COUNT(DISTINCT x): dedup (groups + x), then count per group.
            inner_groups = group_names + [s.input_name for s in specs]
            inner_ndv = self._exprs_ndv(
                node.child,
                [e for e, _ in node.groups] + [a.arg for a in node.aggs],
                loose=True,
            )
            slots = self._agg_slots(proj.output_capacity(), inner_ndv,
                                    child=proj)
            dedup = HashAggregateExec("single", inner_groups, [], proj, slots)
            if inner_ndv:
                # estimate_rows(dedup) would otherwise fall back to
                # sqrt(n) and undersize the outer aggregate's by_est cap
                dedup.est_rows = float(inner_ndv)
            outer_specs = [
                AggSpec("count", s.input_name, s.output_name) for s in specs
            ]
            groups_ndv = self._exprs_ndv(node.child,
                                         [e for e, _ in node.groups],
                                         loose=True)
            slots2 = self._agg_slots(dedup.output_capacity(), groups_ndv,
                                     child=dedup)
            out = HashAggregateExec(
                "single", group_names, outer_specs, dedup, slots2
            )
            if groups_ndv:
                out.est_rows = float(groups_ndv)
            return out

        groups_ndv = self._exprs_ndv(node.child, [e for e, _ in node.groups],
                                     loose=True)
        slots = self._agg_slots(proj.output_capacity(), groups_ndv,
                                child=proj)
        out = HashAggregateExec("single", group_names, specs, proj, slots)
        if groups_ndv:
            # catalog NDV as the group-count estimate (replaces the cost
            # model's sqrt(n) guess; consumed by statistics.estimate_rows)
            out.est_rows = float(groups_ndv)
        return out

    def _predicate_selectivity(self, pred, child: lg.LogicalPlan,
                               ) -> Optional[float]:
        """Selectivity estimate from catalog NDV (the statistics the cost
        model previously guessed as a blanket 1/3): equality on a base
        column keeps ~1/NDV rows, IN keeps k/NDV, AND multiplies, OR adds.
        None = no NDV-backed estimate (range predicates, derived exprs)."""
        if isinstance(pred, pe.BooleanOp):
            l = self._predicate_selectivity(pred.left, child)
            r = self._predicate_selectivity(pred.right, child)
            if l is None and r is None:
                return None
            l = 1.0 / 3.0 if l is None else l
            r = 1.0 / 3.0 if r is None else r
            return max(l * r, 1e-6) if pred.op == "and" else min(l + r, 1.0)
        if isinstance(pred, pe.Not):
            s = self._predicate_selectivity(pred.child, child)
            return None if s is None else max(1.0 - s, 1e-6)
        if isinstance(pred, pe.BinaryOp) and pred.op == "==":
            col, other = pred.left, pred.right
            if not isinstance(col, pe.Col):
                col, other = other, col
            if isinstance(col, pe.Col) and isinstance(other, pe.Literal):
                ndv = self._exprs_ndv(child, [col])
                if ndv:
                    return 1.0 / ndv
        if isinstance(pred, pe.InList) and isinstance(pred.child, pe.Col):
            ndv = self._exprs_ndv(child, [pred.child])
            if ndv:
                s = min(len(pred.values) / ndv, 1.0)
                return max(1.0 - s, 1e-6) if pred.negated else s
        return None

    def _agg_slots(self, cap: int, ndv: Optional[int] = None,
                   child=None) -> int:
        """Hash-table slots for a group-by: capacity-bounded, NDV-driven,
        row-estimate-capped.

        The reference sizes aggregation hash tables dynamically as groups
        arrive; with static shapes the table must be pre-sized, and sizing by
        input *capacity* (round 1) made a 6-group GROUP BY run a 2M-slot
        claim loop — ~260 GB of HBM traffic on TPC-H q1 (measured on TPU
        v5e). When the distinct-group estimate is known, size by it instead:
        2x the planner's slot factor over the estimate keeps the probe chain
        short, and the session's overflow-retry loop (collect_table) widens
        by 4x if the estimate was low — the same optimistic-plan /
        revise-on-overflow posture as join capacities.

        ``child`` (the agg's physical input) adds a third bound: groups
        can never exceed input ROWS, and after selective filters/joins the
        cardinality estimate is far below both the padded capacity and the
        multi-key NDV product (q3's (orderkey, orderdate, shippriority)
        NDV-product saturates while the filtered join feeds ~29k rows).
        Row estimates are coarser than sampled NDV, so this bound gets 4x
        headroom instead of 2x; an underestimate costs one overflow-retry.
        """
        by_cap = min(
            round_up_pow2(max(int(cap * self.config.agg_slot_factor), 16)),
            self.config.max_slots,
        )
        best = by_cap
        if ndv:
            by_ndv = round_up_pow2(
                max(int(ndv * self.config.agg_slot_factor * 2), 16)
            )
            best = min(best, by_ndv)
        if child is not None:
            from datafusion_distributed_tpu.planner.statistics import (
                estimate_rows,
            )

            est = estimate_rows(child)
            by_est = round_up_pow2(
                max(int(est * self.config.agg_slot_factor * 4), 16)
            )
            best = min(best, by_est)
        return best

    def _exprs_ndv(self, child: lg.LogicalPlan,
                   exprs: Sequence[pe.PhysicalExpr],
                   loose: bool = False) -> Optional[int]:
        """Distinct-count estimate for a tuple of expressions, or None.

        Two modes:
        - strict (default): direct base-table column references (via the
          catalog's sampled NDV), followed through projection ALIASES; any
          derived expression makes the tuple unknown. Safe for selectivity
          (1/NDV) estimates.
        - loose=True: additionally derives UPPER bounds for common derived
          shapes — calendar parts (EXTRACT/DATE_TRUNC caps), unary
          value-preserving ops, binary arithmetic (ndv product),
          boolean-valued ops (3), CASE/COALESCE (branch sums). Upper
          bounds are only safe for capacity SIZING (an overestimate just
          pads; q9's (nation, o_year) aggregate sized 2M slots for a true
          NDV of ~175 without them) — NOT for 1/NDV selectivity, where a
          loose bound inverts into an underestimate.

        Products over multiple keys ignore correlation, which biases the
        multi-key estimate *upward* (joins can't mint new key values).
        Per-column estimates, however, come from a strided SAMPLE: below
        the extrapolation threshold they can undercount true NDV, so the
        catalog pads non-extrapolated sampled counts (see
        `Catalog.column_ndv`) — treat the result as a best-effort sizing
        hint backed by the overflow-retry loop, not a hard upper bound."""
        ndv_fn = getattr(self.catalog, "column_ndv", None)
        if ndv_fn is None:
            return None
        aliases: dict[str, str] = {}
        proj_map: dict = {}
        _poisoned = object()
        stack = [child]
        while stack:
            n = stack.pop()
            if isinstance(n, lg.LScan):
                # the same alias naming DIFFERENT base tables in nested
                # scopes (correlated subquery reusing an outer alias) makes
                # the lookup ambiguous: poison it rather than let the
                # last-visited scan win and size against the wrong table
                if aliases.get(n.alias, n.table) != n.table:
                    aliases[n.alias] = None
                else:
                    aliases[n.alias] = n.table
            elif isinstance(n, lg.LProject):
                # projection aliases let bounds see THROUGH derived columns
                # (q9 groups by a subquery's `o_year` = EXTRACT alias);
                # a name bound to different exprs in different branches is
                # ambiguous -> poisoned
                for e, name in n.exprs:
                    if name in proj_map and proj_map[name] is not e:
                        proj_map[name] = _poisoned
                    else:
                        proj_map.setdefault(name, e)
            stack.extend(n.children())
        # calendar-part cardinality caps (EXTRACT/DATE_TRUNC derive columns
        # with small, known ranges — without these, a GROUP BY on
        # EXTRACT(YEAR ...) falls back to row-count sizing: q9's (nation,
        # o_year) aggregate was handed 2M slots for a true NDV of ~175)
        part_caps = {
            "year": 200, "month": 12, "moy": 12, "quarter": 4, "qoy": 4,
            "day": 31, "dom": 31, "dow": 7, "doy": 366, "week": 53,
            "hour": 24, "minute": 60, "second": 60,
        }
        trunc_caps = {"year": 200, "quarter": 800, "month": 2400,
                      "week": 11000, "day": 75000}

        def col_ndv(e: pe.Col) -> Optional[int]:
            if "." not in e.name:
                return None
            alias, col = e.name.split(".", 1)
            table = aliases.get(alias)
            if table is None:
                return None
            ndv = ndv_fn(table, col)
            return int(ndv) if ndv else None

        def bound(e, depth: int = 0) -> Optional[int]:
            """Distinct count (strict) or upper bound (loose), or None."""
            if depth > 8:  # projection-chain guard
                return None
            if isinstance(e, pe.Col):
                direct = col_ndv(e)
                if direct is not None:
                    return direct
                sub = proj_map.get(e.name)
                if sub is not None and sub is not _poisoned and not (
                    isinstance(sub, pe.Col) and sub.name == e.name
                ):
                    return bound(sub, depth + 1)
                return None
            if isinstance(e, pe.Literal):
                return 1
            if not loose:
                return None
            if isinstance(e, (pe.BooleanOp, pe.Not, pe.IsNull, pe.Like,
                              pe.InList)):
                return 3  # true/false/NULL
            if isinstance(e, pe.BinaryOp) and e.op in pe._CMP_OPS:
                return 3
            if isinstance(e, pe.Extract):
                cap = part_caps.get(e.part.lower())
                inner = bound(e.child, depth + 1)
                if cap is None:
                    return inner
                return min(cap, inner) if inner else cap
            if isinstance(e, pe.DateTrunc):
                cap = trunc_caps.get(e.unit.lower())
                inner = bound(e.child, depth + 1)
                if cap is None:
                    return inner
                return min(cap, inner) if inner else cap
            if isinstance(e, (pe.Substring, pe.StringCase, pe.Cast,
                              pe.Abs, pe.Round, pe.StrLength)):
                return bound(e.children()[0], depth + 1)
            if isinstance(e, pe.BinaryOp):
                l, r = bound(e.left, depth + 1), bound(e.right, depth + 1)
                if l and r:
                    return l * r  # upper bound; correlation only shrinks it
                return None
            if isinstance(e, pe.Case):
                # value space = union of branch values (+ otherwise/NULL)
                total = 0
                for _, v in e.branches:
                    b = bound(v, depth + 1)
                    if b is None:
                        return None
                    total += b
                if e.otherwise is not None:
                    b = bound(e.otherwise, depth + 1)
                    if b is None:
                        return None
                    total += b
                return total + 1
            if isinstance(e, pe.Coalesce):
                total = 0
                for c in e.children():
                    b = bound(c, depth + 1)
                    if b is None:
                        return None
                    total += b
                return total
            return None

        est = 1
        for e in exprs:
            b = bound(e)
            if not b:
                return None
            est *= int(b)
        return est

    def _distinct(self, child: ExecutionPlan) -> ExecutionPlan:
        names = child.schema().names
        return HashAggregateExec(
            "single", names, [], child,
            self._agg_slots(child.output_capacity(), child=child),
        )

    # -- join -----------------------------------------------------------------------
    def _plan_join(self, node: lg.LJoin, used: set) -> ExecutionPlan:
        left = self._plan(node.left, used)
        right = self._plan(node.right, used)
        if node.how == "cross":
            return CrossJoinExec(left, right)

        def materialize_keys(plan, keys, side):
            names = []
            extra = []
            schema = plan.schema()
            for k in keys:
                self._resolve_subqueries(k)
                if isinstance(k, pe.Col):
                    names.append(k.name)
                else:
                    nm = f"__jk{side}{next(_TMP)}"
                    extra.append((k, nm))
                    names.append(nm)
            if extra:
                passthrough = [(pe.Col(f.name), f.name) for f in schema.fields]
                plan = ProjectionExec(passthrough + extra, plan)
            return plan, names

        left, lnames = materialize_keys(left, node.left_keys, "l")
        right, rnames = materialize_keys(right, node.right_keys, "r")
        if node.residual is not None:
            self._resolve_subqueries(node.residual)
        # Build-side hash table: CSR over DISTINCT keys (ops/join.py), so
        # slots size by build-key NDV, same rationale (and same
        # overflow-retry widening, via join_expansion_factor) as _agg_slots.
        # HashJoinExec builds over its RIGHT child (probe=left, build=right).
        build_ndv = self._exprs_ndv(node.right, node.right_keys)
        num_slots = None
        if build_ndv:
            num_slots = min(
                round_up_pow2(2 * max(right.output_capacity(), 8)),
                round_up_pow2(max(
                    int(build_ndv * 2
                        * max(1.0, self.config.join_expansion_factor)),
                    16,
                )),
                1 << 21,
            )
        join = HashJoinExec(
            left,
            right,
            lnames,
            rnames,
            node.how,
            residual=node.residual,
            mark_name=node.mark_name or "__mark",
            expansion_factor=self.config.join_expansion_factor
            * max(1.0, getattr(node, "fanout_hint", 1.0)),
            null_aware=node.null_aware,
            num_slots=num_slots,
        )
        # strip materialized key columns from inner/left outputs
        if node.how in ("inner", "left"):
            want = [f.name for f in node.schema().fields]
            have = set(join.schema().names)
            keep = [n for n in want if n in have]
            if set(keep) != set(join.schema().names):
                return ProjectionExec([(pe.Col(n), n) for n in keep], join)
        return join

    # -- sort ------------------------------------------------------------------------
    def _plan_sort(self, node: lg.LSort, used: set) -> ExecutionPlan:
        child = self._plan(node.child, used)
        schema = child.schema()
        keys = []
        extra = []
        for e, asc, nulls_first in node.keys:
            self._resolve_subqueries(e)
            if isinstance(e, pe.Col):
                name = e.name
            else:
                name = f"__sk{next(_TMP)}"
                extra.append((e, name))
            if nulls_first is None:
                nulls_first = not asc  # SQL default: NULLS LAST for ASC
            keys.append(SortKey(name, asc, nulls_first))
        plan: ExecutionPlan = child
        if extra:
            passthrough = [(pe.Col(f.name), f.name) for f in schema.fields]
            plan = ProjectionExec(passthrough + extra, plan)
        plan = SortExec(keys, plan, fetch=node.fetch)
        if extra:
            plan = ProjectionExec(
                [(pe.Col(f.name), f.name) for f in schema.fields], plan
            )
        return plan

    # -- set ops -----------------------------------------------------------------------
    def _plan_setop(self, node: lg.LSetOp, used: set) -> ExecutionPlan:
        left = self._plan(node.left, used)
        right = self._plan(node.right, used)
        if node.op == "union":
            return UnionExec([left, right])
        # INTERSECT/EXCEPT are DISTINCT semi/anti joins on all columns
        left_d = self._distinct(left)
        how = "semi" if node.op == "intersect" else "anti"
        return HashJoinExec(
            left_d, right, list(left_d.schema().names),
            list(right.schema().names), how,
            expansion_factor=self.config.join_expansion_factor,
        )

    # -- scalar subqueries ---------------------------------------------------------------
    def _resolve_subqueries(self, expr: pe.PhysicalExpr) -> None:
        # no memoization guard: a replan after an overflow must re-plan the
        # subquery with the widened config too
        if isinstance(expr, lg.ScalarSubqueryExpr):
            sub_planner = PhysicalPlanner(
                self.catalog, self.config, self.subquery_executor
            )
            expr.physical = sub_planner.plan(expr.logical)
            # Execute NOW, at planning time — this must happen outside any
            # enclosing jit trace (a nested jit during tracing would inline
            # symbolically and break host-side overflow checks).
            value, dtype = _exec_scalar(expr.physical, self.subquery_executor)
            expr.evaluate = _make_scalar_eval(value, dtype)  # type: ignore[method-assign]
            expr.resolved = (value, dtype)  # lets the wire codec ship it
        for c in expr.children():
            self._resolve_subqueries(c)


def _collect_used_columns(plan: lg.LogicalPlan) -> set:
    """Every flat column name referenced by any expression in the tree (plus
    subquery trees). Scans prune to this set — the projection-pushdown
    analogue of DataFusion's physical optimizer."""
    used: set = set()

    def walk_expr(e: pe.PhysicalExpr):
        if isinstance(e, pe.Col):
            used.add(e.name)
        if isinstance(e, lg.ScalarSubqueryExpr):
            used.update(_collect_used_columns(e.logical))
        for c in e.children():
            walk_expr(c)

    def walk(n: lg.LogicalPlan):
        if isinstance(n, lg.LFilter):
            walk_expr(n.predicate)
        elif isinstance(n, lg.LProject):
            for e, _ in n.exprs:
                walk_expr(e)
        elif isinstance(n, lg.LAggregate):
            for e, _ in n.groups:
                walk_expr(e)
            for a in n.aggs:
                if a.arg is not None:
                    walk_expr(a.arg)
        elif isinstance(n, lg.LJoin):
            for e in n.left_keys + n.right_keys:
                walk_expr(e)
            if n.residual is not None:
                walk_expr(n.residual)
        elif isinstance(n, lg.LSort):
            for e, _, _ in n.keys:
                walk_expr(e)
        elif isinstance(n, lg.LWindow):
            for w in n.exprs:
                if w.arg is not None:
                    walk_expr(w.arg)
                for p in w.partition_by:
                    walk_expr(p)
                for oe, _, _ in w.order_by:
                    walk_expr(oe)
        elif isinstance(n, (lg.LSetOp, lg.LDistinct)):
            for f in n.schema().fields:
                used.add(f.name)
        for c in n.children():
            walk(c)

    for f in plan.schema().fields:
        used.add(f.name)
    walk(plan)
    return used


def _exec_scalar(physical: ExecutionPlan, executor=None):
    """Run a scalar subquery plan to completion; -> (python scalar|None, dtype)."""
    from datafusion_distributed_tpu.plan.physical import execute_plan

    result = executor(physical) if executor is not None else execute_plan(physical)
    col = result.columns[0]
    n = int(result.num_rows)
    if n > 1:
        raise RuntimeError(
            f"scalar subquery returned {n} rows (expected at most one)"
        )
    if n == 0:
        return None, col.dtype
    if col.validity is not None and not bool(col.validity[0]):
        return None, col.dtype
    return col.data[0].item(), col.dtype


def _make_scalar_eval(value, dtype):
    import jax.numpy as jnp

    def evaluate(table):
        cap = table.capacity
        if value is None:
            return pe.ExprValue(
                jnp.zeros(cap, dtype=dtype.np_dtype),
                jnp.zeros(cap, dtype=jnp.bool_),
                dtype,
            )
        data = jnp.full(cap, value, dtype=dtype.np_dtype)
        return pe.ExprValue(data, None, dtype)

    return evaluate
