"""SQL REPL over an in-memory distributed cluster.

The reference ships a datafusion-cli fork wired to an InMemoryChannelResolver
— a full distributed REPL in one process (`/root/reference/cli/src/main.rs`).
Same capability here:

    python -m datafusion_distributed_tpu.cli \
        --register lineitem=path/to/lineitem.parquet --tasks 8

Commands inside the REPL:
    <sql>;                 run a query (single-node by default)
    \\d                     list tables
    \\explain <sql>         show the physical plan
    \\explain_dist <sql>    show the staged distributed plan
    \\dist on|off           toggle distributed (mesh) execution
    \\tpch [sf]             generate + register TPC-H tables
    \\q                     quit
"""

from __future__ import annotations

import argparse
import sys
import time


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="TPU query engine REPL")
    parser.add_argument("--register", action="append", default=[],
                        metavar="NAME=PATH", help="register a parquet table")
    parser.add_argument("--tasks", type=int, default=8,
                        help="mesh size for distributed execution")
    parser.add_argument("--command", "-c", default=None,
                        help="run one SQL string and exit")
    parser.add_argument("--tpch", type=float, default=None, metavar="SF",
                        help="generate + register TPC-H tables at this SF")
    args = parser.parse_args(argv)

    from datafusion_distributed_tpu.sql.context import SessionContext

    ctx = SessionContext()
    for spec in args.register:
        name, _, path = spec.partition("=")
        if not path:
            print(f"bad --register {spec!r}; want NAME=PATH", file=sys.stderr)
            return 2
        ctx.register_parquet(name, path)
        print(f"registered {name} from {path}")
    if args.tpch is not None:
        from datafusion_distributed_tpu.data.tpchgen import register_tpch

        register_tpch(ctx, sf=args.tpch)
        print(f"registered TPC-H tables at SF={args.tpch}")

    distributed = False

    def run_sql(sql: str) -> None:
        nonlocal distributed
        t0 = time.perf_counter()
        df = ctx.sql(sql)
        if df is None:
            print("OK")
            return
        if distributed:
            table = df.collect_distributed_table(num_tasks=args.tasks)
            out = df._strip_quals(table).to_pandas()
        else:
            out = df.to_pandas()
        dt = time.perf_counter() - t0
        with _full_width():
            print(out.to_string(index=False, max_rows=40))
        print(f"({len(out)} rows in {dt:.3f}s"
              f"{' distributed' if distributed else ''})")

    if args.command:
        run_sql(args.command)
        return 0

    print("TPU distributed query engine — \\q to quit, \\d to list tables")
    buf = ""
    while True:
        try:
            prompt = "... " if buf else "sql> "
            line = input(prompt)
        except (EOFError, KeyboardInterrupt):
            print()
            return 0
        stripped = line.strip()
        if not buf and stripped.startswith("\\"):
            cmd, _, rest = stripped.partition(" ")
            if cmd == "\\q":
                return 0
            if cmd == "\\d":
                for name in sorted(ctx.catalog.tables):
                    t = ctx.catalog.tables[name]
                    print(f"  {name}  ({int(t.num_rows)} rows, "
                          f"{len(t.names)} cols)")
                continue
            if cmd == "\\dist":
                distributed = rest.strip() == "on"
                print(f"distributed execution: {'on' if distributed else 'off'}")
                continue
            if cmd == "\\explain":
                print(ctx.sql(rest).explain())
                continue
            if cmd == "\\explain_dist":
                print(ctx.sql(rest).explain_distributed(args.tasks))
                continue
            if cmd == "\\tpch":
                from datafusion_distributed_tpu.data.tpchgen import register_tpch

                sf = float(rest) if rest.strip() else 0.01
                register_tpch(ctx, sf=sf)
                print(f"registered TPC-H tables at SF={sf}")
                continue
            print(f"unknown command {cmd}")
            continue
        buf += ("\n" if buf else "") + line
        if stripped.endswith(";"):
            sql, buf = buf, ""
            try:
                run_sql(sql)
            except Exception as e:
                print(f"error: {type(e).__name__}: {e}", file=sys.stderr)


class _full_width:
    def __enter__(self):
        import pandas as pd

        self._ctx = pd.option_context("display.width", 200,
                                      "display.max_columns", 50)
        self._ctx.__enter__()
        return self

    def __exit__(self, *a):
        self._ctx.__exit__(*a)


if __name__ == "__main__":
    sys.exit(main())
