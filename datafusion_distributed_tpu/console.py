"""Terminal cluster monitor — the reference `console/` (ratatui TUI) analogue.

Discovers the cluster from a seed worker via the observability service and
redraws worker + task state at a fixed poll interval
(`/root/reference/console/src/main.rs:14-47` polls GetClusterWorkers once
and GetTaskProgress every 100 ms). Pure ANSI — no curses dependency — so it
runs over any ssh/tmux session next to the bench.

Usage:
    python -m datafusion_distributed_tpu.console grpc://host:port [...]
or programmatically against any resolver/channels pair:
    Console(resolver, channels).run()
"""

from __future__ import annotations

import sys
import time
from typing import Optional

from datafusion_distributed_tpu.runtime.observability import (
    ObservabilityService,
    sample_system_metrics,
)

_CLEAR = "\x1b[2J\x1b[H"
_BOLD = "\x1b[1m"
_DIM = "\x1b[2m"
_RESET = "\x1b[0m"


# one byte formatter for the whole observability surface (panel +
# trace profile report) — defined in runtime/tracing.py
from datafusion_distributed_tpu.runtime.tracing import (  # noqa: E402
    format_bytes as _fmt_bytes,
)


class Console:
    def __init__(self, resolver, channels, poll_s: float = 0.5,
                 out=None, health=None, serving=None, faults=None,
                 checkpoints=None, telemetry=None):
        # ``health``: a coordinator's HealthTracker — wiring it in joins
        # circuit-breaker state into the membership rows below.
        # ``serving``: a runtime/serving.py ServingSession — wiring it in
        # adds the multi-query tier's active/queued/admitted line.
        # ``faults``/``checkpoints``: a coordinator's FaultCounters and a
        # runtime/checkpoint.py CheckpointStore — wiring either adds the
        # robustness line (hedge + checkpoint/resume counters).
        # ``telemetry``: a runtime/telemetry.py MetricRegistry merged
        # into the cluster metrics surface (defaults to the serving
        # session's registry when one is wired)
        self.obs = ObservabilityService(resolver, channels, health=health,
                                        serving=serving,
                                        fault_counters=faults,
                                        checkpoints=checkpoints,
                                        telemetry=telemetry)
        self.poll_s = poll_s
        self.out = out or sys.stdout
        self.tracked_keys: list = []  # TaskKeys to poll progress for
        # time-series ring feeding the sparkline columns: a wired
        # serving session SHARES its ring (its per-query samples and
        # this console's per-frame samples land in one history — the
        # session's registry series and the frame-derived qps/p99/
        # staged/fault values merge per point); standalone consoles
        # keep a local ring sampled once per rendered frame
        from datafusion_distributed_tpu.runtime.telemetry import (
            TelemetryHistory,
        )

        shared = getattr(serving, "history", None)
        # explicit None test: an EMPTY shared ring is len()-falsy but
        # still the ring to share
        self.history = shared if shared is not None else (
            TelemetryHistory(capacity=240, resolution_s=max(poll_s, 0.1))
        )

    def track(self, keys) -> None:
        self.tracked_keys = list(keys)

    @staticmethod
    def _section(lines: list, label: str, fn) -> None:
        """Degrade PER LINE: a failing store/worker/panel renders a dim
        error line instead of aborting the whole refresh loop (the
        console must stay useful exactly when parts of the cluster are
        broken)."""
        try:
            fn()
        except Exception as e:
            lines.append(
                f"{_DIM}{label} unavailable: "
                f"{str(e)[:60] or type(e).__name__}{_RESET}"
            )

    def render_frame(self) -> str:
        """One frame of the display (separated from run() for testing).
        Every panel degrades independently (`_section`): an empty or
        partially broken store renders its line as unavailable and the
        remaining panels still draw."""
        lines = []
        lines.append(
            f"{_BOLD}datafusion-distributed-tpu cluster console{_RESET}  "
            f"{_DIM}{time.strftime('%H:%M:%S')}{_RESET}"
        )
        shared: dict = {}
        self._section(lines, "workers",
                      lambda: self._render_workers(lines, shared))
        self._section(lines, "serving",
                      lambda: self._render_serving(lines, shared))
        self._section(lines, "robustness",
                      lambda: self._render_robustness(lines))
        self._section(lines, "result cache",
                      lambda: self._render_result_cache(lines, shared))
        self._section(lines, "data plane",
                      lambda: self._render_data_plane(lines, shared))
        self._section(lines, "telemetry",
                      lambda: self._render_telemetry(lines, shared))
        self._section(lines, "tracing",
                      lambda: self._render_tracing(lines))
        self._section(lines, "tasks",
                      lambda: self._render_tasks(lines))
        sm = sample_system_metrics()
        lines.append(
            f"\n{_DIM}console rss={_fmt_bytes(sm.rss_bytes)} "
            f"cpu={sm.cpu_seconds:.1f}s{_RESET}"
        )
        return "\n".join(lines)

    def _render_workers(self, lines: list, shared: dict) -> None:
        workers = self.obs.get_cluster_workers()
        shared["workers"] = workers
        mem = self.obs.get_membership()
        health = {
            w["url"]: w.get("health", {})
            for w in mem.get("workers", ())
        }
        draining = list(mem.get("draining", ()))
        head = f"\n{_BOLD}workers ({len(workers)} active"
        if draining:
            head += f", {len(draining)} draining"
        head += f"){_RESET}"
        if mem.get("epoch") is not None:
            head += f"  {_DIM}membership epoch {mem['epoch']}{_RESET}"
        lines.append(head)
        lines.append(
            f"  {'url':<28} {'tasks':>5} {'ver':>7} {'status':>10}"
        )
        for w in workers:
            if "error" in w:
                lines.append(
                    f"  {w.get('url', '?'):<28} {'-':>5} {'-':>7} "
                    f"{'DOWN':>10}  {_DIM}{w['error'][:40]}{_RESET}"
                )
                continue
            url = w.get("url", "?")
            breaker = health.get(url, {}).get("state")
            status = breaker if breaker and breaker != "closed" else "up"
            lines.append(
                f"  {url:<28} "
                f"{w.get('tasks_cached', 0):>5} "
                f"{w.get('version', '-'):>7} "
                f"{status:>10}"
            )
        for url in draining:
            try:
                info = self.obs.channels.get_worker(url).get_info()
                tasks = info.get("tasks_cached", 0)
                ver = info.get("version", "-")
            except Exception:
                tasks, ver = "-", "-"
            lines.append(
                f"  {url:<28} {tasks:>5} {ver:>7} {'draining':>10}"
            )
        # data-plane staged-byte totals from the worker infos ALREADY
        # fetched above (get_info carries "store"): no second get_info
        # fan-out per refresh (ObservabilityService.get_data_plane is the
        # standalone programmatic surface for the same numbers)
        dp = {"nbytes": 0, "entries": 0, "views": 0, "peak_nbytes": 0,
              "dedup_hits": 0, "budget_bytes": 0, "spilled_nbytes": 0,
              "spills": 0, "refaults": 0, "spill_files": 0}
        for w in workers:
            st = w.get("store")
            if isinstance(st, dict):
                for k in dp:
                    dp[k] += int(st.get(k, 0))
        shared["dp"] = dp

    def _render_serving(self, lines: list, shared: dict) -> None:
        dp = shared.get("dp", {})
        srv = self.obs.get_serving_stats()
        shared["srv"] = srv
        if srv and "error" not in srv:
            comp = srv.get("completed", {})
            lat = srv.get("latency", {}) or {}
            p99 = lat.get("p99")
            line = (
                f"\n{_BOLD}serving{_RESET}  "
                f"{srv.get('active', 0)} active, "
                f"{srv.get('queued', 0)} queued, "
                f"{srv.get('admitted_total', 0)} admitted "
                f"({comp.get('done', 0)} done, "
                f"{comp.get('failed', 0)} failed, "
                f"{comp.get('cancelled', 0)} cancelled)"
            )
            budget = srv.get("budget_bytes") or 0
            if budget:
                # admission ESTIMATE next to the ACTUAL staged bytes from
                # the workers' TableStore accounting (get_data_plane)
                line += (
                    f"  {_DIM}footprint "
                    f"{_fmt_bytes(srv.get('in_use_bytes', 0))}/"
                    f"{_fmt_bytes(budget)} est, "
                    f"{_fmt_bytes(dp.get('nbytes', 0))} staged{_RESET}"
                )
            if p99 is not None:
                line += f"  {_DIM}p99 {p99 * 1e3:.0f}ms{_RESET}"
            lines.append(line)
            # SLO line (runtime/telemetry.py SloTracker via serving
            # stats): only rendered once a target is declared
            slo = srv.get("slo") or {}
            if slo.get("p99_target_ms") is not None or (
                slo.get("error_rate_target") is not None
            ):
                segments = []
                att = slo.get("latency_attainment")
                if slo.get("p99_target_ms") is not None:
                    ok = slo.get("p99_ok")
                    # ok is None while the window is empty (idle tier):
                    # that is "no data", not a breach
                    verdict = ("no data" if ok is None
                               else "OK" if ok else "BREACH")
                    seg = (
                        f"p99 {slo.get('p99_ms') or 0:.0f}ms vs "
                        f"{slo['p99_target_ms']:.0f}ms target "
                        f"[{verdict}]"
                    )
                    if att is not None:
                        seg += f", attainment {att * 100:.1f}%"
                    segments.append(seg)
                burn = slo.get("error_budget_burn")
                if burn is not None:
                    segments.append(f"error-budget burn {burn:.2f}x")
                lines.append(
                    f"{_BOLD}slo{_RESET}      " + ", ".join(segments)
                    + f"  {_DIM}window {slo.get('window_n', 0)}q{_RESET}"
                )

    def _render_robustness(self, lines: list) -> None:
        rb = self.obs.get_robustness()
        hed = rb.get("hedging", {})
        ckpt = rb.get("checkpoint", {})
        ck_counts = {k: v for k, v in ckpt.items() if k != "store"}
        if any(hed.values()) or any(ck_counts.values()):
            line = (
                f"\n{_BOLD}robustness{_RESET}  hedges "
                f"{hed.get('hedges_issued', 0)} issued "
                f"({hed.get('hedges_won', 0)} won, "
                f"{hed.get('hedges_lost', 0)} lost, "
                f"{hed.get('hedge_budget_denied', 0)} denied), "
                f"checkpoints {ckpt.get('checkpoint_stages_saved', 0)} "
                f"saved / {ckpt.get('checkpoint_stages_restored', 0)} "
                f"restored, {ckpt.get('queries_resumed', 0)} resumed"
            )
            st = ckpt.get("store")
            if isinstance(st, dict) and not st.get("error"):
                line += (
                    f"  {_DIM}{st.get('recoverable', 0)} recoverable, "
                    f"{_fmt_bytes(st.get('staged_bytes', 0))} "
                    f"staged{_RESET}"
                )
            lines.append(line)

    def _render_result_cache(self, lines: list, shared: dict) -> None:
        """Result/sub-plan cache line: hit/miss totals with a hit-rate
        sparkline (fed through the telemetry sample below — the ring
        records at most one point per frame), live bytes vs budget, and
        spill/invalidation counters. Quiet (no line) until the cache
        sees traffic, like the robustness panel."""
        rcs = self.obs.get_result_cache()
        cache = rcs.get("cache") or {}
        shared["rc"] = cache
        if cache.get("error") or not (
                cache.get("hits") or cache.get("misses")
                or cache.get("entries")):
            return
        rate = cache.get("hit_rate")
        line = (
            f"\n{_BOLD}result cache{_RESET}  "
            f"{cache.get('hits', 0)} hits / "
            f"{cache.get('misses', 0)} misses"
        )
        spark = self.history.sparkline("rc_hit_rate")
        if spark:
            line += f"  hit-rate {spark}"
        if rate is not None:
            line += f" {rate * 100:.0f}%"
        line += (
            f"  {_fmt_bytes(cache.get('nbytes', 0))} in "
            f"{cache.get('entries', 0)}+{cache.get('subplan_entries', 0)} "
            f"entries"
        )
        extras = []
        if cache.get("budget_bytes"):
            extras.append(f"budget {_fmt_bytes(cache['budget_bytes'])}")
        if cache.get("spills"):
            extras.append(
                f"spilled {_fmt_bytes(cache.get('spilled_nbytes', 0))} "
                f"({cache.get('refaults', 0)} refaults)"
            )
        if cache.get("invalidations"):
            extras.append(f"{cache['invalidations']} invalidations")
        sp = rcs.get("subplan", {})
        if sp.get("stages_restored"):
            extras.append(f"{sp['stages_restored']} stages restored")
        if extras:
            line += f"  {_DIM}" + ", ".join(extras) + _RESET
        lines.append(line)

    def _render_data_plane(self, lines: list, shared: dict) -> None:
        dp = shared.get("dp", {})
        if dp.get("entries") or dp.get("peak_nbytes"):
            lines.append(
                f"\n{_BOLD}data plane{_RESET}  staged "
                f"{_fmt_bytes(dp.get('nbytes', 0))} in "
                f"{dp.get('entries', 0)} entries "
                f"({dp.get('views', 0)} views, "
                f"{dp.get('dedup_hits', 0)} dedup)  "
                f"{_DIM}peak {_fmt_bytes(dp.get('peak_nbytes', 0))}{_RESET}"
            )
        # enforced-budget line only once a budget or spill activity
        # exists (a quiet unbudgeted tier adds no noise)
        if dp.get("budget_bytes") or dp.get("spills"):
            lines.append(
                f"{_BOLD}memory{_RESET}     budget "
                f"{_fmt_bytes(dp.get('budget_bytes', 0))}  spilled "
                f"{_fmt_bytes(dp.get('spilled_nbytes', 0))} in "
                f"{dp.get('spill_files', 0)} files  "
                f"{_DIM}{dp.get('spills', 0)} spills / "
                f"{dp.get('refaults', 0)} refaults{_RESET}"
            )

    def _render_telemetry(self, lines: list, shared: dict) -> None:
        """Sparkline columns over the console-local history ring: qps
        and fault rate as counter RATES, p99 and staged bytes as point
        values — the at-a-glance trend row the flat counters above
        cannot show."""
        srv = shared.get("srv") or {}
        dp = shared.get("dp", {})
        comp = srv.get("completed", {}) or {}
        lat = srv.get("latency", {}) or {}
        faults = self.obs.get_fault_counters()
        self.history.sample(None, extra={
            "queries_done": sum(comp.values()) if comp else None,
            "p99_ms": (lat.get("p99") * 1e3
                       if lat.get("p99") is not None else None),
            "staged_bytes": dp.get("nbytes"),
            "faults": sum(faults.values()) if faults else 0,
            "rc_hit_rate": (shared.get("rc") or {}).get("hit_rate"),
        })
        if len(self.history) < 2:
            return  # nothing to trend yet (first frame / empty tier)
        cols = []
        qps = self.history.rate("queries_done")
        spark = self.history.sparkline("queries_done", as_rate=True)
        if spark:
            cols.append(f"qps {spark} {qps if qps is not None else 0:.2f}/s")
        spark = self.history.sparkline("p99_ms")
        if spark:
            cols.append(
                f"p99 {spark} {self.history.latest('p99_ms'):.0f}ms"
            )
        spark = self.history.sparkline("staged_bytes")
        if spark:
            cols.append(
                "staged "
                f"{spark} {_fmt_bytes(self.history.latest('staged_bytes'))}"
            )
        spark = self.history.sparkline("faults", as_rate=True)
        if spark:
            fr = self.history.rate("faults")
            cols.append(f"faults {spark} {fr if fr is not None else 0:.2f}/s")
        if cols:
            lines.append(
                f"\n{_BOLD}telemetry{_RESET}  " + "  ".join(cols)
            )

    def _render_tracing(self, lines: list) -> None:
        ts = self.obs.get_trace_summary()
        if ts and not ts.get("error") and ts.get("traces"):
            line = (
                f"\n{_BOLD}tracing{_RESET}  "
                f"{ts['traces']} traces ({ts.get('running', 0)} running), "
                f"{ts.get('spans', 0)} spans, "
                f"{ts.get('events', 0)} events, "
                f"data plane {_fmt_bytes(ts.get('data_plane_bytes', 0))}"
            )
            if ts.get("spans_dropped"):
                line += f"  {_DIM}{ts['spans_dropped']} dropped{_RESET}"
            ev = ts.get("events_by_name") or {}
            faults = {k: v for k, v in ev.items()
                      if k in ("task_retry", "task_rerouted", "peer_heal",
                               "worker_quarantined", "query_cancel",
                               "hedge_issued", "hedge_won", "hedge_lost",
                               "checkpoint_saved", "query_resumed")}
            if faults:
                line += "  " + _DIM + ", ".join(
                    f"{k}={faults[k]}" for k in sorted(faults)
                ) + _RESET
            lines.append(line)

    def _render_tasks(self, lines: list) -> None:
        if self.tracked_keys:
            prog = self.obs.get_task_progress(self.tracked_keys)
            lines.append(f"\n{_BOLD}tasks ({len(prog)}){_RESET}")
            for key, p in prog.items():
                lines.append(
                    f"  {key}  rows={p.get('output_rows', '?')} "
                    f"worker={p.get('worker', '?')}"
                )

    def run(self, frames: Optional[int] = None) -> None:
        """Redraw loop; frames=None runs until interrupted."""
        count = 0
        try:
            while frames is None or count < frames:
                self.out.write(_CLEAR + self.render_frame() + "\n")
                self.out.flush()
                count += 1
                if frames is None or count < frames:
                    time.sleep(self.poll_s)
        except KeyboardInterrupt:
            pass


def main(argv: Optional[list] = None) -> None:
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print(__doc__)
        raise SystemExit(2)

    class _StaticResolver:
        def __init__(self, urls):
            self.urls = urls

        def get_urls(self):
            return self.urls

    class _GrpcChannels:
        def __init__(self):
            self._clients: dict = {}

        def get_worker(self, url):
            from datafusion_distributed_tpu.runtime.grpc_worker import (
                GrpcWorkerClient,
            )

            if url not in self._clients:
                self._clients[url] = GrpcWorkerClient(url)
            return self._clients[url]

    Console(_StaticResolver(argv), _GrpcChannels()).run()


if __name__ == "__main__":
    main()
