"""Synthetic TPC-DS dataset generator (all 24 tables).

The reference pulls pre-generated TPC-DS parquet from the
datafusion-benchmarks repo (`/root/reference/benchmarks/src/datasets/tpcds.rs`
`download_benchmarks`) and its plan/correctness suites run against it
(`tests/tpcds_plans_test.rs`, `tests/tpcds_correctness_test.rs`). This image
has no network egress, so the dataset is generated here: spec-shaped schemas
(the column/type surface the 99 queries touch, plus the standard surrogate
keys), spec-domain value pools (categories, states, education levels, buy
potentials — so query literals actually select rows), and referential
integrity between fact and dimension tables. Row counts scale with ``sf``
like the dsdgen scale factor, with the spec's fixed-size dimensions kept
fixed.

Statistical fidelity to dsdgen is NOT a goal: plan tests need schemas and
correctness tests compare against a pandas oracle over the same generated
data, so any self-consistent dataset is valid.
"""

from __future__ import annotations

import numpy as np

# spec calendar: queries filter d_year in 1998..2002
_DATE_LO = np.datetime64("1998-01-01")
_DATE_HI = np.datetime64("2003-01-01")
_SK0 = 2450815  # d_date_sk of 1998-01-01 (spec-like julian base)

_CATEGORIES = ["Home", "Books", "Electronics", "Jewelry", "Sports",
               "Women", "Men", "Children", "Music", "Shoes"]
_CLASSES = ["accent", "bedding", "blinds/shades", "curtains/drapes",
            "decor", "flatware", "furniture", "glassware", "kids",
            "lighting", "mattresses", "paint", "rugs", "tables",
            "wallpaper", "classical", "country", "pop", "rock",
            "fiction", "history", "mystery", "romance", "science",
            "computers", "cameras", "audio", "stereo", "televisions",
            "football", "baseball", "basketball", "camping", "fishing",
            "golf", "hockey", "tennis", "athletic", "dresses", "maternity",
            "pants", "shirts", "swimwear", "infants", "newborn", "toddlers",
            "school-uniforms", "accessories", "mens", "womens", "pendants",
            "rings", "earings", "bracelets", "diamonds", "gold"]
_BRAND_POOL = [f"{a}{b} #{n}" for a in
               ["amalg", "edu pack", "scholar", "import", "corp", "brand",
                "univ", "exporti", "maxi", "nameless"]
               for b in ["amalg", "exporti", "maxi", "importo", "corp",
                         "brand", "scholar", "univ", "unimax", "nameless"]
               for n in (1, 2)]
_COLORS = ["pale", "papaya", "peach", "peru", "pink", "plum", "powder",
           "puff", "purple", "red", "rose", "rosy", "royal", "saddle",
           "salmon", "sandy", "seashell", "sienna", "silver", "sky",
           "slate", "smoke", "snow", "spring", "steel", "tan", "thistle",
           "tomato", "turquoise", "violet", "wheat", "white", "yellow",
           "almond", "antique", "aquamarine", "azure", "beige", "bisque",
           "black", "blanched", "blue", "blush", "brown", "burlywood",
           "burnished", "chartreuse", "chiffon", "chocolate", "coral",
           "cornflower", "cornsilk", "cream", "cyan", "dark", "deep",
           "dim", "dodger", "drab", "firebrick", "floral", "forest",
           "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey",
           "honeydew", "hot", "indian", "ivory", "khaki", "lace",
           "lavender", "lawn", "lemon", "light", "lime", "linen",
           "magenta", "maroon", "medium", "metallic", "midnight", "mint",
           "misty", "moccasin", "navajo", "navy", "olive", "orange",
           "orchid", "pale"]
_SIZES = ["petite", "small", "medium", "large", "extra large", "N/A",
          "economy"]
_UNITS = ["Each", "Dozen", "Case", "Pack", "Box", "Carton", "Unknown",
          "Oz", "Lb", "Ton", "Pallet", "Gross", "Cup", "Dram", "Tbl",
          "Bunch", "Tsp", "Ounce", "Bundle", "N/A"]
_STATES = ["AL", "AR", "CA", "CO", "FL", "GA", "IA", "IL", "IN", "KS",
           "KY", "LA", "MI", "MN", "MO", "MS", "NC", "ND", "NE", "NM",
           "NY", "OH", "OK", "OR", "PA", "SC", "SD", "TN", "TX", "UT",
           "VA", "WA", "WI", "WV"]
_COUNTIES = ["Ziebach County", "Williamson County", "Walker County",
             "Ventura County", "Terrell County", "Sumner County",
             "Salem County", "Rush County", "Richland County",
             "Raleigh County", "Perry County", "Oglethorpe County",
             "Mobile County", "Luce County", "Lea County",
             "Jackson County", "Huron County", "Franklin Parish",
             "Fairfield County", "Dona Ana County", "Daviess County",
             "Bronx County", "Barrow County", "Arthur County"]
_CITIES = ["Midway", "Fairview", "Oak Grove", "Five Points", "Centerville",
           "Liberty", "Pleasant Hill", "Union", "Salem", "Riverside",
           "Greenville", "Bethel", "Clinton", "Marion", "Springdale",
           "Antioch", "Concord", "Edgewood", "Farmington", "Glendale",
           "Hamilton", "Jackson", "Kingston", "Lakeside", "Maple Grove",
           "Newport", "Oakland", "Plainview", "Shiloh", "Sunnyside",
           "Walnut Grove", "Wildwood", "Woodland", "Mount Olive",
           "Pleasant Valley", "Red Hill", "Stringtown", "Unionville",
           "White Oak", "Lebanon"]
_COUNTRIES = ["United States"]
_GENDERS = ["M", "F"]
_MARITAL = ["M", "S", "D", "W", "U"]
_EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree",
              "4 yr Degree", "Advanced Degree", "Unknown"]
_CREDIT_RATINGS = ["Low Risk", "Good", "High Risk", "Unknown"]
_BUY_POTENTIAL = [">10000", "5001-10000", "1001-5000", "501-1000",
                  "0-500", "Unknown"]
_STREET_TYPES = ["Street", "Ave", "Blvd", "Ct", "Dr", "Ln", "Pkwy",
                 "Rd", "Way", "Circle"]
_STREET_NAMES = ["Main", "Oak", "Park", "Elm", "First", "Second", "Third",
                 "Fourth", "Cedar", "Pine", "Maple", "Walnut", "Washington",
                 "Lake", "Hill", "College", "Church", "Spring", "Sunset",
                 "Railroad", "Mill", "River", "Highland", "Johnson",
                 "Smith", "Wilson", "Center", "Green", "Lee", "Jackson",
                 "Adams", "Davis", "Locust", "Broadway", "Dogwood",
                 "Hickory", "Poplar", "Sycamore", "View", "Williams"]
_FIRST_NAMES = ["James", "John", "Robert", "Michael", "William", "David",
                "Mary", "Patricia", "Linda", "Barbara", "Elizabeth",
                "Jennifer", "Maria", "Susan", "Margaret", "Dorothy",
                "Lisa", "Nancy", "Karen", "Betty", "Helen", "Sandra",
                "Donna", "Carol", "Ruth", "Sharon", "Michelle", "Laura",
                "Sarah", "Kimberly", "Richard", "Charles", "Joseph",
                "Thomas", "Christopher", "Daniel", "Paul", "Mark",
                "Donald", "George", "Kenneth", "Steven", "Edward",
                "Brian", "Ronald", "Anthony", "Kevin", "Jason", "Matthew",
                "Gary"]
_LAST_NAMES = ["Smith", "Johnson", "Williams", "Jones", "Brown", "Davis",
               "Miller", "Wilson", "Moore", "Taylor", "Anderson", "Thomas",
               "Jackson", "White", "Harris", "Martin", "Thompson",
               "Garcia", "Martinez", "Robinson", "Clark", "Rodriguez",
               "Lewis", "Lee", "Walker", "Hall", "Allen", "Young",
               "Hernandez", "King", "Wright", "Lopez", "Hill", "Scott",
               "Green", "Adams", "Baker", "Gonzalez", "Nelson", "Carter",
               "Mitchell", "Perez", "Roberts", "Turner", "Phillips",
               "Campbell", "Parker", "Evans", "Edwards", "Collins"]
_SALUTATIONS = ["Mr.", "Mrs.", "Ms.", "Miss", "Dr.", "Sir"]
_MEAL_TIMES = ["breakfast", "dinner", "lunch", ""]
_SHIP_CARRIERS = ["UPS", "FEDEX", "AIRBORNE", "USPS", "DHL", "TBS", "ZHOU",
                  "PRIVATECARRIER", "ALLIANCE", "ORIENTAL", "BARIAN",
                  "BOXBUNDLES", "ZOUROS", "GREAT EASTERN", "DIAMOND",
                  "RUPEKSA", "GERMA", "HARMSTORF", "LATVIAN", "MSC"]
_SHIP_TYPES = ["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "TWO DAY",
               "LIBRARY"]
_BUY_COUNTIES = _COUNTIES


def _dec(rng, n, lo, hi):
    """2-digit decimal column."""
    return np.round(rng.uniform(lo, hi, n), 2)


def _pick(rng, pool, n):
    return np.asarray(pool, dtype=object)[rng.integers(0, len(pool), n)]


def _ids(prefix: str, keys: np.ndarray) -> np.ndarray:
    # digits pad to exactly 16 chars INCLUDING the prefix. (A fixed
    # 16-digit format truncated to 16 chopped the LAST digit, colliding
    # ids 0-9 — 500 customers shared 51 c_customer_ids, which broke
    # business-key uniqueness and made q74-class ORDER BY ... LIMIT
    # tie-arbitrary across execution tiers.)
    width = 16 - len(prefix)
    return np.asarray(
        [f"{prefix}{k:0{width}d}" for k in keys], dtype=object
    )


def gen_tpcds(sf: float = 0.01, seed: int = 0) -> dict:
    """Generate all 24 tables as pyarrow Tables, scaled by ``sf``."""
    import pyarrow as pa

    rng = np.random.default_rng(seed)
    out: dict = {}

    def S(base: int, minimum: int = 1) -> int:
        return max(minimum, int(base * sf))

    # ---- date_dim (fixed calendar) ----------------------------------------
    days = np.arange(_DATE_LO, _DATE_HI, dtype="datetime64[D]")
    nd = len(days)
    d_sk = _SK0 + np.arange(nd)
    dts = days.astype("datetime64[D]").astype(object)
    d_year = np.asarray([d.year for d in dts], dtype=np.int32)
    d_moy = np.asarray([d.month for d in dts], dtype=np.int32)
    d_dom = np.asarray([d.day for d in dts], dtype=np.int32)
    d_dow = np.asarray([d.weekday() for d in dts], dtype=np.int32)
    day_names = ["Monday", "Tuesday", "Wednesday", "Thursday", "Friday",
                 "Saturday", "Sunday"]
    d_qoy = (d_moy - 1) // 3 + 1
    # spec month numbering: d_month_seq counts from 1900 (Jan 1998 = 1176),
    # so the standard query ranges (1176+11, 1200+11, ...) select real data
    month_seq = (d_year - 1900) * 12 + (d_moy - 1)
    week_seq = ((d_sk - _SK0) // 7 + 417).astype(np.int64)
    out["date_dim"] = pa.table({
        "d_date_sk": d_sk.astype(np.int64),
        "d_date_id": _ids("D", d_sk),
        "d_date": days,
        "d_day_name": np.asarray([day_names[w] for w in d_dow], dtype=object),
        "d_dom": d_dom,
        "d_dow": d_dow,
        "d_moy": d_moy,
        "d_qoy": d_qoy,
        "d_year": d_year,
        "d_month_seq": month_seq.astype(np.int64),
        "d_week_seq": week_seq,
        "d_quarter_name": np.asarray(
            [f"{y}Q{q}" for y, q in zip(d_year, d_qoy)], dtype=object
        ),
    })

    # ---- time_dim ---------------------------------------------------------
    nt = 1440  # one row per minute of day
    t_time = np.arange(nt) * 60
    out["time_dim"] = pa.table({
        "t_time_sk": np.arange(nt, dtype=np.int64),
        "t_time_id": _ids("T", np.arange(nt)),
        "t_time": t_time.astype(np.int32),
        "t_hour": (np.arange(nt) // 60).astype(np.int32),
        "t_minute": (np.arange(nt) % 60).astype(np.int32),
        "t_meal_time": np.asarray(
            [("breakfast" if 6 <= h < 9 else
              "lunch" if 11 <= h < 13 else
              "dinner" if 17 <= h < 21 else "")
             for h in np.arange(nt) // 60], dtype=object),
    })

    # ---- item -------------------------------------------------------------
    ni = S(18000, 100)
    i_sk = np.arange(1, ni + 1)
    cat_idx = rng.integers(0, len(_CATEGORIES), ni)
    brand_idx = rng.integers(0, len(_BRAND_POOL), ni)
    class_idx = rng.integers(0, len(_CLASSES), ni)
    manufact_id = rng.integers(1, 1000, ni)
    out["item"] = pa.table({
        "i_item_sk": i_sk.astype(np.int64),
        "i_item_id": _ids("I", ((i_sk - 1) // 2) * 2 + 1),  # pairs share ids
        "i_item_desc": np.asarray(
            [f"desc {w} of item {k % 997}" for k, w in
             zip(i_sk, _pick(rng, _STREET_NAMES, ni))], dtype=object),
        "i_current_price": _dec(rng, ni, 0.09, 99.99),
        "i_wholesale_cost": _dec(rng, ni, 0.05, 80.0),
        "i_brand_id": (brand_idx + 1001).astype(np.int32),
        "i_brand": np.asarray(_BRAND_POOL, dtype=object)[brand_idx],
        "i_class_id": (class_idx + 1).astype(np.int32),
        "i_class": np.asarray(_CLASSES, dtype=object)[class_idx],
        "i_category_id": (cat_idx + 1).astype(np.int32),
        "i_category": np.asarray(_CATEGORIES, dtype=object)[cat_idx],
        "i_manufact_id": manufact_id.astype(np.int32),
        "i_manufact": np.asarray(
            [f"manufact{m % 100}" for m in manufact_id], dtype=object),
        "i_size": _pick(rng, _SIZES, ni),
        "i_color": _pick(rng, _COLORS, ni),
        "i_units": _pick(rng, _UNITS, ni),
        "i_manager_id": rng.integers(1, 101, ni).astype(np.int32),
        "i_product_name": np.asarray(
            [f"product{k}" for k in i_sk], dtype=object),
    })

    # ---- customer_address -------------------------------------------------
    na = S(50000, 200)
    ca_sk = np.arange(1, na + 1)
    out["customer_address"] = pa.table({
        "ca_address_sk": ca_sk.astype(np.int64),
        "ca_address_id": _ids("A", ca_sk),
        "ca_street_number": np.asarray(
            [str(x) for x in rng.integers(1, 1000, na)], dtype=object),
        "ca_street_name": _pick(rng, _STREET_NAMES, na),
        "ca_street_type": _pick(rng, _STREET_TYPES, na),
        "ca_suite_number": np.asarray(
            [f"Suite {x}" for x in rng.integers(0, 500, na)], dtype=object),
        "ca_city": _pick(rng, _CITIES, na),
        "ca_county": _pick(rng, _COUNTIES, na),
        "ca_state": _pick(rng, _STATES, na),
        "ca_zip": np.asarray(
            [f"{z:05d}" for z in rng.integers(10000, 99999, na)],
            dtype=object),
        "ca_country": _pick(rng, _COUNTRIES, na),
        "ca_gmt_offset": rng.choice([-10.0, -9.0, -8.0, -7.0, -6.0, -5.0],
                                    na),
        "ca_location_type": _pick(
            rng, ["apartment", "condo", "single family"], na),
    })

    # ---- customer_demographics (fixed cross product, sampled) -------------
    ncd = 7200
    cd_sk = np.arange(1, ncd + 1)
    out["customer_demographics"] = pa.table({
        "cd_demo_sk": cd_sk.astype(np.int64),
        "cd_gender": np.asarray(_GENDERS, dtype=object)[cd_sk % 2],
        "cd_marital_status": np.asarray(_MARITAL, dtype=object)[cd_sk % 5],
        "cd_education_status": np.asarray(
            _EDUCATION, dtype=object)[cd_sk % 7],
        "cd_purchase_estimate": ((cd_sk % 20) * 500 + 500).astype(np.int32),
        "cd_credit_rating": np.asarray(
            _CREDIT_RATINGS, dtype=object)[cd_sk % 4],
        "cd_dep_count": (cd_sk % 7).astype(np.int32),
        "cd_dep_employed_count": (cd_sk % 7).astype(np.int32),
        "cd_dep_college_count": (cd_sk % 7).astype(np.int32),
    })

    # ---- household_demographics / income_band -----------------------------
    nib = 20
    out["income_band"] = pa.table({
        "ib_income_band_sk": np.arange(1, nib + 1, dtype=np.int64),
        "ib_lower_bound": (np.arange(nib) * 10000).astype(np.int32),
        "ib_upper_bound": ((np.arange(nib) + 1) * 10000).astype(np.int32),
    })
    nhd = 7200
    hd_sk = np.arange(1, nhd + 1)
    out["household_demographics"] = pa.table({
        "hd_demo_sk": hd_sk.astype(np.int64),
        "hd_income_band_sk": (hd_sk % nib + 1).astype(np.int64),
        "hd_buy_potential": np.asarray(
            _BUY_POTENTIAL, dtype=object)[hd_sk % 6],
        "hd_dep_count": (hd_sk % 10).astype(np.int32),
        "hd_vehicle_count": (hd_sk % 6).astype(np.int32),
    })

    # ---- customer ---------------------------------------------------------
    nc = S(100000, 500)
    c_sk = np.arange(1, nc + 1)
    out["customer"] = pa.table({
        "c_customer_sk": c_sk.astype(np.int64),
        "c_customer_id": _ids("C", c_sk),
        "c_current_cdemo_sk": rng.integers(1, ncd + 1, nc).astype(np.int64),
        "c_current_hdemo_sk": rng.integers(1, nhd + 1, nc).astype(np.int64),
        "c_current_addr_sk": rng.integers(1, na + 1, nc).astype(np.int64),
        "c_first_shipto_date_sk": rng.integers(
            _SK0, _SK0 + nd, nc).astype(np.int64),
        "c_first_sales_date_sk": rng.integers(
            _SK0, _SK0 + nd, nc).astype(np.int64),
        "c_salutation": _pick(rng, _SALUTATIONS, nc),
        "c_first_name": _pick(rng, _FIRST_NAMES, nc),
        "c_last_name": _pick(rng, _LAST_NAMES, nc),
        "c_preferred_cust_flag": _pick(rng, ["Y", "N"], nc),
        "c_birth_day": rng.integers(1, 29, nc).astype(np.int32),
        "c_birth_month": rng.integers(1, 13, nc).astype(np.int32),
        "c_birth_year": rng.integers(1930, 1993, nc).astype(np.int32),
        "c_birth_country": _pick(
            rng, ["UNITED STATES", "CANADA", "MEXICO", "GERMANY", "JAPAN",
                  "FRANCE", "BRAZIL", "NIGERIA", "INDIA", "CHINA"], nc),
        "c_login": _pick(rng, [""], nc),
        "c_email_address": np.asarray(
            [f"user{k}@example.com" for k in c_sk], dtype=object),
        "c_last_review_date_sk": rng.integers(
            _SK0, _SK0 + nd, nc).astype(np.int64),
    })

    # ---- store ------------------------------------------------------------
    ns = max(2, int(12 * max(sf, 0.2)))
    s_sk = np.arange(1, ns + 1)
    out["store"] = pa.table({
        "s_store_sk": s_sk.astype(np.int64),
        "s_store_id": _ids("S", ((s_sk - 1) // 2) * 2 + 1),
        "s_store_name": np.asarray(
            ["ought", "able", "pri", "ese", "anti", "cally", "ation",
             "eing", "n st", "bar"][: max(ns, 1)] * (ns // 10 + 1),
            dtype=object)[:ns],
        "s_number_employees": rng.integers(200, 300, ns).astype(np.int32),
        "s_floor_space": rng.integers(5000000, 10000000, ns).astype(np.int32),
        "s_hours": _pick(rng, ["8AM-8AM", "8AM-4PM", "8AM-12AM"], ns),
        "s_manager": _pick(rng, _FIRST_NAMES, ns),
        "s_market_id": rng.integers(1, 11, ns).astype(np.int32),
        "s_company_id": np.ones(ns, dtype=np.int32),
        "s_company_name": _pick(rng, ["Unknown"], ns),
        "s_street_number": np.asarray(
            [str(x) for x in rng.integers(1, 1000, ns)], dtype=object),
        "s_street_name": _pick(rng, _STREET_NAMES, ns),
        "s_street_type": _pick(rng, _STREET_TYPES, ns),
        "s_suite_number": np.asarray(
            [f"Suite {x}" for x in rng.integers(0, 500, ns)], dtype=object),
        "s_city": _pick(rng, _CITIES, ns),
        "s_county": _pick(rng, _COUNTIES, ns),
        "s_state": _pick(rng, _STATES[:8], ns),
        "s_zip": np.asarray(
            [f"{z:05d}" for z in rng.integers(10000, 99999, ns)],
            dtype=object),
        "s_gmt_offset": rng.choice([-8.0, -7.0, -6.0, -5.0], ns),
        "s_tax_precentage": _dec(rng, ns, 0.0, 0.11),
    })

    # ---- call_center / catalog_page / web_site / web_page / warehouse -----
    ncc = max(2, int(6 * max(sf, 0.34)))
    cc_sk = np.arange(1, ncc + 1)
    out["call_center"] = pa.table({
        "cc_call_center_sk": cc_sk.astype(np.int64),
        "cc_call_center_id": _ids("CC", ((cc_sk - 1) // 2) * 2 + 1),
        "cc_name": np.asarray(
            [f"{n} center" for n in
             ["NY Metro", "Mid Atlantic", "North Midwest", "California",
              "Pacific Northwest", "South"][:ncc]], dtype=object),
        "cc_manager": _pick(rng, _FIRST_NAMES, ncc),
        "cc_county": _pick(rng, _COUNTIES, ncc),
    })
    ncp = S(11000, 50)
    cp_sk = np.arange(1, ncp + 1)
    out["catalog_page"] = pa.table({
        "cp_catalog_page_sk": cp_sk.astype(np.int64),
        "cp_catalog_page_id": _ids("CP", cp_sk),
    })
    nws = max(2, int(30 * max(sf, 0.1)))
    web_sk = np.arange(1, nws + 1)
    out["web_site"] = pa.table({
        "web_site_sk": web_sk.astype(np.int64),
        "web_site_id": _ids("W", ((web_sk - 1) // 2) * 2 + 1),
        "web_name": np.asarray(
            [f"site_{k % 8}" for k in web_sk], dtype=object),
        "web_company_name": _pick(
            rng, ["pri", "ought", "able", "ese", "anti", "cally"], nws),
    })
    nwp = S(60, 10)
    wp_sk = np.arange(1, nwp + 1)
    out["web_page"] = pa.table({
        "wp_web_page_sk": wp_sk.astype(np.int64),
        "wp_web_page_id": _ids("WP", wp_sk),
        "wp_char_count": rng.integers(100, 8000, nwp).astype(np.int32),
    })
    nw = max(2, int(5 * max(sf, 0.4)))
    w_sk = np.arange(1, nw + 1)
    out["warehouse"] = pa.table({
        "w_warehouse_sk": w_sk.astype(np.int64),
        "w_warehouse_id": _ids("WH", w_sk),
        "w_warehouse_name": np.asarray(
            [f"Warehouse number {k}" for k in w_sk], dtype=object),
        "w_warehouse_sq_ft": rng.integers(50000, 1000000, nw).astype(
            np.int32),
        "w_city": _pick(rng, _CITIES, nw),
        "w_county": _pick(rng, _COUNTIES, nw),
        "w_state": _pick(rng, _STATES[:8], nw),
        "w_country": _pick(rng, _COUNTRIES, nw),
    })

    # ---- promotion / reason / ship_mode -----------------------------------
    npr = S(300, 20)
    p_sk = np.arange(1, npr + 1)
    out["promotion"] = pa.table({
        "p_promo_sk": p_sk.astype(np.int64),
        "p_promo_id": _ids("P", p_sk),
        "p_channel_dmail": _pick(rng, ["Y", "N"], npr),
        "p_channel_email": _pick(rng, ["Y", "N"], npr),
        "p_channel_tv": _pick(rng, ["Y", "N"], npr),
        "p_channel_event": _pick(rng, ["Y", "N"], npr),
        "p_promo_name": _pick(
            rng, ["ought", "able", "pri", "ese", "anti"], npr),
    })
    nr = 35
    r_sk = np.arange(1, nr + 1)
    reasons = ["Package was damaged", "Stopped working", "Did not get it",
               "Not the product that was ordred", "Parts missing",
               "Does not work with a product that I have",
               "Gift exchange", "Did not like the color",
               "Did not like the model", "Did not like the make",
               "Did not like the warranty", "No service location in my area",
               "Found a better price in a store",
               "Found a better extended warranty in a store",
               "reason 15", "reason 16", "reason 17", "reason 18",
               "reason 19", "reason 20", "reason 21", "reason 22",
               "reason 23", "reason 24", "reason 25", "reason 26",
               "reason 27", "reason 28", "reason 29", "reason 30",
               "reason 31", "reason 32", "reason 33", "reason 34",
               "reason 35"]
    out["reason"] = pa.table({
        "r_reason_sk": r_sk.astype(np.int64),
        "r_reason_id": _ids("R", r_sk),
        "r_reason_desc": np.asarray(reasons, dtype=object),
    })
    nsm = 20
    sm_sk = np.arange(1, nsm + 1)
    out["ship_mode"] = pa.table({
        "sm_ship_mode_sk": sm_sk.astype(np.int64),
        "sm_ship_mode_id": _ids("SM", sm_sk),
        "sm_type": np.asarray(
            [_SHIP_TYPES[i % len(_SHIP_TYPES)] for i in range(nsm)],
            dtype=object),
        "sm_code": _pick(rng, ["AIR", "SURFACE", "SEA"], nsm),
        "sm_carrier": np.asarray(_SHIP_CARRIERS, dtype=object)[:nsm],
    })

    # ---- fact: store_sales + store_returns --------------------------------
    nss = S(2_880_000, 2000)
    ticket = rng.integers(1, max(nss // 3, 2), nss)
    ss = {
        "ss_sold_date_sk": rng.integers(_SK0, _SK0 + nd, nss),
        "ss_sold_time_sk": rng.integers(0, nt, nss),
        "ss_item_sk": rng.integers(1, ni + 1, nss),
        "ss_customer_sk": rng.integers(1, nc + 1, nss),
        "ss_cdemo_sk": rng.integers(1, ncd + 1, nss),
        "ss_hdemo_sk": rng.integers(1, nhd + 1, nss),
        "ss_addr_sk": rng.integers(1, na + 1, nss),
        "ss_store_sk": rng.integers(1, ns + 1, nss),
        "ss_promo_sk": rng.integers(1, npr + 1, nss),
        "ss_ticket_number": ticket,
        "ss_quantity": rng.integers(1, 101, nss),
        "ss_wholesale_cost": _dec(rng, nss, 1.0, 100.0),
        "ss_list_price": _dec(rng, nss, 1.0, 200.0),
        "ss_sales_price": _dec(rng, nss, 0.0, 200.0),
        "ss_ext_discount_amt": _dec(rng, nss, 0.0, 1000.0),
        "ss_ext_sales_price": _dec(rng, nss, 0.0, 2000.0),
        "ss_ext_wholesale_cost": _dec(rng, nss, 1.0, 2000.0),
        "ss_ext_list_price": _dec(rng, nss, 1.0, 4000.0),
        "ss_ext_tax": _dec(rng, nss, 0.0, 200.0),
        "ss_coupon_amt": _dec(rng, nss, 0.0, 500.0),
        "ss_net_paid": _dec(rng, nss, 0.0, 2000.0),
        "ss_net_paid_inc_tax": _dec(rng, nss, 0.0, 2200.0),
        "ss_net_profit": _dec(rng, nss, -1000.0, 1000.0),
    }
    # nullable customer FK (queries LEFT JOIN / IS NULL on it)
    null_mask = rng.random(nss) < 0.04
    cols = {
        k: (pa.array(v, type=pa.int64(), mask=null_mask)
            if k == "ss_customer_sk" else v)
        for k, v in ss.items()
    }
    out["store_sales"] = pa.table(cols)

    nsr = max(200, nss // 10)
    ridx = rng.integers(0, nss, nsr)
    out["store_returns"] = pa.table({
        "sr_returned_date_sk": np.minimum(
            ss["ss_sold_date_sk"][ridx] + rng.integers(1, 60, nsr),
            _SK0 + nd - 1),
        "sr_return_time_sk": rng.integers(0, nt, nsr),
        "sr_item_sk": ss["ss_item_sk"][ridx],
        "sr_customer_sk": ss["ss_customer_sk"][ridx],
        "sr_cdemo_sk": ss["ss_cdemo_sk"][ridx],
        "sr_hdemo_sk": ss["ss_hdemo_sk"][ridx],
        "sr_addr_sk": ss["ss_addr_sk"][ridx],
        "sr_store_sk": ss["ss_store_sk"][ridx],
        "sr_reason_sk": rng.integers(1, nr + 1, nsr),
        "sr_ticket_number": ss["ss_ticket_number"][ridx],
        "sr_return_quantity": rng.integers(1, 50, nsr),
        "sr_return_amt": _dec(rng, nsr, 0.0, 1000.0),
        "sr_return_tax": _dec(rng, nsr, 0.0, 100.0),
        "sr_return_amt_inc_tax": _dec(rng, nsr, 0.0, 1100.0),
        "sr_fee": _dec(rng, nsr, 0.0, 100.0),
        "sr_return_ship_cost": _dec(rng, nsr, 0.0, 500.0),
        "sr_refunded_cash": _dec(rng, nsr, 0.0, 1000.0),
        "sr_reversed_charge": _dec(rng, nsr, 0.0, 1000.0),
        "sr_store_credit": _dec(rng, nsr, 0.0, 1000.0),
        "sr_net_loss": _dec(rng, nsr, 0.0, 1000.0),
    })

    # ---- fact: catalog_sales + catalog_returns ----------------------------
    ncs = S(1_440_000, 1000)
    order = rng.integers(1, max(ncs // 3, 2), ncs)
    cs = {
        "cs_sold_date_sk": rng.integers(_SK0, _SK0 + nd, ncs),
        "cs_sold_time_sk": rng.integers(0, nt, ncs),
        "cs_ship_date_sk": None,  # filled below
        "cs_bill_customer_sk": rng.integers(1, nc + 1, ncs),
        "cs_bill_cdemo_sk": rng.integers(1, ncd + 1, ncs),
        "cs_bill_hdemo_sk": rng.integers(1, nhd + 1, ncs),
        "cs_bill_addr_sk": rng.integers(1, na + 1, ncs),
        "cs_ship_customer_sk": rng.integers(1, nc + 1, ncs),
        "cs_ship_addr_sk": rng.integers(1, na + 1, ncs),
        "cs_call_center_sk": rng.integers(1, ncc + 1, ncs),
        "cs_catalog_page_sk": rng.integers(1, ncp + 1, ncs),
        "cs_ship_mode_sk": rng.integers(1, nsm + 1, ncs),
        "cs_warehouse_sk": rng.integers(1, nw + 1, ncs),
        "cs_item_sk": rng.integers(1, ni + 1, ncs),
        "cs_promo_sk": rng.integers(1, npr + 1, ncs),
        "cs_order_number": order,
        "cs_quantity": rng.integers(1, 101, ncs),
        "cs_wholesale_cost": _dec(rng, ncs, 1.0, 100.0),
        "cs_list_price": _dec(rng, ncs, 1.0, 300.0),
        "cs_sales_price": _dec(rng, ncs, 0.0, 300.0),
        "cs_ext_discount_amt": _dec(rng, ncs, 0.0, 1000.0),
        "cs_ext_sales_price": _dec(rng, ncs, 0.0, 3000.0),
        "cs_ext_wholesale_cost": _dec(rng, ncs, 1.0, 2000.0),
        "cs_ext_list_price": _dec(rng, ncs, 1.0, 6000.0),
        "cs_ext_tax": _dec(rng, ncs, 0.0, 300.0),
        "cs_coupon_amt": _dec(rng, ncs, 0.0, 500.0),
        "cs_ext_ship_cost": _dec(rng, ncs, 0.0, 500.0),
        "cs_net_paid": _dec(rng, ncs, 0.0, 3000.0),
        "cs_net_paid_inc_tax": _dec(rng, ncs, 0.0, 3300.0),
        "cs_net_paid_inc_ship": _dec(rng, ncs, 0.0, 3500.0),
        "cs_net_paid_inc_ship_tax": _dec(rng, ncs, 0.0, 3800.0),
        "cs_net_profit": _dec(rng, ncs, -1000.0, 1500.0),
    }
    cs["cs_ship_date_sk"] = np.minimum(
        cs["cs_sold_date_sk"] + rng.integers(1, 120, ncs), _SK0 + nd - 1
    )
    out["catalog_sales"] = pa.table(cs)

    ncr = max(150, ncs // 10)
    ridx = rng.integers(0, ncs, ncr)
    out["catalog_returns"] = pa.table({
        "cr_returned_date_sk": np.minimum(
            cs["cs_ship_date_sk"][ridx] + rng.integers(1, 60, ncr),
            _SK0 + nd - 1),
        "cr_returned_time_sk": rng.integers(0, nt, ncr),
        "cr_item_sk": cs["cs_item_sk"][ridx],
        "cr_refunded_customer_sk": cs["cs_bill_customer_sk"][ridx],
        "cr_refunded_cdemo_sk": cs["cs_bill_cdemo_sk"][ridx],
        "cr_refunded_addr_sk": cs["cs_bill_addr_sk"][ridx],
        "cr_returning_customer_sk": cs["cs_ship_customer_sk"][ridx],
        "cr_returning_cdemo_sk": cs["cs_bill_cdemo_sk"][ridx],
        "cr_returning_addr_sk": cs["cs_ship_addr_sk"][ridx],
        "cr_call_center_sk": cs["cs_call_center_sk"][ridx],
        "cr_catalog_page_sk": cs["cs_catalog_page_sk"][ridx],
        "cr_ship_mode_sk": cs["cs_ship_mode_sk"][ridx],
        "cr_warehouse_sk": cs["cs_warehouse_sk"][ridx],
        "cr_reason_sk": rng.integers(1, nr + 1, ncr),
        "cr_order_number": cs["cs_order_number"][ridx],
        "cr_return_quantity": rng.integers(1, 50, ncr),
        "cr_return_amount": _dec(rng, ncr, 0.0, 1500.0),
        "cr_return_tax": _dec(rng, ncr, 0.0, 150.0),
        "cr_return_amt_inc_tax": _dec(rng, ncr, 0.0, 1650.0),
        "cr_fee": _dec(rng, ncr, 0.0, 100.0),
        "cr_return_ship_cost": _dec(rng, ncr, 0.0, 500.0),
        "cr_refunded_cash": _dec(rng, ncr, 0.0, 1500.0),
        "cr_reversed_charge": _dec(rng, ncr, 0.0, 1500.0),
        "cr_store_credit": _dec(rng, ncr, 0.0, 1500.0),
        "cr_net_loss": _dec(rng, ncr, 0.0, 1500.0),
    })

    # ---- fact: web_sales + web_returns ------------------------------------
    nwsales = S(720_000, 600)
    worder = rng.integers(1, max(nwsales // 3, 2), nwsales)
    ws = {
        "ws_sold_date_sk": rng.integers(_SK0, _SK0 + nd, nwsales),
        "ws_sold_time_sk": rng.integers(0, nt, nwsales),
        "ws_ship_date_sk": None,
        "ws_item_sk": rng.integers(1, ni + 1, nwsales),
        "ws_bill_customer_sk": rng.integers(1, nc + 1, nwsales),
        "ws_bill_cdemo_sk": rng.integers(1, ncd + 1, nwsales),
        "ws_bill_hdemo_sk": rng.integers(1, nhd + 1, nwsales),
        "ws_bill_addr_sk": rng.integers(1, na + 1, nwsales),
        "ws_ship_customer_sk": rng.integers(1, nc + 1, nwsales),
        "ws_ship_cdemo_sk": rng.integers(1, ncd + 1, nwsales),
        "ws_ship_hdemo_sk": rng.integers(1, nhd + 1, nwsales),
        "ws_ship_addr_sk": rng.integers(1, na + 1, nwsales),
        "ws_web_page_sk": rng.integers(1, nwp + 1, nwsales),
        "ws_web_site_sk": rng.integers(1, nws + 1, nwsales),
        "ws_ship_mode_sk": rng.integers(1, nsm + 1, nwsales),
        "ws_warehouse_sk": rng.integers(1, nw + 1, nwsales),
        "ws_promo_sk": rng.integers(1, npr + 1, nwsales),
        "ws_order_number": worder,
        "ws_quantity": rng.integers(1, 101, nwsales),
        "ws_wholesale_cost": _dec(rng, nwsales, 1.0, 100.0),
        "ws_list_price": _dec(rng, nwsales, 1.0, 300.0),
        "ws_sales_price": _dec(rng, nwsales, 0.0, 300.0),
        "ws_ext_discount_amt": _dec(rng, nwsales, 0.0, 1000.0),
        "ws_ext_sales_price": _dec(rng, nwsales, 0.0, 3000.0),
        "ws_ext_wholesale_cost": _dec(rng, nwsales, 1.0, 2000.0),
        "ws_ext_list_price": _dec(rng, nwsales, 1.0, 6000.0),
        "ws_ext_tax": _dec(rng, nwsales, 0.0, 300.0),
        "ws_coupon_amt": _dec(rng, nwsales, 0.0, 500.0),
        "ws_ext_ship_cost": _dec(rng, nwsales, 0.0, 500.0),
        "ws_net_paid": _dec(rng, nwsales, 0.0, 3000.0),
        "ws_net_paid_inc_tax": _dec(rng, nwsales, 0.0, 3300.0),
        "ws_net_profit": _dec(rng, nwsales, -1000.0, 1500.0),
    }
    ws["ws_ship_date_sk"] = np.minimum(
        ws["ws_sold_date_sk"] + rng.integers(1, 120, nwsales), _SK0 + nd - 1
    )
    out["web_sales"] = pa.table(ws)

    nwr = max(100, nwsales // 10)
    ridx = rng.integers(0, nwsales, nwr)
    out["web_returns"] = pa.table({
        "wr_returned_date_sk": np.minimum(
            ws["ws_ship_date_sk"][ridx] + rng.integers(1, 60, nwr),
            _SK0 + nd - 1),
        "wr_returned_time_sk": rng.integers(0, nt, nwr),
        "wr_item_sk": ws["ws_item_sk"][ridx],
        "wr_refunded_customer_sk": ws["ws_bill_customer_sk"][ridx],
        "wr_refunded_cdemo_sk": ws["ws_bill_cdemo_sk"][ridx],
        "wr_refunded_hdemo_sk": ws["ws_bill_hdemo_sk"][ridx],
        "wr_refunded_addr_sk": ws["ws_bill_addr_sk"][ridx],
        "wr_returning_customer_sk": ws["ws_ship_customer_sk"][ridx],
        "wr_returning_cdemo_sk": ws["ws_ship_cdemo_sk"][ridx],
        "wr_returning_hdemo_sk": ws["ws_ship_hdemo_sk"][ridx],
        "wr_returning_addr_sk": ws["ws_ship_addr_sk"][ridx],
        "wr_web_page_sk": ws["ws_web_page_sk"][ridx],
        "wr_reason_sk": rng.integers(1, nr + 1, nwr),
        "wr_order_number": ws["ws_order_number"][ridx],
        "wr_return_quantity": rng.integers(1, 50, nwr),
        "wr_return_amt": _dec(rng, nwr, 0.0, 1500.0),
        "wr_return_tax": _dec(rng, nwr, 0.0, 150.0),
        "wr_return_amt_inc_tax": _dec(rng, nwr, 0.0, 1650.0),
        "wr_fee": _dec(rng, nwr, 0.0, 100.0),
        "wr_return_ship_cost": _dec(rng, nwr, 0.0, 500.0),
        "wr_refunded_cash": _dec(rng, nwr, 0.0, 1500.0),
        "wr_reversed_charge": _dec(rng, nwr, 0.0, 1500.0),
        "wr_account_credit": _dec(rng, nwr, 0.0, 1500.0),
        "wr_net_loss": _dec(rng, nwr, 0.0, 1500.0),
    })

    # ---- fact: inventory (weekly snapshots) -------------------------------
    weeks = np.arange(_SK0, _SK0 + nd, 7)
    ninv_items = min(ni, S(2000, 200))
    inv_date = np.repeat(weeks, ninv_items * nw)
    inv_item = np.tile(np.repeat(np.arange(1, ninv_items + 1), nw),
                       len(weeks))
    inv_wh = np.tile(np.arange(1, nw + 1), len(weeks) * ninv_items)
    out["inventory"] = pa.table({
        "inv_date_sk": inv_date.astype(np.int64),
        "inv_item_sk": inv_item.astype(np.int64),
        "inv_warehouse_sk": inv_wh.astype(np.int64),
        "inv_quantity_on_hand": rng.integers(
            0, 1000, len(inv_date)).astype(np.int32),
    })

    return out


def register_tpcds(ctx, sf: float = 0.01, seed: int = 0) -> dict:
    """Generate and register all TPC-DS tables on a SessionContext."""
    tables = gen_tpcds(sf=sf, seed=seed)
    for name, arrow in tables.items():
        ctx.register_arrow(name, arrow)
    return tables
