"""Synthetic ClickBench `hits` dataset generator.

The reference downloads the real ClickBench parquet (~14 GB,
`/root/reference/benchmarks/src/datasets/clickbench.rs`) for its plan and
correctness suites (`tests/clickbench_plans_test.rs`,
`tests/clickbench_correctness_test.rs`). No network egress here, so the
table is generated: the 25 columns the 43 queries touch, with spec-shaped
domains (EventTime as epoch seconds in July 2013, mostly-empty SearchPhrase
/ MobilePhoneModel, URLs with 'google' substrings for the LIKE queries,
zero-heavy AdvEngineID, ±1 TraficSourceID). Correctness tests compare
against a pandas oracle over the same generated rows, so statistical
fidelity to Yandex traffic is irrelevant — domain SHAPE is what matters
(empty-string majorities and zero-heavy columns drive the queries'
selectivity patterns).
"""

from __future__ import annotations

import numpy as np

_EPOCH_2013_07_01 = 15887  # days since epoch
_SECS_2013_07_01 = _EPOCH_2013_07_01 * 86400
_DAYS = 31

_PHRASES = ["", "", "", "", "", "", "", "",  # ~72% empty like the real data
            "car", "cheap flights", "weather moscow", "news today",
            "how to cook rice", "google maps", "python tutorial",
            "hotel deals", "football scores", "movie times",
            "best laptop 2013", "train tickets"]
_PHONE_MODELS = ["", "", "", "", "", "iPhone 5", "Galaxy S4", "Lumia 920",
                 "Xperia Z", "Nexus 4"]
_URL_HOSTS = ["http://example.com", "http://google.ru/search",
              "http://news.site", "http://shop.online", "http://maps.app",
              "http://video.portal", "http://maps.google.com/dir",
              "http://forum.board", "http://mail.box", "http://blog.spot"]
_TITLES = ["Home", "Search results - Google", "News", "Shop",
           "Google Maps", "Video", "Forum", "Mail", "Blog", "Weather", ""]
_REFERERS = ["", "", "http://google.ru/", "http://direct.link/",
             "http://social.net/", "http://mail.box/"]


def gen_clickbench(rows: int = 100_000, seed: int = 0):
    """Generate the `hits` table as a pyarrow Table."""
    import pyarrow as pa

    rng = np.random.default_rng(seed)
    n = rows

    event_day = rng.integers(0, _DAYS, n)
    event_date = (_EPOCH_2013_07_01 + event_day).astype(np.int32)
    event_time = (
        _SECS_2013_07_01 + event_day * 86400 + rng.integers(0, 86400, n)
    ).astype(np.int64)
    urls = np.asarray(_URL_HOSTS, dtype=object)[
        rng.integers(0, len(_URL_HOSTS), n)
    ]
    paths = rng.integers(0, 5000, n)
    full_urls = np.asarray(
        [f"{u}/{p}" for u, p in zip(urls, paths)], dtype=object
    )

    def _hash_col(values):
        return np.asarray(
            [hash(v) & 0x7FFFFFFF for v in values], dtype=np.int64
        )

    cols = {
        "WatchID": rng.integers(1, 2**31 - 1, n).astype(np.int64),
        "UserID": rng.integers(1, 200_000, n).astype(np.int64),
        "CounterID": rng.integers(1, 100, n).astype(np.int32),
        "ClientIP": rng.integers(0, 2**31 - 1, n).astype(np.int32),
        "RegionID": rng.integers(1, 300, n).astype(np.int32),
        "EventDate": event_date.astype("datetime64[D]"),
        "EventTime": event_time,
        "Title": np.asarray(_TITLES, dtype=object)[
            rng.integers(0, len(_TITLES), n)],
        "URL": full_urls,
        "Referer": np.asarray(_REFERERS, dtype=object)[
            rng.integers(0, len(_REFERERS), n)],
        "URLHash": _hash_col(full_urls),
        "RefererHash": rng.integers(0, 2**31 - 1, n).astype(np.int64),
        "SearchPhrase": np.asarray(_PHRASES, dtype=object)[
            rng.integers(0, len(_PHRASES), n)],
        "SearchEngineID": np.where(
            rng.random(n) < 0.8, 0, rng.integers(1, 6, n)).astype(np.int16),
        "AdvEngineID": np.where(
            rng.random(n) < 0.95, 0, rng.integers(1, 20, n)).astype(np.int16),
        "MobilePhone": np.where(
            rng.random(n) < 0.85, 0, rng.integers(1, 8, n)).astype(np.int16),
        "MobilePhoneModel": np.asarray(_PHONE_MODELS, dtype=object)[
            rng.integers(0, len(_PHONE_MODELS), n)],
        "ResolutionWidth": rng.choice(
            [0, 1024, 1280, 1366, 1440, 1600, 1920, 2560],
            n, p=[0.05, 0.1, 0.2, 0.25, 0.1, 0.1, 0.15, 0.05]
        ).astype(np.int16),
        "WindowClientWidth": rng.integers(0, 2000, n).astype(np.int16),
        "WindowClientHeight": rng.integers(0, 1200, n).astype(np.int16),
        "TraficSourceID": rng.integers(-1, 10, n).astype(np.int8),
        "IsRefresh": (rng.random(n) < 0.1).astype(np.int16),
        "IsLink": (rng.random(n) < 0.2).astype(np.int16),
        "IsDownload": (rng.random(n) < 0.05).astype(np.int16),
        "DontCountHits": (rng.random(n) < 0.05).astype(np.int16),
    }
    return pa.table(cols)


def register_clickbench(ctx, rows: int = 100_000, seed: int = 0):
    t = gen_clickbench(rows=rows, seed=seed)
    ctx.register_arrow("hits", t)
    return t
